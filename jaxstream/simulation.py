"""End-to-end simulation driver: the framework shell around the solvers.

This is the rebuilt form of the reference's implied top-level run loop
(SURVEY.md §3.4): ``load config.yaml -> geometry [zarr] -> initial
conditions -> setup_sharding() -> timestep loop (no recompilation) with
periodic history [zarr] / restart [Orbax] -> analysis``.  The reference
shows only the ``setup_sharding`` method of its unseen driver class
(``/root/reference/JAX-DevLab-Examples.py:19-85``); :class:`Simulation`
is that class built out in full, config-driven end to end.

Design notes (TPU-first):
  * The inner loop is segments of ``lax.fori_loop`` under one cached
    ``jit`` — host contact only at history/checkpoint boundaries, so the
    per-step path is pure device execution ("no recompilation during
    timestepping", deck p.10).
  * Sharding is transparent: with ``num_devices > 1`` the state is
    device_put with a ``('panel','y','x')`` NamedSharding (GSPMD path) or
    stepped inside ``shard_map`` with explicit ``lax.ppermute`` halos
    (``use_shard_map: true``); the numerics are byte-identical either way.
  * Restart is automatic: if the checkpoint directory has a saved step,
    the run resumes from it (sharding-aware restore).
"""

from __future__ import annotations

import functools
import logging
import math
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .config import Config, load_config
from .geometry.cubed_sphere import build_grid
from .io.async_pipeline import BackgroundWriter, HostFetch
from .io.checkpoint import CheckpointManager
from .io.history import HistoryWriter, save_geometry
from .models.advection import TracerAdvection
from .models.diffusion import ThermalDiffusion
from .models.shallow_water import ShallowWater
from .obs import flight
from .obs import metrics as obs_metrics
from .obs.monitor import HealthMonitor
from .obs.sink import TelemetrySink, run_manifest
from .parallel.mesh import (setup_ensemble_sharding, setup_sharding,
                            shard_ensemble_state, shard_state)
from .parallel.sharded_model import make_stepper_for
from .plan import build_proof, plan_for
from .plan import rules as plan_rules
from .physics import initial_conditions as ics
from .stepping import (integrate, integrate_with_metrics, jit_integrate,
                       time_carry)
from .utils import diagnostics as diag
from .utils.logging import get_logger

__all__ = ["Simulation", "run_from_config"]

#: The prognostic keys of every dense state family — what the in-loop
#: metric functions see (fused-stepper strip carries are dropped first).
_PROG_KEYS = ("h", "u", "v", "q", "T")


class _ObsRuntime:
    """Per-Simulation telemetry wiring (built by ``_build_obs``)."""

    def __init__(self, cfg, metric_set, metric_fn, monitor, sink, ref):
        self.cfg = cfg                  # the ObservabilityConfig block
        self.ms = metric_set
        self.metric_fn = metric_fn      # fn(loop_carry, t) -> (k,) vector
        self.monitor = monitor
        self.sink = sink
        self.ref = ref                  # step-0 metric values (np, (k,))
        self.wrote_initial = False

log = get_logger(__name__)


def _run_tasks(tasks):
    """One async-pipeline boundary's writes, in order, as ONE writer
    task — so the queue bound counts segments, and a failure mid-list
    aborts the boundary's remaining writes (fail-stop within the
    boundary, matching the writer's fail-stop across boundaries)."""
    for fn, args in tasks:
        fn(*args)


_DTYPES = {"float32": jnp.float32, "float64": jnp.float64, "bfloat16": jnp.bfloat16}

#: initial-condition name -> model family it drives
IC_FAMILY = {
    "tc1": "advection",
    "cosine_bell": "advection",
    "checkerboard": "diffusion",
    "tc2": "shallow_water",
    "tc5": "shallow_water",
    "tc6": "shallow_water",
    "galewsky": "shallow_water",
}


class Simulation:
    """Config -> grid -> model+IC -> sharding -> run loop -> outputs."""

    def __init__(self, config: Any = None):
        self.config: Config = load_config(config)
        cfg = self.config
        # Round 16: resolve the capability plan FIRST — illegal
        # feature compositions are rejected statically by the
        # declarative rule table (jaxstream.plan.rules), before any
        # grid build, device placement or trace, with the same pointer
        # messages the legacy scattered raises carried.
        self.plan = plan_for(cfg)
        self.proof = None
        dtype = _DTYPES[cfg.grid.dtype]
        mcfg = cfg.model
        halo = cfg.grid.halo
        if mcfg.scheme == "ppm":
            halo = max(halo, 3)
        self.grid = build_grid(
            cfg.grid.n, halo=halo, radius=cfg.grid.radius, dtype=dtype,
            metrics=cfg.grid.metrics,
        )
        # The deck's "Numerics (TT)" tier (pdf p.7): factored-panel
        # solvers behind the same config/IO surface.
        self._tt_keys = None
        self._tt_hs = None
        self.t = 0.0
        self.step_count = 0
        self.setup = None
        self.members = cfg.ensemble.members
        self._classic_run = None
        if self.members < 1:
            raise ValueError(
                f"ensemble.members must be >= 1, got {self.members}")
        if self.members > 1 and cfg.model.numerics != "dense":
            raise ValueError(
                "ensemble.members > 1 runs the dense tier only; set "
                "model.numerics: dense (the factored TT state has no "
                "batched stepper yet)")
        # (Round 11: ensemble history/checkpoints are supported — the
        # member-batched arrays are written as-is and extracted
        # per-member via io.history.extract_member /
        # HistoryWriter.read_member / CheckpointManager.restore_member;
        # member 0 byte-matches an unbatched run on the vmapped path.)
        if cfg.model.numerics == "tt":
            self.model = None
            self.state, self._step = self._build_tt()
        elif cfg.model.numerics != "dense":
            raise ValueError(
                f"model.numerics={cfg.model.numerics!r}; valid: 'dense' "
                "(production solvers) or 'tt' (factored-panel tier)")
        else:
            self.model, self.state = self._build_model_and_state()

            par = cfg.parallelization
            # The sharded tiers run f32 numerics: hand them the
            # precision spec ONLY when they are the executing path
            # (num_devices > 1) so make_stepper_for rejects a non-f32
            # policy with its pointer.  Single-device runs ride the
            # fused stepper below (the classic _step built here is its
            # fallback, and the fused-or-raise check at the end of this
            # constructor guards that case).
            pspec = ({"stage": cfg.precision.stage,
                      "strips": cfg.precision.strips}
                     if par.num_devices > 1 else None)
            if self.members > 1:
                self.state = self._build_ensemble_state()
                if par.num_devices > 1:
                    # ensemble.layout (round 12): 'auto' = the 2-D
                    # ('panel', 'member') mesh; 'member' = the 1-D
                    # member-only mesh (any device count dividing the
                    # ensemble; GSPMD path, zero wire traffic) — the
                    # same layout the serving tier's member-parallel
                    # placement runs on.
                    self.setup = setup_ensemble_sharding(
                        cfg, self.members, layout=cfg.ensemble.layout)
                    self.state = shard_ensemble_state(self.setup,
                                                      self.state)
                self._step = make_stepper_for(
                    self.model, self.setup, self.state, cfg.time.dt,
                    cfg.time.scheme, temporal_block=par.temporal_block,
                    ensemble=self.members, precision=pspec,
                )
            else:
                if par.num_devices > 1:
                    self.setup = setup_sharding(cfg)
                    self.state = shard_state(self.setup, self.state)
                self._step = make_stepper_for(
                    self.model, self.setup, self.state, cfg.time.dt,
                    cfg.time.scheme, temporal_block=par.temporal_block,
                    precision=pspec,
                )
        # Single-device Pallas SWE runs use the fused extended-state
        # SSPRK3 stepper (the bench flagship): extend/restrict happen once
        # per compiled segment, so the strip carry stays on device between
        # I/O strides.  Sharded runs are handled by make_stepper_for.
        self._fused_step = None
        self._fused_prep = None
        # Decode hook for 16-bit carry encodings (precision.carry):
        # applied to every restrict_state exit so self.state, history,
        # checkpoints, diagnostics and the in-loop metrics all see
        # absolute f32 fields; None = identity (the f32 carry).
        self._fused_post = None
        m = self.model
        # nu4 > 0 is fused only where the model declares support (the
        # covariant model's two-kernel del^4 stage pair).
        tb = cfg.parallelization.temporal_block
        pkw, p_enc, p_dec = self._resolve_precision()
        if (self.members > 1 and self.setup is None
                and cfg.time.scheme == "ssprk3"
                and getattr(m, "backend", "").startswith("pallas")
                and getattr(m, "nu4", 0.0) == 0.0
                and hasattr(m, "ensemble_compact_state")):
            # Batched ensemble fast path: the member axis folds into the
            # stage kernels' grid, so all B members ride one kernel
            # launch per stage (jaxstream.ops.pallas.swe_cov).
            try:
                self._fused_step = m.make_fused_step(
                    cfg.time.dt, temporal_block=tb, ensemble=self.members,
                    **pkw)
                if p_enc is not None:
                    # Strip narrowing only (carry encodings are
                    # rejected for ensembles in _resolve_precision).
                    self._fused_prep = (
                        lambda s, _e=p_enc: _e(m.ensemble_compact_state(s)))
                else:
                    self._fused_prep = m.ensemble_compact_state
                log.info("using batched ensemble fused SSPRK3 stepper "
                         "(%d members per kernel launch)", self.members)
            except Exception as e:
                if pkw:
                    raise ValueError(
                        "precision: block configured but the batched "
                        f"fused stepper failed to build ({type(e).__name__}"
                        f": {e}); the policy has no classic-path form, so "
                        "refusing to silently run f32") from e
                log.warning(
                    "batched fused stepper unavailable (%s: %s); falling "
                    "back to the vmapped classic path",
                    type(e).__name__, e,
                )
        elif (self.members == 1 and self.setup is None
                and cfg.time.scheme == "ssprk3"
                and getattr(m, "backend", "").startswith("pallas")
                and (getattr(m, "nu4", 0.0) == 0.0
                     or getattr(m, "fused_supports_nu4", False))
                and hasattr(m, "make_fused_step")):
            try:
                # The stepper and its carry-prep are a matched pair: pick
                # both here so they cannot drift apart.
                def _mk_fused():
                    """Fused step honoring temporal_block where the
                    model knows the knob (covariant multistep factory);
                    exact k-step fusion via stepping.blocked otherwise."""
                    try:
                        return m.make_fused_step(cfg.time.dt,
                                                 temporal_block=tb, **pkw)
                    except TypeError:
                        if pkw:
                            # The precision/nu4_mode kwargs have no
                            # generic fallback — a model that doesn't
                            # know them can't honor the config.
                            raise
                        step = m.make_fused_step(cfg.time.dt)
                        if tb > 1:
                            from .stepping import blocked

                            step = blocked(step, tb, cfg.time.dt)
                            step.steps_per_call = tb
                        return step

                if hasattr(m, "compact_state"):
                    self._fused_step = _mk_fused()
                    if p_enc is not None:
                        self._fused_prep = (
                            lambda s, _e=p_enc: _e(m.compact_state(s)))
                        self._fused_post = p_dec
                    else:
                        self._fused_prep = m.compact_state
                    log.info("using compact fused SSPRK3 stepper "
                             "(interior-only carry)")
                else:
                    if pkw.get("precision") or p_enc is not None:
                        raise ValueError(
                            "precision: block needs the compact-carry "
                            "fused stepper (this model only has the "
                            "extended-state form)")
                    self._fused_step = _mk_fused()
                    self._fused_prep = functools.partial(
                        m.extend_state, with_strips=True)
                    log.info("using fused extended-state SSPRK3 stepper")
            except Exception as e:
                if pkw or p_enc is not None:
                    raise ValueError(
                        "precision: block configured but the fused "
                        f"stepper failed to build ({type(e).__name__}: "
                        f"{e}); the policy has no classic-path form, so "
                        "refusing to silently run f32") from e
                log.warning(
                    "fused stepper unavailable (%s: %s); falling back to "
                    "the classic path (~2x slower on TPU)",
                    type(e).__name__, e,
                )
        if (pkw or p_enc is not None) and self._fused_step is None:
            plan_rules.fail("precision-needs-fused-path")
        # The run's proof stamp: rules verdict + schedule fingerprint +
        # enumerated-matrix coverage for the stepper that will actually
        # execute (the fused gate above may have fallen back to the
        # classic path — re-resolve the tier so the stamp is honest).
        actual = self.plan
        if actual.tier == "fused" and self._fused_step is None:
            import dataclasses as _dc

            actual = plan_rules.normalize(
                _dc.replace(actual, tier="classic"))
        self.proof = build_proof(actual)
        self._segment_cache: Dict[int, Callable] = {}

        # Async host pipeline (io.async_pipeline, round 9): the writer
        # thread is created lazily on the first async run(); _host_wait
        # accumulates the host-side I/O seconds that blocked the next
        # dispatch since the last telemetry record (both modes report
        # it, so the overlap is visible in the sink).
        self._writer: Optional[BackgroundWriter] = None
        self._host_wait = 0.0
        self._t_carry = None

        io = cfg.io
        self.history: Optional[HistoryWriter] = None
        self.checkpoints: Optional[CheckpointManager] = None
        if io.history_stride > 0:
            save_geometry(io.history_path + ".geometry", self.grid)
            hist_rank = io.history_tt_rank or None
            if self._tt_keys is not None and hist_rank:
                log.info("numerics='tt': state snapshots are already "
                         "factored; ignoring io.history_tt_rank")
                hist_rank = None
            self.history = HistoryWriter(
                io.history_path,
                attrs={"model": mcfg.name, "ic": mcfg.initial_condition,
                       "numerics": mcfg.numerics,
                       # Marks the fields member-batched so read_member
                       # can slice the right axis (round 11).
                       "members": self.members},
                tt_rank=hist_rank,
            )
        # Crash forensics (round 20): dump-once latch + resume lineage.
        # Lineage is recorded only when this run actually resumed from
        # a checkpoint AND a committed crash bundle exists in the
        # configured flight dir — the prior incarnation's black box.
        self._flight_dumped = False
        self._resume_lineage: Optional[dict] = None
        if io.checkpoint_stride > 0:
            self.checkpoints = CheckpointManager(io.checkpoint_path)
            self._maybe_resume()
            if self.step_count > 0:
                self._resume_lineage = self._find_lineage()
        # Telemetry last: the metric reference must see the post-resume
        # state, and the guard's postmortem callback needs the
        # checkpoint manager.
        self._obs = self._build_obs()

    # ------------------------------------------------------------------ build
    def _build_obs(self):
        """Wire the ``observability:`` block into this run (or None).

        Builds the resolved :class:`jaxstream.obs.metrics.MetricSet`,
        the loop-carry metric function the instrumented segments trace,
        the :class:`HealthMonitor` (policy != 'off') and the JSONL sink
        (process 0 only), and records the step-0 reference values the
        drift columns are measured against (on a resumed run that
        reference is the resume point).
        """
        o = self.config.observability
        if o.interval <= 0:
            return None
        if self._tt_keys is not None:
            raise ValueError(
                "observability.interval > 0 requires model.numerics: "
                "dense (the factored TT state has no in-loop metric "
                "path; eager Simulation.diagnostics() still works)")
        tb = self.config.parallelization.temporal_block
        if o.interval % tb:
            raise ValueError(
                f"observability.interval={o.interval} must be a multiple "
                f"of parallelization.temporal_block={tb} (samples are "
                "taken at stepper-call boundaries)")
        # Segments are gcd(history_stride, checkpoint_stride) steps long
        # (Simulation.run); an interval longer than that would truncate
        # every segment's sample count to ZERO and silently disable the
        # metrics AND the guards the user just configured — reject the
        # misconfiguration instead.
        io = self.config.io
        strides = [s for s in (io.history_stride, io.checkpoint_stride)
                   if s > 0]
        seg = math.gcd(*strides) if strides else 0
        if seg and o.interval > seg:
            raise ValueError(
                f"observability.interval={o.interval} exceeds the "
                f"compiled segment length {seg} (= gcd of "
                f"io.history_stride/io.checkpoint_stride): every segment "
                "would take zero samples and the guards could never "
                "fire; lower the interval or raise the io strides")
        p, tc = self.config.physics, self.config.time
        ex = {k: v for k, v in self.state.items() if k in _PROG_KEYS}
        # Ensemble runs with guards on get one nonfinite row PER member
        # appended, so a guard event (and the postmortem checkpoint it
        # triggers) names the offending member instead of only an
        # all-member count (round 11).
        ms = obs_metrics.build_metric_set(
            self.grid, self.model, ex, o.metrics, tc.dt, p.gravity,
            member_rows=(self.members > 1 and o.guards != "off"))
        if self._fused_step is not None:
            m = self.model
            if self._fused_post is not None:
                # 16-bit carry: metrics must see absolute f32 fields.
                loop_prep = (lambda y, _m=m, _p=self._fused_post:
                             _p(_m.restrict_state(y)))
            else:
                loop_prep = m.restrict_state
        else:
            def loop_prep(y):
                return {k: v for k, v in y.items() if k in _PROG_KEYS}

        def metric_fn(y, t):
            del t
            return ms.values(loop_prep(y))

        monitor = None
        if o.guards != "off":
            monitor = HealthMonitor(ms.names, o.guards, o.cfl_limit,
                                    on_breach=self._postmortem_checkpoint)
        sink = None
        if o.sink and jax.process_index() == 0:
            cfg = self.config
            manifest = run_manifest(
                ms.names, o.interval, o.guards,
                config={
                    "grid_n": cfg.grid.n, "dtype": cfg.grid.dtype,
                    "dt": tc.dt, "scheme": tc.scheme,
                    "initial_condition": cfg.model.initial_condition,
                    "numerics": cfg.model.numerics,
                    "members": self.members,
                    "num_devices": cfg.parallelization.num_devices,
                    "use_shard_map": cfg.parallelization.use_shard_map,
                    "temporal_block": tb,
                    # Round 16: the run's capability plan + proof
                    # verdict ride the manifest so telemetry names the
                    # verified execution strategy.
                    "plan": self.plan.key(),
                    "proof": (self.proof.to_json()
                              if self.proof is not None else None),
                })
            sink = TelemetrySink(o.sink, manifest)
            if self._resume_lineage is not None:
                # Typed lineage stamp (round 20): this run descends
                # from the named crash bundle's incident; the
                # postmortem CLI joins the two files on it.  Only
                # written when a resume really happened AND a committed
                # bundle exists — otherwise the sink stays
                # byte-identical to round 19.
                sink.write({
                    "kind": "resume",
                    "bundle": self._resume_lineage["bundle"],
                    "checkpoint_step":
                        self._resume_lineage["checkpoint_step"],
                    "step": self.step_count,
                    "path": self._resume_lineage["path"],
                })
        # Step-0 reference for the drift columns: one eager evaluation
        # of the metric vector on the initial (or resumed) state.
        ref = np.asarray(jax.device_get(jax.jit(ms.values)(ex)))
        log.info("observability: %d metrics every %d steps (guards=%s%s)",
                 ms.k, o.interval, o.guards,
                 f", sink={o.sink}" if o.sink else "")
        return _ObsRuntime(o, ms, metric_fn, monitor, sink, ref)

    def _resolve_precision(self):
        """``precision:`` + ``model.nu4_mode`` config -> fused-stepper
        kwargs and carry encode/decode hooks.

        Returns ``(kwargs, encode, decode)``: ``kwargs`` feed
        ``make_fused_step`` (``precision=`` stage/strips policy,
        ``nu4_mode=``, and the ``carry_dtype``/``h_offset``/``h_scale``
        encoding triple); ``encode`` wraps the carry prep, ``decode``
        every carry exit (both None for the f32 carry).  All-default
        config returns ``({}, None, None)`` — the stepper factories are
        called exactly as before, bit-for-bit.  The mixed16 offset is
        the initial state's h mid-range, the same choice bench.py's
        gated mixed16 variant makes; re-encoding at segment boundaries
        is idempotent (round-to-grid of an on-grid value), so segment
        length never changes the trajectory.
        """
        from .ops.pallas.precision import (encode_strips,
                                           resolve_stage_precision)

        pcfg = self.config.precision
        kw = {}
        if self.config.model.nu4_mode != "split":
            kw["nu4_mode"] = self.config.model.nu4_mode
        if pcfg.stage != "f32" or pcfg.strips not in ("auto", "f32"):
            kw["precision"] = {"stage": pcfg.stage, "strips": pcfg.strips}
        # Under a 16-bit strips policy the stage kernels EMIT bf16
        # strips, so the initial carry's strips must be narrowed before
        # the jitted segment loop (fori_loop carry types are fixed);
        # composed below with the carry encoding when both are on.
        pol = resolve_stage_precision(kw.get("precision"))
        narrow = ((lambda y, _p=pol: encode_strips(y, _p))
                  if pol is not None and pol.strips == "bf16" else None)
        if pcfg.carry == "f32":
            return kw, narrow, None
        if pcfg.carry not in ("bf16", "mixed16"):
            raise ValueError(
                f"precision.carry={pcfg.carry!r}; valid: 'f32', 'bf16', "
                "'mixed16'")
        if self.members > 1:
            plan_rules.fail("carry-needs-single-member")
        m = self.model
        if m is None or not hasattr(m, "encode_carry"):
            plan_rules.fail("carry-needs-covariant")
        import jax.numpy as jnp

        h = self.state["h"]
        if pcfg.carry == "mixed16":
            # bench.py's gated encoding, ONE shared definition.
            from .ops.pallas.precision import mixed16_encoding

            cd, off, hs = mixed16_encoding(h)
        else:
            # bf16 h-anomaly + bf16 u: the wider-mass-band encoding
            # (demoted from bench's default gate; kept for experiments).
            off = float(0.5 * (float(jnp.min(h)) + float(jnp.max(h))))
            cd, hs = (jnp.bfloat16, jnp.bfloat16), 1.0
        kw.update(carry_dtype=cd, h_offset=off, h_scale=hs)
        if narrow is not None:
            enc = lambda s: narrow(m.encode_carry(s, cd, off, hs))
        else:
            enc = lambda s: m.encode_carry(s, cd, off, hs)
        dec = lambda s: m.decode_carry(s, off, hs)
        return kw, enc, dec

    def _postmortem_checkpoint(self, event=None):
        """'checkpoint_and_raise' breach callback: save the CURRENT
        (possibly corrupt) state for inspection — the HealthError's
        last-good step is the restart target, this save is evidence.
        ``event``: the guard event (the monitor passes it when the
        callback accepts one); its ``member`` attribution — when the
        breach names one ensemble member — is recorded in the
        checkpoint's ``meta`` so the postmortem says WHICH member blew
        up (round 11).

        Async-pipeline aware: queued background saves are drained FIRST
        (the Orbax manager is used serially — writer FIFO, then this),
        and under the async loop ``self.state`` is the latest
        *dispatched* segment's output, possibly still in flight — the
        save blocks on it, which is exactly what "current state" means
        once the pipeline runs ahead."""
        if self.checkpoints is None:
            log.warning(
                "guard policy 'checkpoint_and_raise' with no checkpoint "
                "manager (io.checkpoint_stride is 0) — raising without "
                "a postmortem save")
            return
        if self._writer is not None and self._writer.alive:
            try:
                self._writer.flush()
            except Exception as e:  # the postmortem save must still run
                log.warning("async writer flush before postmortem failed "
                            "(%s: %s)", type(e).__name__, e)
        t = self.t
        if self._t_carry is not None:
            try:
                t = float(jax.device_get(self._t_carry))
            except Exception:
                pass
        member = (event or {}).get("member")
        self.checkpoints.save(
            self.step_count, self.state, t,
            meta={"postmortem": True, "member": member})
        log.warning("guard breach: postmortem checkpoint saved at step "
                    "%d%s", self.step_count,
                    f" (member {member})" if member is not None else "")

    # ------------------------------------------------- crash forensics
    def _find_lineage(self) -> Optional[dict]:
        """The latest committed crash bundle in the configured flight
        dir, verified readable — the prior incarnation this resumed
        run descends from.  None when no flight dir is configured, no
        bundle exists, or the newest bundle is torn (a torn black box
        must not block the restart; the postmortem CLI reports it)."""
        fdir = flight.resolve_flight_dir(self.config)
        bdir = flight.latest_bundle(fdir) if fdir else None
        if bdir is None:
            return None
        try:
            manifest, _ = flight.read_bundle(bdir)
        except flight.TornBundleError as e:
            log.warning("resume: latest crash bundle %s is torn (%s); "
                        "resuming without lineage", bdir, e)
            return None
        return {"bundle": manifest["bundle_id"], "path": bdir,
                "checkpoint_step": self.step_count}

    def _flight_dump(self, reason: str) -> None:
        """Flush the flight ring as an atomic crash bundle + typed
        ``flight``/``crash`` sink records.  Once per incident (the
        first failure's evidence must not be overwritten by unwind
        noise); no-op without ``observability.flight_dir``; never
        raises (forensics must not mask the in-flight exception)."""
        if self._flight_dumped:
            return
        fdir = flight.resolve_flight_dir(self.config)
        if not fdir:
            return
        self._flight_dumped = True
        try:
            from .utils import jax_compat

            cfg = self.config
            ckpt = None
            if self.checkpoints is not None:
                step = self.checkpoints.latest_step()
                if step is not None:
                    ckpt = {"step": step, "path": self.checkpoints.path}
            writer = flight.BundleWriter(fdir)
            writer.commit(
                reason,
                config={"grid_n": cfg.grid.n, "dt": cfg.time.dt,
                        "members": self.members,
                        "step": self.step_count,
                        "guards": cfg.observability.guards},
                proofs={"run": (self.proof.to_json()
                                if self.proof is not None else None)},
                device_memory=jax_compat.device_memory_stats(
                    jax.devices()[0]),
                checkpoint=ckpt)
            obs = self._obs
            if obs is not None and obs.sink is not None:
                events, threads, dropped = flight.RECORDER.dump()
                obs.sink.write({
                    "kind": "flight", "events": len(events),
                    "threads": len(threads), "dropped": dropped})
                obs.sink.write({
                    "kind": "crash", "bundle": writer.bundle_id,
                    "path": writer.path, "reason": reason})
        except Exception as e:
            log.warning("flight bundle dump failed (%s: %s)",
                        type(e).__name__, e)

    def _ensure_writer(self) -> BackgroundWriter:
        if self._writer is None or not self._writer.alive:
            self._writer = BackgroundWriter(
                self.config.io.async_pipeline.max_pending_segments)
        return self._writer

    def close(self):
        """Release background resources: drain and join the async
        writer thread, close the telemetry sink.  Idempotent.  Call it
        (or use the Simulation as a context manager) when done with a
        run whose ``io.async_pipeline.enabled`` is true — the writer is
        a daemon thread, so skipping close leaks no process, but the
        thread-hygiene tests hold this to zero."""
        if self._writer is not None:
            w, self._writer = self._writer, None
            w.close()
        if self._obs is not None and self._obs.sink is not None:
            self._obs.sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _build_model_and_state(self):
        cfg = self.config
        m, p, g = cfg.model, cfg.physics, self.grid
        name = m.initial_condition
        family = IC_FAMILY.get(name)
        if family is None:
            raise ValueError(
                f"unknown initial_condition {name!r}; valid: {sorted(IC_FAMILY)}"
            )
        allowed = {"auto", family}
        if family == "shallow_water":
            allowed.add("shallow_water_cov")
        if m.name not in allowed:
            raise ValueError(
                f"model.name={m.name!r} is incompatible with "
                f"initial_condition={name!r} (which drives {family!r})"
            )
        fields = self._ic_fields(name, family)
        if family == "advection":
            model = TracerAdvection(g, fields["wind"], scheme=m.scheme,
                                    limiter=m.limiter)
            return model, model.initial_state(fields["q"])
        if family == "diffusion":
            model = ThermalDiffusion(g, kappa=p.diffusivity)
            return model, model.initial_state(fields["T"])
        cls = ShallowWater
        if m.name == "shallow_water_cov":
            from .models.shallow_water_cov import CovariantShallowWater

            cls = CovariantShallowWater
        model = cls(
            g, gravity=p.gravity, omega=p.omega, b_ext=fields["b_ext"],
            scheme=m.scheme, limiter=m.limiter, nu4=p.hyperdiffusion,
            backend=m.backend,
        )
        return model, model.initial_state(fields["h"], fields["v"])

    def _ic_fields(self, name: str, family: str):
        """The extended IC fields for one IC-family — the single
        dispatch shared by the dense and TT tiers, so their initial
        states can never drift apart (the dense twin is the TT parity
        oracle)."""
        cfg = self.config
        m, p, g = cfg.model, cfg.physics, self.grid
        if family == "advection":
            u0 = 2 * math.pi * g.radius / (12 * 86400.0)
            return {"wind": ics.solid_body_wind(g, u0, alpha_rot=m.ic_angle),
                    "q": ics.cosine_bell(g)}
        if family == "diffusion":
            return {"T": ics.checkerboard(g)}
        b_ext = None
        if name == "tc2":
            h, v = ics.williamson_tc2(g, p.gravity, p.omega,
                                      alpha_rot=m.ic_angle)
        elif name == "tc5":
            h, v, b_ext = ics.williamson_tc5(g, p.gravity, p.omega)
        elif name == "tc6":
            h, v = ics.williamson_tc6(g, p.gravity, p.omega)
        else:
            h, v = ics.galewsky(g, p.gravity, p.omega)
        return {"h": h, "v": v, "b_ext": b_ext}

    def _build_ensemble_state(self):
        """Batched perturbed-IC ensemble state ``{"h": (B, 6, n, n),
        "u"|"v": (c, B, 6, n, n)}`` — member 0 unperturbed, members
        1..B-1 from :func:`...initial_conditions.perturbed_ensemble`
        (height-only, deterministic in ``ensemble.seed``)."""
        cfg = self.config
        ens = cfg.ensemble
        name = cfg.model.initial_condition
        family = IC_FAMILY.get(name)
        if family != "shallow_water":
            raise ValueError(
                f"ensemble.members > 1 supports the shallow-water family "
                f"(tc2/tc5/tc6/galewsky); initial_condition={name!r} "
                f"drives {family!r}")
        fields = self._ic_fields(name, family)
        h_b = ics.perturbed_ensemble(self.grid, fields["h"], ens.members,
                                     seed=ens.seed,
                                     amplitude=ens.amplitude)
        states = [self.model.initial_state(h_b[i], fields["v"])
                  for i in range(ens.members)]
        vkey = "u" if "u" in states[0] else "v"
        return {"h": jnp.stack([s["h"] for s in states]),
                vkey: jnp.stack([s[vkey] for s in states], axis=1)}

    def _build_tt(self):
        """The factored-panel ("Numerics (TT)", pdf p.7) solver tier.

        Single-device research numerics: every prognostic is a rank-r
        factor pair stored in the state dict as ``name__ttA`` /
        ``name__ttB`` (the same naming the TT history/checkpoint layers
        use), so history snapshots and Orbax checkpoints are compressed
        for free.  Returns ``(state, step)`` with ``step(y, t) -> y``
        over the flat dict.
        """
        from .tt.sphere import factor_panels, make_tt_sphere_advection
        from .tt.sphere_diffusion import make_tt_sphere_diffusion
        from .tt.sphere_swe import (
            covariant_from_cartesian, make_tt_sphere_swe,
        )

        cfg = self.config
        m, p, g, tc = cfg.model, cfg.physics, self.grid, cfg.time
        par = cfg.parallelization
        sharded = par.num_devices > 1 or par.use_shard_map
        if sharded and par.num_devices != 6:
            hint = (" (or set use_shard_map: false for the "
                    "single-device tier)" if par.num_devices == 1
                    else "")
            raise ValueError(
                "model.numerics='tt' shards one face per device over a "
                "6-device ('panel',) mesh (jaxstream.tt.shard); set "
                "parallelization.num_devices: 6"
                f"{hint} — got {par.num_devices}")
        if sharded and par.tiles_per_edge > 1:
            raise ValueError(
                "model.numerics='tt' supports tiles_per_edge: 1 only "
                "(the factored state is O(n r) per panel; intra-panel "
                f"tiling is not meaningful) — got {par.tiles_per_edge}")
        if g.halo < 1:
            raise ValueError(
                "model.numerics='tt' needs grid.halo >= 1 (the factored "
                "edge statics read the innermost ghost cell at index "
                f"halo-1; with halo={g.halo} that wraps to the opposite "
                "panel edge); set grid.halo: 1 or higher")
        if tc.scheme not in ("ssprk3", "euler"):
            raise ValueError(
                f"model.numerics='tt' supports time.scheme 'ssprk3' or "
                f"'euler', not {tc.scheme!r}")
        if p.hyperdiffusion != 0.0:
            raise ValueError(
                "model.numerics='tt' has no nu4 hyperdiffusion; set "
                "physics.hyperdiffusion: 0 (or run numerics: dense)")
        rank = m.tt_rank
        if not 0 < rank <= g.n:
            raise ValueError(
                f"model.tt_rank={rank} must be in [1, grid.n={g.n}] "
                "(the SVD factors cap at bond dim n, but the step's "
                "rounding rank is exactly tt_rank — a larger value "
                "would break the integration carry shapes)")
        name = m.initial_condition
        family = IC_FAMILY.get(name)
        if family is None:
            raise ValueError(
                f"unknown initial_condition {name!r}; valid: "
                f"{sorted(IC_FAMILY)}")
        if m.name not in ("auto", family):
            raise ValueError(
                f"model.name={m.name!r} is incompatible with "
                f"initial_condition={name!r} (which drives {family!r}; "
                "the TT tier has no model-name variants — use 'auto')")
        if (m.scheme, m.limiter, m.backend) != ("plr", "mc", "jnp"):
            log.info("numerics='tt' uses its own centered factored "
                     "discretization; model.scheme/limiter/backend are "
                     "ignored")
        fac = lambda q: factor_panels(np.asarray(q, np.float64), rank)
        fields = self._ic_fields(name, family)

        mesh = None
        if sharded:
            from .parallel.mesh import _pick_devices
            from .tt.shard import (
                make_tt_sphere_advection_sharded,
                make_tt_sphere_diffusion_sharded,
                make_tt_sphere_swe_sharded, panel_mesh)

            mesh = panel_mesh(_pick_devices(par.device_type, 6))

        rounding = m.tt_rounding
        if rounding == "auto":
            # Forced nonlinear flows need a near-optimal-truncation
            # tier (DESIGN.md stability envelope); the linear families
            # keep the cheaper cross rounding.  Exact svd is CPU-only
            # — TPU f32 QR/eigh lose orthogonality at production bond
            # sizes (cross.svd_lowrank docstring) — so accelerators
            # get the matmul-only rsvd tier instead.
            if family == "shallow_water":
                # The platform the step will EXECUTE on: a sharded run
                # is pinned to its mesh's devices; a single-device run
                # lands on the process default backend regardless of
                # device_type (nothing pins it).
                import jax

                if sharded and par.device_type != "default":
                    exec_backend = par.device_type
                else:
                    exec_backend = jax.default_backend()
                if exec_backend == "cpu":
                    rounding = "svd"
                else:
                    # Accelerators cannot run the exact tier (f32
                    # QR/eigh are measured-broken on the v5e,
                    # cross.svd_lowrank docstring) — but round 5's
                    # matmul-only rsvd tier is near-optimal (<=1.04x
                    # the exact truncation, tests/
                    # test_tt_rounding_tiers.py) and TPU-validated:
                    # mountain-forced TC5 C96 integrates 5+ sim-days
                    # finite on the real chip at the exact tier's f32
                    # error level (DESIGN.md stability envelope,
                    # round-5 addendum).  'aca' would NaN TC5 within
                    # half a sim-day; never auto-select it here.
                    rounding = "rsvd"
            else:
                rounding = "aca"
        elif rounding not in ("aca", "svd", "rsvd", "host_svd"):
            raise ValueError(
                f"model.tt_rounding={rounding!r}: use 'auto', 'aca', "
                "'svd', 'rsvd' or 'host_svd'")
        if (rounding in ("svd", "rsvd", "host_svd")
                and family != "shallow_water"):
            raise ValueError(
                f"model.tt_rounding={rounding!r} applies to the "
                "shallow-water family only (advection/diffusion run "
                "'aca'); set tt_rounding: auto")
        if m.tt_kappa != 0.0 and family != "shallow_water":
            raise ValueError(
                "model.tt_kappa (in-step velocity dissipation) applies "
                "to the shallow-water family only; set tt_kappa: 0 for "
                f"{family!r} runs")

        if family == "advection":
            if sharded:
                tt_step = make_tt_sphere_advection_sharded(
                    g, fields["wind"], tc.dt, rank, mesh,
                    scheme=tc.scheme)
            else:
                tt_step = make_tt_sphere_advection(
                    g, fields["wind"], tc.dt, rank, scheme=tc.scheme)
            tt_step = self._tt_block(tt_step, par.temporal_block)
            keys = ("q",)
            pairs = (fac(g.interior(fields["q"])),)
            single = True
        elif family == "diffusion":
            if sharded:
                tt_step = make_tt_sphere_diffusion_sharded(
                    g, p.diffusivity, tc.dt, rank, mesh,
                    scheme=tc.scheme)
            else:
                tt_step = make_tt_sphere_diffusion(
                    g, p.diffusivity, tc.dt, rank, scheme=tc.scheme)
            tt_step = self._tt_block(tt_step, par.temporal_block)
            keys = ("T",)
            pairs = (fac(g.interior(fields["T"])),)
            single = True
        else:
            b_ext = fields["b_ext"]
            kw = dict(hs=b_ext, omega=p.omega, gravity=p.gravity,
                      scheme=tc.scheme, kappa=m.tt_kappa,
                      rounding=rounding,
                      temporal_block=par.temporal_block)
            tt_step = (make_tt_sphere_swe_sharded(
                           g, tc.dt, rank, mesh,
                           overlap_exchange=par.overlap_exchange, **kw)
                       if sharded else
                       make_tt_sphere_swe(g, tc.dt, rank, **kw))
            ua, ub = covariant_from_cartesian(g, fields["v"])
            keys = ("h", "ua", "ub")
            pairs = (fac(g.interior(fields["h"])), fac(ua), fac(ub))
            single = False
            self._tt_hs = b_ext
        self._tt_keys = keys
        log.info("using factored (TT) %s tier, rank %d%s%s", family, rank,
                 f", rounding {rounding}" if family == "shallow_water"
                 else "",
                 ", panel-sharded over 6 devices" if sharded else "")

        state = {}
        for k, (A, B) in zip(keys, pairs):
            state[k + "__ttA"] = A
            state[k + "__ttB"] = B
        if sharded:
            from .tt.shard import shard_factored_state

            state = shard_factored_state(state, mesh)

        def step(y, t):
            del t
            ps = tuple((y[k + "__ttA"], y[k + "__ttB"]) for k in keys)
            out = tt_step(ps[0]) if single else tt_step(ps)
            if single:
                out = (out,)
            return {kk + s: pair[i]
                    for kk, pair in zip(keys, out)
                    for i, s in ((0, "__ttA"), (1, "__ttB"))}

        # The SWE factory fuses temporal_block steps internally (and
        # _tt_block does it for the linear families), so the flat-dict
        # wrapper advances that many steps per call.
        if par.temporal_block > 1:
            step.steps_per_call = par.temporal_block
        return state, step

    @staticmethod
    def _tt_block(tt_step, k: int):
        """Exact k-step fusion of a single-pair TT step (the linear
        families' form of ``parallelization.temporal_block`` — the SWE
        factories take the knob natively)."""
        if k <= 1:
            return tt_step

        def block(pair):
            for _ in range(k):
                pair = tt_step(pair)
            return pair

        return block

    def _tt_dense(self, key: str):
        """Reconstruct one factored prognostic to a dense (6, n, n)."""
        from .tt.sphere import unfactor_panels

        return unfactor_panels((self.state[key + "__ttA"],
                                self.state[key + "__ttB"]))

    # ---------------------------------------------------------------- running
    def _maybe_resume(self):
        step = self.checkpoints.latest_step()
        if step is None:
            return
        # Host-side restore: inspect (and possibly regrid) before any
        # device placement — a sharded-state restart must never
        # materialize the full arrays on one device.
        from .io.regrid import infer_resolution, regrid_state

        state, self.t = self.checkpoints.restore_host(step)
        n_new = self.config.grid.n
        ckpt_tt = any(k.endswith("__ttA") for k in state)
        run_tt = self._tt_keys is not None
        if ckpt_tt != run_tt:
            raise ValueError(
                "checkpoint/run numerics mismatch: the checkpoint is "
                f"{'factored (TT)' if ckpt_tt else 'dense'} but the run is "
                f"{'factored (TT)' if run_tt else 'dense'}; set "
                "model.numerics to match, or convert with "
                "jaxstream.tt.store.compress_state/decompress_state")
        if run_tt:
            want = {k + s for k in self._tt_keys
                    for s in ("__ttA", "__ttB")}
            if set(state) != want:
                raise ValueError(
                    f"TT checkpoint prognostics {sorted(state)} do not "
                    f"match this run's {sorted(want)}: the checkpoint "
                    "was written by a different model family — point "
                    "io.checkpoint_path somewhere else")
            n_ckpt = next(np.asarray(v).shape[1] for k, v in state.items()
                          if k.endswith("__ttA"))
            r_ckpt = next(np.asarray(v).shape[2] for k, v in state.items()
                          if k.endswith("__ttA"))
            if n_ckpt != n_new:
                raise ValueError(
                    f"TT checkpoint is C{n_ckpt} but the run is C{n_new}: "
                    "cross-resolution resume is dense-only — restart "
                    "dense, or decompress_state + regrid manually")
            if r_ckpt != self.config.model.tt_rank:
                raise ValueError(
                    f"TT checkpoint rank {r_ckpt} != run tt_rank "
                    f"{self.config.model.tt_rank}: set model.tt_rank: "
                    f"{r_ckpt}, or re-factor the state manually")
            self.state = jax.tree_util.tree_map(jnp.asarray, state)
            self.step_count = step
            log.info("resumed factored (TT) state from checkpoint step %d "
                     "(t=%.0f s)", step, self.t)
            return
        if self.members > 1:
            # Ensemble resume (round 11): the checkpoint holds the
            # member-batched arrays; validate the batch shape against
            # this run and place directly (cross-resolution regrid is
            # dense-unbatched-only).
            hb = np.asarray(state["h"]) if "h" in state else None
            if hb is None or hb.ndim != 4:
                raise ValueError(
                    "ensemble.members > 1 but the checkpoint state is "
                    "not member-batched — it was written by an "
                    "unbatched run; point io.checkpoint_path elsewhere")
            if hb.shape[0] != self.members:
                raise ValueError(
                    f"checkpoint has {hb.shape[0]} ensemble members but "
                    f"the run configures {self.members}; set "
                    f"ensemble.members: {hb.shape[0]} (per-member resume: "
                    "CheckpointManager.restore_member)")
            if hb.shape[-1] != n_new:
                raise ValueError(
                    f"ensemble checkpoint is C{hb.shape[-1]} but the run "
                    f"is C{n_new}: cross-resolution resume is "
                    "unbatched-dense-only")
            if self.setup is not None and self.setup.mesh is not None:
                state = shard_ensemble_state(self.setup, state)
            else:
                state = jax.tree_util.tree_map(jnp.asarray, state)
            self.state = state
            self.step_count = step
            log.info("resumed %d-member ensemble state from checkpoint "
                     "step %d (t=%.0f s)", self.members, step, self.t)
            return
        n_ckpt = infer_resolution(state)   # raises clearly on ambiguity
        if n_ckpt != n_new:
            # Resolution-aware resume (SURVEY.md §5): conservative
            # area-weighted regrid of every state field onto the run's
            # grid (io/regrid.py), then shard for the run's mesh.
            log.info("resuming across resolutions: checkpoint C%d -> "
                     "run C%d (conservative regrid)", n_ckpt, n_new)
            state = regrid_state(state, n_new,
                                 dtype=self.grid.area.dtype)
        if self.setup is not None and self.setup.mesh is not None:
            from .parallel.mesh import shard_state

            state = shard_state(self.setup, state)
        else:
            state = jax.tree_util.tree_map(jnp.asarray, state)
        self.state = state
        self.step_count = step
        log.info("resumed from checkpoint step %d (t=%.0f s)", step, self.t)

    def _build_segment_fn(self, k: int):
        """Compile the ``k``-step segment callable (cached per ``k``).

        Without observability this is the historical pair of paths
        (fused-carry / classic ``jit_integrate``), signature
        ``fn(y, t) -> (y, t)``.  With ``observability.interval > 0``
        and at least one sample landing inside the segment, the loop is
        :func:`jaxstream.stepping.integrate_with_metrics` instead —
        same state ops in the same order — with signature
        ``fn(y, t, step0) -> (y, t, buf)`` and an ``obs_samples``
        attribute carrying the buffer's column count.

        Known trade: the metric buffer's ``(k_metrics, samples)`` shape
        is static, so instrumented segments compile once per DISTINCT
        segment length instead of the classic tier's single
        traced-nsteps executable.  A run has at most two distinct
        lengths (the stride gcd and the final remainder), so this is
        one extra compile per run at worst.
        """
        dt = self.config.time.dt
        active = (self._fused_step if self._fused_step is not None
                  else self._step)
        # Temporal blocking: a blocked stepper advances
        # steps_per_call steps per call, so the integrator runs
        # k/spc calls of span spc*dt each (t advances identically
        # — the block's sub-step times are sequential dt adds).
        spc = getattr(active, "steps_per_call", 1)
        if k % spc:
            raise ValueError(
                f"segment of {k} steps is not a multiple of "
                f"parallelization.temporal_block={spc}; make "
                "io.history_stride/io.checkpoint_stride and the "
                "total step count multiples of temporal_block")
        # Both paths DONATE the state carry (round-7 satellite,
        # parallelization.donate_state to opt out): segments are
        # ping-pong by construction (self.state is always replaced
        # by the result), so XLA aliases the input and output state
        # instead of double-buffering every prognostic array for
        # the whole loop.  Accelerator callers holding their own
        # reference to sim.state across run() calls must copy it
        # (np.asarray) first — donation consumes the buffers.
        donate = self.config.parallelization.donate_state
        obs = self._obs
        samples = 0
        if obs is not None:
            every = obs.cfg.interval // spc
            samples = (k // spc) // every
        if samples > 0:
            mfn, fault = obs.metric_fn, obs.cfg.fault_step
            if self._fused_step is not None:
                m, fused, prep = self.model, self._fused_step, \
                    self._fused_prep
                post = self._fused_post or (lambda s: s)

                def fn(y, t, step0, _n=k // spc, _dt=dt * spc,
                       _e=every, _s=samples):
                    y_c = prep(y)
                    y_c, t, buf = integrate_with_metrics(
                        fused, y_c, t, _n, _dt, mfn, _e, _s, step0,
                        steps_per_call=spc, fault_step=fault)
                    return post(m.restrict_state(y_c)), t, buf
            else:
                step = self._step

                def fn(y, t, step0, _n=k // spc, _dt=dt * spc,
                       _e=every, _s=samples):
                    return integrate_with_metrics(
                        step, y, t, _n, _dt, mfn, _e, _s, step0,
                        steps_per_call=spc, fault_step=fault)
            jfn = jax.jit(fn, donate_argnums=(0,) if donate else ())

            def call(y, t, step0, _f=jfn):
                return _f(y, t, step0)

            call.obs_samples = samples
            return call
        if self._fused_step is not None:
            m, fused = self.model, self._fused_step

            prep = self._fused_prep
            post = self._fused_post or (lambda s: s)

            def fn(y, t, _k=k // spc, _dt=dt * spc):
                y_c = prep(y)
                y_c, t = integrate(fused, y_c, t, _k, _dt)
                return post(m.restrict_state(y_c)), t

            return jax.jit(fn, donate_argnums=(0,) if donate else ())
        # unroll=1: the generic tiers' steps are ms-scale (TT
        # roundings, classic jnp), where the while-carry's
        # ~us-scale copies are invisible but a 4x-traced step
        # graph would multiply compile time.  One jit_integrate
        # executable serves every segment length (nsteps rides
        # as a traced operand).
        if self._classic_run is None:
            self._classic_run = jit_integrate(
                self._step, dt * spc, unroll=1, donate=donate)
        run = self._classic_run

        def fn(y, t, _k=k // spc):
            return run(y, t, _k)

        return fn

    def _segment_fn(self, k: int) -> Callable:
        fn = self._segment_cache.get(k)
        if fn is None:
            fn = self._build_segment_fn(k)
            self._segment_cache[k] = fn
        return fn

    def _run_segment(self, k: int):
        fn = self._segment_fn(k)
        if getattr(fn, "obs_samples", 0) > 0:
            # Instrumented segment: the metric buffer rides the compiled
            # loop and is fetched with ONE device->host transfer here —
            # which also blocks on the segment, so `wall` is the true
            # segment wall time.
            step0, t0 = self.step_count, self.t
            wall0 = time.perf_counter()
            self.state, t, buf = fn(self.state, self.t,
                                    jnp.asarray(step0))
            host = obs_metrics.fetch_buffer(buf)
            wall = time.perf_counter() - wall0
            self.t = float(t)
            self.step_count += k
            self._ingest_telemetry(host, step0, t0, k, wall,
                                   self.step_count, self.t)
            return
        self.state, t = fn(self.state, self.t)
        self.t = float(t)
        self.step_count += k

    def _ingest_telemetry(self, host, step0: int, t0: float, k: int,
                          wall: float, step_end: int, t_end: float,
                          emit: Optional[Callable] = None):
        """One fetched segment buffer -> sink record + guard check.

        ``host``: the ``(k_metrics, samples)`` numpy buffer; sample j
        is global step ``step0 + (j+1)*interval``.  Writes the segment
        record first so a guard raise leaves the evidence on disk, then
        runs the monitor (guard events are flushed even when the policy
        raises).  ``emit`` overrides the record destination — the async
        pipeline routes records through its background writer (FIFO
        with the history/checkpoint tasks) instead of writing inline.
        The record's ``host_wait_s`` is the host-side I/O time that
        blocked the next dispatch since the previous record (the
        quantity the async pipeline exists to shrink).
        """
        obs = self._obs
        if emit is None and obs.sink is not None:
            emit = obs.sink.write
        interval = obs.cfg.interval
        names = obs.ms.names
        samples = host.shape[1]
        steps = step0 + interval * np.arange(1, samples + 1)
        dt = self.config.time.dt
        ts = t0 + interval * dt * np.arange(1, samples + 1)
        drift = {}
        for i, n in enumerate(names):
            if n in obs_metrics.CONSERVED:
                v0 = float(obs.ref[i])
                d = float(host[i, -1]) - v0
                drift[n] = d / abs(v0) if v0 else d
        if emit is not None:
            rate = k / wall if wall > 0 else float("inf")
            chips = (self.config.parallelization.num_devices
                     if self.setup is not None else 1)
            host_wait, self._host_wait = self._host_wait, 0.0
            emit({
                "kind": "segment",
                "step": step_end, "t": t_end, "steps": k,
                "wall_s": wall, "steps_per_sec": rate,
                "sim_days_per_sec_per_chip":
                    rate * dt / 86400.0 / chips,
                "host_wait_s": host_wait,
                "metrics": {n: float(host[i, -1])
                            for i, n in enumerate(names)},
                "drift": drift,
                "samples": {"step": steps.tolist(),
                            **{n: host[i].tolist()
                               for i, n in enumerate(names)}},
            })
        if obs.monitor is not None:
            n0 = len(obs.monitor.events)
            try:
                obs.monitor.check(steps, ts, host)
            finally:
                if emit is not None:
                    for ev in obs.monitor.events[n0:]:
                        emit(ev)

    def _emit(self):
        if self.history is not None:
            self.history.append(
                {k: np.asarray(v) for k, v in self.state.items()}, self.t
            )
        # The per-emit log lines cost real host time (a diagnostics
        # compute + one blocking device_get) — only pay it when the
        # lines will actually be shown.  bench.py's io section relies
        # on this to compare sync vs async on identical I/O work.
        if log.isEnabledFor(logging.INFO):
            for k, v in self.diagnostics().items():
                log.info("step %-8d t=%10.0fs  %s=%.10g",
                         self.step_count, self.t, k, v)

    @staticmethod
    def _fetch_scalars(out) -> Dict[str, float]:
        """One host transfer for a whole dict of device scalars.

        The invariants are stacked on device (exact widening to the
        common dtype — an f32 value converts to the identical f64, so
        the returned floats are bitwise what per-metric ``float(x)``
        calls produced) and fetched with a SINGLE ``jax.device_get``:
        one blocking round trip per :meth:`diagnostics` call instead of
        one per metric.
        """
        if not out:
            return {}
        vals = [jnp.asarray(v) for v in out.values()]
        common = jnp.result_type(*[v.dtype for v in vals])
        host = np.asarray(
            jax.device_get(jnp.stack([v.astype(common) for v in vals])))
        return {k: float(host[i]) for i, k in enumerate(out)}

    def diagnostics(self) -> Dict[str, float]:
        """Scalar invariants for the current state (model-appropriate).

        All invariants are computed on device and fetched with one
        batched transfer (:meth:`_fetch_scalars`)."""
        g, s = self.grid, self.state
        out: Dict[str, Any] = {}
        if self._tt_keys is not None:
            from .tt.diagnostics import tt_total_mass

            pair = lambda k: (s[k + "__ttA"], s[k + "__ttB"])
            if self._tt_keys == ("q",):
                out["tracer_mass"] = tt_total_mass(g, pair("q"))
                out["tracer_max"] = jnp.max(self._tt_dense("q"))
            elif self._tt_keys == ("T",):
                out["heat"] = tt_total_mass(g, pair("T"))
            else:
                h = self._tt_dense("h")
                ua = self._tt_dense("ua")
                ub = self._tt_dense("ub")
                out["mass"] = diag.total_mass(g, h)
                sl = slice(g.halo, g.halo + g.n)
                aa = jnp.asarray(g.a_a)[:, :, sl, sl]
                ab = jnp.asarray(g.a_b)[:, :, sl, sl]
                v = aa * ua[None] + ab * ub[None]
                b_int = (g.interior(jnp.asarray(self._tt_hs))
                         if self._tt_hs is not None else 0.0)
                p = self.config.physics
                out["energy"] = diag.total_energy(g, h, v, p.gravity,
                                                  b_int)
            return self._fetch_scalars(out)
        if "h" in s and self.members > 1:
            # Member-0 invariants plus the ensemble's height spread (the
            # quantity a perturbed-IC run exists to grow): per-cell
            # cross-member std, reported at its max.
            p = self.config.physics
            vkey = "u" if "u" in s else "v"
            s0 = {"h": s["h"][0], vkey: s[vkey][:, 0]}
            out["mass_m0"] = diag.total_mass(g, s0["h"])
            b = self.model.b_ext
            b_int = g.interior(b) if b is not None else 0.0
            v = s0["v"] if "v" in s0 else self.model.to_cartesian(s0)
            out["energy_m0"] = diag.total_energy(g, s0["h"], v,
                                                 p.gravity, b_int)
            out["h_spread_max"] = jnp.max(jnp.std(
                s["h"].astype(jnp.float32), axis=0))
            return self._fetch_scalars(out)
        if "h" in s:
            p = self.config.physics
            out["mass"] = diag.total_mass(g, s["h"])
            b = self.model.b_ext
            b_int = g.interior(b) if b is not None else 0.0
            # Covariant models carry "u"; energy wants the Cartesian vector.
            v = s["v"] if "v" in s else self.model.to_cartesian(s)
            out["energy"] = diag.total_energy(g, s["h"], v, p.gravity,
                                              b_int)
        elif "q" in s:
            out["tracer_mass"] = diag.total_mass(g, s["q"])
            out["tracer_max"] = jnp.max(s["q"])
        elif "T" in s:
            out["heat"] = diag.total_mass(g, s["T"])
        return self._fetch_scalars(out)

    def total_steps(self) -> int:
        tc = self.config.time
        if tc.nsteps > 0:
            return tc.nsteps
        return int(round(tc.duration_days * 86400.0 / tc.dt))

    def run(self, nsteps: Optional[int] = None):
        """Integrate to ``nsteps`` total (default: the config's duration).

        Returns the final state.  History/checkpoints fire on their
        configured strides; everything between strides is one compiled
        device loop.  The returned state is ``self.state`` itself and —
        with the default ``parallelization.donate_state: true`` — will
        be CONSUMED by the first segment of any later ``run()`` on an
        accelerator: copy it (``np.asarray``) before continuing the
        simulation if you need to keep it.
        """
        total = self.total_steps() if nsteps is None else nsteps
        start = self.step_count
        io = self.config.io
        strides = [s for s in (io.history_stride, io.checkpoint_stride) if s > 0]
        seg = math.gcd(*strides) if strides else 0
        if self.step_count == 0 and self.history is not None:
            self._emit()  # record the initial condition
        obs = self._obs
        if (obs is not None and obs.sink is not None
                and not obs.wrote_initial):
            # Step-0 record: the drift columns' reference values, so the
            # report CLI's drift table has its anchor in-file.
            obs.sink.write({
                "kind": "segment", "step": self.step_count, "t": self.t,
                "steps": 0, "wall_s": 0.0, "steps_per_sec": 0.0,
                "sim_days_per_sec_per_chip": 0.0,
                "metrics": {n: float(obs.ref[i])
                            for i, n in enumerate(obs.ms.names)},
                "drift": {n: 0.0 for n in obs.ms.names
                          if n in obs_metrics.CONSERVED},
            })
            obs.wrote_initial = True
        wall0 = time.perf_counter()
        try:
            if io.async_pipeline.enabled:
                self._run_loop_async(total, seg, io)
            else:
                while self.step_count < total:
                    k = (min(seg, total - self.step_count) if seg
                         else total - self.step_count)
                    self._run_segment(k)
                    flight.record("segment", step=self.step_count, k=k)
                    if (io.history_stride
                            and self.step_count % io.history_stride == 0):
                        w0 = time.perf_counter()
                        self._emit()
                        self._host_wait += time.perf_counter() - w0
                    if (
                        self.checkpoints is not None
                        and self.step_count % io.checkpoint_stride == 0
                    ):
                        w0 = time.perf_counter()
                        self.checkpoints.save(self.step_count, self.state,
                                              self.t)
                        flight.record("checkpoint",
                                      step=self.step_count)
                        self._host_wait += time.perf_counter() - w0
        except BaseException as e:
            # HealthError / unhandled exception: flush the black box
            # BEFORE unwinding (the sink records ride the same open
            # sink; the bundle commit is atomic on its own).
            self._flight_dump(type(e).__name__)
            raise
        jax.block_until_ready(self.state)
        wall = time.perf_counter() - wall0
        ran = self.step_count - start
        days = ran * self.config.time.dt / 86400.0
        log.info(
            "ran %d steps (%.2f sim-days) in %.2fs wall -> %.2f sim-days/sec",
            ran, days, wall, days / wall if wall > 0 else float("inf"),
        )
        return self.state

    # ------------------------------------------------------- async pipeline
    def _run_loop_async(self, total: int, seg: int, io):
        """The ``io.async_pipeline`` form of the segment loop.

        Double-buffered: segment k+1 is dispatched with segment k's
        boundary still unresolved — its device->host copies were
        started (``copy_to_host_async`` via :class:`HostFetch`) right
        behind segment k's own dispatch, and only after segment k+1 is
        in flight does the host block on them.  Resolved boundaries
        hand their history appends / checkpoint saves / telemetry
        records to the bounded background writer; at the queue bound
        (``max_pending_segments``) ``submit`` blocks, which is the
        pipeline's backpressure — host snapshots stay at a small
        constant (``max_pending_segments`` queued + 1 being written
        + 1 unresolved fetch).  Written bytes are identical to
        the synchronous path: one writer thread, FIFO, same values
        (the time scalar stays on device between segments via
        ``stepping.time_carry`` — bitwise the same float the sync
        path round-trips through python).

        On ANY exception the writer is still flushed before the
        exception propagates (guaranteed flush-on-exception), so a
        guard's sink records and the ``checkpoint_and_raise``
        postmortem land on disk.
        """
        obs = self._obs
        writer = None
        if (self.history is not None or self.checkpoints is not None
                or (obs is not None and obs.sink is not None)):
            writer = self._ensure_writer()
        self._t_carry = time_carry(self.t)
        self._seg_anchor = time.perf_counter()
        t_host = self.t              # resolved host time (trails one seg)
        pending = None
        raised = False
        try:
            while self.step_count < total:
                k = (min(seg, total - self.step_count) if seg
                     else total - self.step_count)
                fn = self._segment_fn(k)
                samples = getattr(fn, "obs_samples", 0)
                step0 = self.step_count
                buf = None
                if samples > 0:
                    self.state, self._t_carry, buf = fn(
                        self.state, self._t_carry, jnp.asarray(step0))
                else:
                    self.state, self._t_carry = fn(self.state,
                                                   self._t_carry)
                self.step_count += k
                want_hist = bool(
                    io.history_stride
                    and self.step_count % io.history_stride == 0
                    and self.history is not None)
                want_ckpt = bool(
                    self.checkpoints is not None and io.checkpoint_stride
                    and self.step_count % io.checkpoint_stride == 0)
                # The boundary snapshot must be a DISTINCT device
                # buffer: the next dispatch donates self.state, and jax
                # marks a donated input deleted at dispatch (python-side
                # bookkeeping, every backend) — fetching the original
                # after that raises.  jnp.copy dispatches an on-device
                # copy asynchronously; its d2h fetch then rides behind
                # the next segment.  One state copy per history/
                # checkpoint boundary, nothing per plain segment.
                snap = None
                if want_hist or want_ckpt:
                    snap = jax.tree_util.tree_map(jnp.copy, self.state)
                b = {
                    "k": k, "step0": step0, "step_end": self.step_count,
                    "samples": samples,
                    "t": HostFetch(self._t_carry),
                    "buf": HostFetch(buf) if samples > 0 else None,
                    "state": HostFetch(snap) if snap is not None else None,
                    "hist": want_hist, "ckpt": want_ckpt,
                }
                # The double buffer: only now — with this segment's
                # dispatch in flight — resolve the previous boundary.
                # (pending is popped BEFORE resolving so a raise inside
                # the resolve can never double-resolve it from the
                # unwind path below.)
                prev, pending = pending, None
                if prev is not None:
                    t_host = self._resolve_boundary(prev, t_host, writer)
                pending = b
            prev, pending = pending, None
            if prev is not None:
                t_host = self._resolve_boundary(prev, t_host, writer)
        except BaseException:
            raised = True
            # A still-pending boundary is fully computed on device — the
            # sync path would have written it before dispatching the
            # segment that just raised, so land its I/O (best-effort,
            # never masking the in-flight exception) before unwinding.
            if pending is not None:
                try:
                    self._resolve_boundary(pending, t_host, writer)
                except Exception:
                    log.warning("could not land the in-flight boundary "
                                "during exception unwind", exc_info=True)
                pending = None
            raise
        finally:
            if writer is not None:
                try:
                    writer.flush()
                except Exception:
                    # Flush-on-exception must not MASK the in-flight
                    # exception; on the success path a writer failure
                    # is the run's failure.
                    if not raised:
                        raise
                    log.warning("async writer flush failed during "
                                "exception unwind", exc_info=True)
        self.t = float(jax.device_get(self._t_carry))

    def _resolve_boundary(self, b, t_prev: float, writer) -> float:
        """Resolve one dispatched segment's host copies and hand its
        boundary I/O to the background writer.  Called with the NEXT
        segment already dispatched; returns the boundary's host time.

        All of a boundary's writes ride ONE queued task, in sync-path
        order (segment record, guard events, history append, checkpoint
        save) — so the writer's FIFO produces byte-identical files AND
        the queue bound counts whole segments, which is what
        ``max_pending_segments`` promises.  A guard raise inside the
        telemetry ingest still submits the records gathered so far
        (segment record + guard events land on disk) but skips the
        history/checkpoint writes, exactly like the synchronous loop,
        which raises before reaching them."""
        t_host = float(np.asarray(b["t"].resolve()))
        self.t = t_host
        now = time.perf_counter()
        wall = now - self._seg_anchor
        self._seg_anchor = now
        flight.record("segment", step=b["step_end"], k=b["k"],
                      wall_s=round(wall, 6))
        host_state = (b["state"].resolve() if b["state"] is not None
                      else None)
        tasks = []
        try:
            if b["samples"] > 0:
                host = b["buf"].resolve()
                emit = None
                obs = self._obs
                if obs is not None and obs.sink is not None:
                    sink_write = obs.sink.write
                    emit = lambda rec: tasks.append((sink_write, (rec,)))
                self._ingest_telemetry(host, b["step0"], t_prev, b["k"],
                                       wall, b["step_end"], t_host,
                                       emit=emit)
            if b["hist"]:
                tasks.append((self.history.append, (host_state, t_host)))
            if b["ckpt"]:
                flight.record("checkpoint", step=b["step_end"])
                tasks.append((self.checkpoints.save,
                              (b["step_end"], host_state, t_host)))
        finally:
            if tasks:
                w0 = time.perf_counter()
                writer.submit(_run_tasks, tasks)
                self._host_wait += time.perf_counter() - w0
        return t_host


def run_from_config(source: Any, nsteps: Optional[int] = None):
    """One-call entry: build a Simulation from ``source`` and run it."""
    sim = Simulation(source)
    sim.run(nsteps)
    return sim
