"""Equiangular (gnomonic) cubed-sphere geometry.

TPU-native re-design of the reference's "Geometry (Math/Mesh)" layer
(reference: Sharding-the-Sphere deck p.4 "Cube Sphere Dual Quadrilateral
Mesh", p.6 pipeline; /root/reference/JAX-DevLab-Examples.py implies a
``(6, N+2, N+2)`` ghosted field layout at :141).  The reference never ships
geometry code, so everything here is derived from first principles for the
equiangular gnomonic projection.

Design notes (TPU-first):
  * All metric terms are precomputed once in float64 NumPy at setup and cast
    to the run dtype (bfloat16/float32) as JAX arrays — nothing here runs in
    the hot loop.
  * Fields are laid out ``(6, M, M)`` with ``M = N + 2*halo`` so the
    last-two axes map onto the TPU (sublane, lane) = (8, 128) register
    tiling, and the panel axis (and optionally x/y block axes) map onto the
    device mesh.
  * Metric terms are evaluated on the *extended* (halo-included) grid: the
    equiangular map extends analytically past ±pi/4, so ghost cells own
    well-defined local coordinates and dual bases.  This is what lets panel
    -edge fluxes be computed entirely in panel-local coordinates while
    velocity is carried as a Cartesian 3-vector (the reference's
    "Cartesian Velocity Exchange", deck p.18).

Face layout convention (ours; the reference's is not published):
  faces 0..3 are equatorial at longitudes 0, 90, 180, 270 degrees;
  face 4 is the north cap, face 5 the south cap.  Each face map
  ``P(X, Y) = c0 + cx*X + cy*Y`` (then normalized) is right-handed:
  ``cx × cy = c0`` (outward normal), with ``X = tan(alpha)``,
  ``Y = tan(beta)``, ``alpha, beta ∈ [-pi/4, pi/4]``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax.numpy as jnp

__all__ = [
    "FACE_AXES",
    "NUM_FACES",
    "face_points",
    "CubedSphereGrid",
    "build_grid",
]

NUM_FACES = 6

# (c0, cx, cy) per face; P = c0 + cx*X + cy*Y, right-handed: cx x cy = c0.
FACE_AXES = np.array(
    [
        [[1, 0, 0], [0, 1, 0], [0, 0, 1]],    # 0: +x, lon 0
        [[0, 1, 0], [-1, 0, 0], [0, 0, 1]],   # 1: +y, lon 90E
        [[-1, 0, 0], [0, -1, 0], [0, 0, 1]],  # 2: -x, lon 180
        [[0, -1, 0], [1, 0, 0], [0, 0, 1]],   # 3: -y, lon 270E
        [[0, 0, 1], [0, 1, 0], [-1, 0, 0]],   # 4: +z, north
        [[0, 0, -1], [0, 1, 0], [1, 0, 0]],   # 5: -z, south
    ],
    dtype=np.float64,
)


def face_points(face: int, alpha: np.ndarray, beta: np.ndarray) -> np.ndarray:
    """Unit-sphere Cartesian points for equiangular coords on one face.

    ``alpha``/``beta`` broadcast together; returns shape ``(..., 3)``.
    """
    c0, cx, cy = FACE_AXES[face]
    x = np.tan(np.asarray(alpha, dtype=np.float64))
    y = np.tan(np.asarray(beta, dtype=np.float64))
    p = (
        c0[(None,) * x.ndim]
        + x[..., None] * cx[(None,) * x.ndim]
        + y[..., None] * cy[(None,) * y.ndim]
    )
    return p / np.linalg.norm(p, axis=-1, keepdims=True)


def _basis_and_metric(face: int, alpha: np.ndarray, beta: np.ndarray, radius: float):
    """Covariant/dual bases + metric at given equiangular coords (float64).

    Returns dict of arrays with trailing vector axis where applicable:
      r (..,3) position on sphere of given radius,
      e_a, e_b (..,3) covariant basis d r/d alpha, d r/d beta,
      a_a, a_b (..,3) dual basis (a^i . e_j = delta_ij, tangent),
      sqrtg (..,)   = |e_a x e_b . rhat| (area element factor),
      khat (..,3)  outward radial unit vector.
    """
    c0, cx, cy = FACE_AXES[face]
    alpha = np.asarray(alpha, dtype=np.float64)
    beta = np.asarray(beta, dtype=np.float64)
    x = np.tan(alpha)
    y = np.tan(beta)
    shp = np.broadcast_shapes(x.shape, y.shape)
    x = np.broadcast_to(x, shp)
    y = np.broadcast_to(y, shp)
    p = c0 + x[..., None] * cx + y[..., None] * cy
    rho = np.linalg.norm(p, axis=-1, keepdims=True)
    rhat = p / rho
    r = radius * rhat

    # dP/dX = cx, dP/dY = cy; d rhat/dX = (cx - rhat (rhat.cx)) / rho, etc.
    dx_da = 1.0 + x * x  # d tan(alpha)/d alpha
    dy_db = 1.0 + y * y
    pc_x = np.sum(rhat * cx, axis=-1, keepdims=True)
    pc_y = np.sum(rhat * cy, axis=-1, keepdims=True)
    e_a = radius * dx_da[..., None] * (cx - rhat * pc_x) / rho
    e_b = radius * dy_db[..., None] * (cy - rhat * pc_y) / rho

    # 2x2 metric and inverse.
    gaa = np.sum(e_a * e_a, axis=-1)
    gab = np.sum(e_a * e_b, axis=-1)
    gbb = np.sum(e_b * e_b, axis=-1)
    det = gaa * gbb - gab * gab
    sqrtg = np.sqrt(det)
    inv_aa = gbb / det
    inv_ab = -gab / det
    inv_bb = gaa / det
    a_a = inv_aa[..., None] * e_a + inv_ab[..., None] * e_b
    a_b = inv_ab[..., None] * e_a + inv_bb[..., None] * e_b
    return {
        "r": r,
        "rhat": rhat,
        "e_a": e_a,
        "e_b": e_b,
        "a_a": a_a,
        "a_b": a_b,
        "sqrtg": sqrtg,
        "inv_gaa": inv_aa,
        "inv_gab": inv_ab,
        "inv_gbb": inv_bb,
    }


@dataclasses.dataclass(frozen=True)
class CubedSphereGrid:
    """Precomputed cubed-sphere geometry on the halo-extended grid.

    Array layout: ``(6, M, M)`` (scalars) / ``(3, 6, M, M)`` (vectors,
    Cartesian component leading so the last two axes keep TPU (sublane,
    lane) tiling) with ``M = n + 2*halo``; index ``[face, j, i]`` where
    ``i`` runs along alpha (x-like) and ``j`` along beta (y-like).
    ``*_xf`` quantities live at the *left* alpha-face of each cell (face i
    is between cells i-1 and i); ``*_yf`` at the *bottom* beta-face.
    """

    n: int
    halo: int
    radius: float
    dalpha: float
    # Cell-center quantities, (6, M, M[, 3]).
    xyz: Any
    khat: Any
    lon: Any
    lat: Any
    e_a: Any
    e_b: Any
    a_a: Any
    a_b: Any
    sqrtg: Any
    area: Any
    # Left/bottom cell-face quantities for fluxes.
    sqrtg_xf: Any
    a_a_xf: Any
    sqrtg_yf: Any
    a_b_yf: Any
    # Inverse-metric components at faces (for Laplacian/diffusion fluxes).
    ginv_aa_xf: Any
    ginv_ab_xf: Any
    ginv_bb_yf: Any
    ginv_ab_yf: Any

    @property
    def m(self) -> int:
        return self.n + 2 * self.halo

    def interior(self, field):
        """Slice the interior ``(..., 6, n, n)`` out of an extended field."""
        h = self.halo
        return field[..., h : h + self.n, h : h + self.n]

    def total_area(self) -> float:
        return float(jnp.sum(self.interior(self.area)))


def build_grid(
    n: int,
    halo: int = 2,
    radius: float = 1.0,
    dtype=jnp.float32,
) -> CubedSphereGrid:
    """Build the grid: all metric terms in float64, cast to ``dtype``."""
    m = n + 2 * halo
    d = (np.pi / 2) / n
    # Cell-center coords of the extended grid (halo cells extend past +-pi/4).
    ac = -np.pi / 4 + (np.arange(m) - halo + 0.5) * d
    # Left-face coords (face i = left face of extended cell i).
    af = ac - 0.5 * d

    cc: dict[str, list] = {k: [] for k in ("xyz", "khat", "e_a", "e_b", "a_a", "a_b", "sqrtg")}
    xf: dict[str, list] = {k: [] for k in ("sqrtg", "a_a", "inv_gaa", "inv_gab")}
    yf: dict[str, list] = {k: [] for k in ("sqrtg", "a_b", "inv_gbb", "inv_gab")}
    lon_l, lat_l = [], []
    for f in range(NUM_FACES):
        # Centers: alpha varies along axis -1 (i), beta along axis -2 (j).
        bb, aa = np.meshgrid(ac, ac, indexing="ij")
        g = _basis_and_metric(f, aa, bb, radius)
        cc["xyz"].append(g["r"])
        cc["khat"].append(g["rhat"])
        for k in ("e_a", "e_b", "a_a", "a_b", "sqrtg"):
            cc[k].append(g[k])
        lon_l.append(np.arctan2(g["rhat"][..., 1], g["rhat"][..., 0]))
        lat_l.append(np.arcsin(np.clip(g["rhat"][..., 2], -1.0, 1.0)))
        # Alpha-faces: alpha at af, beta at centers.
        bb2, aa2 = np.meshgrid(ac, af, indexing="ij")
        gx = _basis_and_metric(f, aa2, bb2, radius)
        xf["sqrtg"].append(gx["sqrtg"])
        xf["a_a"].append(gx["a_a"])
        xf["inv_gaa"].append(gx["inv_gaa"])
        xf["inv_gab"].append(gx["inv_gab"])
        # Beta-faces: alpha at centers, beta at af.
        bb3, aa3 = np.meshgrid(af, ac, indexing="ij")
        gy = _basis_and_metric(f, aa3, bb3, radius)
        yf["sqrtg"].append(gy["sqrtg"])
        yf["a_b"].append(gy["a_b"])
        yf["inv_gbb"].append(gy["inv_gbb"])
        yf["inv_gab"].append(gy["inv_gab"])

    def J(arrs):
        return jnp.asarray(np.stack(arrs), dtype=dtype)

    def Jv(arrs):
        # (6, M, M, 3) -> (3, 6, M, M): component-leading vector layout.
        return jnp.asarray(np.moveaxis(np.stack(arrs), -1, 0), dtype=dtype)

    sqrtg = np.stack(cc["sqrtg"])
    return CubedSphereGrid(
        n=n,
        halo=halo,
        radius=radius,
        dalpha=d,
        xyz=Jv(cc["xyz"]),
        khat=Jv(cc["khat"]),
        lon=J(lon_l),
        lat=J(lat_l),
        e_a=Jv(cc["e_a"]),
        e_b=Jv(cc["e_b"]),
        a_a=Jv(cc["a_a"]),
        a_b=Jv(cc["a_b"]),
        sqrtg=J(cc["sqrtg"]),
        area=jnp.asarray(sqrtg * d * d, dtype=dtype),
        sqrtg_xf=J(xf["sqrtg"]),
        a_a_xf=Jv(xf["a_a"]),
        sqrtg_yf=J(yf["sqrtg"]),
        a_b_yf=Jv(yf["a_b"]),
        ginv_aa_xf=J(xf["inv_gaa"]),
        ginv_ab_xf=J(xf["inv_gab"]),
        ginv_bb_yf=J(yf["inv_gbb"]),
        ginv_ab_yf=J(yf["inv_gab"]),
    )
