"""Equiangular (gnomonic) cubed-sphere geometry.

TPU-native re-design of the reference's "Geometry (Math/Mesh)" layer
(reference: Sharding-the-Sphere deck p.4 "Cube Sphere Dual Quadrilateral
Mesh", p.6 pipeline; /root/reference/JAX-DevLab-Examples.py implies a
``(6, N+2, N+2)`` ghosted field layout at :141).  The reference never ships
geometry code, so everything here is derived from first principles for the
equiangular gnomonic projection.

Design notes (TPU-first):
  * All metric terms are precomputed once in float64 NumPy at setup and cast
    to the run dtype (bfloat16/float32) as JAX arrays — nothing here runs in
    the hot loop.
  * Fields are laid out ``(6, M, M)`` with ``M = N + 2*halo`` so the
    last-two axes map onto the TPU (sublane, lane) = (8, 128) register
    tiling, and the panel axis (and optionally x/y block axes) map onto the
    device mesh.
  * Metric terms are evaluated on the *extended* (halo-included) grid: the
    equiangular map extends analytically past ±pi/4, so ghost cells own
    well-defined local coordinates and dual bases.  This is what lets panel
    -edge fluxes be computed entirely in panel-local coordinates while
    velocity is carried as a Cartesian 3-vector (the reference's
    "Cartesian Velocity Exchange", deck p.18).

Face layout convention (ours; the reference's is not published):
  faces 0..3 are equatorial at longitudes 0, 90, 180, 270 degrees;
  face 4 is the north cap, face 5 the south cap.  Each face map
  ``P(X, Y) = c0 + cx*X + cy*Y`` (then normalized) is right-handed:
  ``cx × cy = c0`` (outward normal), with ``X = tan(alpha)``,
  ``Y = tan(beta)``, ``alpha, beta ∈ [-pi/4, pi/4]``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax.numpy as jnp

__all__ = [
    "FACE_AXES",
    "NUM_FACES",
    "extended_coords",
    "face_points",
    "sphere_to_face_coords",
    "CubedSphereGrid",
    "LazyCubedSphereGrid",
    "build_grid",
]

NUM_FACES = 6

# (c0, cx, cy) per face; P = c0 + cx*X + cy*Y, right-handed: cx x cy = c0.
FACE_AXES = np.array(
    [
        [[1, 0, 0], [0, 1, 0], [0, 0, 1]],    # 0: +x, lon 0
        [[0, 1, 0], [-1, 0, 0], [0, 0, 1]],   # 1: +y, lon 90E
        [[-1, 0, 0], [0, -1, 0], [0, 0, 1]],  # 2: -x, lon 180
        [[0, -1, 0], [1, 0, 0], [0, 0, 1]],   # 3: -y, lon 270E
        [[0, 0, 1], [0, 1, 0], [-1, 0, 0]],   # 4: +z, north
        [[0, 0, -1], [0, 1, 0], [1, 0, 0]],   # 5: -z, south
    ],
    dtype=np.float64,
)


def extended_coords(n: int, halo: int):
    """1-D equiangular coordinates of the halo-extended grid (float64).

    Returns ``(ac, af, d)``: cell-center coords (M,), left-face coords
    (M,), and the spacing d = (pi/2)/n.  Single source of truth for every
    consumer (eager grid, lazy grid, Pallas kernels).
    """
    m = n + 2 * halo
    d = (np.pi / 2) / n
    ac = -np.pi / 4 + (np.arange(m) - halo + 0.5) * d
    return ac, ac - 0.5 * d, d


def sphere_to_face_coords(xyz: np.ndarray):
    """Inverse gnomonic map: unit vectors -> (face, alpha, beta).

    ``xyz``: (..., 3) points on (or off — they are centrally projected to)
    the unit sphere.  Returns ``(face, alpha, beta)`` with ``face`` int
    (..., ), ``alpha``/``beta`` in [-pi/4, pi/4].  The owning face is the
    one whose outward axis has the largest positive projection, which
    partitions the sphere exactly (ties on edges resolve to the lowest
    face index).  Used for lat/lon regridding (analysis/viz layer, deck
    p.6, p.12-13) and observation sampling.
    """
    p = np.asarray(xyz, dtype=np.float64)
    c0 = FACE_AXES[:, 0, :]                      # (6, 3)
    proj = np.tensordot(p, c0, axes=([-1], [-1]))  # (..., 6)
    face = np.argmax(proj, axis=-1)
    fa = FACE_AXES[face]                          # (..., 3, 3)
    d0 = np.sum(p * fa[..., 0, :], axis=-1)
    dx = np.sum(p * fa[..., 1, :], axis=-1)
    dy = np.sum(p * fa[..., 2, :], axis=-1)
    alpha = np.arctan2(dx, d0)
    beta = np.arctan2(dy, d0)
    return face, alpha, beta


def face_points(face: int, alpha: np.ndarray, beta: np.ndarray) -> np.ndarray:
    """Unit-sphere Cartesian points for equiangular coords on one face.

    ``alpha``/``beta`` broadcast together; returns shape ``(..., 3)``.
    """
    c0, cx, cy = FACE_AXES[face]
    x = np.tan(np.asarray(alpha, dtype=np.float64))
    y = np.tan(np.asarray(beta, dtype=np.float64))
    p = (
        c0[(None,) * x.ndim]
        + x[..., None] * cx[(None,) * x.ndim]
        + y[..., None] * cy[(None,) * y.ndim]
    )
    return p / np.linalg.norm(p, axis=-1, keepdims=True)


def _basis_and_metric(face: int, alpha: np.ndarray, beta: np.ndarray, radius: float):
    """Covariant/dual bases + metric at given equiangular coords (float64).

    Returns dict of arrays with trailing vector axis where applicable:
      r (..,3) position on sphere of given radius,
      e_a, e_b (..,3) covariant basis d r/d alpha, d r/d beta,
      a_a, a_b (..,3) dual basis (a^i . e_j = delta_ij, tangent),
      sqrtg (..,)   = |e_a x e_b . rhat| (area element factor),
      khat (..,3)  outward radial unit vector.
    """
    c0, cx, cy = FACE_AXES[face]
    alpha = np.asarray(alpha, dtype=np.float64)
    beta = np.asarray(beta, dtype=np.float64)
    x = np.tan(alpha)
    y = np.tan(beta)
    shp = np.broadcast_shapes(x.shape, y.shape)
    x = np.broadcast_to(x, shp)
    y = np.broadcast_to(y, shp)
    p = c0 + x[..., None] * cx + y[..., None] * cy
    rho = np.linalg.norm(p, axis=-1, keepdims=True)
    rhat = p / rho
    r = radius * rhat

    # dP/dX = cx, dP/dY = cy; d rhat/dX = (cx - rhat (rhat.cx)) / rho, etc.
    dx_da = 1.0 + x * x  # d tan(alpha)/d alpha
    dy_db = 1.0 + y * y
    pc_x = np.sum(rhat * cx, axis=-1, keepdims=True)
    pc_y = np.sum(rhat * cy, axis=-1, keepdims=True)
    e_a = radius * dx_da[..., None] * (cx - rhat * pc_x) / rho
    e_b = radius * dy_db[..., None] * (cy - rhat * pc_y) / rho

    # 2x2 metric and inverse.
    gaa = np.sum(e_a * e_a, axis=-1)
    gab = np.sum(e_a * e_b, axis=-1)
    gbb = np.sum(e_b * e_b, axis=-1)
    det = gaa * gbb - gab * gab
    sqrtg = np.sqrt(det)
    inv_aa = gbb / det
    inv_ab = -gab / det
    inv_bb = gaa / det
    a_a = inv_aa[..., None] * e_a + inv_ab[..., None] * e_b
    a_b = inv_ab[..., None] * e_a + inv_bb[..., None] * e_b
    return {
        "r": r,
        "rhat": rhat,
        "e_a": e_a,
        "e_b": e_b,
        "a_a": a_a,
        "a_b": a_b,
        "sqrtg": sqrtg,
        "inv_gaa": inv_aa,
        "inv_gab": inv_ab,
        "inv_gbb": inv_bb,
    }


@dataclasses.dataclass(frozen=True)
class CubedSphereGrid:
    """Precomputed cubed-sphere geometry on the halo-extended grid.

    Array layout: ``(6, M, M)`` (scalars) / ``(3, 6, M, M)`` (vectors,
    Cartesian component leading so the last two axes keep TPU (sublane,
    lane) tiling) with ``M = n + 2*halo``; index ``[face, j, i]`` where
    ``i`` runs along alpha (x-like) and ``j`` along beta (y-like).
    ``*_xf`` quantities live at the *left* alpha-face of each cell (face i
    is between cells i-1 and i); ``*_yf`` at the *bottom* beta-face.
    """

    n: int
    halo: int
    radius: float
    dalpha: float
    # Cell-center quantities, (6, M, M[, 3]).
    xyz: Any
    khat: Any
    lon: Any
    lat: Any
    e_a: Any
    e_b: Any
    a_a: Any
    a_b: Any
    sqrtg: Any
    area: Any
    # Left/bottom cell-face quantities for fluxes.
    sqrtg_xf: Any
    a_a_xf: Any
    sqrtg_yf: Any
    a_b_yf: Any
    # Inverse-metric components at faces (for Laplacian/diffusion fluxes).
    ginv_aa_xf: Any
    ginv_ab_xf: Any
    ginv_bb_yf: Any
    ginv_ab_yf: Any

    @property
    def m(self) -> int:
        return self.n + 2 * self.halo

    def interior(self, field):
        """Slice the interior ``(..., 6, n, n)`` out of an extended field."""
        h = self.halo
        return field[..., h : h + self.n, h : h + self.n]

    def total_area(self) -> float:
        return float(jnp.sum(self.interior(self.area)))


class LazyCubedSphereGrid:
    """Metric terms computed on the fly from 1-D coordinate arrays.

    The equiangular cubed-sphere metric is *rank-1 separable*: with
    ``X = tan(alpha)`` varying only along columns and ``Y = tan(beta)``
    only along rows, every metric quantity is a closed-form elementwise
    function of broadcast 1-D arrays plus per-face constant frames.
    Storing the full ``(3, 6, M, M)`` basis arrays (as
    :class:`CubedSphereGrid` does) makes the FV stencils HBM-bound on
    *geometry* traffic; recomputing them inside the traced step costs a few
    dozen VPU flops per cell — the canonical TPU trade (HBM bandwidth is
    the scarce resource, deck p.19's roofline: FV-PLR AI ~ 0.25 flops/byte).
    XLA fuses the broadcasts into the consuming stencil kernels and CSEs
    repeated uses within one trace, so each quantity is materialized at
    most once per fusion, streamed from registers not HBM.

    Exposes the same attribute surface as :class:`CubedSphereGrid`; each
    metric attribute is a property that emits (traceable) jnp expressions.
    """

    def __init__(self, n: int, halo: int, radius: float, dtype):
        self.n = n
        self.halo = halo
        self.radius = radius
        self.dtype = dtype
        ac, af, d = extended_coords(n, halo)
        self.dalpha = d
        # 1-D gnomonic coordinates (f64 tan, then cast) — the only stored
        # geometry: 2 x (M,) vectors instead of ~20 x (6, M, M) fields.
        self._xc = jnp.asarray(np.tan(ac), dtype=dtype)
        self._xf = jnp.asarray(np.tan(af), dtype=dtype)
        # Per-face frames as (3, 6, 1, 1) for component-leading broadcast.
        fa = np.transpose(FACE_AXES, (2, 1, 0))[:, :, :, None, None]
        self._c0 = jnp.asarray(fa[:, 0, :, :, :], dtype=dtype)
        self._cx = jnp.asarray(fa[:, 1, :, :, :], dtype=dtype)
        self._cy = jnp.asarray(fa[:, 2, :, :, :], dtype=dtype)

    @property
    def m(self) -> int:
        return self.n + 2 * self.halo

    def interior(self, field):
        h = self.halo
        return field[..., h : h + self.n, h : h + self.n]

    def total_area(self) -> float:
        return float(jnp.sum(self.interior(self.area)))

    # -- core expression builders -------------------------------------------
    def _xy(self, at: str):
        """Broadcastable (1,1,M)/(1,M,1) X,Y for centers/x-faces/y-faces."""
        xc = self._xc[None, None, :]
        yc = self._xc[None, :, None]
        if at == "cc":
            return xc, yc
        if at == "xf":
            return self._xf[None, None, :], yc
        if at == "yf":
            return xc, self._xf[None, :, None]
        raise ValueError(at)

    def _basis(self, at: str):
        """Dict of lazily-built metric expressions at cc/xf/yf points.

        Same math as :func:`_basis_and_metric`, as jnp broadcasts; unused
        entries are dead-code-eliminated by XLA.
        """
        x, y = self._xy(at)  # (1, 1|M, M|1) each
        one = jnp.asarray(1.0, self.dtype)
        rho2 = one + x * x + y * y
        rho = jnp.sqrt(rho2)
        # p: (3, 6, M, M) by broadcast; rhat = p / rho.
        p = self._c0 + x[None] * self._cx + y[None] * self._cy
        rhat = p / rho[None]
        dx_da = one + x * x
        dy_db = one + y * y
        pc_x = jnp.sum(rhat * self._cx, axis=0)
        pc_y = jnp.sum(rhat * self._cy, axis=0)
        R = jnp.asarray(self.radius, self.dtype)
        e_a = (R * dx_da / rho)[None] * (self._cx - rhat * pc_x[None])
        e_b = (R * dy_db / rho)[None] * (self._cy - rhat * pc_y[None])
        # Closed-form 2x2 metric of the equiangular map (avoids forming the
        # dot products of e_a/e_b, keeping fusions small):
        #   g_aa = R^2 (1+X^2)^2 (1+Y^2) / rho^4
        #   g_bb = R^2 (1+X^2) (1+Y^2)^2 / rho^4
        #   g_ab = -R^2 (1+X^2)(1+Y^2) X Y / rho^4
        #   det  = R^4 (1+X^2)^2 (1+Y^2)^2 / rho^6 -> sqrtg = R^2 dxda dydb / rho^3
        R2 = R * R
        rho4 = rho2 * rho2
        gcom = R2 * dx_da * dy_db / rho4
        gaa = gcom * dx_da
        gbb = gcom * dy_db
        gab = -gcom * x * y
        det = gaa * gbb - gab * gab
        sqrtg = R2 * dx_da * dy_db / (rho2 * rho)
        inv_aa = gbb / det
        inv_ab = -gab / det
        inv_bb = gaa / det
        return {
            "rhat": rhat,
            "e_a": e_a,
            "e_b": e_b,
            "a_a": inv_aa[None] * e_a + inv_ab[None] * e_b,
            "a_b": inv_ab[None] * e_a + inv_bb[None] * e_b,
            # Face-independent, but consumers (zeros_like, stacking) expect
            # the face axis; broadcast_to stays lazy under XLA.  Sized from
            # the frames so per-face local blocks (shard_map) stay (1, M, M).
            "sqrtg": jnp.broadcast_to(sqrtg, (self._c0.shape[1], self.m, self.m)),
            "inv_gaa": inv_aa,
            "inv_gab": inv_ab,
            "inv_gbb": inv_bb,
        }

    # -- CubedSphereGrid-compatible attribute surface -----------------------
    @property
    def xyz(self):
        return jnp.asarray(self.radius, self.dtype) * self._basis("cc")["rhat"]

    @property
    def khat(self):
        return self._basis("cc")["rhat"]

    @property
    def lon(self):
        r = self._basis("cc")["rhat"]
        return jnp.arctan2(r[1], r[0])

    @property
    def lat(self):
        r = self._basis("cc")["rhat"]
        return jnp.arcsin(jnp.clip(r[2], -1.0, 1.0))

    @property
    def e_a(self):
        return self._basis("cc")["e_a"]

    @property
    def e_b(self):
        return self._basis("cc")["e_b"]

    @property
    def a_a(self):
        return self._basis("cc")["a_a"]

    @property
    def a_b(self):
        return self._basis("cc")["a_b"]

    @property
    def sqrtg(self):
        return self._basis("cc")["sqrtg"]

    @property
    def area(self):
        return self.sqrtg * jnp.asarray(self.dalpha * self.dalpha, self.dtype)

    @property
    def sqrtg_xf(self):
        return self._basis("xf")["sqrtg"]

    @property
    def a_a_xf(self):
        return self._basis("xf")["a_a"]

    @property
    def sqrtg_yf(self):
        return self._basis("yf")["sqrtg"]

    @property
    def a_b_yf(self):
        return self._basis("yf")["a_b"]

    @property
    def ginv_aa_xf(self):
        return self._basis("xf")["inv_gaa"]

    @property
    def ginv_ab_xf(self):
        return self._basis("xf")["inv_gab"]

    @property
    def ginv_bb_yf(self):
        return self._basis("yf")["inv_gbb"]

    @property
    def ginv_ab_yf(self):
        return self._basis("yf")["inv_gab"]


def build_grid(
    n: int,
    halo: int = 2,
    radius: float = 1.0,
    dtype=jnp.float32,
    metrics: str = "eager",
):
    """Build the grid geometry.

    ``metrics='eager'`` (default) returns a :class:`CubedSphereGrid` whose
    metric terms are precomputed in float64 and cast to ``dtype`` — the
    accuracy reference, and the right choice for low-precision ``dtype``
    experiments (bfloat16 values are still f64-rounded).

    ``metrics='lazy'`` returns a :class:`LazyCubedSphereGrid` whose metric
    terms are recomputed (and fused) inside the traced step instead of
    streamed from HBM — the fast path for TPU production runs.  The whole
    metric chain then evaluates in ``dtype``; use float32 or wider (the
    f32-vs-f64 agreement is ~1e-6 relative, tests/test_lazy_metrics.py).
    """
    if metrics == "lazy":
        return LazyCubedSphereGrid(n, halo, radius, dtype)
    if metrics != "eager":
        raise ValueError(f"metrics must be 'eager' or 'lazy', got {metrics!r}")
    # Centers/left-faces of the extended grid (halos extend past +-pi/4).
    ac, af, d = extended_coords(n, halo)

    cc: dict[str, list] = {k: [] for k in ("xyz", "khat", "e_a", "e_b", "a_a", "a_b", "sqrtg")}
    xf: dict[str, list] = {k: [] for k in ("sqrtg", "a_a", "inv_gaa", "inv_gab")}
    yf: dict[str, list] = {k: [] for k in ("sqrtg", "a_b", "inv_gbb", "inv_gab")}
    lon_l, lat_l = [], []
    for f in range(NUM_FACES):
        # Centers: alpha varies along axis -1 (i), beta along axis -2 (j).
        bb, aa = np.meshgrid(ac, ac, indexing="ij")
        g = _basis_and_metric(f, aa, bb, radius)
        cc["xyz"].append(g["r"])
        cc["khat"].append(g["rhat"])
        for k in ("e_a", "e_b", "a_a", "a_b", "sqrtg"):
            cc[k].append(g[k])
        lon_l.append(np.arctan2(g["rhat"][..., 1], g["rhat"][..., 0]))
        lat_l.append(np.arcsin(np.clip(g["rhat"][..., 2], -1.0, 1.0)))
        # Alpha-faces: alpha at af, beta at centers.
        bb2, aa2 = np.meshgrid(ac, af, indexing="ij")
        gx = _basis_and_metric(f, aa2, bb2, radius)
        xf["sqrtg"].append(gx["sqrtg"])
        xf["a_a"].append(gx["a_a"])
        xf["inv_gaa"].append(gx["inv_gaa"])
        xf["inv_gab"].append(gx["inv_gab"])
        # Beta-faces: alpha at centers, beta at af.
        bb3, aa3 = np.meshgrid(af, ac, indexing="ij")
        gy = _basis_and_metric(f, aa3, bb3, radius)
        yf["sqrtg"].append(gy["sqrtg"])
        yf["a_b"].append(gy["a_b"])
        yf["inv_gbb"].append(gy["inv_gbb"])
        yf["inv_gab"].append(gy["inv_gab"])

    def J(arrs):
        return jnp.asarray(np.stack(arrs), dtype=dtype)

    def Jv(arrs):
        # (6, M, M, 3) -> (3, 6, M, M): component-leading vector layout.
        return jnp.asarray(np.moveaxis(np.stack(arrs), -1, 0), dtype=dtype)

    sqrtg = np.stack(cc["sqrtg"])
    return CubedSphereGrid(
        n=n,
        halo=halo,
        radius=radius,
        dalpha=d,
        xyz=Jv(cc["xyz"]),
        khat=Jv(cc["khat"]),
        lon=J(lon_l),
        lat=J(lat_l),
        e_a=Jv(cc["e_a"]),
        e_b=Jv(cc["e_b"]),
        a_a=Jv(cc["a_a"]),
        a_b=Jv(cc["a_b"]),
        sqrtg=J(cc["sqrtg"]),
        area=jnp.asarray(sqrtg * d * d, dtype=dtype),
        sqrtg_xf=J(xf["sqrtg"]),
        a_a_xf=Jv(xf["a_a"]),
        sqrtg_yf=J(yf["sqrtg"]),
        a_b_yf=Jv(yf["a_b"]),
        ginv_aa_xf=J(xf["inv_gaa"]),
        ginv_ab_xf=J(xf["inv_gab"]),
        ginv_bb_yf=J(yf["inv_gbb"]),
        ginv_ab_yf=J(yf["inv_gab"]),
    )
