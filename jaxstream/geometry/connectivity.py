"""Cubed-sphere panel connectivity, generated — not hard-coded.

The reference hard-codes its 12-edge / 4-stage communication schedule as a
literal table (``/root/reference/JAX-DevLab-Examples.py:105-139``, deck p.9)
and leaves the boundary extract/insert helpers undefined.  Here the
adjacency is *derived numerically* from the face maps in
:mod:`jaxstream.geometry.cubed_sphere` (matching edge points in 3-D), so it
is correct by construction for our face layout, and the race-free stage
schedule is produced by a proper edge-coloring of the face-adjacency graph
(the octahedron graph, chromatic index 4) — the deck's "scalable edge
coloring algorithm" (p.9) made real.

Invariants (tested in ``tests/test_connectivity.py``, mirroring the
reference's verified properties, SURVEY.md §2.5):
  * every face has exactly 4 neighbors, each edge matched exactly once;
  * antipodal face pairs never exchange;
  * the schedule has 4 stages, each a perfect matching on the 6 faces.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List, Tuple

import numpy as np

from .cubed_sphere import NUM_FACES, face_points

__all__ = [
    "EDGE_S",
    "EDGE_E",
    "EDGE_N",
    "EDGE_W",
    "EdgeLink",
    "build_connectivity",
    "edge_pairs",
    "build_schedule",
    "schedule_perms",
    "schedule_fingerprint",
]

# Edge ids: S = beta min, E = alpha max, N = beta max, W = alpha min.
EDGE_S, EDGE_E, EDGE_N, EDGE_W = 0, 1, 2, 3
EDGE_NAMES = ("S", "E", "N", "W")


@dataclasses.dataclass(frozen=True)
class EdgeLink:
    """Face ``face``'s edge ``edge`` abuts ``nbr_face``'s edge ``nbr_edge``.

    ``reversed_`` is True when the along-edge index runs in opposite
    directions on the two faces (the reference's "R"-type orientation ops;
    its "T" op is the depth/along-edge transpose handled by the canonical
    strip frame in :mod:`jaxstream.parallel.halo`).
    """

    face: int
    edge: int
    nbr_face: int
    nbr_edge: int
    reversed_: bool


def _edge_coords(edge: int, t: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(alpha, beta) along an edge at parameter t in [0, 1].

    The along-edge parameter increases with alpha (S/N edges) or with beta
    (E/W edges) — the canonical along-edge direction used everywhere.
    """
    q = np.pi / 4
    s = -q + t * (2 * q)
    if edge == EDGE_S:
        return s, np.full_like(s, -q)
    if edge == EDGE_N:
        return s, np.full_like(s, q)
    if edge == EDGE_W:
        return np.full_like(s, -q), s
    if edge == EDGE_E:
        return np.full_like(s, q), s
    raise ValueError(edge)


def build_connectivity() -> List[List[EdgeLink]]:
    """adj[face][edge] -> EdgeLink, derived by matching 3-D edge points."""
    # Symmetric under t -> 1-t (so a reversed edge matches pointwise after
    # flipping), but not constant spacing collapse: ordering detects reversal.
    t = np.array([0.1, 0.3, 0.5, 0.7, 0.9])
    pts = {}
    for f in range(NUM_FACES):
        for e in range(4):
            a, b = _edge_coords(e, t)
            pts[(f, e)] = face_points(f, a, b)

    adj: List[List[EdgeLink]] = [[None] * 4 for _ in range(NUM_FACES)]  # type: ignore
    for f in range(NUM_FACES):
        for e in range(4):
            found = None
            for g in range(NUM_FACES):
                if g == f:
                    continue
                for e2 in range(4):
                    p, q = pts[(f, e)], pts[(g, e2)]
                    if np.allclose(p, q, atol=1e-12):
                        found = (g, e2, False)
                    elif np.allclose(p, q[::-1], atol=1e-12):
                        found = (g, e2, True)
                    if found:
                        break
                if found:
                    break
            if found is None:
                raise RuntimeError(f"no neighbor found for face {f} edge {e}")
            adj[f][e] = EdgeLink(f, e, *found)
    # Symmetry check: the link back must exist and agree on reversal.
    for f in range(NUM_FACES):
        for e in range(4):
            l = adj[f][e]
            back = adj[l.nbr_face][l.nbr_edge]
            assert back.nbr_face == f and back.nbr_edge == e
            assert back.reversed_ == l.reversed_
    return adj


def edge_pairs(adj=None) -> List[Tuple[EdgeLink, EdgeLink]]:
    """The 12 undirected cube edges as (link, backlink) pairs."""
    adj = adj or build_connectivity()
    seen = set()
    pairs = []
    for f in range(NUM_FACES):
        for e in range(4):
            l = adj[f][e]
            key = tuple(sorted([(f, e), (l.nbr_face, l.nbr_edge)]))
            if key in seen:
                continue
            seen.add(key)
            pairs.append((l, adj[l.nbr_face][l.nbr_edge]))
    assert len(pairs) == 12
    return pairs


def build_schedule(adj=None, num_stages: int = 4) -> List[List[Tuple[EdgeLink, EdgeLink]]]:
    """Proper edge-coloring of the 12 cube edges into race-free stages.

    Each stage is a perfect matching on the 6 faces: no face (hence no
    device, at <=1 face/device) is touched twice within a stage — the
    reference's deadlock/race-avoidance invariant (deck p.9).  Backtracking
    search; the octahedron graph has chromatic index 4 so 4 stages always
    succeed.
    """
    pairs = edge_pairs(adj)

    stages: List[List[Tuple[EdgeLink, EdgeLink]]] = [[] for _ in range(num_stages)]
    busy = [set() for _ in range(num_stages)]

    def place(i: int) -> bool:
        if i == len(pairs):
            return True
        l, _ = pairs[i]
        for s in range(num_stages):
            if l.face in busy[s] or l.nbr_face in busy[s]:
                continue
            busy[s].update((l.face, l.nbr_face))
            stages[s].append(pairs[i])
            if place(i + 1):
                return True
            busy[s].difference_update((l.face, l.nbr_face))
            stages[s].pop()
        return False

    if not place(0):
        raise RuntimeError(f"edge coloring with {num_stages} stages failed")
    return stages


def schedule_perms(adj=None, num_stages: int = 4):
    """The canonical per-stage ``lax.ppermute`` pair lists.

    ``[[(src_face, dst_face), ...], ...]`` — exactly the ``perm``
    argument every face-tier exchange factory passes to ``ppermute``
    (``CovShardProgram`` and ``ShardHaloProgram`` both derive theirs
    from :func:`build_schedule` the same way).  The single source the
    static contract checker and the ``comm_probe`` analytic plans
    fingerprint against.
    """
    perms = []
    for stage in build_schedule(adj, num_stages):
        perm = []
        for link, back in stage:
            perm.append((link.face, link.nbr_face))
            perm.append((back.face, back.nbr_face))
        perms.append(perm)
    return perms


def schedule_fingerprint(perms=None) -> str:
    """Canonical 16-hex digest of a stage schedule's ppermute pairs.

    ``perms`` is a list of stages, each a list of ``(src, dst)`` pairs
    (defaults to :func:`schedule_perms`).  Canonicalization sorts the
    pairs within each stage and the stages among themselves, so the
    digest identifies the *schedule* — which seams exchange together —
    independent of pair issue order; any dropped, duplicated, or
    re-staged pair changes it.  ``comm_probe``'s analytic plans carry
    this value and ``jaxstream.analysis`` recomputes it from the traced
    jaxprs' actual ``ppermute`` params, so the analytic accounting and
    the compiled schedules can never silently diverge.
    """
    if perms is None:
        perms = schedule_perms()
    canon = tuple(sorted(
        tuple(sorted((int(a), int(b)) for a, b in stage))
        for stage in perms))
    return hashlib.sha256(repr(canon).encode()).hexdigest()[:16]
