"""jaxstream — TPU-native cubed-sphere shallow-water framework.

Importing the package applies environment hooks: setting
``JAXSTREAM_COMPILE_CACHE=/path`` enables jax's persistent compilation
cache there (``jaxstream.utils.jax_compat.enable_compile_cache``), so
any entrypoint — ``Simulation``, the CLI, ``bench.py`` — warms compiles
from the environment alone.
"""

from .utils.jax_compat import maybe_enable_compile_cache

maybe_enable_compile_cache()
