"""Initial conditions: Williamson test suite, Galewsky jet, demo fields.

The reference's "Initial Conditions (Physics)" pipeline stage (deck p.6)
with its two demo ICs — the checkerboard "Lima Flag" heat source (p.12/17)
and the equatorial cosine bell (p.13/18) — plus the formal Williamson
(1992) cases TC1/TC2/TC5/TC6 and the Galewsky (2004) jet pinned by
``BASELINE.json``.

All fields are evaluated analytically at *extended* cell centers where
useful (prescribed winds fill their own ghosts exactly — no exchange
needed), in float64 NumPy, cast to the grid dtype on the way out.
Velocities are Cartesian 3-vectors ``(3, 6, M, M)`` tangent to the sphere.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..config import EARTH_RADIUS
from ..geometry.cubed_sphere import CubedSphereGrid

__all__ = [
    "solid_body_wind",
    "zonal_meridional_to_cartesian",
    "cosine_bell",
    "checkerboard",
    "williamson_tc2",
    "williamson_tc5",
    "williamson_tc6",
    "galewsky",
    "perturbed_ensemble",
]


def _np(x):
    return np.asarray(x, dtype=np.float64)


def solid_body_wind(grid: CubedSphereGrid, u0: float, alpha_rot: float = 0.0):
    """Solid-body rotation wind, W x r with the axis tilted by alpha_rot.

    Williamson TC1/TC2 wind: u = u0 (cos(lat) cos(a) + sin(lat) cos(lon)
    sin(a)).  Exact at every extended cell center (ghosts included).
    Returns (3, 6, M, M) in grid dtype.
    """
    xyz = _np(grid.xyz)  # (3, 6, M, M), |.| = radius
    a = grid.radius
    w = (u0 / a) * np.array([-np.sin(alpha_rot), 0.0, np.cos(alpha_rot)])
    v = np.stack([
        w[1] * xyz[2] - w[2] * xyz[1],
        w[2] * xyz[0] - w[0] * xyz[2],
        w[0] * xyz[1] - w[1] * xyz[0],
    ])
    return jnp.asarray(v, dtype=grid.sqrtg.dtype)


def zonal_meridional_to_cartesian(grid: CubedSphereGrid, u, v):
    """(u zonal, v meridional) at extended centers -> Cartesian (3,6,M,M)."""
    lon = _np(grid.lon)
    lat = _np(grid.lat)
    e_lon = np.stack([-np.sin(lon), np.cos(lon), np.zeros_like(lon)])
    e_lat = np.stack([
        -np.sin(lat) * np.cos(lon),
        -np.sin(lat) * np.sin(lon),
        np.cos(lat),
    ])
    vec = _np(u) * e_lon + _np(v) * e_lat
    return jnp.asarray(vec, dtype=grid.sqrtg.dtype)


def _great_circle(grid, lon_c, lat_c):
    lon = _np(grid.lon)
    lat = _np(grid.lat)
    c = np.sin(lat_c) * np.sin(lat) + np.cos(lat_c) * np.cos(lat) * np.cos(lon - lon_c)
    return grid.radius * np.arccos(np.clip(c, -1.0, 1.0))


def cosine_bell(
    grid: CubedSphereGrid,
    h0: float = 1000.0,
    lon_c: float = 3 * np.pi / 2,
    lat_c: float = 0.0,
    radius_frac: float = 1.0 / 3.0,
):
    """Williamson TC1 cosine bell (the deck's advection demo IC, p.13/18).

    Returns the *extended* scalar (6, M, M); slice with ``grid.interior``
    for the prognostic state.
    """
    r = _great_circle(grid, lon_c, lat_c)
    R = radius_frac * grid.radius
    h = np.where(r < R, 0.5 * h0 * (1.0 + np.cos(np.pi * r / R)), 0.0)
    return jnp.asarray(h, dtype=grid.sqrtg.dtype)


def checkerboard(
    grid: CubedSphereGrid,
    face: int = 4,
    lo: float = 1.0,
    hi: float = 1000.0,
    tiles: int = 6,
):
    """The deck's "Lima Flag" checkerboard heat source on one panel
    (p.12/17): alternating lo/hi blocks on ``face``, ``lo`` elsewhere.
    Returns extended (6, M, M)."""
    m = grid.m
    jj, ii = np.meshgrid(np.arange(m), np.arange(m), indexing="ij")
    block = max(1, grid.n // tiles)
    pattern = np.where(((jj - grid.halo) // block + (ii - grid.halo) // block) % 2 == 0, hi, lo)
    field = np.full((6, m, m), lo)
    field[face] = pattern
    return jnp.asarray(field, dtype=grid.sqrtg.dtype)


def williamson_tc2(
    grid: CubedSphereGrid,
    gravity: float,
    omega: float,
    u0: float = 2 * np.pi * EARTH_RADIUS / (12 * 86400),
    gh0: float = 2.94e4,
    alpha_rot: float = 0.0,
):
    """TC2 steady geostrophic flow: returns (h_ext, v_ext).

    gh = gh0 - (a*Omega*u0 + u0^2/2) * (-cos(lon)cos(lat)sin(a) +
    sin(lat)cos(a))^2; exact steady state of the SWE.
    """
    lon = _np(grid.lon)
    lat = _np(grid.lat)
    a = grid.radius
    mu = -np.cos(lon) * np.cos(lat) * np.sin(alpha_rot) + np.sin(lat) * np.cos(alpha_rot)
    gh = gh0 - (a * omega * u0 + 0.5 * u0 * u0) * mu * mu
    h = jnp.asarray(gh / gravity, dtype=grid.sqrtg.dtype)
    v = solid_body_wind(grid, u0, alpha_rot)
    return h, v


def williamson_tc5(
    grid: CubedSphereGrid,
    gravity: float,
    omega: float,
    u0: float = 20.0,
    h0: float = 5960.0,
    mountain_h: float = 2000.0,
    lon_c: float = 3 * np.pi / 2,
    lat_c: float = np.pi / 6,
    mountain_r: float = np.pi / 9,
):
    """TC5 zonal flow over an isolated mountain: returns (h_ext, v_ext,
    b_ext) where b is the mountain surface height and h the *fluid depth*
    (so the free surface is h + b)."""
    lon = _np(grid.lon)
    lat = _np(grid.lat)
    a = grid.radius
    # Zonal balanced height for alpha=0 solid-body flow.
    gh = gravity * h0 - (a * omega * u0 + 0.5 * u0 * u0) * np.sin(lat) ** 2
    # Mountain: b = b0 (1 - r/R) with r the *angular* distance, clipped.
    dlon = np.arctan2(np.sin(lon - lon_c), np.cos(lon - lon_c))
    r = np.sqrt(np.minimum(mountain_r**2, dlon**2 + (lat - lat_c) ** 2))
    b = mountain_h * (1.0 - r / mountain_r)
    h = gh / gravity - b
    v = solid_body_wind(grid, u0, 0.0)
    dt = grid.sqrtg.dtype
    return jnp.asarray(h, dtype=dt), v, jnp.asarray(b, dtype=dt)


def williamson_tc6(
    grid: CubedSphereGrid,
    gravity: float,
    omega: float,
    omega_w: float = 7.848e-6,
    k_w: float = 7.848e-6,
    h0: float = 8000.0,
    r_w: int = 4,
):
    """TC6 Rossby-Haurwitz wave: returns (h_ext, v_ext)."""
    lon = _np(grid.lon)
    th = _np(grid.lat)
    a = grid.radius
    R = r_w
    cos = np.cos(th)
    sin = np.sin(th)

    u = a * omega_w * cos + a * k_w * cos ** (R - 1) * (
        R * sin * sin - cos * cos
    ) * np.cos(R * lon)
    v = -a * k_w * R * cos ** (R - 1) * sin * np.sin(R * lon)

    A = 0.5 * omega_w * (2 * omega + omega_w) * cos**2 + 0.25 * k_w**2 * cos ** (
        2 * R
    ) * ((R + 1) * cos**2 + (2 * R**2 - R - 2) - 2 * R**2 * cos ** (-2))
    B = (
        2 * (omega + omega_w) * k_w / ((R + 1) * (R + 2)) * cos**R
        * ((R**2 + 2 * R + 2) - (R + 1) ** 2 * cos**2)
    )
    C = 0.25 * k_w**2 * cos ** (2 * R) * ((R + 1) * cos**2 - (R + 2))
    gh = gravity * h0 + a * a * (A + B * np.cos(R * lon) + C * np.cos(2 * R * lon))

    h = jnp.asarray(gh / gravity, dtype=grid.sqrtg.dtype)
    vec = zonal_meridional_to_cartesian(grid, u, v)
    return h, vec


def perturbed_ensemble(
    grid: CubedSphereGrid,
    h_ext,
    members: int,
    seed: int = 0,
    amplitude: float = 1.0e-3,
):
    """Perturbed-IC height ensemble for batched runs: ``(B, 6, M, M)``.

    Member 0 is the unperturbed ``h_ext``; members ``1..B-1`` add a
    smooth large-scale perturbation ``amplitude * mean|h| * mode`` with
    ``mode`` a random unit-normalized combination of three ``l = 1``
    spherical modes ``ghat_j . rhat`` (the gentlest fields that still
    decorrelate trajectories — the standard perturbed-IC recipe for TC5
    / Galewsky spread studies).  Everything is evaluated analytically at
    extended cell centers in float64 (ghosts exact, like every IC in
    this module) with a deterministic ``numpy`` generator, so a given
    ``(seed, members)`` pair reproduces bit-identical ICs across runs
    and processes.  The wind is left unperturbed — height-only
    perturbations keep members balanced to the same order as the base
    state, so no member needs its own spin-up.
    """
    if members < 1:
        raise ValueError(f"members must be >= 1, got {members}")
    h = _np(h_ext)
    rhat = _np(grid.xyz) / grid.radius               # (3, 6, M, M)
    rng = np.random.default_rng(seed)
    href = float(np.mean(np.abs(h)))
    out = [h]
    for _ in range(members - 1):
        g = rng.standard_normal((3, 3))
        g /= np.linalg.norm(g, axis=1, keepdims=True)
        w = rng.standard_normal(3)
        mode = np.einsum("jk,k...->...", g * w[:, None], rhat)
        mode /= max(float(np.abs(mode).max()), 1e-300)
        out.append(h + amplitude * href * mode)
    return jnp.asarray(np.stack(out), dtype=grid.sqrtg.dtype)


def galewsky(
    grid: CubedSphereGrid,
    gravity: float,
    omega: float,
    u_max: float = 80.0,
    h_mean: float = 10158.0,
    lat0: float = np.pi / 7,
    lat1: float = np.pi / 2 - np.pi / 7,
    perturb: bool = True,
    h_hat: float = 120.0,
    alpha_p: float = 1.0 / 3.0,
    beta_p: float = 1.0 / 15.0,
    lat2: float = np.pi / 4,
):
    """Galewsky et al. (2004) barotropic-instability jet: (h_ext, v_ext).

    The balanced height is integrated numerically (fine trapezoid in
    float64) from gh'(lat) = -a u (f + u tan(lat)/a).
    """
    a = grid.radius
    en = np.exp(-4.0 / (lat1 - lat0) ** 2)

    def u_of(phi):
        inside = (phi > lat0) & (phi < lat1)
        safe = np.where(inside, (phi - lat0) * (phi - lat1), -1.0)
        return np.where(inside, u_max / en * np.exp(1.0 / safe), 0.0)

    # Fine latitude grid for the balance integral.
    phi_f = np.linspace(-np.pi / 2, np.pi / 2, 20001)
    u_f = u_of(phi_f)
    integrand = a * u_f * (2 * omega * np.sin(phi_f) + u_f * np.tan(phi_f) / a)
    gh_f = -np.concatenate([[0.0], np.cumsum(
        0.5 * (integrand[1:] + integrand[:-1]) * np.diff(phi_f)
    )])
    # Normalize to the prescribed global-mean-ish level.
    gh_f = gh_f - gh_f.mean() + gravity * h_mean

    lat = _np(grid.lat)
    lon = _np(grid.lon)
    gh = np.interp(lat, phi_f, gh_f)
    h = gh / gravity
    if perturb:
        lonp = np.arctan2(np.sin(lon), np.cos(lon))  # wrap to (-pi, pi)
        h = h + h_hat * np.cos(lat) * np.exp(-((lonp / alpha_p) ** 2)) * np.exp(
            -(((lat2 - lat) / beta_p) ** 2)
        )

    u = u_of(lat)
    vec = zonal_meridional_to_cartesian(grid, u, np.zeros_like(u))
    return jnp.asarray(h, dtype=grid.sqrtg.dtype), vec
