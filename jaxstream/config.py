"""YAML-driven configuration.

Source-compatible superset of the reference's config surface: the deck's
``config.yaml`` has a ``parallelization:`` block with ``tiles_per_edge``,
``num_devices``, ``device_type`` (screenshot deck p.8; consumed with
``.get`` defaults at ``/root/reference/JAX-DevLab-Examples.py:21-24``).
We keep those keys and defaults verbatim and add the sections the full
framework needs (grid, physics, time, io) — SURVEY.md §5 "Config / flag
system" rebuild note.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

import yaml

__all__ = [
    "GridConfig",
    "ParallelConfig",
    "PhysicsConfig",
    "TimeConfig",
    "AsyncPipelineConfig",
    "IOConfig",
    "EnsembleConfig",
    "ObservabilityConfig",
    "PrecisionConfig",
    "PlacementConfig",
    "ServeConfig",
    "DAConfig",
    "Config",
    "load_config",
]

EARTH_RADIUS = 6.37122e6
EARTH_OMEGA = 7.292e-5
EARTH_GRAVITY = 9.80616


@dataclasses.dataclass(frozen=True)
class GridConfig:
    n: int = 48                      # cells per panel edge (C{n})
    halo: int = 2                    # >=2 for PLR, >=3 for PPM
    radius: float = EARTH_RADIUS
    dtype: str = "float32"
    metrics: str = "eager"           # 'eager' (precomputed f64) | 'lazy' (fused)


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    # Reference-compatible keys + defaults (JAX-DevLab-Examples.py:21-24).
    tiles_per_edge: int = 1
    num_devices: int = 6
    device_type: str = "cpu"         # 'cpu' (virtual devices) | 'tpu' | 'gpu'
    # Extension: explicit shard_map+ppermute stepping (needs num_devices=6,
    # one face per device) instead of the GSPMD-inferred path.  Honored by
    # jaxstream.parallel.sharded_model.make_stepper_for.
    use_shard_map: bool = False
    # Overlapped halo exchange (explicit shard_map paths + the sharded
    # factored tier): issue every ppermute stage up front, run the
    # interior-only RHS kernel while the collectives are in flight, and
    # finish with the boundary-band pass on the received strips.  The
    # split path is parity-tested against the serialized default on all
    # tiers; default off so the serialized exchange stays the reference.
    overlap_exchange: bool = False
    # Donate the state carry to the compiled segment loops (XLA aliases
    # input/output state instead of double-buffering every prognostic).
    # On accelerators a donated buffer is CONSUMED: references a caller
    # holds to sim.state (or a previous run()'s return value) become
    # invalid once the next segment runs.  Set false to keep every
    # intermediate state alive at the cost of one extra state copy of
    # HBM residency.
    donate_state: bool = True
    # Temporal halo blocking: run `temporal_block` SSPRK3 steps per
    # compiled block.  On the explicit one-face-per-device tier this is
    # the deep-halo form — ONE exchange of width 3*k*halo strips per
    # block, then 3*k exchange-free RK stages on shrinking windows
    # (redundant ghost-band compute instead of collectives; seam values
    # are then face-local continuations, consistent to the stencil's own
    # O(d^2) — see docs/USAGE.md "Temporal halo blocking" for when k > 1
    # loses).  On the single-device fused, block-mesh, and factored TT
    # tiers the k steps are fused exactly (unchanged exchange data, one
    # dispatch per block).  Default 1 = the serialized reference path.
    temporal_block: int = 1


@dataclasses.dataclass(frozen=True)
class PhysicsConfig:
    gravity: float = EARTH_GRAVITY
    omega: float = EARTH_OMEGA
    hyperdiffusion: float = 0.0      # nu4 coefficient (m^4/s)
    divergence_damping: float = 0.0  # nondimensional d2 coefficient
    diffusivity: float = 1.0e5       # kappa (m^2/s) for the diffusion model


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str = "auto"               # 'auto' | 'shallow_water' | 'advection' | 'diffusion'
    initial_condition: str = "tc2"   # tc1/cosine_bell | checkerboard | tc2 | tc5 | tc6 | galewsky
    scheme: str = "plr"              # 'plr' | 'ppm' reconstruction
    limiter: str = "mc"              # 'minmod' | 'mc' | 'vanleer' | 'none'
    backend: str = "jnp"             # 'jnp' | 'pallas' RHS stencils
    ic_angle: float = 0.0            # flow-orientation angle (TC1/TC2 alpha)
    # The deck's "Numerics (TT)" pipeline stage (pdf p.7): 'tt' runs the
    # factored-panel solver tier (jaxstream.tt.sphere*) — every panel
    # field a rank-`tt_rank` factor pair, nothing (n, n) materialized.
    numerics: str = "dense"          # 'dense' | 'tt'
    tt_rank: int = 16                # factored-state rank when numerics='tt'
    # In-step Laplacian dissipation on the factored SWE's velocity
    # components (m^2/s; numerics='tt' + shallow_water only) — ordinary
    # explicit viscosity for the factored tier.  0 disables.
    tt_kappa: float = 0.0
    # Factored-step rounding: 'auto' picks 'svd' (exact truncation —
    # the stability tier; forced nonlinear flows NaN within a sim-day
    # under 'aca', DESIGN.md stability envelope) for shallow-water runs
    # and 'aca' (cross approximation — the speed tier, no
    # factorization kernels in the step) for advection/diffusion.
    tt_rounding: str = "auto"        # 'auto' | 'aca' | 'svd'
    # del^4 filter placement on the fused covariant path (nu4 > 0):
    # 'split' (the round-5 once-per-step filter kernel — the reference),
    # 'refused' (round 10: filter fused into the stage-1 kernel, 3
    # kernels + 3 routes/step; trajectories equal to split up to one
    # endpoint filter application, Galewsky day-6 gated), or 'stage'
    # (the round-4 in-stage kernel pair, kept as the parity oracle).
    nu4_mode: str = "split"          # 'split' | 'refused' | 'stage'


@dataclasses.dataclass(frozen=True)
class TimeConfig:
    dt: float = 600.0
    scheme: str = "ssprk3"
    duration_days: float = 1.0       # total integration length ...
    nsteps: int = 0                  # ... or an explicit step count (wins if > 0)


@dataclasses.dataclass(frozen=True)
class AsyncPipelineConfig:
    """Async host pipeline (``io.async_pipeline:`` block) — default off,
    and when off the run is bit-for-bit today's synchronous behavior.
    With ``enabled: true`` the segment loop double-buffers: segment k+1
    is dispatched before segment k's host work resolves, device->host
    copies start with ``copy_to_host_async`` behind the next dispatch,
    and history appends / checkpoint saves / telemetry JSONL records
    drain on a bounded background writer thread (docs/USAGE.md "Async
    host pipeline").  Written outputs are bitwise identical to the
    synchronous path — only the overlap changes."""
    enabled: bool = False
    # Backpressure bound: the writer queue blocks the main thread when
    # it already holds this many pending segments of tasks.  Host-side
    # snapshot memory stays bounded at max_pending_segments queued + 1
    # writing + 1 unresolved fetch (= 4 segments at the default)
    # regardless of how far the device runs ahead.  Must be >= 1.
    max_pending_segments: int = 2


@dataclasses.dataclass(frozen=True)
class IOConfig:
    history_path: str = "history"
    history_stride: int = 0          # steps between snapshots; 0 = off
    history_tt_rank: int = 0         # >0: TT-compress snapshots (lossy)
    checkpoint_path: str = "checkpoints"
    checkpoint_stride: int = 0
    async_pipeline: AsyncPipelineConfig = AsyncPipelineConfig()


@dataclasses.dataclass(frozen=True)
class EnsembleConfig:
    """Perturbed-IC ensemble block — the many-concurrent-simulations
    workload (Williamson TC5 / Galewsky perturbed ensembles).  With
    ``members > 1`` the run advances all members per step through the
    batched steppers (member axis folded into the kernel grid on the
    fused path; one ppermute carries every member's halo strips on the
    sharded tiers — docs/USAGE.md "Ensembles")."""
    members: int = 1          # ensemble size (1 = plain single run)
    seed: int = 0             # perturbation generator seed (deterministic)
    # Relative height-perturbation amplitude of members 1..B-1 (member 0
    # stays unperturbed): dh = amplitude * mean|h| * smooth mode.
    amplitude: float = 1.0e-3
    # Device-mesh layout for multi-device ensemble runs (round 12):
    # 'auto' = the 2-D ('panel', 'member') mesh (num_devices must be a
    # multiple of 6 — faces exchange over 'panel', members scatter over
    # 'member'); 'member' = a 1-D ('member',) mesh sharding ONLY the
    # member axis (any device count that divides `members`; zero wire
    # traffic, GSPMD path only — use_shard_map needs the panel axis).
    layout: str = "auto"      # 'auto' | 'panel_member' | 'member'


@dataclasses.dataclass(frozen=True)
class ObservabilityConfig:
    """In-loop run telemetry (jaxstream.obs) — off by default, and when
    off the run is bit-for-bit today's behavior.  With ``interval > 0``
    the compiled segment loops compute the configured invariant ladder
    on device every ``interval`` steps into a small buffer fetched with
    ONE device->host transfer per segment (docs/USAGE.md
    "Observability")."""
    # Comma-separated metric names (jaxstream.obs.metrics.METRICS), or
    # 'default' for the model family's ladder — SWE: mass, energy,
    # [enstrophy,] h_min, h_max, max_speed, cfl, nonfinite_count.
    metrics: str = "default"
    interval: int = 0         # steps between in-loop samples; 0 = off
    sink: str = ""            # JSONL path for manifest/segment records; '' = none
    # Guard policy on a NaN/Inf sample or CFL breach:
    # 'off' | 'warn' | 'checkpoint_and_raise' | 'halt'.
    guards: str = "off"
    cfl_limit: float = 2.0    # local-CFL guard threshold
    # Testing hook: inject NaN into the metric STREAM (never the state)
    # at this global step (must be a sampled step); -1 = disabled.
    fault_step: int = -1
    # Round 20: where crash-forensics bundles land (jaxstream.obs.
    # flight).  The in-memory flight recorder is ALWAYS on (bounded
    # ring, zero sink writes in steady state); a non-empty directory
    # here additionally flushes an atomic crash bundle on HealthError /
    # unhandled exception (and the serving stack keeps a live bundle
    # re-committed at segment boundaries, so a SIGKILL still leaves a
    # readable one).  '' = no bundle dumping — byte-identical on-disk
    # behavior to round 19.  scripts/serve.py and scripts/gateway.py
    # derive a default next to their sinks (--flight-dir overrides).
    flight_dir: str = ""


@dataclasses.dataclass(frozen=True)
class PrecisionConfig:
    """Per-stage dtype policy for the fused covariant stepper (round
    10; ``jaxstream.ops.pallas.precision`` holds the op-level
    semantics).  Defaults are all-f32 = bit-for-bit today's behavior.

    ``stage: bf16`` runs the stage kernels' flux face-average
    velocities, the PLR limiter algebra, and the strip router's
    rotation multiplies in bfloat16 — every accumulator and every
    metric term stays f32.  ``strips`` sets the inter-stage
    strip/ghost storage dtype ('auto' follows ``stage``); 16-bit
    strips halve strip HBM/wire traffic and keep exact mass
    conservation (one shared symmetrized edge value per physical
    edge).  ``carry`` selects the between-step HBM storage encoding —
    'bf16' (h and u bf16) or 'mixed16' (h int16 fixed-point about a
    static offset + u bf16, the bench's gated encoding); orthogonal to
    ``stage`` (arithmetic vs storage), the two stack.  See
    docs/USAGE.md "Precision" for measured budgets and the
    when-it-loses caveats."""
    stage: str = "f32"        # 'f32' | 'bf16' stage-kernel arithmetic
    strips: str = "auto"      # 'auto' | 'f32' | 'bf16' strip storage
    carry: str = "f32"        # 'f32' | 'bf16' | 'mixed16' carry storage


@dataclasses.dataclass(frozen=True)
class PlacementConfig:
    """Multi-chip serving placement (``serve.placement:`` block, round
    12) — default off, and when off the server is bit-for-bit the
    single-chip round-11 path.  ``mode: member`` shards the packed
    member axis across a 1-D ``('member',)`` device mesh (a B=16
    bucket on 8 chips runs 2 members/chip; zero wire traffic; classic
    jnp RHS only — GSPMD cannot split the fused kernels' member fold);
    ``mode: panel`` spreads each request's 6 faces over the 2-D
    ``('panel', 'member')`` mesh through the batched-exchange ensemble
    stepper (large grids; num_devices must be a multiple of 6;
    composes with ``parallelization.overlap_exchange``).  See
    docs/USAGE.md "Serving" (multi-chip) for when each mode wins."""
    mode: str = "off"         # 'off' | 'member' | 'panel'
    # Devices the server may span; 0 = every available device of
    # device_type.  Buckets that cannot use the whole pool (the plan
    # needs equal members per chip) use the largest fitting subset.
    num_devices: int = 0
    device_type: str = "cpu"  # 'cpu' (virtual devices) | 'tpu' | 'gpu'


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Continuous-batching ensemble server (``jaxstream.serve``, round
    11) — scenario requests packed into the member axis the way LLM
    servers pack prompts into a batch.  The server keeps one compiled
    masked-segment stepper warm per batch-size bucket (steady-state
    serving triggers ZERO recompiles once the bucket set is warm;
    ``JAXSTREAM_COMPILE_CACHE`` makes the warmup itself cheap across
    restarts) and refills a finished member's slot from the bounded
    request queue at the next segment boundary (docs/USAGE.md
    "Serving")."""
    # Comma-separated batch-size buckets.  A batch's size is the
    # smallest bucket >= the number of packable requests, so the whole
    # serving life of a deployment compiles len(buckets) segment
    # steppers per scenario group and nothing else.
    buckets: str = "1,4,16"
    # Steps per compiled masked segment — the refill granularity: a
    # finished member idles at most segment_steps - 1 steps before its
    # slot is refilled.  Smaller = tighter packing, more host
    # boundaries.
    segment_steps: int = 8
    # Bounded request queue (admission control): submit raises
    # QueueFull at capacity instead of buffering unboundedly.
    queue_capacity: int = 64
    # Per-request zarr result stores are written under this directory
    # (streamed through the async BackgroundWriter); '' = results are
    # only retained in memory (server.results).
    output_dir: str = ""
    # Serving telemetry JSONL (obs.sink format: 'serve' records with
    # slot occupancy + queue depth); '' = none.
    sink: str = ""
    # On a member's nonfinite state: 'evict' (default — fail only that
    # request, refill the slot, keep the batch alive), 'halt' (raise,
    # stopping the server), 'off' (no per-member guard).
    guards: str = "evict"
    # Admission control driven by the HealthMonitor: once this many
    # guard events have been recorded the server refuses NEW requests
    # (AdmissionRefused) — a deployment that keeps blowing up members
    # should fail fast, not accept more traffic.  0 disables.
    max_guard_events: int = 16
    # Testing hook (pairs with observability.fault_step): mark this
    # member's health count bad when its own step count reaches
    # fault_step — injected into the monitor STREAM on the host, never
    # the state — so the evict->refill path is testable without
    # integrating a real blowup.  -1 = disabled.
    fault_member: int = -1
    # Donate the segment carry (XLA aliases input/output state).
    donate: bool = True
    # Round 17: request-scoped tracing (jaxstream.obs.trace).  Every
    # admitted request gets a deterministic trace id and its lifecycle
    # phases (queue wait, pack, per-segment compute/host-wait,
    # finalize/fetch/flush) land as typed 'span' records in the serve
    # sink, reassemblable into a tree whose leaf durations sum to the
    # request's end-to-end latency (docs/USAGE.md "Operator view").
    # Default off = the sink stream is byte-identical to the untraced
    # round-14 records (no span records, no trace fields).
    trace: bool = False
    # Round 19 (performance observatory): poll device.memory_stats()
    # at every segment boundary (the autoscale-tick cadence) into the
    # per-chip jaxstream_device_memory_* gauges on /v1/metrics and
    # typed 'memory' sink records.  Off = the watcher is never
    # constructed — zero polling, sink byte-identical to round 18.
    memory_watch: bool = False
    # Round 19: measure every warm bucket's segment executable with
    # XLA's cost/memory analysis (ahead-of-time compile) so its cost
    # stamp carries real footprint bytes + the flops-vs-analytic
    # ratio, and the bucket plan gains the advisory headroom_frac.
    # COSTS one extra XLA compile per bucket at warmup (the measured
    # compile IS the recorded compile_seconds); off = stamps carry
    # the analytic half + warmup wall seconds only.
    cost_stamps: bool = False
    # Round 12: orography (the TC5 mountain) rides the batch as a
    # traced per-member field (zeros for the flat families), so
    # tc2/tc5/tc6/galewsky requests pack into ONE bucket in strict
    # queue FIFO order (bitwise-equal to the baked-static stepper,
    # tested).  `true` restores the round-11 batching groups (orography
    # baked as a stepper static; group-local FIFO; the fused
    # member-fold kernels apply where they compile) — the parity mode,
    # and required by placement mode 'panel' (the shard_map stepper
    # bakes orography per device).
    group_by_orography: bool = False
    # Round 21 (warm pools): directory of disk-backed serialized bucket
    # executables (jaxstream.serve.warmpool).  A restarted or freshly
    # spawned server LOADS its masked-segment executables from here
    # instead of recompiling — the degradation ladder is full AOT
    # executable -> serialized StableHLO -> persistent compile cache ->
    # cold compile, every rung a typed 'warmpool' sink record.  '' =
    # off (byte-identical warmup to round 20).
    warm_pool: str = ""
    # Round 21: jax persistent-compilation-cache directory, the warm
    # pool's third rung.  Gated behind a SUBPROCESS feature probe:
    # this image's jaxlib 0.4.37 is documented to segfault when a
    # different process deserializes CPU cache entries (the jax_compat
    # quarantine note), so the rung only engages after a child-process
    # write+read probe exits clean.  '' = rung disabled.
    compile_cache: str = ""
    # Round 21: background speculative compilation of ADJACENT plans
    # (the next configured bucket up/down from the active cap) on a
    # worker thread, nudged by resize()/autoscale — a later resize to
    # a not-yet-warm bucket stops paying jit at a segment boundary.
    # Requires warm_pool (the speculated executables persist there).
    speculate: bool = False
    # Round 21: the first CONSUMER of the round-19 advisory
    # headroom_frac — resize() and speculative compilation REFUSE a
    # bucket whose stamped per-chip footprint would leave less than
    # this headroom fraction (HeadroomRefused + a typed 'headroom'
    # sink record).  The default 0.0 refuses only footprints that
    # exceed per-chip capacity outright; advisory stays advisory for
    # request admission.  Enforcement needs a stamped plan
    # (serve.cost_stamps + serve.memory_watch) — unstamped plans are
    # never refused.
    min_headroom_frac: float = 0.0
    # Multi-chip placement sub-block (round 12; default mode 'off' =
    # the single-chip path, byte-for-byte).
    placement: PlacementConfig = PlacementConfig()


@dataclasses.dataclass(frozen=True)
class DAConfig:
    """Ensemble data assimilation (``jaxstream.da``, round 18) — the
    EnKF cycle on the batched ensemble steppers.  ``cycles: 0`` (the
    default) disables cycling entirely; with ``cycles > 0`` the
    drivers (:func:`jaxstream.da.run_cycle` in-process,
    :func:`jaxstream.da.run_cycle_gateway` through the HTTP gateway,
    ``scripts/assimilate.py``) run that many forecast->observe->
    analyze rounds against a hidden truth run.  Ensemble size/seed/
    amplitude come from the ``ensemble:`` block; the plan layer
    rejects illegal compositions statically (members >= 2, dense f32
    single-device tiers, no temporal blocking — docs/USAGE.md "Data
    assimilation")."""
    cycles: int = 0           # assimilation cycles; 0 = da off
    cycle_steps: int = 8      # forecast steps between analyses
    nstations: int = 64       # seeded h-observing stations
    obs_seed: int = 7         # station draw + obs noise seed
    obs_sigma: float = 1.0    # observation error std (m of h)
    inflation: float = 1.05   # multiplicative prior inflation
    # Gaspari-Cohn localization half-width in km; 0 = OFF (the pure
    # B x B ensemble-space solve — fine for dense networks/large B,
    # spurious at small B; see USAGE "when EnKF loses").
    localization_km: float = 0.0
    # Ensemble-statistics guards over the cycle (spread collapse /
    # filter divergence): 'off' | 'warn' | 'halt'.
    guards: str = "warn"
    # Posterior spread below this fraction of the INITIAL spread
    # trips the spread_collapse guard.  A healthy analysis contracts
    # spread a lot (to ~ the posterior error) — the guard is for the
    # runaway contraction that leaves the filter rejecting all future
    # observations, hence the deliberately low default.
    spread_collapse_factor: float = 0.01
    # Prior RMSE above this multiple of prior spread trips the
    # filter_divergence guard.
    divergence_ratio: float = 10.0
    sink: str = ""            # JSONL path for per-cycle 'da' records


@dataclasses.dataclass(frozen=True)
class Config:
    grid: GridConfig = GridConfig()
    parallelization: ParallelConfig = ParallelConfig()
    physics: PhysicsConfig = PhysicsConfig()
    model: ModelConfig = ModelConfig()
    time: TimeConfig = TimeConfig()
    io: IOConfig = IOConfig()
    ensemble: EnsembleConfig = EnsembleConfig()
    observability: ObservabilityConfig = ObservabilityConfig()
    precision: PrecisionConfig = PrecisionConfig()
    serve: ServeConfig = ServeConfig()
    da: DAConfig = DAConfig()


_SECTIONS = {
    "grid": GridConfig,
    "parallelization": ParallelConfig,
    "physics": PhysicsConfig,
    "model": ModelConfig,
    "time": TimeConfig,
    "io": IOConfig,
    "ensemble": EnsembleConfig,
    "observability": ObservabilityConfig,
    "precision": PrecisionConfig,
    "serve": ServeConfig,
    "da": DAConfig,
}


#: Dataclass-typed fields nested inside a section (config sub-blocks);
#: their YAML value is a mapping built recursively by _build_section.
_NESTED_SECTIONS = {
    "AsyncPipelineConfig": AsyncPipelineConfig,
    "PlacementConfig": PlacementConfig,
}


def _suggest(unknown, valid) -> str:
    """Did-you-mean tail for unknown-key errors: the closest valid
    name per typo (difflib ratio), so a plan-layer config mistake
    (``overlap_exchang``, ``temporal_blocks``) names its fix."""
    import difflib

    hints = []
    for k in sorted(unknown):
        close = difflib.get_close_matches(k, valid, n=1, cutoff=0.6)
        if close:
            hints.append(f"{k!r} -> did you mean {close[0]!r}?")
    return (" (" + "; ".join(hints) + ")") if hints else ""


def _build_section(cls, data: dict):
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise ValueError(
            f"unknown {cls.__name__} keys {sorted(unknown)}; valid: "
            f"{sorted(fields)}{_suggest(unknown, fields)}"
        )
    # Coerce to the declared field types: YAML 1.1 parses exponent
    # literals without a sign ("1.0e14") as *strings*, and users write
    # "6" where an int is declared — both must land as numbers.
    coerced = {}
    for k, v in data.items():
        ftype = fields[k].type
        ftype = getattr(ftype, "__name__", ftype)  # str or type object
        if ftype in _NESTED_SECTIONS:
            # Recurse OUTSIDE the coercion try: a bad key/value inside
            # the nested mapping must surface _build_section's own
            # message (which names the unknown key and the valid set),
            # not a generic "expects a <section>" rewrap.
            nested = _NESTED_SECTIONS[ftype]
            if isinstance(v, nested):
                pass
            elif isinstance(v, dict) or v is None:
                v = _build_section(nested, v or {})
            else:
                raise ValueError(
                    f"{cls.__name__}.{k} expects a {ftype} mapping, "
                    f"got {v!r}"
                )
            coerced[k] = v
            continue
        try:
            if ftype == "float" and not isinstance(v, float):
                v = float(v)
            elif ftype == "int" and not isinstance(v, (int, bool)):
                v = int(v)
        except (TypeError, ValueError):
            raise ValueError(
                f"{cls.__name__}.{k} expects a {ftype}, got {v!r}"
            ) from None
        coerced[k] = v
    return cls(**coerced)


def load_config(source: Any = None) -> Config:
    """Build a Config from a YAML path, a YAML string, a dict, or None."""
    if source is None:
        return Config()
    if isinstance(source, Config):
        return source
    if isinstance(source, dict):
        data = source
    else:
        text = str(source)
        if os.path.exists(text):
            with open(text) as fh:
                data = yaml.safe_load(fh) or {}
        else:
            loaded = yaml.safe_load(text)
            if not isinstance(loaded, dict):
                raise ValueError(
                    f"config source {text!r} is neither an existing file path "
                    f"nor a YAML mapping"
                )
            data = loaded
    kwargs = {}
    unknown = set(data) - set(_SECTIONS)
    if unknown:
        raise ValueError(
            f"unknown config sections {sorted(unknown)}; valid: "
            f"{sorted(_SECTIONS)}{_suggest(unknown, _SECTIONS)}"
        )
    for name, cls in _SECTIONS.items():
        if name in data:
            kwargs[name] = _build_section(cls, data[name] or {})
    return Config(**kwargs)
