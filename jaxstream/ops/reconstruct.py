"""Slope-limited reconstructions for finite-volume fluxes.

The reference's numerics layer is described but not shipped ("Finite Volume
(PLR) Method ... 2nd Order", deck p.4, p.13; SURVEY.md §2.2).  These are the
piecewise-linear (PLR) limiters and the piecewise-parabolic (PPM) face
values, written axis-agnostically over extended (halo-carrying) arrays so
the same code serves x- and y-direction fluxes under dimension splitting.

Everything is branch-free elementwise math (``jnp.where``/min/max) — VPU
-friendly, no data-dependent control flow under ``jit``.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["slope", "plr_face_states", "ppm_face_states", "LIMITERS"]


def _minmod2(a, b):
    # Sign-free form (bitwise equal to the 0.5(sign+sign)min(abs) form).
    return (jnp.maximum(0.0, jnp.minimum(a, b))
            + jnp.minimum(0.0, jnp.maximum(a, b)))


def _slope_none(dqm, dqp):
    # Unlimited centered slope: plain 2nd order (good for smooth fields).
    return 0.5 * (dqm + dqp)


def _slope_minmod(dqm, dqp):
    return _minmod2(dqm, dqp)


def _slope_mc(dqm, dqp):
    # Monotonized-central: minmod((dqm+dqp)/2, 2 dqm, 2 dqp), written as
    # max(0, min3) + min(0, max3) — the sign-free 3-arg minmod.  Bitwise
    # equal to the sign() form (mul by 2/0.5 is exact; for same-sign
    # args min3/max3 reproduce sgn*mag, for mixed signs both give 0)
    # and ~4 VPU ops cheaper per cell: no sign() (2 compare+selects
    # each) and no abs chain.  Measured on the fused C384 stepper this
    # is most of the "limiter algebra" lever (DESIGN.md perf ladder).
    a = 0.5 * (dqm + dqp)
    b = 2.0 * dqm
    c = 2.0 * dqp
    return (jnp.maximum(0.0, jnp.minimum(jnp.minimum(a, b), c))
            + jnp.minimum(0.0, jnp.maximum(jnp.maximum(a, b), c)))


def _slope_vanleer(dqm, dqp):
    prod = dqm * dqp
    return jnp.where(prod > 0, 2.0 * prod / (dqm + dqp + 1e-300), 0.0)


def _slope_mc_sign(dqm, dqp):
    # The sign() form of MC (bitwise equal to _slope_mc); kept for A/B
    # perf measurement.
    sgn = 0.5 * (jnp.sign(dqm) + jnp.sign(dqp))
    mag = jnp.minimum(
        0.5 * jnp.abs(dqm + dqp), 2.0 * jnp.minimum(jnp.abs(dqm), jnp.abs(dqp))
    )
    return sgn * mag


LIMITERS = {
    "none": _slope_none,
    "minmod": _slope_minmod,
    "mc": _slope_mc,
    "mc_sign": _slope_mc_sign,
    "vanleer": _slope_vanleer,
}


def _sl(arr, lo, hi, axis):
    idx = [slice(None)] * arr.ndim
    idx[axis] = slice(lo, hi)
    return arr[tuple(idx)]


def slope(q, axis: int, limiter: str = "mc", slope_dtype=None):
    """Limited slope for cells 1..len-2 along ``axis`` (shrinks by 2).

    ``slope_dtype`` (round-10 precision policy): run the limiter algebra
    — the candidate/min/max chain, most of the reconstruction's VPU ops
    — in a narrower dtype by casting the cell DIFFERENCES (never the
    cell values) on the way in.  ``None`` is bitwise the historical
    trace."""
    lim = LIMITERS[limiter]
    qm = _sl(q, 0, -2, axis)
    qc = _sl(q, 1, -1, axis)
    qp = _sl(q, 2, None, axis)
    if slope_dtype is None:
        return lim(qc - qm, qp - qc)
    return lim((qc - qm).astype(slope_dtype),
               (qp - qc).astype(slope_dtype))


def plr_face_states(q, axis: int, h: int, n: int, limiter: str = "mc",
                    slope_dtype=None):
    """Left/right states at the n+1 interior-bounding faces along ``axis``.

    ``q`` is extended along ``axis`` (length n + 2h, h >= 2).  Face i (for
    i = h..h+n) separates cells i-1 and i; returns ``(qL, qR)`` each of
    length n+1 along ``axis``.

    ``slope_dtype`` (round-10 precision policy, e.g. ``jnp.bfloat16``):
    the limiter algebra runs in that dtype and the face state is
    assembled as ``q.dtype cell value +- q.dtype(narrow half-slope)`` —
    quantization lands on the *slope correction*, never the cell value,
    so the face-state error is O(ulp) of the local gradient (a direct
    bf16 cast of h ~ 5e3 m would be a ~16 m quantum; this form is
    ~4e-2 m per m/cell of slope).  ``None`` is bitwise the historical
    path.  Measured budgets: tests/test_precision.py.
    """
    if h < 2:
        raise ValueError(f"PLR fluxes need halo >= 2, got halo={h}")
    # Slopes for cells h-1..h+n (n+2 of them).
    c1 = _sl(q, h - 1, h + n + 1, axis)
    sigma = slope(_sl(q, h - 2, h + n + 2, axis), axis, limiter,
                  slope_dtype)
    half = 0.5 * sigma
    if slope_dtype is not None:
        half = half.astype(q.dtype)
    recon_hi = c1 + half
    recon_lo = c1 - half
    qL = _sl(recon_hi, 0, n + 1, axis)  # upwind state from cell i-1
    qR = _sl(recon_lo, 1, n + 2, axis)  # upwind state from cell i
    return qL, qR


def ppm_face_states(q, axis: int, h: int, n: int):
    """PPM (piecewise-parabolic, Colella-Woodward) face states.

    Needs h >= 3 (reads the 4-cell stencil around each face and the
    limited 6th-order-ish edge interpolant).  Returns ``(qL, qR)`` at the
    n+1 faces, with the standard PPM monotonicity limiting applied to the
    parabola in each upwind cell.  This is the reference deck's roadmap
    "PPM upgrade" (SURVEY.md §2.2) in axis-agnostic form.
    """
    if h < 3:
        raise ValueError(f"PPM needs halo >= 3, got {h}")

    # Edge value at face i: 7/12 (q_{i-1}+q_i) - 1/12 (q_{i-2}+q_{i+1}),
    # computed for faces h-1 .. h+n+1 (n+3 faces) so each of the cells
    # h-1..h+n has both its edges.
    qm2 = _sl(q, h - 3, h + n, axis)
    qm1 = _sl(q, h - 2, h + n + 1, axis)
    qp0 = _sl(q, h - 1, h + n + 2, axis)
    qp1 = _sl(q, h, h + n + 3, axis)
    edge = (7.0 / 12.0) * (qm1 + qp0) - (1.0 / 12.0) * (qm2 + qp1)

    # Per-cell left/right edge values for cells h-1..h+n (n+2 cells).
    ql_c = _sl(edge, 0, n + 2, axis)
    qr_c = _sl(edge, 1, n + 3, axis)
    qc = _sl(q, h - 1, h + n + 1, axis)

    # PPM limiter (CW84 eq. 1.10): enforce monotonicity of the parabola.
    # 1) If qc is a local extremum w.r.t. its edges, flatten.
    extremum = (qr_c - qc) * (qc - ql_c) <= 0
    ql_c = jnp.where(extremum, qc, ql_c)
    qr_c = jnp.where(extremum, qc, qr_c)
    # 2) Clip overshooting parabolas.
    dq = qr_c - ql_c
    q6 = 6.0 * (qc - 0.5 * (ql_c + qr_c))
    ql_c = jnp.where(dq * q6 > dq * dq, 3.0 * qc - 2.0 * qr_c, ql_c)
    qr_c = jnp.where(-(dq * dq) > dq * q6, 3.0 * qc - 2.0 * ql_c, qr_c)

    # Face i takes the right edge of cell i-1 (qL) and left edge of cell i.
    qL = _sl(qr_c, 0, n + 1, axis)
    qR = _sl(ql_c, 1, n + 2, axis)
    return qL, qR
