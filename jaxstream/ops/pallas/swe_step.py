"""Fused SSPRK3 stage kernels: RHS + stage combination in one HBM pass.

The headline bottleneck is HBM bandwidth (SURVEY.md §6: FV numerics are
memory-bound, deck p.19).  The straightforward step — embed interior ->
exchange -> RHS kernel -> tree_map axpy per RK stage — moves each field
through HBM several extra times per stage (the embed pad, the tendency
array, and the axpy read-modify-write are all full-field passes).

This module removes all of them.  State is carried *extended* (ghosts
included, ``(6, M, M)`` / ``(3, 6, M, M)``) across the whole integration,
and each SSPRK3 stage

    y_out = a * y0 + b * y_c + (b * dt) * f(y_c)

is ONE Pallas kernel per face that reads the ghost-filled stage state,
computes the complete SWE right-hand side in VMEM
(:func:`jaxstream.ops.pallas.swe_rhs.rhs_core`), and writes the combined
next-stage state directly — tendencies never touch HBM, and the only
other per-stage traffic is the halo strip writes.  Ghost cells of the
output are written as ``a*y0 + b*y_c`` (finite, cheap) and are refilled
by the next exchange before anything reads them.

Shu-Osher coefficients: stage 1 (a=0, b=1), stage 2 (a=3/4, b=1/4),
stage 3 (a=1/3, b=2/3).  Stage 1 has ``a == 0`` and is built without the
``y0`` inputs at all so their blocks are never fetched.

The pure-JAX path (:mod:`jaxstream.stepping` over
:meth:`ShallowWater.rhs`) remains the parity oracle; see
tests/test_fused_step.py.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...utils.jax_compat import tpu_compiler_params

from ...geometry.connectivity import (
    EDGE_E,
    EDGE_N,
    EDGE_S,
    EDGE_W,
    build_connectivity,
)
from .swe_rhs import coord_rows, pick_recon, rhs_core, rhs_core_fast

__all__ = [
    "make_swe_stage_pallas",
    "make_fused_ssprk3_step",
    "make_swe_stage_inkernel",
    "make_fused_ssprk3_step_inkernel",
    "raw_strips",
    "route_strips",
]

SSPRK3_COEFFS = ((0.0, 1.0), (0.75, 0.25), (1.0 / 3.0, 2.0 / 3.0))


def make_swe_stage_pallas(
    n: int,
    halo: int,
    dalpha: float,
    radius: float,
    gravity: float,
    omega: float,
    dt: float,
    a: float,
    b: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    fast: bool = True,
):
    """Build one fused RK-stage call with static coefficients ``(a, b)``.

    Returns ``stage(hc, vc, b_ext) -> (h_out, v_out)`` when ``a == 0``
    (stage 1: no dependence on the step-start state), else
    ``stage(h0, v0, hc, vc, b_ext) -> (h_out, v_out)``.  All fields are
    extended; outputs have valid interiors and finite-but-stale ghosts.
    """
    m = n + 2 * halo
    i0, i1 = halo, halo + n
    d = float(dalpha)
    g_dt = b * dt  # tendency multiplier: y_out = a*y0 + b*yc + (b*dt)*f(yc)
    recon = pick_recon(scheme, halo, n, limiter)
    x_row, xf_row, x_col, xf_col, frames = coord_rows(n, halo)
    with_y0 = a != 0.0

    def kernel(*refs):
        if with_y0:
            (frame_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
             h0_ref, v0_ref, hc_ref, vc_ref, b_ref, ho_ref, vo_ref) = refs
        else:
            (frame_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
             hc_ref, vc_ref, b_ref, ho_ref, vo_ref) = refs

        hf = hc_ref[0]                       # (M, M)
        v = [vc_ref[0, 0], vc_ref[1, 0], vc_ref[2, 0]]
        bf = b_ref[0]

        dh, dv = (rhs_core_fast if fast else rhs_core)(
            frame_ref, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
            hf, v, bf, n=n, halo=halo, d=d, radius=radius,
            gravity=gravity, omega=omega, recon=recon,
        )

        fa = jnp.float32(a)
        fb = jnp.float32(b)
        fg = jnp.float32(g_dt)
        if with_y0:
            out_h = fa * h0_ref[0] + fb * hf
            out_v = [fa * v0_ref[i, 0] + fb * v[i] for i in range(3)]
        else:
            # a == 0: no y0 term, but honor b (stage 1 of SSPRK3 has b=1,
            # other schemes may not).
            out_h = hf if b == 1.0 else fb * hf
            out_v = v if b == 1.0 else [fb * v[i] for i in range(3)]
        # Full-block write (keeps ghosts finite), then the interior gets
        # the tendency added on top — both stores stay in VMEM until the
        # block flushes, so HBM sees each output exactly once.
        ho_ref[0] = out_h
        ho_ref[0, i0:i1, i0:i1] = out_h[i0:i1, i0:i1] + fg * dh
        for i in range(3):
            vo_ref[i, 0] = out_v[i]
            vo_ref[i, 0, i0:i1, i0:i1] = out_v[i][i0:i1, i0:i1] + fg * dv[i]

    scalar_specs = [
        pl.BlockSpec((1, 3, 3), lambda f: (f, 0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
    ]
    h_spec = pl.BlockSpec((1, m, m), lambda f: (f, 0, 0),
                          memory_space=pltpu.VMEM)
    v_spec = pl.BlockSpec((3, 1, m, m), lambda f: (0, f, 0, 0),
                          memory_space=pltpu.VMEM)
    state_specs = [h_spec, v_spec]
    in_specs = scalar_specs + (state_specs if with_y0 else []) + \
        state_specs + [h_spec]

    call = pl.pallas_call(
        kernel,
        grid_spec=pl.GridSpec(grid=(6,), in_specs=in_specs,
                              out_specs=[h_spec, v_spec]),
        out_shape=[
            jax.ShapeDtypeStruct((6, m, m), jnp.float32),
            jax.ShapeDtypeStruct((3, 6, m, m), jnp.float32),
        ],
        # Same scoped-VMEM story as the RHS kernel (swe_rhs.py): whole-face
        # stencil intermediates at C384 exceed the 16 MB default.
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    if with_y0:
        def stage(h0, v0, hc, vc, b_ext) -> Tuple[jax.Array, jax.Array]:
            return tuple(call(frames, x_row, xf_row, x_col, xf_col,
                              h0, v0, hc, vc, b_ext))
    else:
        def stage(hc, vc, b_ext) -> Tuple[jax.Array, jax.Array]:
            return tuple(call(frames, x_row, xf_row, x_col, xf_col,
                              hc, vc, b_ext))
    return stage


def make_fused_ssprk3_step(
    n: int,
    halo: int,
    dalpha: float,
    radius: float,
    gravity: float,
    omega: float,
    dt: float,
    exchange,
    b_ext,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    fast: bool = True,
):
    """Build ``step(y_ext, t) -> y_ext`` over extended-state pytrees.

    ``y_ext = {"h": (6, M, M), "v": (3, 6, M, M)}`` with ghosts in any
    state (stale is fine: every stage exchanges before it reads).
    ``exchange`` is a scalar/vector halo exchanger over extended arrays
    (leading axes carried through).
    """
    mk = lambda a, b: make_swe_stage_pallas(
        n, halo, dalpha, radius, gravity, omega, dt, a, b,
        scheme=scheme, limiter=limiter, interpret=interpret, fast=fast,
    )
    (a1, b1), (a2, b2), (a3, b3) = SSPRK3_COEFFS
    stage1 = mk(a1, b1)
    stage2 = mk(a2, b2)
    stage3 = mk(a3, b3)

    def step(y, t):
        del t  # the SWE RHS is autonomous
        h0 = exchange(y["h"])
        v0 = exchange(y["v"])
        h1, v1 = stage1(h0, v0, b_ext)
        h1 = exchange(h1)
        v1 = exchange(v1)
        h2, v2 = stage2(h0, v0, h1, v1, b_ext)
        h2 = exchange(h2)
        v2 = exchange(v2)
        h3, v3 = stage3(h0, v0, h2, v2, b_ext)
        return {"h": h3, "v": v3}

    return step


# ---------------------------------------------------------------------------
# In-kernel exchange: the whole step with zero standalone exchange passes.
#
# Each stage kernel emits, besides the combined next-stage state, the RAW
# boundary strips of its face (4 static slices, no data transforms — the
# Mosaic TPU lowering has no `rev`, so flips stay out of kernels).  A tiny
# jnp "router" between stages turns every face's raw strips into its
# neighbors' ghost data — the full cube topology (canonical frames,
# along-edge reversals, W/E transposes) applied to ~74 KB of strip
# tensors.  The next stage kernel then fills its ghost ring with 4 static
# writes.  Net: the halo exchange costs strip traffic only; full fields
# move through HBM exactly once per stage.  The strips ride the
# integration carry: y = {h, v, sh_sn, sh_we, sv_sn, sv_we}.
# ---------------------------------------------------------------------------


def raw_strips(field, n: int, halo: int):
    """Raw boundary strips of an extended field, kernel-output layout.

    Returns ``(sn, we)``: ``sn = (..., 6, 2, halo, n)`` holding the
    untransformed S/N interior rows, ``we = (..., 6, 2, n, halo)`` the W/E
    interior columns.  Carry initialisation for the in-kernel-exchange
    stepper (afterwards the kernels maintain the strips themselves).
    """
    i0, i1 = halo, halo + n
    sn = jnp.stack([
        jnp.stack([field[..., f, i0 : i0 + halo, i0:i1],
                   field[..., f, i1 - halo : i1, i0:i1]], axis=-3)
        for f in range(6)
    ], axis=-4)
    we = jnp.stack([
        jnp.stack([field[..., f, i0:i1, i0 : i0 + halo],
                   field[..., f, i0:i1, i1 - halo : i1]], axis=-3)
        for f in range(6)
    ], axis=-4)
    return sn, we


def route_strips(sn, we):
    """Raw strips -> placed ghost tensors (the cube-edge communication).

    Input: the output of :func:`raw_strips` (any leading axes).  Output
    ``(gsn, gwe)`` with ``gsn[..., f, 0] = (halo, n)`` rows to write at
    face ``f``'s S ghost ``[0:halo, halo:halo+n]``, ``gsn[..., f, 1]``
    the N ghost rows, and ``gwe[..., f, 0/1] = (n, halo)`` the W/E ghost
    columns.  All canonical-frame math (depth ordering, along-edge
    reversal, transposes — jaxstream.parallel.halo read/write_strip
    conventions) happens here, on strip-sized arrays.
    """
    from ...parallel.halo import canonicalize_strip, place_strip

    adj = build_connectivity()

    def ghost(f, e):
        link = adj[f][e]
        ne = link.nbr_edge
        if ne in (EDGE_S, EDGE_N):
            raw = sn[..., link.nbr_face, 0 if ne == EDGE_S else 1, :, :]
        else:
            raw = we[..., link.nbr_face, 0 if ne == EDGE_W else 1, :, :]
        s = canonicalize_strip(ne, raw)
        if link.reversed_:
            s = jnp.flip(s, axis=-1)
        return place_strip(e, s)

    gsn = jnp.stack([
        jnp.stack([ghost(f, EDGE_S), ghost(f, EDGE_N)], axis=-3)
        for f in range(6)
    ], axis=-4)
    gwe = jnp.stack([
        jnp.stack([ghost(f, EDGE_W), ghost(f, EDGE_E)], axis=-3)
        for f in range(6)
    ], axis=-4)
    return gsn, gwe


def make_swe_stage_inkernel(
    n: int,
    halo: int,
    dalpha: float,
    radius: float,
    gravity: float,
    omega: float,
    dt: float,
    a: float,
    b: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    fast: bool = True,
):
    """One fused RK stage with the halo fill inside the kernel.

    ``a == 0``: ``stage(hc, vc, ghosts, b_ext)``; else
    ``stage(h0, v0, hc, vc, ghosts, b_ext)``; ``ghosts`` is the routed
    4-tuple ``(gsn, gwe, vgsn, vgwe)`` from :func:`route_strips`.
    Returns ``(h, v, sn, we, vsn, vwe)`` — the combined state plus its
    raw boundary strips.  Ghost corners are left stale — the
    dimension-split stencils never read them (see halo._fill_corners).
    """
    m = n + 2 * halo
    i0, i1 = halo, halo + n
    d = float(dalpha)
    g_dt = b * dt
    recon = pick_recon(scheme, halo, n, limiter)
    x_row, xf_row, x_col, xf_col, frames = coord_rows(n, halo)
    with_y0 = a != 0.0
    h = halo

    def fill_ghosts(scratch, face_val, gsn, gwe):
        """Ghost-filled face via a VMEM scratch buffer.

        Mosaic TPU lowers neither ``scatter`` nor value-level
        ``dynamic_update_slice`` nor lane-misaligned ``concatenate``, but
        *ref stores with static slices* are first-class: copy the face
        into scratch, overwrite the 4 ghost strips, read it back.  Ghost
        corners keep the previous stage's (finite, never-read) values.
        """
        scratch[:] = face_val
        scratch[0:h, i0:i1] = gsn[0]
        scratch[i1 : i1 + h, i0:i1] = gsn[1]
        scratch[i0:i1, 0:h] = gwe[0]
        scratch[i0:i1, i1 : i1 + h] = gwe[1]
        return scratch[:]

    def kernel(*refs):
        if with_y0:
            (frame_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
             h0_ref, v0_ref, hc_ref, vc_ref,
             gsn_ref, gwe_ref, vgsn_ref, vgwe_ref, b_ref,
             ho_ref, vo_ref, sno_ref, weo_ref, vsno_ref, vweo_ref,
             *scratch) = refs
        else:
            (frame_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
             hc_ref, vc_ref,
             gsn_ref, gwe_ref, vgsn_ref, vgwe_ref, b_ref,
             ho_ref, vo_ref, sno_ref, weo_ref, vsno_ref, vweo_ref,
             *scratch) = refs

        hf = fill_ghosts(scratch[0], hc_ref[0], gsn_ref[0], gwe_ref[0])
        v = [fill_ghosts(scratch[1 + i], vc_ref[i, 0],
                         vgsn_ref[i, 0], vgwe_ref[i, 0])
             for i in range(3)]
        bf = b_ref[0]

        dh, dv = (rhs_core_fast if fast else rhs_core)(
            frame_ref, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
            hf, v, bf, n=n, halo=halo, d=d, radius=radius,
            gravity=gravity, omega=omega, recon=recon,
        )

        fa = jnp.float32(a)
        fb = jnp.float32(b)
        fg = jnp.float32(g_dt)
        if with_y0:
            out_h = fa * h0_ref[0] + fb * hf
            out_v = [fa * v0_ref[i, 0] + fb * v[i] for i in range(3)]
        else:
            out_h = hf if b == 1.0 else fb * hf
            out_v = list(v) if b == 1.0 else [fb * v[i] for i in range(3)]

        def emit(val, tend, out_ref, sn_ref, we_ref, lead=()):
            """Store combined state: full block, then the tendency-updated
            interior on top (both stores flush from VMEM once), plus the
            raw boundary strips of the *final* interior."""
            int_new = val[i0:i1, i0:i1] + fg * tend
            out_ref[lead + (0,)] = val
            out_ref[lead + (0, slice(i0, i1), slice(i0, i1))] = int_new
            sn_ref[lead + (0, 0)] = int_new[0:h, :]
            sn_ref[lead + (0, 1)] = int_new[n - h : n, :]
            we_ref[lead + (0, 0)] = int_new[:, 0:h]
            we_ref[lead + (0, 1)] = int_new[:, n - h : n]

        emit(out_h, dh, ho_ref, sno_ref, weo_ref)
        for i in range(3):
            emit(out_v[i], dv[i], vo_ref, vsno_ref, vweo_ref, lead=(i,))

    frame_spec = pl.BlockSpec((1, 3, 3), lambda f: (f, 0, 0),
                              memory_space=pltpu.SMEM)
    coord_specs = [
        pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
    ]
    h_blk = pl.BlockSpec((1, m, m), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM)
    v_blk = pl.BlockSpec((3, 1, m, m), lambda f: (0, f, 0, 0),
                         memory_space=pltpu.VMEM)
    sn_blk = pl.BlockSpec((1, 2, h, n), lambda f: (f, 0, 0, 0),
                          memory_space=pltpu.VMEM)
    we_blk = pl.BlockSpec((1, 2, n, h), lambda f: (f, 0, 0, 0),
                          memory_space=pltpu.VMEM)
    vsn_blk = pl.BlockSpec((3, 1, 2, h, n), lambda f: (0, f, 0, 0, 0),
                           memory_space=pltpu.VMEM)
    vwe_blk = pl.BlockSpec((3, 1, 2, n, h), lambda f: (0, f, 0, 0, 0),
                           memory_space=pltpu.VMEM)

    in_specs = [frame_spec] + coord_specs
    if with_y0:
        in_specs += [h_blk, v_blk]
    in_specs += [h_blk, v_blk, sn_blk, we_blk, vsn_blk, vwe_blk, h_blk]

    call = pl.pallas_call(
        kernel,
        grid_spec=pl.GridSpec(
            grid=(6,),
            in_specs=in_specs,
            out_specs=[h_blk, v_blk, sn_blk, we_blk, vsn_blk, vwe_blk],
            scratch_shapes=[pltpu.VMEM((m, m), jnp.float32)
                            for _ in range(4)],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((6, m, m), jnp.float32),
            jax.ShapeDtypeStruct((3, 6, m, m), jnp.float32),
            jax.ShapeDtypeStruct((6, 2, h, n), jnp.float32),
            jax.ShapeDtypeStruct((6, 2, n, h), jnp.float32),
            jax.ShapeDtypeStruct((3, 6, 2, h, n), jnp.float32),
            jax.ShapeDtypeStruct((3, 6, 2, n, h), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    if with_y0:
        def stage(h0, v0, hc, vc, ghosts, b_ext):
            return tuple(call(frames, x_row, xf_row, x_col, xf_col,
                              h0, v0, hc, vc, *ghosts, b_ext))
    else:
        def stage(hc, vc, ghosts, b_ext):
            return tuple(call(frames, x_row, xf_row, x_col, xf_col,
                              hc, vc, *ghosts, b_ext))
    return stage


def make_fused_ssprk3_step_inkernel(
    n: int,
    halo: int,
    dalpha: float,
    radius: float,
    gravity: float,
    omega: float,
    dt: float,
    b_ext,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    fast: bool = True,
):
    """``step(y, t) -> y``, ``y = {h, v, sh_sn, sh_we, sv_sn, sv_we}``.

    The minimum-HBM-traffic step: three kernel launches plus three
    strip-routing shuffles, no standalone exchange or axpy passes.
    Initialise the strip carry with :func:`raw_strips`; ``h``/``v`` ghost
    rings are maintained by the kernels (corners stay stale — never read
    by the stencils).
    """
    mk = lambda a, b: make_swe_stage_inkernel(
        n, halo, dalpha, radius, gravity, omega, dt, a, b,
        scheme=scheme, limiter=limiter, interpret=interpret, fast=fast,
    )
    (a1, b1), (a2, b2), (a3, b3) = SSPRK3_COEFFS
    stage1 = mk(a1, b1)
    stage2 = mk(a2, b2)
    stage3 = mk(a3, b3)

    def ghosts_of(sn, we, vsn, vwe):
        # Direct small-op routing: measured faster on TPU than a
        # one-big-gather formulation (trace route_strips over index
        # arrays, replay as one jnp.take) — arbitrary-index gathers are
        # expensive on TPU; the 2xN strip shuffles fuse well.
        return route_strips(sn, we) + route_strips(vsn, vwe)

    def step(y, t):
        del t
        h0, v0 = y["h"], y["v"]
        g0 = ghosts_of(y["sh_sn"], y["sh_we"], y["sv_sn"], y["sv_we"])
        h1, v1, *s1 = stage1(h0, v0, g0, b_ext)
        h2, v2, *s2 = stage2(h0, v0, h1, v1, ghosts_of(*s1), b_ext)
        h3, v3, *s3 = stage3(h0, v0, h2, v2, ghosts_of(*s2), b_ext)
        return {"h": h3, "v": v3, "sh_sn": s3[0], "sh_we": s3[1],
                "sv_sn": s3[2], "sv_we": s3[3]}

    return step
