"""Per-stage precision policy for the fused covariant stage kernels.

Round 10 (ROADMAP open item 3).  ``mixed16`` previously existed only as
a *carry* encoding between steps (``carry_dtype`` on the compact
stepper: 16-bit HBM storage, every arithmetic op still f32).  Bench r05
showed the fused TC5 C384 path compute-bound at ~48% of the VPU roof —
the remaining headroom is in the stage arithmetic itself, and the SWE
accuracy budget tolerates reduced-precision arithmetic in exactly the
flop-dominant places (Danis et al. 2024, PAPERS.md; the Putman & Lin
2007 flux/reconstruction stages).  This module is the one definition of
*which* ops drop to bfloat16 and which must not:

``compute='bf16'`` — the stage kernels' **flux face-average
velocities**, the **PLR limiter algebra** (the slope min/max chain,
about half of the reconstruction's VPU ops), and the strip **router's
rotation multiplies** run in bfloat16.  Everything else keeps f32:

  * **accumulators** — upwind flux products, divergences, Bernoulli /
    vorticity gradients, and the RK combines all accumulate in f32 (a
    bf16 value entering an f32 op promotes; the quantization lands on
    the *operand*, never the running sum);
  * **metric terms** — the closed-form ``_fast_frame`` fields stay f32
    (they multiply into f32 accumulators, and metric roundoff is a
    systematic, not statistical, error source);
  * **reconstruction base values** — face states are assembled as
    ``f32 cell value +- f32(bf16 half-slope)``: the bf16 quantization is
    O(2^-9) *of the local slope* (a correction term), never of the cell
    value — truncation-class by construction, no anomaly offset needed.

``strips='bf16'`` — the inter-stage boundary-strip/ghost tensors (and
hence the wire payload wherever strips ride a collective) are stored
bfloat16; the kernels widen them to f32 on the in-VMEM ghost fill.
Panel-seam conservation survives 16-bit strips unchanged: the router
computes ONE symmetrized edge-normal value per physical edge and
distributes the *identical* (rounded-once) row to both faces, so
cross-seam flux equality — hence exact mass conservation — is preserved
at any strips dtype (see ``sym_edge_normals``).

The policy is intentionally NOT a blanket cast: vorticity and Bernoulli
gradients difference nearly-equal large values (catastrophic in bf16's
8-bit mantissa), and h itself is ~5e3 m where a direct bf16 cast is a
~16 m quantum.  Measured budgets for what IS cast live in
tests/test_precision.py and DESIGN.md "Precision ladder".

``precision=None`` everywhere means OFF, and off is *bitwise* the
historical f32 path (tested) — the policy threads through the existing
stage factories rather than forking new ones, so it composes with
temporal blocking, ensembles, donation, and the carry encodings.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

__all__ = ["StagePrecision", "resolve_stage_precision", "encode_strips",
           "strip_dtype_bytes", "mixed16_encoding"]

_COMPUTE = ("f32", "bf16")
_STRIPS = ("f32", "bf16")


@dataclasses.dataclass(frozen=True)
class StagePrecision:
    """Resolved per-stage dtype policy (see module docstring).

    ``compute``: 'f32' | 'bf16' — flux/reconstruction/router arithmetic.
    ``strips``:  'f32' | 'bf16' — inter-stage strip/ghost storage (the
    exchange payload on sharded tiers).
    """

    compute: str = "f32"
    strips: str = "f32"

    def __post_init__(self):
        if self.compute not in _COMPUTE:
            raise ValueError(
                f"StagePrecision.compute must be one of {_COMPUTE}, "
                f"got {self.compute!r}")
        if self.strips not in _STRIPS:
            raise ValueError(
                f"StagePrecision.strips must be one of {_STRIPS}, "
                f"got {self.strips!r}")

    @property
    def compute_dtype(self):
        return jnp.bfloat16 if self.compute == "bf16" else jnp.float32

    @property
    def strips_dtype(self):
        return jnp.bfloat16 if self.strips == "bf16" else jnp.float32

    @property
    def is_off(self) -> bool:
        return self.compute == "f32" and self.strips == "f32"


def resolve_stage_precision(precision) -> StagePrecision | None:
    """Normalize a user-facing precision spec to a policy (or None = off).

    Accepts ``None`` / ``'f32'`` (off), ``'bf16'`` (bf16 compute + bf16
    strips — the production ladder rung), a :class:`StagePrecision`, or
    a ``{'stage'|'compute': ..., 'strips': ...}`` mapping (the config
    block's shape; ``strips='auto'`` follows the compute policy).
    Returns ``None`` when the resolved policy is entirely f32, so every
    factory's ``precision is None`` fast path — the bitwise historical
    trace — is taken whenever the policy is off.
    """
    if precision is None:
        return None
    if isinstance(precision, StagePrecision):
        return None if precision.is_off else precision
    if isinstance(precision, str):
        name = precision.lower()
        if name in ("f32", "off", "none", ""):
            return None
        if name == "bf16":
            return StagePrecision(compute="bf16", strips="bf16")
        raise ValueError(
            f"unknown precision policy {precision!r}; valid: 'f32', "
            "'bf16', a StagePrecision, or a {'stage','strips'} mapping")
    if isinstance(precision, dict):
        unknown = set(precision) - {"stage", "compute", "strips"}
        if unknown:
            # A misspelled key must not silently resolve to the f32
            # default — an experiment would then report f32 rates and
            # budgets labeled as its intended policy.
            raise ValueError(
                f"unknown precision keys {sorted(unknown)}; valid: "
                "'stage' (or 'compute') and 'strips'")
        compute = precision.get("stage", precision.get("compute", "f32"))
        strips = precision.get("strips", "auto")
        if strips == "auto":
            strips = compute
        return resolve_stage_precision(
            StagePrecision(compute=compute, strips=strips))
    raise TypeError(
        f"precision must be None/str/dict/StagePrecision, "
        f"got {type(precision).__name__}")


def encode_strips(y, precision):
    """Narrow a compact carry's strip tensors to the policy's strips
    dtype (identity when the policy keeps f32 strips, or for carries
    without strips).

    The stage kernels EMIT strips in the strips dtype, so a jitted
    integration loop (``fori_loop``/``scan``, whose carry type must be
    stable across iterations) needs the INITIAL carry's strips in that
    dtype too — ``compact_state``/``ensemble_compact_state`` build them
    f32.  h/u are untouched: the carry encodings
    (:meth:`CovariantShallowWater.encode_carry`) are the separate,
    orthogonal storage hook.
    """
    pol = resolve_stage_precision(precision)
    if pol is None or pol.strips != "bf16":
        return y
    sdt = pol.strips_dtype
    return {k: (v.astype(sdt) if k in ("strips_sn", "strips_we") else v)
            for k, v in y.items()}


def strip_dtype_bytes(precision) -> int:
    """Bytes per strip element under a policy (4 = f32, 2 = bf16) — the
    comm_probe/bench wire-byte accounting hook."""
    pol = resolve_stage_precision(precision)
    return 2 if (pol is not None and pol.strips == "bf16") else 4


def mixed16_encoding(h):
    """The bench-gated mixed16 carry triple for an initial h field:
    ``(carry_dtype, h_offset, h_scale)`` = h int16 fixed-point in
    1/16 m quanta about the field's mid-range + u bf16 (round 5,
    DESIGN.md carry ladder; mass held at the default 1e-3 band).  ONE
    definition shared by bench_tc5's gated variant,
    ``bench_precision_report`` and ``Simulation._resolve_precision`` —
    a retune here is a retune of what the bench gates certify."""
    off = float(0.5 * (float(jnp.min(h)) + float(jnp.max(h))))
    return (jnp.int16, jnp.bfloat16), off, 0.0625
