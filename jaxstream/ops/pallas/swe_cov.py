"""Fused TPU kernels for the covariant-component SWE formulation.

The covariant twin of :mod:`swe_rhs`/:mod:`swe_step`: one kernel per face
computes the complete vector-invariant RHS from the prognostic
``(h, u_a, u_b)`` — three (M, M) fields instead of the Cartesian path's
four, and the metric work collapses to the closed-form scalar fields of
:func:`jaxstream.ops.pallas.swe_rhs._fast_frame` (no 3-vector bases, dot
or cross products at all; the only frame data left is the three z-
components needed for the Coriolis parameter).

Panel-seam conservation: the two panels sharing an edge raise the index
through different covariant components/metrics, so their edge-face normal
velocities differ at truncation level (see
:func:`jaxstream.ops.fv.covariant_face_normal_velocity`).  The kernels
therefore take per-face *symmetrized edge-normal strips* — computed once
per physical edge outside the kernel (:func:`sym_edge_normals`) and
written over the boundary face values with iota-mask selects — so both
panels use bitwise-identical edge velocities and mass is conserved to
roundoff, matching the jnp oracle's ``symmetrize=True`` arithmetic
exactly.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...utils.jax_compat import named_scope, tpu_compiler_params

from ...geometry.connectivity import (
    EDGE_E,
    EDGE_N,
    EDGE_S,
    EDGE_W,
    build_connectivity,
    edge_pairs,
)
from ...geometry.cubed_sphere import FACE_AXES
from .precision import StagePrecision, resolve_stage_precision
from .swe_rhs import _fast_frame, coord_rows, pick_recon

__all__ = [
    "sym_edge_normals",
    "rhs_core_cov",
    "pick_recon_precision",
    "make_cov_rhs_pallas",
    "make_cov_rhs_interior_local",
    "make_cov_rhs_band_local",
    "make_cov_strip_router",
    "make_cov_strip_router_linear",
    "make_cov_strip_router_split",
    "pack_strips_cov",
    "pack_strips_cov_split",
    "make_cov_stage_inkernel",
    "make_fused_ssprk3_cov_inkernel",
    "make_cov_stage_compact",
    "make_fused_ssprk3_cov_compact",
    "make_fused_ssprk3_cov_multistep",
    "lap_core",
    "make_cov_stage_nu4",
    "make_fused_ssprk3_cov_nu4",
    "make_cov_nu4_filter",
    "make_fused_ssprk3_cov_split_nu4",
    "make_fused_ssprk3_cov_refused_nu4",
]


def pick_recon_precision(scheme: str, halo: int, n: int, limiter: str,
                         precision: StagePrecision | None = None):
    """Reconstruction for the stage kernels under a precision policy.

    Policy off: plain :func:`pick_recon` — the bitwise historical path.
    ``compute='bf16'`` + PLR: cell differences are formed in f32, the
    limiter algebra (the slope-candidate min/max chain — most of the
    reconstruction's VPU ops, and on TPU a 2x-wide lane mix in bf16)
    runs in bfloat16, and the face state is assembled as ``f32 cell
    value +- f32(bf16 half-slope)``.  Quantization lands on the *slope*
    — the O(dx) correction term — never on the cell value, so the
    face-state error is O(2^-9) of the local gradient: truncation-class
    with no anomaly offset (a direct bf16 cast of h ~ 5e3 m would be a
    ~16 m quantum; this form is ~4e-2 m per m/cell of slope).  Measured
    budgets: tests/test_precision.py.

    PPM + a bf16 compute policy is REJECTED, not half-run: the policy's
    op split (and the roofline's ``bf16_flop_fraction``) is defined on
    the PLR limiter algebra — silently running f32 reconstruction under
    a 'bf16' label would publish wrong mixed-roof accounting.
    """
    if precision is None or precision.compute != "bf16":
        return pick_recon(scheme, halo, n, limiter)
    if scheme == "ppm":
        raise ValueError(
            "the bf16 stage policy is defined for the PLR "
            "reconstruction (its op split and mixed-roof accounting "
            "assume the limiter algebra); PPM has no bf16 form — drop "
            "the precision policy or use scheme='plr'")
    import functools

    from ...ops.reconstruct import plr_face_states

    # ONE definition of PLR (ops/reconstruct.py) — the policy only
    # selects the slope dtype, so limiter/stencil fixes propagate to
    # both paths.
    return functools.partial(plr_face_states, h=halo, n=n,
                             limiter=limiter, slope_dtype=jnp.bfloat16)

_OUT_SIGN = {EDGE_S: -1.0, EDGE_W: -1.0, EDGE_N: 1.0, EDGE_E: 1.0}

# Slot order of the vectorized routers' per-face edge tables.
_EORDER = (EDGE_S, EDGE_N, EDGE_W, EDGE_E)
_SLOT = {e: s for s, e in enumerate(_EORDER)}


def _pair_sym_tables(grid):
    """Shared static tables of the routers' edge-normal symmetrization.

    Returns ``(M0, M1, link_rows, back_rows, rev, sga, sgb, sym_src)``:
    the (1, 4, n) edge-face inverse-metric rows per slot (face-independent
    on the equiangular grid; (iab, ibb) for S/N, (iaa, iab) for W/E —
    covariant_face_normal_velocity's pairs), the 12 physical edges' row
    selections into the (24, n) local-normal table, reversal/sign
    columns, and the scatter order back to (face*4 + slot) rows.
    """
    import numpy as np

    n, halo = grid.n, grid.halo
    i0, i1 = halo, halo + n
    adj = build_connectivity()
    met = {
        EDGE_W: (grid.ginv_aa_xf[0, i0:i1, i0], grid.ginv_ab_xf[0, i0:i1, i0]),
        EDGE_E: (grid.ginv_aa_xf[0, i0:i1, i1], grid.ginv_ab_xf[0, i0:i1, i1]),
        EDGE_S: (grid.ginv_ab_yf[0, i0, i0:i1], grid.ginv_bb_yf[0, i0, i0:i1]),
        EDGE_N: (grid.ginv_ab_yf[0, i1, i0:i1], grid.ginv_bb_yf[0, i1, i0:i1]),
    }
    M0 = jnp.stack([jnp.asarray(met[e][0]) for e in _EORDER])[None]
    M1 = jnp.stack([jnp.asarray(met[e][1]) for e in _EORDER])[None]

    links = [lk for lk, _ in edge_pairs(adj)]
    backs = [bk for _, bk in edge_pairs(adj)]
    link_rows = jnp.asarray([lk.face * 4 + _SLOT[lk.edge] for lk in links])
    back_rows = jnp.asarray([bk.face * 4 + _SLOT[bk.edge] for bk in backs])
    rev = jnp.asarray([[lk.reversed_] for lk in links])
    sga = jnp.asarray([[_OUT_SIGN[lk.edge]] for lk in links], jnp.float32)
    sgb = jnp.asarray([[_OUT_SIGN[bk.edge]] for bk in backs], jnp.float32)
    sym_src = np.empty(24, np.int64)
    for i, (lk, bk) in enumerate(zip(links, backs)):
        sym_src[lk.face * 4 + _SLOT[lk.edge]] = i
        sym_src[bk.face * 4 + _SLOT[bk.edge]] = 12 + i
    return (M0, M1, link_rows, back_rows, rev, sga, sgb,
            jnp.asarray(sym_src))


def _pair_symmetrize(I_u, gadj_a, gadj_b, tables):
    """Vectorized :func:`_symmetrized_strips` algebra on (6, 4, n) rows.

    ``I_u``: (2, 6, 4, n) interior boundary-adjacent covariant rows;
    ``gadj_*``: (6, 4, n) edge-adjacent ghost rows (rotated).  Returns the
    per-face sym strips as (6, 4, n) in slot order — operand order matches
    the loop implementation exactly (bitwise, tested).
    """
    M0, M1, link_rows, back_rows, rev, sga, sgb, sym_src = tables
    ubar0 = 0.5 * (I_u[0] + gadj_a)
    ubar1 = 0.5 * (I_u[1] + gadj_b)
    L = (M0 * ubar0 + M1 * ubar1).reshape(24, -1)
    la = jnp.take(L, link_rows, axis=0)
    lb = jnp.take(L, back_rows, axis=0)
    lb = jnp.where(rev, jnp.flip(lb, -1), lb)
    avg = 0.5 * (sga * la - sgb * lb)
    na = sga * avg
    nb = sgb * (-avg)
    nb = jnp.where(rev, jnp.flip(nb, -1), nb)
    return jnp.take(jnp.concatenate([na, nb], axis=0), sym_src,
                    axis=0).reshape(6, 4, -1)


def _local_edge_normal(grid, u_ext, face: int, edge: int):
    """This panel's own normal velocity at one edge's boundary faces.

    Returns the stored +alpha (W/E) or +beta (S/N) face value as a
    canonical along-edge ``(n,)`` strip — the same arithmetic (same
    operand order) as :func:`jaxstream.ops.fv.covariant_face_normal_velocity`
    restricted to that edge, so replacing the kernel's values with the
    paired averages reproduces the oracle bitwise.
    """
    h, n = grid.halo, grid.n
    i0, i1 = h, h + n
    if edge in (EDGE_W, EDGE_E):
        fi = i0 if edge == EDGE_W else i1
        ub_a = 0.5 * (u_ext[0, face, i0:i1, fi - 1] + u_ext[0, face, i0:i1, fi])
        ub_b = 0.5 * (u_ext[1, face, i0:i1, fi - 1] + u_ext[1, face, i0:i1, fi])
        iaa = grid.ginv_aa_xf[face, i0:i1, fi]
        iab = grid.ginv_ab_xf[face, i0:i1, fi]
        return iaa * ub_a + iab * ub_b
    fi = i0 if edge == EDGE_S else i1
    ub_a = 0.5 * (u_ext[0, face, fi - 1, i0:i1] + u_ext[0, face, fi, i0:i1])
    ub_b = 0.5 * (u_ext[1, face, fi - 1, i0:i1] + u_ext[1, face, fi, i0:i1])
    iab = grid.ginv_ab_yf[face, fi, i0:i1]
    ibb = grid.ginv_bb_yf[face, fi, i0:i1]
    return iab * ub_a + ibb * ub_b


def _symmetrized_strips(local_normal):
    """Average the two panels' edge normals and distribute to both sides.

    ``local_normal(face, edge) -> (n,)`` is each panel's own stored
    +alpha/+beta edge-face value in canonical along-edge order.  Applies
    the ``_symmetrize_edge_fluxes`` outward-sign/reversal algebra once per
    physical edge, so both faces receive bitwise-identical values; the
    single implementation keeps the non-fused RHS path and the fused
    stepper's router seam-consistent by construction.  Returns
    ``(sym_sn (6, 2, n), sym_we (6, n, 2))`` — W/E strips stored with the
    pair axis last so kernels can slice lane-cheap (n, 1) columns.
    """
    sn = [[None, None] for _ in range(6)]
    we = [[None, None] for _ in range(6)]

    def put(face, edge, strip):
        if edge == EDGE_S:
            sn[face][0] = strip
        elif edge == EDGE_N:
            sn[face][1] = strip
        elif edge == EDGE_W:
            we[face][0] = strip
        else:
            we[face][1] = strip

    for link, back in edge_pairs(build_connectivity()):
        s_a = local_normal(link.face, link.edge)
        s_b = local_normal(back.face, back.edge)
        if link.reversed_:
            s_b = jnp.flip(s_b, axis=-1)
        out_a = _OUT_SIGN[link.edge] * s_a
        out_b = _OUT_SIGN[back.edge] * s_b
        avg = 0.5 * (out_a - out_b)
        new_a = _OUT_SIGN[link.edge] * avg
        new_b = _OUT_SIGN[back.edge] * (-avg)
        if link.reversed_:
            new_b = jnp.flip(new_b, axis=-1)
        put(link.face, link.edge, new_a)
        put(back.face, back.edge, new_b)

    sym_sn = jnp.stack([jnp.stack(rows) for rows in sn])        # (6, 2, n)
    sym_we = jnp.stack([jnp.stack(cols, axis=-1) for cols in we])  # (6, n, 2)
    return sym_sn, sym_we


def sym_edge_normals(grid, u_ext):
    """Symmetrized panel-edge normal velocities for the covariant kernels.

    ``u_ext``: (2, 6, M, M) covariant components with ghosts filled.
    Returns ``(sym_sn, sym_we)`` per :func:`_symmetrized_strips`, with
    each panel's local values from the grid's stored face metric
    (bitwise-equal to the jnp oracle's symmetrize path).
    """
    return _symmetrized_strips(
        lambda f, e: _local_edge_normal(grid, u_ext, f, e)
    )


def rhs_core_cov(fz, xr, xfr, yc, yfc, hf, ua, ub, bf, sym_sn, sym_we, *,
                 n, halo, d, radius, gravity, omega, recon,
                 seam_scratch=None, sym_prescaled=False,
                 seam_edges=(True, True, True, True), precision=None):
    """One face's covariant-SWE right-hand side as traceable kernel math.

    ``fz = (c0z, cxz, cyz)`` are the face frame's z-components (scalars,
    for the Coriolis parameter 2 Omega rhat_z); ``hf``/``bf`` (M, M),
    ``ua``/``ub`` (M, M) covariant components, ghosts filled.
    ``sym_sn`` (2, n) / ``sym_we`` (n, 2) are the symmetrized edge
    normals imposed on the panel-boundary faces (pass ``None`` for both
    to keep the local values — single-panel tests).  Returns
    ``(dh, dua, dub)`` interior (n, n) tendencies.

    Rectangular windows (the interior/boundary split of the overlapped
    exchange path): pass ``n=(ny, nx)`` with operand windows extended by
    ``halo`` on every side, and ``recon=(recon_y, recon_x)`` partials
    built for the matching extents.  ``seam_edges = (S, N, W, E)`` gates
    each seam imposition individually — a window whose edge is NOT a
    panel/block seam must leave that flux row/column at its local value
    (the full-face call imposes all four).  Every arithmetic operation
    on a given output cell is identical (same operand windows, same op
    order) to the square full-face call, so a tiling of rectangular
    calls reproduces the full kernel at the trace level; the compiled
    equality is ulp-level in general (execution-context fusion — see
    the interior/boundary split section comment).
    """
    ny, nx = (n, n) if isinstance(n, int) else n
    recon_y, recon_x = recon if isinstance(recon, tuple) else (recon, recon)
    eS, eN, eW, eE = seam_edges
    h0y, h1y = halo, halo + ny
    h0x, h1x = halo, halo + nx
    inv2d = jnp.float32(1.0 / (2.0 * d))
    g = jnp.float32(gravity)
    two_omega = jnp.float32(2.0 * omega)
    # Precision policy (see ops/pallas/precision.py): `lo` casts the
    # flux face-average VELOCITY operands to bf16 — the policy's "flux
    # arithmetic" half (the reconstruction half rides `recon`, built by
    # pick_recon_precision).  A bf16 value multiplied into the f32
    # metric promotes back to f32, so every accumulator (flux products,
    # divergence, gradients, RK combine) stays f32; with the policy off
    # `lo` is identity and the trace is bitwise the historical one.
    if precision is not None and precision.compute == "bf16":
        lo = lambda x: x.astype(jnp.bfloat16)
    else:
        lo = lambda x: x

    # ---- continuity ------------------------------------------------------
    # Flux-form velocities U = sqrtg u^perp directly via the folded metric
    # (fg_*: sqrtg g^ij is cheaper than either factor, see _fast_frame) —
    # the upwind flux then needs no separate sqrtg multiply.  Symmetrized
    # seam normals are imposed as sqrtg_edge * sym: both panels multiply
    # the identical sym strip by the identical edge sqrtg (the equiangular
    # sqrtg is even in the along-edge coordinate), so cross-seam flux
    # equality — hence exact mass conservation — is preserved.
    Fx = _fast_frame(xfr[:, h0x:h1x + 1], yc[h0y:h1y], radius)
    uba = 0.5 * (lo(ua[h0y:h1y, h0x - 1:h1x]) + lo(ua[h0y:h1y, h0x:h1x + 1]))
    ubb = 0.5 * (lo(ub[h0y:h1y, h0x - 1:h1x]) + lo(ub[h0y:h1y, h0x:h1x + 1]))
    ux = Fx["fg_aa"] * uba + Fx["fg_ab"] * ubb      # sqrtg u^a, (ny, nx+1)
    if sym_we is not None and (eW or eE):
        # Seam imposition: replace the two boundary flux-velocity
        # columns/rows with the symmetrized-edge values.  The in-kernel
        # edge-sqrtg evals are tiny (n, 1)-shaped op chains — expensive
        # per-op on the VPU — so the fused path pre-scales the sym rows
        # in the strip ROUTER (vectorized across faces, sym_prescaled)
        # and the kernel only merges.  Merge via VMEM scratch ref
        # slice-stores when provided; iota-select otherwise (concat
        # assembly was no cheaper, misaligned lane-dim concat and
        # value-level dynamic_update_slice are rejected by Mosaic).
        if sym_prescaled:
            uW, uE = sym_we[:, 0:1], sym_we[:, 1:2]
        else:
            sgW = (_fast_frame(xfr[:, h0x:h0x + 1], yc[h0y:h1y],
                               radius)["sqrtg"] if eW else None)
            sgE = (_fast_frame(xfr[:, h1x:h1x + 1], yc[h0y:h1y],
                               radius)["sqrtg"] if eE else None)
            uW = sgW * sym_we[:, 0:1] if eW else None
            uE = sgE * sym_we[:, 1:2] if eE else None
        if seam_scratch is not None:
            sx = seam_scratch[0]
            sx[:, :] = ux
            if eW:
                sx[:, 0:1] = uW
            if eE:
                sx[:, nx:nx + 1] = uE
            ux = sx[:, :]
        else:
            colx = jax.lax.broadcasted_iota(jnp.int32, (ny, nx + 1), 1)
            if eW:
                ux = jnp.where(colx == 0, uW, ux)
            if eE:
                ux = jnp.where(colx == nx, uE, ux)
    qL, qR = recon_x(hf[h0y:h1y, :], -1)
    fx = jnp.maximum(ux, 0.0) * qL + jnp.minimum(ux, 0.0) * qR

    Fy = _fast_frame(xr[:, h0x:h1x], yfc[h0y:h1y + 1], radius)
    vba = 0.5 * (lo(ua[h0y - 1:h1y, h0x:h1x]) + lo(ua[h0y:h1y + 1, h0x:h1x]))
    vbb = 0.5 * (lo(ub[h0y - 1:h1y, h0x:h1x]) + lo(ub[h0y:h1y + 1, h0x:h1x]))
    uy = Fy["fg_ab"] * vba + Fy["fg_bb"] * vbb      # sqrtg u^b, (ny+1, nx)
    if sym_sn is not None and (eS or eN):
        if sym_prescaled:
            uS, uN = sym_sn[0:1, :], sym_sn[1:2, :]
        else:
            sgS = (_fast_frame(xr[:, h0x:h1x], yfc[h0y:h0y + 1],
                               radius)["sqrtg"] if eS else None)
            sgN = (_fast_frame(xr[:, h0x:h1x], yfc[h1y:h1y + 1],
                               radius)["sqrtg"] if eN else None)
            uS = sgS * sym_sn[0:1, :] if eS else None
            uN = sgN * sym_sn[1:2, :] if eN else None
        if seam_scratch is not None:
            sy = seam_scratch[1]
            sy[:, :] = uy
            if eS:
                sy[0:1, :] = uS
            if eN:
                sy[ny:ny + 1, :] = uN
            uy = sy[:, :]
        else:
            rowy = jax.lax.broadcasted_iota(jnp.int32, (ny + 1, nx), 0)
            if eS:
                uy = jnp.where(rowy == 0, uS, uy)
            if eN:
                uy = jnp.where(rowy == ny, uN, uy)
    qL, qR = recon_y(hf[:, h0x:h1x], -2)
    fy = jnp.maximum(uy, 0.0) * qL + jnp.minimum(uy, 0.0) * qR

    # ---- momentum (vector-invariant, covariant components) ---------------
    # The cell-center frame Fc is the interior slice of the band frame Fb:
    # every _fast_frame output is an elementwise function of the same
    # coordinate-row values, so slicing is bitwise-identical to
    # recomputing — and saves a full (n, n) metric evaluation per stage.
    b0y, b1y = h0y - 1, h1y + 1
    b0x, b1x = h0x - 1, h1x + 1
    Fb = _fast_frame(xr[:, b0x:b1x], yc[b0y:b1y], radius)
    Fc = {k: v[-1:, 1:-1] if v.shape[-2] == 1 else
             (v[1:-1, -1:] if v.shape[-1] == 1 else v[1:-1, 1:-1])
          for k, v in Fb.items()}
    inv_sg_d = Fc["inv_sqrtg"] * jnp.float32(1.0 / d)
    dh = -((fx[:, 1:] - fx[:, :-1]) + (fy[1:, :] - fy[:-1, :])) * inv_sg_d
    uab = ua[b0y:b1y, b0x:b1x]
    ubb_ = ub[b0y:b1y, b0x:b1x]
    uca = Fb["inv_aa"] * uab + Fb["inv_ab"] * ubb_        # u^alpha, band
    ucb = Fb["inv_ab"] * uab + Fb["inv_bb"] * ubb_        # u^beta, band
    ke = 0.5 * (uca * uab + ucb * ubb_)
    bern = g * (hf[b0y:b1y, b0x:b1x] + bf[b0y:b1y, b0x:b1x]) + ke
    dba = (bern[1:-1, 2:] - bern[1:-1, :-2]) * inv2d
    dbb = (bern[2:, 1:-1] - bern[:-2, 1:-1]) * inv2d

    dub_da = (ub[h0y:h1y, h0x + 1:h1x + 1]
              - ub[h0y:h1y, h0x - 1:h1x - 1]) * inv2d
    dua_db = (ua[h0y + 1:h1y + 1, h0x:h1x]
              - ua[h0y - 1:h1y - 1, h0x:h1x]) * inv2d

    # (zeta + f) sqrtg expanded: zeta sqrtg is just the covariant curl
    # (zeta = curl / sqrtg), so only the Coriolis part needs the metric —
    # two fewer full-field multiplies and no inv_sqrtg/sqrtg pair.
    # f = 2 Omega rhat_z, rhat_z = (c0z + X cxz + Y cyz)/rho.
    rz = (fz[0] + Fc["x"] * fz[1] + Fc["y"] * fz[2]) * Fc["inv_rho"]
    absv = (dub_da - dua_db) + (two_omega * rz) * Fc["sqrtg"]

    dua = absv * ucb[1:-1, 1:-1] - dba
    dub = -absv * uca[1:-1, 1:-1] - dbb
    return dh, dua, dub


def make_cov_rhs_pallas(
    grid,
    gravity: float,
    omega: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    n_faces: int = 6,
    external_sym: bool = False,
):
    """Build ``rhs(h_ext, u_ext, b_ext) -> (dh, du)`` as one fused kernel.

    Drop-in for the stencil section of
    :meth:`jaxstream.models.shallow_water_cov.CovariantShallowWater.rhs`:
    extended inputs with ghosts filled, interior tendencies out
    (``du`` stacked (2, 6, n, n)).  The symmetrized edge normals are
    computed outside the kernel from the same ``u_ext`` (they read the
    grid's stored face metric, keeping them bitwise-equal to the oracle).

    ``n_faces=1`` + ``external_sym=True`` is the shard_map-local variant
    (one face per device): the returned function has signature
    ``rhs(fz, h_ext, u_ext, b_ext, sym_sn, sym_we)`` with the per-face
    frame z-components ``fz (1, 1, 3)`` and symmetrized edge normals
    supplied by the caller (the explicit ppermute exchange computes them).
    """
    n, halo = grid.n, grid.halo
    m = n + 2 * halo
    d = float(grid.dalpha)
    radius = float(grid.radius)
    recon = pick_recon(scheme, halo, n, limiter)
    x_row, xf_row, x_col, xf_col, _ = coord_rows(n, halo)
    import numpy as np

    # (6, 1, 3): Mosaic requires the block's last two dims to equal the
    # array's, so keep a unit middle axis rather than a (6, 3) table.
    frames_z = jnp.asarray(np.asarray(FACE_AXES)[:, None, :, 2], jnp.float32)

    def kernel(fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref, h_ref, u_ref,
               b_ref, ssn_ref, swe_ref, dh_ref, du_ref):
        fz = (fz_ref[0, 0, 0], fz_ref[0, 0, 1], fz_ref[0, 0, 2])
        dh, dua, dub = rhs_core_cov(
            fz, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
            h_ref[0], u_ref[0, 0], u_ref[1, 0], b_ref[0],
            ssn_ref[0], swe_ref[0], n=n, halo=halo, d=d, radius=radius,
            gravity=gravity, omega=omega, recon=recon,
        )
        dh_ref[0] = dh
        du_ref[0, 0] = dua
        du_ref[1, 0] = dub

    nf = n_faces
    grid_spec = pl.GridSpec(
        grid=(nf,),
        in_specs=[
            pl.BlockSpec((1, 1, 3), lambda f: (f, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m, m), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, 1, m, m), lambda f: (0, f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m, m), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, n), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, 2), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n, n), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, 1, n, n), lambda f: (0, f, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
    )

    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nf, n, n), jnp.float32),
            jax.ShapeDtypeStruct((2, nf, n, n), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    if external_sym:
        def rhs_ext(fz, h_ext, u_ext, b_ext, sym_sn, sym_we):
            return tuple(call(fz, x_row, xf_row, x_col, xf_col,
                              h_ext, u_ext, b_ext, sym_sn, sym_we))

        return rhs_ext

    def rhs(h_ext, u_ext, b_ext) -> Tuple[jax.Array, jax.Array]:
        sym_sn, sym_we = sym_edge_normals(grid, u_ext)
        dh, du = call(frames_z, x_row, xf_row, x_col, xf_col,
                      h_ext, u_ext, b_ext, sym_sn, sym_we)
        return dh, du

    return rhs


def make_cov_rhs_pallas_local(
    n: int,
    halo: int,
    dalpha: float,
    radius: float,
    gravity: float,
    omega: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
):
    """Covariant RHS for ONE local block with runtime coordinates.

    The sub-panel (block-mesh) twin of ``make_cov_rhs_pallas(n_faces=1,
    external_sym=True)``: here the gnomonic coordinate rows/columns are
    *runtime operands* too, because each device's block covers a
    different patch of its face.  Signature::

        rhs(fz, xr, xfr, yc, yfc, h_ext, u_ext, b_ext, sym_sn, sym_we)
            -> (dh (1, n, n), du (2, 1, n, n))

    with ``xr``/``xfr`` (1, m) rows, ``yc``/``yfc`` (m, 1) columns of
    the block's extended tan-coordinates, ``fz`` (1, 1, 3) the face
    frame z-components, and sym strips imposed at all four block edges
    (panel seams get the pair-symmetrized values; intra-panel seams the
    plain shared face normal — both sides bitwise-equal either way, so
    cross-device flux telescoping is exact).
    """
    m = n + 2 * halo
    d = float(dalpha)
    recon = pick_recon(scheme, halo, n, limiter)

    def kernel(fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref, h_ref, u_ref,
               b_ref, ssn_ref, swe_ref, dh_ref, du_ref):
        fz = (fz_ref[0, 0, 0], fz_ref[0, 0, 1], fz_ref[0, 0, 2])
        dh, dua, dub = rhs_core_cov(
            fz, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
            h_ref[0], u_ref[0, 0], u_ref[1, 0], b_ref[0],
            ssn_ref[0], swe_ref[0], n=n, halo=halo, d=d, radius=radius,
            gravity=gravity, omega=omega, recon=recon,
        )
        dh_ref[0] = dh
        du_ref[0, 0] = dua
        du_ref[1, 0] = dub

    grid_spec = pl.GridSpec(
        grid=(1,),
        in_specs=[
            pl.BlockSpec((1, 1, 3), lambda f: (f, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m, m), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, 1, m, m), lambda f: (0, f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m, m), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, n), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, 2), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n, n), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, 1, n, n), lambda f: (0, f, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
    )

    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((1, n, n), jnp.float32),
            jax.ShapeDtypeStruct((2, 1, n, n), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    def rhs(fz, xr, xfr, yc, yfc, h_ext, u_ext, b_ext, sym_sn, sym_we):
        return tuple(call(fz, xr, xfr, yc, yfc,
                          h_ext, u_ext, b_ext, sym_sn, sym_we))

    return rhs


# ---------------------------------------------------------------------------
# Interior/boundary split of the covariant RHS — the overlapped-exchange
# building blocks (parallelization.overlap_exchange).
#
# A halo of depth h is exactly the stencil radius of one RHS evaluation,
# so the tendency of any interior cell at distance >= h from the panel
# (or block) boundary reads NO ghost value: that "interior of the
# interior" — an (n-2h)^2 core out of n^2 cells, 97.9% of the face at
# C384 — is computable before any exchange completes (Putman & Lin 2007
# make the same observation for ghost-cell fills).  The sharded steppers
# therefore issue their ppermute stages FIRST, run the interior-only
# kernel below while XLA's async collectives are in flight, and finish
# with the boundary-band pass on the received strips.
#
# The band pass is four rectangular rhs_core_cov windows (S/N full-width
# rows, W/E the remaining columns: an exact disjoint tiling of the ring)
# kept as traced jnp rather than a fourth Pallas variant: the band is
# O(h*n) work — ~2% of the face at C384 — and leaving it to XLA lets the
# scheduler start it the moment the last receive lands, with no
# custom-call boundary in between.  Both passes slice the SAME operand
# windows in the SAME op order as the full-face kernel; at the default
# halo=2 the interior+band tiling reproduces the serialized path
# bitwise under one jit (tested), and the general contract is
# ulp-level — XLA may fuse the differently-shaped band subgraphs with
# different FMA/reassociation choices (measured: single-ulp band drift
# at halo=3) — the same budget the multi-step overlap parities carry.
# ---------------------------------------------------------------------------


def make_cov_rhs_interior_local(
    n: int,
    halo: int,
    dalpha: float,
    radius: float,
    gravity: float,
    omega: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
):
    """Interior-pass covariant RHS for ONE local block, no ghosts read.

    Signature::

        rhs(fz, xr, xfr, yc, yfc, h_int, u_int, b_int)
            -> (dh (1, ni, ni), du (2, 1, ni, ni)),  ni = n - 2*halo

    ``h_int`` (1, n, n) / ``u_int`` (2, 1, n, n) are the block's plain
    interior fields (exactly the sharded state — no embed, no exchange);
    ``b_int`` the (1, n, n) interior window of the orography;
    ``xr``/``xfr`` (1, n), ``yc``/``yfc`` (n, 1) the INTERIOR coordinate
    windows (extended coords sliced ``[halo : halo+n]``).  The interior
    field plays the role of the extended array for the core window: its
    outer ``halo`` ring is the stencil halo of the ``ni x ni`` output.
    No seam strips exist this deep inside a block, so the seam machinery
    is off entirely.
    """
    ni = n - 2 * halo
    if ni <= 0:
        raise ValueError(
            f"interior split needs n > 2*halo (got n={n}, halo={halo}): "
            "with no ghost-free core the serialized exchange is the "
            "whole kernel")
    d = float(dalpha)
    recon = pick_recon(scheme, halo, ni, limiter)

    def kernel(fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref, h_ref, u_ref,
               b_ref, dh_ref, du_ref):
        fz = (fz_ref[0, 0, 0], fz_ref[0, 0, 1], fz_ref[0, 0, 2])
        dh, dua, dub = rhs_core_cov(
            fz, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
            h_ref[0], u_ref[0, 0], u_ref[1, 0], b_ref[0],
            None, None, n=ni, halo=halo, d=d, radius=radius,
            gravity=gravity, omega=omega, recon=recon,
        )
        dh_ref[0] = dh
        du_ref[0, 0] = dua
        du_ref[1, 0] = dub

    grid_spec = pl.GridSpec(
        grid=(1,),
        in_specs=[
            pl.BlockSpec((1, 1, 3), lambda f: (f, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, n), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((n, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((n, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, n), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, 1, n, n), lambda f: (0, f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, n), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, ni, ni), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, 1, ni, ni), lambda f: (0, f, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
    )

    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((1, ni, ni), jnp.float32),
            jax.ShapeDtypeStruct((2, 1, ni, ni), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    def rhs(fz, xr, xfr, yc, yfc, h_int, u_int, b_int):
        return tuple(call(fz, xr, xfr, yc, yfc, h_int, u_int, b_int))

    return rhs


def make_cov_rhs_band_local(
    n: int,
    halo: int,
    dalpha: float,
    radius: float,
    gravity: float,
    omega: float,
    scheme: str = "plr",
    limiter: str = "mc",
):
    """Boundary-band covariant RHS + stitch for ONE local block.

    Signature::

        band(fz, xr, xfr, yc, yfc, h_ext, u_ext, b_ext, sym_sn, sym_we,
             dh_core, du_core) -> (dh (1, n, n), du (2, 1, n, n))

    Operands as :func:`make_cov_rhs_pallas_local` (extended block with
    ghosts filled by the completed exchange, sym strips for all four
    edges) plus the interior pass's core tendencies, which are stitched
    into the full interior output.  Four rectangular windows tile the
    depth-``halo`` ring exactly: S/N rows over the full width (they own
    the corners), W/E the remaining ``n - 2h`` rows.  Each window's
    seam flags impose exactly the strip rows the full-face kernel
    imposes there — sym values at a window edge that is NOT the block
    edge are never touched, and every imposition site is covered by
    exactly one window.  Traced jnp by design (see the section comment).
    """
    h = halo
    ni = n - 2 * h
    if ni <= 0:
        raise ValueError(
            f"band split needs n > 2*halo (got n={n}, halo={halo})")
    m = n + 2 * h
    d = float(dalpha)
    recon_n = pick_recon(scheme, h, n, limiter)
    recon_h = pick_recon(scheme, h, h, limiter)
    recon_i = pick_recon(scheme, h, ni, limiter)
    kw = dict(halo=h, d=d, radius=radius, gravity=gravity, omega=omega)

    def band(fz, xr, xfr, yc, yfc, h_ext, u_ext, b_ext, sym_sn, sym_we,
             dh_core, du_core):
        fz3 = (fz[0, 0, 0], fz[0, 0, 1], fz[0, 0, 2])
        hf, ua, ub, bf = h_ext[0], u_ext[0, 0], u_ext[1, 0], b_ext[0]
        ssn, swe = sym_sn[0], sym_we[0]            # (2, n) / (n, 2)

        def win(r0, r1, c0, c1):
            sl = (slice(r0, r1), slice(c0, c1))
            return (xr[:, c0:c1], xfr[:, c0:c1], yc[r0:r1], yfc[r0:r1],
                    hf[sl], ua[sl], ub[sl], bf[sl])

        # S/N bands: (h, n) outputs over the full width.
        dS = rhs_core_cov(fz3, *win(0, 3 * h, 0, m), ssn, swe[0:h],
                          n=(h, n), recon=(recon_h, recon_n),
                          seam_edges=(True, False, True, True), **kw)
        dN = rhs_core_cov(fz3, *win(m - 3 * h, m, 0, m), ssn,
                          swe[n - h:n], n=(h, n),
                          recon=(recon_h, recon_n),
                          seam_edges=(False, True, True, True), **kw)
        # W/E bands: (ni, h) outputs on the remaining rows.
        dW = rhs_core_cov(fz3, *win(h, n + h, 0, 3 * h), None,
                          swe[h:n - h], n=(ni, h),
                          recon=(recon_i, recon_h),
                          seam_edges=(False, False, True, False), **kw)
        dE = rhs_core_cov(fz3, *win(h, n + h, m - 3 * h, m), None,
                          swe[h:n - h], n=(ni, h),
                          recon=(recon_i, recon_h),
                          seam_edges=(False, False, False, True), **kw)

        def stitch(i, core):
            mid = jnp.concatenate([dW[i], core, dE[i]], axis=-1)
            return jnp.concatenate([dS[i], mid, dN[i]], axis=-2)

        dh = stitch(0, dh_core[0])[None]
        du = jnp.stack([stitch(1, du_core[0, 0])[None],
                        stitch(2, du_core[1, 0])[None]])
        return dh, du

    return band


# ---------------------------------------------------------------------------
# Fused SSPRK3 with in-kernel exchange — the covariant TPU fast path.
#
# Mirrors jaxstream.ops.pallas.swe_step's strip-carry design with two
# covariant-specific twists: (1) velocity strips carry raw covariant
# components in the SOURCE panel's basis; the inter-stage router applies
# precomputed per-ghost-slot 2x2 rotations (the strip-sized form of the
# vector_halo exchange) while routing; (2) the router also produces the
# symmetrized panel-edge normal-velocity strips from the same carry, so
# each stage kernel's edge fluxes agree bitwise across seams (exact mass
# conservation without any cross-face traffic beyond the strips).
# ---------------------------------------------------------------------------


# Packed strip layout: ONE (6, 12*halo, n) tensor holds every boundary
# strip of the 3-field state — for each field fi in (h, u_a, u_b), base =
# fi*4*halo, rows [base, base+halo) = S block, [+halo, +2halo) = N block,
# [+2halo, +3halo) = W column block transposed depth-major, [+3halo,
# +4halo) = E ditto.  Rationale: (a) lane-major everywhere (an (n, 2)
# strip stores as 8-byte HBM rows — thousands of tiny DMAs per step);
# (b) ONE kernel operand instead of eight — each extra per-face block
# costs fixed DMA setup per grid step, and those fixed costs, not the
# RHS math, dominate the fused step (measured: an empty-body stage costs
# the same as the full RHS).  The routed-ghost input tensor uses the
# same 12*halo rows (placed layout) plus 4 trailing rows: the
# symmetrized edge normals for S, N and (transposed) W, E.


def _strip_base(fi: int, halo: int) -> int:
    return fi * 4 * halo


def pack_strips_cov(h_ext, u_ext, n: int, halo: int):
    """Boundary strips of extended (h, u) as one ``(6, 12*halo, n)``."""
    i0, i1 = halo, halo + n
    fields = (h_ext, u_ext[0], u_ext[1])
    rows = []
    for f in range(6):
        per_face = []
        for q in fields:
            per_face += [
                q[f, i0 : i0 + halo, i0:i1],
                q[f, i1 - halo : i1, i0:i1],
                jnp.swapaxes(q[f, i0:i1, i0 : i0 + halo], 0, 1),
                jnp.swapaxes(q[f, i0:i1, i1 - halo : i1], 0, 1),
            ]
        rows.append(jnp.concatenate(per_face, axis=0))
    return jnp.stack(rows)


def _rotation_tables(grid):
    """Per-ghost-slot covariant rotation tensors in *canonical* layout.

    For every ghost slot, ``T[i*2+j][f, e] = e_i^local(ghost cell) .
    a_j^src(source cell)`` — the same rotation as
    ``make_vector_halo_exchanger(components='covariant')`` — indexed by
    the receiving face's (face, edge) in canonical (depth, along) strip
    order with the pair's reversal already folded into the source side,
    so it multiplies the router's post-reversal canonical strips
    elementwise.  Returned packed as one float32 ``(4, 6, 4, halo, n)``
    tensor (i*2+j major): four separate well-tiled slices rather than a
    trailing ``(..., 2, 2)``, which would cost ~512x in (8, 128) tile
    padding.
    """
    import numpy as np

    from ...parallel.vector_halo import _strip_indices

    n, halo, m = grid.n, grid.halo, grid.m
    adj = build_connectivity()
    src_idx, dst_idx = _strip_indices(n, halo)
    e_b = np.stack([np.moveaxis(np.asarray(grid.e_a, np.float64), 0, -1),
                    np.moveaxis(np.asarray(grid.e_b, np.float64), 0, -1)])
    a_b = np.stack([np.moveaxis(np.asarray(grid.a_a, np.float64), 0, -1),
                    np.moveaxis(np.asarray(grid.a_b, np.float64), 0, -1)])
    ef = e_b.reshape(2, 6 * m * m, 3)
    af = a_b.reshape(2, 6 * m * m, 3)

    out = np.zeros((4, 6, 4, halo, n), np.float32)
    for f in range(6):
        for e in range(4):
            link = adj[f][e]
            src = src_idx[link.nbr_edge].reshape(halo, n)
            if link.reversed_:
                src = src[:, ::-1]
            src = src.reshape(-1) + link.nbr_face * m * m
            dst = dst_idx[e] + f * m * m
            for i in range(2):
                for j in range(2):
                    out[i * 2 + j, f, e] = np.einsum(
                        "...k,...k->...", ef[i][dst], af[j][src]
                    ).reshape(halo, n)
    return jnp.asarray(out)


def make_cov_strip_router(grid):
    """Build ``route(strips) -> ghosts`` over the packed strip layout.

    ``strips``: (6, 12*halo, n) per :func:`pack_strips_cov` — raw
    covariant components in each source panel's basis.  Returns the
    packed ghost tensor (6, 12*halo + 4, n): the same row layout holding
    the *placed* ghost blocks (u rotated into each destination panel's
    basis), followed by the four symmetrized edge-normal rows (S, N,
    then W, E transposed) — computed once per physical edge so both
    faces' flux inputs are bitwise-identical.
    """
    n, halo = grid.n, grid.halo
    i0, i1 = halo, halo + n
    h = halo
    Tc = _rotation_tables(grid)                     # (4, 6, 4, halo, n)
    adj = build_connectivity()

    # Edge-face metric rows (the equiangular metric is face-independent).
    met = {
        EDGE_W: (jnp.asarray(grid.ginv_aa_xf[0, i0:i1, i0]),
                 jnp.asarray(grid.ginv_ab_xf[0, i0:i1, i0])),
        EDGE_E: (jnp.asarray(grid.ginv_aa_xf[0, i0:i1, i1]),
                 jnp.asarray(grid.ginv_ab_xf[0, i0:i1, i1])),
        EDGE_S: (jnp.asarray(grid.ginv_ab_yf[0, i0, i0:i1]),
                 jnp.asarray(grid.ginv_bb_yf[0, i0, i0:i1])),
        EDGE_N: (jnp.asarray(grid.ginv_ab_yf[0, i1, i0:i1]),
                 jnp.asarray(grid.ginv_bb_yf[0, i1, i0:i1])),
    }
    # Within-field row offsets: S, N, W(T), E(T) blocks of `halo` rows.
    off = {EDGE_S: 0, EDGE_N: h, EDGE_W: 2 * h, EDGE_E: 3 * h}

    def raw_block(strips, fi, f, e):
        b = _strip_base(fi, h) + off[e]
        return strips[f, b : b + h, :]

    def canonical(strips, fi, f, e):
        """Face f / edge e's canonical ghost source (depth 0 nearest)."""
        link = adj[f][e]
        c = raw_block(strips, fi, link.nbr_face, link.nbr_edge)
        if link.nbr_edge in (EDGE_N, EDGE_E):
            c = jnp.flip(c, axis=-2)
        if link.reversed_:
            c = jnp.flip(c, axis=-1)
        return c

    def place(c, e):
        """Canonical ghost strip -> the slot layout the kernel stores."""
        return jnp.flip(c, axis=-2) if e in (EDGE_S, EDGE_W) else c

    def route(strips):
        ghost_rows = [[None] * 12 for _ in range(6)]
        g_adj = {}
        for f in range(6):
            for e in range(4):
                ch = place(canonical(strips, 0, f, e), e)
                cu = [canonical(strips, 1 + c_, f, e) for c_ in range(2)]
                ru = [Tc[0, f, e] * cu[0] + Tc[1, f, e] * cu[1],
                      Tc[2, f, e] * cu[0] + Tc[3, f, e] * cu[1]]
                slot = {EDGE_S: 0, EDGE_N: 1, EDGE_W: 2, EDGE_E: 3}[e]
                ghost_rows[f][slot] = ch
                ghost_rows[f][4 + slot] = place(ru[0], e)
                ghost_rows[f][8 + slot] = place(ru[1], e)
                # Edge-adjacent ghost row (placed: S/W blocks are depth-
                # flipped so the adjacent row is h-1; N/E it is 0).
                k = h - 1 if e in (EDGE_S, EDGE_W) else 0
                g_adj[(f, e)] = jnp.stack(
                    [place(ru[0], e)[k], place(ru[1], e)[k]])

        def local_normal(f, e):
            ui = jnp.stack([raw_block(strips, 1 + c_, f, e)[
                h - 1 if e in (EDGE_N, EDGE_E) else 0] for c_ in range(2)])
            ubar = 0.5 * (ui + g_adj[(f, e)])
            m0, m1 = met[e]
            return m0 * ubar[0] + m1 * ubar[1]

        sym_sn, sym_we = _symmetrized_strips(local_normal)

        out = []
        for f in range(6):
            out.append(jnp.concatenate(
                ghost_rows[f] + [sym_sn[f], jnp.swapaxes(sym_we[f], 0, 1)],
                axis=0))
        return jnp.stack(out)

    return route


def make_cov_strip_router_linear(grid):
    """Vectorized twin of :func:`make_cov_strip_router` — same output.

    The loop router emits hundreds of strip-sized XLA ops per call (per
    face/edge slices, flips, rotation multiplies, concats); at C384 that
    op-dispatch overhead is ~36 us x 3 routes/step, a quarter of the whole
    fused step.  But every router output row is a *linear* function of the
    packed strip rows, so the whole thing collapses to a handful of
    tensor-sized ops: one lane flip, one static row-gather (placement +
    orientation, both row permutations), two elementwise multiply-adds
    (the per-slot 2x2 covariant rotations), and a short vectorized
    pair-average for the symmetrized edge normals.  Arithmetic per element
    is kept in the loop router's operand order, so results are bitwise
    identical (tested) and seam conservation is preserved by construction
    (one sym value per physical edge, distributed by exact permutation).
    """
    import numpy as np

    n, halo = grid.n, grid.halo
    h = halo
    R = 12 * h
    adj = build_connectivity()
    off = {EDGE_S: 0, EDGE_N: h, EDGE_W: 2 * h, EDGE_E: 3 * h}

    # Rotation tables in *placed* layout, slot-ordered (4, 6, 4, halo, n):
    # place() depth-flips the S and W ghost blocks, and commutes with the
    # elementwise rotation, so flipping the canonical tables once here lets
    # the routed strips be multiplied in placed layout directly.
    Tc = np.asarray(_rotation_tables(grid))          # (4, 6, 4, h, n) by EDGE_*
    Tp = np.stack([Tc[:, :, e] for e in _EORDER], axis=2)
    for s, e in enumerate(_EORDER):
        if e in (EDGE_S, EDGE_W):
            Tp[:, :, s] = Tp[:, :, s, ::-1]
    Tp = jnp.asarray(Tp)

    # Row-gather index: output C row (fi, f, slot, k) <- packed strip row,
    # offset by 6*R when the pair is lane-reversed (gathers from the
    # flipped copy).  Folds place() (depth flip for S/W destinations) and
    # canonicalization (depth flip for N/E sources) into the permutation.
    idx = np.empty((3, 6, 4, h), np.int64)
    for f in range(6):
        for s, e in enumerate(_EORDER):
            link = adj[f][e]
            for k in range(h):
                kc = (h - 1 - k) if e in (EDGE_S, EDGE_W) else k
                kr = ((h - 1 - kc)
                      if link.nbr_edge in (EDGE_N, EDGE_E) else kc)
                row = link.nbr_face * R + off[link.nbr_edge] + kr
                for fi in range(3):
                    src = row + fi * 4 * h
                    idx[fi, f, s, k] = src + (6 * R if link.reversed_ else 0)
    # 48 more rows: each face/edge's own interior boundary-adjacent row of
    # (u_a, u_b) — raw canonical order, never reversed — for the edge
    # normals.  Nearest-to-edge depth is h-1 for N/E blocks, 0 for S/W.
    idx_int = np.empty((2, 6, 4), np.int64)
    for f in range(6):
        for s, e in enumerate(_EORDER):
            k = h - 1 if e in (EDGE_N, EDGE_E) else 0
            for c in range(2):
                idx_int[c, f, s] = f * R + (1 + c) * 4 * h + off[e] + k
    idx_all = jnp.asarray(np.concatenate([idx.reshape(-1),
                                          idx_int.reshape(-1)]))

    sym_tables = _pair_sym_tables(grid)

    # Adjacent ghost row of each placed (h, n) block: S/W blocks are
    # depth-flipped so the edge-adjacent row is h-1; N/E it is row 0.
    adj_k = [h - 1, 0, h - 1, 0]

    def route(strips):
        s_flat = strips.reshape(6 * R, n)
        s_all = jnp.concatenate([s_flat, jnp.flip(s_flat, -1)], axis=0)
        rows = jnp.take(s_all, idx_all, axis=0)
        C = rows[: 3 * 24 * h].reshape(3, 6, 4, h, n)
        I_u = rows[3 * 24 * h :].reshape(2, 6, 4, n)

        G_h = C[0]
        G_ua = Tp[0] * C[1] + Tp[1] * C[2]
        G_ub = Tp[2] * C[1] + Tp[3] * C[2]

        gadj_a = jnp.stack([G_ua[:, s, adj_k[s]] for s in range(4)], axis=1)
        gadj_b = jnp.stack([G_ub[:, s, adj_k[s]] for s in range(4)], axis=1)
        sym = _pair_symmetrize(I_u, gadj_a, gadj_b, sym_tables)

        return jnp.concatenate(
            [G_h.reshape(6, 4 * h, n), G_ua.reshape(6, 4 * h, n),
             G_ub.reshape(6, 4 * h, n), sym], axis=1)

    return route


def make_cov_stage_inkernel(
    n: int,
    halo: int,
    dalpha: float,
    radius: float,
    gravity: float,
    omega: float,
    dt: float,
    a: float,
    b: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    precision=None,
):
    """One fused covariant RK stage with the halo fill inside the kernel.

    ``a == 0``: ``stage(hc, uc, ghosts, b_ext)``; else
    ``stage(h0, u0, hc, uc, ghosts, b_ext)``.  ``ghosts`` is the packed
    (6, 12*halo + 4, n) tensor from :func:`make_cov_strip_router` (placed
    ghost blocks + symmetrized edge-normal rows).  Returns ``(h, u,
    strips)`` — the combined state plus its packed boundary strips
    (:func:`pack_strips_cov` layout).  Ghost corners stay stale (never
    read by the dimension-split stencils).

    ``precision``: compute half of the stage policy only (bf16
    flux/recon arithmetic); this legacy extended-carry layout keeps its
    packed strips f32 — 16-bit strip storage lives on the compact path.
    """
    import numpy as np

    m = n + 2 * halo
    i0, i1 = halo, halo + n
    d = float(dalpha)
    g_dt = b * dt
    precision = resolve_stage_precision(precision)
    if precision is not None and precision.strips == "bf16":
        raise ValueError(
            "the extended-carry (in-kernel exchange) stepper keeps f32 "
            "strips; 16-bit strip storage needs the compact carry "
            "(make_cov_stage_compact / make_fused_ssprk3_cov_compact)")
    recon = pick_recon_precision(scheme, halo, n, limiter, precision)
    x_row, xf_row, x_col, xf_col, _ = coord_rows(n, halo)
    frames_z = jnp.asarray(np.asarray(FACE_AXES)[:, None, :, 2], jnp.float32)
    with_y0 = a != 0.0
    h = halo
    R = 12 * halo

    def fill_ghosts(scratch, face_val, gi, fi):
        # Ghost blocks arrive packed and lane-major; W/E un-transpose is
        # a supported, cheap Mosaic op.
        base = _strip_base(fi, h)
        scratch[:] = face_val
        scratch[0:h, i0:i1] = gi[base : base + h]
        scratch[i1 : i1 + h, i0:i1] = gi[base + h : base + 2 * h]
        scratch[i0:i1, 0:h] = jnp.swapaxes(gi[base + 2 * h : base + 3 * h],
                                           0, 1)
        scratch[i0:i1, i1 : i1 + h] = jnp.swapaxes(
            gi[base + 3 * h : base + 4 * h], 0, 1)
        return scratch[:]

    def kernel(*refs):
        if with_y0:
            (fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
             h0_ref, u0_ref, hc_ref, uc_ref, gi_ref, b_ref,
             ho_ref, uo_ref, so_ref, *scratch) = refs
        else:
            (fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
             hc_ref, uc_ref, gi_ref, b_ref,
             ho_ref, uo_ref, so_ref, *scratch) = refs

        gi = gi_ref[0]
        hf = fill_ghosts(scratch[0], hc_ref[0], gi, 0)
        ua = fill_ghosts(scratch[1], uc_ref[0, 0], gi, 1)
        ub = fill_ghosts(scratch[2], uc_ref[1, 0], gi, 2)
        fz = (fz_ref[0, 0, 0], fz_ref[0, 0, 1], fz_ref[0, 0, 2])
        ssn = gi[R : R + 2]
        swe = jnp.swapaxes(gi[R + 2 : R + 4], 0, 1)

        dh, dua, dub = rhs_core_cov(
            fz, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
            hf, ua, ub, b_ref[0], ssn, swe,
            n=n, halo=halo, d=d, radius=radius,
            gravity=gravity, omega=omega, recon=recon,
            precision=precision,
        )

        fa = jnp.float32(a)
        fb = jnp.float32(b)
        fg = jnp.float32(g_dt)
        if with_y0:
            out_h = fa * h0_ref[0] + fb * hf
            out_u = [fa * u0_ref[i, 0] + fb * (ua if i == 0 else ub)
                     for i in range(2)]
        else:
            out_h = hf if b == 1.0 else fb * hf
            out_u = ([ua, ub] if b == 1.0
                     else [fb * ua, fb * ub])

        def emit(val, tend, out_ref, fi, lead=()):
            int_new = val[i0:i1, i0:i1] + fg * tend
            out_ref[lead + (0,)] = val
            out_ref[lead + (0, slice(i0, i1), slice(i0, i1))] = int_new
            base = _strip_base(fi, h)
            so_ref[0, base : base + h] = int_new[0:h, :]
            so_ref[0, base + h : base + 2 * h] = int_new[n - h : n, :]
            so_ref[0, base + 2 * h : base + 3 * h] = jnp.swapaxes(
                int_new[:, 0:h], 0, 1)
            so_ref[0, base + 3 * h : base + 4 * h] = jnp.swapaxes(
                int_new[:, n - h : n], 0, 1)

        emit(out_h, dh, ho_ref, 0)
        emit(out_u[0], dua, uo_ref, 1, lead=(0,))
        emit(out_u[1], dub, uo_ref, 2, lead=(1,))

    fz_spec = pl.BlockSpec((1, 1, 3), lambda f: (f, 0, 0),
                           memory_space=pltpu.SMEM)
    coord_specs = [
        pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
    ]
    h_blk = pl.BlockSpec((1, m, m), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM)
    u_blk = pl.BlockSpec((2, 1, m, m), lambda f: (0, f, 0, 0),
                         memory_space=pltpu.VMEM)
    gi_blk = pl.BlockSpec((1, R + 4, n), lambda f: (f, 0, 0),
                          memory_space=pltpu.VMEM)
    so_blk = pl.BlockSpec((1, R, n), lambda f: (f, 0, 0),
                          memory_space=pltpu.VMEM)

    in_specs = [fz_spec] + coord_specs
    if with_y0:
        in_specs += [h_blk, u_blk]
    in_specs += [h_blk, u_blk, gi_blk, h_blk]

    call = pl.pallas_call(
        kernel,
        grid_spec=pl.GridSpec(
            grid=(6,),
            in_specs=in_specs,
            out_specs=[h_blk, u_blk, so_blk],
            scratch_shapes=[pltpu.VMEM((m, m), jnp.float32)
                            for _ in range(3)],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((6, m, m), jnp.float32),
            jax.ShapeDtypeStruct((2, 6, m, m), jnp.float32),
            jax.ShapeDtypeStruct((6, R, n), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    if with_y0:
        def stage(h0, u0, hc, uc, ghosts, b_ext):
            return tuple(call(frames_z, x_row, xf_row, x_col, xf_col,
                              h0, u0, hc, uc, ghosts, b_ext))
    else:
        def stage(hc, uc, ghosts, b_ext):
            return tuple(call(frames_z, x_row, xf_row, x_col, xf_col,
                              hc, uc, ghosts, b_ext))
    return stage


def make_fused_ssprk3_cov_inkernel(
    grid,
    gravity: float,
    omega: float,
    dt: float,
    b_ext,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    precision=None,
):
    """``step(y, t) -> y`` over ``y = {h, u, strips}``.

    The covariant minimum-HBM-traffic step: three fused stage kernels plus
    three strip-routing shuffles (rotations + symmetrized edge normals on
    one packed strip tensor).  Initialise the carry with
    :meth:`CovariantShallowWater.extend_state(state, with_strips=True)`.
    ``precision``: compute-half policy only (see
    :func:`make_cov_stage_inkernel`).
    """
    from .swe_step import SSPRK3_COEFFS

    n, halo = grid.n, grid.halo
    route = make_cov_strip_router_linear(grid)
    mk = lambda a, b: make_cov_stage_inkernel(
        n, halo, float(grid.dalpha), float(grid.radius), gravity, omega,
        dt, a, b, scheme=scheme, limiter=limiter, interpret=interpret,
        precision=precision,
    )
    (a1, b1), (a2, b2), (a3, b3) = SSPRK3_COEFFS
    stage1 = mk(a1, b1)
    stage2 = mk(a2, b2)
    stage3 = mk(a3, b3)

    def step(y, t):
        del t
        h0, u0 = y["h"], y["u"]
        h1, u1, s1 = stage1(h0, u0, route(y["strips"]), b_ext)
        h2, u2, s2 = stage2(h0, u0, h1, u1, route(s1), b_ext)
        h3, u3, s3 = stage3(h0, u0, h2, u2, route(s2), b_ext)
        return {"h": h3, "u": u3, "strips": s3}

    return step


# ---------------------------------------------------------------------------
# Compact fused stage: interior-only state in HBM, split-orientation strips.
#
# Two layout changes over the in-kernel stepper above:
#
# 1. Interior-only carry.  Extended (M, M) fields, M = n + 2h, are (388,
#    388) blocks at C384: the lane dimension pads to 4x128 = 512, so every
#    field DMA moves ~32% dead lanes, and the kernel writes each face
#    twice (full block + interior overwrite).  The carry holds only the
#    (n, n) interiors — perfectly (8, 128)-tiled at production sizes —
#    and the stage kernel assembles the extended field in VMEM scratch
#    from the interior block and the routed ghosts.
#
# 2. Split-orientation strips.  The single packed strip tensor stores W/E
#    strips transposed, so the kernel pays ~13 thin (h, n)<->(n, h)
#    transposes per face per stage (measured ~7 us/stage at C384 — Mosaic
#    lowers them to sublane/lane shuffle chains).  Instead the S/N strips
#    and sym rows live in a row-major tensor and the W/E strips and sym
#    cols in a column-major tensor; the kernel reads/writes both natively
#    with zero transposes, and the router (already a handful of big XLA
#    ops) absorbs the orientation change in its one static row-gather
#    plus a single whole-tensor transpose each way.  The (6, n, 6h+2)
#    column tensor DMAs with lane padding (6h+2 -> 128), ~1 MB extra per
#    stage — noise next to the transpose savings.
#
# Arithmetic is unchanged: interiors are bitwise-identical to the
# extended-carry stepper (tested).  Ghost corners in scratch are
# uninitialized garbage; the dimension-split stencils never read them
# (the only corner touches are produced-then-sliced-away bern band
# cells, see rhs_core_cov).
# ---------------------------------------------------------------------------


def pack_strips_cov_split(h_int, u_int, n: int, halo: int):
    """Boundary strips of interior fields, split by orientation.

    Returns ``(strips_sn, strips_we)``: ``strips_sn`` is ``(6, 6h, n)``
    holding, per field in (h, u_a, u_b), the raw S rows then N rows;
    ``strips_we`` is ``(6, n, 6h)`` holding, per field, the raw W columns
    then E columns.  Raw = interior values in storage order (row 0 / col 0
    nearest the S/W edge; row h-1 / col h-1 nearest the N/E edge).
    """
    h = halo
    fields = (h_int, u_int[0], u_int[1])
    sn = jnp.concatenate(
        [blk for q in fields for blk in (q[:, 0:h, :], q[:, n - h : n, :])],
        axis=1)
    we = jnp.concatenate(
        [blk for q in fields for blk in (q[:, :, 0:h], q[:, :, n - h : n])],
        axis=2)
    return sn, we


def make_cov_strip_router_split(grid, prescale_sym: bool = False,
                                precision: StagePrecision | None = None):
    """Linear router over the split-orientation strip layout.

    ``route(strips_sn, strips_we) -> (ghosts_sn, ghosts_we)`` with
    ``ghosts_sn`` ``(6, 6h+2, n)`` (placed S/N ghost blocks per field +
    the two symmetrized S/N edge-normal rows) and ``ghosts_we``
    ``(6, n, 6h+2)`` (placed W/E ghost columns + sym W/E columns).  Same
    algebra as :func:`make_cov_strip_router_linear` (bitwise-identical
    ghost/sym values); only the storage orientation differs, so the stage
    kernel never transposes.

    ``prescale_sym``: multiply the sym rows by the static edge sqrtg
    here (vectorized over faces) so the stage kernel imposes them
    directly — the in-kernel (n, 1)-shaped sqrtg evals were measured at
    several us/stage of VPU time (``rhs_core_cov`` ``sym_prescaled``).

    ``precision`` (ops/pallas/precision.py): with ``compute='bf16'``
    AND ``strips='bf16'`` the 2x2 rotation multiply-adds — the router's
    arithmetic — run in bfloat16 (tables cast once at build; against
    f32 strip operands they would promote to f32 and only round the
    coefficients, so the cast is gated on both knobs); with
    ``strips='bf16'`` inputs are taken (and ghost/sym outputs emitted)
    in bfloat16, halving the strip HBM/wire traffic.  The symmetrized edge normals are computed
    in f32 from the (widened) strip rows and rounded ONCE per physical
    edge before distribution, so both faces receive the identical
    16-bit value — cross-seam flux equality, hence exact mass
    conservation, is dtype-independent.  Policy off = the bitwise
    historical route (identity casts).
    """
    import numpy as np

    n, halo = grid.n, grid.halo
    h = halo
    adj = build_connectivity()
    F = 2 * 6 * 6 * h          # sn section + weT section row count

    def src_row(fi: int, g: int, e: int, depth: int) -> int:
        """Flat source row of face g / edge e / field fi at canonical
        ``depth`` (0 = nearest the edge), in [sn ; weT] order."""
        kr = depth if e in (EDGE_S, EDGE_W) else h - 1 - depth
        sec = 0 if e in (EDGE_S, EDGE_N) else 6 * 6 * h
        pair = 0 if e in (EDGE_S, EDGE_W) else h
        return sec + g * 6 * h + fi * 2 * h + pair + kr

    # Ghost-block gather: output (fi, f, epos, k) in placed layout.  The
    # placed depth flip applies to S and W destinations (their edge-
    # adjacent slot is the last row/col of the ghost block).
    def ghost_idx(edges):
        out = np.empty((3, 6, 2, h), np.int64)
        for fi in range(3):
            for f in range(6):
                for p, e in enumerate(edges):
                    link = adj[f][e]
                    for k in range(h):
                        dep = (h - 1 - k) if e in (EDGE_S, EDGE_W) else k
                        r = src_row(fi, link.nbr_face, link.nbr_edge, dep)
                        out[fi, f, p, k] = r + (F if link.reversed_ else 0)
        return out

    idx_sn = ghost_idx((EDGE_S, EDGE_N))
    idx_we = ghost_idx((EDGE_W, EDGE_E))
    # Interior boundary-adjacent rows of (u_a, u_b) for the edge normals.
    idx_int = np.empty((2, 6, 4), np.int64)
    for c in range(2):
        for f in range(6):
            for s, e in enumerate(_EORDER):
                idx_int[c, f, s] = src_row(1 + c, f, e, 0)
    idx_all = jnp.asarray(np.concatenate(
        [idx_sn.reshape(-1), idx_we.reshape(-1), idx_int.reshape(-1)]))
    n_sn = idx_sn.size
    n_we = idx_we.size

    # Placed rotation tables, split by orientation: (4, 6, 2, h, n).
    Tc = np.asarray(_rotation_tables(grid))
    T_sn = jnp.asarray(np.stack(
        [Tc[:, :, EDGE_S, ::-1], Tc[:, :, EDGE_N]], axis=2))
    T_we = jnp.asarray(np.stack(
        [Tc[:, :, EDGE_W, ::-1], Tc[:, :, EDGE_E]], axis=2))
    pol = precision
    sdt = jnp.float32 if pol is None else pol.strips_dtype
    if (pol is not None and pol.compute == "bf16"
            and pol.strips == "bf16"):
        # bf16 rotation algebra: tables cast once at build, products and
        # adds ride the 2x-wide bf16 lanes.  Gated on 16-bit strips as
        # well as compute: against f32 strip operands the multiplies
        # would promote to f32 anyway (no lane packing), so bf16 tables
        # would round the rotation coefficients for zero benefit.
        T_sn = T_sn.astype(jnp.bfloat16)
        T_we = T_we.astype(jnp.bfloat16)

    sym_tables = _pair_sym_tables(grid)
    adj_k = [h - 1, 0]          # placed edge-adjacent row: S/W flip, N/E not

    sym_scale = None
    if prescale_sym:
        # Static edge sqrtg rows in [S, N, W, E] order — identical for
        # all faces (the equiangular metric is face-independent), same
        # closed forms the kernel would otherwise evaluate per stage.
        x_row, xf_row, x_col, xf_col, _ = coord_rows(n, h)
        h0, h1 = h, h + n
        r = float(grid.radius)
        sgS = _fast_frame(x_row[:, h0:h1], xf_col[h0:h0 + 1], r)["sqrtg"]
        sgN = _fast_frame(x_row[:, h0:h1], xf_col[h1:h1 + 1], r)["sqrtg"]
        sgW = _fast_frame(xf_row[:, h0:h0 + 1], x_col[h0:h1], r)["sqrtg"]
        sgE = _fast_frame(xf_row[:, h1:h1 + 1], x_col[h0:h1], r)["sqrtg"]
        sym_scale = jnp.stack([sgS.reshape(n), sgN.reshape(n),
                               sgW.reshape(n), sgE.reshape(n)])[None]

    def route(strips_sn, strips_we):
        # The input casts absorb an f32 initial carry under a 16-bit
        # strips policy (and are no-ops thereafter — the stage kernels
        # emit strips in sdt); every cast below is identity with the
        # policy off, keeping that path bitwise the historical route.
        s_src = jnp.concatenate(
            [strips_sn.astype(sdt).reshape(6 * 6 * h, n),
             jnp.transpose(strips_we.astype(sdt),
                           (0, 2, 1)).reshape(6 * 6 * h, n)],
            axis=0)
        s_all = jnp.concatenate([s_src, jnp.flip(s_src, -1)], axis=0)
        rows = jnp.take(s_all, idx_all, axis=0)
        C_sn = rows[:n_sn].reshape(3, 6, 2, h, n)
        C_we = rows[n_sn : n_sn + n_we].reshape(3, 6, 2, h, n)
        # Sym inputs widen to f32: the pair-symmetrization algebra is
        # the conservation-critical path and stays full precision.
        I_u = rows[n_sn + n_we :].reshape(2, 6, 4, n).astype(jnp.float32)

        G_sn = [C_sn[0],
                T_sn[0] * C_sn[1] + T_sn[1] * C_sn[2],
                T_sn[2] * C_sn[1] + T_sn[3] * C_sn[2]]
        G_we = [C_we[0],
                T_we[0] * C_we[1] + T_we[1] * C_we[2],
                T_we[2] * C_we[1] + T_we[3] * C_we[2]]

        gadj_a = jnp.stack(
            [G_sn[1][:, 0, adj_k[0]], G_sn[1][:, 1, adj_k[1]],
             G_we[1][:, 0, adj_k[0]], G_we[1][:, 1, adj_k[1]]],
            axis=1).astype(jnp.float32)
        gadj_b = jnp.stack(
            [G_sn[2][:, 0, adj_k[0]], G_sn[2][:, 1, adj_k[1]],
             G_we[2][:, 0, adj_k[0]], G_we[2][:, 1, adj_k[1]]],
            axis=1).astype(jnp.float32)
        sym = _pair_symmetrize(I_u, gadj_a, gadj_b, sym_tables)
        if sym_scale is not None:
            sym = sym * sym_scale
        # Rounded ONCE per physical edge, then distributed — both faces
        # get the identical sdt value, so seam conservation is exact at
        # any strips dtype.
        sym = sym.astype(sdt)

        gsn = jnp.concatenate(
            [jnp.concatenate([g.reshape(6, 2 * h, n).astype(sdt)
                              for g in G_sn], axis=1),
             sym[:, 0:2]], axis=1)
        gwe_rows = jnp.concatenate(
            [jnp.concatenate([g.reshape(6, 2 * h, n).astype(sdt)
                              for g in G_we], axis=1),
             sym[:, 2:4]], axis=1)
        return gsn, jnp.transpose(gwe_rows, (0, 2, 1))

    return route


def _cov_blockspecs(n, halo, groups: int = 6):
    """The shared BlockSpec set of the compact-carry stage kernels.

    ``groups``: total kernel-grid extent.  The default 6 is the plain
    one-face-per-grid-step layout; the batched ensemble steppers fold
    the member axis into the face axis (``groups = 6 * B``, member-major
    ``(B, 6) -> B*6``) so ONE kernel launch sweeps every member's faces
    — the per-call dispatch/DMA-setup glue is paid once per ensemble
    step instead of once per member.  Static per-face operands (frame
    z-components, orography) stay 6-deep in HBM and index ``f % 6``;
    per-member state indexes ``f`` directly.
    """
    m = n + 2 * halo
    h = halo
    face = (lambda f: (f, 0, 0)) if groups == 6 else \
        (lambda f: (f % 6, 0, 0))
    fz_spec = pl.BlockSpec((1, 1, 3), face,
                           memory_space=pltpu.SMEM)
    coord_specs = [
        pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
    ]
    hi_blk = pl.BlockSpec((1, n, n), lambda f: (f, 0, 0),
                          memory_space=pltpu.VMEM)
    ui_blk = pl.BlockSpec((2, 1, n, n), lambda f: (0, f, 0, 0),
                          memory_space=pltpu.VMEM)
    be_blk = pl.BlockSpec((1, m, m), face,
                          memory_space=pltpu.VMEM)
    gsn_blk = pl.BlockSpec((1, 6 * h + 2, n), lambda f: (f, 0, 0),
                           memory_space=pltpu.VMEM)
    gwe_blk = pl.BlockSpec((1, n, 6 * h + 2), lambda f: (f, 0, 0),
                           memory_space=pltpu.VMEM)
    ssn_blk = pl.BlockSpec((1, 6 * h, n), lambda f: (f, 0, 0),
                           memory_space=pltpu.VMEM)
    swe_blk = pl.BlockSpec((1, n, 6 * h), lambda f: (f, 0, 0),
                           memory_space=pltpu.VMEM)
    return fz_spec, coord_specs, hi_blk, ui_blk, be_blk, gsn_blk, gwe_blk, \
        ssn_blk, swe_blk


def _make_fill(n, halo, i0, i1, corners: bool = False,
               interior: bool = True, base=(0, 0),
               precision: StagePrecision | None = None):
    """Shared in-kernel ghost fill / strip emit over the split layout.

    ``interior=False`` skips the interior store (the manual-DMA stage
    kernels land the interior in the scratch straight from HBM; only
    the ghost bands need the VPU).  ``base=(by, bx)`` shifts the whole
    extended window inside a larger scratch — the manual-DMA layout
    puts the interior at a (8, 128)-tile-aligned offset because Mosaic
    only accepts tile-aligned DMA destination windows, which parks the
    extended window's top-left at ``(8 - halo, 128 - halo)``.

    ``precision``: under a 16-bit strips policy the routed ghost blocks
    arrive in bfloat16 and are widened to f32 on the scratch store (the
    extended frame the stencils read is always f32), and the emitted
    boundary strips are narrowed to the strips dtype on the way out —
    the two casts that bound the 16-bit region to strip storage."""
    h = halo
    by, bx = base
    m = n + 2 * h
    if precision is not None and precision.strips == "bf16":
        gc = lambda x: x.astype(jnp.float32)
        sc = lambda x: x.astype(jnp.bfloat16)
    else:
        gc = sc = lambda x: x

    def fill_ghosts(scratch, int_val, gsn, gwe, fi):
        if interior:
            scratch[by + i0 : by + i1, bx + i0 : bx + i1] = int_val
        scratch[by : by + h, bx + i0 : bx + i1] = \
            gc(gsn[fi * 2 * h : fi * 2 * h + h])
        scratch[by + i1 : by + i1 + h, bx + i0 : bx + i1] = \
            gc(gsn[fi * 2 * h + h : (fi + 1) * 2 * h])
        scratch[by + i0 : by + i1, bx : bx + h] = \
            gc(gwe[:, fi * 2 * h : fi * 2 * h + h])
        scratch[by + i0 : by + i1, bx + i1 : bx + i1 + h] = \
            gc(gwe[:, fi * 2 * h + h : (fi + 1) * 2 * h])
        if corners:
            # The Laplacian's cross-derivative faces read the h x h ghost
            # corners (unlike the dimension-split advective stencils).
            # Same edge-ghost averaging as parallel.halo._fill_corners —
            # purely face-local, no extra communication.
            half = jnp.float32(0.5)
            scratch[by : by + h, bx : bx + h] = half * (
                scratch[by : by + h, bx + i0 : bx + i0 + 1]
                + scratch[by + i0 : by + i0 + 1, bx : bx + h])
            scratch[by : by + h, bx + i1 : bx + i1 + h] = half * (
                scratch[by : by + h, bx + i1 - 1 : bx + i1]
                + scratch[by + i0 : by + i0 + 1, bx + i1 : bx + i1 + h])
            scratch[by + i1 : by + i1 + h, bx : bx + h] = half * (
                scratch[by + i1 : by + i1 + h, bx + i0 : bx + i0 + 1]
                + scratch[by + i1 - 1 : by + i1, bx : bx + h])
            scratch[by + i1 : by + i1 + h, bx + i1 : bx + i1 + h] = half * (
                scratch[by + i1 : by + i1 + h, bx + i1 - 1 : bx + i1]
                + scratch[by + i1 - 1 : by + i1, bx + i1 : bx + i1 + h])
        if (by, bx) == (0, 0):
            return scratch[:]
        # Manual-DMA path: hand back the REF, not a loaded value — the
        # caller wraps it in an _OffsetView and every consumer loads just
        # its own shifted window.  (A full load would materialize the
        # padding lanes; a ref *window* at the misaligned base is
        # rejected by Mosaic; per-site shifted loads are fine.)
        return scratch

    def emit_strips(ssn_ref, swe_ref, int_new, fi):
        ssn_ref[0, fi * 2 * h : fi * 2 * h + h] = sc(int_new[0:h, :])
        ssn_ref[0, fi * 2 * h + h : (fi + 1) * 2 * h] = \
            sc(int_new[n - h : n, :])
        swe_ref[0, :, fi * 2 * h : fi * 2 * h + h] = sc(int_new[:, 0:h])
        swe_ref[0, :, fi * 2 * h + h : (fi + 1) * 2 * h] = \
            sc(int_new[:, n - h : n])

    return fill_ghosts, emit_strips


class _OffsetView:
    """Presents a padded 2-D value as if it were the (m, m) extended
    frame at a static offset ``(by, bx)`` inside it.

    Only the slice forms :func:`rhs_core_cov` uses are supported:
    non-negative starts/stops or ``None``, no steps.  Mosaic rejects
    ref windows whose offsets are not tile-aligned, so the manual-DMA
    stage kernels never materialize the (m, m) window — every consumer
    slices through this view and gets a plain shifted value slice.
    """

    __slots__ = ("v", "by", "bx", "m")

    def __init__(self, v, by, bx, m):
        self.v, self.by, self.bx, self.m = v, by, bx, m

    def __getitem__(self, idx):
        r, c = idx

        def sh(s, off, size):
            if isinstance(s, slice):
                if s.step not in (None, 1):
                    raise ValueError("_OffsetView: slice steps are "
                                     "unsupported")
                start = (s.start or 0)
                stop = size if s.stop is None else s.stop
                if start < 0 or stop < 0:
                    raise ValueError("_OffsetView: negative slice bounds")
                return slice(start + off, stop + off)
            if s < 0:
                raise ValueError("_OffsetView: negative integer indices")
            return s + off

        return self.v[sh(r, self.by, self.m), sh(c, self.bx, self.m)]


def make_cov_stage_compact(
    n: int,
    halo: int,
    dalpha: float,
    radius: float,
    gravity: float,
    omega: float,
    dt: float,
    a: float,
    b: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    carry_dtype=jnp.float32,
    h_offset: float = 0.0,
    h_scale: float = 1.0,
    u_scale: float = 1.0,
    seam: bool = True,
    sym_prescaled: bool = False,
    manual_dma: bool | None = None,
    groups: int = 6,
    precision: StagePrecision | None = None,
):
    """One fused covariant RK stage over interior-only state.

    ``a == 0``: ``stage(hc, uc, gsn, gwe, b_ext)``; else
    ``stage(h0, u0, hc, uc, gsn, gwe, b_ext)``.  Prognostic fields are
    interior ``(6, n, n)`` / ``(2, 6, n, n)``; ``b_ext`` stays extended
    (static, needs its one-deep ring for the Bernoulli band); ``gsn`` /
    ``gwe`` per :func:`make_cov_strip_router_split`.  Returns
    ``(h, u, strips_sn, strips_we)``.  No transposes anywhere in the
    kernel: every strip read/write is in its storage orientation.

    ``carry_dtype``: HBM storage dtype of the prognostic carry — one
    dtype for both fields or ``(h_dtype, u_dtype)``.  Compute is always
    f32 in-VMEM; strips stay f32.  16-bit storage halves that field's
    carry DMA — see DESIGN.md for the measured speed/accuracy ladder.
    ``h_offset`` stores h as an anomaly about a static offset (the
    stored value is ``h - h_offset``), shrinking 16-bit quantization by
    the ratio ``|h| / |h - h_offset|`` — the RK combine is affine with
    coefficients summing to 1, so anomalies combine exactly.
    ``u_scale`` stores u divided by a static scale (use ~grid.radius to
    bring covariant components to O(wind speed)) so ``float16`` storage
    neither overflows nor wastes exponent range; fp16's 10-bit mantissa
    then makes u quantization ~8x finer than bf16.  ``seam=False``
    ablates the symmetrized-seam imposition (measurement only: breaks
    cross-panel conservation).

    ``groups``: kernel-grid extent (see :func:`_cov_blockspecs`) — 6 for
    the single-state stepper, ``6 * B`` for the batched ensemble carry
    with the member axis folded into the face axis.  The kernel body is
    identical per grid step either way, so the ``B = 1`` batched stage
    is bitwise-equal to the plain one.

    ``manual_dma`` (measurement knob, default OFF — measured a dead
    end on v5e): the h/u carry arrives as ANY-space refs and each
    face's interior is DMA'd from HBM *directly into the extended
    scratch's interior window* (``True``: double-buffered one face
    ahead; ``"single"``: one static buffer, issue-and-wait).  The goal
    was deleting the in-kernel VPU interior copy (measured 18 us/step
    at C384: block fetch writes VMEM once, the placement copy reads +
    writes it again).  Measured at C384 (bitwise-identical outputs):
    block 303-310 us/step, manual double-buffered 314.5, manual single
    370.8.  The interior-window DMA destination is a strided row
    window of the padded halo frame and runs at ~70 GB/s effective
    (per-row descriptor overhead), so un-overlapped it stalls ~26
    us/stage, and even fully overlapped it loses ~10 us/step of
    HBM/VMEM bandwidth to the extra traffic — Pallas's compact tiled
    block bursts + VPU placement copy are the better structure on this
    chip.  Kept (parity-tested) because the DMA/VPU balance shifts per
    TPU generation.  Requires a plain f32 carry.
    """
    import numpy as np

    m = n + 2 * halo
    i0, i1 = halo, halo + n
    d = float(dalpha)
    g_dt = b * dt
    precision = resolve_stage_precision(precision)
    sdt = jnp.float32 if precision is None else precision.strips_dtype
    # Widen sym rows to f32 at extraction under a 16-bit strips policy
    # (the seam imposition stores into f32 seam scratch / iota-selects
    # against the f32 flux tensor); identity with the policy off.
    wide = ((lambda x: x.astype(jnp.float32))
            if sdt != jnp.float32 else (lambda x: x))
    recon = pick_recon_precision(scheme, halo, n, limiter, precision)
    x_row, xf_row, x_col, xf_col, _ = coord_rows(n, halo)
    frames_z = jnp.asarray(np.asarray(FACE_AXES)[:, None, :, 2], jnp.float32)
    with_y0 = a != 0.0
    h = halo
    cdt_h, cdt_u = ((jnp.dtype(carry_dtype[0]), jnp.dtype(carry_dtype[1]))
                    if isinstance(carry_dtype, (tuple, list))
                    else (jnp.dtype(carry_dtype),) * 2)
    h_offset = float(h_offset)
    with_off = h_offset != 0.0
    if with_off and ((with_y0 and abs(a + b - 1.0) > 1e-9)
                     or (not with_y0 and b != 1.0)):
        raise ValueError("h_offset needs stage coefficients summing to 1 "
                         "(anomaly combine is only exact then); got "
                         f"a={a}, b={b}")

    u_scale = float(u_scale)
    h_scale = float(h_scale)
    with_scale = u_scale != 1.0
    with_hscale = h_scale != 1.0

    plain_f32 = (cdt_h == jnp.float32 and cdt_u == jnp.float32
                 and not with_off and not with_scale and not with_hscale)
    if groups < 6 or groups % 6:
        raise ValueError(
            f"groups must be a positive multiple of 6 (6 * ensemble "
            f"members), got {groups}")
    if manual_dma is None:
        manual_dma = False
    elif manual_dma and not plain_f32:
        raise ValueError("manual_dma needs a plain f32 carry (the DMA "
                         "engine cannot widen or rescale)")
    if manual_dma and precision is not None:
        raise ValueError("manual_dma needs the plain f32 precision "
                         "policy (its scratch DMA layout is f32-only); "
                         "drop precision or manual_dma")
    if manual_dma and groups != 6:
        raise ValueError("manual_dma is wired for the single-state "
                         "stepper only (its fetch-ahead hardcodes the "
                         "6-face grid); use the block pipeline for "
                         "ensemble carries")
    if manual_dma and n % 128 != 0:
        raise ValueError(
            f"manual_dma needs n % 128 == 0 (got n={n}): the ANY-space "
            "carry's per-face slices must span whole 128-lane tiles")

    def f32h(x):
        # jnp scalars must be born inside the kernel trace (a captured
        # module-level constant is rejected by pallas_call).
        x = x if cdt_h == jnp.float32 else x.astype(jnp.float32)
        if with_hscale:
            x = x * jnp.float32(h_scale)
        return x + jnp.float32(h_offset) if with_off else x

    def f32u(x):
        x = x if cdt_u == jnp.float32 else x.astype(jnp.float32)
        return x * jnp.float32(u_scale) if with_scale else x

    def store(x, cdt):
        """Round-to-nearest for integer storage (truncation toward zero
        would bias every increment); plain cast for float storage.

        Rounding via the magic-constant trick ``(x + 1.5*2^23) - 1.5*2^23``
        (exact round-to-nearest-even for |x| < 2^22, which the int16
        encodings guarantee by construction): two VPU adds, measured
        ~2x cheaper than ``lax.round``'s lowering.
        """
        if cdt == jnp.float32:
            return x
        if jnp.issubdtype(cdt, jnp.integer):
            c = jnp.float32(1.5 * 2.0**23)
            return ((x + c) - c).astype(cdt)
        return x.astype(cdt)
    # Manual-DMA scratch layout: interior window at (8, 128) — the
    # smallest (sublane, lane)-tile-aligned offset that leaves room for
    # the ghost bands above/left of it.
    _OY, _OX = 8, 128
    fill_ghosts, emit_strips = _make_fill(
        n, halo, i0, i1, interior=not manual_dma,
        base=(_OY - halo, _OX - halo) if manual_dma else (0, 0),
        precision=precision)

    def kernel(*refs):
        if with_y0:
            (fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
             h0_ref, u0_ref, hc_ref, uc_ref, gsn_ref, gwe_ref, b_ref,
             ho_ref, uo_ref, ssn_ref, swe_ref, *scratch) = refs
        else:
            (fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
             hc_ref, uc_ref, gsn_ref, gwe_ref, b_ref,
             ho_ref, uo_ref, ssn_ref, swe_ref, *scratch) = refs

        gsn = gsn_ref[0]
        gwe = gwe_ref[0]
        if manual_dma:
            # The carry is ANY-space: DMA each face's interior from HBM
            # straight into the extended scratch's interior window,
            # double-buffered one face ahead (the hand-rolled version of
            # the block pipeline's fetch-ahead, minus the VPU placement
            # copy).  Buffer parity alternates per face; face f-1 is
            # fully consumed before face f starts (the TPU grid is
            # sequential), so re-targeting its buffer is race-free.
            sh2, sa2, sb2 = scratch[0], scratch[1], scratch[2]
            sems = scratch[-1]
            f = pl.program_id(0)
            dsy, dsx = pl.ds(_OY, n), pl.ds(_OX, n)

            def copies(face, buf):
                return (
                    pltpu.make_async_copy(
                        hc_ref.at[face], sh2.at[buf, dsy, dsx],
                        sems.at[buf, 0]),
                    pltpu.make_async_copy(
                        uc_ref.at[0, face], sa2.at[buf, dsy, dsx],
                        sems.at[buf, 1]),
                    pltpu.make_async_copy(
                        uc_ref.at[1, face], sb2.at[buf, dsy, dsx],
                        sems.at[buf, 2]),
                )

            if manual_dma == "single":
                for c in copies(f, 0):
                    c.start()
                buf = 0
            else:
                @pl.when(f == 0)
                def _():
                    for c in copies(0, 0):
                        c.start()

                @pl.when(f + 1 < 6)
                def _():
                    for c in copies(f + 1, (f + 1) % 2):
                        c.start()

                buf = f % 2
            for c in copies(f, buf):
                c.wait()
            ov = lambda v: _OffsetView(v, _OY - halo, _OX - halo, m)
            hf = ov(fill_ghosts(sh2.at[buf], None, gsn, gwe, 0))
            ua = ov(fill_ghosts(sa2.at[buf], None, gsn, gwe, 1))
            ub = ov(fill_ghosts(sb2.at[buf], None, gsn, gwe, 2))
            hc_int = hf[i0:i1, i0:i1]
            ua_int = ua[i0:i1, i0:i1]
            ub_int = ub[i0:i1, i0:i1]
        else:
            hf = fill_ghosts(scratch[0], f32h(hc_ref[0]), gsn, gwe, 0)
            ua = fill_ghosts(scratch[1], f32u(uc_ref[0, 0]), gsn, gwe, 1)
            ub = fill_ghosts(scratch[2], f32u(uc_ref[1, 0]), gsn, gwe, 2)
            hc_int = hc_ref[0]
            ua_int = uc_ref[0, 0]
            ub_int = uc_ref[1, 0]
        fz = (fz_ref[0, 0, 0], fz_ref[0, 0, 1], fz_ref[0, 0, 2])
        ssn = wide(gsn[6 * h : 6 * h + 2]) if seam else None
        swe = wide(gwe[:, 6 * h : 6 * h + 2]) if seam else None

        dh, dua, dub = rhs_core_cov(
            fz, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
            hf, ua, ub, b_ref[0], ssn, swe,
            n=n, halo=halo, d=d, radius=radius,
            gravity=gravity, omega=omega, recon=recon,
            seam_scratch=(scratch[3], scratch[4]) if seam else None,
            sym_prescaled=sym_prescaled, precision=precision,
        )

        fa = jnp.float32(a)
        fb = jnp.float32(b)
        fg = jnp.float32(g_dt)

        def emit(int_old, y0, tend, out_ref, fi, lead=(), is_h=False):
            # The combine runs in STORED space (h: the anomaly, u: the
            # scaled-down value): exact because the stage coefficients
            # sum to 1 and scaling is linear — the tendency constant
            # absorbs 1/u_scale at trace time.  Only the emitted strips
            # need the absolute value back.
            cdt = cdt_h if is_h else cdt_u
            up = ((lambda x: x) if cdt == jnp.float32
                  else (lambda x: x.astype(jnp.float32)))
            scale = h_scale if is_h else u_scale
            fgf = fg if scale == 1.0 else jnp.float32(g_dt / scale)
            if with_y0:
                int_new = (fa * up(y0) + fb * up(int_old)) + fgf * tend
            elif b == 1.0:
                int_new = up(int_old) + fgf * tend
            else:
                int_new = fb * up(int_old) + fgf * tend
            out_ref[lead + (0,)] = store(int_new, cdt)
            sval = int_new
            if scale != 1.0:
                sval = sval * jnp.float32(scale)
            if is_h and with_off:
                sval = sval + jnp.float32(h_offset)
            emit_strips(ssn_ref, swe_ref, sval, fi)

        if with_y0:
            emit(hc_int, h0_ref[0], dh, ho_ref, 0, is_h=True)
            emit(ua_int, u0_ref[0, 0], dua, uo_ref, 1, lead=(0,))
            emit(ub_int, u0_ref[1, 0], dub, uo_ref, 2, lead=(1,))
        else:
            emit(hc_int, None, dh, ho_ref, 0, is_h=True)
            emit(ua_int, None, dua, uo_ref, 1, lead=(0,))
            emit(ub_int, None, dub, uo_ref, 2, lead=(1,))

    (fz_spec, coord_specs, hi_blk, ui_blk, be_blk, gsn_blk, gwe_blk,
     ssn_blk, swe_blk) = _cov_blockspecs(n, halo, groups)

    in_specs = [fz_spec] + coord_specs
    if with_y0:
        in_specs += [hi_blk, ui_blk]
    if manual_dma:
        any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
        in_specs += [any_spec, any_spec, gsn_blk, gwe_blk, be_blk]
    else:
        in_specs += [hi_blk, ui_blk, gsn_blk, gwe_blk, be_blk]

    call = pl.pallas_call(
        kernel,
        grid_spec=pl.GridSpec(
            grid=(groups,),
            in_specs=in_specs,
            out_specs=[hi_blk, ui_blk, ssn_blk, swe_blk],
            scratch_shapes=(
                # Logical shape rounded up to whole (8, 128) tiles:
                # slicing the buffer dim needs tile-aligned trailing
                # SHAPES, not just offsets.
                ([pltpu.VMEM((2, -(-(_OY + n + halo) // 8) * 8,
                              -(-(_OX + n + halo) // 128) * 128),
                             jnp.float32) for _ in range(3)]
                 if manual_dma else
                 [pltpu.VMEM((m, m), jnp.float32) for _ in range(3)])
                + [pltpu.VMEM((n, n + 1), jnp.float32),
                   pltpu.VMEM((n + 1, n), jnp.float32)]
                + ([pltpu.SemaphoreType.DMA((2, 3))]
                   if manual_dma else [])),
        ),
        out_shape=[
            jax.ShapeDtypeStruct((groups, n, n), cdt_h),
            jax.ShapeDtypeStruct((2, groups, n, n), cdt_u),
            jax.ShapeDtypeStruct((groups, 6 * h, n), sdt),
            jax.ShapeDtypeStruct((groups, n, 6 * h), sdt),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    if with_y0:
        def stage(h0, u0, hc, uc, gsn, gwe, b_ext):
            return tuple(call(frames_z, x_row, xf_row, x_col, xf_col,
                              h0, u0, hc, uc, gsn, gwe, b_ext))
    else:
        def stage(hc, uc, gsn, gwe, b_ext):
            return tuple(call(frames_z, x_row, xf_row, x_col, xf_col,
                              hc, uc, gsn, gwe, b_ext))
    return stage


def make_fused_ssprk3_cov_compact(
    grid,
    gravity: float,
    omega: float,
    dt: float,
    b_ext,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    carry_dtype=jnp.float32,
    h_offset: float = 0.0,
    h_scale: float = 1.0,
    u_scale: float = 1.0,
    seam: bool = True,
    ensemble: int = 0,
    precision=None,
):
    """``step(y, t) -> y`` over ``y = {h, u, strips_sn, strips_we}``.

    The production stepper: three compact stage kernels (interior-only
    fields, orientation-native strips) plus three linear strip routes.
    Initialise the carry with :meth:`CovariantShallowWater.compact_state`
    (encode ``h``/``u`` per ``carry_dtype``/``h_offset`` — see
    :meth:`CovariantShallowWater.encode_carry`).

    ``precision`` (ops/pallas/precision.py): the per-stage dtype policy
    — bf16 flux/reconstruction/router arithmetic with f32 accumulators
    and metric terms, optionally bf16 strip storage.  Orthogonal to
    ``carry_dtype`` (in-stage arithmetic vs between-step storage); the
    two stack.  ``None`` is bitwise the historical f32 path.  A 16-bit
    strips policy accepts an f32 initial strip carry (the first route
    narrows it).

    ``ensemble = B > 0``: the carry gains a leading member axis —
    ``{h: (B, 6, n, n), u: (2, B, 6, n, n), strips_sn: (B, 6, 6h, n),
    strips_we: (B, 6, n, 6h)}`` — and each stage runs as ONE kernel
    launch over a ``6 * B`` grid (the member axis folded into the face
    axis, :func:`_cov_blockspecs`), with the strip router vmapped over
    members (its gathers/rotations batch into single whole-ensemble XLA
    ops).  Per-member arithmetic is the plain stepper's, op for op, so
    the ``B = 1`` batched step is bitwise-identical to the unbatched one
    (tested); what changes is dispatch and DMA-setup amortization —
    small per-member grids stop paying the fixed per-call glue that
    dominates below ~C128.  Initialise with
    :meth:`CovariantShallowWater.ensemble_compact_state`.
    """
    from .swe_step import SSPRK3_COEFFS

    B = int(ensemble)
    precision = resolve_stage_precision(precision)
    route = make_cov_strip_router_split(grid, prescale_sym=seam,
                                        precision=precision)
    if B:
        # Member-mapped router: the static row-gather and 2x2 rotation
        # multiply-adds batch into single whole-ensemble XLA ops.
        route = jax.vmap(route)
    mk = lambda a, b: make_cov_stage_compact(
        grid.n, grid.halo, float(grid.dalpha), float(grid.radius), gravity,
        omega, dt, a, b, scheme=scheme, limiter=limiter, interpret=interpret,
        carry_dtype=carry_dtype, h_offset=h_offset, h_scale=h_scale,
        u_scale=u_scale, seam=seam, sym_prescaled=seam,
        groups=6 * max(B, 1), precision=precision,
    )
    (a1, b1), (a2, b2), (a3, b3) = SSPRK3_COEFFS
    stage1 = mk(a1, b1)
    stage2 = mk(a2, b2)
    stage3 = mk(a3, b3)

    if not B:
        def step(y, t):
            del t
            h0, u0 = y["h"], y["u"]
            with named_scope("rk_stage1"):
                gsn, gwe = route(y["strips_sn"], y["strips_we"])
                h1, u1, sn1, we1 = stage1(h0, u0, gsn, gwe, b_ext)
            with named_scope("rk_stage2"):
                gsn, gwe = route(sn1, we1)
                h2, u2, sn2, we2 = stage2(h0, u0, h1, u1, gsn, gwe, b_ext)
            with named_scope("rk_stage3"):
                gsn, gwe = route(sn2, we2)
                h3, u3, sn3, we3 = stage3(h0, u0, h2, u2, gsn, gwe, b_ext)
            return {"h": h3, "u": u3, "strips_sn": sn3, "strips_we": we3}

        return step

    # Batched ensemble step: fold (B, 6) -> B*6 around the stage kernels
    # (free reshapes — leading axes are contiguous), unfold for the
    # vmapped router.  ONE pallas_call per stage sweeps all members.
    def fold(x, lead=0):
        s = x.shape
        return x.reshape(s[:lead] + (B * 6,) + s[lead + 2:])

    def unfold(x, lead=0):
        s = x.shape
        return x.reshape(s[:lead] + (B, 6) + s[lead + 1:])

    def step(y, t):
        del t
        h0, u0 = fold(y["h"]), fold(y["u"], 1)
        with named_scope("rk_stage1"):
            gsn, gwe = route(y["strips_sn"], y["strips_we"])
            h1, u1, sn1, we1 = stage1(h0, u0, fold(gsn), fold(gwe), b_ext)
        with named_scope("rk_stage2"):
            gsn, gwe = route(unfold(sn1), unfold(we1))
            h2, u2, sn2, we2 = stage2(h0, u0, h1, u1, fold(gsn),
                                      fold(gwe), b_ext)
        with named_scope("rk_stage3"):
            gsn, gwe = route(unfold(sn2), unfold(we2))
            h3, u3, sn3, we3 = stage3(h0, u0, h2, u2, fold(gsn),
                                      fold(gwe), b_ext)
        return {"h": unfold(h3), "u": unfold(u3, 1),
                "strips_sn": unfold(sn3), "strips_we": unfold(we3)}

    step.ensemble = B
    return step


def make_fused_ssprk3_cov_multistep(
    grid,
    gravity: float,
    omega: float,
    dt: float,
    b_ext,
    temporal_block: int,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    carry_dtype=jnp.float32,
    h_offset: float = 0.0,
    h_scale: float = 1.0,
    u_scale: float = 1.0,
    seam: bool = True,
    ensemble: int = 0,
    precision=None,
):
    """``block(y, t) -> y`` running ``temporal_block`` fused SSPRK3 steps.

    The temporal-blocking form of :func:`make_fused_ssprk3_cov_compact`
    (``parallelization.temporal_block``): one traced block = k steps
    back-to-back, sharing ONE set of stage kernels and one router.  On a
    single device every strip route is face-local and exact, so the k
    steps are *bitwise-identical* to k separate compact steps — the k=1
    path stays the reference by construction; what changes is dispatch
    granularity (one call per k steps) and that the whole k-step chain
    of strip/state intermediates is one XLA liveness region (nothing is
    re-packed at step boundaries — the carry never round-trips through
    the caller).  The exchange-count story (deep halos, redundant band
    compute) lives in the sharded tiers
    (:func:`jaxstream.parallel.shard_cov.make_sharded_cov_stepper` with
    ``temporal_block > 1``) where strip routes are collectives.
    """
    if temporal_block < 1:
        raise ValueError(
            f"temporal_block must be >= 1, got {temporal_block}")
    step1 = make_fused_ssprk3_cov_compact(
        grid, gravity, omega, dt, b_ext, scheme=scheme, limiter=limiter,
        interpret=interpret, carry_dtype=carry_dtype, h_offset=h_offset,
        h_scale=h_scale, u_scale=u_scale, seam=seam, ensemble=ensemble,
        precision=precision,
    )
    if temporal_block == 1:
        return step1
    from ...stepping import blocked

    # stepping.blocked threads t with sequential dt adds — the compact
    # step ignores t today, but the shared helper keeps the sub-step
    # times right if it ever reads them (and keeps one k-loop, not
    # three copies across the temporal_block call sites).
    block = blocked(step1, temporal_block, dt)
    block.steps_per_call = temporal_block
    if ensemble:
        block.ensemble = int(ensemble)
    return block


# ---------------------------------------------------------------------------
# Fused hyperdiffusion (del^4) stepper: two kernels + two routes per stage.
#
# The Galewsky jet — the flagship validation case — needs a del^4 filter
# (nu4 > 0), which the single-kernel stages above cannot provide: del^4
# is two chained Laplacians with a ghost refill between them (the
# second Laplacian reads the FIRST one's halo, which lives on the
# neighbor panel).  Rather than widening halos (the 2-ring band near
# cube corners would need corner ghosts, which the cubed sphere does
# not have), each RK stage runs the existing strip machinery twice:
#
#   route(state strips) -> kernel A: fill state ghosts, advective RHS,
#       partial stage combine y_adv = (a y0 + b yc) + b dt L_adv, and
#       l1 = lap(h), lap(u_a), lap(u_b); emits l1 boundary strips
#   route(l1 strips)    -> kernel B: fill l1 ghosts (the same rotation
#       tables apply — lap of covariant components IS a covariant pair),
#       l2 = lap(l1), y_new = y_adv - b dt nu4 l2; emits state strips
#
# This reproduces the classic path's fill(lap(fill(lap)))) structure
# (jaxstream/models/shallow_water_cov.py rhs, nu4 branch) with closed-
# form in-kernel metrics; agreement is op-reordering roundoff (tested).
# ---------------------------------------------------------------------------


def lap_core(xr, xfr, yc, yfc, psi, *, n, halo, d, radius, ring=0):
    """Laplace-Beltrami of one ghost-filled (M, M) face.

    The kernel-math twin of :func:`jaxstream.ops.fv.laplacian` (same
    conservative flux form and stencils, cross-shaped and corner-free),
    with face metrics from the sqrtg-folded closed forms.

    ``ring``: how many ghost rings to INCLUDE in the output — 0 gives
    the interior ``(n, n)``; ``ring=g`` gives ``(n+2g, n+2g)``,
    evaluating the operator on the innermost ``g`` ghost rings too
    (their stencils read ghosts to depth ``g+1``, so ``g <= halo - 1``;
    the cross-derivative faces additionally read the corner-filled
    ghost corners).  The split-nu4 filter uses ``ring=1`` so the
    second Laplacian can consume the first one's ring without a
    mid-filter exchange — the ring values are face-local evaluations
    at the neighbor's physical points, consistent to the stencil's
    own O(d^2) (the same class of seam approximation as the ghost
    resampling itself).
    """
    if not 0 <= ring <= halo - 1:
        raise ValueError(f"lap_core: ring={ring} needs 0 <= ring <= "
                         f"halo-1 (halo={halo}; the ring stencil reads "
                         "ghosts to depth ring+1)")
    h0, h1 = halo - ring, halo + n + ring
    invd = jnp.float32(1.0 / d)
    inv2d = jnp.float32(0.5 / d)

    pr = psi[h0:h1, :]
    dpa = (pr[:, h0:h1 + 1] - pr[:, h0 - 1:h1]) * invd
    dpb_c = (psi[h0 + 1:h1 + 1, :] - psi[h0 - 1:h1 - 1, :]) * inv2d
    dpb_f = 0.5 * (dpb_c[:, h0 - 1:h1] + dpb_c[:, h0:h1 + 1])
    Fx = _fast_frame(xfr[:, h0:h1 + 1], yc[h0:h1], radius)
    fx = Fx["fg_aa"] * dpa + Fx["fg_ab"] * dpb_f

    pc = psi[:, h0:h1]
    dpb = (pc[h0:h1 + 1, :] - pc[h0 - 1:h1, :]) * invd
    dpa_c = (psi[:, h0 + 1:h1 + 1] - psi[:, h0 - 1:h1 - 1]) * inv2d
    dpa_f = 0.5 * (dpa_c[h0 - 1:h1, :] + dpa_c[h0:h1 + 1, :])
    Fy = _fast_frame(xr[:, h0:h1], yfc[h0:h1 + 1], radius)
    fy = Fy["fg_bb"] * dpb + Fy["fg_ab"] * dpa_f

    Fc = _fast_frame(xr[:, h0:h1], yc[h0:h1], radius)
    return ((fx[:, 1:] - fx[:, :-1]) + (fy[1:, :] - fy[:-1, :])) * (
        Fc["inv_sqrtg"] * invd)


def make_cov_stage_nu4(
    grid,
    gravity: float,
    omega: float,
    dt: float,
    a: float,
    b: float,
    nu4: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
):
    """One covariant RK stage with del^4 filtering, as a kernel pair.

    Returns ``(stage_a, stage_b)``:
      * ``stage_a(y0h, y0u, hc, uc, gsn, gwe, b_ext) -> (h_adv, u_adv,
        l1h, l1u, sn_l1, we_l1)`` (``y0*`` omitted when ``a == 0``),
      * ``stage_b(h_adv, u_adv, l1h, l1u, gsn, gwe) -> (h, u, sn, we)``.
    """
    import numpy as np

    n, halo = grid.n, grid.halo
    m = n + 2 * halo
    i0, i1 = halo, halo + n
    d = float(grid.dalpha)
    radius = float(grid.radius)
    g_dt = b * dt
    recon = pick_recon(scheme, halo, n, limiter)
    x_row, xf_row, x_col, xf_col, _ = coord_rows(n, halo)
    frames_z = jnp.asarray(np.asarray(FACE_AXES)[:, None, :, 2], jnp.float32)
    with_y0 = a != 0.0
    h = halo
    fill_ghosts, emit_strips = _make_fill(n, halo, i0, i1, corners=True)
    (fz_spec, coord_specs, hi_blk, ui_blk, be_blk, gsn_blk, gwe_blk,
     ssn_blk, swe_blk) = _cov_blockspecs(n, halo)

    lap = lambda xr, xfr, ycol, yfcol, psi: lap_core(
        xr, xfr, ycol, yfcol, psi, n=n, halo=halo, d=d, radius=radius)

    def kernel_a(*refs):
        if with_y0:
            (fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
             h0_ref, u0_ref, hc_ref, uc_ref, gsn_ref, gwe_ref, b_ref,
             ha_ref, ua_ref, l1h_ref, l1u_ref, ssn_ref, swe_ref,
             *scratch) = refs
        else:
            (fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
             hc_ref, uc_ref, gsn_ref, gwe_ref, b_ref,
             ha_ref, ua_ref, l1h_ref, l1u_ref, ssn_ref, swe_ref,
             *scratch) = refs

        gsn = gsn_ref[0]
        gwe = gwe_ref[0]
        hf = fill_ghosts(scratch[0], hc_ref[0], gsn, gwe, 0)
        ua = fill_ghosts(scratch[1], uc_ref[0, 0], gsn, gwe, 1)
        ub = fill_ghosts(scratch[2], uc_ref[1, 0], gsn, gwe, 2)
        fz = (fz_ref[0, 0, 0], fz_ref[0, 0, 1], fz_ref[0, 0, 2])
        ssn = gsn[6 * h : 6 * h + 2]
        swe = gwe[:, 6 * h : 6 * h + 2]

        dh, dua, dub = rhs_core_cov(
            fz, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
            hf, ua, ub, b_ref[0], ssn, swe,
            n=n, halo=halo, d=d, radius=radius,
            gravity=gravity, omega=omega, recon=recon,
        )

        fa = jnp.float32(a)
        fb = jnp.float32(b)
        fg = jnp.float32(g_dt)

        def combine(int_old, y0, tend):
            if with_y0:
                return (fa * y0 + fb * int_old) + fg * tend
            if b == 1.0:
                return int_old + fg * tend
            return fb * int_old + fg * tend

        if with_y0:
            ha_ref[0] = combine(hc_ref[0], h0_ref[0], dh)
            ua_ref[0, 0] = combine(uc_ref[0, 0], u0_ref[0, 0], dua)
            ua_ref[1, 0] = combine(uc_ref[1, 0], u0_ref[1, 0], dub)
        else:
            ha_ref[0] = combine(hc_ref[0], None, dh)
            ua_ref[0, 0] = combine(uc_ref[0, 0], None, dua)
            ua_ref[1, 0] = combine(uc_ref[1, 0], None, dub)

        for fi, (psi, ref, lead) in enumerate(
                ((hf, l1h_ref, ()), (ua, l1u_ref, (0,)), (ub, l1u_ref, (1,)))):
            l1 = lap(xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:], psi)
            ref[lead + (0,)] = l1
            emit_strips(ssn_ref, swe_ref, l1, fi)

    def kernel_b(*refs):
        (xr_ref, xfr_ref, yc_ref, yfc_ref,
         ha_ref, ua_ref, l1h_ref, l1u_ref, gsn_ref, gwe_ref,
         ho_ref, uo_ref, ssn_ref, swe_ref, *scratch) = refs

        gsn = gsn_ref[0]
        gwe = gwe_ref[0]
        damp = jnp.float32(g_dt * nu4)
        for fi, (int_ref, lead, adv_ref, out_ref) in enumerate(
                ((l1h_ref, (), ha_ref, ho_ref),
                 (l1u_ref, (0,), ua_ref, uo_ref),
                 (l1u_ref, (1,), ua_ref, uo_ref))):
            l1f = fill_ghosts(scratch[fi], int_ref[lead + (0,)], gsn, gwe, fi)
            l2 = lap(xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:], l1f)
            int_new = adv_ref[lead + (0,)] - damp * l2
            out_ref[lead + (0,)] = int_new
            emit_strips(ssn_ref, swe_ref, int_new, fi)

    in_a = [fz_spec] + coord_specs
    if with_y0:
        in_a += [hi_blk, ui_blk]
    in_a += [hi_blk, ui_blk, gsn_blk, gwe_blk, be_blk]
    call_a = pl.pallas_call(
        kernel_a,
        grid_spec=pl.GridSpec(
            grid=(6,),
            in_specs=in_a,
            out_specs=[hi_blk, ui_blk, hi_blk, ui_blk, ssn_blk, swe_blk],
            scratch_shapes=[pltpu.VMEM((m, m), jnp.float32)
                            for _ in range(3)],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((2, 6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((2, 6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((6, 6 * h, n), jnp.float32),
            jax.ShapeDtypeStruct((6, n, 6 * h), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    call_b = pl.pallas_call(
        kernel_b,
        grid_spec=pl.GridSpec(
            grid=(6,),
            in_specs=coord_specs + [hi_blk, ui_blk, hi_blk, ui_blk,
                                    gsn_blk, gwe_blk],
            out_specs=[hi_blk, ui_blk, ssn_blk, swe_blk],
            scratch_shapes=[pltpu.VMEM((m, m), jnp.float32)
                            for _ in range(3)],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((2, 6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((6, 6 * h, n), jnp.float32),
            jax.ShapeDtypeStruct((6, n, 6 * h), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    if with_y0:
        def stage_a(h0, u0, hc, uc, gsn, gwe, b_ext):
            return tuple(call_a(frames_z, x_row, xf_row, x_col, xf_col,
                                h0, u0, hc, uc, gsn, gwe, b_ext))
    else:
        def stage_a(hc, uc, gsn, gwe, b_ext):
            return tuple(call_a(frames_z, x_row, xf_row, x_col, xf_col,
                                hc, uc, gsn, gwe, b_ext))

    def stage_b(h_adv, u_adv, l1h, l1u, gsn, gwe):
        return tuple(call_b(x_row, xf_row, x_col, xf_col,
                            h_adv, u_adv, l1h, l1u, gsn, gwe))

    return stage_a, stage_b


def _nu4_filtered_value(xr, xfr, yc, yfc, psi, iv, *, n, halo, d,
                        radius, damp):
    """``q - damp * lap(lap q)`` for one face — the ONE definition of
    the del^4 filter arithmetic (ring-1 first Laplacian on the
    halo-deep extended frame ``psi``, halo-1 second Laplacian on l1's
    ``(n+2)^2`` window whose ``[1:n+1]`` maps to the interior),
    shared by the split filter kernel (:func:`make_cov_nu4_filter`)
    and the re-fused stage-1 kernel
    (:func:`make_cov_stage_refused_nu4`) so a stencil/window fix
    propagates to both placements.  ``iv`` is the face's unfiltered
    interior values."""
    m = n + 2 * halo
    h = halo
    l1 = lap_core(xr, xfr, yc, yfc, psi, n=n, halo=halo, d=d,
                  radius=radius, ring=1)                # (n+2, n+2)
    l2 = lap_core(xr[:, h - 1:m - h + 1], xfr[:, h - 1:m - h + 2],
                  yc[h - 1:m - h + 1, :], yfc[h - 1:m - h + 2, :],
                  l1, n=n, halo=1, d=d, radius=radius)
    return iv - damp * l2


def make_cov_nu4_filter(
    grid,
    nu4: float,
    dt_eff: float,
    interpret: bool = False,
    precision=None,
):
    """Once-per-step del^4 filter as ONE kernel (round 5).

    ``filter(h, u, gsn, gwe) -> (h', u', sn, we)`` applying
    ``q -= dt_eff nu4 lap(lap q)`` to the three prognostics.  The
    in-stage pair (:func:`make_cov_stage_nu4`) refills the first
    Laplacian's ghosts from the neighbor panel between the two
    Laplacians; here the first Laplacian is instead evaluated on the
    extended ring (``lap_core(ring=1)``, legal at halo >= 2 with the
    in-kernel corner fill) so the second one needs no exchange.  The
    ring values are face-local evaluations at the neighbor's physical
    points — an O(d^2) seam approximation on a damp-scaled (~1e-3
    relative) term; the Galewsky day-6 physics gate (vorticity band,
    quiescent hemisphere, mass) is the acceptance test
    (bench_galewsky), plus interpret-mode split-vs-stage parity in
    tests/test_cov_swe.py::test_cov_split_nu4_matches_stage.

    Splitting the filter out of the RK stages (standard dycore
    practice: hyperdiffusion applied once per step, first-order in
    time like any split filter) removes 12 of the in-stage path's 18
    Laplacian evaluations and 3 of its 6 routes — measured budget in
    DESIGN.md "Galewsky/nu4 step budget".
    """
    n, halo = grid.n, grid.halo
    if halo < 2:
        raise ValueError(f"split nu4 filter needs halo >= 2 (ring-1 "
                         f"first Laplacian), got halo={halo}")
    m = n + 2 * halo
    i0, i1 = halo, halo + n
    d = float(grid.dalpha)
    radius = float(grid.radius)
    h = halo
    precision = resolve_stage_precision(precision)
    sdt = jnp.float32 if precision is None else precision.strips_dtype
    # The filter arithmetic itself is always f32 (a damp-scaled 4th-
    # order operator is exactly where low-precision differencing bites);
    # the policy only narrows the strip storage at the boundary.
    fill_ghosts, emit_strips = _make_fill(n, halo, i0, i1, corners=True,
                                          precision=precision)
    x_row, xf_row, x_col, xf_col, _ = coord_rows(n, halo)
    (fz_spec, coord_specs, hi_blk, ui_blk, be_blk, gsn_blk, gwe_blk,
     ssn_blk, swe_blk) = _cov_blockspecs(n, halo)

    def kernel(*refs):
        (xr_ref, xfr_ref, yc_ref, yfc_ref,
         hc_ref, uc_ref, gsn_ref, gwe_ref,
         ho_ref, uo_ref, ssn_ref, swe_ref, *scratch) = refs

        gsn = gsn_ref[0]
        gwe = gwe_ref[0]
        damp = jnp.float32(dt_eff * nu4)
        for fi, (int_ref, lead, out_ref) in enumerate(
                ((hc_ref, (), ho_ref),
                 (uc_ref, (0,), uo_ref),
                 (uc_ref, (1,), uo_ref))):
            psi = fill_ghosts(scratch[fi], int_ref[lead + (0,)],
                              gsn, gwe, fi)
            int_new = _nu4_filtered_value(
                xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:], psi,
                int_ref[lead + (0,)], n=n, halo=halo, d=d,
                radius=radius, damp=damp)
            out_ref[lead + (0,)] = int_new
            emit_strips(ssn_ref, swe_ref, int_new, fi)

    call = pl.pallas_call(
        kernel,
        grid_spec=pl.GridSpec(
            grid=(6,),
            in_specs=coord_specs + [hi_blk, ui_blk, gsn_blk, gwe_blk],
            out_specs=[hi_blk, ui_blk, ssn_blk, swe_blk],
            scratch_shapes=[pltpu.VMEM((m, m), jnp.float32)
                            for _ in range(3)],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((2, 6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((6, 6 * h, n), sdt),
            jax.ShapeDtypeStruct((6, n, 6 * h), sdt),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    def filt(hc, uc, gsn, gwe):
        return tuple(call(x_row, xf_row, x_col, xf_col,
                          hc, uc, gsn, gwe))

    return filt


def make_fused_ssprk3_cov_split_nu4(
    grid,
    gravity: float,
    omega: float,
    dt: float,
    b_ext,
    nu4: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    interval: int = 1,
    precision=None,
):
    """``step(y, t) -> y``: three PLAIN compact RK stages + one del^4
    filter kernel per step (4 kernels + 4 routes, vs the in-stage
    pair's 6 + 6 with twice the Laplacian count).

    The split form is first-order in time in the filter term — the
    standard operator-split treatment of hyperdiffusion in dynamical
    cores — so trajectories differ from the in-stage path at the
    damp-scale; the Galewsky day-6 physics gate is the equivalence
    standard (see :func:`make_cov_nu4_filter`).  Carry/router identical
    to :func:`make_fused_ssprk3_cov_compact`; the stage kernels ARE
    that stepper's (un-prescaled router shared with the filter).

    ``interval``: apply the filter every ``interval``-th step with an
    ``interval x`` coefficient (filter-cycling, the same split-filter
    logic one level up).  The explicit del^4 stability bound is miles
    away (nu4 dt interval / dx^4 ~ 0.03 at C384/interval=2), so the
    arbiter is the physics gate, not stability.  Step counting rides an
    integer ``"filter_k"`` counter in the carry — seed it with
    ``jnp.int32(0)`` alongside :meth:`compact_state`'s fields.  (It
    must NOT be reconstructed as ``round(t/dt)``: ``t`` is accumulated
    in f32 one ``+ dt`` at a time, and for a dt whose multiples are not
    exactly representable the accumulated rounding makes ``round(t/dt)``
    skip or repeat an index — double- or un-applied filter steps.)
    """
    from .swe_step import SSPRK3_COEFFS

    precision = resolve_stage_precision(precision)
    route = make_cov_strip_router_split(grid, precision=precision)
    mk = lambda a, b: make_cov_stage_compact(
        grid.n, grid.halo, float(grid.dalpha), float(grid.radius),
        gravity, omega, dt, a, b, scheme=scheme, limiter=limiter,
        interpret=interpret, seam=True, sym_prescaled=False,
        precision=precision,
    )
    (a1, b1), (a2, b2), (a3, b3) = SSPRK3_COEFFS
    stage1 = mk(a1, b1)
    stage2 = mk(a2, b2)
    stage3 = mk(a3, b3)
    filt = make_cov_nu4_filter(grid, nu4, dt * interval,
                               interpret=interpret, precision=precision)

    def step(y, t):
        del t
        h0, u0 = y["h"], y["u"]
        with named_scope("rk_stage1"):
            gsn, gwe = route(y["strips_sn"], y["strips_we"])
            h1, u1, sn1, we1 = stage1(h0, u0, gsn, gwe, b_ext)
        with named_scope("rk_stage2"):
            gsn, gwe = route(sn1, we1)
            h2, u2, sn2, we2 = stage2(h0, u0, h1, u1, gsn, gwe, b_ext)
        with named_scope("rk_stage3"):
            gsn, gwe = route(sn2, we2)
            h3, u3, sn3, we3 = stage3(h0, u0, h2, u2, gsn, gwe, b_ext)
        if interval == 1:
            with named_scope("nu4_filter"):
                gsn, gwe = route(sn3, we3)
                hf, uf, snf, wef = filt(h3, u3, gsn, gwe)
            return {"h": hf, "u": uf, "strips_sn": snf, "strips_we": wef}

        if "filter_k" not in y:
            raise ValueError(
                "the interval > 1 filter-cycling carry needs an integer "
                "'filter_k' step counter; seed it as "
                "dict(model.compact_state(state), filter_k=jnp.int32(0))")
        k = y["filter_k"]

        def do_filter(args):
            h3, u3, sn3, we3 = args
            gsn, gwe = route(sn3, we3)
            return filt(h3, u3, gsn, gwe)

        hf, uf, snf, wef = jax.lax.cond(
            k % interval == interval - 1,
            do_filter, lambda args: args, (h3, u3, sn3, we3))
        return {"h": hf, "u": uf, "strips_sn": snf, "strips_we": wef,
                "filter_k": (k + 1) % interval}

    return step


def make_fused_ssprk3_cov_nu4(
    grid,
    gravity: float,
    omega: float,
    dt: float,
    b_ext,
    nu4: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
):
    """``step(y, t) -> y`` with del^4 filtering, over the compact carry.

    Six kernels + six routes per step (two per RK stage); same carry and
    router as :func:`make_fused_ssprk3_cov_compact`.
    """
    from .swe_step import SSPRK3_COEFFS

    route = make_cov_strip_router_split(grid)
    mk = lambda a, b: make_cov_stage_nu4(
        grid, gravity, omega, dt, a, b, nu4,
        scheme=scheme, limiter=limiter, interpret=interpret,
    )
    (a1, b1), (a2, b2), (a3, b3) = SSPRK3_COEFFS
    s1a, s1b = mk(a1, b1)
    s2a, s2b = mk(a2, b2)
    s3a, s3b = mk(a3, b3)

    def half_stage(sa, sb, args):
        ha, uadv, l1h, l1u, sn1, we1 = sa(*args)
        gsn, gwe = route(sn1, we1)
        return sb(ha, uadv, l1h, l1u, gsn, gwe)

    def step(y, t):
        del t
        h0, u0 = y["h"], y["u"]
        gsn, gwe = route(y["strips_sn"], y["strips_we"])
        h1, u1, sn, we = half_stage(s1a, s1b, (h0, u0, gsn, gwe, b_ext))
        gsn, gwe = route(sn, we)
        h2, u2, sn, we = half_stage(
            s2a, s2b, (h0, u0, h1, u1, gsn, gwe, b_ext))
        gsn, gwe = route(sn, we)
        h3, u3, sn, we = half_stage(
            s3a, s3b, (h0, u0, h2, u2, gsn, gwe, b_ext))
        return {"h": h3, "u": u3, "strips_sn": sn, "strips_we": we}

    return step


# ---------------------------------------------------------------------------
# Re-fused del^4 (round 10): the filter folded INTO the stage-1 kernel.
#
# The split filter (round 5) pays one extra kernel launch + one extra
# strip route per step — 4 + 4 against the plain stepper's 3 + 3 — and
# on the blocked tiers that fourth route is exactly the exchange the
# temporal block (PR 2) exists to amortize away.  The re-fusion
# observes that the split step's last op (filter y using route(y's
# strips)) and the NEXT step's first op (stage 1 using the same
# route(y's strips)) consume the identical routed ghosts: commuting the
# filter to the head of the step makes them one kernel.  Per step:
#
#   split:    route S1 route S2 route S3 route FILT     (4 kernels, 4 routes)
#   re-fused: route [FILT+S1] route S2 route S3         (3 kernels, 3 routes)
#
# Operator sequence: split is (F R)^k y0, re-fused is (R F)^k y0 — the
# identical infinite product shifted by half a split step, so the two
# trajectories differ by one filter application at the endpoints (an
# O(damp) ~ 1e-3-relative perturbation on the filter term, the same
# class as the split form's own first-order splitting).  Seam detail:
# the in-kernel filter can only produce the FILTERED interior (filtered
# ghosts would need depth-6 strips), so the advective stencils near the
# boundary read filtered interior + unfiltered ghost values — an
# O(damp) seam inconsistency on a damp-scaled term, the same class as
# the split filter's own ring-1 seam approximation.  Mass conservation
# is exact regardless: the symmetrized edge normals come from the
# router (one shared value per physical edge, both faces identical), so
# cross-seam flux equality never depends on ghost consistency.
# Equivalence standard: the Galewsky day-6 physics gate
# (bench_galewsky, refused line) + the damp-scale parity smoke in
# tests/test_precision.py.
# ---------------------------------------------------------------------------


def make_cov_stage_refused_nu4(
    grid,
    gravity: float,
    omega: float,
    dt: float,
    nu4: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    precision=None,
):
    """Stage-1 kernel with the del^4 filter fused in front of the RHS.

    ``stage1f(hc, uc, gsn, gwe, b_ext) -> (h1, u1, h0f, u0f, sn, we)``:
    fills ghosts once (corner-filled — the Laplacian ring needs them;
    the advective stencils never read corners so their arithmetic is
    unchanged), applies ``q -= dt nu4 lap(lap q)`` to the three
    prognostics' interiors (ring-1 first Laplacian, exactly
    :func:`make_cov_nu4_filter`'s arithmetic), overwrites the scratch
    interiors with the filtered fields, and runs the plain stage-1
    advective RHS + combine on the result.  Emits the filtered base
    state ``(h0f, u0f)`` so stages 2/3 combine against the same y0 the
    split stepper would have produced.
    """
    import numpy as np

    n, halo = grid.n, grid.halo
    if halo < 2:
        raise ValueError(f"re-fused nu4 needs halo >= 2 (ring-1 first "
                         f"Laplacian), got halo={halo}")
    m = n + 2 * halo
    i0, i1 = halo, halo + n
    d = float(grid.dalpha)
    radius = float(grid.radius)
    h = halo
    precision = resolve_stage_precision(precision)
    sdt = jnp.float32 if precision is None else precision.strips_dtype
    wide = ((lambda x: x.astype(jnp.float32))
            if sdt != jnp.float32 else (lambda x: x))
    recon = pick_recon_precision(scheme, halo, n, limiter, precision)
    fill_ghosts, emit_strips = _make_fill(n, halo, i0, i1, corners=True,
                                          precision=precision)
    x_row, xf_row, x_col, xf_col, _ = coord_rows(n, halo)
    frames_z = jnp.asarray(np.asarray(FACE_AXES)[:, None, :, 2], jnp.float32)
    (fz_spec, coord_specs, hi_blk, ui_blk, be_blk, gsn_blk, gwe_blk,
     ssn_blk, swe_blk) = _cov_blockspecs(n, halo)

    def kernel(*refs):
        (fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
         hc_ref, uc_ref, gsn_ref, gwe_ref, b_ref,
         ho_ref, uo_ref, h0f_ref, u0f_ref, ssn_ref, swe_ref,
         *scratch) = refs

        gsn = gsn_ref[0]
        gwe = gwe_ref[0]
        damp = jnp.float32(dt * nu4)

        filt = []
        exts = []
        for fi, (int_ref, lead) in enumerate(
                ((hc_ref, ()), (uc_ref, (0,)), (uc_ref, (1,)))):
            iv = int_ref[lead + (0,)]
            fill_ghosts(scratch[fi], iv, gsn, gwe, fi)
            fv = _nu4_filtered_value(
                xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
                scratch[fi][:], iv, n=n, halo=halo, d=d,
                radius=radius, damp=damp)
            # Filtered interior + unfiltered ghosts: the O(damp) seam
            # inconsistency documented in the section comment.
            scratch[fi][i0:i1, i0:i1] = fv
            filt.append(fv)
            exts.append(scratch[fi][:])

        fz = (fz_ref[0, 0, 0], fz_ref[0, 0, 1], fz_ref[0, 0, 2])
        ssn = wide(gsn[6 * h : 6 * h + 2])
        swe = wide(gwe[:, 6 * h : 6 * h + 2])
        dh, dua, dub = rhs_core_cov(
            fz, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
            exts[0], exts[1], exts[2], b_ref[0], ssn, swe,
            n=n, halo=halo, d=d, radius=radius,
            gravity=gravity, omega=omega, recon=recon,
            seam_scratch=(scratch[3], scratch[4]),
            sym_prescaled=True, precision=precision,
        )

        fg = jnp.float32(dt)                 # stage 1: a = 0, b = 1
        for fi, (tend, out_ref, base_ref, lead) in enumerate(
                ((dh, ho_ref, h0f_ref, ()),
                 (dua, uo_ref, u0f_ref, (0,)),
                 (dub, uo_ref, u0f_ref, (1,)))):
            int_new = filt[fi] + fg * tend
            out_ref[lead + (0,)] = int_new
            base_ref[lead + (0,)] = filt[fi]
            emit_strips(ssn_ref, swe_ref, int_new, fi)

    call = pl.pallas_call(
        kernel,
        grid_spec=pl.GridSpec(
            grid=(6,),
            in_specs=[fz_spec] + coord_specs
                     + [hi_blk, ui_blk, gsn_blk, gwe_blk, be_blk],
            out_specs=[hi_blk, ui_blk, hi_blk, ui_blk, ssn_blk, swe_blk],
            scratch_shapes=[pltpu.VMEM((m, m), jnp.float32)
                            for _ in range(3)]
                           + [pltpu.VMEM((n, n + 1), jnp.float32),
                              pltpu.VMEM((n + 1, n), jnp.float32)],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((2, 6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((2, 6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((6, 6 * h, n), sdt),
            jax.ShapeDtypeStruct((6, n, 6 * h), sdt),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    def stage1f(hc, uc, gsn, gwe, b_ext):
        return tuple(call(frames_z, x_row, xf_row, x_col, xf_col,
                          hc, uc, gsn, gwe, b_ext))

    return stage1f


def make_fused_ssprk3_cov_refused_nu4(
    grid,
    gravity: float,
    omega: float,
    dt: float,
    b_ext,
    nu4: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    precision=None,
):
    """``step(y, t) -> y``: the re-fused del^4 stepper — 3 kernels + 3
    routes per step (the split form's 4 + 4 with the filter commuted
    into stage 1; see the section comment for the equivalence story).
    Carry/router identical to :func:`make_fused_ssprk3_cov_compact`
    (prescaled sym rows); composes with the stage precision policy, and
    with temporal blocking via the caller's generic exact-fusion wrap
    (``stepping.blocked`` — the filter is inside the stage, so blocking
    adds no extra routes).  No ``interval`` support: filter-cycling
    stays on the split path.
    """
    from .swe_step import SSPRK3_COEFFS

    precision = resolve_stage_precision(precision)
    route = make_cov_strip_router_split(grid, prescale_sym=True,
                                        precision=precision)
    stage1f = make_cov_stage_refused_nu4(
        grid, gravity, omega, dt, nu4, scheme=scheme, limiter=limiter,
        interpret=interpret, precision=precision)
    mk = lambda a, b: make_cov_stage_compact(
        grid.n, grid.halo, float(grid.dalpha), float(grid.radius),
        gravity, omega, dt, a, b, scheme=scheme, limiter=limiter,
        interpret=interpret, seam=True, sym_prescaled=True,
        precision=precision,
    )
    (_, _), (a2, b2), (a3, b3) = SSPRK3_COEFFS
    stage2 = mk(a2, b2)
    stage3 = mk(a3, b3)

    def step1(y, t):
        del t
        with named_scope("rk_stage1_nu4"):
            gsn, gwe = route(y["strips_sn"], y["strips_we"])
            h1, u1, h0f, u0f, sn1, we1 = stage1f(y["h"], y["u"],
                                                 gsn, gwe, b_ext)
        with named_scope("rk_stage2"):
            gsn, gwe = route(sn1, we1)
            h2, u2, sn2, we2 = stage2(h0f, u0f, h1, u1, gsn, gwe, b_ext)
        with named_scope("rk_stage3"):
            gsn, gwe = route(sn2, we2)
            h3, u3, sn3, we3 = stage3(h0f, u0f, h2, u2, gsn, gwe, b_ext)
        return {"h": h3, "u": u3, "strips_sn": sn3, "strips_we": we3}

    return step1


