"""Fused Pallas TPU kernel for the shallow-water RHS.

The reference's numerics are memory-bound ("Traditional FV-PLR ... AI ~0.25
flops/byte", deck p.19), so the TPU-native answer is *fusion*: one Pallas
kernel per cubed-sphere face computes the complete SWE right-hand side —
contravariant face velocities, PLR-upwind fluxes, divergence, vorticity,
Bernoulli gradient, Coriolis — in VMEM, reading the (already ghost-filled)
state exactly once from HBM and writing only the tendencies.  No stencil
intermediate ever round-trips through HBM.

Geometry is not read from memory at all: the equiangular metric is rank-1
separable (see :class:`jaxstream.geometry.cubed_sphere.LazyCubedSphereGrid`),
so the kernel rebuilds every basis vector from two (1, M) gnomonic
coordinate rows plus a per-face 3x3 frame in SMEM — a few dozen VPU flops
per cell in exchange for ~100 MB/step of HBM traffic.

Numerics are identical (to f32 roundoff) to the pure-JAX path in
:mod:`jaxstream.ops.fv` — the PLR/PPM reconstructions are literally the
same code (:mod:`jaxstream.ops.reconstruct` is axis-agnostic jnp and traces
fine inside a Pallas kernel).  The pure-JAX path stays the reference
implementation and the parity-test oracle (SURVEY.md §7: Pallas kernels
"flag-switched, numerics-identical").
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...utils.jax_compat import tpu_compiler_params

from ...geometry.cubed_sphere import FACE_AXES, extended_coords
from ..reconstruct import plr_face_states, ppm_face_states

__all__ = ["make_swe_rhs_pallas", "rhs_core", "rhs_core_fast", "coord_rows",
           "pick_recon"]


def _frame_scalars(ref, k):
    """Read one 3-vector of a face frame from SMEM as Python-level scalars."""
    return ref[0, k, 0], ref[0, k, 1], ref[0, k, 2]


def _basis(xr, yc, c0, cx, cy, radius, need):
    """Metric quantities on the grid xr x yc (broadcast (1,mx) x (my,1)).

    ``c0``/``cx``/``cy`` are tuples of 3 scalars (the face frame).  Returns
    a dict restricted to ``need`` — everything is closed-form in
    X = tan(alpha), Y = tan(beta) (same math as LazyCubedSphereGrid._basis,
    specialized to scalar frame components so it vectorizes on the VPU
    without a leading component axis).
    """
    one = jnp.float32(1.0)
    R = jnp.float32(radius)
    x2 = xr * xr
    y2 = yc * yc
    rho2 = one + x2 + y2
    # rsqrt + reciprocal-multiply forms throughout: TPU VPU divides and
    # sqrts are multi-cycle, and this basis is recomputed per RK stage
    # (cheaper than streaming 20+ precomputed metric fields from HBM, but
    # only if the transcendental count stays minimal).
    inv_rho = jax.lax.rsqrt(rho2)
    inv_rho2 = inv_rho * inv_rho
    dxda = one + x2
    dydb = one + y2

    out = {}
    p = [c0[i] + xr * cx[i] + yc * cy[i] for i in range(3)]
    rhat = [p[i] * inv_rho for i in range(3)]
    if "rhat" in need:
        out["rhat"] = rhat
    if "sqrtg" in need:
        out["sqrtg"] = R * R * dxda * dydb * inv_rho * inv_rho2
    if "e" in need or "a" in need:
        pcx = rhat[0] * cx[0] + rhat[1] * cx[1] + rhat[2] * cx[2]
        pcy = rhat[0] * cy[0] + rhat[1] * cy[1] + rhat[2] * cy[2]
        fa = R * dxda * inv_rho
        fb = R * dydb * inv_rho
        e_a = [fa * (cx[i] - rhat[i] * pcx) for i in range(3)]
        e_b = [fb * (cy[i] - rhat[i] * pcy) for i in range(3)]
        if "e" in need:
            out["e_a"] = e_a
            out["e_b"] = e_b
        if "a" in need:
            # Closed-form 2x2 inverse metric of the equiangular map.
            R2 = R * R
            inv_rho4 = inv_rho2 * inv_rho2
            gcom = R2 * dxda * dydb * inv_rho4
            gaa = gcom * dxda
            gbb = gcom * dydb
            gab = -gcom * xr * yc
            inv_det = one / (gaa * gbb - gab * gab)
            inv_aa = gbb * inv_det
            inv_ab = -gab * inv_det
            inv_bb = gaa * inv_det
            out["a_a"] = [inv_aa * e_a[i] + inv_ab * e_b[i] for i in range(3)]
            out["a_b"] = [inv_ab * e_a[i] + inv_bb * e_b[i] for i in range(3)]
    return out


def pick_recon(scheme: str, halo: int, n: int, limiter: str):
    """Face-state reconstruction for the kernels (PLR default, PPM option)."""
    if scheme == "ppm":
        return functools.partial(ppm_face_states, h=halo, n=n)
    return functools.partial(plr_face_states, h=halo, n=n, limiter=limiter)


def coord_rows(n: int, halo: int):
    """Gnomonic coordinate rows/cols for kernel broadcast, plus face frames.

    Returns ``(x_row, xf_row, x_col, xf_col, frames)`` — the (1, M)/(M, 1)
    tan-coordinate arrays and the (6, 3, 3) face-frame table (same source
    of truth as the grid builders).
    """
    ac, af, _ = extended_coords(n, halo)
    x_row = jnp.asarray(np.tan(ac), jnp.float32)[None, :]     # (1, M)
    xf_row = jnp.asarray(np.tan(af), jnp.float32)[None, :]    # (1, M)
    x_col = jnp.asarray(np.tan(ac), jnp.float32)[:, None]     # (M, 1)
    xf_col = jnp.asarray(np.tan(af), jnp.float32)[:, None]    # (M, 1)
    frames = jnp.asarray(FACE_AXES, jnp.float32)              # (6, 3, 3)
    return x_row, xf_row, x_col, xf_col, frames


def rhs_core(frame_ref, xr, xfr, yc, yfc, hf, v, bf, *,
             n, halo, d, radius, gravity, omega, recon):
    """One face's complete SWE right-hand side, as traceable kernel math.

    ``hf``/``bf`` are (M, M) values, ``v`` a list of 3 (M, M) components
    (ghosts filled); returns ``(dh, [dv0, dv1, dv2])`` interior (n, n)
    tendencies.  Shared by the plain-RHS kernel and the fused SSPRK3 stage
    kernel (:mod:`jaxstream.ops.pallas.swe_step`).
    """
    h0, h1 = halo, halo + n
    inv2d = 1.0 / (2.0 * d)
    c0 = _frame_scalars(frame_ref, 0)
    cx = _frame_scalars(frame_ref, 1)
    cy = _frame_scalars(frame_ref, 2)
    g = jnp.float32(gravity)
    two_omega = jnp.float32(2.0 * omega)

    # ---- continuity: dh = -div(h v), PLR-upwind flux form ------------
    # x-faces i = h0..h1 on interior rows: coords (xf cols, center rows).
    bx = _basis(xfr[:, h0:h1 + 1], yc[h0:h1], c0, cx, cy, radius,
                need=("a", "sqrtg"))
    vxf = [0.5 * (v[i][h0:h1, h0 - 1:h1] + v[i][h0:h1, h0:h1 + 1])
           for i in range(3)]
    ux = (vxf[0] * bx["a_a"][0] + vxf[1] * bx["a_a"][1]
          + vxf[2] * bx["a_a"][2])                       # (n, n+1)
    qx = hf[h0:h1, :]                                    # (n, M)
    qL, qR = recon(qx, -1)
    fx = bx["sqrtg"] * (jnp.maximum(ux, 0.0) * qL
                        + jnp.minimum(ux, 0.0) * qR)     # (n, n+1)

    # y-faces.
    by = _basis(xr[:, h0:h1], yfc[h0:h1 + 1], c0, cx, cy, radius,
                need=("a", "sqrtg"))
    vyf = [0.5 * (v[i][h0 - 1:h1, h0:h1] + v[i][h0:h1 + 1, h0:h1])
           for i in range(3)]
    uy = (vyf[0] * by["a_b"][0] + vyf[1] * by["a_b"][1]
          + vyf[2] * by["a_b"][2])                       # (n+1, n)
    qy = hf[:, h0:h1]                                    # (M, n)
    qL, qR = recon(qy, -2)
    fy = by["sqrtg"] * (jnp.maximum(uy, 0.0) * qL
                        + jnp.minimum(uy, 0.0) * qR)     # (n+1, n)

    bc = _basis(xr[:, h0:h1], yc[h0:h1], c0, cx, cy, radius,
                need=("rhat", "sqrtg", "a"))
    inv_sg = 1.0 / bc["sqrtg"]
    inv_sg_d = inv_sg * jnp.float32(1.0 / d)
    dh = -((fx[:, 1:] - fx[:, :-1]) + (fy[1:, :] - fy[:-1, :])) * inv_sg_d

    # ---- momentum: vector-invariant with Cartesian velocity ----------
    # Band = interior +- 1 ring, for the centered first derivatives.
    b0, b1 = h0 - 1, h1 + 1
    bb = _basis(xr[:, b0:b1], yc[b0:b1], c0, cx, cy, radius, need=("e",))
    vb_band = [v[i][b0:b1, b0:b1] for i in range(3)]     # (n+2, n+2)
    va = (vb_band[0] * bb["e_a"][0] + vb_band[1] * bb["e_a"][1]
          + vb_band[2] * bb["e_a"][2])
    vbeta = (vb_band[0] * bb["e_b"][0] + vb_band[1] * bb["e_b"][1]
             + vb_band[2] * bb["e_b"][2])
    # zeta = (d vbeta/d alpha - d va/d beta) / sqrtg, interior cells.
    dvb_da = (vbeta[1:-1, 2:] - vbeta[1:-1, :-2]) * jnp.float32(inv2d)
    dva_db = (va[2:, 1:-1] - va[:-2, 1:-1]) * jnp.float32(inv2d)
    zeta = (dvb_da - dva_db) * inv_sg

    # Bernoulli function on the band: g (h + b) + |v|^2 / 2.
    ke = 0.5 * (vb_band[0] * vb_band[0] + vb_band[1] * vb_band[1]
                + vb_band[2] * vb_band[2])
    bern = g * (hf[b0:b1, b0:b1] + bf[b0:b1, b0:b1]) + ke
    dpa = (bern[1:-1, 2:] - bern[1:-1, :-2]) * jnp.float32(inv2d)
    dpb = (bern[2:, 1:-1] - bern[:-2, 1:-1]) * jnp.float32(inv2d)

    k = bc["rhat"]                                       # interior khat
    fcor = two_omega * k[2]
    absv = zeta + fcor

    vi = [v[i][h0:h1, h0:h1] for i in range(3)]
    # Tangentialize, then k x v, then assemble and re-project.
    vdotk = vi[0] * k[0] + vi[1] * k[1] + vi[2] * k[2]
    vt = [vi[i] - k[i] * vdotk for i in range(3)]
    kxv = [k[1] * vt[2] - k[2] * vt[1],
           k[2] * vt[0] - k[0] * vt[2],
           k[0] * vt[1] - k[1] * vt[0]]
    a_a, a_b = bc["a_a"], bc["a_b"]
    dv = [-absv * kxv[i] - (a_a[i] * dpa + a_b[i] * dpb)
          for i in range(3)]
    dvdotk = dv[0] * k[0] + dv[1] * k[1] + dv[2] * k[2]
    return dh, [dv[i] - k[i] * dvdotk for i in range(3)]


def _fast_frame(xr, yc, radius):
    """Scalar metric fields from orthonormal-frame closed forms.

    The face frames (c0, cx, cy) are orthonormal, which collapses the
    general basis algebra: ``rhat.cx = X/rho``, ``rhat.cy = Y/rho``,
    ``rhat.c0 = 1/rho``, and the inverse metric is closed-form
    (``g^aa = rho^2/(R^2 (1+X^2))``, ``g^bb = rho^2/(R^2 (1+Y^2))``,
    ``g^ab = X Y rho^2/(R^2 (1+X^2)(1+Y^2))``; derived from
    ``det g = (sqrtg)^2`` with ``(1+X^2)(1+Y^2) = rho^2 + X^2 Y^2``).
    Everything divides only on the 1-D coordinate rows/cols (negligible),
    so the per-cell cost is ~a dozen mul/adds plus one rsqrt — ~5x fewer
    VPU flops than the general :func:`_basis` path, which matters because
    the fused kernels recompute the metric every RK stage.

    ``xr``: (1, mx) row of X = tan(alpha); ``yc``: (my, 1) col of Y.
    """
    one = jnp.float32(1.0)
    R = jnp.float32(radius)
    R2 = R * R
    x2r = xr * xr
    y2c = yc * yc
    dxda_r = one + x2r                       # (1, mx) rows
    dydb_c = one + y2c                       # (my, 1) cols
    rho2 = dxda_r + y2c                      # 1 + X^2 + Y^2
    inv_rho = jax.lax.rsqrt(rho2)
    inv_rho2 = inv_rho * inv_rho
    inv_R2dxda_r = one / (R2 * dxda_r)       # 1-D divides only
    inv_dydb_c = one / dydb_c
    sg_row = R2 * dxda_r
    return {
        "x": xr, "y": yc,
        "inv_rho": inv_rho, "inv_rho2": inv_rho2,
        "fa": (R * dxda_r) * inv_rho,
        "fb": (R * dydb_c) * inv_rho,
        "inv_aa": rho2 * inv_R2dxda_r,
        "inv_bb": (rho2 * inv_R2dxda_r) * (dxda_r * inv_dydb_c),
        "inv_ab": rho2 * ((xr * inv_R2dxda_r) * (yc * inv_dydb_c)),
        "sqrtg": (sg_row * dydb_c) * (inv_rho2 * inv_rho),
        "inv_sqrtg": ((one / sg_row) * inv_dydb_c) * (rho2 * rho2 * inv_rho),
        # Flux-form (sqrtg-folded) inverse metric: the continuity flux
        # needs sqrtg * g^ij, whose closed forms are *cheaper* than either
        # factor — sqrtg g^aa = (1+Y^2)/rho, sqrtg g^bb = (1+X^2)/rho,
        # sqrtg g^ab = X Y / rho.  (Unused entries are pruned at trace
        # time, so the extra entries cost nothing where not consumed.)
        "fg_aa": dydb_c * inv_rho,
        "fg_bb": dxda_r * inv_rho,
        "fg_ab": (xr * yc) * inv_rho,
    }


def rhs_core_fast(frame_ref, xr, xfr, yc, yfc, hf, v, bf, *,
                  n, halo, d, radius, gravity, omega, recon):
    """Flop-lean twin of :func:`rhs_core` (same discretization).

    Identical stencils and upwinding; the metric algebra runs through
    :func:`_fast_frame` scalar forms (v.e_a, v.a_a etc. as scalar
    combinations of the three constant-frame dot products) instead of
    materializing 3-vector bases.  Agreement with :func:`rhs_core` is
    f32 op-reordering roundoff (tests/test_fused_step.py::test_fast_core_parity
    compares the two cores directly; the oracle-path parity tests cover it
    end to end).
    """
    h0, h1 = halo, halo + n
    inv2d = jnp.float32(1.0 / (2.0 * d))
    c0 = _frame_scalars(frame_ref, 0)
    cx = _frame_scalars(frame_ref, 1)
    cy = _frame_scalars(frame_ref, 2)
    g = jnp.float32(gravity)
    two_omega = jnp.float32(2.0 * omega)

    def dots(vl):
        """(v.c0, v.cx, v.cy) — the only 3-vector contractions needed."""
        return (
            vl[0] * c0[0] + vl[1] * c0[1] + vl[2] * c0[2],
            vl[0] * cx[0] + vl[1] * cx[1] + vl[2] * cx[2],
            vl[0] * cy[0] + vl[1] * cy[1] + vl[2] * cy[2],
        )

    def covariant(F, d0, dxx, dyy):
        """(v.e_a, v.e_b, v.P) from the frame dots."""
        vp = d0 + F["x"] * dxx + F["y"] * dyy
        u = vp * F["inv_rho2"]
        vea = F["fa"] * (dxx - F["x"] * u)
        veb = F["fb"] * (dyy - F["y"] * u)
        return vea, veb, vp

    # ---- continuity ------------------------------------------------------
    Fx = _fast_frame(xfr[:, h0:h1 + 1], yc[h0:h1], radius)
    vxf = [0.5 * (v[i][h0:h1, h0 - 1:h1] + v[i][h0:h1, h0:h1 + 1])
           for i in range(3)]
    d0, dxx, dyy = dots(vxf)
    vea, veb, _ = covariant(Fx, d0, dxx, dyy)
    ux = Fx["inv_aa"] * vea + Fx["inv_ab"] * veb       # v . a_a
    qL, qR = recon(hf[h0:h1, :], -1)
    fx = Fx["sqrtg"] * (jnp.maximum(ux, 0.0) * qL
                        + jnp.minimum(ux, 0.0) * qR)

    Fy = _fast_frame(xr[:, h0:h1], yfc[h0:h1 + 1], radius)
    vyf = [0.5 * (v[i][h0 - 1:h1, h0:h1] + v[i][h0:h1 + 1, h0:h1])
           for i in range(3)]
    d0, dxx, dyy = dots(vyf)
    vea, veb, _ = covariant(Fy, d0, dxx, dyy)
    uy = Fy["inv_ab"] * vea + Fy["inv_bb"] * veb       # v . a_b
    qL, qR = recon(hf[:, h0:h1], -2)
    fy = Fy["sqrtg"] * (jnp.maximum(uy, 0.0) * qL
                        + jnp.minimum(uy, 0.0) * qR)

    Fc = _fast_frame(xr[:, h0:h1], yc[h0:h1], radius)
    inv_sg_d = Fc["inv_sqrtg"] * jnp.float32(1.0 / d)
    dh = -((fx[:, 1:] - fx[:, :-1]) + (fy[1:, :] - fy[:-1, :])) * inv_sg_d

    # ---- momentum --------------------------------------------------------
    b0, b1 = h0 - 1, h1 + 1
    Fb = _fast_frame(xr[:, b0:b1], yc[b0:b1], radius)
    vb = [v[i][b0:b1, b0:b1] for i in range(3)]
    d0, dxx, dyy = dots(vb)
    va, vbeta, _ = covariant(Fb, d0, dxx, dyy)
    dvb_da = (vbeta[1:-1, 2:] - vbeta[1:-1, :-2]) * inv2d
    dva_db = (va[2:, 1:-1] - va[:-2, 1:-1]) * inv2d
    zeta = (dvb_da - dva_db) * Fc["inv_sqrtg"]

    ke = 0.5 * (vb[0] * vb[0] + vb[1] * vb[1] + vb[2] * vb[2])
    bern = g * (hf[b0:b1, b0:b1] + bf[b0:b1, b0:b1]) + ke
    dpa = (bern[1:-1, 2:] - bern[1:-1, :-2]) * inv2d
    dpb = (bern[2:, 1:-1] - bern[:-2, 1:-1]) * inv2d

    # grad = (a_a dpa + a_b dpb) expressed in the constant frame:
    # A cx + B cy + C c0 with scalar coefficient fields.
    ca = Fc["inv_aa"] * dpa + Fc["inv_ab"] * dpb
    cb = Fc["inv_ab"] * dpa + Fc["inv_bb"] * dpb
    uu = ca * Fc["fa"]
    ww = cb * Fc["fb"]
    tt = (uu * Fc["x"] + ww * Fc["y"]) * Fc["inv_rho2"]
    A = uu - tt * Fc["x"]
    B = ww - tt * Fc["y"]
    C = -tt
    grad = [A * cx[i] + B * cy[i] + C * c0[i] for i in range(3)]

    # rhat at centers, componentwise from the frame.
    ir = Fc["inv_rho"]
    k = [ir * (c0[i] + Fc["x"] * cx[i] + Fc["y"] * cy[i]) for i in range(3)]
    fcor = two_omega * k[2]
    absv = zeta + fcor

    vi = [v[i][h0:h1, h0:h1] for i in range(3)]
    vdotk = vi[0] * k[0] + vi[1] * k[1] + vi[2] * k[2]
    vt = [vi[i] - k[i] * vdotk for i in range(3)]
    kxv = [k[1] * vt[2] - k[2] * vt[1],
           k[2] * vt[0] - k[0] * vt[2],
           k[0] * vt[1] - k[1] * vt[0]]
    dv = [-absv * kxv[i] - grad[i] for i in range(3)]
    dvdotk = dv[0] * k[0] + dv[1] * k[1] + dv[2] * k[2]
    return dh, [dv[i] - k[i] * dvdotk for i in range(3)]


def make_swe_rhs_pallas(
    n: int,
    halo: int,
    dalpha: float,
    radius: float,
    gravity: float,
    omega: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
):
    """Build ``rhs(h_ext, v_ext, b_ext) -> (dh, dv)`` as one fused kernel.

    Inputs are extended ``(6, M, M)`` / ``(3, 6, M, M)`` fields with ghosts
    already filled; outputs are interior tendencies ``(6, n, n)`` /
    ``(3, 6, n, n)`` — drop-in for the stencil section of
    :meth:`jaxstream.models.shallow_water.ShallowWater.rhs`.
    """
    m = n + 2 * halo
    d = float(dalpha)
    recon = pick_recon(scheme, halo, n, limiter)
    x_row, xf_row, x_col, xf_col, frames = coord_rows(n, halo)

    def kernel(frame_ref, xr_ref, xfr_ref, yc_ref, yfc_ref, h_ref, v_ref,
               b_ref, dh_ref, dv_ref):
        hf = h_ref[0]                        # (M, M)
        v = [v_ref[0, 0], v_ref[1, 0], v_ref[2, 0]]
        bf = b_ref[0]
        dh, dv = rhs_core(
            frame_ref, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
            hf, v, bf, n=n, halo=halo, d=d, radius=radius,
            gravity=gravity, omega=omega, recon=recon,
        )
        dh_ref[0] = dh
        for i in range(3):
            dv_ref[i, 0] = dv[i]

    grid_spec = pl.GridSpec(
        grid=(6,),
        in_specs=[
            pl.BlockSpec((1, 3, 3), lambda f: (f, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m, m), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 1, m, m), lambda f: (0, f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m, m), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n, n), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 1, n, n), lambda f: (0, f, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
    )

    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((3, 6, n, n), jnp.float32),
        ],
        # Whole-face blocks at C384 need ~26 MB of scoped VMEM for the
        # stencil intermediates — above the compiler's 16 MB default but
        # well inside the chip's 128 MB VMEM.  (C768+ would need row-band
        # tiling instead.)
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    def rhs(h_ext, v_ext, b_ext) -> Tuple[jax.Array, jax.Array]:
        dh, dv = call(frames, x_row, xf_row, x_col, xf_col,
                      h_ext, v_ext, b_ext)
        return dh, dv

    return rhs
