"""Pallas TPU kernels for the hot FV stencils.

The performance-critical stencil path (SURVEY.md §7 step 6: "flux
-divergence, Coriolis, PPM advection stencils as Pallas TPU kernels behind
a flag (pure-JAX fallback retained for parity testing)").
"""

from .swe_rhs import make_swe_rhs_pallas

__all__ = ["make_swe_rhs_pallas"]
