"""Finite-volume differential operators on the cubed sphere.

The tile-local stencil layer below the halo exchange (SURVEY.md §1.2
"Numerics"; the reference only *describes* it — deck p.4: "Finite Volume
(PLR) Method ... 2nd Order").  All operators:

  * take extended fields ``(..., 6, M, M)`` whose ghosts have been filled
    by :func:`jaxstream.parallel.halo.make_halo_exchanger`,
  * return interior-shaped results ``(..., 6, n, n)``,
  * are pure elementwise/stencil math with static shapes — they trace into
    a single fused XLA computation under the top-level step ``jit`` and are
    the profile targets for the Pallas kernels in
    :mod:`jaxstream.ops.pallas` (flag-switched, numerics-identical).

Velocity is a Cartesian 3-vector ``(3, 6, M, M)`` (the reference's
"Cartesian Velocity Exchange" design, deck p.18): panel-local contravariant
components are formed on the fly by dotting with the grid's dual basis, so
no vector rotation is needed at panel edges.
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp

from ..geometry.connectivity import (
    EDGE_E,
    EDGE_N,
    EDGE_S,
    EDGE_W,
    build_connectivity,
    edge_pairs,
)
from ..geometry.cubed_sphere import CubedSphereGrid
from .reconstruct import _sl, plr_face_states, ppm_face_states

__all__ = [
    "embed_interior",
    "contravariant",
    "covariant_components",
    "covariant_face_normal_velocity",
    "flux_divergence",
    "flux_divergence_faces",
    "gradient",
    "vorticity",
    "vorticity_cov",
    "laplacian",
    "kinetic_energy",
]


def embed_interior(grid: CubedSphereGrid, arr, fill=0.0):
    """Pad an interior ``(..., 6, n, n)`` array out to ``(..., 6, M, M)``."""
    h = grid.halo
    pad = [(0, 0)] * (arr.ndim - 2) + [(h, h), (h, h)]
    return jnp.pad(arr, pad, constant_values=fill)


def contravariant(grid: CubedSphereGrid, v):
    """Contravariant components (u^alpha, u^beta) of a Cartesian vector.

    ``v``: (3, 6, M, M) at cell centers -> two (6, M, M) arrays.
    """
    ua = jnp.sum(v * grid.a_a, axis=0)
    ub = jnp.sum(v * grid.a_b, axis=0)
    return ua, ub


def covariant_components(grid: CubedSphereGrid, v):
    """Covariant components ``(v.e_a, v.e_b)`` of a Cartesian vector.

    ``v``: (3, 6, M, M) -> (2, 6, M, M).  The prognostic representation of
    :class:`jaxstream.models.CovariantShallowWater`.
    """
    return jnp.stack([
        jnp.sum(v * grid.e_a, axis=0),
        jnp.sum(v * grid.e_b, axis=0),
    ])


def covariant_face_normal_velocity(grid: CubedSphereGrid, u,
                                   symmetrize: bool = True):
    """Face-normal contravariant velocity from covariant components.

    ``u``: (2, 6, M, M) covariant ``(u_a, u_b)`` at centers.  Averages the
    covariant components to the face, then raises the index with the
    *face* inverse metric (metric-exact at the face — the covariant twin
    of :func:`_face_normal_velocity`).  Returns ``(ux, uy)`` shaped
    (6, n, n+1) / (6, n+1, n).

    Unlike the Cartesian route (where ghost copies make both panels'
    panel-edge normal velocities bitwise equal), the two panels sharing an
    edge raise the index through *different* covariant components and face
    metrics, so their edge values differ at truncation level and mass
    would leak at seams.  ``symmetrize`` (default) replaces both sides'
    edge-face normal velocity with the averaged outward value — the
    Putman & Lin (2007) edge-matching idea applied one level earlier than
    :func:`flux_divergence`'s ``conservative_edges`` — restoring exact
    conservation while keeping the flux upwinding self-consistent.
    """
    h, n = grid.halo, grid.n
    ubar = 0.5 * (_sl(u, h - 1, h + n, -1) + _sl(u, h, h + n + 1, -1))
    ubar = _sl(ubar, h, h + n, -2)
    iaa = _sl(_sl(grid.ginv_aa_xf, h, h + n + 1, -1), h, h + n, -2)
    iab = _sl(_sl(grid.ginv_ab_xf, h, h + n + 1, -1), h, h + n, -2)
    ux = iaa * ubar[0] + iab * ubar[1]
    vbar = 0.5 * (_sl(u, h - 1, h + n, -2) + _sl(u, h, h + n + 1, -2))
    vbar = _sl(vbar, h, h + n, -1)
    iab2 = _sl(_sl(grid.ginv_ab_yf, h, h + n + 1, -2), h, h + n, -1)
    ibb = _sl(_sl(grid.ginv_bb_yf, h, h + n + 1, -2), h, h + n, -1)
    uy = iab2 * vbar[0] + ibb * vbar[1]
    if symmetrize:
        # _symmetrize_edge_fluxes is shape-generic over (6,n,n+1)/(6,n+1,n)
        # boundary strips; the outward-sign algebra is identical.
        ux, uy = _symmetrize_edge_fluxes(ux, uy, n)
    return ux, uy


def vorticity_cov(grid: CubedSphereGrid, u):
    """Relative vorticity directly from covariant components.

    zeta = (d u_b/d alpha - d u_a/d beta) / sqrt(g); no basis dot products
    needed — the covariant-formulation advantage.  ``u``: (2, 6, M, M) ->
    (6, n, n).
    """
    h, n, d = grid.halo, grid.n, grid.dalpha
    dub_da = (_sl(_sl(u[1], h + 1, h + n + 1, -1), h, h + n, -2)
              - _sl(_sl(u[1], h - 1, h + n - 1, -1), h, h + n, -2)) / (2 * d)
    dua_db = (_sl(_sl(u[0], h + 1, h + n + 1, -2), h, h + n, -1)
              - _sl(_sl(u[0], h - 1, h + n - 1, -2), h, h + n, -1)) / (2 * d)
    return (dub_da - dua_db) / grid.interior(grid.sqrtg)


def _face_normal_velocity(grid: CubedSphereGrid, v):
    """Contravariant normal velocity at interior-bounding faces.

    Returns ``(ux, uy)``: ``ux`` is u^alpha at the n+1 x-faces of each
    interior row, shape (6, n, n+1); ``uy`` is u^beta at y-faces,
    shape (6, n+1, n).  Cell-centered Cartesian ``v`` is averaged to the
    face then dotted with the face dual basis (metric-exact at the face).
    """
    h, n = grid.halo, grid.n
    # x-faces: average v over cells i-1, i for i = h..h+n; rows interior.
    vxf = 0.5 * (_sl(v, h - 1, h + n, -1) + _sl(v, h, h + n + 1, -1))
    vxf = _sl(vxf, h, h + n, -2)
    aaxf = _sl(_sl(grid.a_a_xf, h, h + n + 1, -1), h, h + n, -2)
    ux = jnp.sum(vxf * aaxf, axis=0)
    # y-faces.
    vyf = 0.5 * (_sl(v, h - 1, h + n, -2) + _sl(v, h, h + n + 1, -2))
    vyf = _sl(vyf, h, h + n, -1)
    abyf = _sl(_sl(grid.a_b_yf, h, h + n + 1, -2), h, h + n, -1)
    uy = jnp.sum(vyf * abyf, axis=0)
    return ux, uy


@lru_cache(maxsize=1)
def _edge_pair_table():
    return edge_pairs(build_connectivity())


# Outward-normal sign of the stored +alpha/+beta face flux at each edge.
_OUT_SIGN = {EDGE_S: -1.0, EDGE_W: -1.0, EDGE_N: 1.0, EDGE_E: 1.0}


def _read_edge_flux(fx, fy, face, edge, n):
    """Panel-boundary face flux as a canonical along-edge strip (n,)."""
    if edge == EDGE_S:
        return fy[..., face, 0, :]
    if edge == EDGE_N:
        return fy[..., face, n, :]
    if edge == EDGE_W:
        return fx[..., face, :, 0]
    if edge == EDGE_E:
        return fx[..., face, :, n]
    raise ValueError(edge)


def _write_edge_flux(fx, fy, face, edge, strip, n):
    if edge == EDGE_S:
        return fx, fy.at[..., face, 0, :].set(strip)
    if edge == EDGE_N:
        return fx, fy.at[..., face, n, :].set(strip)
    if edge == EDGE_W:
        return fx.at[..., face, :, 0].set(strip), fy
    if edge == EDGE_E:
        return fx.at[..., face, :, n].set(strip), fy
    raise ValueError(edge)


def _symmetrize_edge_fluxes(fx, fy, n):
    """Make panel-edge fluxes exactly antisymmetric across shared edges.

    Each panel computes its own boundary-face flux with its own metric and
    reconstruction; the two values for one physical edge face differ by
    O(dx^2), so mass leaks at panel seams (the reference, which computes
    fluxes per-panel after a ghost copy, has the same leak).  Replacing
    both with the average outward flux makes the scheme globally
    conservative to roundoff — the FV analogue of Putman & Lin (2007)'s
    edge-flux matching.
    """
    for link, back in _edge_pair_table():
        s_a = _read_edge_flux(fx, fy, link.face, link.edge, n)
        s_b = _read_edge_flux(fx, fy, back.face, back.edge, n)
        if link.reversed_:
            s_b = jnp.flip(s_b, axis=-1)
        out_a = _OUT_SIGN[link.edge] * s_a
        out_b = _OUT_SIGN[back.edge] * s_b
        avg = 0.5 * (out_a - out_b)
        new_a = _OUT_SIGN[link.edge] * avg
        new_b = _OUT_SIGN[back.edge] * (-avg)
        if link.reversed_:
            new_b = jnp.flip(new_b, axis=-1)
        fx, fy = _write_edge_flux(fx, fy, link.face, link.edge, new_a, n)
        fx, fy = _write_edge_flux(fx, fy, back.face, back.edge, new_b, n)
    return fx, fy


def flux_divergence(
    grid: CubedSphereGrid,
    q,
    v,
    scheme: str = "plr",
    limiter: str = "mc",
    conservative_edges: bool = False,
):
    """Divergence of the advective flux, div(q v), on interior cells.

    Flux-form FV: (1/(sqrt(g) d)) * [ delta_a(sqrt(g) u^a q*) +
    delta_b(sqrt(g) u^b q*) ] with q* the upwind PLR/PPM face state.
    ``q``: (6, M, M) extended scalar; ``v``: (3, 6, M, M) Cartesian.
    Returns (6, n, n).  Mass-conservative by construction — including
    across panel edges: ghost copies are value-exact and sqrt(g) a^alpha
    is continuous at edges, so both panels compute bitwise-matching edge
    fluxes (verified in tests).  ``conservative_edges`` additionally
    averages the two sides' edge fluxes — a no-op today, insurance for
    future interpolated (non-copy) ghost fills.
    """
    ux, uy = _face_normal_velocity(grid, v)
    return flux_divergence_faces(
        grid, q, ux, uy, scheme=scheme, limiter=limiter,
        conservative_edges=conservative_edges,
    )


def flux_divergence_faces(
    grid: CubedSphereGrid,
    q,
    ux,
    uy,
    scheme: str = "plr",
    limiter: str = "mc",
    conservative_edges: bool = False,
):
    """:func:`flux_divergence` from precomputed face-normal velocities.

    ``ux``: u^alpha at the interior-bounding x-faces, (6, n, n+1); ``uy``:
    u^beta at y-faces, (6, n+1, n) — any velocity representation that can
    produce these (Cartesian dot products, covariant components through
    the face inverse metric, prescribed winds) shares this flux path.
    """
    h, n, d = grid.halo, grid.n, grid.dalpha
    recon = ppm_face_states if scheme == "ppm" else plr_face_states
    kw = {} if scheme == "ppm" else {"limiter": limiter}

    # x-direction: restrict rows first, reconstruct along axis -1.
    qx = _sl(q, h, h + n, -2)
    qL, qR = recon(qx, -1, h, n, **kw)
    sgx = _sl(_sl(grid.sqrtg_xf, h, h + n + 1, -1), h, h + n, -2)
    fx = sgx * (jnp.maximum(ux, 0.0) * qL + jnp.minimum(ux, 0.0) * qR)

    # y-direction.
    qy = _sl(q, h, h + n, -1)
    qL, qR = recon(qy, -2, h, n, **kw)
    sgy = _sl(_sl(grid.sqrtg_yf, h, h + n + 1, -2), h, h + n, -1)
    fy = sgy * (jnp.maximum(uy, 0.0) * qL + jnp.minimum(uy, 0.0) * qR)

    if conservative_edges:
        fx, fy = _symmetrize_edge_fluxes(fx, fy, n)

    sg_c = grid.interior(grid.sqrtg)
    return (
        (_sl(fx, 1, None, -1) - _sl(fx, 0, -1, -1))
        + (_sl(fy, 1, None, -2) - _sl(fy, 0, -1, -2))
    ) / (sg_c * d)


def gradient(grid: CubedSphereGrid, psi):
    """Tangent-plane gradient of a scalar as a Cartesian 3-vector.

    ``psi``: (6, M, M) extended -> (3, 6, n, n); centered differences.
    """
    h, n, d = grid.halo, grid.n, grid.dalpha
    dpa = (_sl(_sl(psi, h + 1, h + n + 1, -1), h, h + n, -2)
           - _sl(_sl(psi, h - 1, h + n - 1, -1), h, h + n, -2)) / (2 * d)
    dpb = (_sl(_sl(psi, h + 1, h + n + 1, -2), h, h + n, -1)
           - _sl(_sl(psi, h - 1, h + n - 1, -2), h, h + n, -1)) / (2 * d)
    a_a = grid.interior(grid.a_a)
    a_b = grid.interior(grid.a_b)
    return a_a * dpa + a_b * dpb


def vorticity(grid: CubedSphereGrid, v):
    """Radial relative vorticity zeta = k . curl(v) on interior cells.

    zeta = (1/sqrt(g)) (d v_beta / d alpha - d v_alpha / d beta) with
    v_alpha = v . e_alpha the covariant components; centered differences.
    ``v``: (3, 6, M, M) -> (6, n, n).
    """
    h, n, d = grid.halo, grid.n, grid.dalpha
    va = jnp.sum(v * grid.e_a, axis=0)
    vb = jnp.sum(v * grid.e_b, axis=0)
    dvb_da = (_sl(_sl(vb, h + 1, h + n + 1, -1), h, h + n, -2)
              - _sl(_sl(vb, h - 1, h + n - 1, -1), h, h + n, -2)) / (2 * d)
    dva_db = (_sl(_sl(va, h + 1, h + n + 1, -2), h, h + n, -1)
              - _sl(_sl(va, h - 1, h + n - 1, -2), h, h + n, -1)) / (2 * d)
    return (dvb_da - dva_db) / grid.interior(grid.sqrtg)


def laplacian(grid: CubedSphereGrid, psi):
    """Laplace-Beltrami operator in conservative flux form.

    lap(psi) = (1/sqrt(g)) [ d_a( sqrt(g)(g^aa psi_a + g^ab psi_b) )
                           + d_b( sqrt(g)(g^ab psi_a + g^bb psi_b) ) ]
    with face-centered metric terms; used for diffusion and (iterated,
    with halo refills between applications) del^4 hyperdiffusion.
    ``psi``: (6, M, M) -> (6, n, n).
    """
    h, n, d = grid.halo, grid.n, grid.dalpha

    # x-faces i = h..h+n on interior rows.
    pr = _sl(psi, h, h + n, -2)                      # interior rows, all cols
    dpa = (_sl(pr, h, h + n + 1, -1) - _sl(pr, h - 1, h + n, -1)) / d
    # d psi/d beta at the x-face: average the centered row-derivative of the
    # two abutting cells.
    dpb_c = (_sl(psi, h + 1, h + n + 1, -2) - _sl(psi, h - 1, h + n - 1, -2)) / (2 * d)
    dpb_f = 0.5 * (_sl(dpb_c, h - 1, h + n, -1) + _sl(dpb_c, h, h + n + 1, -1))
    sgx = _sl(_sl(grid.sqrtg_xf, h, h + n + 1, -1), h, h + n, -2)
    iaa = _sl(_sl(grid.ginv_aa_xf, h, h + n + 1, -1), h, h + n, -2)
    iab = _sl(_sl(grid.ginv_ab_xf, h, h + n + 1, -1), h, h + n, -2)
    fx = sgx * (iaa * dpa + iab * dpb_f)

    # y-faces j = h..h+n on interior columns.
    pc = _sl(psi, h, h + n, -1)
    dpb = (_sl(pc, h, h + n + 1, -2) - _sl(pc, h - 1, h + n, -2)) / d
    dpa_c = (_sl(psi, h + 1, h + n + 1, -1) - _sl(psi, h - 1, h + n - 1, -1)) / (2 * d)
    dpa_f = 0.5 * (_sl(dpa_c, h - 1, h + n, -2) + _sl(dpa_c, h, h + n + 1, -2))
    sgy = _sl(_sl(grid.sqrtg_yf, h, h + n + 1, -2), h, h + n, -1)
    ibb = _sl(_sl(grid.ginv_bb_yf, h, h + n + 1, -2), h, h + n, -1)
    iab2 = _sl(_sl(grid.ginv_ab_yf, h, h + n + 1, -2), h, h + n, -1)
    fy = sgy * (ibb * dpb + iab2 * dpa_f)

    sg_c = grid.interior(grid.sqrtg)
    return (
        (_sl(fx, 1, None, -1) - _sl(fx, 0, -1, -1))
        + (_sl(fy, 1, None, -2) - _sl(fy, 0, -1, -2))
    ) / (sg_c * d)


def kinetic_energy(v):
    """|v|^2 / 2 for a Cartesian vector field (any trailing shape)."""
    return 0.5 * jnp.sum(v * v, axis=0)
