"""Cross / ACA low-rank approximation of implicitly-factored operands.

The LANL route to TT-compressed nonlinear terms (Danis et al. 2024,
arXiv:2408.03483 — deck p.14): instead of projecting the full operand
(randomized sketch) or forming Gram matrices (exact rounding, one eigh/
SVD per product), **adaptive cross approximation** builds a rank-k
skeleton from k actual rows and columns of the operand, chosen by
partial pivoting on the residual.  Everything is matvecs, slicing, and
argmax — no factorization kernels at all — which matters because the
N-independent eigh/SVD calls were measured to eat ~2/3 of the TT step
at N=1024 (DESIGN.md "Tensor-Train numerics"): cross removes that floor
from the quadratic-term roundings.

``aca_lowrank(P, Q, k)`` approximates ``M = P @ Q`` (never formed, with
``P (n, R)``, ``Q (R, m)`` — e.g. the Khatri-Rao factors of a product
of two rank-r fields, R = r^2) by the classic partially-pivoted ACA:

    for t < k:
        c   = M[:, j] - U V[:, j]          (residual column at pivot j)
        i   = argmax |c|   (excluding used rows)
        r   = M[i, :] - U[i] V             (residual row at pivot i)
        U[:, t] = c / r[j];  V[t] = r
        j   = argmax |r|   (excluding used columns)

After k steps ``U V ~ M`` with the standard ACA quasi-optimality (error
~ the (k+1)-th singular value up to a k-dependent factor, tight for the
smooth fields this layer carries).  All shapes static; pivot selection
is data-dependent but jit-safe (argmax + dynamic slices in a fori_loop).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["aca_lowrank", "aca_lowrank_many", "svd_lowrank",
           "rsvd_lowrank", "host_svd_lowrank"]


def svd_lowrank(P, Q, k: int, backend: str | None = None):
    """EXACT best rank-``k`` truncation of ``M = P @ Q`` (never formed):
    thin QR of ``P`` then SVD of the small ``(R, m)`` product —
    O(n R^2 + R^2 m + R m min(R, m)), one QR + one SVD per call.

    The quality tier above :func:`aca_lowrank`: ACA is quasi-optimal
    (error ~ sigma_{k+1} up to a k-dependent factor) and its pivoted
    skeleton can inject perturbations far above optimal on operands
    with slowly-decaying spectra.  Measured consequence (round 4,
    DESIGN.md stability envelope): the factored sphere SWE on
    mountain-forced TC5 C96 NaNs within 0.17-0.5 sim-days under ACA
    rounding at EVERY rank/dissipation tried, but integrates 5+ days
    with physical fields under this exact rounding — the
    "non-dissipative perturbation" that destabilized the flow was
    dominated by ACA's excess over optimal truncation, not by optimal
    truncation itself.  Factors are balanced ``sqrt(s)`` per side (the
    layer's convention).

    Backend status (round 4, measured): CPU f32/f64 run the QR+SVD
    path (LAPACK; TC5 C96 stable 8+ sim-hours in f32, 5 days in f64).
    On ACCELERATOR f32 the QR+SVD path NaNs the TC5 run within 4-8
    sim-hours — with AND without pinned matmul precision (TPU f32 QR
    loses orthogonality on near-rank-deficient operands, the same
    failure qtt.py:418-432 hit) — so that combination routes to the
    masked-Gram-eigh path below (qtt's proven f32 construction).  The
    v5e's f32 ``eigh`` then ALSO degrades at production bond sizes
    (garbage eigenbasis at bond ~100, followed by a TPU-worker crash;
    correct at bond ~20), so the svd stability tier is currently
    CPU-validated only; the TPU path stays in place as the
    best-known-construction for when TPU linalg robustness improves
    (Simulation's 'auto' picks it only for CPU runs).

    ``backend``: the platform this rounding will execute on ('cpu' /
    'tpu' / ...).  Callers that place computation explicitly (the
    panel-sharded tier's CPU mesh inside a TPU-enabled process) MUST
    pass it — the default consults the process-global
    ``jax.default_backend()``, which is where an un-pinned jit runs.
    """
    if backend is None:
        backend = jax.default_backend()
    if P.dtype == jnp.float32 and backend != "cpu":
        return _svd_lowrank_gram(P, Q, k)
    with jax.default_matmul_precision("highest"):
        Qf, Rf = jnp.linalg.qr(P)
        U, s, Vt = jnp.linalg.svd(Rf @ Q, full_matrices=False)
        kk = min(k, s.shape[0])
        rs = jnp.sqrt(s[:kk])
        A = Qf @ (U[:, :kk] * rs[None])
        B = rs[:, None] * Vt[:kk]
        if kk < k:  # zero-pad to exactly rank k (the gram path's contract)
            A = jnp.pad(A, ((0, 0), (0, k - kk)))
            B = jnp.pad(B, ((0, k - kk), (0, 0)))
        return A, B


def _svd_lowrank_gram(P, Q, k: int):
    """f32 exact-truncation path: two masked Gram eighs, no QR/SVD.

    ``M = P Q``; eigh of ``Q Q^T`` gives ``Q = S W`` with orthonormal
    rows ``W`` (masked against zero modes), so ``M = (P S) W`` and the
    best rank-k of ``M`` is the best rank-k of ``T = P S`` against
    ``W``; eigh of ``T^T T`` then yields the singular pairs.  Balanced
    ``sqrt(sigma)`` per side; zero-padded to exactly rank k."""
    fi = jnp.finfo(P.dtype)
    with jax.default_matmul_precision("highest"):
        lam_q, Eq = jnp.linalg.eigh(Q @ Q.T)            # ascending
        keep_q = lam_q > fi.eps * lam_q[-1] + fi.tiny
        sq = jnp.sqrt(jnp.where(keep_q, lam_q, 1.0))
        W = jnp.where(keep_q, 1.0 / sq, 0.0)[:, None] * (Eq.T @ Q)
        T = P @ (Eq * jnp.where(keep_q, sq, 0.0)[None, :])
        lam, E = jnp.linalg.eigh(T.T @ T)
        lam, E = lam[::-1], E[:, ::-1]
        kk = min(k, T.shape[1])
        keep = lam[:kk] > fi.eps * jnp.maximum(lam[0], 0.0) + fi.tiny
        s = jnp.sqrt(jnp.where(keep, lam[:kk], 1.0))    # sigma_i of M
        root = jnp.sqrt(s)
        A = T @ (E[:, :kk] * jnp.where(keep, root / s, 0.0)[None, :])
        B = jnp.where(keep, root, 0.0)[:, None] * (E[:, :kk].T @ W)
        if kk < k:
            A = jnp.pad(A, ((0, 0), (0, k - kk)))
            B = jnp.pad(B, ((0, k - kk), (0, 0)))
        return A, B


def _ns_orth(X, iters: int = 90):
    """Orthonormalize the columns of ``X (n, l)`` by Newton-Schulz
    polar iteration — **matmul-only**, no QR/eigh/SVD primitives.

    The cubic map ``X <- 1.5 X - 0.5 X (X^T X)`` drives every singular
    value of the Frobenius-prenormalized operand toward 1 (monotone on
    (0, sqrt(3)); ~1.5x growth per sweep for small values, quadratic
    contraction near the fixed point), so the limit is the orthogonal
    polar factor of ``X`` — same column span, orthonormal columns.
    This is the v5e-robust replacement for the f32 ``jnp.linalg.qr``
    whose orthogonality loss on near-rank-deficient operands NaN'd the
    svd rounding tier on TPU (see :func:`svd_lowrank` backend notes):
    matmuls carry none of the Householder pivoting that breaks there,
    and exactly-zero columns (rank-deficient operands, zero-padded
    factors) stay exactly zero instead of poisoning the basis.
    """
    fi = jnp.finfo(X.dtype)
    X = X / (jnp.sqrt(jnp.sum(X * X)) + fi.tiny)
    with jax.default_matmul_precision("highest"):
        def body(_, Y):
            return 1.5 * Y - 0.5 * (Y @ (Y.T @ Y))

        return jax.lax.fori_loop(0, iters, body, X)


_SKETCH_SEED = 7031  # fixed: rounding is deterministic run to run


def _balanced(A, B, k: int):
    """Rescale mode ``j`` so each side carries ``sqrt(sigma_j)`` (the
    layer's factor convention; ``sigma_j ~ |A_j| |B_j|``), zero dead
    modes, and zero-pad to exactly width ``k``.  The product ``A B`` is
    unchanged on live modes."""
    fi = jnp.finfo(A.dtype)
    na = jnp.sqrt(jnp.sum(A * A, axis=0))
    nb = jnp.sqrt(jnp.sum(B * B, axis=1))
    s = na * nb
    keep = s > fi.tiny
    root = jnp.sqrt(jnp.where(keep, s, 1.0))
    A = A * jnp.where(keep, root / jnp.maximum(na, fi.tiny), 0.0)[None, :]
    B = jnp.where(keep, root / jnp.maximum(nb, fi.tiny), 0.0)[:, None] * B
    w = A.shape[1]
    if w < k:
        A = jnp.pad(A, ((0, 0), (0, k - w)))
        B = jnp.pad(B, ((0, k - w), (0, 0)))
    return A, B


def rsvd_lowrank(P, Q, k: int, oversample: int = 8, power: int = 2,
                 subspace_iters: int = 6, ns_iters: int = 90,
                 compute_dtype=None):
    """Near-optimal rank-``k`` truncation of ``M = P @ Q`` using ONLY
    matrix multiplies — the TPU-viable stability tier (round 5).

    The exact tier (:func:`svd_lowrank`) is measured-blocked on v5e
    f32: QR loses orthogonality and ``eigh`` returns garbage at
    production bond sizes (its docstring).  This tier replaces every
    factorization primitive with Newton-Schulz polar orthogonalization
    (:func:`_ns_orth`) inside a two-stage randomized-SVD:

    1. **Range finder** (Halko-Martinsson-Tropp): a deterministic
       Gaussian sketch of width ``l = k + oversample`` gives
       ``Y = P (Q Om)``; ``power`` subspace iterations with NS
       re-orthogonalization tighten the basis ``U`` toward the top-l
       left singular space.  Oversampling keeps the *top-k* angle
       small even where the spectrum is flat at the cutoff.
    2. **Core truncation**: project ``C = (U^T P) Q`` (small,
       ``(l, m)``) and extract its top-k right basis ``V`` by NS-
       orthogonalized subspace iteration on the explicit core — cheap,
       so ``subspace_iters`` can be generous.  ``M ~ (U C V) V^T``.

    Error ~ sigma_{k+1} times a modest factor (measured against the
    exact tier in tests/test_tt_rounding_tiers.py); deterministic
    (fixed sketch key) and jit/vmap-safe.  Factors balanced
    ``sqrt(sigma)`` per side, zero-padded to exactly ``k``.
    """
    out_dtype = P.dtype
    if compute_dtype is not None:
        P = P.astype(compute_dtype)
        Q = Q.astype(compute_dtype)
    n, R = P.shape
    m = Q.shape[1]
    rmax = min(n, m, R)
    l = min(k + oversample, rmax)
    with jax.default_matmul_precision("highest"):
        # Distinct subkeys for the two independent draws: the range
        # sketch Om and the core-truncation subspace initializer V must
        # not share randomness (with one key, V's k columns replicate
        # the first k columns' pattern of Om's draw — a correlated
        # start the subspace iteration then has to work away from).
        key_om, key_v = jax.random.split(jax.random.PRNGKey(_SKETCH_SEED))
        Om = jax.random.normal(key_om, (m, l), P.dtype)
        U = _ns_orth(P @ (Q @ Om), ns_iters)
        for _ in range(power):
            Z = Q.T @ (P.T @ U)                       # (m, l)
            U = _ns_orth(P @ (Q @ Z), ns_iters)
        C = (U.T @ P) @ Q                             # (l, m)
        if l <= k:  # the basis already spans rank(M): exact, just pad
            A, B = _balanced(U, C, k)
            return A.astype(out_dtype), B.astype(out_dtype)
        V = jax.random.normal(key_v, (m, k), P.dtype)
        for _ in range(subspace_iters):
            V = _ns_orth(C.T @ (C @ V), ns_iters)
        A = U @ (C @ V)                               # (n, k)
        A, B = _balanced(A, V.T, k)
        return A.astype(out_dtype), B.astype(out_dtype)


#: Platforms whose runtimes are known to execute ``jax.pure_callback``.
#: Plugin backends (e.g. the 'axon' PJRT plugin this image uses for its
#: TPU) may lack host-callback support entirely and fail at RUN time
#: with an opaque runtime error — exactly the backends this rung is
#: pitched at, hence the explicit build-time gate below.
_HOST_CALLBACK_PLATFORMS = frozenset({"cpu", "gpu", "cuda", "rocm", "tpu"})


def host_svd_lowrank(P, Q, k: int, backend: str | None = None):
    """EXACT rank-``k`` truncation with the small factorization on the
    HOST (numpy/LAPACK, f64) via ``jax.pure_callback`` — the guaranteed
    stopgap rung for backends whose on-device linalg is unreliable.
    Bit-identical quality to the CPU svd tier; costs one host round
    trip per call (measured cost line in DESIGN.md).  Supports leading
    batch dims (numpy stacked linalg), so it vmaps via broadcast.

    .. warning:: **Requires host-callback support in the executing
       runtime.**  ``pure_callback`` is a host round trip per call: the
       device runtime must be able to pause the program and call back
       into Python.  Standard CPU/GPU/TPU runtimes can; out-of-tree
       PJRT plugin backends often cannot, and without this gate the
       failure surfaces as an obscure runtime error mid-run.  Pass
       ``backend`` (the platform this rounding will execute on — same
       contract as :func:`svd_lowrank`) when placing computation
       explicitly; the default consults ``jax.default_backend()``.
    """
    import numpy as np

    if backend is None:
        backend = jax.default_backend()
    if backend not in _HOST_CALLBACK_PLATFORMS:
        raise NotImplementedError(
            f"host_svd_lowrank executes a jax.pure_callback host round "
            f"trip, and the {backend!r} backend is not known to support "
            f"host callbacks (supported: "
            f"{sorted(_HOST_CALLBACK_PLATFORMS)}). Use rounding='rsvd' "
            f"(matmul-only, runs anywhere) or place this rounding on a "
            f"CPU mesh."
        )

    dt = P.dtype
    m = Q.shape[-1]

    def _host(p, q):
        p = np.asarray(p, np.float64)
        q = np.asarray(q, np.float64)
        Qf, Rf = np.linalg.qr(p)
        U, s, Vt = np.linalg.svd(Rf @ q, full_matrices=False)
        kk = min(k, s.shape[-1])
        rs = np.sqrt(s[..., :kk])
        A = Qf @ (U[..., :, :kk] * rs[..., None, :])
        B = rs[..., :, None] * Vt[..., :kk, :]
        if kk < k:
            pad = [(0, 0)] * (A.ndim - 1)
            A = np.pad(A, pad + [(0, k - kk)])
            B = np.pad(B, pad[:-1] + [(0, k - kk), (0, 0)])
        return (np.ascontiguousarray(A, dtype=dt),
                np.ascontiguousarray(B, dtype=dt))

    out = (jax.ShapeDtypeStruct(P.shape[:-1] + (k,), dt),
           jax.ShapeDtypeStruct(Q.shape[:-2] + (k, m), dt))
    return jax.pure_callback(_host, out, P, Q,
                             vmap_method="broadcast_all")


def aca_lowrank(P, Q, k: int):
    """Rank-``k`` cross approximation ``(U, V)`` of ``M = P @ Q``.

    ``P (n, R)``, ``Q (R, m)`` -> ``U (n, k)``, ``V (k, m)`` with
    ``U @ V ~ P @ Q``.  O(k (n + m) (R + k)) flops, no eigh/SVD/QR.
    The factors are balanced per direction (each ACA term is
    ``c_t r_t / pivot``; we split the pivot as ``1/sqrt|pivot|`` on each
    side to keep both factors at comparable scale — the same balancing
    convention as ``solver._round_factored``).
    """
    n, R = P.shape
    R2, m = Q.shape
    assert R == R2, (P.shape, Q.shape)
    dt = P.dtype
    # Factor-accumulation strategy, resolved at trace time (same
    # backend-gating convention as sphere_swe's batch_rounding).
    onehot = jax.default_backend() != "cpu"

    def body(t, carry):
        U, V, j, used_r, used_c = carry
        # Residual column at pivot column j.
        c = P @ jax.lax.dynamic_slice_in_dim(Q, j, 1, axis=1)[:, 0] \
            - U @ jax.lax.dynamic_slice_in_dim(V, j, 1, axis=1)[:, 0]
        c_m = jnp.where(used_r, 0.0, jnp.abs(c))
        i = jnp.argmax(c_m)
        # Residual row at pivot row i.
        r = jax.lax.dynamic_slice_in_dim(P, i, 1, axis=0)[0] @ Q \
            - jax.lax.dynamic_slice_in_dim(U, i, 1, axis=0)[0] @ V
        piv = r[j]
        # Dead pivot (exactly-representable operand of lower rank):
        # write zero vectors instead of dividing by ~0.
        ok = jnp.abs(piv) > jnp.finfo(dt).tiny * 16
        inv = jnp.where(ok, 1.0 / jnp.sqrt(jnp.abs(
            jnp.where(ok, piv, 1.0))), 0.0)
        sgn = jnp.where(piv < 0, -1.0, 1.0)
        u_t = c * inv
        v_t = r * (inv * sgn)
        if onehot:
            # One-hot outer-product accumulation: bitwise-identical to
            # the DUS (each column/row is written exactly once onto
            # zeros), measured 1.8x faster per vmapped call on TPU —
            # the 17.5 us/iteration DUS was the largest op family in
            # the batched factored-SWE step's device trace.  On CPU the
            # k-fold extra factor traffic measures 9-16% SLOWER, hence
            # the backend gate.
            oh = (jnp.arange(k, dtype=jnp.int32) == t).astype(dt)
            U = U + u_t[:, None] * oh[None, :]
            V = V + oh[:, None] * v_t[None, :]
        else:
            U = jax.lax.dynamic_update_slice_in_dim(U, u_t[:, None], t,
                                                    axis=1)
            V = jax.lax.dynamic_update_slice_in_dim(V, v_t[None, :], t,
                                                    axis=0)
        used_r = used_r.at[i].set(True)
        used_c = used_c.at[j].set(True)
        j_next = jnp.argmax(jnp.where(used_c, 0.0, jnp.abs(r)))
        return U, V, j_next, used_r, used_c

    U0 = jnp.zeros((n, k), dt)
    V0 = jnp.zeros((k, m), dt)
    # First pivot column: the one with the largest column of Q-energy
    # proxy (cheap, deterministic): argmax of column norms of Q summed
    # through P's column scales.
    col_proxy = jnp.einsum("ij,j->i", jnp.abs(Q.T), jnp.sum(jnp.abs(P), 0))
    j0 = jnp.argmax(col_proxy)
    carry = (U0, V0, j0, jnp.zeros((n,), bool), jnp.zeros((m,), bool))
    from ..utils.jax_compat import LEGACY_SHARD_MAP

    if LEGACY_SHARD_MAP:
        # jax 0.4.x: a vmapped while under shard_map trips an XLA
        # hlo-verifier bug ("tile_assignment should have N devices") —
        # the bound is static, so unroll the sweep instead (same ops,
        # same order; only the loop construct differs).
        for t in range(k):
            carry = body(t, carry)
        U, V = carry[0], carry[1]
    else:
        U, V, _, _, _ = jax.lax.fori_loop(0, k, body, carry)
    return U, V


def aca_lowrank_many(ops, k: int):
    """Round MANY independent face-batched operands in ONE ACA sweep.

    ``ops``: list of stacked factor pairs ``(A (F, n, R_i), B (F, R_i,
    n))`` with differing bond ranks ``R_i``.  Zero-pads every operand to
    ``max R_i`` (zero bond columns leave ``P @ Q`` unchanged, so the
    rounding is identical), stacks to one ``(len(ops) * F, ...)`` batch,
    and runs a single vmapped :func:`aca_lowrank`.  Returns the list of
    rounded ``(U (F, n, k), V (F, k, n))`` pairs.

    This is the TT analogue of kernel-launch batching: on TPU the
    factored SWE step was measured latency-bound on its ~36 *sequential*
    vmapped ACA loops (DESIGN.md "Round 2 (cont.)"); independent
    roundings grouped here run as one fori_loop instead of one per
    operand.
    """
    if not ops:
        return []
    R = max(A.shape[-1] for A, _ in ops)
    F = ops[0][0].shape[0]
    if any(A.shape[0] != F or B.shape[0] != F for A, B in ops):
        raise ValueError(
            "aca_lowrank_many needs a common face/batch count; got "
            f"{[(A.shape[0], B.shape[0]) for A, B in ops]}")
    padded_A = [jnp.pad(A, ((0, 0), (0, 0), (0, R - A.shape[-1])))
                for A, _ in ops]
    padded_B = [jnp.pad(B, ((0, 0), (0, R - B.shape[-2]), (0, 0)))
                for _, B in ops]
    As = jnp.concatenate(padded_A, axis=0)
    Bs = jnp.concatenate(padded_B, axis=0)
    U, V = jax.vmap(lambda a, b: aca_lowrank(a, b, k))(As, Bs)
    return [(U[i * F:(i + 1) * F], V[i * F:(i + 1) * F])
            for i in range(len(ops))]
