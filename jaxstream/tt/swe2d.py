"""Nonlinear 2-D shallow water in factored (rank-r TT) form.

The deck's research frontier made runnable: its TT story (p.3/5/19)
cites LANL's 124x on *nonlinear* Cartesian-2D SWE (Danis et al. 2024,
arXiv:2408.03483), but ships no TT code.  This module evolves the full
nonlinear SWE with every field held as a rank-r factored form
``q = A @ B`` (the order-2 TT of an (nx, ny) field) and never
materializes an (nx, ny) array:

  * derivatives act on single factors (roll-based periodic stencils on
    A's rows / B's columns — O(N r) per operator);
  * the quadratic nonlinearities are Khatri-Rao products of the factors
    (``(A1 @ B1) * (A2 @ B2) = kr(A1, A2) @ kr(B1, B2)^T`` with
    column/row-wise Kronecker factors of rank r^2), immediately
    re-truncated to rank r by the static-shape Gram rounding of
    :mod:`jaxstream.tt.solver` — the "step-and-truncate" scheme;
  * SSPRK3 stage combines stack scaled factor pairs and round once.

All shapes are static, so the whole step jits into one XLA program of
small matmuls/eighs (MXU-shaped work).  Equations (advective form,
periodic domain, f-plane optional):

    h_t = -(h u)_x - (h v)_y
    u_t = -u u_x - v u_y - g h_x + f v
    v_t = -u v_x - v v_y - g h_y - f u

Validated against a dense roll-based stencil oracle in
tests/test_tt_swe2d.py; examples/demo_tt.py reports measured wall-clock.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from .solver import _round_factored, factor_field, unfactor_field

__all__ = ["kr_product", "make_tt_swe_stepper", "make_dense_swe_stepper",
           "sw_factor", "sw_unfactor"]

# One factor convention for the whole TT layer (balanced sqrt-sigma
# factors — see solver._round_factored).
sw_factor = factor_field
sw_unfactor = unfactor_field


def kr_raw(x, y):
    """Unrounded Khatri-Rao product pair: the exact factored form of the
    elementwise product of two factored fields (rank r1*r2)."""
    A1, B1 = x
    A2, B2 = y
    n = A1.shape[0]
    m = B1.shape[1]
    return ((A1[:, :, None] * A2[:, None, :]).reshape(n, -1),
            (B1[:, None, :] * B2[None, :, :]).reshape(-1, m))


def kr_product(x, y, rank: int, sketch=None):
    """Elementwise product of two factored fields, re-truncated to rank.

    ``kr(A1, A2)[i, a*r2+b] = A1[i, a] A2[i, b]`` (column-wise Kronecker),
    so the product's exact factored form has rank r1*r2; rounding brings
    it back to ``rank``.  With ``sketch=None`` the exact Gram rounding
    runs in O(N (r1 r2)^2).  Passing a fixed random test matrix
    ``sketch`` (R, k), k = rank + oversample, uses randomized range
    finding instead: project the R-dimensional bond space to k
    dimensions first (O(N R k)), then Gram-round the small form — the
    standard randomized-SVD guarantee puts the extra truncation error at
    the sigma_{rank+1} level, i.e. at the rounding's own floor.  (The
    cross/ACA route lives in the stepper itself — ``rounding='cross'``
    batches the six per-stage product ACAs; use
    :func:`jaxstream.tt.cross.aca_lowrank` on ``kr_raw`` output
    directly for one-off products.)
    """
    A, B = kr_raw(x, y)
    if sketch is None:
        return _round_factored(A, B, rank)
    # Randomized range finder (Halko-Martinsson-Tropp): Y = M @ sketch
    # spans M's leading column space; project M onto it and round the
    # small rank-k pair exactly.  Never materializes M.
    Y = A @ (B @ sketch)                   # (n, k)
    G = Y.T @ Y
    va, Ea = jnp.linalg.eigh(G)
    fi = jnp.finfo(va.dtype)
    keep = va > fi.eps * va[-1] + fi.tiny
    inv_s = jnp.where(keep, 1.0 / jnp.sqrt(jnp.where(keep, va, 1.0)), 0.0)
    Qs = Ea * inv_s[None, :]               # Q = Y @ Qs orthonormal
    Cb = (Qs.T @ (Y.T @ A)) @ B            # (k, m): Q^T M
    return _round_factored(Y @ Qs, Cb, rank)


def make_tt_swe_stepper(
    nx: int,
    ny: int,
    dx: float,
    dy: float,
    dt: float,
    gravity: float,
    rank: int,
    f_cor: float = 0.0,
    nu: float = 0.0,
    rounding: str = "sketch",
    oversample: int = 8,
) -> Callable:
    """Jit-able fixed-rank SSPRK3 step for factored-form 2-D SWE.

    State: ``(h, u, v)``, each a factor pair ``(A (nx, r), B (r, ny))``.
    ``nu`` adds Laplacian viscosity/diffusion on all fields (stabilizes
    long nonlinear runs at low rank, as in step-and-truncate practice).
    ``rounding='sketch'`` (default) rounds the rank-r^2 quadratic terms
    through a fixed randomized range finder — O(N r^2 k) instead of the
    exact O(N r^4) Gram rounding (``rounding='exact'``); the extra
    truncation error sits at the rounding's own sigma_{r+1} floor.
    ``rounding='cross'`` uses partially-pivoted ACA (the LANL method,
    deck p.14) for BOTH the quadratic products and the stage combines:
    the entire step becomes matvecs + argmax — no eigh/SVD anywhere —
    removing the N-independent factorization floor that dominates at
    moderate N (see DESIGN.md).
    """
    cx = 0.5 / dx
    cy = 0.5 / dy
    vx = nu / (dx * dx)
    vy = nu / (dy * dy)
    cross = rounding in ("cross", "cross_fused")
    fused = rounding == "cross_fused"
    if rounding == "sketch":
        # float32 test matrix: promotion follows the state dtype, and the
        # range finder needs no more precision than the directions it
        # sketches.
        sketch = jax.random.normal(jax.random.PRNGKey(7),
                                   (ny, rank + oversample), jnp.float32)
    elif rounding == "exact":
        sketch = None
    elif cross:
        sketch = None               # unused: cross modes bypass kr_product
    else:
        raise ValueError(f"unknown rounding {rounding!r}")

    def ddx(q):       # centered d/dx acts on the A factor's rows
        A, B = q
        return ((jnp.roll(A, -1, 0) - jnp.roll(A, 1, 0)) * cx, B)

    def ddy(q):       # centered d/dy acts on the B factor's columns
        A, B = q
        return (A, (jnp.roll(B, -1, 1) - jnp.roll(B, 1, 1)) * cy)

    def lap_pairs(q, scale):
        A, B = q
        return [
            (scale * vx * (jnp.roll(A, 1, 0) + jnp.roll(A, -1, 0) - 2.0 * A),
             B),
            (scale * A,
             vy * (jnp.roll(B, 1, 1) + jnp.roll(B, -1, 1) - 2.0 * B)),
        ]

    def scale(q, s):
        A, B = q
        return (s * A, B)

    def combine(pairs, r):
        A = jnp.concatenate([p[0] for p in pairs], axis=1)
        B = jnp.concatenate([p[1] for p in pairs], axis=0)
        if cross:
            from .cross import aca_lowrank

            return aca_lowrank(A, B, r)
        return _round_factored(A, B, r)

    if cross and not fused:
        from .cross import aca_lowrank

        _aca6 = jax.vmap(lambda A, B: aca_lowrank(A, B, rank))

    def rhs_pairs(state, s):
        """Factor pairs of ``s * dt * RHS`` for each field (h, u, v)."""
        h, u, v = state
        sdt = s * dt
        if fused:
            # Defer rounding to the stage combine (rank-r^2 pairs ride).
            hu, hv, uux, vuy, uvx, vvy = (
                kr_raw(h, u), kr_raw(h, v), kr_raw(u, ddx(u)),
                kr_raw(v, ddy(u)), kr_raw(u, ddx(v)), kr_raw(v, ddy(v)))
        elif cross:
            # One BATCHED ACA for the stage's six quadratic products
            # (identical shapes).  Measured ~neutral vs per-product
            # calls on a single CPU core (the floor is the sequential
            # per-iteration matvec, DESIGN.md), kept for dispatch
            # hygiene and for batch-friendly backends.
            raws = [kr_raw(h, u), kr_raw(h, v), kr_raw(u, ddx(u)),
                    kr_raw(v, ddy(u)), kr_raw(u, ddx(v)),
                    kr_raw(v, ddy(v))]
            UA, VB = _aca6(jnp.stack([p[0] for p in raws]),
                           jnp.stack([p[1] for p in raws]))
            hu, hv, uux, vuy, uvx, vvy = [
                (UA[i], VB[i]) for i in range(6)]
        else:
            # Products re-truncated to `rank` before differentiation
            # keeps every stacked pair at rank r (step-and-truncate's
            # core move).
            prod = lambda x, y: kr_product(x, y, rank, sketch)
            hu = prod(h, u)
            hv = prod(h, v)
            uux = prod(u, ddx(u))
            vuy = prod(v, ddy(u))
            uvx = prod(u, ddx(v))
            vvy = prod(v, ddy(v))

        dh = [scale(ddx(hu), -sdt), scale(ddy(hv), -sdt)]
        du = [scale(uux, -sdt), scale(vuy, -sdt),
              scale(ddx(h), -sdt * gravity)]
        dv = [scale(uvx, -sdt), scale(vvy, -sdt),
              scale(ddy(h), -sdt * gravity)]
        if f_cor != 0.0:
            du.append(scale(v, sdt * f_cor))
            dv.append(scale(u, -sdt * f_cor))
        if nu != 0.0:
            dh += lap_pairs(h, sdt)
            du += lap_pairs(u, sdt)
            dv += lap_pairs(v, sdt)
        return dh, du, dv

    def stage(y0, a, yc, b):
        """a*y0 + b*yc + b*dt*RHS(yc): ONE rounding per field (stacking
        the prior terms with the RHS pairs keeps both the cost and the
        truncation-error count at one combine per field per stage)."""
        dh, du, dv = rhs_pairs(yc, b)
        prior = lambda i: ([scale(y0[i], a)] if a != 0.0 else []) + \
            [scale(yc[i], b) if b != 1.0 else yc[i]]
        return (combine(prior(0) + dh, rank),
                combine(prior(1) + du, rank),
                combine(prior(2) + dv, rank))

    def step(state):
        y1 = stage(None, 0.0, state, 1.0)
        y2 = stage(state, 0.75, y1, 0.25)
        return stage(state, 1.0 / 3.0, y2, 2.0 / 3.0)

    return step


def make_dense_swe_stepper(dx: float, dy: float, dt: float, gravity: float,
                           f_cor: float = 0.0, nu: float = 0.0) -> Callable:
    """Dense roll-based stencil SSPRK3 for the same equations.

    The reference oracle the factored stepper is validated (and timed)
    against — one source of truth shared by tests/test_tt_swe2d.py and
    examples/demo_tt.py.  State: plain ``(h, u, v)`` arrays.
    """
    cx = 0.5 / dx
    cy = 0.5 / dy
    vx = nu / (dx * dx)
    vy = nu / (dy * dy)

    def dxo(q):
        return (jnp.roll(q, -1, 0) - jnp.roll(q, 1, 0)) * cx

    def dyo(q):
        return (jnp.roll(q, -1, 1) - jnp.roll(q, 1, 1)) * cy

    def lapo(q):
        return (vx * (jnp.roll(q, 1, 0) + jnp.roll(q, -1, 0) - 2.0 * q)
                + vy * (jnp.roll(q, 1, 1) + jnp.roll(q, -1, 1) - 2.0 * q))

    def rhs(s):
        h, u, v = s
        return (-dxo(h * u) - dyo(h * v) + lapo(h),
                -u * dxo(u) - v * dyo(u) - gravity * dxo(h)
                + f_cor * v + lapo(u),
                -u * dxo(v) - v * dyo(v) - gravity * dyo(h)
                - f_cor * u + lapo(v))

    def step(s):
        k = rhs(s)
        y1 = tuple(a + dt * b for a, b in zip(s, k))
        k = rhs(y1)
        y2 = tuple(0.75 * a + 0.25 * (b + dt * c)
                   for a, b, c in zip(s, y1, k))
        k = rhs(y2)
        return tuple(a / 3.0 + (2.0 / 3.0) * (b + dt * c)
                     for a, b, c in zip(s, y2, k))

    return step
