"""QTT (order-d quantized TT) operator numerics — jit-able, O(log N).

The deck's compression claim is "N x N -> O(d N r^2)" (p.3); the
*quantized* TT form goes further: reshape the (N, N) field into base-b
digits (``tensor_train.quantize_shape``) and a smooth field's state is
``O(d b^2 r^2)`` with ``d = 2 log_b N`` — **sublinear in N**.  Round 1/2
built the compression layer (:mod:`.tensor_train`) and order-2 factored
*solvers*; this module closes the order-d gap: linear operators as
**TT-matrices** over the digit chain and a **static-rank two-sweep
rounding**, so an entire PDE step — matvec, add, round — runs inside
``jax.jit`` on cores whose shapes never depend on data.

Layout: the (N, N) field (index ``[y, x]``) becomes the order-2k tensor
``[y_0, x_0, y_1, x_1, ...]`` — digits most-significant first,
interleaved for locality (same digit convention as
``tensor_train.tt_compress_field``, but unmerged so each core owns ONE
digit of ONE axis, which is what makes per-axis operators cheap).

Operators: the periodic shift-by-one on a k-digit base-b index is an
exact TT-matrix of bond 2 — the bond carries the "carry" bit of the
increment; an axis operator threads that bond unchanged through the
other axis' digit cores.  The 5-point periodic Laplacian is then
``Sx + Sx' + Sy + Sy' - 4 I`` by block-diagonal TT-matrix addition
(bond 9, exact — no operator rounding needed).

References: Oseledets 2011 (TT), Kazeev & Khoromskij 2012 (explicit
QTT ranks of the 1-D Laplacian); deck p.3/5/19 for the thesis.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .tensor_train import (
    TTTensor,
    _block_diag_cores,
    quantize_shape,
    tt_reconstruct,
)

__all__ = [
    "interleaved_digits", "qtt_compress", "qtt_compress_separable",
    "qtt_decompress",
    "shift_ttm", "identity_ttm", "diag_ttm", "ttm_add", "ttm_scale",
    "ttm_matvec", "ttm_matmat",
    "laplacian_ttm", "variable_diffusion_ttm", "advection_ttm",
    "tt_round_static", "ttm_round_static", "ttm_compress_np", "qtt_hadamard",
    "make_qtt_diffusion_stepper", "make_qtt_operator_stepper",
    "make_qtt_burgers_stepper", "make_qtt_swe_stepper",
    "make_dense_swe_twin",
]


# --------------------------------------------------------------- layout

def interleaved_digits(N: int, base: int = 4) -> List[int]:
    """Digit dims of the interleaved order-2k layout for an (N, N)
    field: ``[b, b, ..., b]`` of length ``2k`` with ``N = b^k``."""
    dy = quantize_shape(N, base)
    if any(v != base for v in dy):
        raise ValueError(f"N={N} is not a power of base={base}")
    return [base] * (2 * len(dy))


def _ns(*arrays):
    """Namespace dispatch: the ENTIRE eager build/compress layer runs
    in numpy f64 (an operator built through f32 jnp math — what
    jax_enable_x64=False forces — was measured 96% wrong: the shift
    algebra's +1/-1 cancellations do not survive f32 build rounding);
    the runtime path (jit tracers / device arrays) uses jnp."""
    return np if all(isinstance(a, np.ndarray) for a in arrays) else jnp


def _to_digit_tensor(q, base: int):
    """(N, N) -> interleaved digit tensor [y0, x0, y1, x1, ...]."""
    k = len(quantize_shape(q.shape[0], base))
    perm = [i for pair in zip(range(k), range(k, 2 * k)) for i in pair]
    xp = _ns(q)
    return xp.transpose(q.reshape((base,) * (2 * k)), perm)


def _from_digit_tensor(t, base: int):
    k = t.ndim // 2
    inv = [2 * i for i in range(k)] + [2 * i + 1 for i in range(k)]
    N = base ** k
    return _ns(t).transpose(t, inv).reshape(N, N)


def _pad_bond(c, r0: int, r1: int):
    """Zero-pad a core's bond dims up to (r0, n, r1)."""
    return _ns(c).pad(c, ((0, r0 - c.shape[0]), (0, 0),
                          (0, r1 - c.shape[2])))


def _decompose_np(t, max_rank: int) -> List[np.ndarray]:
    """Numpy-f64 TT-SVD (build-time twin of ``tensor_train.
    tt_decompose``, which runs through jnp and therefore f32 when
    jax_enable_x64 is off — not enough for operator construction)."""
    dims = t.shape
    d = len(dims)
    cores = []
    r_prev = 1
    mat = t.reshape(r_prev * dims[0], -1)
    for k in range(d - 1):
        u, s, vt = np.linalg.svd(mat, full_matrices=False)
        floor = (s[0] if s.size else 0.0) * 32 * np.finfo(t.dtype).eps
        r = max(1, min(max_rank, int((s > floor).sum())))
        cores.append(u[:, :r].reshape(r_prev, dims[k], r))
        mat = s[:r, None] * vt[:r, :]
        r_prev = r
        if k < d - 2:
            mat = mat.reshape(r_prev * dims[k + 1], -1)
    cores.append(mat.reshape(r_prev, dims[-1], 1))
    return cores


def qtt_compress(q, rank: int, base: int = 4) -> List[np.ndarray]:
    """(N, N) -> static-rank core list (every bond exactly ``rank``,
    zero-padded past the field's numerical rank) in the interleaved
    digit layout.  Eager numpy f64; cast the cores to the runtime dtype
    before feeding the jit-able stepper."""
    t = _to_digit_tensor(np.asarray(q, np.float64), base)
    cores = _decompose_np(t, rank)
    d = len(cores)
    return [_pad_bond(c,
                      1 if j == 0 else rank,
                      1 if j == d - 1 else rank)
            for j, c in enumerate(cores)]


def qtt_decompress(cores: Sequence, base: int = 4):
    """Core list -> dense (N, N) (numpy path stays f64)."""
    if isinstance(cores[0], np.ndarray):
        out = cores[0]
        for c in cores[1:]:
            out = np.einsum("...a,abc->...bc", out, c)
        return _from_digit_tensor(out[0, ..., 0], base)
    return _from_digit_tensor(tt_reconstruct(TTTensor(list(cores))), base)


def qtt_compress_separable(rows, cols, rank: int,
                           base: int = 4) -> List[np.ndarray]:
    """Static-rank QTT cores of ``sum_k outer(rows[k], cols[k])``
    WITHOUT ever forming the (N, N) field — O(K N) work, so state prep
    stays feasible at N far beyond dense-array reach (N = 65536 is a
    128 MB field per f64 copy; its QTT state is a few kB).

    Each 1-D factor is TT-decomposed over its own digits (cheap); a
    term's interleaved 2-D cores are the factor cores Kronecker-threaded
    past the other axis' bond; terms sum block-diagonally and one
    static-rank rounding brings the result to ``rank``.
    """
    rows = np.asarray(rows, np.float64)
    cols = np.asarray(cols, np.float64)
    if rows.ndim == 1:
        rows, cols = rows[None], cols[None]
    K, N = rows.shape
    k = len(quantize_shape(N, base))
    terms = []
    for t in range(K):
        vy = _decompose_np(rows[t].reshape((base,) * k), N)
        vx = _decompose_np(cols[t].reshape((base,) * k), N)
        cores = []
        for j in range(k):
            ry0, _, ry1 = vy[j].shape
            rx0, _, rx1 = vx[j].shape
            # y_j: act on the y digit, thread the x bond (dim rx0).
            cores.append(np.einsum("anb,cd->acnbd", vy[j], np.eye(rx0))
                         .reshape(ry0 * rx0, base, ry1 * rx0))
            # x_j: act on the x digit, thread the (new) y bond — bond
            # index order is y-major on both sides, matching the y_j
            # cores' (ry, rx) flattening.
            cores.append(np.einsum("ef,anb->eanfb", np.eye(ry1), vx[j])
                         .reshape(ry1 * rx0, base, ry1 * rx1))
        terms.append(cores)
    # Block-diagonal sum of the K terms, then one fixed-rank rounding.
    d = 2 * k
    summed = terms[0]
    for term in terms[1:]:
        summed = [_block_diag_cores(a, b, j == 0, j == d - 1)
                  for j, (a, b) in enumerate(zip(summed, term))]
    out = tt_round_static(summed, rank)
    return [_pad_bond(c,
                      1 if j == 0 else rank,
                      1 if j == d - 1 else rank)
            for j, c in enumerate(out)]


# ---------------------------------------------------- TT-matrix algebra
# A TT-matrix is a list of cores (r, n_out, n_in, r').

def _carry_core(b: int, sign: int) -> np.ndarray:
    """The (2, b, b, 2) core of periodic shift-by-(+-1): left bond =
    carry OUT toward the more significant digit, right bond = carry IN
    from the less significant side.  ``core[c, d', d, cin] = 1`` iff
    ``d' = (d + sign*cin) mod b`` and ``c = 1`` exactly when the
    addition wrapped."""
    core = np.zeros((2, b, b, 2))
    for d in range(b):
        for cin in (0, 1):
            v = d + sign * cin
            core[1 if (v < 0 or v >= b) else 0, v % b, d, cin] = 1.0
    return core


def _pass_core(b: int) -> np.ndarray:
    """Identity on the digit, bond (2) threaded through unchanged."""
    core = np.zeros((2, b, b, 2))
    for c in (0, 1):
        for d in range(b):
            core[c, d, d, c] = 1.0
    return core


def shift_ttm(N: int, axis: int, sign: int,
              base: int = 4) -> List[np.ndarray]:
    """TT-matrix of the periodic shift ``q[..., i, ...] -> q[..., i+s,
    ...]`` along ``axis`` (0 = y, 1 = x) of the (N, N) field, on the
    interleaved digit chain.  Exact, bond 2.

    ``sign=+1`` gives the matrix with ``M[i', i] = 1`` iff ``i' = i + 1
    mod N``, i.e. ``(M q)[i] = q[i - 1]`` — values move forward.  The
    Laplacian uses both signs, so either convention closes it.
    """
    dims = interleaved_digits(N, base)
    cy = _carry_core(base, sign)
    pas = _pass_core(base)
    cores = [np.array(cy if (j % 2) == axis else pas)
             for j in range(len(dims))]
    # Boundary closure: the chain's right end injects carry = 1 (the
    # "+1"); the left end sums both carry states (mod-N wrap).  The
    # digits run most-significant-first, the axis' LAST digit core is
    # its least significant — but non-axis cores pass the bond through,
    # so closing at the chain ends is equivalent.
    left = np.ones((1, 2))                    # sum over final carry
    right = np.array([[0.0], [1.0]])          # inject carry=1
    cores[0] = np.einsum("ab,bxyc->axyc", left, cores[0])
    cores[-1] = np.einsum("axyb,bc->axyc", cores[-1], right)
    return cores


def identity_ttm(N: int, base: int = 4) -> List[np.ndarray]:
    return [np.eye(b)[None, :, :, None]
            for b in interleaved_digits(N, base)]


def ttm_scale(op: Sequence, s: float) -> List:
    out = list(op)
    out[0] = out[0] * s
    return out


def ttm_add(*ops: Sequence) -> List:
    """Block-diagonal TT-matrix sum (bonds add)."""
    d = len(ops[0])
    out = []
    for j in range(d):
        cs = [op[j] for op in ops]
        n_out, n_in = cs[0].shape[1], cs[0].shape[2]
        xp = _ns(*cs)
        if j == 0:
            out.append(xp.concatenate(cs, axis=3))
        elif j == d - 1:
            out.append(xp.concatenate(cs, axis=0))
        else:
            r0 = sum(c.shape[0] for c in cs)
            r1 = sum(c.shape[3] for c in cs)
            if xp is np:
                blk = np.zeros((r0, n_out, n_in, r1), cs[0].dtype)
                a = b = 0
                for c in cs:
                    blk[a:a + c.shape[0], :, :, b:b + c.shape[3]] = c
                    a += c.shape[0]
                    b += c.shape[3]
            else:
                blk = jnp.zeros((r0, n_out, n_in, r1), cs[0].dtype)
                a = b = 0
                for c in cs:
                    blk = blk.at[a:a + c.shape[0], :, :,
                                 b:b + c.shape[3]].set(c)
                    a += c.shape[0]
                    b += c.shape[3]
            out.append(blk)
    return out


def ttm_matvec(op: Sequence, x: Sequence) -> List:
    """Apply a TT-matrix to a TT-vector core-by-core (bonds multiply)."""
    out = []
    for co, cx in zip(op, x):
        xp = _ns(co, cx)
        if xp is np:
            c = np.einsum("aijb,cjd->acibd", co, cx)
        else:
            # TPU f32 einsum defaults to bf16 accumulation — fatal to
            # difference operators (O(1) operands cancelling to O(h^2)
            # results); pin full precision at the op level.
            c = jnp.einsum("aijb,cjd->acibd", co, cx,
                           precision=jax.lax.Precision.HIGHEST)
        out.append(c.reshape(co.shape[0] * cx.shape[0], co.shape[1],
                             co.shape[3] * cx.shape[2]))
    return out


def laplacian_ttm(N: int, base: int = 4) -> List[np.ndarray]:
    """The 5-point periodic Laplacian (unit spacing) as an exact
    TT-matrix (bond 9) on the interleaved digit chain."""
    ops = [shift_ttm(N, a, s, base) for a in (0, 1) for s in (1, -1)]
    ops.append(ttm_scale(identity_ttm(N, base), -4.0))
    return ttm_add(*ops)


def diag_ttm(field_cores: Sequence) -> List:
    """Lift a QTT *field* to the diagonal TT-matrix ``diag(C)`` —
    multiplication by a variable coefficient.  Bond = the field's bond:
    each vector core ``(r, n, r')`` becomes the matrix core whose
    ``(n_out, n_in)`` slice is diagonal in the digit."""
    out = []
    for c in field_cores:
        xp = _ns(c)
        eye = xp.eye(c.shape[1], dtype=c.dtype)
        out.append(xp.einsum("anb,nm->anmb", c, eye))
    return out


def ttm_matmat(A: Sequence, B: Sequence) -> List:
    """TT-matrix product ``A @ B`` core-by-core (bonds multiply)."""
    out = []
    for ca, cb in zip(A, B):
        xp = _ns(ca, cb)
        if xp is np:
            c = np.einsum("aikb,ckjd->acijbd", ca, cb)
        else:
            # Same bf16-accumulation hazard as ttm_matvec: operator
            # compositions cancel O(1) entries down to O(h^2).
            c = jnp.einsum("aikb,ckjd->acijbd", ca, cb,
                           precision=jax.lax.Precision.HIGHEST)
        out.append(c.reshape(ca.shape[0] * cb.shape[0], ca.shape[1],
                             cb.shape[2], ca.shape[3] * cb.shape[3]))
    return out


def ttm_round_static(op: Sequence, rank: int) -> List:
    """Fixed-rank rounding of a TT-matrix: fold each core's
    ``(n_out, n_in)`` into one physical index and reuse
    :func:`tt_round_static`."""
    folded = [c.reshape(c.shape[0], c.shape[1] * c.shape[2], c.shape[3])
              for c in op]
    out = tt_round_static(folded, rank)
    return [o.reshape(o.shape[0], c.shape[1], c.shape[2], o.shape[2])
            for o, c in zip(out, op)]


def variable_diffusion_ttm(C, N: int, coeff_rank: int = 8,
                           base: int = 4) -> List[np.ndarray]:
    """Flux-form variable-coefficient diffusion ``div(C grad q)``
    (periodic, unit spacing) as a TT-matrix.

    Per axis: ``D_-(C_half (.) D_+)`` with ``D_+ = S_+ - I`` (forward
    difference to the half point), ``C_half`` the face-averaged
    coefficient ``(C + S_+ C)/2`` lifted by :func:`diag_ttm`, and
    ``D_- = I - S_-`` closing the flux difference — the standard
    conservative 2nd-order stencil, exactly, at bond
    ``~2 * 3 * r_C * 3`` per axis.  ``C``: the (N, N) coefficient field
    (any array) or a prebuilt QTT core list.
    """
    if isinstance(C, (list, tuple)):
        # Operator construction MUST run in f64 numpy (see _ns): a
        # prebuilt jnp/f32 core list would silently rebuild the
        # measured-96%-wrong operator.
        cs = [np.asarray(c, np.float64) for c in C]
    else:
        cs = qtt_compress(np.asarray(C, np.float64), coeff_rank, base)
    I = identity_ttm(N, base)
    d = len(cs)
    terms = []
    for axis in (0, 1):
        Sp = shift_ttm(N, axis, -1, base)   # (Sp q)[i] = q[i+1]
        Sm = shift_ttm(N, axis, +1, base)   # (Sm q)[i] = q[i-1]
        Dp = ttm_add(Sp, ttm_scale(I, -1.0))            # q[i+1] - q[i]
        Dm = ttm_add(I, ttm_scale(Sm, -1.0))            # f[i] - f[i-1]
        # Face coefficient at i+1/2: (C + Sp C)/2 as a field — exact
        # block-diag sum (the operator is built once; its bond is a
        # build-time cost, so no rounding here).
        half = lambda f, j: f * (0.5 if j == 0 else 1.0)
        CSp = ttm_matvec(Sp, cs)
        Ch = [_block_diag_cores(half(cs[j], j), half(CSp[j], j),
                                j == 0, j == d - 1) for j in range(d)]
        terms.append(ttm_matmat(Dm, ttm_matmat(diag_ttm(Ch), Dp)))
    return ttm_add(*terms)


# ------------------------------------------------- static-rank rounding

def tt_round_static(cores: Sequence, rank: int) -> List:
    """Two-sweep TT rounding at a FIXED output rank — fully jit-able.

    Right-to-left QR sweep orthogonalizes; the left-to-right truncation
    sweep QRs the (tall, possibly exactly rank-deficient) unfolding
    first — Householder QR is robust to zero columns — and SVDs only
    the small triangular factor, the same small-square-SVD shape class
    as the production ``solver._round_factored`` coupling core (runs
    NaN-free under jit where XLA's SVD of *tall rank-deficient
    unfoldings* is the documented eager-only failure mode,
    tensor_train.py).  Every bond truncates to ``min(rank, bond)`` and
    zero-pads back to exactly ``rank`` (interior bonds), so output
    shapes are static regardless of the input's (static) bond dims;
    padded directions are exact zeros.
    """
    d = len(cores)
    cs = list(cores)
    xp = _ns(*cs)           # numpy-f64 eager build path / jnp runtime
    if xp is jnp:
        # Pin full matmul precision for the whole sweep (QR/SVD
        # internals included): bf16 accumulation wrecks the
        # orthogonality the truncation relies on (measured 4 orders
        # of magnitude on TPU f32).
        with jax.default_matmul_precision("highest"):
            if cs[0].dtype == jnp.float32:
                # TPU f32 jnp.linalg.qr LOSES ORTHOGONALITY
                # catastrophically (measured |Q'Q - I| up to 1.5e5) on
                # the heavily rank-deficient structured matrices this
                # sweep produces; eigh stays orthonormal to 1e-6 on the
                # same operands.  The f32 path therefore rounds via
                # masked Gram eigh on BOTH sweeps (sqrt-eps precision
                # loss ~3e-4 — below the f32 matvec error).
                return _round_sweeps_gram(cs, d, rank)
            return _round_sweeps(cs, d, rank, xp)
    return _round_sweeps(cs, d, rank, xp)


def _round_sweeps_gram(cs, d, rank):
    """f32 two-sweep rounding via masked Gram eigh (no QR/SVD)."""
    fi = jnp.finfo(cs[0].dtype)
    for j in range(d - 1, 0, -1):
        r0, n, r1 = cs[j].shape
        M = cs[j].reshape(r0, n * r1)
        lam, E = jnp.linalg.eigh(M @ M.T)          # ascending
        keep = lam > fi.eps * lam[-1] + fi.tiny
        s = jnp.sqrt(jnp.where(keep, lam, 1.0))
        inv_s = jnp.where(keep, 1.0 / s, 0.0)
        cs[j] = (inv_s[:, None] * (E.T @ M)).reshape(r0, n, r1)
        R = E * jnp.where(keep, s, 0.0)[None, :]   # M = R @ rows(cs[j])
        cs[j - 1] = jnp.einsum("anb,bc->anc", cs[j - 1], R)
    for j in range(d - 1):
        r0, n, r1 = cs[j].shape
        M = cs[j].reshape(r0 * n, r1)
        lam, E = jnp.linalg.eigh(M.T @ M)
        lam, E = lam[::-1], E[:, ::-1]
        k = min(rank, r1)
        keep = lam[:k] > fi.eps * lam[0] + fi.tiny
        s = jnp.sqrt(jnp.where(keep, lam[:k], 1.0))
        inv_s = jnp.where(keep, 1.0 / s, 0.0)
        Q = M @ (E[:, :k] * inv_s[None, :])
        R = jnp.where(keep, s, 0.0)[:, None] * E[:, :k].T
        if k < rank:
            Q = jnp.pad(Q, ((0, 0), (0, rank - k)))
            R = jnp.pad(R, ((0, rank - k), (0, 0)))
        cs[j] = Q.reshape(r0, n, rank)
        cs[j + 1] = jnp.einsum("ab,bnc->anc", R, cs[j + 1])
    return _balance(cs, jnp)


def _round_sweeps(cs, d, rank, xp):
    # Right-to-left orthogonalization (row-orthonormal right cores).
    for j in range(d - 1, 0, -1):
        r0, n, r1 = cs[j].shape
        q, r = xp.linalg.qr(cs[j].reshape(r0, n * r1).T)
        k = q.shape[1]                       # min(r0, n*r1), static
        cs[j] = q.T.reshape(k, n, r1)
        cs[j - 1] = xp.einsum("anb,cb->anc", cs[j - 1], r)
    # Left-to-right truncation sweep (QR + small-core SVD).
    for j in range(d - 1):
        r0, n, r1 = cs[j].shape
        q2, r2 = xp.linalg.qr(cs[j].reshape(r0 * n, r1))
        u, s, vt = xp.linalg.svd(r2)         # (min(m,r1), r1): small
        k = min(rank, s.shape[0])
        Q = q2 @ u[:, :k]
        R = s[:k, None] * vt[:k, :]
        if k < rank:
            Q = xp.pad(Q, ((0, 0), (0, rank - k)))
            R = xp.pad(R, ((0, rank - k), (0, 0)))
        cs[j] = Q.reshape(r0, n, rank)
        cs[j + 1] = xp.einsum("ab,bnc->anc", R, cs[j + 1])
    return _balance(cs, xp)


def _balance(cs, xp):
    """Equalize core Frobenius norms (product of scales = 1, value
    unchanged).  Load-bearing for f32: the truncation sweep concentrates
    the WHOLE tensor norm in the last core (e.g. 1.5e5 with a 1/dx-
    scaled operator), and f32 QR absorptions through that scale destroy
    O(1) values that emerge by cancellation — the chain form of the
    'balance the factors' lesson in solver._round_factored."""
    norms = [xp.linalg.norm(c.reshape(-1)) for c in cs]
    if xp is np:
        logs = [np.log(max(float(v), np.finfo(np.float64).tiny))
                for v in norms]
        g = np.exp(np.mean(logs))
        return [c * (g / v if float(v) > 0 else 1.0)
                for c, v in zip(cs, norms)]
    safe = [jnp.maximum(v, jnp.finfo(cs[0].dtype).tiny) for v in norms]
    g = jnp.exp(sum(jnp.log(v) for v in safe) / len(cs))
    # Guard on the RAW norm: a zero core must scale by 1 (g/tiny would
    # overflow to inf and 0*inf -> NaN).
    return [c * jnp.where(v > 0, g / s, 1.0)
            for c, v, s in zip(cs, norms, safe)]


def advection_ttm(vx, vy, N: int, coeff_rank: int = 8,
                  base: int = 4) -> List[np.ndarray]:
    """Centered variable-wind advection ``-(vx D_x + vy D_y) q``
    (periodic, unit spacing; scale by 1/dx outside) as a TT-matrix —
    the deck's cosine-bell transport (p.13/18) in operator form.

    ``vx``/``vy``: (N, N) wind component fields (y is axis 0).  The
    centered difference is ``(S_+ - S_-)/2`` per axis, each lifted wind
    a :func:`diag_ttm` factor.
    """
    ops = []
    for axis, v in ((0, vy), (1, vx)):
        Sp = shift_ttm(N, axis, -1, base)   # (Sp q)[i] = q[i+1]
        Sm = shift_ttm(N, axis, +1, base)
        Dc = ttm_add(ttm_scale(Sp, 0.5), ttm_scale(Sm, -0.5))
        Dv = diag_ttm(qtt_compress(np.asarray(v, np.float64),
                                   coeff_rank, base))
        ops.append(ttm_matmat(Dv, Dc))
    return ttm_scale(ttm_add(*ops), -1.0)


# ------------------------------------------------------------- stepper

def _combine(parts, rank: int) -> List:
    """``sum_i coef_i * cores_i`` at static rank: ONE chained block-diag
    sum, ONE two-sweep rounding.  The rounding sweeps dominate a step,
    so each RK stage must round exactly once (note: folding a stage's
    terms into one rounding was also measured 10-16% slower than nested
    rounded axpys — kept for the single-truncation structure, see
    DESIGN.md)."""
    d = len(parts[0][1])
    acc = [c * (parts[0][0] if j == 0 else 1.0)
           for j, c in enumerate(parts[0][1])]
    for coef, cores in parts[1:]:
        sc = [c * (coef if j == 0 else 1.0)
              for j, c in enumerate(cores)]
        acc = [_block_diag_cores(acc[j], sc[j], j == 0, j == d - 1)
               for j in range(d)]
    return tt_round_static(acc, rank)


def make_qtt_operator_stepper(L, dt: float, rank: int,
                              scheme: str = "ssprk3") -> Callable:
    """Jit-able SSPRK3/Euler step of ``q_t = L q`` for ANY linear
    TT-matrix ``L``.  The state is a static-rank core list; each RK
    stage is one matvec, one chained block-diag combine, and one
    two-sweep rounding — every shape static, cost independent of N
    (O(d) small QR/SVDs)."""
    # Default real dtype (f64 under jax_enable_x64, else f32).
    dtype = jnp.zeros(()).dtype
    L = [jnp.asarray(c, dtype) for c in L]

    combine = lambda parts: _combine(parts, rank)

    def step(y):
        Ly = ttm_matvec(L, y)
        if scheme == "euler":
            return combine([(dt, Ly), (1.0, y)])
        if scheme != "ssprk3":
            raise ValueError(f"unknown scheme {scheme!r}")
        y1 = combine([(dt, Ly), (1.0, y)])
        # y2 = 3/4 y + 1/4 y1 + 1/4 dt L y1
        y2 = combine([(0.25 * dt, ttm_matvec(L, y1)), (0.25, y1),
                      (0.75, y)])
        # y' = 1/3 y + 2/3 y2 + 2/3 dt L y2
        return combine([((2.0 / 3.0) * dt, ttm_matvec(L, y2)),
                        (2.0 / 3.0, y2), (1.0 / 3.0, y)])

    return step


def make_qtt_diffusion_stepper(N: int, kappa: float, dx: float,
                               dt: float, rank: int, base: int = 4,
                               scheme: str = "ssprk3") -> Callable:
    """Jit-able QTT step for 2-D periodic diffusion ``q_t = kappa lap
    q`` — :func:`make_qtt_operator_stepper` over the bond-9 Laplacian."""
    return make_qtt_operator_stepper(
        ttm_scale(laplacian_ttm(N, base), kappa / (dx * dx)), dt, rank,
        scheme=scheme)


def qtt_hadamard(a: Sequence, b: Sequence) -> List:
    """Elementwise product of two QTT fields, core-by-core (bonds
    multiply) — the NONLINEAR-term primitive: ``q (.) (D q)`` pairs
    feed :func:`tt_round_static` exactly like the order-2 layer's
    Khatri-Rao products feed ACA."""
    out = []
    for ca, cb in zip(a, b):
        xp = _ns(ca, cb)
        if xp is np:
            c = np.einsum("anb,cnd->acnbd", ca, cb)
        else:
            c = jnp.einsum("anb,cnd->acnbd", ca, cb,
                           precision=jax.lax.Precision.HIGHEST)
        out.append(c.reshape(ca.shape[0] * cb.shape[0], ca.shape[1],
                             ca.shape[2] * cb.shape[2]))
    return out


def _ttm_fro2(op: Sequence[np.ndarray]) -> float:
    """Squared Frobenius norm of a TT-matrix by chain contraction."""
    env = np.ones((1, 1))
    for c in op:
        env = np.einsum("ac,aijb,cijd->bd", env, c, c)
    return float(env[0, 0])


def ttm_compress_np(op: Sequence[np.ndarray],
                    rtol: float = 1e-13) -> List[np.ndarray]:
    """Build-time TT-matrix compression to TRUE numerical bond ranks
    (eager numpy f64 only — shapes shrink dynamically; the jit-able
    :func:`ttm_round_static` pads every bond back to its cap, so it
    cannot shrink an operator).  Two-sweep with tolerance truncation,
    then a Frobenius self-check: if the compressed operator differs
    relatively by more than ``10 * rtol * sqrt(d)``, the original is
    returned unchanged."""
    cs = [np.asarray(c, np.float64) for c in op]
    shapes = [(c.shape[1], c.shape[2]) for c in cs]
    folded = [c.reshape(c.shape[0], -1, c.shape[3]) for c in cs]
    d = len(folded)
    for j in range(d - 1, 0, -1):
        r0, n, r1 = folded[j].shape
        q, r = np.linalg.qr(folded[j].reshape(r0, n * r1).T)
        folded[j] = q.T.reshape(-1, n, r1)
        folded[j - 1] = np.einsum("anb,cb->anc", folded[j - 1], r)
    for j in range(d - 1):
        r0, n, r1 = folded[j].shape
        u, sv, vt = np.linalg.svd(folded[j].reshape(r0 * n, r1),
                                  full_matrices=False)
        k = max(1, int((sv > rtol * (sv[0] if sv.size else 1.0)).sum()))
        folded[j] = u[:, :k].reshape(r0, n, k)
        folded[j + 1] = np.einsum("ab,bnc->anc",
                                  sv[:k, None] * vt[:k, :],
                                  folded[j + 1])
    out = [c.reshape(c.shape[0], no, ni, c.shape[2])
           for c, (no, ni) in zip(folded, shapes)]
    # Verified-or-identity: never silently return a lossy operator.
    diff = []
    for j, (a, b) in enumerate(zip(op, out)):
        a = np.asarray(a, np.float64)
        if j == 0:
            diff.append(np.concatenate([a, -b], axis=-1))
        elif j == d - 1:
            diff.append(np.concatenate([a, b], axis=0))
        else:
            blk = np.zeros((a.shape[0] + b.shape[0],) + a.shape[1:3]
                           + (a.shape[3] + b.shape[3],))
            blk[:a.shape[0], ..., :a.shape[3]] = a
            blk[a.shape[0]:, ..., a.shape[3]:] = b
            diff.append(blk)
    err2 = max(_ttm_fro2(diff), 0.0)
    ref2 = _ttm_fro2([np.asarray(c, np.float64) for c in op])
    # The Frobenius-difference contraction computes ||A - A'||^2 by
    # cancellation, so its own roundoff floor is ~eps * ||A||^2 — it
    # can only certify relative error down to ~1e-8.  That is far
    # tighter than any lossy trim would land (dropped directions carry
    # >= rtol-level mass), and far looser than the contraction noise.
    if err2 > 1e-14 * max(ref2, 1e-300):
        return [np.asarray(c, np.float64) for c in op]
    return out


def make_qtt_swe_stepper(N: int, gravity: float, depth: float,
                         dx: float, dt: float,
                         rank: int, base: int = 4, f: float = 0.0,
                         nu: float = 0.0,
                         scheme: str = "ssprk3") -> Callable:
    """Jit-able QTT step for the 2-D periodic shallow-water equations —
    the deck's target system (p.3/p.19: LANL's 124x was Cartesian-2D
    SWE) in the order-d digit-chain form (round 5, VERDICT ask #3).

    Anomaly form on an f-plane: the state's ``h`` is the anomaly about
    the constant mean ``depth`` (H), so the mass equation splits into
    the linear ``-H div(u)`` part plus the quadratic flux of the
    anomaly — the standard split, and the anomaly is what compresses::

        h_t = -H (D_x u + D_y v) - (D_x (h u) + D_y (h v))
        u_t = -(u D_x u + v D_y u) - g D_x h + f v + nu lap u
        v_t = -(u D_x v + v D_y v) - g D_y h - f u + nu lap v

    State: three static-rank QTT core lists ``(h, u, v)``.
    Every quadratic term is one :func:`qtt_hadamard`, **rounded at
    formation** (nested rounded products, the order-2 layer's own
    structure): with 10 quadratic/derivative intermediates per stage,
    Burgers' fold-everything-into-one-stage-rounding form puts the
    chained combine at bond ~2000 and was measured at 16.4 s/step
    (N=256 r12 CPU f64) — two orders above the nested form, whose
    roundings all sit at bond <= r^2 (gradients pre-rounded to r
    before entering Hadamards).  Cost per step is O(d) small
    factorizations — independent of N.

    Validated against a dense jnp twin built from the SAME centered
    stencils (tests/test_qtt.py::test_qtt_swe_*); the rung table and
    crossover live in scripts/tt_probe.py ``qttswe`` mode + DESIGN.md.
    """
    dtype = jnp.zeros(()).dtype
    cast = lambda op: [jnp.asarray(c, dtype) for c in op]
    # Layout is [y, x] (interleaved digits): axis 0 = y, axis 1 = x.
    Dy = cast(centered_diff_ttm(N, 0, dx, base))
    Dx = cast(centered_diff_ttm(N, 1, dx, base))
    L = None
    if nu:
        L = [jnp.asarray(c, dtype)
             for c in ttm_scale(laplacian_ttm(N, base), nu / (dx * dx))]

    combine = lambda parts: _combine(parts, rank)

    rnd = lambda cores: tt_round_static(cores, rank)

    def rhs_parts(y):
        h, u, v = y
        # Pre-rounded gradients (bond 5r -> r), then rounded Hadamards
        # (bond r^2 -> r): every factorization in the stage sits at
        # bond <= r^2.
        hx, hy = rnd(ttm_matvec(Dx, h)), rnd(ttm_matvec(Dy, h))
        ux, uy = rnd(ttm_matvec(Dx, u)), rnd(ttm_matvec(Dy, u))
        vx, vy = rnd(ttm_matvec(Dx, v)), rnd(ttm_matvec(Dy, v))
        hu, hv = rnd(qtt_hadamard(h, u)), rnd(qtt_hadamard(h, v))
        dh = [(-depth * dt, ux), (-depth * dt, vy),
              (-dt, rnd(ttm_matvec(Dx, hu))),
              (-dt, rnd(ttm_matvec(Dy, hv)))]
        du = [(-dt, rnd(qtt_hadamard(u, ux))),
              (-dt, rnd(qtt_hadamard(v, uy))),
              (-gravity * dt, hx)]
        dv = [(-dt, rnd(qtt_hadamard(u, vx))),
              (-dt, rnd(qtt_hadamard(v, vy))),
              (-gravity * dt, hy)]
        if f:
            du.append((f * dt, v))
            dv.append((-f * dt, u))
        if L is not None:
            du.append((dt, rnd(ttm_matvec(L, u))))
            dv.append((dt, rnd(ttm_matvec(L, v))))
        return dh, du, dv

    def axpy(parts3, extras):
        return tuple(combine(list(p) + list(e))
                     for p, e in zip(parts3, extras))

    def step(y):
        if scheme == "euler":
            return axpy(rhs_parts(y), [[(1.0, c)] for c in y])
        if scheme != "ssprk3":
            raise ValueError(f"unknown scheme {scheme!r}")
        y1 = axpy(rhs_parts(y), [[(1.0, c)] for c in y])
        y2 = axpy(
            tuple([(0.25 * c, p) for c, p in parts]
                  for parts in rhs_parts(y1)),
            [[(0.25, c1), (0.75, c0)] for c1, c0 in zip(y1, y)])
        return axpy(
            tuple([((2.0 / 3.0) * c, p) for c, p in parts]
                  for parts in rhs_parts(y2)),
            [[(2.0 / 3.0, c2), (1.0 / 3.0, c0)]
             for c2, c0 in zip(y2, y)])

    return step


def centered_diff_ttm(N: int, axis: int, dx: float,
                      base: int = 4) -> List[np.ndarray]:
    """The periodic centered first-derivative TT-matrix along one axis
    (``(q[i+1]-q[i-1])/(2 dx)``), compressed to its true numerical bond
    at build time — the single stencil-to-TTM recipe shared by the
    Burgers and SWE steppers (one place to fix, both stay in step).
    Returns numpy f64 cores (the eager build convention; cast at the
    jit boundary)."""
    op = ttm_add(ttm_scale(shift_ttm(N, axis, -1, base), 0.5),
                 ttm_scale(shift_ttm(N, axis, +1, base), -0.5))
    op = ttm_compress_np(op)
    return [np.asarray(c / dx if j == 0 else c, np.float64)
            for j, c in enumerate(op)]


def make_dense_swe_twin(N: int, gravity: float, depth: float,
                        dx: float, dt: float, f: float = 0.0,
                        nu: float = 0.0) -> Callable:
    """The dense jnp twin of :func:`make_qtt_swe_stepper` — SAME
    centered stencils, SAME anomaly split, SAME SSPRK3 — shared by the
    parity test (tests/test_qtt.py) and the rung-table probe
    (scripts/tt_probe.py ``qttswe``) so the correctness oracle and the
    benchmarked reference can never desynchronize.  ``step(s) -> s``
    over dense ``(h, u, v)`` arrays."""
    del N  # shapes come from the state; kept for signature symmetry

    def dgrad(q, axis):
        return (jnp.roll(q, -1, axis) - jnp.roll(q, 1, axis)) / (2 * dx)

    def lap(q):
        return (jnp.roll(q, 1, 0) + jnp.roll(q, -1, 0)
                + jnp.roll(q, 1, 1) + jnp.roll(q, -1, 1)
                - 4 * q) / (dx * dx)

    def rhs(s):
        h, u, v = s
        dh = (-depth * (dgrad(u, 1) + dgrad(v, 0))
              - dgrad(h * u, 1) - dgrad(h * v, 0))
        du = (-u * dgrad(u, 1) - v * dgrad(u, 0)
              - gravity * dgrad(h, 1) + f * v + nu * lap(u))
        dv = (-u * dgrad(v, 1) - v * dgrad(v, 0)
              - gravity * dgrad(h, 0) - f * u + nu * lap(v))
        return dh, du, dv

    def step(s):
        k1 = tuple(q + dt * d for q, d in zip(s, rhs(s)))
        k2 = tuple(0.75 * q + 0.25 * (q1 + dt * d)
                   for q, q1, d in zip(s, k1, rhs(k1)))
        return tuple(q / 3 + (2.0 / 3.0) * (q2 + dt * d)
                     for q, q2, d in zip(s, k2, rhs(k2)))

    return step


def make_qtt_burgers_stepper(N: int, nu: float, dx: float, dt: float,
                             rank: int, base: int = 4,
                             scheme: str = "ssprk3") -> Callable:
    """Jit-able QTT step for the 2-D viscous Burgers equation
    ``q_t = -q (q_x + q_y) + nu lap q`` (periodic) — the NONLINEAR
    demonstration of order-d stepping: the quadratic term is one
    Hadamard of the state with the gradient sum (the operator-rounded
    ``D`` has bond ~5, so the product bond entering the stage rounding
    is ~5 r^2 + state terms), mirroring how the order-2 layer handles
    the SWE's quadratic terms with Khatri-Rao + ACA.
    """
    dtype = jnp.zeros(()).dtype
    # The combined (d/dx + d/dy) operator from the shared per-axis
    # recipe, re-compressed to the true numerical bond of the sum —
    # every step's Hadamard and rounding cost scales with this bond.
    Dc = ttm_compress_np(ttm_add(centered_diff_ttm(N, 0, dx, base),
                                 centered_diff_ttm(N, 1, dx, base)))
    Dc = [jnp.asarray(c, dtype) for c in Dc]
    L = [jnp.asarray(c, dtype)
         for c in ttm_scale(laplacian_ttm(N, base), nu / (dx * dx))]

    combine = lambda parts: _combine(parts, rank)

    def rhs_parts(y):
        adv = qtt_hadamard(y, ttm_matvec(Dc, y))   # bond r * (bond_D r)
        return [(-dt, adv), (dt, ttm_matvec(L, y))]

    def step(y):
        if scheme == "euler":
            return combine(rhs_parts(y) + [(1.0, y)])
        if scheme != "ssprk3":
            raise ValueError(f"unknown scheme {scheme!r}")
        y1 = combine(rhs_parts(y) + [(1.0, y)])
        y2 = combine([(0.25 * c, p) for c, p in rhs_parts(y1)]
                     + [(0.25, y1), (0.75, y)])
        return combine([((2.0 / 3.0) * c, p) for c, p in rhs_parts(y2)]
                       + [(2.0 / 3.0, y2), (1.0 / 3.0, y)])

    return step
