"""QTT (order-d quantized TT) operator numerics — jit-able, O(log N).

The deck's compression claim is "N x N -> O(d N r^2)" (p.3); the
*quantized* TT form goes further: reshape the (N, N) field into base-b
digits (``tensor_train.quantize_shape``) and a smooth field's state is
``O(d b^2 r^2)`` with ``d = 2 log_b N`` — **sublinear in N**.  Round 1/2
built the compression layer (:mod:`.tensor_train`) and order-2 factored
*solvers*; this module closes the order-d gap: linear operators as
**TT-matrices** over the digit chain and a **static-rank two-sweep
rounding**, so an entire PDE step — matvec, add, round — runs inside
``jax.jit`` on cores whose shapes never depend on data.

Layout: the (N, N) field (index ``[y, x]``) becomes the order-2k tensor
``[y_0, x_0, y_1, x_1, ...]`` — digits most-significant first,
interleaved for locality (same digit convention as
``tensor_train.tt_compress_field``, but unmerged so each core owns ONE
digit of ONE axis, which is what makes per-axis operators cheap).

Operators: the periodic shift-by-one on a k-digit base-b index is an
exact TT-matrix of bond 2 — the bond carries the "carry" bit of the
increment; an axis operator threads that bond unchanged through the
other axis' digit cores.  The 5-point periodic Laplacian is then
``Sx + Sx' + Sy + Sy' - 4 I`` by block-diagonal TT-matrix addition
(bond 9, exact — no operator rounding needed).

References: Oseledets 2011 (TT), Kazeev & Khoromskij 2012 (explicit
QTT ranks of the 1-D Laplacian); deck p.3/5/19 for the thesis.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import numpy as np

import jax.numpy as jnp

from .tensor_train import (
    TTTensor,
    _block_diag_cores,
    quantize_shape,
    tt_decompose,
    tt_reconstruct,
)

__all__ = [
    "interleaved_digits", "qtt_compress", "qtt_compress_separable",
    "qtt_decompress",
    "shift_ttm", "identity_ttm", "ttm_add", "ttm_scale", "ttm_matvec",
    "laplacian_ttm", "tt_round_static", "make_qtt_diffusion_stepper",
]


# --------------------------------------------------------------- layout

def interleaved_digits(N: int, base: int = 4) -> List[int]:
    """Digit dims of the interleaved order-2k layout for an (N, N)
    field: ``[b, b, ..., b]`` of length ``2k`` with ``N = b^k``."""
    dy = quantize_shape(N, base)
    if any(v != base for v in dy):
        raise ValueError(f"N={N} is not a power of base={base}")
    return [base] * (2 * len(dy))


def _to_digit_tensor(q, base: int):
    """(N, N) -> interleaved digit tensor [y0, x0, y1, x1, ...]."""
    k = len(quantize_shape(q.shape[0], base))
    perm = [i for pair in zip(range(k), range(k, 2 * k)) for i in pair]
    return jnp.transpose(jnp.asarray(q).reshape((base,) * (2 * k)), perm)


def _from_digit_tensor(t, base: int):
    k = t.ndim // 2
    inv = [2 * i for i in range(k)] + [2 * i + 1 for i in range(k)]
    N = base ** k
    return jnp.transpose(t, inv).reshape(N, N)


def _pad_bond(c, r0: int, r1: int):
    """Zero-pad a core's bond dims up to (r0, n, r1)."""
    return jnp.pad(c, ((0, r0 - c.shape[0]), (0, 0),
                       (0, r1 - c.shape[2])))


def qtt_compress(q, rank: int, base: int = 4) -> List[jnp.ndarray]:
    """(N, N) -> static-rank core list (every bond exactly ``rank``,
    zero-padded past the field's numerical rank) in the interleaved
    digit layout.  Eager (TT-SVD); the stepper itself is jit-able."""
    t = _to_digit_tensor(np.asarray(q, np.float64), base)
    tt = tt_decompose(t, max_rank=rank)
    d = len(tt.cores)
    return [_pad_bond(c,
                      1 if j == 0 else rank,
                      1 if j == d - 1 else rank)
            for j, c in enumerate(tt.cores)]


def qtt_decompress(cores: Sequence[jnp.ndarray], base: int = 4):
    """Core list -> dense (N, N)."""
    return _from_digit_tensor(tt_reconstruct(TTTensor(list(cores))), base)


def qtt_compress_separable(rows, cols, rank: int,
                           base: int = 4) -> List[jnp.ndarray]:
    """Static-rank QTT cores of ``sum_k outer(rows[k], cols[k])``
    WITHOUT ever forming the (N, N) field — O(K N) work, so state prep
    stays feasible at N far beyond dense-array reach (N = 65536 is a
    128 MB field per f64 copy; its QTT state is a few kB).

    Each 1-D factor is TT-decomposed over its own digits (cheap); a
    term's interleaved 2-D cores are the factor cores Kronecker-threaded
    past the other axis' bond; terms sum block-diagonally and one
    static-rank rounding brings the result to ``rank``.
    """
    rows = np.asarray(rows, np.float64)
    cols = np.asarray(cols, np.float64)
    if rows.ndim == 1:
        rows, cols = rows[None], cols[None]
    K, N = rows.shape
    k = len(quantize_shape(N, base))
    terms = []
    for t in range(K):
        vy = tt_decompose(rows[t].reshape((base,) * k)).cores
        vx = tt_decompose(cols[t].reshape((base,) * k)).cores
        cores = []
        for j in range(k):
            ry0, _, ry1 = vy[j].shape
            rx0, _, rx1 = vx[j].shape
            # y_j: act on the y digit, thread the x bond (dim rx0).
            eye_x = jnp.eye(rx0)
            cores.append(jnp.einsum("anb,cd->acnbd", vy[j], eye_x)
                         .reshape(ry0 * rx0, base, ry1 * rx0))
            # x_j: act on the x digit, thread the (new) y bond — bond
            # index order is y-major on both sides, matching the y_j
            # cores' (ry, rx) flattening.
            eye_y = jnp.eye(ry1)
            cores.append(jnp.einsum("ef,anb->eanfb", eye_y, vx[j])
                         .reshape(ry1 * rx0, base, ry1 * rx1))
        terms.append(cores)
    # Block-diagonal sum of the K terms, then one fixed-rank rounding.
    d = 2 * k
    summed = terms[0]
    for term in terms[1:]:
        summed = [_block_diag_cores(a, b, j == 0, j == d - 1)
                  for j, (a, b) in enumerate(zip(summed, term))]
    out = tt_round_static(summed, rank)
    return [_pad_bond(c,
                      1 if j == 0 else rank,
                      1 if j == d - 1 else rank)
            for j, c in enumerate(out)]


# ---------------------------------------------------- TT-matrix algebra
# A TT-matrix is a list of cores (r, n_out, n_in, r').

def _carry_core(b: int, sign: int) -> np.ndarray:
    """The (2, b, b, 2) core of periodic shift-by-(+-1): left bond =
    carry OUT toward the more significant digit, right bond = carry IN
    from the less significant side.  ``core[c, d', d, cin] = 1`` iff
    ``d' = (d + sign*cin) mod b`` and ``c = 1`` exactly when the
    addition wrapped."""
    core = np.zeros((2, b, b, 2))
    for d in range(b):
        for cin in (0, 1):
            v = d + sign * cin
            core[1 if (v < 0 or v >= b) else 0, v % b, d, cin] = 1.0
    return core


def _pass_core(b: int) -> np.ndarray:
    """Identity on the digit, bond (2) threaded through unchanged."""
    core = np.zeros((2, b, b, 2))
    for c in (0, 1):
        for d in range(b):
            core[c, d, d, c] = 1.0
    return core


def shift_ttm(N: int, axis: int, sign: int,
              base: int = 4) -> List[jnp.ndarray]:
    """TT-matrix of the periodic shift ``q[..., i, ...] -> q[..., i+s,
    ...]`` along ``axis`` (0 = y, 1 = x) of the (N, N) field, on the
    interleaved digit chain.  Exact, bond 2.

    ``sign=+1`` gives the matrix with ``M[i', i] = 1`` iff ``i' = i + 1
    mod N``, i.e. ``(M q)[i] = q[i - 1]`` — values move forward.  The
    Laplacian uses both signs, so either convention closes it.
    """
    dims = interleaved_digits(N, base)
    cy = _carry_core(base, sign)
    pas = _pass_core(base)
    cores = []
    for j, b in enumerate(dims):
        is_axis = (j % 2) == axis
        cores.append(jnp.asarray(cy if is_axis else pas))
    # Boundary closure: the chain's right end injects carry = 1 (the
    # "+1"); the left end sums both carry states (mod-N wrap).  The
    # digits run most-significant-first, the axis' LAST digit core is
    # its least significant — but non-axis cores pass the bond through,
    # so closing at the chain ends is equivalent.
    left = jnp.asarray(np.ones((1, 2)))       # sum over final carry
    right = jnp.asarray(np.array([[0.0], [1.0]]))  # inject carry=1
    cores[0] = jnp.einsum("ab,bxyc->axyc", left, cores[0])
    cores[-1] = jnp.einsum("axyb,bc->axyc", cores[-1], right)
    return cores


def identity_ttm(N: int, base: int = 4) -> List[jnp.ndarray]:
    return [jnp.eye(b)[None, :, :, None]
            for b in interleaved_digits(N, base)]


def ttm_scale(op: Sequence[jnp.ndarray], s: float) -> List[jnp.ndarray]:
    out = list(op)
    out[0] = out[0] * s
    return out


def ttm_add(*ops: Sequence[jnp.ndarray]) -> List[jnp.ndarray]:
    """Block-diagonal TT-matrix sum (bonds add)."""
    d = len(ops[0])
    out = []
    for j in range(d):
        cs = [op[j] for op in ops]
        n_out, n_in = cs[0].shape[1], cs[0].shape[2]
        if j == 0:
            out.append(jnp.concatenate(cs, axis=3))
        elif j == d - 1:
            out.append(jnp.concatenate(cs, axis=0))
        else:
            r0 = sum(c.shape[0] for c in cs)
            r1 = sum(c.shape[3] for c in cs)
            blk = jnp.zeros((r0, n_out, n_in, r1), cs[0].dtype)
            a = b = 0
            for c in cs:
                blk = blk.at[a:a + c.shape[0], :, :,
                             b:b + c.shape[3]].set(c)
                a += c.shape[0]
                b += c.shape[3]
            out.append(blk)
    return out


def ttm_matvec(op: Sequence[jnp.ndarray],
               x: Sequence[jnp.ndarray]) -> List[jnp.ndarray]:
    """Apply a TT-matrix to a TT-vector core-by-core (bonds multiply)."""
    out = []
    for co, cx in zip(op, x):
        c = jnp.einsum("aijb,cjd->acibd", co, cx)
        out.append(c.reshape(co.shape[0] * cx.shape[0], co.shape[1],
                             co.shape[3] * cx.shape[2]))
    return out


def laplacian_ttm(N: int, base: int = 4) -> List[jnp.ndarray]:
    """The 5-point periodic Laplacian (unit spacing) as an exact
    TT-matrix (bond 9) on the interleaved digit chain."""
    ops = [shift_ttm(N, a, s, base) for a in (0, 1) for s in (1, -1)]
    ops.append(ttm_scale(identity_ttm(N, base), -4.0))
    return ttm_add(*ops)


# ------------------------------------------------- static-rank rounding

def tt_round_static(cores: Sequence[jnp.ndarray],
                    rank: int) -> List[jnp.ndarray]:
    """Two-sweep TT rounding at a FIXED output rank — fully jit-able.

    Right-to-left QR sweep orthogonalizes; the left-to-right truncation
    sweep QRs the (tall, possibly exactly rank-deficient) unfolding
    first — Householder QR is robust to zero columns — and SVDs only
    the small triangular factor, the same small-square-SVD shape class
    as the production ``solver._round_factored`` coupling core (runs
    NaN-free under jit where XLA's SVD of *tall rank-deficient
    unfoldings* is the documented eager-only failure mode,
    tensor_train.py).  Every bond truncates to ``min(rank, bond)`` and
    zero-pads back to exactly ``rank`` (interior bonds), so output
    shapes are static regardless of the input's (static) bond dims;
    padded directions are exact zeros.
    """
    d = len(cores)
    cs = list(cores)
    # Right-to-left orthogonalization (row-orthonormal right cores).
    for j in range(d - 1, 0, -1):
        r0, n, r1 = cs[j].shape
        q, r = jnp.linalg.qr(cs[j].reshape(r0, n * r1).T)
        k = q.shape[1]                       # min(r0, n*r1), static
        cs[j] = q.T.reshape(k, n, r1)
        cs[j - 1] = jnp.einsum("anb,cb->anc", cs[j - 1], r)
    # Left-to-right truncation sweep (QR + small-core SVD).
    for j in range(d - 1):
        r0, n, r1 = cs[j].shape
        q2, r2 = jnp.linalg.qr(cs[j].reshape(r0 * n, r1))
        u, s, vt = jnp.linalg.svd(r2)        # (min(m,r1), r1): small
        k = min(rank, s.shape[0])
        Q = q2 @ u[:, :k]
        R = s[:k, None] * vt[:k, :]
        if k < rank:
            Q = jnp.pad(Q, ((0, 0), (0, rank - k)))
            R = jnp.pad(R, ((0, rank - k), (0, 0)))
        cs[j] = Q.reshape(r0, n, rank)
        cs[j + 1] = jnp.einsum("ab,bnc->anc", R, cs[j + 1])
    return cs


# ------------------------------------------------------------- stepper

def make_qtt_diffusion_stepper(N: int, kappa: float, dx: float,
                               dt: float, rank: int, base: int = 4,
                               scheme: str = "ssprk3") -> Callable:
    """Jit-able QTT step for 2-D periodic diffusion ``q_t = kappa lap q``.

    The state is the static-rank core list of :func:`qtt_compress`; the
    step is matvec (bond-9 operator), axpy, and two-sweep rounding —
    every shape static, cost independent of N (O(d) small SVDs).
    """
    # Default real dtype (f64 under jax_enable_x64, else f32) — the
    # operator entries are exact small integers times kappa/dx^2.
    dtype = jnp.zeros(()).dtype
    L = [jnp.asarray(c, dtype)
         for c in ttm_scale(laplacian_ttm(N, base), kappa / (dx * dx))]

    def axpy(a, x, y):
        """a*x + y at static rank (block-diag add, then round)."""
        d = len(x)
        out = [_block_diag_cores(x[j] * (a if j == 0 else 1.0), y[j],
                                 j == 0, j == d - 1)
               for j in range(d)]
        return tt_round_static(out, rank)

    def rhs_step(y, scale):
        return axpy(scale * dt, ttm_matvec(L, y), y)

    def step(y):
        if scheme == "euler":
            return rhs_step(y, 1.0)
        if scheme != "ssprk3":
            raise ValueError(f"unknown scheme {scheme!r}")
        scale0 = lambda ys, a: [c * (a if j == 0 else 1.0)
                                for j, c in enumerate(ys)]
        y1 = rhs_step(y, 1.0)
        # y2 = 3/4 y + 1/4 (y1 + dt L y1)
        y2 = axpy(0.25, rhs_step(y1, 1.0), scale0(y, 0.75))
        # y' = 1/3 y + 2/3 (y2 + dt L y2)
        return axpy(2.0 / 3.0, rhs_step(y2, 1.0), scale0(y, 1.0 / 3.0))

    return step
