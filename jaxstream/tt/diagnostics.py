"""Diagnostics computed directly on factored (TT) panel fields.

The factored twins of :mod:`jaxstream.utils.diagnostics`: scalar
integrals and spectra without materializing any ``(n, n)`` panel —
O(n r^2) contractions instead of O(n^2) reductions, usable inside a
jitted factored run at every step.
"""

from __future__ import annotations

import weakref

import numpy as np

import jax.numpy as jnp

from .sphere import factor_panels, _numerical_rank

__all__ = ["factored_weighted_sum", "tt_total_mass", "panel_spectra"]

# Per-grid cache of the factored area weight: without it, every default
# tt_total_mass call would re-run a host-side O(6 n^3) SVD — the exact
# dense cost this module exists to avoid.  Keyed by id() (grids hold
# unhashable arrays); a finalizer evicts on garbage collection.
_AREA_CACHE: dict = {}


def factored_weighted_sum(w_pair, q_pair):
    """``sum_f sum_ij W[f,i,j] Q[f,i,j]`` with both operands factored.

    With ``W = Aw @ Bw`` and ``Q = A @ B`` per face, the weighted sum is
    ``sum_{s,r} (Aw^T A)_{sr} (Bw B^T)_{sr}`` — two thin matmuls and an
    elementwise product, O(n r rw) per face, exact (no rounding).
    """
    Aw, Bw = w_pair
    A, B = q_pair
    M1 = jnp.einsum("fis,fir->fsr", Aw, A)
    M2 = jnp.einsum("fsj,frj->fsr", Bw, B)
    return jnp.sum(M1 * M2)


def make_area_pair(grid, tol: float = 1e-12):
    """The cell-area weight field factored once per grid (numerically
    exact: the equiangular area element is smooth low rank); cached."""
    key = (id(grid), tol)
    hit = _AREA_CACHE.get(key)
    if hit is not None:
        return hit
    h, n = grid.halo, grid.n
    sl = slice(h, h + n)
    area = np.asarray(grid.area, np.float64)[:, sl, sl]
    pair = factor_panels(area, _numerical_rank(area, tol, 32))
    try:
        weakref.finalize(grid, _AREA_CACHE.pop, key, None)
    except TypeError:
        # Non-weakref-able grid: no finalizer means a later grid could
        # reuse this id() and read the wrong cached weights — don't cache.
        return pair
    _AREA_CACHE[key] = pair
    return pair


def tt_total_mass(grid, h_pair, area_pair=None):
    """``integral h dA`` from a factored height field — the factored
    twin of :func:`jaxstream.utils.diagnostics.total_mass`."""
    if area_pair is None:
        area_pair = make_area_pair(grid)
    return factored_weighted_sum(area_pair, h_pair)


def panel_spectra(q_pair):
    """Per-face singular values of the factored panels, (6, r).

    The TT-native spectrum diagnostic: QR-reduce each factor and take
    the SVD of the r x r core — O(n r^2), no (n, n) matrix.  Monitoring
    the tail of these values is how a factored run observes whether its
    rank is adequate (deck p.5's compressibility question, made
    measurable in-line).
    """
    A, B = q_pair
    qa, ra = jnp.linalg.qr(A)                    # (6, n, r), (6, r, r)
    qb, rb = jnp.linalg.qr(jnp.swapaxes(B, -1, -2))
    core = jnp.einsum("fsr,ftr->fst", ra, rb)
    return jnp.linalg.svd(core, compute_uv=False)
