"""TT-compressed advection on the cubed sphere — factored panels end to end.

The deck's whole TT thesis is compressing the *cubed-sphere* solver
("TT-friendly 2D tiles", p.4, feeding the solver pipeline's "Numerics
(TT)" box, p.7).  Round 1 left TT on periodic Cartesian panels; this
module runs the reference's flagship demo — cosine-bell advection
(TC1, deck p.13/18) — with every panel held as a rank-r factored form
``q_f = A_f @ B_f`` and **no (n, n) field ever materialized**:

* **Halo exchange on reconstructed edge strips** (the round-2 design
  called for by VERDICT): each face reconstructs only its four
  ``halo``-deep boundary strips from the factors (O(n h r) each), the
  strips route through the same connectivity/orientation table as every
  dense path (``geometry.connectivity``), and the received dense ghost
  strips re-enter the factored algebra as **rank-``halo`` correction
  pairs** of the derivative stencils — a ghost column times a stencil
  selector row is a rank-1 term.
* **Spatially-varying coefficients ride as factored fields**: the
  flux-form advection operator on a panel is
  ``dq/dt = -(1/sqrtg) [ D_a(Ca q) + D_b(Cb q) ]`` with
  ``Ca = sqrtg U^a``, ``Cb = sqrtg U^b`` (contravariant wind against
  the dual basis) and ``isg = 1/sqrtg`` — all smooth equiangular
  fields, factored once at build time to their numerical rank
  (``coeff_tol``, default 1e-7).  Products are Khatri-Rao pairs rounded by
  cross/ACA (:mod:`jaxstream.tt.cross`) — no eigh/SVD in the step.
* Discretization: 2nd-order centered flux differences on cell centers
  (the TT layer's own scheme; its dense twin
  :func:`make_dense_sphere_advection` shares the exact stencils and the
  exchange, and is the parity oracle in tests/test_tt_sphere.py).

State: ``(A, B)`` stacked over faces — ``A (6, n, r)``, ``B (6, r, n)``
with ``q[f] = A[f] @ B[f]`` matching the dense ``(6, n, n)`` interior
layout (axis -2 = beta/rows, axis -1 = alpha/cols).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp

from ..parallel.halo import (
    EDGE_E,
    EDGE_N,
    EDGE_S,
    EDGE_W,
    directed_copies,
)
from .cross import aca_lowrank
from .swe2d import kr_raw

__all__ = [
    "factor_panels", "unfactor_panels", "tt_strip_ghosts",
    "dense_strip_ghosts", "edge_resample", "resample_strip",
    "make_tt_sphere_advection", "make_dense_sphere_advection",
]


def factor_panels(q, rank: int):
    """(6, n, n) -> (A (6, n, rank), B (6, rank, n)), balanced SVD."""
    u, s, vt = np.linalg.svd(np.asarray(q, np.float64),
                             full_matrices=False)
    rs = np.sqrt(s[:, :rank])
    A = u[:, :, :rank] * rs[:, None, :]
    B = rs[:, :, None] * vt[:, :rank]
    return jnp.asarray(A), jnp.asarray(B)


def _numerical_rank(q, tol: float, cap: int) -> int:
    """Smallest rank covering every face to ``tol`` relative (<= cap)."""
    s = np.linalg.svd(np.asarray(q, np.float64), compute_uv=False)
    # Identically-zero faces (e.g. a localized topography away from its
    # panel) have s[0] = 0: they need rank 0, not a 0/0 warning.
    lead = np.where(s[:, :1] > 0.0, s[:, :1], 1.0)
    need = int(np.max((s / lead > tol).sum(axis=1)))
    return max(1, min(cap, need))


def unfactor_panels(q) -> jnp.ndarray:
    A, B = q
    return jnp.einsum("fnr,frm->fnm", A, B)


_COPIES = directed_copies()


def _read_strip_fact(A, B, face: int, edge: int, h: int):
    """Canonical (h, n) interior boundary strip reconstructed from the
    factors — the factored twin of ``parallel.halo.read_strip`` (which
    reads the extended array; interior row/col i here is extended
    index halo + i).  O(n h r)."""
    Af, Bf = A[face], B[face]
    if edge == EDGE_S:
        return Af[0:h, :] @ Bf                                  # (h, n)
    if edge == EDGE_N:
        return jnp.flip(Af[-h:, :] @ Bf, axis=-2)
    if edge == EDGE_W:
        return (Af @ Bf[:, 0:h]).T                              # -> (h, n)
    if edge == EDGE_E:
        return jnp.flip(Af @ Bf[:, -h:], axis=-1).T
    raise ValueError(edge)


def _read_strip_dense(q, face: int, edge: int, h: int):
    """Dense twin of :func:`_read_strip_fact`: canonical (h, n) interior
    boundary strip read straight from a ``(6, n, n)`` interior array."""
    qf = q[face]
    if edge == EDGE_S:
        return qf[0:h, :]
    if edge == EDGE_N:
        return jnp.flip(qf[-h:, :], axis=-2)
    if edge == EDGE_W:
        return qf[:, 0:h].T
    if edge == EDGE_E:
        return jnp.flip(qf[:, -h:], axis=-1).T
    raise ValueError(edge)


def _route_strips(read_strip, h: int):
    """Route canonical source strips through the connectivity table into
    placed per-edge ghost blocks — the shared core of the factored and
    dense strip exchanges.  ``read_strip(face, edge, h) -> (h, n)``."""
    gS = [None] * 6
    gN = [None] * 6
    gW = [None] * 6
    gE = [None] * 6
    for df, de, sf, se, rev in _COPIES:
        s = read_strip(sf, se, h)
        if rev:
            s = jnp.flip(s, axis=-1)
        # Place into the destination edge's ghost block with depth 0
        # adjacent to the interior (canonical depth axis already is).
        if de == EDGE_S:
            gS[df] = s
        elif de == EDGE_N:
            gN[df] = s
        elif de == EDGE_W:
            gW[df] = s.T
        elif de == EDGE_E:
            gE[df] = s.T
    return (jnp.stack(gS), jnp.stack(gN), jnp.stack(gW), jnp.stack(gE))


def tt_strip_ghosts(q, h: int):
    """Ghost strips for all faces from factored panels.

    Returns ``(gS, gN, gW, gE)``: ``gS/gN (6, h, n)`` with depth index 0
    = nearest the edge; ``gW/gE (6, n, h)`` likewise.  Exactly the
    values the dense exchanger writes into the ghost ring (same
    connectivity, canonicalization, and placement transforms), but no
    extended array exists anywhere.
    """
    A, B = q
    return _route_strips(lambda f, e, hh: _read_strip_fact(A, B, f, e, hh),
                         h)


def dense_strip_ghosts(q, h: int):
    """Ghost strips for all faces from a dense ``(6, n, n)`` interior
    array — same routing/placement as :func:`tt_strip_ghosts`, so dense
    twins of factored operators can share stencil code exactly."""
    return _route_strips(lambda f, e, hh: _read_strip_dense(q, f, e, hh), h)


def edge_resample(n: int, d: float, depth: int = 1):
    """Tangential resampling of a received ghost line onto the local
    coordinate continuation — the collocation-scheme seam fix.

    Geometry fact (verified to machine precision on all 24 edges in
    tests/test_tt_sphere_diffusion.py): the neighbor cells feeding a
    depth-``g`` ghost line lie **exactly on** the local continuation
    line ``alpha = pi/4 + (g - 1/2) d`` — the gnomonic line is a great
    circle in the plane mirror-symmetric through the cube edge — but at
    tangential positions ``beta_src(k) = arctan(c * tan(beta'_k))``,
    ``c = tan(pi/4 + (g - 1/2) d)``, fanned out by up to d/2 at the
    edge ends.  Treating them as if at the uniform ``beta_j`` (what a
    raw ghost copy does) is an O(d) value error — harmless to FV cell
    averages, fatal to 1/d^2-weighted collocation stencils.

    Returns ``(idx (n, 4) int32, wgt (n, 4))``: 4-point Lagrange
    interpolation from the fanned source positions to the uniform
    targets, O(d^4) on smooth fields; apply with
    :func:`resample_strip`.  Static data — build once per operator.
    """
    if n < 4:
        raise ValueError(f"edge_resample needs n >= 4 (got n={n}): the "
                         "4-point Lagrange window cannot be formed")
    b = -np.pi / 4 + (np.arange(n) + 0.5) * d
    c = np.tan(np.pi / 4 + (depth - 0.5) * d)
    src = np.arctan(c * np.tan(b))
    lo = np.clip(np.searchsorted(src, b) - 2, 0, n - 4)
    idx = lo[:, None] + np.arange(4)[None, :]             # (n, 4)
    x = src[idx]                                          # (n, 4)
    wgt = np.ones((n, 4))
    for m in range(4):
        for l in range(4):
            if l != m:
                wgt[:, m] *= (b - x[:, l]) / (x[:, m] - x[:, l])
    return idx.astype(np.int32), wgt


def resample_strip(s, idx, wgt):
    """Apply :func:`edge_resample` along the last axis of ``s``
    (``(..., n)`` ghost line) — a 4-tap gather, O(4 n)."""
    return jnp.einsum("...nm,nm->...n", s[..., idx],
                      jnp.asarray(wgt, s.dtype))


def resampled_ghost_lines(ghosts, idx, wgt):
    """Depth-1 ghost lines from placed strip blocks ``(gS, gN, gW,
    gE)``, tangentially resampled onto the continuation points — the
    shared seam-fix step of every collocation operator.  Returns a dict
    ``'S'/'N'/'W'/'E' -> (6, n)``."""
    gS, gN, gW, gE = ghosts
    rs = lambda v: resample_strip(v, idx, wgt)
    return {"S": rs(gS[:, 0, :]), "N": rs(gN[:, 0, :]),
            "W": rs(gW[:, :, 0]), "E": rs(gE[:, :, 0])}


def stack_pairs(pairs):
    """Stack a list of factor pairs into one unrounded pair: the exact
    factored form of the sum, rank = sum of ranks.  Single source of
    truth for the (A on axis 2, B on axis 1) layout."""
    return (jnp.concatenate([p[0] for p in pairs], axis=2),
            jnp.concatenate([p[1] for p in pairs], axis=1))


def _local_statics(ST, face_slice):
    """Device-local view of a face-leading statics pytree.

    Every static array in the factored factories carries the face axis
    FIRST (including factored coefficient pairs and the edge-statics
    dicts).  ``face_slice=None`` (single-device) returns ``ST``
    unchanged; under the panel-sharded tier it is
    ``lambda x: lax.dynamic_index_in_dim(x, lax.axis_index('panel'), 0,
    keepdims=True)`` — applied at trace time inside ``shard_map`` so
    each device computes with its own face's coefficients."""
    if face_slice is None:
        return ST
    return jax.tree_util.tree_map(face_slice, ST)


def _factored_stepper_multi(rhs_pairs, rnd_many, scheme: str) -> Callable:
    """SSPRK3/Euler stepper over a TUPLE of factored panel fields.

    ``rhs_pairs(state, scale)`` returns, per field, the (possibly
    stacked, unrounded) factor pair of ``scale * dt * RHS(state)``;
    ``rnd_many(list of stacked pairs) -> list of rounded pairs`` rounds
    every field's stage combine in ONE batched sweep (sequential-ACA
    latency is the TPU wall — see cross.aca_lowrank_many).  Single
    source of the scheme coefficients for every factored factory
    (advection, diffusion, SWE)."""

    def combines(per_field_pairs):
        return tuple(rnd_many([stack_pairs(p) for p in per_field_pairs]))

    def stage(y0, a, yc, b):
        ds = rhs_pairs(yc, b)
        return combines([
            ([(a * y0[k][0], y0[k][1])] if a != 0.0 else [])
            + [(b * yc[k][0], yc[k][1]), ds[k]]
            for k in range(len(ds))])

    def step(q):
        if scheme == "euler":
            ds = rhs_pairs(q, 1.0)
            return combines([[(q[k][0], q[k][1]), ds[k]]
                             for k in range(len(ds))])
        if scheme != "ssprk3":
            raise ValueError(f"unknown scheme {scheme!r}")
        y1 = stage(None, 0.0, q, 1.0)
        y2 = stage(q, 0.75, y1, 0.25)
        return stage(q, 1.0 / 3.0, y2, 2.0 / 3.0)

    return step


def _factored_stepper(rhs_pairs, aca, scheme: str) -> Callable:
    """Single-field convenience wrapper over
    :func:`_factored_stepper_multi` (state is one ``(A, B)`` pair;
    ``aca`` is the face-vmapped rounding fn)."""
    rnd_many = lambda ops: [tuple(aca(*p)) for p in ops]
    multi = _factored_stepper_multi(
        lambda s, scale: (rhs_pairs(s[0], scale),), rnd_many, scheme)
    return lambda q: multi((q,))[0]


def _diff_last(x, inv2d):
    """Centered first difference along the LAST axis, zero closure at
    both ends (ghost contributions enter as explicit rank-1 pairs).
    O(size) — shifted slices, no (n, n) matrix."""
    lo = jnp.pad(x[..., 1:], [(0, 0)] * (x.ndim - 1) + [(0, 1)])
    hi = jnp.pad(x[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    return (lo - hi) * inv2d


def _diff_mid(x, inv2d):
    """Same, along axis -2."""
    return jnp.swapaxes(_diff_last(jnp.swapaxes(x, -1, -2), inv2d), -1, -2)


def make_tt_sphere_advection(grid, wind_ext, dt: float, rank: int,
                             coeff_tol: float = 1e-7,
                             scheme: str = "ssprk3",
                             strip_ghosts=None,
                             face_slice=None) -> Callable:
    """Jit-able factored-panel SSPRK3 step for cosine-bell advection.

    ``wind_ext``: Cartesian wind on the extended grid ``(3, 6, M, M)``
    (the IC functions' output).  Coefficient fields are factored once
    here at their own numerical rank (``coeff_tol``; the equiangular
    metric/wind fields are nearly exact low rank — sqrtg U^a needs 4-5,
    1/sqrtg 3-4 — and the coefficient rank multiplies every product's
    Khatri-Rao rank, so auto-sizing it is the difference between TT
    winning and losing).  The returned ``step((A, B)) -> (A, B)`` never
    materializes a panel.

    ``strip_ghosts``/``face_slice``: the panel-sharded tier's injection
    points (:mod:`jaxstream.tt.shard`) — a device-local ppermute strip
    exchange replacing :func:`tt_strip_ghosts`, and the per-device
    statics slicer (:func:`_local_statics`).  Defaults run the
    single-device global exchange.
    """
    n, h = grid.n, grid.halo
    d = float(grid.dalpha)
    inv2d = 1.0 / (2.0 * d)

    # ---- dense coefficient prep (build time, numpy f64) ----------------
    sg = np.asarray(grid.sqrtg, np.float64)              # (6, M, M)
    ua = np.einsum("cfij,cfij->fij", np.asarray(grid.a_a, np.float64),
                   np.asarray(wind_ext, np.float64))
    ub = np.einsum("cfij,cfij->fij", np.asarray(grid.a_b, np.float64),
                   np.asarray(wind_ext, np.float64))
    Ca_e = sg * ua                                        # sqrtg U^a
    Cb_e = sg * ub
    sl = slice(h, h + n)
    Ca_i = Ca_e[:, sl, sl]
    Cb_i = Cb_e[:, sl, sl]
    isg_i = 1.0 / sg[:, sl, sl]
    ST = {
        "Ca": factor_panels(Ca_i, _numerical_rank(Ca_i, coeff_tol, 16)),
        "Cb": factor_panels(Cb_i, _numerical_rank(Cb_i, coeff_tol, 16)),
        "isg": factor_panels(isg_i, _numerical_rank(isg_i, coeff_tol, 16)),
        # Static ghost strips of the coefficients (placed layout, depth-1
        # nearest value only — the centered stencil reads one ghost deep).
        "CaW": jnp.asarray(Ca_e[:, sl, h - 1]),           # (6, n)
        "CaE": jnp.asarray(Ca_e[:, sl, h + n]),
        "CbS": jnp.asarray(Cb_e[:, h - 1, sl]),
        "CbN": jnp.asarray(Cb_e[:, h + n, sl]),
    }

    ridx, rwgt = edge_resample(n, d)

    dtype = ST["Ca"][0].dtype
    e0 = jnp.zeros((1, n), dtype).at[0, 0].set(1.0)
    eN = jnp.zeros((1, n), dtype).at[0, n - 1].set(1.0)
    if strip_ghosts is None:
        strip_ghosts = lambda q: tt_strip_ghosts(q, 1)

    aca = jax.vmap(lambda A, B: aca_lowrank(A, B, rank))

    # Batched-over-faces Khatri-Rao pair: same kernel (and column
    # ordering convention) as the Cartesian layer's kr_raw.
    kr_raw_f = jax.vmap(kr_raw)

    def rhs_pairs(q, scale):
        """Factor pairs (lists of (A (6,n,k), B (6,k,n))) of
        ``scale * dt * RHS(q)``."""
        S = _local_statics(ST, face_slice)
        gS, gN, gW, gE = strip_ghosts(q)
        # Flux pairs F = C (.) q, rank r * r_c.
        Fa = kr_raw_f(S["Ca"], q)
        Fb = kr_raw_f(S["Cb"], q)
        # Dense ghost values of the fluxes at the nearest ring — ghost q
        # resampled onto the local continuation positions (the seam fix,
        # :func:`edge_resample`) where the static coefficients live.
        rs = lambda v: resample_strip(v, ridx, rwgt)
        FaW = S["CaW"] * rs(gW[:, :, 0])                  # (F, n)
        FaE = S["CaE"] * rs(gE[:, :, 0])
        FbS = S["CbS"] * rs(gS[:, 0, :])
        FbN = S["CbN"] * rs(gN[:, 0, :])
        ones = jnp.ones((q[0].shape[0], 1, 1), dtype)
        # D_a F: columns (axis -1): shifted-slice difference on the B
        # factor (O(n r), no (n, n) matrix) + rank-1 ghost corrections
        # at columns 0 / n-1 (D_a F[i, 0] = (F[i, 1] - F_gW[i])/(2 d)).
        da = [
            (Fa[0], _diff_last(Fa[1], inv2d)),
            (FaW[:, :, None] * (-inv2d), ones * e0[None]),
            (FaE[:, :, None] * inv2d, ones * eN[None]),
        ]
        # D_b F: rows (axis -2): difference on the A factor's rows +
        # rank-1 ghost-row corrections.
        db = [
            (_diff_mid(Fb[0], inv2d), Fb[1]),
            (e0.T[None] * ones, FbS[:, None, :] * (-inv2d)),
            (eN.T[None] * ones, FbN[:, None, :] * inv2d),
        ]
        # Round the flux-divergence stack to rank first (keeps the isg
        # product's Khatri-Rao rank at r * r_c instead of
        # r_c * (2 r r_c + 4)), then multiply by isg and scale; the
        # stage combine performs the final rounding.
        dA, dB = aca(*stack_pairs(da + db))
        Ai, Bi = kr_raw_f(S["isg"], (dA, dB))
        return (-(scale * dt)) * Ai, Bi

    return _factored_stepper(rhs_pairs, aca, scheme)


def make_dense_sphere_advection(grid, wind_ext, dt: float,
                                scheme: str = "ssprk3") -> Callable:
    """Dense twin of :func:`make_tt_sphere_advection` — identical
    stencils, coefficients, and exchange; the parity oracle and the
    speed baseline.  ``step(q (6, n, n)) -> (6, n, n)``."""
    from ..parallel.halo import make_halo_exchanger

    n, h = grid.n, grid.halo
    d = float(grid.dalpha)
    inv2d = 1.0 / (2.0 * d)
    sl = slice(h, h + n)

    sg = np.asarray(grid.sqrtg, np.float64)
    ua = np.einsum("cfij,cfij->fij", np.asarray(grid.a_a, np.float64),
                   np.asarray(wind_ext, np.float64))
    ub = np.einsum("cfij,cfij->fij", np.asarray(grid.a_b, np.float64),
                   np.asarray(wind_ext, np.float64))
    Ca = jnp.asarray(sg * ua)
    Cb = jnp.asarray(sg * ub)
    isg = jnp.asarray(1.0 / sg[:, sl, sl])
    exchange = make_halo_exchanger(n, h, fill_corners=False)
    m = n + 2 * h
    ridx, rwgt = edge_resample(n, d)

    def rhs(q):
        ext = jnp.zeros((6, m, m), q.dtype).at[:, sl, sl].set(q)
        ext = exchange(ext)
        # Resample the depth-1 ghost lines (all the centered stencil
        # reads) onto the continuation positions — same seam fix as the
        # factored path, keeping the two twins the same discretization.
        rs = lambda v: resample_strip(v, ridx, rwgt)
        for line in ((slice(None), sl, h - 1), (slice(None), sl, h + n),
                     (slice(None), h - 1, sl), (slice(None), h + n, sl)):
            ext = ext.at[line].set(rs(ext[line]))
        F_a = Ca * ext
        F_b = Cb * ext
        da = (F_a[:, sl, h + 1:h + n + 1] - F_a[:, sl, h - 1:h + n - 1])
        db = (F_b[:, h + 1:h + n + 1, sl] - F_b[:, h - 1:h + n - 1, sl])
        return -isg * inv2d * (da + db)

    def step(q):
        if scheme == "euler":
            return q + dt * rhs(q)
        k = rhs(q)
        y1 = q + dt * k
        y2 = 0.75 * q + 0.25 * (y1 + dt * rhs(y1))
        return q / 3.0 + (2.0 / 3.0) * (y2 + dt * rhs(y2))

    return step
