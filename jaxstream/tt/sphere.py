"""TT-compressed advection on the cubed sphere — factored panels end to end.

The deck's whole TT thesis is compressing the *cubed-sphere* solver
("TT-friendly 2D tiles", p.4, feeding the solver pipeline's "Numerics
(TT)" box, p.7).  Round 1 left TT on periodic Cartesian panels; this
module runs the reference's flagship demo — cosine-bell advection
(TC1, deck p.13/18) — with every panel held as a rank-r factored form
``q_f = A_f @ B_f`` and **no (n, n) field ever materialized**:

* **Halo exchange on reconstructed edge strips** (the round-2 design
  called for by VERDICT): each face reconstructs only its four
  ``halo``-deep boundary strips from the factors (O(n h r) each), the
  strips route through the same connectivity/orientation table as every
  dense path (``geometry.connectivity``), and the received dense ghost
  strips re-enter the factored algebra as **rank-``halo`` correction
  pairs** of the derivative stencils — a ghost column times a stencil
  selector row is a rank-1 term.
* **Spatially-varying coefficients ride as factored fields**: the
  flux-form advection operator on a panel is
  ``dq/dt = -(1/sqrtg) [ D_a(Ca q) + D_b(Cb q) ]`` with
  ``Ca = sqrtg U^a``, ``Cb = sqrtg U^b`` (contravariant wind against
  the dual basis) and ``isg = 1/sqrtg`` — all smooth equiangular
  fields, factored once at build time to their numerical rank
  (``coeff_tol``, default 1e-7).  Products are Khatri-Rao pairs rounded by
  cross/ACA (:mod:`jaxstream.tt.cross`) — no eigh/SVD in the step.
* Discretization: 2nd-order centered flux differences on cell centers
  (the TT layer's own scheme; its dense twin
  :func:`make_dense_sphere_advection` shares the exact stencils and the
  exchange, and is the parity oracle in tests/test_tt_sphere.py).

State: ``(A, B)`` stacked over faces — ``A (6, n, r)``, ``B (6, r, n)``
with ``q[f] = A[f] @ B[f]`` matching the dense ``(6, n, n)`` interior
layout (axis -2 = beta/rows, axis -1 = alpha/cols).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp

from ..parallel.halo import (
    EDGE_E,
    EDGE_N,
    EDGE_S,
    EDGE_W,
    directed_copies,
)
from .cross import aca_lowrank
from .swe2d import kr_raw

__all__ = [
    "factor_panels", "unfactor_panels", "tt_strip_ghosts",
    "make_tt_sphere_advection", "make_dense_sphere_advection",
]


def factor_panels(q, rank: int):
    """(6, n, n) -> (A (6, n, rank), B (6, rank, n)), balanced SVD."""
    u, s, vt = np.linalg.svd(np.asarray(q, np.float64),
                             full_matrices=False)
    rs = np.sqrt(s[:, :rank])
    A = u[:, :, :rank] * rs[:, None, :]
    B = rs[:, :, None] * vt[:, :rank]
    return jnp.asarray(A), jnp.asarray(B)


def _numerical_rank(q, tol: float, cap: int) -> int:
    """Smallest rank covering every face to ``tol`` relative (<= cap)."""
    s = np.linalg.svd(np.asarray(q, np.float64), compute_uv=False)
    need = int(np.max((s / s[:, :1] > tol).sum(axis=1)))
    return max(1, min(cap, need))


def unfactor_panels(q) -> jnp.ndarray:
    A, B = q
    return jnp.einsum("fnr,frm->fnm", A, B)


_COPIES = directed_copies()


def _read_strip_fact(A, B, face: int, edge: int, h: int):
    """Canonical (h, n) interior boundary strip reconstructed from the
    factors — the factored twin of ``parallel.halo.read_strip`` (which
    reads the extended array; interior row/col i here is extended
    index halo + i).  O(n h r)."""
    Af, Bf = A[face], B[face]
    if edge == EDGE_S:
        return Af[0:h, :] @ Bf                                  # (h, n)
    if edge == EDGE_N:
        return jnp.flip(Af[-h:, :] @ Bf, axis=-2)
    if edge == EDGE_W:
        return (Af @ Bf[:, 0:h]).T                              # -> (h, n)
    if edge == EDGE_E:
        return jnp.flip(Af @ Bf[:, -h:], axis=-1).T
    raise ValueError(edge)


def tt_strip_ghosts(q, h: int):
    """Ghost strips for all faces from factored panels.

    Returns ``(gS, gN, gW, gE)``: ``gS/gN (6, h, n)`` with depth index 0
    = nearest the edge; ``gW/gE (6, n, h)`` likewise.  Exactly the
    values the dense exchanger writes into the ghost ring (same
    connectivity, canonicalization, and placement transforms), but no
    extended array exists anywhere.
    """
    A, B = q
    n = A.shape[1]
    gS = [None] * 6
    gN = [None] * 6
    gW = [None] * 6
    gE = [None] * 6
    for df, de, sf, se, rev in _COPIES:
        s = _read_strip_fact(A, B, sf, se, h)
        if rev:
            s = jnp.flip(s, axis=-1)
        # Place into the destination edge's ghost block with depth 0
        # adjacent to the interior (canonical depth axis already is).
        if de == EDGE_S:
            gS[df] = s
        elif de == EDGE_N:
            gN[df] = s
        elif de == EDGE_W:
            gW[df] = s.T
        elif de == EDGE_E:
            gE[df] = s.T
    return (jnp.stack(gS), jnp.stack(gN), jnp.stack(gW), jnp.stack(gE))


def _diff_last(x, inv2d):
    """Centered first difference along the LAST axis, zero closure at
    both ends (ghost contributions enter as explicit rank-1 pairs).
    O(size) — shifted slices, no (n, n) matrix."""
    lo = jnp.pad(x[..., 1:], [(0, 0)] * (x.ndim - 1) + [(0, 1)])
    hi = jnp.pad(x[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    return (lo - hi) * inv2d


def _diff_mid(x, inv2d):
    """Same, along axis -2."""
    return jnp.swapaxes(_diff_last(jnp.swapaxes(x, -1, -2), inv2d), -1, -2)


def make_tt_sphere_advection(grid, wind_ext, dt: float, rank: int,
                             coeff_tol: float = 1e-7,
                             scheme: str = "ssprk3") -> Callable:
    """Jit-able factored-panel SSPRK3 step for cosine-bell advection.

    ``wind_ext``: Cartesian wind on the extended grid ``(3, 6, M, M)``
    (the IC functions' output).  Coefficient fields are factored once
    here at their own numerical rank (``coeff_tol``; the equiangular
    metric/wind fields are nearly exact low rank — sqrtg U^a needs 4-5,
    1/sqrtg 3-4 — and the coefficient rank multiplies every product's
    Khatri-Rao rank, so auto-sizing it is the difference between TT
    winning and losing).  The returned ``step((A, B)) -> (A, B)`` never
    materializes a panel.
    """
    n, h = grid.n, grid.halo
    d = float(grid.dalpha)
    inv2d = 1.0 / (2.0 * d)

    # ---- dense coefficient prep (build time, numpy f64) ----------------
    sg = np.asarray(grid.sqrtg, np.float64)              # (6, M, M)
    ua = np.einsum("cfij,cfij->fij", np.asarray(grid.a_a, np.float64),
                   np.asarray(wind_ext, np.float64))
    ub = np.einsum("cfij,cfij->fij", np.asarray(grid.a_b, np.float64),
                   np.asarray(wind_ext, np.float64))
    Ca_e = sg * ua                                        # sqrtg U^a
    Cb_e = sg * ub
    sl = slice(h, h + n)
    Ca_i = Ca_e[:, sl, sl]
    Cb_i = Cb_e[:, sl, sl]
    isg_i = 1.0 / sg[:, sl, sl]
    Ca_tt = factor_panels(Ca_i, _numerical_rank(Ca_i, coeff_tol, 16))
    Cb_tt = factor_panels(Cb_i, _numerical_rank(Cb_i, coeff_tol, 16))
    isg_tt = factor_panels(isg_i, _numerical_rank(isg_i, coeff_tol, 16))
    # Static ghost strips of the coefficients (placed layout, depth-1
    # nearest value only — the centered stencil reads one ghost deep).
    CaW = jnp.asarray(Ca_e[:, sl, h - 1])                 # (6, n)
    CaE = jnp.asarray(Ca_e[:, sl, h + n])
    CbS = jnp.asarray(Cb_e[:, h - 1, sl])
    CbN = jnp.asarray(Cb_e[:, h + n, sl])

    dtype = Ca_tt[0].dtype
    e0 = jnp.zeros((1, n), dtype).at[0, 0].set(1.0)
    eN = jnp.zeros((1, n), dtype).at[0, n - 1].set(1.0)

    aca = jax.vmap(lambda A, B: aca_lowrank(A, B, rank))

    # Batched-over-faces Khatri-Rao pair: same kernel (and column
    # ordering convention) as the Cartesian layer's kr_raw.
    kr_raw_f = jax.vmap(kr_raw)

    def rhs_pairs(q, scale):
        """Factor pairs (lists of (A (6,n,k), B (6,k,n))) of
        ``scale * dt * RHS(q)``."""
        gS, gN, gW, gE = tt_strip_ghosts(q, 1)
        # Flux pairs F = C (.) q, rank r * r_c.
        Fa = kr_raw_f(Ca_tt, q)
        Fb = kr_raw_f(Cb_tt, q)
        # Dense ghost values of the fluxes at the nearest ring.
        FaW = CaW * gW[:, :, 0]                           # (6, n)
        FaE = CaE * gE[:, :, 0]
        FbS = CbS * gS[:, 0, :]
        FbN = CbN * gN[:, 0, :]
        ones = jnp.ones((6, 1, 1), dtype)
        # D_a F: columns (axis -1): shifted-slice difference on the B
        # factor (O(n r), no (n, n) matrix) + rank-1 ghost corrections
        # at columns 0 / n-1 (D_a F[i, 0] = (F[i, 1] - F_gW[i])/(2 d)).
        da = [
            (Fa[0], _diff_last(Fa[1], inv2d)),
            (FaW[:, :, None] * (-inv2d), ones * e0[None]),
            (FaE[:, :, None] * inv2d, ones * eN[None]),
        ]
        # D_b F: rows (axis -2): difference on the A factor's rows +
        # rank-1 ghost-row corrections.
        db = [
            (_diff_mid(Fb[0], inv2d), Fb[1]),
            (e0.T[None] * ones, FbS[:, None, :] * (-inv2d)),
            (eN.T[None] * ones, FbN[:, None, :] * inv2d),
        ]
        # Round the flux-divergence stack to rank first (keeps the isg
        # product's Khatri-Rao rank at r * r_c instead of
        # r_c * (2 r r_c + 4)), then multiply by isg and scale; the
        # stage combine performs the final rounding.
        Astk = jnp.concatenate([p[0] for p in da + db], axis=2)
        Bstk = jnp.concatenate([p[1] for p in da + db], axis=1)
        dA, dB = aca(Astk, Bstk)
        Ai, Bi = kr_raw_f(isg_tt, (dA, dB))
        return (-(scale * dt)) * Ai, Bi

    def combine(pairs):
        Astk = jnp.concatenate([p[0] for p in pairs], axis=2)
        Bstk = jnp.concatenate([p[1] for p in pairs], axis=1)
        return tuple(aca(Astk, Bstk))

    def stage(y0, a, yc, b):
        dA, dB = rhs_pairs(yc, b)
        pairs = ([(a * y0[0], y0[1])] if a != 0.0 else []) \
            + [(b * yc[0], yc[1]), (dA, dB)]
        return combine(pairs)

    def step(q):
        if scheme == "euler":
            dA, dB = rhs_pairs(q, 1.0)
            return combine([(q[0], q[1]), (dA, dB)])
        if scheme != "ssprk3":
            raise ValueError(f"unknown scheme {scheme!r}")
        y1 = stage(None, 0.0, q, 1.0)
        y2 = stage(q, 0.75, y1, 0.25)
        return stage(q, 1.0 / 3.0, y2, 2.0 / 3.0)

    return step


def make_dense_sphere_advection(grid, wind_ext, dt: float,
                                scheme: str = "ssprk3") -> Callable:
    """Dense twin of :func:`make_tt_sphere_advection` — identical
    stencils, coefficients, and exchange; the parity oracle and the
    speed baseline.  ``step(q (6, n, n)) -> (6, n, n)``."""
    from ..parallel.halo import make_halo_exchanger

    n, h = grid.n, grid.halo
    d = float(grid.dalpha)
    inv2d = 1.0 / (2.0 * d)
    sl = slice(h, h + n)

    sg = np.asarray(grid.sqrtg, np.float64)
    ua = np.einsum("cfij,cfij->fij", np.asarray(grid.a_a, np.float64),
                   np.asarray(wind_ext, np.float64))
    ub = np.einsum("cfij,cfij->fij", np.asarray(grid.a_b, np.float64),
                   np.asarray(wind_ext, np.float64))
    Ca = jnp.asarray(sg * ua)
    Cb = jnp.asarray(sg * ub)
    isg = jnp.asarray(1.0 / sg[:, sl, sl])
    exchange = make_halo_exchanger(n, h, fill_corners=False)
    m = n + 2 * h

    def rhs(q):
        ext = jnp.zeros((6, m, m), q.dtype).at[:, sl, sl].set(q)
        ext = exchange(ext)
        F_a = Ca * ext
        F_b = Cb * ext
        da = (F_a[:, sl, h + 1:h + n + 1] - F_a[:, sl, h - 1:h + n - 1])
        db = (F_b[:, h + 1:h + n + 1, sl] - F_b[:, h - 1:h + n - 1, sl])
        return -isg * inv2d * (da + db)

    def step(q):
        if scheme == "euler":
            return q + dt * rhs(q)
        k = rhs(q)
        y1 = q + dt * k
        y2 = 0.75 * q + 0.25 * (y1 + dt * rhs(y1))
        return q / 3.0 + (2.0 / 3.0) * (y2 + dt * rhs(y2))

    return step
