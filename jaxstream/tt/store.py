"""TT-compressed checkpointing of dense states.

Two directions, both riding the existing Orbax
:class:`jaxstream.io.checkpoint.CheckpointManager` unchanged (it
accepts any pytree, so a *factored run's* state — pairs of thin factors
— already checkpoints compressed with no code here):

* ``compress_state``: factor each compressible ``(6, n, n)`` leaf of a
  *dense* state to rank r before saving — an O(n/r)-smaller restart
  artifact with SVD-truncation (lossy, bounded, reported) error;
* ``decompress_state``: reconstruct on restore.

Non-2D / non-float leaves and panels needing full rank pass through
unchanged (marked raw), so the round trip is always well-defined.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax.numpy as jnp

from .sphere import factor_panels, unfactor_panels

__all__ = ["compress_state", "decompress_state"]


def _compressible(v) -> bool:
    a = np.asarray(v)
    return (a.ndim == 3 and a.dtype.kind == "f"
            and a.shape[1] == a.shape[2] and a.shape[2] > 0)


def compress_state(state: Dict[str, Any], rank: int) -> Dict[str, Any]:
    """Dense state dict -> TT-compressed checkpoint payload.

    Each compressible leaf ``name`` becomes ``name__ttA`` /
    ``name__ttB`` (balanced SVD factors, rank ``min(rank, n)``); other
    leaves pass through.  Inverse: :func:`decompress_state`.
    """
    out: Dict[str, Any] = {"__tt_rank__": int(rank)}
    for k, v in state.items():
        n = np.asarray(v).shape[-1] if _compressible(v) else 0
        # Factor only when the factors are actually smaller (2 r n <
        # n^2); a panel needing full-ish rank passes through raw.
        if _compressible(v) and 2 * min(rank, n) * n < n * n:
            A, B = factor_panels(np.asarray(v), min(rank, n))
            out[k + "__ttA"] = A
            out[k + "__ttB"] = B
        else:
            out[k] = v
    return out


def decompress_state(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`compress_state` (idempotent on raw states)."""
    out: Dict[str, Any] = {}
    for k, v in payload.items():
        if k == "__tt_rank__" or k.endswith("__ttB"):
            continue
        if k.endswith("__ttA"):
            name = k[: -len("__ttA")]
            out[name] = unfactor_panels((jnp.asarray(v),
                                         jnp.asarray(payload[name + "__ttB"])))
        else:
            out[k] = v
    return out
