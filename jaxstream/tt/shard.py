"""Panel-sharded factored (TT) tier: one cubed-sphere face per device.

Puts the deck's "Numerics (TT)" stage *inside* the parallelization
pipeline (pdf p.7: the TT tier sits downstream of the halo exchange in
the sharded pipeline — round-3 verdict ask #4): the rank-r factor pairs
``(A (6, n, r), B (6, r, n))`` shard over a 6-device ``('panel',)``
mesh, and the reconstructed depth-1 edge strips cross panels as
``lax.ppermute`` payloads over the SAME race-free 4-stage connectivity
schedule the dense explicit paths use
(:class:`jaxstream.parallel.shard_halo.ShardHaloProgram`, built from
:func:`jaxstream.geometry.connectivity.build_schedule`).

Design: the single-device factories
(:func:`..sphere.make_tt_sphere_advection`,
:func:`..sphere_diffusion.make_tt_sphere_diffusion`,
:func:`..sphere_swe.make_tt_sphere_swe`) expose two injection points —
``strip_ghosts`` (the exchange) and ``face_slice`` (per-device statics
slicing) — and this module supplies the sharded implementations and
wraps the resulting device-local step in ``jax.shard_map``.  All the
factored numerics (Khatri-Rao products, shifted-slice derivatives,
ACA rounding) are face-local and run unchanged on the local
``(1, n, r)`` slices; only the strip exchange communicates, and its
payloads are O(n) lines.  MEASURED from the compiled HLO's
collective-permutes (scripts/tt_probe.py ``sharded`` mode, round 5):
exactly r-independent — 2 304 elements/step at C48 for rank 12 AND
rank 24 (4 608 at C96) — and 0.67x the dense explicit-ppermute tier's
per-step volume at every n (both are O(n); the factored tier ships
depth-1 reconstructed strips where the dense tier ships depth-halo
strips).  The structural win over exchanging factors directly is that
payloads do not grow with rank.

Parity: bitwise-equal routing with the single-device
:func:`..sphere.tt_strip_ghosts` is asserted in
tests/test_tt_shard.py, along with end-to-end step parity for all
three families on 6 virtual CPU devices.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.jax_compat import shard_map

from ..parallel.halo import EDGE_E, EDGE_N, EDGE_S, EDGE_W
from ..parallel.shard_halo import ShardHaloProgram
from .sphere import _read_strip_fact

__all__ = [
    "make_tt_strip_exchange",
    "make_tt_strip_exchange_many",
    "make_tt_ensemble_exchange",
    "make_tt_sphere_advection_sharded",
    "make_tt_sphere_diffusion_sharded",
    "make_tt_sphere_swe_sharded",
    "panel_mesh",
    "shard_factored_state",
]


def panel_mesh(devices=None, axis_name: str = "panel") -> Mesh:
    """A 1-D 6-device ``('panel',)`` mesh — device i owns face i."""
    import numpy as np

    if devices is None:
        devices = jax.devices()
    if len(devices) < 6:
        raise ValueError(
            f"the panel-sharded TT tier needs 6 devices (one face "
            f"each); got {len(devices)}")
    return Mesh(np.array(devices[:6]), (axis_name,))


def shard_factored_state(state, mesh, axis_name: str = "panel"):
    """Place a face-leading factored-state pytree on the panel mesh."""
    sh = NamedSharding(mesh, P(axis_name))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), state)


def make_tt_strip_exchange_many(axis_name: str = "panel"):
    """Batched device-local strip exchange: ONE schedule, many fields.

    Returns ``exchange_many(pairs) -> [(gS, gN, gW, gE), ...]`` over a
    list of LOCAL one-face factor pairs ``(A (1, n, r_i), B (1, r_i,
    n))``.  All fields' canonical depth-1 strips are stacked into a
    single ``(P, 1, n)`` payload per stage, so the 4-stage race-free
    schedule's ICI latency chain is paid ONCE for the whole field set
    instead of once per field — the factored-tier face of the
    overlapped-exchange redesign (``parallelization.overlap_exchange``):
    the SWE step's four exchanges (h + three Cartesian velocity
    components) collapse to one, and every ppermute is issued up front
    where only the strip reconstructions (O(n r) matvecs) precede it,
    so the collectives fly under the step's Khatri-Rao/rounding work.
    Per-field ghost values are bitwise-identical to the per-field
    exchange (a ppermute of stacked payloads IS the stack of per-field
    ppermutes).
    """
    program = ShardHaloProgram(axis_name)
    edge_sel = program.edge_sel            # (6, 4) int32
    rev_sel = jnp.asarray(program.rev_sel)  # (6, 4) bool

    def exchange_many(pairs):
        for A, B in pairs:
            if A.shape[0] != 1:
                raise ValueError(
                    f"panel-sharded TT exchange expects one face per "
                    f"device (local face extent 1); got {A.shape[0]} — "
                    "run the single-device tier for other layouts")
        f = lax.axis_index(axis_name)
        esel = edge_sel[f]                  # (4,) traced
        rsel = rev_sel[f]
        # All fields' four canonical (1, n) strips (h=1), reconstructed
        # once from the factors: (P, 4, 1, n).
        strips = jnp.stack([
            jnp.stack([_read_strip_fact(A, B, 0, e, 1) for e in range(4)])
            for A, B in pairs])
        recv = jnp.zeros_like(strips)
        for s, perm in enumerate(program.perms):
            st = jnp.take(strips, esel[s], axis=1)       # (P, 1, n)
            st = jnp.where(rsel[s], jnp.flip(st, axis=-1), st)
            st = lax.ppermute(st, axis_name, perm)
            # The strip received in stage s belongs to the same edge I
            # exchanged (edge pairs are bidirectional on the cube edge).
            recv = recv.at[:, esel[s]].set(st)
        # Placement transforms of sphere._route_strips: S/N canonical,
        # W/E transposed; leading face axis restored as 1.
        out = []
        for p in range(len(pairs)):
            gS = recv[p, EDGE_S][None]             # (1, 1, n)
            gN = recv[p, EDGE_N][None]
            gW = jnp.swapaxes(recv[p, EDGE_W], -2, -1)[None]   # (1, n, 1)
            gE = jnp.swapaxes(recv[p, EDGE_E], -2, -1)[None]
            out.append((gS, gN, gW, gE))
        return out

    return exchange_many


def make_tt_ensemble_exchange(axis_name: str = "panel"):
    """Ensemble form of :func:`make_tt_strip_exchange_many`.

    Returns ``exchange(member_pairs) -> [[(gS, gN, gW, gE), ...], ...]``
    over a list of B members, each a list of that member's local factor
    pairs (e.g. the factored SWE's ``(h, ua, ub)``).  All members'
    fields flatten into ONE :func:`make_tt_strip_exchange_many`
    schedule, so the whole ensemble's strips ride a single 4-stage
    ppermute chain — per-stage payload ``(B * P, 1, n)`` — and the ICI
    latency chain is paid once per ensemble step instead of once per
    member.  Per-field ghosts are bitwise-identical to a per-member
    exchange loop (a ppermute of stacked payloads IS the stack of
    per-member ppermutes; tested in tests/test_ensemble.py).
    """
    exchange_many = make_tt_strip_exchange_many(axis_name)

    def exchange(member_pairs):
        sizes = [len(m) for m in member_pairs]
        out = exchange_many([p for m in member_pairs for p in m])
        res, i = [], 0
        for s in sizes:
            res.append(out[i:i + s])
            i += s
        return res

    return exchange


def make_tt_strip_exchange(axis_name: str = "panel"):
    """Device-local factored strip exchange for use inside shard_map.

    Returns ``exchange(pair) -> (gS, gN, gW, gE)`` operating on a LOCAL
    one-face factor pair ``(A (1, n, r), B (1, r, n))``: reconstructs
    the four canonical depth-1 boundary strips from the factors
    (O(n r) each, never the panel), then runs the 4-stage race-free
    schedule — per stage every device flips its outgoing strip if the
    edge pair reverses and one joint ``ppermute`` moves all six strips
    at once.  Output blocks match :func:`..sphere.tt_strip_ghosts`
    exactly (same canonicalization and placement transforms, leading
    face axis of 1).  The single-field form of
    :func:`make_tt_strip_exchange_many`.
    """
    exchange_many = make_tt_strip_exchange_many(axis_name)

    def exchange(pair):
        return exchange_many([pair])[0]

    return exchange


def _face_slicer(axis_name: str):
    return lambda x: lax.dynamic_index_in_dim(
        x, lax.axis_index(axis_name), 0, keepdims=True)


def _shard_step(build_local, mesh, axis_name: str):
    """Build the device-local step via ``build_local(strip_ghosts,
    face_slice)`` and wrap it in shard_map over the panel axis."""
    if dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis_name) != 6:
        raise ValueError(
            f"the panel-sharded TT tier needs a 6-device '{axis_name}' "
            f"mesh axis; got {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    step_local = build_local(
        strip_ghosts=make_tt_strip_exchange(axis_name),
        face_slice=_face_slicer(axis_name))
    spec = P(axis_name)
    # check_vma=False: the ACA rounding loop initializes its fori_loop
    # carry from replicated zeros, which the varying-manual-axes checker
    # rejects against the axis-varying loop outputs; the computation is
    # per-device-pure so the check adds nothing here.
    return shard_map(step_local, mesh=mesh,
                         in_specs=spec, out_specs=spec, check_vma=False)


def make_tt_sphere_advection_sharded(grid, wind_ext, dt, rank, mesh,
                                     axis_name: str = "panel", **kw):
    """Panel-sharded :func:`..sphere.make_tt_sphere_advection`."""
    from .sphere import make_tt_sphere_advection

    return _shard_step(
        partial(make_tt_sphere_advection, grid, wind_ext, dt, rank, **kw),
        mesh, axis_name)


def make_tt_sphere_diffusion_sharded(grid, kappa, dt, rank, mesh,
                                     axis_name: str = "panel", **kw):
    """Panel-sharded :func:`..sphere_diffusion.make_tt_sphere_diffusion`."""
    from .sphere_diffusion import make_tt_sphere_diffusion

    return _shard_step(
        partial(make_tt_sphere_diffusion, grid, kappa, dt, rank, **kw),
        mesh, axis_name)


def make_tt_sphere_swe_sharded(grid, dt, rank, mesh,
                               axis_name: str = "panel",
                               overlap_exchange: bool = False,
                               temporal_block: int = 1, **kw):
    """Panel-sharded :func:`..sphere_swe.make_tt_sphere_swe`.

    ``temporal_block = k > 1`` fuses k steps *inside* the shard_map
    body (``parallelization.temporal_block``): one SPMD dispatch per k
    steps.  The exchange/rounding sequence is unchanged (the TT ghost
    lines are rebuilt from the rounded factors every stage either way),
    so reconstructed fields stay bitwise-equal to k=1 — on this tier
    temporal blocking amortizes dispatch, not collectives.

    ``batch_rounding`` defaults to False here regardless of backend:
    the device-local operands are one face, where the zero-padding
    traffic of the batched ACA sweep loses (the measured trade in
    DESIGN.md is for 6-face operands on one chip).

    ``overlap_exchange``: route the step's four per-field exchanges
    (h + three Cartesian velocity components) through ONE batched
    4-stage schedule issued up front
    (:func:`make_tt_strip_exchange_many`) — the ICI latency chain is
    paid once per step instead of four times, and the collectives
    overlap the step's face-local Khatri-Rao/rounding work.  Ghost
    values are bitwise-identical to the serialized default.
    """
    from .sphere_swe import make_tt_sphere_swe

    kw.setdefault("batch_rounding", False)
    # The svd rounding's CPU/accelerator dispatch must follow the
    # MESH's platform, not the process default backend (a CPU panel
    # mesh inside a TPU-enabled process must keep the CPU path).
    kw.setdefault("rounding_backend",
                  mesh.devices.flat[0].platform)
    if overlap_exchange:
        kw.setdefault("strip_ghosts_many",
                      make_tt_strip_exchange_many(axis_name))
    return _shard_step(
        partial(make_tt_sphere_swe, grid, dt, rank,
                temporal_block=temporal_block, **kw),
        mesh, axis_name)
