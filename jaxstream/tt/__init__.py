"""Tensor-Train compressed numerics (the reference's research direction).

Deck p.3/p.5/p.19: TT compression of panel fields, the compressed-
algebra layer (:mod:`.tensor_train`), operator-level TT stepping with a
jit-able static-rank fast path (:mod:`.solver`), and the full nonlinear
2-D SWE in factored form (:mod:`.swe2d`) — the LANL problem the deck
cites, one step past its roadmap.  On the cubed sphere itself:
factored-panel advection (:mod:`.sphere`), Laplace-Beltrami diffusion
(:mod:`.sphere_diffusion`), and the full nonlinear SWE
(:mod:`.sphere_swe`), all with reconstructed-strip halo exchange.
Factored diagnostics live in :mod:`.diagnostics`, TT-compressed
checkpoint payloads in :mod:`.store`; TT-compressed history output
plugs into the pipeline via ``io.history_tt_rank``.
"""

from .tensor_train import (
    TTTensor,
    quantize_shape,
    tt_add,
    tt_compress_field,
    tt_decompose,
    tt_decompress_field,
    tt_dot,
    tt_hadamard,
    tt_norm,
    tt_reconstruct,
    tt_round,
    tt_scale,
)

__all__ = [
    "TTTensor",
    "quantize_shape",
    "tt_add",
    "tt_compress_field",
    "tt_decompose",
    "tt_decompress_field",
    "tt_dot",
    "tt_hadamard",
    "tt_norm",
    "tt_reconstruct",
    "tt_round",
    "tt_scale",
]
