"""Tensor-Train compressed numerics (the reference's research direction).

Deck p.3/p.5/p.19: TT compression of panel fields and the compressed
-algebra layer; operator-level TT numerics are roadmap (SURVEY.md §2.2).
"""

from .tensor_train import (
    TTTensor,
    quantize_shape,
    tt_add,
    tt_compress_field,
    tt_decompose,
    tt_decompress_field,
    tt_dot,
    tt_hadamard,
    tt_norm,
    tt_reconstruct,
    tt_round,
    tt_scale,
)

__all__ = [
    "TTTensor",
    "quantize_shape",
    "tt_add",
    "tt_compress_field",
    "tt_decompose",
    "tt_decompress_field",
    "tt_dot",
    "tt_hadamard",
    "tt_norm",
    "tt_reconstruct",
    "tt_round",
    "tt_scale",
]
