"""TT-format numerics: time stepping directly on compressed cores.

The deck's research thesis (p.3/5/19; SURVEY.md §5 "Tensor-Train
subsystem"): keep the field in TT form and apply the PDE operators to
the *cores*, never decompressing — N x N work becomes O(N r^2) core
contractions (small matmuls, the MXU's native shape), and rank
re-truncation (``tt_round``) after each linear combination keeps r
bounded.  LANL demonstrated 124x on Cartesian-2D SWE this way (Danis et
al. 2024, arXiv:2408.03483, deck p.14).

This module implements that machinery for *separable linear* operators
(sums of Kronecker terms ``I x..x A_k x..x I``), which covers diffusion
and constant-coefficient advection on a 2-D panel exactly:

  * :func:`tt_apply_mode` — matrix acting on one TT mode: a single
    einsum on one core, O(n r^2) flops.
  * :class:`KroneckerOperator` — sum of mode-matrices; ``apply`` maps a
    TT to a TT (ranks add across terms; round after).
  * :func:`tt_rk_step` — SSPRK3/Euler in TT arithmetic with rounding
    after every accumulation (the standard "step-and-truncate" scheme).
  * :func:`diff2_periodic` / :func:`diff1_periodic` — 1-D FV stencil
    matrices to assemble 2-D operators from.

The nonlinear SWE terms need TT cross-approximation to stay compressed
(roadmap, SURVEY.md §2.2); the cubed-sphere production path remains the
dense solver in :mod:`jaxstream.models` — this is the compressed-numerics
subsystem the reference describes, validated against the dense oracle in
tests/test_tt_solver.py.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .tensor_train import TTTensor, tt_add, tt_round, tt_scale

__all__ = [
    "tt_apply_mode",
    "KroneckerOperator",
    "diff2_periodic",
    "diff1_periodic",
    "tt_rk_step",
    "make_tt_stepper",
    "make_tt_stepper_static",
    "factor_field",
    "unfactor_field",
]


def tt_apply_mode(tt: TTTensor, mode: int, mat) -> TTTensor:
    """Apply ``mat`` (m_out, m_in) to TT mode ``mode``: one core einsum."""
    cores = list(tt.cores)
    cores[mode] = jnp.einsum("ij,ajb->aib", mat, cores[mode])
    return TTTensor(cores=cores, qtt_meta=tt.qtt_meta)


@dataclasses.dataclass
class KroneckerOperator:
    """L = sum_k (I x ... x mat_k at mode_k x ... x I).

    ``terms``: list of (mode, matrix).  Applying to a TT of rank r gives
    rank ``len(terms) * r`` (each Kronecker term keeps the input's ranks;
    the sum concatenates them) — call ``tt_round`` after.
    """

    terms: List[Tuple[int, jnp.ndarray]]

    def apply(self, tt: TTTensor) -> TTTensor:
        out = None
        for mode, mat in self.terms:
            term = tt_apply_mode(tt, mode, mat)
            out = term if out is None else tt_add(out, term)
        return out


def diff2_periodic(n: int, dx: float, dtype=jnp.float64) -> jnp.ndarray:
    """1-D periodic second-difference matrix (FV diffusion stencil)."""
    m = np.zeros((n, n))
    i = np.arange(n)
    m[i, i] = -2.0
    m[i, (i + 1) % n] = 1.0
    m[i, (i - 1) % n] = 1.0
    return jnp.asarray(m / (dx * dx), dtype=dtype)


def diff1_periodic(n: int, dx: float, dtype=jnp.float64) -> jnp.ndarray:
    """1-D periodic centered first-difference matrix (advection stencil)."""
    m = np.zeros((n, n))
    i = np.arange(n)
    m[i, (i + 1) % n] = 1.0
    m[i, (i - 1) % n] = -1.0
    return jnp.asarray(m / (2.0 * dx), dtype=dtype)


def tt_rk_step(
    rhs: Callable[[TTTensor], TTTensor],
    q: TTTensor,
    dt: float,
    max_rank: int,
    scheme: str = "ssprk3",
) -> TTTensor:
    """One time step in TT arithmetic, rounding after each combination.

    Rounding IS the compression: every axpy would otherwise grow ranks
    multiplicatively over steps.  Mirrors jaxstream.stepping's schemes.
    """

    def axpy(y: TTTensor, a: float, k: TTTensor) -> TTTensor:
        return tt_round(tt_add(y, tt_scale(k, a)), max_rank=max_rank)

    if scheme == "euler":
        return axpy(q, dt, rhs(q))
    if scheme == "ssprk3":
        # Shu-Osher: u1 = u + dt L(u); u2 = 3/4 u + 1/4 (u1 + dt L(u1));
        # u' = 1/3 u + 2/3 (u2 + dt L(u2)).
        y1 = axpy(q, dt, rhs(q))
        y2_ = axpy(y1, dt, rhs(y1))
        y2 = tt_round(
            tt_add(tt_scale(q, 0.75), tt_scale(y2_, 0.25)), max_rank=max_rank
        )
        y3 = axpy(y2, dt, rhs(y2))
        return tt_round(
            tt_add(tt_scale(q, 1.0 / 3.0), tt_scale(y3, 2.0 / 3.0)),
            max_rank=max_rank,
        )
    raise ValueError(f"unknown scheme {scheme!r}")


def make_tt_stepper(
    op: KroneckerOperator,
    dt: float,
    max_rank: int,
    scheme: str = "ssprk3",
) -> Callable[[TTTensor], TTTensor]:
    """``step(q_tt) -> q_tt`` for dq/dt = L q, all in TT format."""

    def rhs(q: TTTensor) -> TTTensor:
        return tt_round(op.apply(q), max_rank=max_rank)

    def step(q: TTTensor) -> TTTensor:
        return tt_rk_step(rhs, q, dt, max_rank, scheme)

    return step


# ---------------------------------------------------------------------------
# Static-rank factored stepper (order-2 TT): the jit-able fast path.
#
# The generic stepper above works on arbitrary-order TTs but rounds by
# reconstruct+decompose with *data-dependent* ranks — unjittable, eager,
# host-SVD round-trips per stage: fine as the compression-layer oracle,
# hopeless as a performance demonstration.  For a 2-D panel field the TT
# is just a factored low-rank form q = A @ B (cores (1,n,r)/(r,n,1)),
# and step-and-truncate SSPRK3 becomes static-shape linear algebra:
# each stage stacks a known number of scaled factor pairs (rank grows
# r -> kr with k fixed by the scheme/operator), and rounding back to r
# is QR(A'), QR(B'^T), SVD of the (kr, kr) coupling matrix, top-r slice
# — every shape static, so the whole step compiles into ONE XLA
# executable of small dense matmuls (the deck's "r x r x r multiplies,
# ideal for TPU/GPU", p.5/p.19).  The d-dimensional version is the same
# two QR sweeps per bond; order-2 is what the per-panel fields need.
# ---------------------------------------------------------------------------


def _round_factored(A, B, r: int):
    """Truncate the factored form A (n, R) @ B (R, m) to rank ``r``.

    Gram-matrix form of the two-sided orthogonalization: G = A^T A and
    H = B B^T are (R, R); their eigh square roots replace tall QRs, the
    (R, R) coupling core is SVD'd, and the top-r directions are applied
    back as one (n, R) @ (R, r) matmul per side.  Same O(n R^2) flops as
    QR, but all of it is *matmul* — the MXU/BLAS-native shape (tall
    XLA QRs measured ~4x slower than the equivalent Gram matmuls on
    CPU, and matmul is the TPU-native path).

    The returned factors are **balanced** — each side carries
    ``sqrt(sigma)`` — which is load-bearing for numerics, not cosmetic:
    with balanced inputs the Gram eigenvalues are ~sigma rather than
    sigma^2 (half the conditioning exponent), and an exactly-zero field
    has BOTH factors zero, so no orphaned O(1) basis rows (from the SVD
    of a zero matrix) survive to masquerade as real directions in later
    Gram passes — that pathology produced O(1) errors in the nonlinear
    SWE stepper before balancing.  Numerically-dead directions (below
    eps * max + tiny) are masked out of the inverse scalings rather
    than floored: dividing roundoff-level rows by a floored sigma
    injects garbage.

    All shapes static (R and r are trace-time constants) — jit-safe.
    """
    G = A.T @ A                          # (R, R)
    H = B @ B.T                          # (R, R)
    va, Ea = jnp.linalg.eigh(G)
    vb, Eb = jnp.linalg.eigh(H)
    fi = jnp.finfo(va.dtype)
    keep_a = va > fi.eps * va[-1] + fi.tiny
    keep_b = vb > fi.eps * vb[-1] + fi.tiny
    sa = jnp.sqrt(jnp.where(keep_a, va, 1.0))
    sb = jnp.sqrt(jnp.where(keep_b, vb, 1.0))
    sa_m = jnp.where(keep_a, sa, 0.0)
    sb_m = jnp.where(keep_b, sb, 0.0)
    inv_sa = jnp.where(keep_a, 1.0 / sa, 0.0)
    inv_sb = jnp.where(keep_b, 1.0 / sb, 0.0)
    # A = Qa Ra with Qa = A Ea sa^-1 (orthonormal on kept directions),
    # Ra = sa Ea^T; likewise for B^T.  SVD the (R, R) coupling core.
    core = (sa_m[:, None] * (Ea.T @ Eb)) * sb_m[None, :]
    u, s, vt = jnp.linalg.svd(core)
    rs = jnp.sqrt(s[:r])
    A_new = A @ (Ea @ (u[:, :r] * rs[None, :] * inv_sa[:, None]))
    B_new = ((vt[:r] * rs[:, None] * inv_sb[None, :]) @ Eb.T) @ B
    return A_new, B_new


def make_tt_stepper_static(
    apply_x,
    apply_y,
    dt: float,
    rank: int,
    scheme: str = "ssprk3",
) -> Callable[[Tuple[jnp.ndarray, jnp.ndarray]],
              Tuple[jnp.ndarray, jnp.ndarray]]:
    """Jit-able fixed-rank stepper for dq/dt = Dx q + q Dy^T, q = A @ B.

    ``apply_x(A) -> Dx @ A`` and ``apply_y(B) -> B @ Dy^T`` act on the
    *factors* — pass matrices wrapped in a lambda, or (the point of the
    factored form) the 1-D stencil itself as rolls/slices, making each
    operator application O(N r) instead of O(N^2 r).

    ``step((A, B)) -> (A, B)`` with A (n, rank), B (rank, m) — wrap in
    ``jax.jit`` (or a ``lax.fori_loop``) and the whole step compiles to a
    handful of (n, kr) matmuls/QRs and one (kr, kr) SVD per stage.
    Truncation is fixed-rank (top-``rank``), matching the generic
    stepper's ``max_rank`` behavior whenever the numerical rank exceeds
    ``rank`` (below that the extra directions carry ~zero energy).

    Use :func:`factor_field` / :func:`unfactor_field` to enter/leave the
    factored form.
    """

    def L_pairs(A, B, scale):
        # scale * (Dx q + q Dy^T) as two factor pairs.
        return [(scale * apply_x(A), B), (scale * A, apply_y(B))]

    def combine(pairs, r):
        A = jnp.concatenate([p[0] for p in pairs], axis=1)
        B = jnp.concatenate([p[1] for p in pairs], axis=0)
        return _round_factored(A, B, r)

    def step(q):
        A, B = q
        if scheme == "euler":
            return combine([(A, B)] + L_pairs(A, B, dt), rank)
        if scheme != "ssprk3":
            raise ValueError(f"unknown scheme {scheme!r}")
        A1, B1 = combine([(A, B)] + L_pairs(A, B, dt), rank)
        A2, B2 = combine(
            [(0.75 * A, B), (0.25 * A1, B1)] + L_pairs(A1, B1, 0.25 * dt),
            rank)
        return combine(
            [(A / 3.0, B), ((2.0 / 3.0) * A2, B2)]
            + L_pairs(A2, B2, (2.0 / 3.0) * dt),
            rank)

    return step


def factor_field(q, rank: int):
    """(n, m) field -> balanced rank-``rank`` factors via truncated SVD.

    Balanced (each side carries sqrt(sigma)) to match
    :func:`_round_factored` — see its docstring for why that matters.
    """
    u, s, vt = jnp.linalg.svd(jnp.asarray(q), full_matrices=False)
    rs = jnp.sqrt(s[:rank])
    return u[:, :rank] * rs[None, :], rs[:, None] * vt[:rank]


def unfactor_field(q):
    A, B = q
    return A @ B
