"""TT-format numerics: time stepping directly on compressed cores.

The deck's research thesis (p.3/5/19; SURVEY.md §5 "Tensor-Train
subsystem"): keep the field in TT form and apply the PDE operators to
the *cores*, never decompressing — N x N work becomes O(N r^2) core
contractions (small matmuls, the MXU's native shape), and rank
re-truncation (``tt_round``) after each linear combination keeps r
bounded.  LANL demonstrated 124x on Cartesian-2D SWE this way (Danis et
al. 2024, arXiv:2408.03483, deck p.14).

This module implements that machinery for *separable linear* operators
(sums of Kronecker terms ``I x..x A_k x..x I``), which covers diffusion
and constant-coefficient advection on a 2-D panel exactly:

  * :func:`tt_apply_mode` — matrix acting on one TT mode: a single
    einsum on one core, O(n r^2) flops.
  * :class:`KroneckerOperator` — sum of mode-matrices; ``apply`` maps a
    TT to a TT (ranks add across terms; round after).
  * :func:`tt_rk_step` — SSPRK3/Euler in TT arithmetic with rounding
    after every accumulation (the standard "step-and-truncate" scheme).
  * :func:`diff2_periodic` / :func:`diff1_periodic` — 1-D FV stencil
    matrices to assemble 2-D operators from.

The nonlinear SWE terms need TT cross-approximation to stay compressed
(roadmap, SURVEY.md §2.2); the cubed-sphere production path remains the
dense solver in :mod:`jaxstream.models` — this is the compressed-numerics
subsystem the reference describes, validated against the dense oracle in
tests/test_tt_solver.py.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .tensor_train import TTTensor, tt_add, tt_round, tt_scale

__all__ = [
    "tt_apply_mode",
    "KroneckerOperator",
    "diff2_periodic",
    "diff1_periodic",
    "tt_rk_step",
    "make_tt_stepper",
]


def tt_apply_mode(tt: TTTensor, mode: int, mat) -> TTTensor:
    """Apply ``mat`` (m_out, m_in) to TT mode ``mode``: one core einsum."""
    cores = list(tt.cores)
    cores[mode] = jnp.einsum("ij,ajb->aib", mat, cores[mode])
    return TTTensor(cores=cores, qtt_meta=tt.qtt_meta)


@dataclasses.dataclass
class KroneckerOperator:
    """L = sum_k (I x ... x mat_k at mode_k x ... x I).

    ``terms``: list of (mode, matrix).  Applying to a TT of rank r gives
    rank ``len(terms) * r`` (each Kronecker term keeps the input's ranks;
    the sum concatenates them) — call ``tt_round`` after.
    """

    terms: List[Tuple[int, jnp.ndarray]]

    def apply(self, tt: TTTensor) -> TTTensor:
        out = None
        for mode, mat in self.terms:
            term = tt_apply_mode(tt, mode, mat)
            out = term if out is None else tt_add(out, term)
        return out


def diff2_periodic(n: int, dx: float, dtype=jnp.float64) -> jnp.ndarray:
    """1-D periodic second-difference matrix (FV diffusion stencil)."""
    m = np.zeros((n, n))
    i = np.arange(n)
    m[i, i] = -2.0
    m[i, (i + 1) % n] = 1.0
    m[i, (i - 1) % n] = 1.0
    return jnp.asarray(m / (dx * dx), dtype=dtype)


def diff1_periodic(n: int, dx: float, dtype=jnp.float64) -> jnp.ndarray:
    """1-D periodic centered first-difference matrix (advection stencil)."""
    m = np.zeros((n, n))
    i = np.arange(n)
    m[i, (i + 1) % n] = 1.0
    m[i, (i - 1) % n] = -1.0
    return jnp.asarray(m / (2.0 * dx), dtype=dtype)


def tt_rk_step(
    rhs: Callable[[TTTensor], TTTensor],
    q: TTTensor,
    dt: float,
    max_rank: int,
    scheme: str = "ssprk3",
) -> TTTensor:
    """One time step in TT arithmetic, rounding after each combination.

    Rounding IS the compression: every axpy would otherwise grow ranks
    multiplicatively over steps.  Mirrors jaxstream.stepping's schemes.
    """

    def axpy(y: TTTensor, a: float, k: TTTensor) -> TTTensor:
        return tt_round(tt_add(y, tt_scale(k, a)), max_rank=max_rank)

    if scheme == "euler":
        return axpy(q, dt, rhs(q))
    if scheme == "ssprk3":
        # Shu-Osher: u1 = u + dt L(u); u2 = 3/4 u + 1/4 (u1 + dt L(u1));
        # u' = 1/3 u + 2/3 (u2 + dt L(u2)).
        y1 = axpy(q, dt, rhs(q))
        y2_ = axpy(y1, dt, rhs(y1))
        y2 = tt_round(
            tt_add(tt_scale(q, 0.75), tt_scale(y2_, 0.25)), max_rank=max_rank
        )
        y3 = axpy(y2, dt, rhs(y2))
        return tt_round(
            tt_add(tt_scale(q, 1.0 / 3.0), tt_scale(y3, 2.0 / 3.0)),
            max_rank=max_rank,
        )
    raise ValueError(f"unknown scheme {scheme!r}")


def make_tt_stepper(
    op: KroneckerOperator,
    dt: float,
    max_rank: int,
    scheme: str = "ssprk3",
) -> Callable[[TTTensor], TTTensor]:
    """``step(q_tt) -> q_tt`` for dq/dt = L q, all in TT format."""

    def rhs(q: TTTensor) -> TTTensor:
        return tt_round(op.apply(q), max_rank=max_rank)

    def step(q: TTTensor) -> TTTensor:
        return tt_rk_step(rhs, q, dt, max_rank, scheme)

    return step
