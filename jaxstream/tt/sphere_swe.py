"""TT-compressed shallow-water equations on the cubed sphere.

The endpoint of the deck's TT thesis (pdf p.4/5/7/19): the full
nonlinear SWE stepped with every panel field in rank-r factored form —
no ``(n, n)`` array is ever materialized.  Builds on the machinery of
:mod:`jaxstream.tt.sphere` (reconstructed-strip halo exchange with the
exact-geometry seam resampling, factored smooth coefficients,
Khatri-Rao products rounded by cross/ACA) and
:mod:`jaxstream.tt.sphere_diffusion` (rank-1 ghost-correction stencils).

Formulation (the TT layer's own scheme; its dense twin
:func:`make_dense_sphere_swe` shares the stencils exactly and is the
parity oracle — the *production* cubed-sphere SWE solvers live in
:mod:`jaxstream.models` and are unrelated discretizations):

* **Vector-invariant covariant form** on each equiangular panel —
  prognostics ``(h, u_a, u_b)`` with ``u_i = e_i . v`` (covariant
  velocity against the panel basis):

      dh/dt  = -(1/sqrtg) [ D_a(sqrtg h u^a) + D_b(sqrtg h u^b) ]
      du_a/dt =  (zeta + f) sqrtg u^b - D_a(K + Phi)
      du_b/dt = -(zeta + f) sqrtg u^a - D_b(K + Phi)

  with ``u^i = g^ij u_j``, ``K = u_i u^i / 2``, ``Phi = g (h + hs)``,
  ``zeta = (1/sqrtg)(D_a u_b - D_b u_a)``.  Only first derivatives
  appear; every coefficient (``g^ij, sqrtg, 1/sqrtg, f``) is a smooth
  equiangular field factored once at build time.
* **Velocity halo exchange in Cartesian components** — the strategy the
  reference demonstrably ran ("Cartesian Velocity Exchange", deck
  p.18), done factored: the three Cartesian scalars
  ``v_c = a^a_c (.) u_a + a^b_c (.) u_b`` exist only as Khatri-Rao
  *pairs*; their boundary strips are reconstructed (O(n R) per edge),
  routed through the shared connectivity, tangentially resampled onto
  the continuation points (:func:`jaxstream.tt.sphere.edge_resample`),
  and projected back onto the *local* basis ``e_i`` evaluated at those
  exact points (the grid's own extended arrays) — an exact basis
  change, no rotation-angle bookkeeping.
* Ghost values of the differenced composites (``sqrtg h u^i``,
  ``K + Phi``, ``u_a``, ``u_b``) are computed densely on the four
  depth-1 lines from the exchanged primitives and enter the factored
  algebra as rank-1 correction pairs.

Not conservative across seams to roundoff (the two sides' edge fluxes
are independently resampled); measured mass drift is at the resampling
truncation level — the conservative production path is
:mod:`jaxstream.models.shallow_water`.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp

from ..config import EARTH_GRAVITY, EARTH_OMEGA
from ..utils.jax_compat import named_scope
from .cross import (aca_lowrank, aca_lowrank_many, host_svd_lowrank,
                    rsvd_lowrank, svd_lowrank)
from .swe2d import kr_raw
from .sphere import (
    _diff_last,
    _diff_mid,
    _factored_stepper_multi,
    _local_statics,
    _numerical_rank,
    dense_strip_ghosts,
    edge_resample,
    factor_panels,
    resampled_ghost_lines,
    stack_pairs,
    tt_strip_ghosts,
)

__all__ = ["make_tt_sphere_swe", "make_dense_sphere_swe",
           "covariant_from_cartesian"]

_EDGES = ("S", "N", "W", "E")


def covariant_from_cartesian(grid, v_ext):
    """Interior covariant components ``(u_a, u_b)`` (6, n, n) from a
    Cartesian wind ``(3, 6, M, M)`` (the IC functions' output)."""
    h, n = grid.halo, grid.n
    sl = slice(h, h + n)
    ea = np.asarray(grid.e_a, np.float64)[:, :, sl, sl]
    eb = np.asarray(grid.e_b, np.float64)[:, :, sl, sl]
    v = np.asarray(v_ext, np.float64)[:, :, sl, sl]
    return (np.einsum("cfij,cfij->fij", ea, v),
            np.einsum("cfij,cfij->fij", eb, v))


def _swe_statics(grid, hs, omega: float):
    """Build-time f64 coefficient fields.

    Returns ``(interior, edges)``: ``interior`` maps name -> (6, n, n)
    (``gaa/gab/gbb`` contravariant metric, ``sg``, ``isg``, ``f``,
    ``hs``, and ``aax/abx`` the (3, 6, n, n) Cartesian dual-basis
    components); ``edges`` maps 'S'/'N'/'W'/'E' -> per-line statics at
    the depth-1 *continuation* points (where the grid's extended arrays
    already live): ``ea/eb`` (3, 6, n), ``gaa/gab/gbb/sg/hs`` (6, n).
    """
    n, h = grid.n, grid.halo
    sl = slice(h, h + n)
    aa = np.asarray(grid.a_a, np.float64)
    ab = np.asarray(grid.a_b, np.float64)
    ea = np.asarray(grid.e_a, np.float64)
    eb = np.asarray(grid.e_b, np.float64)
    sg = np.asarray(grid.sqrtg, np.float64)
    lat = np.asarray(grid.lat, np.float64)
    hs_e = (np.zeros_like(sg) if hs is None
            else np.asarray(hs, np.float64))
    dot = lambda x, y: np.einsum("cfij,cfij->fij", x, y)

    interior = {
        "gaa": dot(aa, aa)[:, sl, sl], "gab": dot(aa, ab)[:, sl, sl],
        "gbb": dot(ab, ab)[:, sl, sl], "sg": sg[:, sl, sl],
        "isg": 1.0 / sg[:, sl, sl],
        "f": 2.0 * omega * np.sin(lat)[:, sl, sl],
        "hs": hs_e[:, sl, sl],
        "aax": aa[:, :, sl, sl], "abx": ab[:, :, sl, sl],
    }
    cut = {"S": (Ellipsis, h - 1, sl), "N": (Ellipsis, h + n, sl),
           "W": (Ellipsis, sl, h - 1), "E": (Ellipsis, sl, h + n)}
    edges = {}
    for X, c in cut.items():
        edges[X] = {
            # Face axis FIRST on every edge static (ea/eb are
            # (6, 3, n)) so the sharded tier's per-device slicer
            # (sphere._local_statics) can treat the whole pytree
            # uniformly.
            "ea": np.moveaxis(ea[c], 0, 1), "eb": np.moveaxis(eb[c], 0, 1),
            "gaa": dot(aa, aa)[c], "gab": dot(aa, ab)[c],
            "gbb": dot(ab, ab)[c], "sg": sg[c],
            "hs": hs_e[c],
        }
    return interior, edges


def _ghost_composites(hl, vl, ES, grav):
    """Derived ghost-line values from exchanged primitives — shared by
    the factored and dense twins.  ``hl[X] (6, n)``; ``vl[X]`` list of
    three Cartesian component lines; ``ES`` the edge statics.  Returns
    per-edge dict with ``ua, ub, Fa, Fb, KP``."""
    out = {}
    for X in _EDGES:
        es = ES[X]
        ua = sum(es["ea"][:, c] * vl[X][c] for c in range(3))
        ub = sum(es["eb"][:, c] * vl[X][c] for c in range(3))
        uua = es["gaa"] * ua + es["gab"] * ub
        uub = es["gab"] * ua + es["gbb"] * ub
        sgh = es["sg"] * hl[X]
        out[X] = {
            "ua": ua, "ub": ub,
            "Fa": sgh * uua, "Fb": sgh * uub,
            "KP": 0.5 * (ua * uua + ub * uub)
                  + grav * (hl[X] + es["hs"]),
        }
    return out


def make_tt_sphere_swe(grid, dt: float, rank: int,
                       hs=None,
                       coeff_tol: float = 1e-7,
                       omega: float = EARTH_OMEGA,
                       gravity: float = EARTH_GRAVITY,
                       scheme: str = "ssprk3",
                       batch_rounding=None,
                       kappa: float = 0.0,
                       rounding: str = "aca",
                       rounding_backend: str | None = None,
                       strip_ghosts=None,
                       strip_ghosts_many=None,
                       face_slice=None,
                       temporal_block: int = 1) -> Callable:
    """Jit-able factored-panel SWE step.

    State: ``((hA, hB), (uaA, uaB), (ubA, ubB))`` — rank-``rank``
    factor pairs per prognostic, ``q[f] = A[f] @ B[f]`` in the interior
    layout.  ``step(state) -> state``; nothing (n, n) is ever formed.

    ``kappa`` (m^2/s): in-step Laplace-Beltrami dissipation on the
    velocity components — ``du_i/dt += kappa lap u_i`` in factored form
    via the :mod:`..sphere_diffusion` pair machinery, reusing the ghost
    lines the velocity exchange already produced.  h stays undissipated
    (mass is untouched).  The dense twin applies identical terms.

    ``strip_ghosts_many``: optional batched form of the exchange
    injection — ``strip_ghosts_many(pairs) -> [ghosts, ...]`` for a
    LIST of factor pairs.  The step fetches all four ghost sets (h +
    three Cartesian velocity components) through one call, so a
    sharded implementation can ship them over ONE up-front 4-stage
    ppermute schedule instead of four sequential ones
    (:func:`jaxstream.tt.shard.make_tt_strip_exchange_many`, gated by
    ``parallelization.overlap_exchange``).  Defaults to a loop over
    ``strip_ghosts`` — identical values either way.

    ``temporal_block = k > 1``: the returned step advances k SSPRK3
    steps per call, fused inside one trace (under the sharded tier's
    shard_map that is ONE collective program per k steps —
    ``parallelization.temporal_block``).  The factored state is rounded
    back to rank ``rank`` after every stage either way, so the k-step
    block evaluates the *identical* exchange/rounding sequence as k
    separate calls — reconstructed fields are bitwise-equal to the k=1
    reference (tests/test_temporal_block.py).

    ``rounding``: ``'aca'`` (cross approximation, no factorization
    kernels — the speed tier) or ``'svd'`` (exact best-rank-k
    truncation via QR+SVD, :func:`..cross.svd_lowrank` — the stability
    tier).  Measured on mountain-forced TC5 C96 (round 4, DESIGN.md
    stability envelope): under 'aca' the run NaNs within 0.17-0.5
    sim-days at every rank/kappa tried — the quasi-optimal skeleton's
    excess truncation error acts as a large non-dissipative
    perturbation the nonlinear flow amplifies, and kappa cannot damp
    it; under 'svd' the same configurations integrate 5+ days with
    physical fields.  Steady/short-horizon flows (TC2) are stable
    under either.  Use 'svd' for forced nonlinear flows; kappa then
    controls the ordinary grid-scale cascade like any explicit
    viscosity.
    """
    n = grid.n
    d = float(grid.dalpha)
    inv2d = 1.0 / (2.0 * d)
    I, ES = _swe_statics(grid, hs, omega)

    fac = lambda c: factor_panels(c, _numerical_rank(c, coeff_tol, 16))
    ST = {
        "gaa": fac(I["gaa"]), "gab": fac(I["gab"]), "gbb": fac(I["gbb"]),
        "sg": fac(I["sg"]), "isg": fac(I["isg"]), "f": fac(I["f"]),
        "aax": tuple(fac(I["aax"][c]) for c in range(3)),
        "abx": tuple(fac(I["abx"][c]) for c in range(3)),
        "ES": {X: {k: jnp.asarray(v) for k, v in es.items()}
               for X, es in ES.items()},
    }
    if hs is not None:
        ST["hs"] = fac(I["hs"])

    ridx, rwgt = edge_resample(n, d)
    dtype = ST["sg"][0].dtype
    e0 = jnp.zeros((1, n), dtype).at[0, 0].set(1.0)
    eN = jnp.zeros((1, n), dtype).at[0, n - 1].set(1.0)
    if strip_ghosts is None:
        strip_ghosts = lambda q: tt_strip_ghosts(q, 1)
    if strip_ghosts_many is None:
        strip_ghosts_many = lambda qs: [strip_ghosts(q) for q in qs]

    lap_pairs = None
    if kappa != 0.0:
        from .sphere_diffusion import make_lap_pairs

        lap_pairs = make_lap_pairs(grid, coeff_tol,
                                   face_slice=face_slice)

    kr = jax.vmap(kr_raw)
    if rounding == "svd":
        # rounding_backend: where this step will actually execute —
        # the sharded tier passes its mesh's platform so a CPU mesh
        # inside a TPU-enabled process keeps the CPU-validated path.
        vsvd = jax.vmap(
            lambda A, B: svd_lowrank(A, B, rank,
                                     backend=rounding_backend))
        rnd_many = lambda ops: [tuple(vsvd(*p)) for p in ops]
    elif rounding == "rsvd":
        # Matmul-only near-optimal truncation (Newton-Schulz polar +
        # two-stage randomized SVD) — the rounding that runs on TPU
        # f32, where the exact tier's QR/eigh primitives fail
        # (cross.rsvd_lowrank; round-5 stability tier).
        vr = jax.vmap(lambda A, B: rsvd_lowrank(A, B, rank))
        rnd_many = lambda ops: [tuple(vr(*p)) for p in ops]
    elif rounding == "host_svd":
        # Exact truncation with the small factorization on the host
        # (LAPACK f64 via pure_callback) — the guaranteed rung for
        # backends with unreliable on-device linalg.  Handles the
        # 6-face batch natively (numpy stacked linalg): one round trip
        # per operand, not per face.
        rnd_many = lambda ops: [
            tuple(host_svd_lowrank(A, B, rank, backend=rounding_backend))
            for A, B in ops]
    elif rounding != "aca":
        raise ValueError(f"rounding must be 'aca', 'svd', 'rsvd' or "
                         f"'host_svd', got {rounding!r}")
    else:
        if batch_rounding is None:
            # Measured trade (DESIGN.md): batching the independent ACA
            # sweeps wins on accelerators (dispatch-latency-bound,
            # -14..23% on v5e) and loses on CPU (the zero-padding to
            # the largest operand's bond rank adds real memory traffic,
            # up to 1.8x at C1536).
            batch_rounding = jax.default_backend() != "cpu"
        if batch_rounding:
            rnd_many = lambda ops: aca_lowrank_many(ops, rank)
        else:
            aca = jax.vmap(lambda A, B: aca_lowrank(A, B, rank))
            rnd_many = lambda ops: [tuple(aca(*p)) for p in ops]

    def rhs3(state, scale):
        hp, uap, ubp = state
        S = _local_statics(ST, face_slice)
        hs_tt = S.get("hs")
        ES_l = S["ES"]
        ones = jnp.ones((hp[0].shape[0], 1, 1), dtype)

        def da_pairs(pair, W, E):
            """Factor pairs of D_a(pair) with ghost-line corrections."""
            A, B = pair
            return [(A, _diff_last(B, inv2d)),
                    (W[:, :, None] * (-inv2d), ones * e0[None]),
                    (E[:, :, None] * inv2d, ones * eN[None])]

        def db_pairs(pair, Sl, N):
            A, B = pair
            return [(_diff_mid(A, inv2d), B),
                    (e0.T[None] * ones, Sl[:, None, :] * (-inv2d)),
                    (eN.T[None] * ones, N[:, None, :] * inv2d)]

        # --- ghost primitives: h strips + Cartesian velocity strips ---
        # One batched fetch for all four fields: the velocity payloads
        # are depth-1 strips of the (un-rounded) Khatri-Rao pairs —
        # O(n r r_c) strip reconstructions, no rounding in between — so
        # a sharded strip_ghosts_many can put every ppermute on the
        # wire before any of the step's heavy face-local work starts.
        with named_scope("tt_ghosts"):
            vcs = [stack_pairs([kr(S["aax"][c], uap),
                                kr(S["abx"][c], ubp)])
                   for c in range(3)]
            ghosts = strip_ghosts_many([hp] + vcs)
            hl = resampled_ghost_lines(ghosts[0], ridx, rwgt)
            vl = {X: [] for X in _EDGES}
            for c in range(3):
                lc = resampled_ghost_lines(ghosts[1 + c], ridx, rwgt)
                for X in _EDGES:
                    vl[X].append(lc[X])
            G = _ghost_composites(hl, vl, ES_l, gravity)

        # --- interior factored intermediates, rounded in TWO batched
        # sweeps (sequential ACA latency is the TPU wall; the operands
        # within each sweep are independent — cross.aca_lowrank_many).
        stk = stack_pairs
        # Sweep 1: u^a, u^b, sqrtg h, and the curl (needs only
        # primitives + ghost lines).
        curl_ops = (da_pairs(ubp, G["W"]["ub"], G["E"]["ub"])
                    + [(-a, b) for a, b in
                       db_pairs(uap, G["S"]["ua"], G["N"]["ua"])])
        with named_scope("tt_sweep1"):
            uua, uub, sgh, curl = rnd_many([
                stk([kr(S["gaa"], uap), kr(S["gab"], ubp)]),
                stk([kr(S["gab"], uap), kr(S["gbb"], ubp)]),
                stk([kr(S["sg"], hp)]),
                stk(curl_ops),
            ])

        # Sweep 2: everything needing sweep 1 — flux divergence, K+Phi,
        # absolute vorticity, sqrtg u^i.
        kp_pairs = [(0.5 * a, b) for a, b in
                    (kr(uap, uua), kr(ubp, uub))]
        kp_pairs.append((gravity * hp[0], hp[1]))
        if hs_tt is not None:
            kp_pairs.append((gravity * hs_tt[0], hs_tt[1]))
        with named_scope("tt_sweep2"):
            div, KP, zeta, mau, mbu = rnd_many([
                stk(da_pairs(kr(sgh, uua), G["W"]["Fa"], G["E"]["Fa"])
                    + db_pairs(kr(sgh, uub), G["S"]["Fb"], G["N"]["Fb"])),
                stk(kp_pairs),
                stk([kr(S["isg"], curl), S["f"]]),
                stk([kr(S["sg"], uua)]),
                stk([kr(S["sg"], uub)]),
            ])

        dh = kr(S["isg"], div)
        dh = ((-scale * dt) * dh[0], dh[1])
        dua = [kr(zeta, mbu)] + [(-a, b) for a, b in
                                 da_pairs(KP, G["W"]["KP"], G["E"]["KP"])]
        dub = [(-a, b) for a, b in ([kr(zeta, mau)]
               + db_pairs(KP, G["S"]["KP"], G["N"]["KP"]))]
        if lap_pairs is not None:
            # In-step velocity dissipation, factored: the exchange's own
            # resampled ghost lines of u_a/u_b serve as the Laplacian's
            # depth-1 strips — no extra communication.
            lines = lambda k: tuple(G[X][k] for X in _EDGES)
            dua += [(kappa * a, b)
                    for a, b in lap_pairs(uap, lines("ua"))]
            dub += [(kappa * a, b)
                    for a, b in lap_pairs(ubp, lines("ub"))]
        sc = lambda pairs: stack_pairs(
            [((scale * dt) * a, b) for a, b in pairs])
        return dh, sc(dua), sc(dub)

    step1 = _factored_stepper_multi(rhs3, rnd_many, scheme)
    if temporal_block == 1:
        return step1
    if temporal_block < 1:
        raise ValueError(
            f"temporal_block must be >= 1, got {temporal_block}")

    def block(state):
        for _ in range(temporal_block):
            state = step1(state)
        return state

    return block


def make_dense_sphere_swe(grid, dt: float,
                          hs=None,
                          omega: float = EARTH_OMEGA,
                          gravity: float = EARTH_GRAVITY,
                          scheme: str = "ssprk3",
                          kappa: float = 0.0) -> Callable:
    """Dense twin of :func:`make_tt_sphere_swe` — identical stencils,
    ghost composites, and exchange; the parity oracle and speed
    baseline.  ``step((h, ua, ub)) -> (h, ua, ub)``, each (6, n, n).
    ``kappa``: the same in-step velocity dissipation as the factored
    tier (see :func:`make_tt_sphere_swe`)."""
    n = grid.n
    d = float(grid.dalpha)
    inv2d = 1.0 / (2.0 * d)
    I, ES = _swe_statics(grid, hs, omega)
    dtype = grid.sqrtg.dtype
    gaa, gab, gbb, sg, isg, f, hsI = (
        jnp.asarray(I[k], dtype)
        for k in ("gaa", "gab", "gbb", "sg", "isg", "f", "hs"))
    aax = jnp.asarray(I["aax"], dtype)
    abx = jnp.asarray(I["abx"], dtype)
    ES = {X: {k: jnp.asarray(v, dtype) for k, v in es.items()}
          for X, es in ES.items()}
    ridx, rwgt = edge_resample(n, d)

    lap = None
    if kappa != 0.0:
        from .sphere_diffusion import make_dense_lap

        lap = make_dense_lap(grid)

    def Da(x, W, E):
        lo = jnp.pad(x[:, :, 1:], ((0, 0), (0, 0), (0, 1)))
        hi = jnp.pad(x[:, :, :-1], ((0, 0), (0, 0), (1, 0)))
        out = inv2d * (lo - hi)
        return (out.at[:, :, 0].add(-inv2d * W)
                .at[:, :, -1].add(inv2d * E))

    def Db(x, S, N):
        lo = jnp.pad(x[:, 1:, :], ((0, 0), (0, 1), (0, 0)))
        hi = jnp.pad(x[:, :-1, :], ((0, 0), (1, 0), (0, 0)))
        out = inv2d * (lo - hi)
        return (out.at[:, 0, :].add(-inv2d * S)
                .at[:, -1, :].add(inv2d * N))

    def rhs(state):
        h, ua, ub = state
        vcs = [aax[c] * ua + abx[c] * ub for c in range(3)]
        hl = resampled_ghost_lines(dense_strip_ghosts(h, 1), ridx, rwgt)
        vl_raw = [resampled_ghost_lines(dense_strip_ghosts(vc, 1), ridx, rwgt)
                  for vc in vcs]
        vl = {X: [vl_raw[c][X] for c in range(3)] for X in _EDGES}
        G = _ghost_composites(hl, vl, ES, gravity)

        uua = gaa * ua + gab * ub
        uub = gab * ua + gbb * ub
        Fa = sg * h * uua
        Fb = sg * h * uub
        dh = -isg * (Da(Fa, G["W"]["Fa"], G["E"]["Fa"])
                     + Db(Fb, G["S"]["Fb"], G["N"]["Fb"]))
        KP = 0.5 * (ua * uua + ub * uub) + gravity * (h + hsI)
        zeta = isg * (Da(ub, G["W"]["ub"], G["E"]["ub"])
                      - Db(ua, G["S"]["ua"], G["N"]["ua"])) + f
        dua = zeta * sg * uub - Da(KP, G["W"]["KP"], G["E"]["KP"])
        dub = -zeta * sg * uua - Db(KP, G["S"]["KP"], G["N"]["KP"])
        if lap is not None:
            lines = lambda k: tuple(G[X][k] for X in _EDGES)
            dua = dua + kappa * lap(ua, lines("ua"))
            dub = dub + kappa * lap(ub, lines("ub"))
        return dh, dua, dub

    def step(state):
        if scheme == "euler":
            k = rhs(state)
            return tuple(state[i] + dt * k[i] for i in range(3))
        if scheme != "ssprk3":
            raise ValueError(f"unknown scheme {scheme!r}")
        k1 = rhs(state)
        y1 = tuple(state[i] + dt * k1[i] for i in range(3))
        k2 = rhs(y1)
        y2 = tuple(0.75 * state[i] + 0.25 * (y1[i] + dt * k2[i])
                   for i in range(3))
        k3 = rhs(y2)
        return tuple(state[i] / 3.0
                     + (2.0 / 3.0) * (y2[i] + dt * k3[i])
                     for i in range(3))

    return step
