"""Tensor-Train (TT/QTT) compressed fields.

The reference's research direction (deck p.3: TT compresses N x N fields
to O(d N r^2), r << N, citing LANL's 124x speedup on Cartesian-2D SWE,
Danis et al. 2024, arXiv:2408.03483; deck p.5/p.19: TT numerics turn
memory-bound stencils (AI ~ 0.25 flops/byte) into compute-bound r x r
matmuls (AI ~ 5 flops/byte) — "Ideal for TPU/GPU devices").  The deck
ships no TT code; this module provides the compression layer:

  * ``tt_decompose`` — TT-SVD (Oseledets 2011) over an arbitrary-order
    tensor, with either fixed max rank or a relative Frobenius tolerance
    distributed over the unfoldings.
  * ``quantize``/``dequantize`` — the QTT reshape: a (2^k, 2^k) panel
    field becomes a k-dimensional (4, 4, ..., 4) tensor whose TT ranks
    stay small for smooth atmospheric fields (this is what makes
    "TT-friendly 2D tiles", deck p.4, concrete).
  * TT algebra: ``tt_add``, ``tt_scale``, ``tt_hadamard``, and
    ``tt_round`` (rank re-truncation after algebra).
  * ``tt_dot``, ``tt_norm`` — inner products without decompression.

Everything is jnp + einsum — the r x r core contractions are exactly the
small-matmul workload the deck's roofline analysis targets at the MXU.
Operator-level TT numerics (applying FV stencils directly on cores) are
the round-2+ roadmap (SURVEY.md §2.2 "Optional/roadmap").
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = [
    "TTTensor",
    "tt_decompose",
    "tt_reconstruct",
    "tt_round",
    "tt_add",
    "tt_scale",
    "tt_hadamard",
    "tt_dot",
    "tt_norm",
    "quantize_shape",
    "tt_compress_field",
    "tt_decompress_field",
]


@dataclasses.dataclass
class TTTensor:
    """A tensor in TT format: cores[k] has shape (r_k, n_k, r_{k+1}).

    ``qtt_meta`` carries the field-reshape bookkeeping of
    :func:`tt_compress_field` (original 2-D shape + per-axis factors); the
    algebra ops propagate it so compress -> algebra -> decompress works.
    """

    cores: List[jnp.ndarray]
    qtt_meta: Optional[Tuple] = None

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(c.shape[1] for c in self.cores)

    @property
    def ranks(self) -> Tuple[int, ...]:
        return (1,) + tuple(c.shape[2] for c in self.cores)

    @property
    def n_params(self) -> int:
        return sum(int(np.prod(c.shape)) for c in self.cores)

    def compression_ratio(self) -> float:
        full = int(np.prod(self.shape))
        return full / max(self.n_params, 1)


def tt_decompose(
    tensor,
    max_rank: Optional[int] = None,
    rel_tol: Optional[float] = None,
) -> TTTensor:
    """TT-SVD: sequential truncated SVDs of the unfoldings.

    ``rel_tol`` is a relative Frobenius-norm error budget for the whole
    decomposition (distributed as tol/sqrt(d-1) per unfolding, the
    standard Oseledets bound); ``max_rank`` caps every bond dimension.
    """
    a = jnp.asarray(tensor)
    dims = a.shape
    d = len(dims)
    if d < 2:
        raise ValueError("TT needs an order >= 2 tensor")
    delta = None
    if rel_tol is not None:
        delta = rel_tol * float(jnp.linalg.norm(a.ravel())) / math.sqrt(d - 1)

    cores: List[jnp.ndarray] = []
    r_prev = 1
    mat = a.reshape(r_prev * dims[0], -1)
    for k in range(d - 1):
        u, s, vt = jnp.linalg.svd(mat, full_matrices=False)
        ok = bool((jnp.isfinite(s).all() & jnp.isfinite(u).all()
                   & jnp.isfinite(vt).all()))
        if not ok:
            if bool(jnp.all(jnp.isfinite(mat))):
                # XLA's CPU SVD can fail (NaN) on exactly rank-deficient
                # unfoldings — which step-and-truncate TT evolution
                # produces routinely once a field's numerical rank drops
                # below the rank cap.  LAPACK via numpy handles these;
                # tt_decompose is eager-only (concrete rank arithmetic
                # below), so a host round-trip is legal here.
                u_, s_, vt_ = np.linalg.svd(np.asarray(mat),
                                            full_matrices=False)
                u, s, vt = (jnp.asarray(u_, a.dtype),
                            jnp.asarray(s_, a.dtype),
                            jnp.asarray(vt_, a.dtype))
            # else: the *input* is non-finite (blown-up evolution) — keep
            # the NaN factors so the divergence propagates to the caller
            # instead of dying in the fallback with a misleading
            # LinAlgError.
        r = int(s.shape[0])
        # Always drop numerically-zero directions: carrying noise cores
        # wastes rank budget and feeds degenerate matrices to later SVDs.
        floor = float(s[0]) * (32.0 * float(jnp.finfo(a.dtype).eps))
        r = max(1, min(r, int(jnp.sum(s > floor))))
        if delta is not None:
            # Largest truncation whose dropped tail stays under delta.
            tail = jnp.sqrt(jnp.cumsum(s[::-1] ** 2))[::-1]
            keep = int(jnp.sum(tail > delta))
            r = max(1, min(r, keep))
        if max_rank is not None:
            r = min(r, max_rank)
        cores.append(u[:, :r].reshape(r_prev, dims[k], r))
        mat = (s[:r, None] * vt[:r, :])
        r_prev = r
        if k < d - 2:
            mat = mat.reshape(r_prev * dims[k + 1], -1)
    cores.append(mat.reshape(r_prev, dims[-1], 1))
    return TTTensor(cores)


def tt_reconstruct(tt: TTTensor) -> jnp.ndarray:
    """Contract cores back to the full tensor."""
    out = tt.cores[0]  # (1, n0, r1)
    for c in tt.cores[1:]:
        out = jnp.einsum("...a,abc->...bc", out, c)
    return out[0, ..., 0]


def tt_round(tt: TTTensor, max_rank: Optional[int] = None,
             rel_tol: Optional[float] = None) -> TTTensor:
    """Re-truncate ranks after TT algebra (right-to-left QR, then TT-SVD).

    Small tensors: implemented as reconstruct + decompose, which is exact
    and simple; fine for the compression-layer scope (operator-level TT
    keeps everything in cores and needs the proper two-sweep rounding —
    roadmap).
    """
    out = tt_decompose(tt_reconstruct(tt), max_rank=max_rank,
                       rel_tol=rel_tol)
    out.qtt_meta = tt.qtt_meta
    return out


def _join_meta(x: TTTensor, y: TTTensor) -> Optional[Tuple]:
    if x.qtt_meta is not None and y.qtt_meta is not None \
            and x.qtt_meta != y.qtt_meta:
        raise ValueError(
            f"QTT layouts differ: {x.qtt_meta} vs {y.qtt_meta}"
        )
    return x.qtt_meta if x.qtt_meta is not None else y.qtt_meta


def _block_diag_cores(a, b, first: bool, last: bool):
    """Block-diagonal stack of two TT cores.  Dispatches on array kind:
    numpy inputs stay numpy (the eager f64 build path — see
    qtt.py), jax inputs use jnp (trace-safe)."""
    ra0, n, ra1 = a.shape
    rb0, _, rb1 = b.shape
    if isinstance(a, np.ndarray) and isinstance(b, np.ndarray):
        if first:
            return np.concatenate([a, b], axis=2)
        if last:
            return np.concatenate([a, b], axis=0)
        out = np.zeros((ra0 + rb0, n, ra1 + rb1), dtype=a.dtype)
        out[:ra0, :, :ra1] = a
        out[ra0:, :, ra1:] = b
        return out
    if first:
        return jnp.concatenate([a, b], axis=2)
    if last:
        return jnp.concatenate([a, b], axis=0)
    out = jnp.zeros((ra0 + rb0, n, ra1 + rb1), dtype=a.dtype)
    out = out.at[:ra0, :, :ra1].set(a)
    out = out.at[ra0:, :, ra1:].set(b)
    return out


def tt_add(x: TTTensor, y: TTTensor) -> TTTensor:
    """x + y via block-diagonal core stacking (ranks add; round after)."""
    if x.shape != y.shape:
        raise ValueError(f"shape mismatch {x.shape} vs {y.shape}")
    d = len(x.cores)
    return TTTensor([
        _block_diag_cores(cx, cy, k == 0, k == d - 1)
        for k, (cx, cy) in enumerate(zip(x.cores, y.cores))
    ], qtt_meta=_join_meta(x, y))


def tt_scale(x: TTTensor, s) -> TTTensor:
    cores = list(x.cores)
    cores[0] = cores[0] * s
    return TTTensor(cores, qtt_meta=x.qtt_meta)


def tt_hadamard(x: TTTensor, y: TTTensor) -> TTTensor:
    """Elementwise product: Kronecker product of bond spaces (ranks multiply)."""
    if x.shape != y.shape:
        raise ValueError(f"shape mismatch {x.shape} vs {y.shape}")
    cores = []
    for cx, cy in zip(x.cores, y.cores):
        c = jnp.einsum("anb,cnd->acnbd", cx, cy)
        r0 = cx.shape[0] * cy.shape[0]
        r1 = cx.shape[2] * cy.shape[2]
        cores.append(c.reshape(r0, cx.shape[1], r1))
    return TTTensor(cores, qtt_meta=_join_meta(x, y))


def tt_dot(x: TTTensor, y: TTTensor):
    """<x, y> contracted core-by-core (never forms the full tensor)."""
    if x.shape != y.shape:
        raise ValueError(f"shape mismatch {x.shape} vs {y.shape}")
    env = jnp.ones((1, 1), dtype=x.cores[0].dtype)
    for cx, cy in zip(x.cores, y.cores):
        env = jnp.einsum("ac,anb,cnd->bd", env, cx, cy)
    return env[0, 0]


def tt_norm(x: TTTensor):
    return jnp.sqrt(jnp.maximum(tt_dot(x, x), 0.0))


def quantize_shape(n: int, base: int = 4) -> List[int]:
    """Factor n into `base` factors (QTT); remainder goes in one trailing dim."""
    dims = []
    while n % base == 0 and n > base:
        dims.append(base)
        n //= base
    dims.append(n)
    return dims


def tt_compress_field(field2d, max_rank: Optional[int] = None,
                      rel_tol: Optional[float] = 1e-6,
                      base: int = 4) -> TTTensor:
    """QTT-compress one (ny, nx) panel field.

    Reshapes to the quantized (base, ..., base) tensor with *interleaved*
    y/x factors (locality-preserving ordering — keeps smooth-field ranks
    low) and TT-decomposes.
    """
    f = jnp.asarray(field2d)
    ny, nx = f.shape
    dy, dx = quantize_shape(ny, base), quantize_shape(nx, base)
    if len(dy) != len(dx) or len(dy) < 2:
        # Plain order-2 TT (= truncated SVD) on ragged or tiny shapes.
        return tt_decompose(f, max_rank=max_rank, rel_tol=rel_tol)
    # (y0..yk, x0..xk) -> interleave -> (y0, x0, y1, x1, ...)
    t = f.reshape(tuple(dy) + tuple(dx))
    k = len(dy)
    perm = [i for pair in zip(range(k), range(k, 2 * k)) for i in pair]
    t = jnp.transpose(t, perm)
    merged = t.reshape(tuple(dy[i] * dx[i] for i in range(k)))
    tt = tt_decompose(merged, max_rank=max_rank, rel_tol=rel_tol)
    tt.qtt_meta = (ny, nx, tuple(dy), tuple(dx))
    return tt


def tt_decompress_field(tt: TTTensor) -> jnp.ndarray:
    """Inverse of :func:`tt_compress_field` (meta survives TT algebra)."""
    meta = tt.qtt_meta
    full = tt_reconstruct(tt)
    if meta is None:
        return full
    ny, nx, dy, dx = meta
    k = len(dy)
    t = full.reshape(tuple(v for pair in zip(dy, dx) for v in pair))
    inv = [2 * i for i in range(k)] + [2 * i + 1 for i in range(k)]
    return jnp.transpose(t, inv).reshape(ny, nx)
