"""TT-compressed scalar diffusion on the cubed sphere — factored panels.

Completes the deck's first demo (thermal diffusion of the checkerboard
"Lima flag", pdf p.12/17) in rank-r factored form: the Laplace-Beltrami
operator on each equiangular panel, stepped without ever materializing
an ``(n, n)`` field.  Same machinery as :mod:`jaxstream.tt.sphere`
(reconstructed-strip halo exchange, factored smooth coefficients,
Khatri-Rao products rounded by cross/ACA) extended to second
derivatives, whose cross term is the new design point.

Discretization (the TT layer's own scheme; the dense twin
:func:`make_dense_sphere_diffusion` shares the exact stencils and is
the parity oracle):

* Expanded non-conservative form — on a panel with metric ``g``,

      lap q = g^aa D_aa q + 2 g^ab D_ab q + g^bb D_bb q
              + L^a D_a q + L^b D_b q,
      L^j   = (1/sqrtg) [ D_a(sqrtg g^aj) + D_b(sqrtg g^bj) ]

  with all five coefficient fields (``g^aa, g^ab, g^bb, L^a, L^b``)
  smooth equiangular functions, evaluated analytically in f64 at build
  time and factored to their numerical rank.  Unlike the advection
  flux form, no coefficient ghost values are needed: coefficients
  multiply interior derivative fields pointwise.
* Centered 2nd-order stencils with zero closure; ghost contributions
  re-enter as **rank-1 correction pairs** built from the depth-1
  reconstructed strips (a ghost column times a stencil selector row).
* The cross derivative ``D_ab`` at panel-edge cells needs ghost values
  displaced *along* the edge — including, at the four panel corners,
  the cube-corner ghost where three panels meet and no 4th neighbor
  exists (SURVEY.md "hard parts": corner treatment must be designed).
  Design: each corner ghost is estimated once as the mean of the two
  quadratic extrapolations along the adjacent received strips (FV3-style
  one-sided closure), the **column** corrections own the corner terms
  (their strips are corner-extended), and the **row** corrections use
  zero-extended strips — so every stencil term is counted exactly once.

State and conventions match :mod:`jaxstream.tt.sphere`: ``(A, B)`` with
``q[f] = A[f] @ B[f]``, axis -2 = beta (rows), axis -1 = alpha (cols).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp

from .cross import aca_lowrank
from .swe2d import kr_raw
from .sphere import (
    _factored_stepper,
    _local_statics,
    _numerical_rank,
    dense_strip_ghosts,
    edge_resample,
    factor_panels,
    resampled_ghost_lines,
    stack_pairs,
    tt_strip_ghosts,
)

__all__ = ["make_tt_sphere_diffusion", "make_dense_sphere_diffusion",
           "make_lap_pairs", "make_dense_lap"]


def _diffusion_coeffs(grid):
    """Interior f64 coefficient fields ``(Gaa, Gab, Gbb, La, Lb)`` of the
    expanded Laplace-Beltrami operator, from the grid's dual basis
    (``g^ij = a^i . a^j``) — evaluated on the extended grid so the
    first-derivative coefficients ``L^j`` difference cleanly."""
    n, h = grid.n, grid.halo
    d = float(grid.dalpha)
    sl = slice(h, h + n)
    sg = np.asarray(grid.sqrtg, np.float64)               # (6, M, M)
    aa = np.asarray(grid.a_a, np.float64)                 # (3, 6, M, M)
    ab = np.asarray(grid.a_b, np.float64)
    Gaa = np.einsum("cfij,cfij->fij", aa, aa)
    Gab = np.einsum("cfij,cfij->fij", aa, ab)
    Gbb = np.einsum("cfij,cfij->fij", ab, ab)
    # L^j on the extended grid via centered differences (alpha = axis -1,
    # beta = axis -2; np.gradient is centered everywhere but the outer
    # ring, which lies outside the interior slice for halo >= 1).
    da = lambda x: np.gradient(x, d, axis=-1)
    db = lambda x: np.gradient(x, d, axis=-2)
    isg = 1.0 / sg
    La = isg * (da(sg * Gaa) + db(sg * Gab))
    Lb = isg * (da(sg * Gab) + db(sg * Gbb))
    return (Gaa[:, sl, sl], Gab[:, sl, sl], Gbb[:, sl, sl],
            La[:, sl, sl], Lb[:, sl, sl])


def _resampled_lines(ghosts, idx, wgt):
    """Depth-1 resampled ghost lines as an (S, N, W, E) tuple — thin
    adapter over :func:`jaxstream.tt.sphere.resampled_ghost_lines`."""
    L = resampled_ghost_lines(ghosts, idx, wgt)
    return L["S"], L["N"], L["W"], L["E"]


def _corner_ghosts(gS0, gN0, gW0, gE0):
    """The four cube-corner ghost estimates per face, each the mean of
    the quadratic extrapolations along the two adjacent depth-1 strips.
    Strips are placed layout: gS0/gN0 ``(6, n)`` indexed by column,
    gW0/gE0 ``(6, n)`` indexed by row."""
    # Quadratic extrapolation one spacing past the strip end: O(d^3)
    # value error, so the corner cells' cross-derivative correction
    # (1/d^2 weight) stays O(d) — linear extrapolation measurably
    # plateaus the corner error at O(1).
    ex0 = lambda v: 3.0 * (v[:, 0] - v[:, 1]) + v[:, 2]
    exN = lambda v: 3.0 * (v[:, -1] - v[:, -2]) + v[:, -3]
    sw = 0.5 * (ex0(gW0) + ex0(gS0))              # q[-1, -1]
    se = 0.5 * (ex0(gE0) + exN(gS0))              # q[-1,  n]
    nw = 0.5 * (exN(gW0) + ex0(gN0))              # q[ n, -1]
    ne = 0.5 * (exN(gE0) + exN(gN0))              # q[ n,  n]
    return sw, se, nw, ne


def _edge_cdiff(core, lo, hi):
    """Centered difference ``(v[i+1] - v[i-1]) / 2`` along a ghost line
    ``[lo, core..., hi]`` — (6, n) from (6, n) core and (6,) end values
    (spacing folded into the caller's scale)."""
    ext = jnp.concatenate([lo[:, None], core, hi[:, None]], axis=1)
    return 0.5 * (ext[:, 2:] - ext[:, :-2])


def make_lap_pairs(grid, coeff_tol: float = 1e-7,
                   face_slice=None) -> Callable:
    """Factored Laplace-Beltrami term builder, reusable across tiers.

    Factors the five coefficient fields once and returns
    ``lap_pairs(q, lines) -> [(A, B), ...]``: the UNROUNDED factor
    pairs of ``lap q`` for a factored panel field ``q = (A, B)``, with
    ``lines = (gS0, gN0, gW0, gE0)`` the depth-1 resampled ghost lines
    of ``q`` (however the caller obtained them — its own strip
    exchange, or the SWE tier's already-exchanged primitives).  The
    caller scales/stacks/rounds.  Used by
    :func:`make_tt_sphere_diffusion` and by the factored SWE's in-step
    velocity dissipation (:func:`..sphere_swe.make_tt_sphere_swe`
    ``kappa``).
    """
    n = grid.n
    d = float(grid.dalpha)
    inv2d = 1.0 / (2.0 * d)
    invd2 = 1.0 / (d * d)

    cfs = _diffusion_coeffs(grid)
    ST = {k: factor_panels(c, _numerical_rank(c, coeff_tol, 16))
          for k, c in zip(("Gaa", "Gab", "Gbb", "La", "Lb"), cfs)}

    dtype = ST["Gaa"][0].dtype
    e0 = jnp.zeros((1, n), dtype).at[0, 0].set(1.0)
    eN = jnp.zeros((1, n), dtype).at[0, n - 1].set(1.0)

    kr_raw_f = jax.vmap(kr_raw)
    stack = stack_pairs

    def lap_pairs(q, lines):
        S = _local_statics(ST, face_slice)
        A, B = q
        ones = jnp.ones((A.shape[0], 1, 1), dtype)
        gS0, gN0, gW0, gE0 = lines
        sw, se, nw, ne = _corner_ghosts(gS0, gN0, gW0, gE0)

        # First derivatives: factor-local shifted-slice diffs (zero
        # closure) + rank-1 ghost corrections at the boundary lines.
        dB = inv2d * (jnp.pad(B[:, :, 1:], ((0, 0), (0, 0), (0, 1)))
                      - jnp.pad(B[:, :, :-1], ((0, 0), (0, 0), (1, 0))))
        dA = inv2d * (jnp.pad(A[:, 1:, :], ((0, 0), (0, 1), (0, 0)))
                      - jnp.pad(A[:, :-1, :], ((0, 0), (1, 0), (0, 0))))
        Da = [(A, dB),
              (gW0[:, :, None] * (-inv2d), ones * e0[None]),
              (gE0[:, :, None] * inv2d, ones * eN[None])]
        Db = [(dA, B),
              (e0.T[None] * ones, gS0[:, None, :] * (-inv2d)),
              (eN.T[None] * ones, gN0[:, None, :] * inv2d)]

        # Second derivatives: 3-point zero-closure diff + ghost value
        # re-entering with weight +1/d^2 at the boundary line.
        d2B = invd2 * (jnp.pad(B[:, :, 1:], ((0, 0), (0, 0), (0, 1)))
                       + jnp.pad(B[:, :, :-1], ((0, 0), (0, 0), (1, 0)))
                       - 2.0 * B)
        d2A = invd2 * (jnp.pad(A[:, 1:, :], ((0, 0), (0, 1), (0, 0)))
                       + jnp.pad(A[:, :-1, :], ((0, 0), (1, 0), (0, 0)))
                       - 2.0 * A)
        Daa = [(A, d2B),
               (gW0[:, :, None] * invd2, ones * e0[None]),
               (gE0[:, :, None] * invd2, ones * eN[None])]
        Dbb = [(d2A, B),
               (e0.T[None] * ones, gS0[:, None, :] * invd2),
               (eN.T[None] * ones, gN0[:, None, :] * invd2)]

        # Cross derivative: both factors differenced (zero closure);
        # boundary-line corrections are strip derivatives along the
        # edge.  Column corrections use corner-extended strips (they own
        # the corner terms); row corrections use zero-extended strips.
        zero = jnp.zeros((A.shape[0],), dtype)
        cW = -inv2d * inv2d * _edge_cdiff(gW0, sw, nw) * 2.0
        cE = inv2d * inv2d * _edge_cdiff(gE0, se, ne) * 2.0
        rS = -inv2d * inv2d * _edge_cdiff(gS0, zero, zero) * 2.0
        rN = inv2d * inv2d * _edge_cdiff(gN0, zero, zero) * 2.0
        Dab = [(dA, dB),
               (cW[:, :, None], ones * e0[None]),
               (cE[:, :, None], ones * eN[None]),
               (e0.T[None] * ones, rS[:, None, :]),
               (eN.T[None] * ones, rN[:, None, :])]

        return [kr_raw_f(S["Gaa"], stack(Daa)),
                kr_raw_f(S["Gbb"], stack(Dbb)),
                kr_raw_f(S["Gab"], stack([(2.0 * a, b) for a, b in Dab])),
                kr_raw_f(S["La"], stack(Da)),
                kr_raw_f(S["Lb"], stack(Db))]

    return lap_pairs


def make_tt_sphere_diffusion(grid, kappa: float, dt: float, rank: int,
                             coeff_tol: float = 1e-7,
                             scheme: str = "ssprk3",
                             strip_ghosts=None,
                             face_slice=None) -> Callable:
    """Jit-able factored-panel diffusion step ``dq/dt = kappa * lap q``.

    Coefficients are factored once at their own numerical rank
    (equiangular ``g^ij`` / ``L^j`` are nearly exact low rank).  The
    returned ``step((A, B)) -> (A, B)`` never materializes a panel.
    ``strip_ghosts``/``face_slice``: the panel-sharded tier's injection
    points (:mod:`jaxstream.tt.shard`; see
    :func:`..sphere.make_tt_sphere_advection`).
    """
    n = grid.n
    d = float(grid.dalpha)
    lap_pairs = make_lap_pairs(grid, coeff_tol, face_slice=face_slice)
    ridx, rwgt = edge_resample(n, d)
    aca = jax.vmap(lambda A, B: aca_lowrank(A, B, rank))
    if strip_ghosts is None:
        strip_ghosts = lambda q: tt_strip_ghosts(q, 1)

    def rhs_pairs(q, scale):
        lines = _resampled_lines(strip_ghosts(q), ridx, rwgt)
        Astk, Bstk = stack_pairs(lap_pairs(q, lines))
        dAo, dBo = aca(Astk, Bstk)
        return (scale * dt * kappa) * dAo, dBo

    return _factored_stepper(rhs_pairs, aca, scheme)


def make_dense_lap(grid) -> Callable:
    """Dense twin of :func:`make_lap_pairs`: returns
    ``lap(q, lines) -> (6, n, n)`` with the identical stencils and
    strip/corner corrections, ``lines = (gS0, gN0, gW0, gE0)``."""
    n = grid.n
    d = float(grid.dalpha)
    inv2d = 1.0 / (2.0 * d)
    invd2 = 1.0 / (d * d)

    Gaa, Gab, Gbb, La, Lb = (jnp.asarray(c, grid.sqrtg.dtype)
                             for c in _diffusion_coeffs(grid))

    def lap(q, lines):
        dtype = q.dtype
        gS0, gN0, gW0, gE0 = lines
        sw, se, nw, ne = _corner_ghosts(gS0, gN0, gW0, gE0)

        pad = lambda x, axis, side: jnp.pad(
            x, [(0, 0) if a != axis % 3 else side for a in range(3)])
        qe = pad(q[:, :, 1:], 2, (0, 1))      # shift left  (j+1)
        qw = pad(q[:, :, :-1], 2, (1, 0))     # shift right (j-1)
        qn = pad(q[:, 1:, :], 1, (0, 1))
        qs = pad(q[:, :-1, :], 1, (1, 0))

        Da = inv2d * (qe - qw)
        Da = Da.at[:, :, 0].add(-inv2d * gW0).at[:, :, -1].add(inv2d * gE0)
        Db = inv2d * (qn - qs)
        Db = Db.at[:, 0, :].add(-inv2d * gS0).at[:, -1, :].add(inv2d * gN0)

        Daa = invd2 * (qe + qw - 2.0 * q)
        Daa = Daa.at[:, :, 0].add(invd2 * gW0).at[:, :, -1].add(invd2 * gE0)
        Dbb = invd2 * (qn + qs - 2.0 * q)
        Dbb = Dbb.at[:, 0, :].add(invd2 * gS0).at[:, -1, :].add(invd2 * gN0)

        dj = inv2d * (qe - qw)
        Dab = inv2d * (pad(dj[:, 1:, :], 1, (0, 1))
                       - pad(dj[:, :-1, :], 1, (1, 0)))
        zero = jnp.zeros((6,), dtype)
        cW = -inv2d * inv2d * _edge_cdiff(gW0, sw, nw) * 2.0
        cE = inv2d * inv2d * _edge_cdiff(gE0, se, ne) * 2.0
        rS = -inv2d * inv2d * _edge_cdiff(gS0, zero, zero) * 2.0
        rN = inv2d * inv2d * _edge_cdiff(gN0, zero, zero) * 2.0
        Dab = (Dab.at[:, :, 0].add(cW).at[:, :, -1].add(cE)
               .at[:, 0, :].add(rS).at[:, -1, :].add(rN))

        return (Gaa * Daa + 2.0 * Gab * Dab + Gbb * Dbb
                + La * Da + Lb * Db)

    return lap


def make_dense_sphere_diffusion(grid, kappa: float, dt: float,
                                scheme: str = "ssprk3") -> Callable:
    """Dense twin of :func:`make_tt_sphere_diffusion` — identical
    stencils (zero-closure diffs + the same strip/corner corrections),
    coefficients, and exchange; the parity oracle and speed baseline.
    ``step(q (6, n, n)) -> (6, n, n)``."""
    n = grid.n
    d = float(grid.dalpha)
    lap = make_dense_lap(grid)
    ridx, rwgt = edge_resample(n, d)

    def rhs(q):
        lines = _resampled_lines(dense_strip_ghosts(q, 1), ridx, rwgt)
        return kappa * lap(q, lines)

    def step(q):
        if scheme == "euler":
            return q + dt * rhs(q)
        if scheme != "ssprk3":
            raise ValueError(f"unknown scheme {scheme!r}")
        y1 = q + dt * rhs(q)
        y2 = 0.75 * q + 0.25 * (y1 + dt * rhs(y1))
        return q / 3.0 + (2.0 / 3.0) * (y2 + dt * rhs(y2))

    return step
