"""Profiling and roofline analysis — the observability subsystem.

The reference does performance analysis offline with a roofline model
("Roofline (TPU v4 class)": peak BW 900 GB/s, 275 TFLOP/s FP32, ridge
305.6 flops/byte; FV-PLR at 870 flops/cell, AI ~ 0.25 — deck p.19;
SURVEY.md §5 "Tracing / profiling" + §6).  This module makes that frame a
first-class tool:

  * :func:`cost_analysis` asks XLA itself for the compiled program's
    flops and bytes — no hand counting, and it reflects what fusion
    actually kept.
  * :func:`roofline` turns (flops, bytes, measured seconds) into the
    deck's chart: arithmetic intensity, achieved vs roof throughput,
    and which resource binds.
  * :class:`StepTimer` measures steady-state step time without compile
    skew; :func:`trace` wraps ``jax.profiler`` for TensorBoard traces.
"""

from __future__ import annotations

import contextlib
import dataclasses
import statistics
import time
from typing import Callable, Dict, Optional, Sequence

import jax

__all__ = [
    "HardwareRoof", "TPU_V4_CLASS", "TPU_V5E", "TPU_V5P",
    "cost_analysis", "roofline", "Roofline", "StepTimer", "trace",
]


@dataclasses.dataclass(frozen=True)
class HardwareRoof:
    """Peak memory bandwidth and compute for a roofline chart."""
    name: str
    hbm_gbps: float          # GB/s
    peak_tflops: float       # TFLOP/s at the working precision

    @property
    def ridge(self) -> float:
        """Flops/byte where the machine turns compute-bound."""
        return self.peak_tflops * 1e12 / (self.hbm_gbps * 1e9)


# The deck's example roofline (p.19) and the chips this repo targets.
TPU_V4_CLASS = HardwareRoof("TPU v4 class (deck p.19)", 900.0, 275.0)
TPU_V5E = HardwareRoof("TPU v5e", 819.0, 197.0)       # bf16 peak; f32 ~ half
TPU_V5P = HardwareRoof("TPU v5p", 2765.0, 459.0)


def cost_analysis(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """XLA's own cost model for ``jit(fn)(*args)``: flops, bytes accessed.

    Returns ``{"flops": F, "bytes": B, "ai": F/B}`` from the compiled
    executable — post-fusion, so it reflects real HBM traffic estimates.
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    costs = compiled.cost_analysis()
    if isinstance(costs, list):  # older jax returns [dict]
        costs = costs[0]
    flops = float(costs.get("flops", 0.0))
    nbytes = float(costs.get("bytes accessed", 0.0))
    return {
        "flops": flops,
        "bytes": nbytes,
        "ai": flops / nbytes if nbytes else float("inf"),
    }


@dataclasses.dataclass(frozen=True)
class Roofline:
    """One point on the roofline chart, with the roof it's plotted against."""
    flops: float
    bytes: float
    seconds: float
    roof: HardwareRoof

    @property
    def ai(self) -> float:
        return self.flops / self.bytes if self.bytes else float("inf")

    @property
    def achieved_tflops(self) -> float:
        return self.flops / self.seconds / 1e12

    @property
    def achieved_gbps(self) -> float:
        return self.bytes / self.seconds / 1e9

    @property
    def bound(self) -> str:
        return "memory" if self.ai < self.roof.ridge else "compute"

    @property
    def roof_tflops(self) -> float:
        """Attainable TFLOP/s at this AI (the roofline itself)."""
        return min(self.roof.peak_tflops, self.ai * self.roof.hbm_gbps * 1e-3)

    @property
    def efficiency(self) -> float:
        """Achieved / attainable at this AI (1.0 = on the roof)."""
        return self.achieved_tflops / self.roof_tflops if self.roof_tflops else 0.0

    def report(self) -> str:
        return (
            f"roofline [{self.roof.name}]: AI={self.ai:.3f} flops/byte "
            f"(ridge {self.roof.ridge:.1f} -> {self.bound}-bound); "
            f"achieved {self.achieved_tflops:.2f} TFLOP/s, "
            f"{self.achieved_gbps:.0f} GB/s; "
            f"roof at this AI {self.roof_tflops:.2f} TFLOP/s "
            f"({100 * self.efficiency:.0f}% of attainable)"
        )


def roofline(fn: Callable, *args, seconds: float,
             roof: HardwareRoof = TPU_V4_CLASS, **kwargs) -> Roofline:
    """Roofline point for one measured execution of ``fn(*args)``."""
    c = cost_analysis(fn, *args, **kwargs)
    return Roofline(c["flops"], c["bytes"], seconds, roof)


class StepTimer:
    """Steady-state step timing: call ``t = timer(step_fn, state)``.

    Blocks on the result each rep, so each sample is one full device
    round-trip; the first ``discard`` samples (compile + warmup) are
    dropped from the stats.
    """

    def __init__(self, discard: int = 1):
        self.discard = discard
        self.samples: list = []

    def time(self, fn: Callable, *args, reps: int = 10, **kwargs):
        out = None
        for _ in range(self.discard + reps):
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
            self.samples.append(time.perf_counter() - t0)
        return out

    @property
    def kept(self) -> Sequence[float]:
        return self.samples[self.discard:]

    def stats(self) -> Dict[str, float]:
        k = sorted(self.kept)
        if not k:
            return {}
        return {
            "n": len(k),
            "mean_s": statistics.fmean(k),
            "min_s": k[0],
            "p50_s": k[len(k) // 2],
            "p90_s": k[int(len(k) * 0.9) - 1 if len(k) > 1 else 0],
        }

    def sim_days_per_sec(self, dt: float, steps_per_call: int = 1) -> float:
        s = self.stats()
        if not s:
            return 0.0
        return steps_per_call * dt / 86400.0 / s["p50_s"]


@contextlib.contextmanager
def trace(logdir: str):
    """``with trace('/tmp/tb'):`` — jax.profiler trace for TensorBoard/xprof."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
