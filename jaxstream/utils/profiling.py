"""Profiling and roofline analysis — the observability subsystem.

The reference does performance analysis offline with a roofline model
("Roofline (TPU v4 class)": peak BW 900 GB/s, 275 TFLOP/s FP32, ridge
305.6 flops/byte; FV-PLR at 870 flops/cell, AI ~ 0.25 — deck p.19;
SURVEY.md §5 "Tracing / profiling" + §6).  This module makes that frame a
first-class tool:

  * :func:`cost_analysis` asks XLA itself for the compiled program's
    flops and bytes — no hand counting, and it reflects what fusion
    actually kept.
  * :func:`roofline` turns (flops, bytes, measured seconds) into the
    deck's chart: arithmetic intensity, achieved vs roof throughput,
    and which resource binds.
  * :class:`StepTimer` measures steady-state step time without compile
    skew; :func:`trace` wraps ``jax.profiler`` for TensorBoard traces.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import statistics
import time
from typing import Callable, Dict, Optional, Sequence

import jax

__all__ = [
    "HardwareRoof", "TPU_V4_CLASS", "TPU_V5E", "TPU_V5P",
    "TPU_V5E_VPU", "TPU_V5E_VPU_BF16", "mixed_vpu_roof",
    "cost_analysis", "analytic_cov_step_cost", "roofline", "Roofline",
    "StepTimer", "median_chain_seconds", "steady_state_rate", "trace",
]


@dataclasses.dataclass(frozen=True)
class HardwareRoof:
    """Peak memory bandwidth and compute for a roofline chart."""
    name: str
    hbm_gbps: float          # GB/s
    peak_tflops: float       # TFLOP/s at the working precision

    @property
    def ridge(self) -> float:
        """Flops/byte where the machine turns compute-bound."""
        return self.peak_tflops * 1e12 / (self.hbm_gbps * 1e9)


# The deck's example roofline (p.19) and the chips this repo targets.
TPU_V4_CLASS = HardwareRoof("TPU v4 class (deck p.19)", 900.0, 275.0)
TPU_V5E = HardwareRoof("TPU v5e", 819.0, 197.0)       # bf16 MXU peak
TPU_V5P = HardwareRoof("TPU v5p", 2765.0, 459.0)
# VPU (elementwise f32) roofs: the FV stencil kernels never touch the
# MXU, so their compute roof is the vector unit.  The nominal FMA peak is
# ~(8, 128) lanes x 2 (FMA) x ~1.7 GHz ~ 3.5 TFLOP/s on v5e, but the
# stencil op mix is ~half selects/abs/min/max (limiters, upwinding) which
# occupy a full VPU slot for 1 flop — the *effective* elementwise roof
# for this mix is ~2.6 TFLOP/s.  DESIGN.md's stage-kernel bisection
# sustains ~2.0 TFLOP/s in the RHS window (~77% of this roof, "at or
# near the VPU roofline").  v5p scaled by clock/core ratio.
TPU_V5E_VPU = HardwareRoof("TPU v5e VPU f32 stencil-mix", 819.0, 2.6)
TPU_V5P_VPU = HardwareRoof("TPU v5p VPU f32 stencil-mix", 2765.0, 5.5)
# bf16 elementwise ops pack 2x per VPU lane, so the same stencil-mix
# argument doubles the effective roof for the ops that actually run
# bf16.  A MIXED kernel (the round-10 stage precision policy casts only
# the flux face-averages + limiter algebra) lands between the two
# roofs; mixed_vpu_roof() computes the harmonic blend for a given bf16
# flop fraction.
TPU_V5E_VPU_BF16 = HardwareRoof("TPU v5e VPU bf16 stencil-mix", 819.0, 5.2)


def mixed_vpu_roof(bf16_fraction: float,
                   f32_roof: HardwareRoof = TPU_V5E_VPU,
                   bf16_roof: HardwareRoof = TPU_V5E_VPU_BF16
                   ) -> HardwareRoof:
    """Effective VPU roof for a kernel running a bf16/f32 op mix.

    Time to issue F flops with fraction ``phi`` at the bf16 rate is
    ``F*((1-phi)/P32 + phi/P16)`` — the harmonic blend, NOT the linear
    one (a linear average would overstate the roof whenever the slow
    class dominates the op stream).  ``phi = 0`` returns the f32 roof
    unchanged; ``phi = 1`` the bf16 roof.
    """
    if not 0.0 <= bf16_fraction <= 1.0:
        raise ValueError(
            f"bf16_fraction must be in [0, 1], got {bf16_fraction}")
    peak = 1.0 / ((1.0 - bf16_fraction) / f32_roof.peak_tflops
                  + bf16_fraction / bf16_roof.peak_tflops)
    return HardwareRoof(
        f"{f32_roof.name} + {100 * bf16_fraction:.0f}% bf16",
        f32_roof.hbm_gbps, peak)


def cost_analysis(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """XLA's own cost model for ``jit(fn)(*args)``: flops, bytes accessed.

    Returns ``{"flops": F, "bytes": B, "ai": F/B}`` from the compiled
    executable — post-fusion, so it reflects real HBM traffic estimates.

    .. warning:: **Excludes Pallas kernels.**  XLA cannot see inside
       custom calls, so a program whose math lives in Pallas kernels
       reports near-zero flops here (the round-1 bench printed a roofline
       ~200x off this way).  For the fused SWE steppers use
       :func:`analytic_cov_step_cost` — the kernels are static stencils
       with countable work.
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    costs = compiled.cost_analysis()
    if isinstance(costs, list):  # older jax returns [dict]
        costs = costs[0]
    flops = float(costs.get("flops", 0.0))
    nbytes = float(costs.get("bytes accessed", 0.0))
    return {
        "flops": flops,
        "bytes": nbytes,
        "ai": flops / nbytes if nbytes else float("inf"),
    }


# Itemized per-cell VPU-op counts for the covariant fused SSPRK3 stage
# kernel (ops/pallas/swe_cov.py::rhs_core_cov + the in-kernel RK combine),
# counting each elementwise add/mul/min/max/abs/sign/select/rsqrt as one
# flop.  Derivation (per interior cell, per stage):
#   continuity, per direction (x2):
#     band frame consumed entries (rho2, rsqrt, fg_aa, fg_ab)      ~5
#     face-average velocities + flux-form contraction               7
#     upwind flux (max/min selects + 2 mul + add)                   5
#     PLR reconstruction = 4 + limiter slope (see _RECON_FLOPS)
#   divergence + inv_sqrtg scaling                                  9
#   momentum (band frame ~10, u^i raise 6, KE+Bernoulli 7, grads 8,
#             Coriolis rz 5, abs-vorticity 4, tendencies 4)        44
#   in-kernel SSPRK3 combine (axpy on 3 fields)                    12
# Totals (MC): 2*(17+19) + 9 + 44 + 12 = 137 flops/cell/stage — the
# DESIGN.md stage-kernel bisection measured "~150 flops/cell" at
# ~2 TFLOP/s sustained; treat the count as +-15%.
_RECON_FLOPS = {"none": 6, "minmod": 14, "mc": 19, "vanleer": 16}

# Of those, the ops the round-10 bf16 stage policy actually runs in
# bfloat16 (ops/pallas/precision.py): the flux face-average velocity
# adds/halvings (~4 of the 7 "face-average + contraction" ops per
# direction — the metric contraction accumulates f32) and the limiter
# slope chain (the candidate/min/max algebra, ~15 of the 4+19 recon
# ops; the f32-cell +- f32(bf16 half-slope) assembly stays f32).
# Per cell/stage (MC): 2 * (4 + 15) = 38 of 137 -> bf16 fraction ~0.28.
# Everything else — metric terms, upwind products, divergences,
# gradients, RK combines — is f32 by policy.  Same +-15% caveat.
_BF16_STAGE_FLOPS = {"none": 2 * (4 + 4), "minmod": 2 * (4 + 11),
                     "mc": 2 * (4 + 15), "vanleer": 2 * (4 + 13)}

# del^4 filter per-cell flop count (ops/pallas/swe_cov.py::lap_core,
# applied twice + the damp axpy, per prognostic field).  Per Laplacian
# application per direction: face gradient dpa (diff + mul) 2,
# cross-gradient dpb_c 2, face-average dpb_f 2, frame consumed entries
# ~5, flux contraction 3 -> 14; divergence + inv_sqrtg scaling ~6.
# Per Laplacian: 2*14 + 6 = 34; per field: 2 Laplacians + axpy = 70;
# 3 fields -> 210 flops/cell/step.  (The round-6..9 bench billed the
# filter at scale=4/3 == one extra 137-flop stage — ~35% under this
# count; re-derived here per the round-10 accounting satellite.)
# The filter arithmetic is identical in 'split' and 'refused' placement
# — re-fusion changes kernel/route COUNT and bytes, not flops.
_NU4_FILTER_FLOPS = 3 * (2 * 34 + 2)

#: Extra f32 field passes the filter adds per step.  'split': its own
#: kernel reads 3 fields (+ghost strips, <1%) and writes 3 fields + new
#: strips -> ~6 passes.  'refused': the filter rides the stage-1 kernel
#: (ghosts already resident); the only NEW traffic is the filtered-base
#: (h0f, u0f) output stages 2-3 combine against -> 3 passes.
_NU4_FIELD_PASSES = {"split": 6, "refused": 3}


def analytic_cov_step_cost(n: int, *, limiter: str = "mc",
                           dtype_bytes: int = 4, stages: int = 3,
                           n_faces: int = 6,
                           ensemble: int = 1,
                           carry_bytes: int = None,
                           nu4: str = None,
                           precision: str = None) -> Dict[str, float]:
    """Analytic flops/bytes for ONE fused covariant SSPRK3 step at C``n``.

    Pallas custom calls are invisible to :func:`cost_analysis`; this is
    the hand-counted replacement for the production stepper
    (``make_fused_ssprk3_cov_compact``).  Bytes model the compact
    interior-only carry: per stage each face reads its 3-field carry,
    the 3-field y0 (stages 2-3), the orography, and writes 3 fields —
    amortized ~9 field-passes/stage — plus the strip traffic
    (~4*n*(halo+...) per face, <1% at C384, folded into the field count).

    ``ensemble = B``: cost of one step of the batched B-member stepper
    (``make_fused_ssprk3_cov_compact(ensemble=B)``) — ONE such step
    advances every member, so flops AND bytes scale by B together and
    the arithmetic intensity is unchanged.  Scaling both here (rather
    than letting callers multiply flops alone) is what keeps ensemble
    rooflines truthful: B-scaled flops against single-member bytes
    would report a B-inflated intensity that no hardware counter
    would ever reproduce.  (The per-face orography re-read per member
    is real extra traffic the model already charges — b rides the
    per-stage field-pass count.)

    ``carry_bytes`` (round-10 accounting satellite): bytes per element
    of the h/u CARRY storage — 2 for the 16-bit encodings (mixed16 /
    bf16), default = ``dtype_bytes``.  Only the 24 carry field passes
    scale; the orography re-read (1 pass/stage) stays at
    ``dtype_bytes`` — the earlier coarse ``bytes * 0.5`` model
    overstated the 16-bit savings by billing b at 2 bytes too
    (0.500x vs the honest 0.556x at the default shape), overstating AI
    for the 16-bit-carry variants.

    ``nu4``: ``'split'`` / ``'refused'`` adds the del^4 filter —
    identical arithmetic (+``_NU4_FILTER_FLOPS`` = 210 flops/cell/step,
    re-derived from lap_core; the old ``scale = 4/3`` billed it as one
    extra 137-flop stage, ~35% under) but different bytes: the split
    form's standalone kernel pays ~6 extra f32 field passes, the
    re-fused form only the 3 filtered-base output passes
    (``_NU4_FIELD_PASSES``).  Filter traffic is f32 at any
    ``carry_bytes`` (the nu4 paths reject carry encodings).

    ``precision='bf16'``: tags the fraction of flops the stage policy
    runs in bfloat16 (``bf16_flop_fraction``, from
    ``_BF16_STAGE_FLOPS``; filter flops are always f32) so callers can
    plot against :func:`mixed_vpu_roof`.  Flops/bytes themselves are
    unchanged — the policy re-types ops, it does not remove them (the
    strip-storage halving is <1% of bytes at C384, folded like the f32
    strip traffic).

    Returns ``{"flops", "bytes", "ai", "flops_per_cell_stage",
    "bf16_flop_fraction"}``.
    """
    if ensemble < 1:
        raise ValueError(f"ensemble must be >= 1, got {ensemble}")
    if nu4 not in (None, "split", "refused"):
        raise ValueError(f"nu4 must be None, 'split' or 'refused', "
                         f"got {nu4!r}")
    if precision not in (None, "f32", "bf16"):
        raise ValueError(f"precision must be None, 'f32' or 'bf16', "
                         f"got {precision!r}")
    if carry_bytes is None:
        carry_bytes = dtype_bytes
    recon = _RECON_FLOPS.get(limiter, _RECON_FLOPS["mc"])
    per_cell_stage = 2 * (17 + recon) + 9 + 44 + 12
    cells = n_faces * n * n * ensemble
    flops = float(per_cell_stage * cells * stages)
    # field passes: stage1 reads y(3)+b(1) writes 3 = 7;
    # stages 2,3 read y(3)+y0(3)+b(1) write 3 = 10  -> 27 per 3 stages.
    # Of those, 1 pass/stage is the orography (always dtype_bytes);
    # the rest are the carry fields (carry_bytes).
    field_passes = 7 + 10 * (stages - 1)
    carry_passes = field_passes - stages
    nbytes = float(cells * (carry_passes * carry_bytes
                            + stages * dtype_bytes))
    bf16_flops = 0.0
    if precision == "bf16":
        bf16_flops = float(
            _BF16_STAGE_FLOPS.get(limiter, _BF16_STAGE_FLOPS["mc"])
            * cells * stages)
    if nu4 is not None:
        flops += float(_NU4_FILTER_FLOPS * cells)
        nbytes += float(_NU4_FIELD_PASSES[nu4] * cells * dtype_bytes)
    return {
        "flops": flops,
        "bytes": nbytes,
        "ai": flops / nbytes,
        "flops_per_cell_stage": float(per_cell_stage),
        "bf16_flop_fraction": bf16_flops / flops if flops else 0.0,
    }


@dataclasses.dataclass(frozen=True)
class Roofline:
    """One point on the roofline chart, with the roof it's plotted against."""
    flops: float
    bytes: float
    seconds: float
    roof: HardwareRoof

    @property
    def ai(self) -> float:
        return self.flops / self.bytes if self.bytes else float("inf")

    @property
    def achieved_tflops(self) -> float:
        return self.flops / self.seconds / 1e12

    @property
    def achieved_gbps(self) -> float:
        return self.bytes / self.seconds / 1e9

    @property
    def bound(self) -> str:
        """Chart-side classification: which side of the ridge the AI is on."""
        return "memory" if self.ai < self.roof.ridge else "compute"

    @property
    def binding(self) -> str:
        """Which resource the *measured* run leans on harder.

        Utilization-based (achieved/peak per resource) — the right label
        when DMA and compute overlap: a kernel at 57% of the VPU roof and
        36% of HBM is compute-bound even if its AI sits left of the
        ridge.  Matches DESIGN.md's stage-kernel bisection methodology.
        """
        cu = self.achieved_tflops / self.roof.peak_tflops
        mu = self.achieved_gbps / self.roof.hbm_gbps
        return "compute" if cu >= mu else "memory"

    @property
    def roof_tflops(self) -> float:
        """Attainable TFLOP/s at this AI (the roofline itself)."""
        return min(self.roof.peak_tflops, self.ai * self.roof.hbm_gbps * 1e-3)

    @property
    def efficiency(self) -> float:
        """Achieved / attainable at this AI (1.0 = on the roof)."""
        return self.achieved_tflops / self.roof_tflops if self.roof_tflops else 0.0

    def report(self) -> str:
        cu = 100 * self.achieved_tflops / self.roof.peak_tflops
        mu = 100 * self.achieved_gbps / self.roof.hbm_gbps
        return (
            f"roofline [{self.roof.name}]: AI={self.ai:.3f} flops/byte "
            f"(ridge {self.roof.ridge:.1f}); "
            f"achieved {self.achieved_tflops:.2f} TFLOP/s ({cu:.0f}% of "
            f"compute roof), {self.achieved_gbps:.0f} GB/s ({mu:.0f}% of "
            f"HBM) -> {self.binding}-bound; "
            f"attainable at this AI {self.roof_tflops:.2f} TFLOP/s "
            f"({100 * self.efficiency:.0f}%)"
        )


def roofline(fn: Callable, *args, seconds: float,
             roof: HardwareRoof = TPU_V4_CLASS, **kwargs) -> Roofline:
    """Roofline point for one measured execution of ``fn(*args)``."""
    c = cost_analysis(fn, *args, **kwargs)
    return Roofline(c["flops"], c["bytes"], seconds, roof)


def median_chain_seconds(fn, args, iters: int, reps: int = 5):
    """Median wall seconds of one blocking ``fn(*args)`` call, / iters.

    The latency-chain methodology of scripts/comm_probe.py: ``fn`` must
    internally chain ``iters`` DEPENDENT repetitions of the measured
    operation (each iteration consuming the previous one's output), so
    one dispatch amortizes over the chain and the per-iteration figure
    is the operation's true serial latency — the ping-pong structure
    every collective microbenchmark uses.  The first call (compile) is
    discarded; the median of ``reps`` timed calls is returned.
    """
    jax.block_until_ready(fn(*args))
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2] / iters


def steady_state_rate(run, y, k1: int = 3000, k2: int = 15000):
    """Dispatch-overhead-free steps/sec of a compiled ``run(y, k)``.

    ``run`` must integrate ``k`` steps from carry ``y`` and return the
    new carry (donated), with ``k`` a traced argument (one executable
    for any window).  Each dispatch through a remote/tunneled device
    can pay ~0.1 s of fixed latency, biasing single-window rates down
    3-15% (measured on this machine's TPU: 2 000-step window ->
    2 758 steps/s, 12 000 -> 3 105, identical code).  Timing two window
    sizes and differencing removes the intercept exactly:
    ``rate = (k2 - k1) / (T2 - T1)``.

    Returns ``(rate, y_final)``; the caller warms up/compiles first.
    """
    def window(y, k):
        t0 = time.perf_counter()
        y = run(y, k)
        jax.block_until_ready(jax.tree_util.tree_leaves(y)[0])
        return y, time.perf_counter() - t0

    for attempt in range(3):
        y, t1 = window(y, k1)
        y, t2 = window(y, k2)
        if t2 > t1:
            return (k2 - k1) / (t2 - t1), y
        # t2 <= t1 is physically impossible for k2 > k1 — a transient
        # tunnel/runtime hiccup polluted a window (observed once);
        # re-measure rather than return a negative rate.
    raise RuntimeError(
        f"steady_state_rate: inconsistent windows (t1={t1:.4f}s for {k1} "
        f"steps, t2={t2:.4f}s for {k2}) after 3 attempts")


class StepTimer:
    """Steady-state step timing: call ``t = timer(step_fn, state)``.

    Blocks on the result each rep, so each sample is one full device
    round-trip; the first ``discard`` samples (compile + warmup) are
    dropped from the stats.
    """

    def __init__(self, discard: int = 1):
        self.discard = discard
        self.samples: list = []

    def time(self, fn: Callable, *args, reps: int = 10, **kwargs):
        out = None
        for _ in range(self.discard + reps):
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
            self.samples.append(time.perf_counter() - t0)
        return out

    @property
    def kept(self) -> Sequence[float]:
        return self.samples[self.discard:]

    @staticmethod
    def _percentile(sorted_samples: Sequence[float], q: float) -> float:
        """Nearest-rank (ceil) percentile: the smallest sample >= the
        q-quantile.  The previous p90 used ``int(n * 0.9) - 1``, which
        under-indexes for small n (n=2 returned the MINIMUM as p90;
        n=10 was only right by accident of truncation) — the ceil
        convention is exact for all n >= 1."""
        return sorted_samples[max(0, math.ceil(q * len(sorted_samples)) - 1)]

    def stats(self) -> Dict[str, float]:
        k = sorted(self.kept)
        if not k:
            return {}
        return {
            "n": len(k),
            "mean_s": statistics.fmean(k),
            "min_s": k[0],
            "p50_s": self._percentile(k, 0.50),
            "p90_s": self._percentile(k, 0.90),
            "p99_s": self._percentile(k, 0.99),
        }

    def sim_days_per_sec(self, dt: float, steps_per_call: int = 1) -> float:
        s = self.stats()
        if not s:
            return 0.0
        return steps_per_call * dt / 86400.0 / s["p50_s"]


@contextlib.contextmanager
def trace(logdir: str):
    """``with trace('/tmp/tb'):`` — jax.profiler trace for TensorBoard/xprof."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
