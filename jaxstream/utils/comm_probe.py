"""Per-stage cube-edge exchange latency probes (library half).

The race-free 4-stage schedule puts FOUR sequential ``ppermute``s on
every SSPRK3 stage's critical path; the overlapped-exchange redesign
(``parallelization.overlap_exchange``) exists to hide exactly that
chain under the interior RHS kernel.  These probes make the chain —
and the win — measurable, per stage:

  * :func:`probe_exchange` — for each of the 4 schedule stages, a
    jitted shard_map program chains ``iters`` back-to-back
    ``ppermute``s of a real-sized ``(3, halo, n)`` payload (each hop
    depends on the last, so wall time / iters is the per-stage
    wire+dispatch latency — the same methodology as a ping-pong
    NCCL/ICI probe), plus the production 4-stage exchange (rotation +
    seam symmetrization included) chained the same way.
  * :func:`probe_step_rates` — steady-state steps/s of the explicit
    covariant face stepper, serialized vs overlapped schedule.

Consumed by ``scripts/comm_probe.py`` (the CLI), ``bench.py``'s
multichip section, and the driver's MULTICHIP dryrun gate.  On CPU the
numbers characterize dispatch/copy structure, not ICI — the probes'
reason to exist is running unchanged on a real slice.
"""

from __future__ import annotations

from .profiling import median_chain_seconds

__all__ = ["temporal_block_plan", "batched_exchange_plan",
           "serve_placement_plan", "probe_exchange", "probe_step_rates",
           "run_default_probe", "format_report"]

#: ppermutes per SSPRK3 step of the serialized face-tier exchange:
#: 4 race-free schedule stages x 3 RK stages.
SERIALIZED_PPERMUTES_PER_STEP = 12


def temporal_block_plan(n: int, halo: int, temporal_block: int,
                        rk_stages: int = 3,
                        strip_dtype_bytes: int = 4) -> dict:
    """Static exchange/compute accounting of temporal halo blocking.

    Pure arithmetic — no devices, no jax — shared by the CLI report,
    ``bench.py``'s JSON, and the non-slow schedule test.  For a k-step
    block on the one-face-per-device tier the deep halo width is
    ``D = rk_stages * k * halo`` (each RK stage consumes ``halo`` of
    ghost validity) and stage ``i`` (0-based, of ``rk_stages*k``)
    computes an ``(n + 2*(D - (i+1)*halo))^2`` window:

    * ``ppermutes_per_step``: 4 schedule stages once per block / k
      steps, vs the serialized 12 per step.
    * ``payload_elems_per_step``: per-edge payload elements shipped per
      simulated step each way (3 fields x D-deep x n strips once per
      block) — equal to the serialized path's by construction (the k
      exchanges collapse, they don't shrink).
    * ``redundant_compute_fraction``: extra RHS cell-evaluations vs the
      k=1 path, averaged over the block's ``rk_stages*k`` windows —
      ``mean_i ((n + 2*(D - (i+1)h))^2 - n^2) / n^2``; the first-stage
      (worst) term is ``((n + 2*(D - h))^2 - n^2) / n^2``, bounded by
      the docs' headline ``((n + 2kh)^2 - n^2) / n^2`` with ``k``
      counting exchange-free RHS evaluations (``rk_stages *
      temporal_block``).

    ``strip_dtype_bytes`` (round 10): bytes per exchanged strip element
    — 4 (f32, the default) or 2 when the strips ride a 16-bit precision
    policy (``jaxstream.ops.pallas.precision.strip_dtype_bytes``).
    Sets ``payload_bytes_per_step`` and the reported
    ``wire_bytes_saving_vs_f32`` fraction; element counts are
    dtype-independent.

    ``schedule_fingerprint`` (round 13): the canonical digest of the
    4-stage race-free schedule this accounting assumes
    (:func:`jaxstream.geometry.connectivity.schedule_fingerprint`).
    ``jaxstream.analysis`` recomputes the fingerprint from the traced
    steppers' actual ``ppermute`` perms and cross-checks it against
    this field, so the analytic plan and the compiled schedule can
    never silently diverge.
    """
    from ..geometry.connectivity import schedule_fingerprint

    if temporal_block < 1:
        raise ValueError(
            f"temporal_block must be >= 1, got {temporal_block}")
    k = temporal_block
    D = rk_stages * k * halo
    stages = rk_stages * k
    windows = [n + 2 * (D - (i + 1) * halo) for i in range(stages)]
    redundant = [(w * w - n * n) / float(n * n) for w in windows]
    from ..plan.rules import RULES_VERSION

    return {
        "temporal_block": k,
        "schedule_fingerprint": schedule_fingerprint(),
        "rules_version": RULES_VERSION,
        "deep_halo_width": D,
        "fits": n >= D,
        "ppermutes_per_step": 4.0 / k,
        "serialized_ppermutes_per_step": float(
            SERIALIZED_PPERMUTES_PER_STEP),
        "exchange_latency_ratio": (4.0 / k)
            / SERIALIZED_PPERMUTES_PER_STEP,
        "payload_elems_per_step": 3 * D * n * 4 / k,
        "strip_dtype_bytes": strip_dtype_bytes,
        "payload_bytes_per_step": 3 * D * n * 4 * strip_dtype_bytes / k,
        "wire_bytes_saving_vs_f32": 1.0 - strip_dtype_bytes / 4.0,
        "redundant_compute_fraction": sum(redundant) / stages,
        "redundant_compute_fraction_first_stage": redundant[0],
    }


def batched_exchange_plan(n: int, halo: int, members: int,
                          rk_stages: int = 3,
                          dtype_bytes: int = 4) -> dict:
    """Static exchange accounting of the batched ensemble exchange.

    Pure arithmetic — no devices, no jax — the batched-exchange twin of
    :func:`temporal_block_plan`, shared by the CLI report, bench.py's
    ensemble section, and the non-slow plumbing test.  A B-member
    ensemble step on the face tier issues the SAME 12 ppermutes per
    step as a single member (4 schedule stages x ``rk_stages``) with
    every payload stacked ``(B, 3, halo, n)``; a per-member loop would
    issue ``12 * B``.  Per-member wire bytes are unchanged by
    construction — stacking amortizes collective LAUNCH latency, it
    does not compress anything.

    Keys: ``ppermutes_per_step`` (whole ensemble), ``ppermutes_per_
    member_step`` (12/B), ``serialized_ppermutes_per_member_step`` (12),
    ``launch_latency_ratio`` (1/B), ``payload_bytes_per_ppermute``
    (each way, per edge), ``wire_bytes_per_member_step`` (invariant
    in B).  ``dtype_bytes=2`` is the 16-bit-strips policy
    (round 10) — payload and wire bytes halve; the saving fraction is
    reported as ``wire_bytes_saving_vs_f32``.
    ``schedule_fingerprint`` (round 13): the canonical schedule digest
    the analyzer cross-checks against traced ppermute perms (see
    :func:`temporal_block_plan`).
    """
    from ..geometry.connectivity import schedule_fingerprint

    if members < 1:
        raise ValueError(f"members must be >= 1, got {members}")
    if halo < 1 or n < 1:
        raise ValueError(f"need n >= 1 and halo >= 1, got n={n}, "
                         f"halo={halo}")
    from ..plan.rules import RULES_VERSION

    B = members
    per_step = 4 * rk_stages
    payload = B * 3 * halo * n * dtype_bytes
    return {
        "members": B,
        "schedule_fingerprint": schedule_fingerprint(),
        "rules_version": RULES_VERSION,
        "ppermutes_per_step": float(per_step),
        "ppermutes_per_member_step": per_step / B,
        "serialized_ppermutes_per_member_step": float(per_step),
        "launch_latency_ratio": 1.0 / B,
        "payload_bytes_per_ppermute": payload,
        "wire_bytes_per_member_step": per_step * 3 * halo * n
            * dtype_bytes,
        "strip_dtype_bytes": dtype_bytes,
        "wire_bytes_saving_vs_f32": 1.0 - dtype_bytes / 4.0,
    }


def serve_placement_plan(buckets, num_devices: int, n: int,
                         halo: int = 2, dtype_bytes: int = 4) -> dict:
    """Static serving-placement accounting (round 12) — the
    ``comm_probe --serve`` report body.

    Pure arithmetic — no devices, no jax — a thin wrap of
    :func:`jaxstream.serve.placement.placement_report`: for each
    placement mode (member-parallel / panel-sharded), per batch-size
    bucket, the resolved device split and the halo-exchange bytes per
    step it would put on the wire (member mode: ZERO — members never
    communicate; panel mode: the face tier's 12 ppermutes/step at the
    batched-exchange payload).  ``dtype_bytes=2`` re-bills a 16-bit
    strips policy, like the other plans.
    The panel accounting assumes the canonical race-free schedule; its
    ``schedule_fingerprint`` is the analyzer's cross-check hook
    (round 13, see :func:`temporal_block_plan`).
    """
    from ..geometry.connectivity import schedule_fingerprint
    from ..serve.placement import placement_report

    from ..plan.rules import RULES_VERSION

    out = placement_report(buckets, num_devices, n, halo,
                           dtype_bytes=dtype_bytes)
    out["schedule_fingerprint"] = schedule_fingerprint()
    out["rules_version"] = RULES_VERSION
    return out


def run_default_probe(iters: int = 100, steps: int = 30, n: int = 0,
                      temporal_block: int = 0, members: int = 0,
                      devices=None, plan_only: bool = False,
                      strip_dtype_bytes: int = 4):
    """Full probe suite with the shared device/size policy.

    The one place the selection lives (CLI, bench multichip, dryrun
    gate all call through here): the DEFAULT platform's devices when at
    least 6 exist (a real slice measures real ICI), else 6 virtual CPU
    devices (structural dispatch-level numbers, platform-tagged in the
    report); face size ``n`` defaults to a production-ish 96 on real
    accelerators and 16 on the CPU smoke.  Returns the result dict
    (``n``, ``devices``, ``platform``, stage/exchange latencies, step
    rates, and — when ``temporal_block > 1`` — the blocked-vs-serialized
    rates plus the :func:`temporal_block_plan` accounting).

    ``devices``: explicit device list overriding the policy (tests pass
    fakes with a ``platform`` attribute).  ``plan_only=True`` stops
    after the device/size/schedule selection — everything that needs no
    compilation — so the plumbing is testable in milliseconds.

    ``strip_dtype_bytes``: bytes per exchanged strip element for the
    PLAN accounting (2 under a 16-bit strips policy — CLI
    ``--strip-dtype bf16``).  The measured latencies always ship f32
    strips: the sharded steppers run f32 numerics (the 16-bit wire is
    the single-device fused path's policy), so the plans report the
    savings a 16-bit exchange WOULD bank, explicitly tagged.
    """
    from ..geometry.connectivity import build_connectivity, build_schedule

    if devices is None:
        import jax

        devices = jax.devices()
    device_type = "default" if len(devices) >= 6 else "cpu"
    platform = (getattr(devices[0], "platform", "cpu")
                if device_type == "default" else "cpu")
    n = n or (96 if platform != "cpu" else 16)
    halo = 2
    result = {"n": n, "devices": 6, "platform": platform}
    result["schedule_stages"] = len(build_schedule(build_connectivity()))
    if temporal_block > 1:
        result["temporal_block_plan"] = temporal_block_plan(
            n, halo, temporal_block,
            strip_dtype_bytes=strip_dtype_bytes)
    if members > 1:
        result["batched_exchange_plan"] = batched_exchange_plan(
            n, halo, members, dtype_bytes=strip_dtype_bytes)
    if plan_only:
        return result

    import jax.numpy as jnp

    from ..config import EARTH_RADIUS
    from ..geometry.cubed_sphere import build_grid
    from ..parallel.mesh import setup_sharding

    setup = setup_sharding({"parallelization": {
        "num_devices": 6, "device_type": device_type,
        "use_shard_map": True}})
    platform = setup.mesh.devices.flat[0].platform
    result["platform"] = platform
    grid = build_grid(n, halo=halo, radius=EARTH_RADIUS, dtype=jnp.float32)
    result.update(probe_exchange(grid, setup.mesh, iters=iters))
    result.update(probe_step_rates(grid, setup, steps=steps,
                                   temporal_block=temporal_block,
                                   members=members))
    return result


def probe_exchange(grid, mesh, iters: int = 100):
    """Per-stage + full-exchange latency on a ``(panel=6,1,1)`` mesh.

    Returns ``{"stage_us": [4 floats], "exchange_us": float}`` —
    median microseconds per chained iteration.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.shard_cov import (CovShardProgram,
                                      make_cov_shard_exchange)
    from .jax_compat import shard_map

    n, halo, m = grid.n, grid.halo, grid.m
    program = CovShardProgram(grid)
    axis = program.axis_name
    axes = mesh.axis_names
    sh = NamedSharding(mesh, P(axes[0]))

    stage_us = []
    for s, perm in enumerate(program.perms):
        def chain(x, _perm=perm):
            for _ in range(iters):
                x = lax.ppermute(x, axis, _perm)
            return x

        fn = jax.jit(shard_map(
            chain, mesh=mesh, in_specs=P(axes[0]), out_specs=P(axes[0]),
            check_vma=False))
        x = jax.device_put(jnp.zeros((6, 3, halo, n), jnp.float32), sh)
        stage_us.append(1e6 * median_chain_seconds(fn, (x,), iters))

    # Full production exchange (ghost writes + rotations + seam sym),
    # chained through its own output so each iteration depends on the
    # last.
    exchange = make_cov_shard_exchange(program)
    tables = {k: jax.device_put(v, sh) for k, v in program.tables.items()}
    ex_iters = max(1, iters // 10)

    def chain_ex(h_blk, u_blk, t):
        for _ in range(ex_iters):
            h_blk, u_blk, ssn, swe = exchange(h_blk, u_blk, t)
            h_blk = h_blk + ssn[:, :1, :1]
        return h_blk

    fn = jax.jit(shard_map(
        chain_ex, mesh=mesh,
        in_specs=(P(axes[0]), P(None, axes[0]),
                  {k: P(axes[0]) for k in tables}),
        out_specs=P(axes[0]), check_vma=False))
    h_blk = jax.device_put(jnp.zeros((6, m, m), jnp.float32), sh)
    u_blk = jax.device_put(jnp.zeros((2, 6, m, m), jnp.float32),
                           NamedSharding(mesh, P(None, axes[0])))
    ex_us = 1e6 * median_chain_seconds(
        fn, (h_blk, u_blk, tables), ex_iters)
    return {"stage_us": [round(u, 2) for u in stage_us],
            "exchange_us": round(ex_us, 2)}


def probe_step_rates(grid, setup, dt: float = 300.0, steps: int = 50,
                     temporal_block: int = 0, members: int = 0):
    """Steady-state steps/s of the explicit covariant face stepper,
    serialized vs overlapped.  Returns ``{"serialized_steps_per_sec",
    "overlap_steps_per_sec", "overlap_speedup"}`` — plus, when
    ``temporal_block = k > 1`` fits the grid, the deep-halo blocked
    stepper's rate (``temporal_block_steps_per_sec`` counts SIMULATED
    steps: blocks/s x k) and its speedup over the serialized path, and,
    when ``members = B > 1``, the batched ensemble stepper's rate
    (``ensemble_member_steps_per_sec`` counts MEMBER-steps: calls/s x B
    — one call advances every member) with its per-member speedup over
    the serialized single-member path."""
    import jax
    import jax.numpy as jnp

    from ..config import EARTH_GRAVITY, EARTH_OMEGA
    from ..models.shallow_water_cov import CovariantShallowWater
    from ..parallel.mesh import shard_state
    from ..parallel.shard_cov import make_sharded_cov_stepper
    from ..physics.initial_conditions import williamson_tc2

    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA)
    ss = shard_state(setup, model.initial_state(h_ext, v_ext))

    variants = [("serialized", dict(overlap=False)),
                ("overlap", dict(overlap=True))]
    k = temporal_block
    with_blocked = k > 1 and grid.n >= 3 * k * grid.halo
    if with_blocked:
        variants.append(("temporal_block", dict(temporal_block=k)))

    rates = {}
    for key, kw in variants:
        step = make_sharded_cov_stepper(model, setup, dt, **kw)
        spc = getattr(step, "steps_per_call", 1)
        ncalls = max(1, steps // spc)

        # fori_loop, not a Python-unrolled window: the step traces ONCE
        # however long the window (at the real-slice configuration an
        # unrolled 50-step program is hundreds of kernels/ppermutes and
        # can take minutes to compile); the carry dependency preserves
        # the chained-latency methodology.
        @jax.jit
        def run(y, _step=step, _ncalls=ncalls):
            return jax.lax.fori_loop(
                0, _ncalls, lambda i, yy: _step(yy, jnp.float32(0.0)), y)

        sec = median_chain_seconds(run, (ss,), ncalls * spc, reps=3)
        rates[f"{key}_steps_per_sec"] = round(1.0 / sec, 2)
    rates["overlap_speedup"] = round(
        rates["overlap_steps_per_sec"]
        / rates["serialized_steps_per_sec"], 4)
    if with_blocked:
        rates["temporal_block_speedup"] = round(
            rates["temporal_block_steps_per_sec"]
            / rates["serialized_steps_per_sec"], 4)
    elif k > 1:
        rates["temporal_block_skipped"] = (
            f"n={grid.n} < 3*k*halo={3 * k * grid.halo}")

    B = members
    if B > 1:
        from ..parallel.shard_cov import make_sharded_cov_ensemble_stepper

        estep = make_sharded_cov_ensemble_stepper(model, setup, dt, B)
        ssb = {"h": jnp.stack([ss["h"]] * B),
               "u": jnp.stack([ss["u"]] * B, axis=1)}
        from ..parallel.mesh import shard_ensemble_state

        ssb = shard_ensemble_state(setup, ssb)
        ncalls = max(1, steps // 4)

        @jax.jit
        def runb(y):
            return jax.lax.fori_loop(
                0, ncalls, lambda i, yy: estep(yy, jnp.float32(0.0)), y)

        sec = median_chain_seconds(runb, (ssb,), ncalls, reps=3)
        rates["ensemble_members"] = B
        # One call advances every member: member-steps/s = B / call sec.
        rates["ensemble_member_steps_per_sec"] = round(B / sec, 2)
        rates["ensemble_per_member_speedup"] = round(
            (B / sec) / rates["serialized_steps_per_sec"], 4)
    return rates


def format_report(result: dict) -> str:
    """One human-readable line per measurement (CI-log friendly)."""
    plat = result.get("platform")
    tag = f" [{plat}]" if plat else ""
    lines = []
    st = result.get("stage_us")
    if st:
        lines.append(f"comm_probe{tag}: per-stage exchange latency "
                     + "  ".join(f"stage{i}={u:.1f}us"
                                 for i, u in enumerate(st))
                     + f"  full-exchange={result['exchange_us']:.1f}us")
    if "serialized_steps_per_sec" in result:
        line = (
            f"comm_probe{tag}: steps/s "
            f"serialized={result['serialized_steps_per_sec']:.1f} "
            f"overlap={result['overlap_steps_per_sec']:.1f} "
            f"(x{result['overlap_speedup']:.3f})")
        if "temporal_block_steps_per_sec" in result:
            line += (
                f" temporal_block="
                f"{result['temporal_block_steps_per_sec']:.1f} "
                f"(x{result['temporal_block_speedup']:.3f})")
        lines.append(line)
    if "ensemble_member_steps_per_sec" in result:
        lines.append(
            f"comm_probe{tag}: ensemble B={result['ensemble_members']} "
            f"member-steps/s="
            f"{result['ensemble_member_steps_per_sec']:.1f} "
            f"(x{result['ensemble_per_member_speedup']:.3f} per member "
            f"vs serialized)")
    be = result.get("batched_exchange_plan")
    if be:
        lines.append(
            f"comm_probe{tag}: batched exchange B={be['members']} "
            f"ppermutes/member-step="
            f"{be['ppermutes_per_member_step']:.2f} "
            f"(vs {be['serialized_ppermutes_per_member_step']:.0f}) "
            f"payload/ppermute={be['payload_bytes_per_ppermute']} B "
            f"wire/member-step={be['wire_bytes_per_member_step']} B"
            + (f" (16-bit strips: -"
               f"{100 * be['wire_bytes_saving_vs_f32']:.0f}% wire)"
               if be.get("wire_bytes_saving_vs_f32") else "")
            + (f" sched={be['schedule_fingerprint']}"
               if be.get("schedule_fingerprint") else "")
            + (f" rules=v{be['rules_version']}"
               if be.get("rules_version") else ""))
    sp = result.get("serve_placement_plan")
    if sp:
        if sp.get("schedule_fingerprint"):
            lines.append(
                f"comm_probe{tag}: serve placement panel exchange "
                f"assumes the canonical race-free schedule "
                f"sched={sp['schedule_fingerprint']}")
        for mode, info in sp["modes"].items():
            if "skipped" in info:
                lines.append(
                    f"comm_probe{tag}: serve placement {mode} on "
                    f"{sp['num_devices']} devices: skipped "
                    f"({info['skipped']})")
                continue
            for row in info["buckets"]:
                lines.append(
                    f"comm_probe{tag}: serve placement {mode} B="
                    f"{row['bucket']}: {row['mode']} on "
                    f"{row['devices']} device(s) "
                    f"({row['panel_shards']}x{row['member_shards']} "
                    f"mesh, {row['members_per_shard']} members/shard) "
                    f"exchange/step={row['exchange_bytes_per_step']:.0f} B")
    tb = result.get("temporal_block_plan")
    if tb:
        lines.append(
            f"comm_probe{tag}: temporal_block k={tb['temporal_block']} "
            f"deep_halo={tb['deep_halo_width']} "
            f"exchanges/step={tb['ppermutes_per_step']:.2f} "
            f"(vs {tb['serialized_ppermutes_per_step']:.0f}) "
            f"redundant_compute="
            f"{tb['redundant_compute_fraction']:.3f}"
            f" (first stage "
            f"{tb['redundant_compute_fraction_first_stage']:.3f})"
            + (f" payload/step={tb['payload_bytes_per_step']:.0f} B "
               f"(16-bit strips: -"
               f"{100 * tb['wire_bytes_saving_vs_f32']:.0f}% wire)"
               if tb.get("wire_bytes_saving_vs_f32") else "")
            + (f" sched={tb['schedule_fingerprint']}"
               if tb.get("schedule_fingerprint") else "")
            + (f" rules=v{tb['rules_version']}"
               if tb.get("rules_version") else ""))
    return "\n".join(lines)
