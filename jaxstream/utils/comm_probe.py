"""Per-stage cube-edge exchange latency probes (library half).

The race-free 4-stage schedule puts FOUR sequential ``ppermute``s on
every SSPRK3 stage's critical path; the overlapped-exchange redesign
(``parallelization.overlap_exchange``) exists to hide exactly that
chain under the interior RHS kernel.  These probes make the chain —
and the win — measurable, per stage:

  * :func:`probe_exchange` — for each of the 4 schedule stages, a
    jitted shard_map program chains ``iters`` back-to-back
    ``ppermute``s of a real-sized ``(3, halo, n)`` payload (each hop
    depends on the last, so wall time / iters is the per-stage
    wire+dispatch latency — the same methodology as a ping-pong
    NCCL/ICI probe), plus the production 4-stage exchange (rotation +
    seam symmetrization included) chained the same way.
  * :func:`probe_step_rates` — steady-state steps/s of the explicit
    covariant face stepper, serialized vs overlapped schedule.

Consumed by ``scripts/comm_probe.py`` (the CLI), ``bench.py``'s
multichip section, and the driver's MULTICHIP dryrun gate.  On CPU the
numbers characterize dispatch/copy structure, not ICI — the probes'
reason to exist is running unchanged on a real slice.
"""

from __future__ import annotations

from .profiling import median_chain_seconds

__all__ = ["probe_exchange", "probe_step_rates", "run_default_probe",
           "format_report"]


def run_default_probe(iters: int = 100, steps: int = 30, n: int = 0):
    """Full probe suite with the shared device/size policy.

    The one place the selection lives (CLI, bench multichip, dryrun
    gate all call through here): the DEFAULT platform's devices when at
    least 6 exist (a real slice measures real ICI), else 6 virtual CPU
    devices (structural dispatch-level numbers, platform-tagged in the
    report); face size ``n`` defaults to a production-ish 96 on real
    accelerators and 16 on the CPU smoke.  Returns the result dict
    (``n``, ``devices``, ``platform``, stage/exchange latencies, step
    rates).
    """
    import jax
    import jax.numpy as jnp

    from ..config import EARTH_RADIUS
    from ..geometry.cubed_sphere import build_grid
    from ..parallel.mesh import setup_sharding

    device_type = "default" if len(jax.devices()) >= 6 else "cpu"
    setup = setup_sharding({"parallelization": {
        "num_devices": 6, "device_type": device_type,
        "use_shard_map": True}})
    platform = setup.mesh.devices.flat[0].platform
    n = n or (96 if platform != "cpu" else 16)
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    result = {"n": n, "devices": setup.num_devices, "platform": platform}
    result.update(probe_exchange(grid, setup.mesh, iters=iters))
    result.update(probe_step_rates(grid, setup, steps=steps))
    return result


def probe_exchange(grid, mesh, iters: int = 100):
    """Per-stage + full-exchange latency on a ``(panel=6,1,1)`` mesh.

    Returns ``{"stage_us": [4 floats], "exchange_us": float}`` —
    median microseconds per chained iteration.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.shard_cov import (CovShardProgram,
                                      make_cov_shard_exchange)
    from .jax_compat import shard_map

    n, halo, m = grid.n, grid.halo, grid.m
    program = CovShardProgram(grid)
    axis = program.axis_name
    axes = mesh.axis_names
    sh = NamedSharding(mesh, P(axes[0]))

    stage_us = []
    for s, perm in enumerate(program.perms):
        def chain(x, _perm=perm):
            for _ in range(iters):
                x = lax.ppermute(x, axis, _perm)
            return x

        fn = jax.jit(shard_map(
            chain, mesh=mesh, in_specs=P(axes[0]), out_specs=P(axes[0]),
            check_vma=False))
        x = jax.device_put(jnp.zeros((6, 3, halo, n), jnp.float32), sh)
        stage_us.append(1e6 * median_chain_seconds(fn, (x,), iters))

    # Full production exchange (ghost writes + rotations + seam sym),
    # chained through its own output so each iteration depends on the
    # last.
    exchange = make_cov_shard_exchange(program)
    tables = {k: jax.device_put(v, sh) for k, v in program.tables.items()}
    ex_iters = max(1, iters // 10)

    def chain_ex(h_blk, u_blk, t):
        for _ in range(ex_iters):
            h_blk, u_blk, ssn, swe = exchange(h_blk, u_blk, t)
            h_blk = h_blk + ssn[:, :1, :1]
        return h_blk

    fn = jax.jit(shard_map(
        chain_ex, mesh=mesh,
        in_specs=(P(axes[0]), P(None, axes[0]),
                  {k: P(axes[0]) for k in tables}),
        out_specs=P(axes[0]), check_vma=False))
    h_blk = jax.device_put(jnp.zeros((6, m, m), jnp.float32), sh)
    u_blk = jax.device_put(jnp.zeros((2, 6, m, m), jnp.float32),
                           NamedSharding(mesh, P(None, axes[0])))
    ex_us = 1e6 * median_chain_seconds(
        fn, (h_blk, u_blk, tables), ex_iters)
    return {"stage_us": [round(u, 2) for u in stage_us],
            "exchange_us": round(ex_us, 2)}


def probe_step_rates(grid, setup, dt: float = 300.0, steps: int = 50):
    """Steady-state steps/s of the explicit covariant face stepper,
    serialized vs overlapped.  Returns ``{"serialized_steps_per_sec",
    "overlap_steps_per_sec", "overlap_speedup"}``."""
    import jax
    import jax.numpy as jnp

    from ..config import EARTH_GRAVITY, EARTH_OMEGA
    from ..models.shallow_water_cov import CovariantShallowWater
    from ..parallel.mesh import shard_state
    from ..parallel.shard_cov import make_sharded_cov_stepper
    from ..physics.initial_conditions import williamson_tc2

    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA)
    ss = shard_state(setup, model.initial_state(h_ext, v_ext))

    rates = {}
    for key, overlap in (("serialized", False), ("overlap", True)):
        step = make_sharded_cov_stepper(model, setup, dt, overlap=overlap)

        # fori_loop, not a Python-unrolled window: the step traces ONCE
        # however long the window (at the real-slice configuration an
        # unrolled 50-step program is hundreds of kernels/ppermutes and
        # can take minutes to compile); the carry dependency preserves
        # the chained-latency methodology.
        @jax.jit
        def run(y, _step=step):
            return jax.lax.fori_loop(
                0, steps, lambda i, yy: _step(yy, jnp.float32(0.0)), y)

        sec = median_chain_seconds(run, (ss,), steps, reps=3)
        rates[f"{key}_steps_per_sec"] = round(1.0 / sec, 2)
    rates["overlap_speedup"] = round(
        rates["overlap_steps_per_sec"]
        / rates["serialized_steps_per_sec"], 4)
    return rates


def format_report(result: dict) -> str:
    """One human-readable line per measurement (CI-log friendly)."""
    plat = result.get("platform")
    tag = f" [{plat}]" if plat else ""
    lines = []
    st = result.get("stage_us")
    if st:
        lines.append(f"comm_probe{tag}: per-stage exchange latency "
                     + "  ".join(f"stage{i}={u:.1f}us"
                                 for i, u in enumerate(st))
                     + f"  full-exchange={result['exchange_us']:.1f}us")
    if "serialized_steps_per_sec" in result:
        lines.append(
            f"comm_probe{tag}: steps/s "
            f"serialized={result['serialized_steps_per_sec']:.1f} "
            f"overlap={result['overlap_steps_per_sec']:.1f} "
            f"(x{result['overlap_speedup']:.3f})")
    return "\n".join(lines)
