"""Structured logging (replaces the reference's print banners,
``JAX-DevLab-Examples.py:26-28,59-85,218,235,245`` — SURVEY.md §5).

Multihost-aware (round-8 satellite): records carry the JAX process
index, and by default only process 0 logs below WARNING — a 24-device
pod (or the 24-virtual-device subprocess tests) emits ONE stream of
INFO banners instead of 24 interleaved copies, while real problems on
any host still surface.  Setting ``JAXSTREAM_LOG`` (any level) is the
explicit override: every process then logs at that level, prefixed
``p<idx>`` so the streams remain attributable.

Process identity is resolved lazily per record, never at import:
``jax.distributed`` initializes long after the first ``get_logger``
call, and pre-init ``jax.process_index()`` is simply 0 — the filter
picks up the real index from the first record logged after init.
"""

from __future__ import annotations

import logging
import os

_FORMAT = "%(asctime)s %(levelname).1s %(pidx)s%(name)s: %(message)s"
_configured = False


def _process_info():
    """(process_index, process_count), lazily and failure-proof.

    MUST NOT initialize anything: ``jax.process_index()`` triggers
    backend initialization as a side effect, and a log record emitted
    before ``jax.distributed.initialize()`` would lock a pod run into
    single-process mode.  Until the distributed client exists or some
    real computation has initialized the backends anyway, report
    (0, 1) — the filter then picks up the true identity from the first
    record logged after initialization.
    """
    try:
        from jax._src import distributed

        if getattr(distributed.global_state, "client", None) is None:
            from jax._src import xla_bridge

            if not getattr(xla_bridge, "_backends", None):
                return 0, 1
        import jax

        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1


class _MultihostFilter(logging.Filter):
    """Stamp the process prefix; demote non-zero processes to WARNING.

    ``forced=True`` (the ``JAXSTREAM_LOG`` override) keeps every
    process at the configured level — prefixed, so interleaved streams
    stay attributable.
    """

    def __init__(self, forced: bool):
        super().__init__()
        self.forced = forced

    def filter(self, record):
        idx, nproc = _process_info()
        record.pidx = f"p{idx} " if nproc > 1 else ""
        if idx != 0 and not self.forced \
                and record.levelno < logging.WARNING:
            return False
        return True


def get_logger(name: str = "jaxstream") -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("JAXSTREAM_LOG", "INFO").upper()
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        handler.addFilter(_MultihostFilter("JAXSTREAM_LOG" in os.environ))
        root = logging.getLogger("jaxstream")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True
    return logging.getLogger(name if name.startswith("jaxstream") else f"jaxstream.{name}")
