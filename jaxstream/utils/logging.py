"""Structured logging (replaces the reference's print banners,
``JAX-DevLab-Examples.py:26-28,59-85,218,235,245`` — SURVEY.md §5)."""

from __future__ import annotations

import logging
import os

_FORMAT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"
_configured = False


def get_logger(name: str = "jaxstream") -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("JAXSTREAM_LOG", "INFO").upper()
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        root = logging.getLogger("jaxstream")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True
    return logging.getLogger(name if name.startswith("jaxstream") else f"jaxstream.{name}")
