"""Scalar diagnostics: conservation integrals and Williamson error norms.

The reference's scientific observability channel (SURVEY.md §5 "Metrics"):
mass/energy/enstrophy integrals and the normalized l1/l2/linf error norms
of Williamson et al. (1992) used for TC2 parity in ``BASELINE.json``.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..geometry.cubed_sphere import CubedSphereGrid

__all__ = ["total_mass", "total_energy", "potential_enstrophy",
           "error_norms", "ensemble_area_weights", "ensemble_spread",
           "ensemble_mean_rmse", "ensemble_mean_drift"]


def _wsum(grid: CubedSphereGrid, field_int):
    return jnp.sum(field_int * grid.interior(grid.area))


def total_mass(grid: CubedSphereGrid, h_int):
    """integral h dA (h interior (6,n,n))."""
    return _wsum(grid, h_int)


# -- ensemble statistics (round 18) -----------------------------------
# The ONE definition of the area-weighted ensemble spread/RMSE/drift
# formulas: the in-loop MetricSpecs (obs.metrics h_spread /
# ens_mean_drift) and the EnKF cycle's guards + records (jaxstream.da)
# both consume these — the guard compares prior (in-loop) against
# posterior (analysis) spread, so the two sides must be the same
# formula by construction, not by parallel maintenance.

def ensemble_area_weights(grid: CubedSphereGrid, dtype=None):
    """Normalized interior cell-area weights (sum 1)."""
    w = grid.interior(grid.area)
    w = w / jnp.sum(w)
    return w.astype(dtype) if dtype is not None else w


def ensemble_spread(h_b, w):
    """Area-weighted RMS ensemble spread of ``h_b`` ``(B, 6, n, n)``:
    ``sqrt(sum_cells w * var_members)`` (ddof=1)."""
    return jnp.sqrt(jnp.sum(w * jnp.var(h_b, axis=0, ddof=1)))


def ensemble_mean_rmse(h_b, ref, w):
    """Area-weighted RMSE of the ensemble mean against ``ref``."""
    err = jnp.mean(h_b, axis=0) - ref
    return jnp.sqrt(jnp.sum(w * err * err))


def ensemble_mean_drift(h_b, w):
    """Area-weighted RMS distance of the ensemble mean from member
    0."""
    return ensemble_mean_rmse(h_b, h_b[0], w)


def total_energy(grid: CubedSphereGrid, h_int, v_int, gravity: float, b_int=0.0):
    """integral [ h |v|^2/2 + g h (h/2 + b) ] dA."""
    ke = 0.5 * jnp.sum(v_int * v_int, axis=0)
    return _wsum(grid, h_int * ke + gravity * h_int * (0.5 * h_int + b_int))


def potential_enstrophy(grid: CubedSphereGrid, h_int, abs_vort_int):
    """integral (zeta + f)^2 / (2h) dA."""
    return _wsum(grid, abs_vort_int**2 / (2.0 * h_int))


def error_norms(grid: CubedSphereGrid, field_int, ref_int):
    """Williamson normalized l1, l2, linf norms of (field - ref)."""
    w = grid.interior(grid.area)
    diff = field_int - ref_int
    l1 = jnp.sum(jnp.abs(diff) * w) / jnp.sum(jnp.abs(ref_int) * w)
    l2 = jnp.sqrt(jnp.sum(diff**2 * w) / jnp.sum(ref_int**2 * w))
    linf = jnp.max(jnp.abs(diff)) / jnp.max(jnp.abs(ref_int))
    return {"l1": l1, "l2": l2, "linf": linf}
