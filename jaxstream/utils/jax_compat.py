"""Version-portability shims for the narrow band of JAX APIs we use.

The repo targets current JAX (``jax.shard_map``, ``pltpu.CompilerParams``)
but must also run on the 0.4.x line some images ship, where those spell
``jax.experimental.shard_map.shard_map(..., check_rep=...)`` and
``pltpu.TPUCompilerParams``.  Every call site routes through this module
so the rest of the codebase is written against ONE (the current) API.

Only strictly-renamed APIs belong here — behavioral divergences must be
handled (and documented) at the call site.
"""

from __future__ import annotations

import contextlib
import os

import jax

__all__ = ["LEGACY_SHARD_MAP", "compile_count", "copy_to_host_async",
           "deserialize_executable", "deserialize_stablehlo",
           "device_memory_stats", "enable_compile_cache",
           "executable_serialization_available",
           "maybe_enable_compile_cache", "memory_analysis",
           "named_scope", "profiler_available", "serialize_executable",
           "serialize_stablehlo", "shard_map",
           "stablehlo_serialization_available", "start_profiler_trace",
           "stop_profiler_trace", "tpu_compiler_params"]

#: True on the 0.4.x line.  Besides the spelling differences shimmed
#: below, that line's XLA trips an hlo-verifier bug ("tile_assignment
#: should have N devices") on ``vmap(while)`` bodies inside shard_map —
#: loops that can be statically unrolled should be when this is set
#: (see cross.aca_lowrank).
LEGACY_SHARD_MAP = not hasattr(jax, "shard_map")


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax < 0.5: experimental namespace, check_vma spelled check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f=None, /, *, mesh, in_specs, out_specs,
                  check_vma: bool = True, **kw):
        """``jax.shard_map`` signature adapter over the experimental API.

        ``check_vma`` (varying-manual-axes checking) is the renamed
        ``check_rep``; axis semantics are identical for the SPMD
        programs this repo builds (no auto axes used).
        """
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma,
                              **kw)


def named_scope(name: str):
    """``jax.named_scope`` under any supported version; inert otherwise.

    A trace-time name-stack annotation: every op traced inside the
    context carries ``name`` as a prefix, so xprof/TensorBoard traces
    show the fused steppers as named regions (exchange start/finish,
    interior vs band RHS, RK stages, TT sweeps) instead of anonymous
    custom-call soup.  Zero runtime cost — the name lives in HLO
    metadata only — and a no-op context if the API is ever absent, so
    annotated code never gains a hard version dependency.
    """
    ns = getattr(jax, "named_scope", None)
    if ns is None:  # pragma: no cover - every supported jax has it
        return contextlib.nullcontext()
    return ns(name)


def profiler_available() -> bool:
    """True when this jax build exposes the on-demand trace profiler.

    ``jax.profiler.start_trace``/``stop_trace`` is the capture API on
    every supported line, but some stripped builds ship without the
    profiler extension — the gateway's ``POST /v1/profile`` degrades to
    a typed 501 instead of a 500 stack when this returns False.
    """
    prof = getattr(jax, "profiler", None)
    return (prof is not None and hasattr(prof, "start_trace")
            and hasattr(prof, "stop_trace"))


def start_profiler_trace(log_dir: str) -> None:
    """Begin a ``jax.profiler`` trace capture into ``log_dir``.

    Raises ``RuntimeError`` when the build has no profiler (callers
    map it to the typed 501) — never AttributeError soup.  One capture
    at a time is the profiler's own contract; the gateway serializes
    start/stop behind its profile state.
    """
    if not profiler_available():
        raise RuntimeError(
            "jax.profiler.start_trace is unavailable in this jax "
            "build; on-demand profiling is disabled")
    try:
        os.makedirs(log_dir, exist_ok=True)
        jax.profiler.start_trace(log_dir)
    except RuntimeError:
        raise
    except Exception as e:
        # An unwritable log dir (OSError) or a foreign profiler
        # session must surface as the typed RuntimeError the callers
        # map to their 501 contract, never an untyped 500.
        raise RuntimeError(
            f"profiler trace could not start in {log_dir!r}: "
            f"{type(e).__name__}: {e}")


def stop_profiler_trace() -> None:
    """End the in-flight ``jax.profiler`` trace capture."""
    if not profiler_available():
        raise RuntimeError(
            "jax.profiler.stop_trace is unavailable in this jax "
            "build; on-demand profiling is disabled")
    try:
        jax.profiler.stop_trace()
    except RuntimeError:
        raise
    except Exception as e:
        raise RuntimeError(
            f"profiler trace could not stop: {type(e).__name__}: {e}")


#: ``Compiled.memory_analysis()`` size attributes -> the short names the
#: cost stamps carry (``jaxstream.obs.perf``).  ``alias_size_in_bytes``
#: is excluded from ``total_bytes`` — aliased (donated) buffers are
#: already counted once in the argument bytes.
_MEMORY_FIELDS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
)


def memory_analysis(compiled) -> dict:
    """XLA's static memory accounting of one compiled executable.

    Returns ``{"argument_bytes", "output_bytes", "temp_bytes",
    "generated_code_bytes", "alias_bytes", "total_bytes"}`` from
    ``Compiled.memory_analysis()`` — the per-plan footprint the
    performance observatory stamps on every measured stepper
    (``jaxstream.obs.perf``).  Raises ``RuntimeError`` (the typed
    "unavailable" the cost stamps record verbatim) on jax builds /
    backends that expose no memory analysis — never AttributeError
    soup, so a stamp on an exotic backend says *why* it has no bytes
    instead of crashing the build path.
    """
    ma = getattr(compiled, "memory_analysis", None)
    if ma is None:
        raise RuntimeError(
            "unavailable: this jax build exposes no "
            "Compiled.memory_analysis()")
    try:
        st = ma()
    except Exception as e:
        raise RuntimeError(
            f"unavailable: memory_analysis failed "
            f"({type(e).__name__}: {e})")
    out = {}
    for attr, key in _MEMORY_FIELDS:
        v = getattr(st, attr, None)
        if v is not None:
            out[key] = int(v)
    if not out:
        raise RuntimeError(
            "unavailable: memory_analysis returned no size fields "
            f"(got {type(st).__name__})")
    out["total_bytes"] = sum(v for k, v in out.items()
                             if k != "alias_bytes")
    return out


def compile_count(fn):
    """Compiled-executable count of one jitted callable, or None.

    The jit-cache introspection the serving stack's zero-steady-state-
    recompile proofs use (``EnsembleServer.compile_count``), promoted
    here (round 19) so the compile-event counters on the metrics
    scrape and the test assertions read the SAME private surface —
    ``fn._cache_size()`` on every supported jax line; None when the
    build exposes no cache introspection (callers decide how loudly to
    degrade).
    """
    cs = getattr(fn, "_cache_size", None)
    return None if cs is None else int(cs())


def device_memory_stats(device):
    """``device.memory_stats()`` as a dict, or None when the backend
    keeps no per-device allocator stats (CPU returns None; stripped
    builds may omit the method).  The MemoryWatcher's one read — a
    poll can never raise out of the serving loop.
    """
    ms = getattr(device, "memory_stats", None)
    if ms is None:
        return None
    try:
        return ms()
    except Exception:
        return None


def copy_to_host_async(tree):
    """Start device->host copies of every array leaf; returns ``tree``.

    The async-host-pipeline primitive (``jaxstream.io.async_pipeline``):
    enqueues a non-blocking d2h transfer per ``jax.Array`` leaf — the
    transfer is sequenced after the array's definition event, so calling
    this on the *future* a just-dispatched segment returned costs
    nothing on the dispatch path.  A later ``np.asarray`` on the same
    array resolves against the in-flight copy instead of starting a
    blocking one.  Spelled ``Array.copy_to_host_async()`` on every
    supported jax; leaves without the method (numpy arrays, python
    scalars) pass through untouched, so whole state pytrees can be
    handed over unfiltered.
    """
    def start(x):
        m = getattr(x, "copy_to_host_async", None)
        if m is not None:
            m()
        return x

    return jax.tree_util.tree_map(start, tree)


def enable_compile_cache(path: str) -> str:
    """Point jax's persistent compilation cache at ``path`` (created).

    Also zeroes ``jax_persistent_cache_min_compile_time_secs`` so every
    executable is cached — the fast tier's compiles are individually
    sub-second but collectively dominate its wall time.  KNOWN LIMIT on
    this image's jaxlib (0.4.37): a *different process* deserializing
    CPU cache entries segfaults (tests/conftest.py round-8 note), so
    cross-process reuse is an opt-in via ``JAXSTREAM_COMPILE_CACHE``
    rather than a default; same-process reuse (``jax.clear_caches()``
    then recompile, what ``bench.py --compile-report`` measures) is
    solid.
    """
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    for flag, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(flag, val)
        except Exception:  # flag spelling drifts across jax versions
            pass
    try:
        # jax latches cache-enablement once per process at the first
        # compile (is_cache_used's _cache_checked); enabling the cache
        # AFTER something already compiled needs the latch reset or the
        # directory silently stays empty.
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)

        _cc.reset_cache()
    except Exception:
        pass
    return path


def maybe_enable_compile_cache(env: str = "JAXSTREAM_COMPILE_CACHE"):
    """Enable the persistent compile cache iff ``$JAXSTREAM_COMPILE_CACHE``
    names a directory; returns the path or None.  Called on package
    import (jaxstream/__init__.py) so any entrypoint — Simulation, the
    CLI, bench.py — picks the cache up from the environment alone."""
    path = os.environ.get(env, "")
    if not path:
        return None
    return enable_compile_cache(path)


# ----------------------------------------------- executable serialization
# Round 21 (warm pools): the two serialization surfaces the
# jaxstream.serve.warmpool degradation ladder stands on.  Both are
# version-portable shims with typed RuntimeErrors — a build that lacks
# one rung must say so (the pool records the typed miss and drops to
# the next rung), never AttributeError soup.

def executable_serialization_available() -> bool:
    """True when this jax build can serialize a COMPILED executable
    (``jax.experimental.serialize_executable``) — the warm pool's top
    rung: a load skips trace, lower AND backend compile entirely."""
    try:
        from jax.experimental import serialize_executable as se

        return (hasattr(se, "serialize")
                and hasattr(se, "deserialize_and_load"))
    except Exception:
        return False


def serialize_executable(compiled) -> bytes:
    """One compiled executable -> portable bytes (pickled payload).

    ``jax.experimental.serialize_executable.serialize`` returns
    ``(unloaded_bytes, in_tree, out_tree)``; the pytree defs are part
    of the call contract, so the three are pickled together as ONE
    opaque payload ``deserialize_executable`` reverses.  Raises the
    typed RuntimeError on builds without the API.
    """
    if not executable_serialization_available():
        raise RuntimeError(
            "unavailable: this jax build exposes no "
            "jax.experimental.serialize_executable")
    import pickle

    from jax.experimental import serialize_executable as se

    try:
        return pickle.dumps(se.serialize(compiled))
    except Exception as e:
        raise RuntimeError(
            f"unavailable: executable serialization failed "
            f"({type(e).__name__}: {e})")


def deserialize_executable(payload: bytes):
    """Bytes from :func:`serialize_executable` -> a loaded, callable
    ``Compiled`` — ZERO XLA compiles (the warm pool's zero-compile
    parity gate reads exactly this property).  The payload must come
    from the same jaxlib/backend/device-count — the warm-pool cache
    key enforces that; this function only reverses the encoding."""
    if not executable_serialization_available():
        raise RuntimeError(
            "unavailable: this jax build exposes no "
            "jax.experimental.serialize_executable")
    import pickle

    from jax.experimental import serialize_executable as se

    try:
        return se.deserialize_and_load(*pickle.loads(payload))
    except Exception as e:
        raise RuntimeError(
            f"unavailable: executable deserialization failed "
            f"({type(e).__name__}: {e})")


def stablehlo_serialization_available() -> bool:
    """True when this jax build has the ``jax.export`` StableHLO
    round-trip — the warm pool's middle rung: a load re-runs the
    backend compile but skips trace + lower."""
    try:
        import jax.export as jex

        return hasattr(jex, "export") and hasattr(jex, "deserialize")
    except Exception:
        return False


def serialize_stablehlo(jitted, *args, **kwargs) -> bytes:
    """Trace + lower ``jitted(*args)`` once and serialize the exported
    StableHLO module (``jax.export``) — portable across processes and
    (unlike the executable rung) across jaxlib patch versions."""
    if not stablehlo_serialization_available():
        raise RuntimeError(
            "unavailable: this jax build exposes no jax.export")
    import jax.export as jex

    try:
        return jex.export(jitted)(*args, **kwargs).serialize()
    except Exception as e:
        raise RuntimeError(
            f"unavailable: StableHLO export failed "
            f"({type(e).__name__}: {e})")


def deserialize_stablehlo(payload: bytes, donate_argnums=()):
    """Bytes from :func:`serialize_stablehlo` -> a jitted callable.

    The first call performs ONE backend compile (trace + lower are
    skipped — that is the rung's value); ``donate_argnums`` re-applies
    the original jit's donation, which the exported module does not
    carry on its own."""
    if not stablehlo_serialization_available():
        raise RuntimeError(
            "unavailable: this jax build exposes no jax.export")
    import jax.export as jex

    try:
        exported = jex.deserialize(bytearray(payload))
        return jax.jit(exported.call,
                       donate_argnums=tuple(donate_argnums))
    except Exception as e:
        raise RuntimeError(
            f"unavailable: StableHLO import failed "
            f"({type(e).__name__}: {e})")


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams(**kwargs)`` under either spelling."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
