"""Version-portability shims for the narrow band of JAX APIs we use.

The repo targets current JAX (``jax.shard_map``, ``pltpu.CompilerParams``)
but must also run on the 0.4.x line some images ship, where those spell
``jax.experimental.shard_map.shard_map(..., check_rep=...)`` and
``pltpu.TPUCompilerParams``.  Every call site routes through this module
so the rest of the codebase is written against ONE (the current) API.

Only strictly-renamed APIs belong here — behavioral divergences must be
handled (and documented) at the call site.
"""

from __future__ import annotations

import contextlib

import jax

__all__ = ["LEGACY_SHARD_MAP", "named_scope", "shard_map",
           "tpu_compiler_params"]

#: True on the 0.4.x line.  Besides the spelling differences shimmed
#: below, that line's XLA trips an hlo-verifier bug ("tile_assignment
#: should have N devices") on ``vmap(while)`` bodies inside shard_map —
#: loops that can be statically unrolled should be when this is set
#: (see cross.aca_lowrank).
LEGACY_SHARD_MAP = not hasattr(jax, "shard_map")


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax < 0.5: experimental namespace, check_vma spelled check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f=None, /, *, mesh, in_specs, out_specs,
                  check_vma: bool = True, **kw):
        """``jax.shard_map`` signature adapter over the experimental API.

        ``check_vma`` (varying-manual-axes checking) is the renamed
        ``check_rep``; axis semantics are identical for the SPMD
        programs this repo builds (no auto axes used).
        """
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma,
                              **kw)


def named_scope(name: str):
    """``jax.named_scope`` under any supported version; inert otherwise.

    A trace-time name-stack annotation: every op traced inside the
    context carries ``name`` as a prefix, so xprof/TensorBoard traces
    show the fused steppers as named regions (exchange start/finish,
    interior vs band RHS, RK stages, TT sweeps) instead of anonymous
    custom-call soup.  Zero runtime cost — the name lives in HLO
    metadata only — and a no-op context if the API is ever absent, so
    annotated code never gains a hard version dependency.
    """
    ns = getattr(jax, "named_scope", None)
    if ns is None:  # pragma: no cover - every supported jax has it
        return contextlib.nullcontext()
    return ns(name)


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams(**kwargs)`` under either spelling."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
