"""Tracer advection on the cubed sphere (the deck's cosine-bell demo).

Rebuild of the reference's advection demonstration — "Cosine Bell
Advection ... PLR 2nd-Order ... Cartesian Velocity Exchange" (deck p.13,
p.18; SURVEY.md §3.5) — as a real model: flux-form FV transport of a
scalar by a prescribed (analytic, ghost-exact) Cartesian wind, PLR or PPM
reconstruction, SSPRK3, everything under one ``jit``.  Williamson TC1 is
this model with the solid-body wind.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..geometry.cubed_sphere import CubedSphereGrid
from ..ops.fv import flux_divergence
from .base import Model, State

__all__ = ["TracerAdvection"]


class TracerAdvection(Model):
    def __init__(
        self,
        grid: CubedSphereGrid,
        wind_ext,
        scheme: str = "plr",
        limiter: str = "mc",
    ):
        """``wind_ext``: Cartesian wind (3, 6, M, M) valid in ghosts
        (prescribed winds are evaluated analytically there, so no vector
        exchange is needed; for dynamic winds see the SWE model)."""
        super().__init__(grid)
        if scheme == "ppm" and grid.halo < 3:
            raise ValueError("PPM advection needs a grid built with halo >= 3")
        self.wind_ext = wind_ext
        self.scheme = scheme
        self.limiter = limiter

    def initial_state(self, q_ext) -> State:
        return {"q": self.grid.interior(q_ext)}

    def rhs(self, state: State, t) -> State:
        q_ext = self.fill(state["q"])
        dq = -flux_divergence(
            self.grid, q_ext, self.wind_ext, scheme=self.scheme, limiter=self.limiter
        )
        return {"q": dq}
