"""Shallow-water equations on the cubed sphere — the flagship solver.

The reference framework's end goal: "FV Cubed-Sphere Shallow Water Solver"
(``/root/reference/README.md:4``; deck p.4-7; SURVEY.md §2.2 "FV-PLR
numerics ... SWE").  The reference ships no numerics; this is a TPU-first
design:

  * **Vector-invariant form with Cartesian 3-vector velocity**:
    dh/dt = -div(h v),
    dv/dt = -(zeta + f) k x v - grad(g (h + b) + |v|^2 / 2),
    with v kept tangent to the sphere by projection.  Carrying velocity as
    a Cartesian vector makes panel-edge exchange a plain componentwise
    copy — the reference's proven "Cartesian Velocity Exchange" (deck
    p.18) — and removes all panel-edge rotation special cases from the hot
    loop.  (A great-circle-rotation exchange for panel-local (u,v)
    components is provided separately in
    :mod:`jaxstream.parallel.vector_halo` for parity with the north-star
    formulation.)
  * **Two halo exchanges per RHS** (h and v); the Bernoulli function
    g(h+b)+K is formed on the already-filled extended fields so its
    gradient needs no third exchange.
  * Flux-form continuity with PLR/PPM upwinding -> exact mass
    conservation; vorticity/gradient centered 2nd order.
  * Optional del^4 hyperdiffusion (Galewsky/TC6 need it) via iterated
    conservative Laplacian with a ghost refill between applications.

Everything traces into one XLA computation under the step ``jit``; no
data-dependent Python control flow.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..geometry.cubed_sphere import CubedSphereGrid
from ..ops.fv import (
    flux_divergence,
    gradient,
    kinetic_energy,
    laplacian,
    vorticity,
)
from .base import Model, State

__all__ = ["SWEBase", "ShallowWater"]


def _cross(a, b):
    return jnp.stack([
        a[1] * b[2] - a[2] * b[1],
        a[2] * b[0] - a[0] * b[2],
        a[0] * b[1] - a[1] * b[0],
    ])


class SWEBase(Model):
    """Shared SWE setup: scheme/backend validation, Coriolis, topography.

    Subclasses (Cartesian :class:`ShallowWater`, covariant
    :class:`jaxstream.models.shallow_water_cov.CovariantShallowWater`)
    provide ``_make_pallas_rhs(interpret)`` returning their fused RHS
    callable, or raise if no kernel exists for the formulation.
    """

    def __init__(
        self,
        grid: CubedSphereGrid,
        gravity: float,
        omega: float,
        b_ext: Optional[jnp.ndarray] = None,
        scheme: str = "plr",
        limiter: str = "mc",
        nu4: float = 0.0,
        backend: str = "jnp",
    ):
        super().__init__(grid)
        if scheme == "ppm" and grid.halo < 3:
            raise ValueError("PPM fluxes need a grid built with halo >= 3")
        self.gravity = gravity
        self.omega = omega
        self.scheme = scheme
        self.limiter = limiter
        self.nu4 = nu4
        # backend='pallas' fuses the whole stencil section of the RHS into
        # one TPU kernel per face; 'jnp' is the reference implementation
        # and parity oracle.
        if backend not in ("jnp", "pallas", "pallas_interpret"):
            raise ValueError(f"unknown backend {backend!r}")
        self._pallas_rhs = None
        if backend.startswith("pallas"):
            if grid.sqrtg.dtype != jnp.float32:
                raise ValueError(
                    f"backend='pallas' supports float32 grids only (the TPU "
                    f"kernel is f32); got grid dtype {grid.sqrtg.dtype}. Use "
                    f"backend='jnp' or build the grid with dtype=float32."
                )
            self._pallas_rhs = self._make_pallas_rhs(
                interpret=(backend == "pallas_interpret")
            )
        self.backend = backend
        # Coriolis parameter f = 2 Omega sin(lat) at interior centers.
        self.fcor = 2.0 * omega * jnp.sin(grid.interior(grid.lat))
        # Bottom topography, extended; ghosts must be valid (analytic ICs
        # evaluate there; otherwise we fill them once here).
        if b_ext is None:
            b_ext = jnp.zeros_like(grid.sqrtg)
        self.b_ext = self.exchange(b_ext)

    def _make_pallas_rhs(self, interpret: bool):  # pragma: no cover
        raise NotImplementedError


class ShallowWater(SWEBase):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.khat_int = self.grid.interior(self.grid.khat)

    def _make_pallas_rhs(self, interpret: bool):
        from ..ops.pallas.swe_rhs import make_swe_rhs_pallas

        grid = self.grid
        return make_swe_rhs_pallas(
            grid.n, grid.halo, grid.dalpha, grid.radius,
            self.gravity, self.omega, scheme=self.scheme,
            limiter=self.limiter, interpret=interpret,
        )

    def initial_state(self, h_ext, v_ext) -> State:
        return {
            "h": self.grid.interior(h_ext),
            "v": self.grid.interior(v_ext),
        }

    # -- fused extended-state fast path (TPU) -------------------------------
    def extend_state(self, state: State, with_strips: bool = False) -> State:
        """Interior state -> extended state (ghosts zeroed; filled on use).

        ``with_strips=True`` adds the canonical edge-strip carry
        (``sh``/``sv``) used by the in-kernel-exchange stepper.
        """
        from ..ops.fv import embed_interior

        g = self.grid
        y = {k: embed_interior(g, v) for k, v in state.items()}
        if with_strips:
            from ..ops.pallas.swe_step import raw_strips

            y["sh_sn"], y["sh_we"] = raw_strips(y["h"], g.n, g.halo)
            y["sv_sn"], y["sv_we"] = raw_strips(y["v"], g.n, g.halo)
        return y

    def restrict_state(self, y_ext: State) -> State:
        """Extended state -> interior state (strip carries dropped)."""
        return {k: self.grid.interior(v) for k, v in y_ext.items()
                if k in ("h", "v")}

    def make_fused_step(self, dt: float, in_kernel_exchange: bool = True):
        """SSPRK3 step over *extended* state, one fused kernel per stage.

        Each stage reads the ghost-filled state once from HBM and writes
        the combined next-stage state once (RHS + stage axpy fused in
        VMEM; :mod:`jaxstream.ops.pallas.swe_step`) — the minimum-traffic
        formulation of the step for the memory-bound FV numerics (deck
        p.19).  With ``in_kernel_exchange`` (default) the halo fill also
        happens inside the kernel via the strip carry (state pytree
        ``{"h","v","sh_sn","sh_we","sv_sn","sv_we"}``; build with
        ``extend_state(state, with_strips=True)``); otherwise a
        concat-layout jnp exchange runs between kernels.  Requires
        ``backend='pallas'`` and ``nu4 == 0`` (the hyperdiffusion refill
        pattern is a different dataflow); use :meth:`make_step` otherwise.
        """
        if self._pallas_rhs is None:
            raise ValueError("make_fused_step requires backend='pallas'")
        if self.nu4 != 0.0:
            raise ValueError("make_fused_step does not support nu4 > 0")
        g = self.grid
        interpret = self.backend == "pallas_interpret"
        if in_kernel_exchange:
            from ..ops.pallas.swe_step import make_fused_ssprk3_step_inkernel

            return make_fused_ssprk3_step_inkernel(
                g.n, g.halo, g.dalpha, g.radius, self.gravity, self.omega,
                dt, self.b_ext, scheme=self.scheme, limiter=self.limiter,
                interpret=interpret,
            )
        from ..ops.pallas.swe_step import make_fused_ssprk3_step
        from ..parallel.halo import make_concat_exchanger

        # Concat-layout exchange: one read + one write per field instead
        # of a 48-update scatter chain (the dominant cost once the RHS and
        # stage combination are fused).
        exchange = make_concat_exchanger(g.n, g.halo)
        return make_fused_ssprk3_step(
            g.n, g.halo, g.dalpha, g.radius, self.gravity, self.omega,
            dt, exchange, self.b_ext,
            scheme=self.scheme, limiter=self.limiter,
            interpret=interpret,
        )

    def _hyperdiffuse(self, q_ext):
        """-nu4 del^4 q (interior), with a ghost refill between Laplacians."""
        l1 = laplacian(self.grid, q_ext)
        return -self.nu4 * laplacian(self.grid, self.fill(l1))

    def rhs(self, state: State, t) -> State:
        grid = self.grid
        k = self.khat_int

        h_ext = self.fill(state["h"])
        v_ext = self.fill(state["v"])

        if self._pallas_rhs is not None:
            dh, dv = self._pallas_rhs(h_ext, v_ext, self.b_ext)
            if self.nu4 > 0.0:
                dh = dh + self._hyperdiffuse(h_ext)
                dv_hyp = self._hyperdiffuse(v_ext)
                kk = self.khat_int
                dv_hyp = dv_hyp - kk * jnp.sum(dv_hyp * kk, axis=0)
                dv = dv + dv_hyp
            return {"h": dh, "v": dv}

        # Continuity: dh/dt = -div(h v).
        dh = -flux_divergence(
            grid, h_ext, v_ext, scheme=self.scheme, limiter=self.limiter
        )

        # Momentum, vector-invariant.
        zeta = vorticity(grid, v_ext)
        bern_ext = (
            self.gravity * (h_ext + self.b_ext) + kinetic_energy(v_ext)
        )
        grad_b = gradient(grid, bern_ext)

        v_int = grid.interior(v_ext)
        # Tangentialize before use so any radial drift cannot feed back.
        v_int = v_int - k * jnp.sum(v_int * k, axis=0)
        kxv = _cross(k, v_int)
        dv = -(zeta + self.fcor) * kxv - grad_b

        if self.nu4 > 0.0:
            dh = dh + self._hyperdiffuse(h_ext)
            # Batched over the component axis (laplacian/exchange operate on
            # trailing axes).  Componentwise Laplacian of a tangent field is
            # not tangent on the sphere — add BEFORE the projection below.
            dv = dv + self._hyperdiffuse(v_ext)

        # Project the full tendency onto the tangent plane.
        dv = dv - k * jnp.sum(dv * k, axis=0)
        return {"h": dh, "v": dv}
