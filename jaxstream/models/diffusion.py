"""Thermal diffusion on the cubed sphere (the deck's "Lima Flag" demo).

Rebuild of the reference's first sharded demonstration — checkerboard heat
source on the top panel, 1-1000 K, integrated for weeks; "Proof that
sharding works" (deck p.12, p.17; SURVEY.md §3.5).  dT/dt = kappa lap(T)
with the conservative Laplace-Beltrami operator.
"""

from __future__ import annotations

from ..geometry.cubed_sphere import CubedSphereGrid
from ..ops.fv import laplacian
from .base import Model, State

__all__ = ["ThermalDiffusion"]


class ThermalDiffusion(Model):
    def __init__(self, grid: CubedSphereGrid, kappa: float):
        super().__init__(grid)
        self.kappa = kappa

    def initial_state(self, t_ext) -> State:
        return {"T": self.grid.interior(t_ext)}

    def rhs(self, state: State, t) -> State:
        t_ext = self.fill(state["T"])
        return {"T": self.kappa * laplacian(self.grid, t_ext)}
