"""Model base: the solver-orchestration layer.

The reference implies an unseen driver class holding ``self.config``,
``self.mesh``, ``self.sharding`` whose ``setup_sharding`` method survives
in the snippets (``/root/reference/JAX-DevLab-Examples.py:19-21,78-79``;
SURVEY.md §2.2 "Solver orchestration class").  This is its rebuilt form:
a model owns the grid, the halo exchanger, and a pure ``rhs``; stepping and
multi-step integration live in :mod:`jaxstream.stepping` and are composed
here under a single top-level ``jit``.

State is a plain dict pytree of interior arrays ``(6, n, n)`` (scalars) /
``(3, 6, n, n)`` (Cartesian vectors) — jit/scan/checkpoint friendly.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax

from ..geometry.cubed_sphere import CubedSphereGrid
from ..parallel.halo import make_halo_exchanger
from ..stepping import SCHEMES, integrate, integrate_with_history

State = Dict[str, jax.Array]


class Model:
    """Base class wiring grid + halo exchange + stepping together."""

    def __init__(self, grid: CubedSphereGrid):
        self.grid = grid
        self.exchange = make_halo_exchanger(grid.n, grid.halo)
        self._run_cache: dict = {}

    # -- subclasses implement ------------------------------------------------
    def rhs(self, state: State, t) -> State:  # pragma: no cover - interface
        raise NotImplementedError

    # -- shared machinery ----------------------------------------------------
    def fill(self, interior):
        """Embed an interior array and fill its ghosts (scalar or vector)."""
        from ..ops.fv import embed_interior

        return self.exchange(embed_interior(self.grid, interior))

    def make_step(self, dt: float, scheme: str = "ssprk3") -> Callable:
        stepper = SCHEMES[scheme]

        def step(state, t):
            return stepper(self.rhs, state, t, dt)

        return step

    def run(
        self,
        state: State,
        nsteps: int,
        dt: float,
        t0: float = 0.0,
        scheme: str = "ssprk3",
        history_stride: int = 0,
        snapshot: Optional[Callable] = None,
    ):
        """Integrate ``nsteps`` under one compiled call.

        Returns ``(state, t)`` or ``(state, t, history)`` if
        ``history_stride > 0``.
        """
        # Cache the compiled integrator: a fresh jit per call would retrace
        # and recompile the whole loop every run() (restarts, sweeps).
        # Keying on the snapshot object itself (not id()) keeps a strong
        # reference, so a freed-and-reallocated callable can't alias a key.
        # t0 is a *traced* argument, not part of the key: resuming a
        # segmented run at a new start time reuses the compiled loop.
        key = (nsteps, dt, scheme, history_stride, snapshot)
        fn = self._run_cache.get(key)
        if fn is None:
            step = self.make_step(dt, scheme)
            if history_stride > 0:
                snap = snapshot or (lambda s: s)
                fn = jax.jit(
                    lambda y, t: integrate_with_history(
                        step, y, t, nsteps, dt, history_stride, snap
                    )
                )
            else:
                fn = jax.jit(lambda y, t: integrate(step, y, t, nsteps, dt))
            self._run_cache[key] = fn
        return fn(state, t0)
