"""Covariant-component shallow water — the flop-lean TPU formulation.

Same PDE and FV discretization family as :class:`ShallowWater` (the
reference's end goal, ``/root/reference/README.md:4``, deck p.4-7), with
velocity carried as *panel-local covariant components* ``(u_a, u_b) =
(v.e_a, v.e_b)`` instead of a Cartesian 3-vector:

    dh/dt  = -(1/sqrtg) [ d_a(sqrtg u^a h*) + d_b(sqrtg u^b h*) ]
    du_a/dt =  (zeta + f) sqrtg u^b - d_a(g (h + b) + K)
    du_b/dt = -(zeta + f) sqrtg u^a - d_b(g (h + b) + K)

with ``u^i = g^ij u_j``, ``K = (u^a u_a + u^b u_b)/2`` and
``zeta = (d_a u_b - d_b u_a)/sqrtg``.  The vector-invariant form needs no
Christoffel symbols, and two prognostic velocity fields replace three:
25% less state HBM traffic and none of the 3-vector basis dot products,
cross products, or tangent-plane projections of the Cartesian path — the
trade is a 2x2 rotation at panel edges, applied only to halo strips
(:func:`jaxstream.parallel.vector_halo.make_vector_halo_exchanger` with
``components='covariant'``; the north-star "rotation form" exchange,
SURVEY.md §2.2).

Both formulations solve the same equations with the same reconstruction
and differ only in velocity representation; agreement is to truncation
error, verified in tests/test_cov_swe.py (TC2 L2-error parity with the
Cartesian model).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..geometry.cubed_sphere import CubedSphereGrid
from ..ops.fv import (
    covariant_components,
    covariant_face_normal_velocity,
    embed_interior,
    flux_divergence_faces,
    laplacian,
    vorticity_cov,
)
from ..parallel.vector_halo import make_vector_halo_exchanger
from .base import State
from .shallow_water import SWEBase

__all__ = ["CovariantShallowWater", "ENSEMBLE_STATE_AXES",
           "ENSEMBLE_CARRY_AXES"]

#: Member-axis position per leaf of the batched interior state
#: ``{"h": (B, 6, n, n), "u": (2, B, 6, n, n)}`` (u's component axis
#: precedes the member axis so the trailing (6, n, n) layout every
#: face-indexed consumer assumes is preserved).
ENSEMBLE_STATE_AXES = {"h": 0, "u": 1}
#: Ditto for the batched compact fused-stepper carry.
ENSEMBLE_CARRY_AXES = {"h": 0, "u": 1, "strips_sn": 0, "strips_we": 0}


class CovariantShallowWater(SWEBase):
    """State ``{"h": (6, n, n), "u": (2, 6, n, n)}``, u covariant."""

    #: make_fused_step handles nu4 > 0 (two-kernel del^4 stage pair);
    #: Simulation's fused-path gate reads this capability flag.
    fused_supports_nu4 = True

    def __init__(
        self,
        grid: CubedSphereGrid,
        gravity: float,
        omega: float,
        b_ext: Optional[jnp.ndarray] = None,
        scheme: str = "plr",
        limiter: str = "mc",
        nu4: float = 0.0,
        backend: str = "jnp",
    ):
        super().__init__(
            grid, gravity, omega, b_ext=b_ext, scheme=scheme,
            limiter=limiter, nu4=nu4, backend=backend,
        )
        self.exchange_u = make_vector_halo_exchanger(
            grid, components="covariant"
        )
        # Cell-center inverse metric on the extended grid, from the exact
        # dual-basis identity g^ij = a^i . a^j (works for eager and lazy
        # grids; three (6, M, M) scalars).
        self.ginv_aa = jnp.sum(grid.a_a * grid.a_a, axis=0)
        self.ginv_ab = jnp.sum(grid.a_a * grid.a_b, axis=0)
        self.ginv_bb = jnp.sum(grid.a_b * grid.a_b, axis=0)

    def _make_pallas_rhs(self, interpret: bool):
        from ..ops.pallas.swe_cov import make_cov_rhs_pallas

        return make_cov_rhs_pallas(
            self.grid, self.gravity, self.omega, scheme=self.scheme,
            limiter=self.limiter, interpret=interpret,
        )

    # -- fused extended-state fast path (TPU) -------------------------------
    def extend_state(self, state: State, with_strips: bool = False) -> State:
        """Interior state -> extended state for the fused stepper."""
        g = self.grid
        y = {k: embed_interior(g, v) for k, v in state.items()}
        if with_strips:
            from ..ops.pallas.swe_cov import pack_strips_cov

            y["strips"] = pack_strips_cov(y["h"], y["u"], g.n, g.halo)
        return y

    def restrict_state(self, y_ext: State) -> State:
        g = self.grid
        out = {}
        for k, v in y_ext.items():
            if k not in ("h", "u"):
                continue
            out[k] = g.interior(v) if v.shape[-1] == g.m else v
        return out

    def compact_state(self, state: State) -> State:
        """Interior state -> the compact fused-stepper carry."""
        from ..ops.pallas.swe_cov import pack_strips_cov_split

        g = self.grid
        sn, we = pack_strips_cov_split(state["h"], state["u"], g.n, g.halo)
        return {"h": state["h"], "u": state["u"],
                "strips_sn": sn, "strips_we": we}

    @staticmethod
    def stack_ensemble(states) -> State:
        """A list of interior states -> one batched ensemble state
        ``{"h": (B, 6, n, n), "u": (2, B, 6, n, n)}`` (member-axis
        layout per :data:`ENSEMBLE_STATE_AXES`)."""
        return {"h": jnp.stack([s["h"] for s in states], axis=0),
                "u": jnp.stack([s["u"] for s in states], axis=1)}

    def member_state(self, batched: State, i: int) -> State:
        """One member's interior state out of a batched ensemble state."""
        return {"h": batched["h"][i], "u": batched["u"][:, i]}

    def ensemble_compact_state(self, batched: State) -> State:
        """Batched interior state -> the batched compact carry.

        The strip pack runs on the member axis folded into the face
        axis ((B, 6, ...) -> (B*6, ...) contiguous reshape) — the same
        layout trick the batched stage kernels use — then unfolds, so
        each member's strips are bitwise the unbatched pack's.
        """
        from ..ops.pallas.swe_cov import pack_strips_cov_split

        g = self.grid
        h, u = batched["h"], batched["u"]
        B = h.shape[0]
        sn, we = pack_strips_cov_split(
            h.reshape((B * 6,) + h.shape[2:]),
            u.reshape((2, B * 6) + u.shape[3:]), g.n, g.halo)
        return {"h": h, "u": u,
                "strips_sn": sn.reshape((B, 6) + sn.shape[1:]),
                "strips_we": we.reshape((B, 6) + we.shape[1:])}

    def encode_carry(self, y: State, carry_dtype=None,
                     h_offset: float = 0.0, h_scale: float = 1.0,
                     u_scale: float = 1.0) -> State:
        """Cast a :meth:`compact_state` carry to the stepper's storage
        encoding (per-field dtype; h stored as anomaly about
        ``h_offset``, u divided by ``u_scale``)."""
        import jax.numpy as jnp

        if carry_dtype is None:
            if h_offset or h_scale != 1.0 or u_scale != 1.0:
                # f32 storage with an anomaly/scale encoding is legal in
                # the stepper — encode it rather than silently skipping.
                carry_dtype = jnp.float32
            else:
                return y
        dt_h, dt_u = (tuple(carry_dtype)
                      if isinstance(carry_dtype, (tuple, list))
                      else (carry_dtype,) * 2)
        def enc(x, off, scale, dt):
            if off:
                x = x - jnp.float32(off)
            if scale != 1.0:
                x = x / jnp.float32(scale)
            if jnp.issubdtype(jnp.dtype(dt), jnp.integer):
                return jnp.round(x).astype(dt)
            return x.astype(dt)

        out = dict(y)
        out["h"] = enc(y["h"], h_offset, h_scale, dt_h)
        out["u"] = enc(y["u"], 0.0, u_scale, dt_u)
        return out

    def decode_carry(self, y: State, h_offset: float = 0.0,
                     h_scale: float = 1.0, u_scale: float = 1.0) -> State:
        """Inverse of :meth:`encode_carry`: back to absolute f32."""
        import jax.numpy as jnp

        def dec(x, off, scale):
            x = x.astype(jnp.float32)
            if scale != 1.0:
                x = x * jnp.float32(scale)
            return x + jnp.float32(off) if off else x

        out = dict(y)
        out["h"] = dec(y["h"], h_offset, h_scale)
        out["u"] = dec(y["u"], 0.0, u_scale)
        return out

    def make_fused_step(self, dt: float, compact: bool = True,
                        carry_dtype=None, h_offset: float = 0.0,
                        h_scale: float = 1.0, u_scale: float = 1.0,
                        _ablate_seam: bool = False,
                        nu4_mode: str = "split",
                        temporal_block: int = 1,
                        ensemble: int = 0,
                        ensemble_impl: str = "kernel",
                        precision=None):
        """Fused SSPRK3: one Pallas kernel per stage (halo fill in-kernel,
        edge rotations/symmetrization on a packed strip carry,
        :mod:`jaxstream.ops.pallas.swe_cov`).  ``compact=True`` (the
        production path) carries interior-only fields — initialise with
        :meth:`compact_state`; ``compact=False`` keeps the extended-state
        carry from :meth:`extend_state` ``(with_strips=True)``.
        ``nu4 > 0`` (the Galewsky filter) uses the split once-per-step
        del^4 filter kernel (``nu4_mode='split'``, round 5 — 1.9x the
        in-stage pair, same day-6 physics) or the in-stage two-kernel
        pair (``nu4_mode='stage'``, the round-4 path, kept as the
        parity oracle); compact carry only.  Requires
        ``backend='pallas'``.

        ``carry_dtype`` (compact only): HBM storage dtype of the h/u
        carry — cast the :meth:`compact_state` output to match.  bf16
        halves carry DMA; compute stays f32 (accuracy trade measured in
        DESIGN.md).  ``_ablate_seam`` disables seam imposition — for
        perf measurement only (breaks conservation).

        ``temporal_block = k > 1``: the returned step advances k fused
        SSPRK3 steps per call (``parallelization.temporal_block``) —
        bitwise-identical to k separate calls on every path (the strip
        routes are face-local on one device), with a ``steps_per_call``
        attribute so integrators can account for it.

        ``ensemble = B > 0``: the step runs B perturbed-IC members per
        call over the batched compact carry (member-axis layout
        :data:`ENSEMBLE_CARRY_AXES`; initialise with
        :meth:`ensemble_compact_state`).  ``ensemble_impl`` picks the
        execution strategy: ``'kernel'`` (production) folds the member
        axis into the stage kernels' grid — one launch per stage for
        the whole ensemble (:func:`...make_fused_ssprk3_cov_compact`
        with ``ensemble=B``); ``'vmap'`` is the vmapped reference path
        (B per-member kernel launches, bitwise the same values) kept as
        the parity oracle and the portability fallback.  Compact carry
        and nu4 = 0 only.

        ``precision`` (round 10, ``jaxstream.ops.pallas.precision``):
        the per-stage dtype policy — ``'bf16'`` runs the
        flux/reconstruction/router arithmetic in bfloat16 with f32
        accumulators and metric terms and stores the inter-stage strips
        bf16; ``None``/``'f32'`` is bitwise today's path.  Composes
        with ``temporal_block``, ``ensemble``, the carry encodings
        (``carry_dtype`` — storage — is orthogonal to ``precision`` —
        arithmetic — and the two stack), and the split/refused nu4
        modes; the ``'stage'`` nu4 oracle and the extended
        (``compact=False``) carry reject 16-bit strips with pointers.

        ``nu4_mode='refused'`` (round 10): the del^4 filter fused into
        the stage-1 kernel — 3 kernels + 3 routes per step vs the
        split form's 4 + 4, trajectories equal to split up to one
        filter application at the endpoints (O(damp); Galewsky day-6
        physics is the equivalence gate, same standard as
        split-vs-stage).  Composes with ``temporal_block`` and
        ``precision``; filter-cycling (``interval``) stays on 'split'.
        """
        from ..ops.pallas.precision import resolve_stage_precision
        from ..plan import rules as plan_rules

        if self._pallas_rhs is None:
            raise ValueError("make_fused_step requires backend='pallas'")
        if nu4_mode not in ("split", "stage", "refused"):
            raise ValueError(f"nu4_mode must be 'split', 'stage' or "
                             f"'refused', got {nu4_mode!r}")
        precision = resolve_stage_precision(precision)
        if temporal_block < 1:
            raise ValueError(
                f"temporal_block must be >= 1, got {temporal_block}")
        if ensemble < 0:
            raise ValueError(f"ensemble must be >= 0, got {ensemble}")
        if ensemble:
            if ensemble_impl not in ("kernel", "vmap"):
                raise ValueError(f"ensemble_impl must be 'kernel' or "
                                 f"'vmap', got {ensemble_impl!r}")
            if not compact:
                raise ValueError(
                    "ensemble > 0 requires the compact carry (the "
                    "extended-state stepper has no batched form)")
            if self.nu4 != 0.0:
                plan_rules.fail("fused-ensemble-nu4")
            if carry_dtype is not None:
                # Deliberate round-16 tightening: the batched carry
                # has no encode/decode plumbing or parity coverage —
                # reject the pair explicitly (the same rule plan_for
                # rejects the config with) instead of building an
                # untested composition.
                plan_rules.fail("carry-needs-single-member")
        interpret = self.backend == "pallas_interpret"

        def _proofed(step):
            from ..plan.plan import CapabilityPlan
            from ..plan.proof import attach_proof

            if carry_dtype is None:
                carry = "f32"
            else:
                dts = (tuple(carry_dtype)
                       if isinstance(carry_dtype, (tuple, list))
                       else (carry_dtype,))
                carry = ("mixed16" if any(
                    jnp.issubdtype(jnp.dtype(d), jnp.integer)
                    for d in dts) else "bf16")
            return attach_proof(step, plan_rules.normalize(
                CapabilityPlan(
                    tier="fused", n=self.grid.n, halo=self.grid.halo,
                    temporal_block=temporal_block,
                    ensemble=max(1, ensemble),
                    stage=("bf16" if precision is not None
                           and precision.compute == "bf16" else "f32"),
                    strips=("bf16" if precision is not None
                            and precision.strips == "bf16" else "f32"),
                    carry=carry,
                    nu4=self.nu4 != 0.0, nu4_mode=nu4_mode,
                    backend="pallas", covariant=True)))

        def _blocked(step1):
            if temporal_block == 1:
                return step1
            from ..stepping import blocked

            step = blocked(step1, temporal_block, dt)
            step.steps_per_call = temporal_block
            return step
        if self.nu4 != 0.0:
            if not compact:
                raise ValueError("nu4 > 0 requires the compact carry")
            if (carry_dtype is not None or h_offset or h_scale != 1.0
                    or u_scale != 1.0 or _ablate_seam):
                plan_rules.fail("nu4-no-carry-encoding")
            if nu4_mode == "stage" and precision is not None:
                plan_rules.fail("nu4-stage-oracle-f32")
            from ..ops.pallas.swe_cov import (
                make_fused_ssprk3_cov_nu4,
                make_fused_ssprk3_cov_refused_nu4,
                make_fused_ssprk3_cov_split_nu4)

            if nu4_mode == "refused":
                return _proofed(_blocked(make_fused_ssprk3_cov_refused_nu4(
                    self.grid, self.gravity, self.omega, dt, self.b_ext,
                    self.nu4, scheme=self.scheme, limiter=self.limiter,
                    interpret=interpret, precision=precision,
                )))
            if nu4_mode == "split":
                return _proofed(_blocked(make_fused_ssprk3_cov_split_nu4(
                    self.grid, self.gravity, self.omega, dt, self.b_ext,
                    self.nu4, scheme=self.scheme, limiter=self.limiter,
                    interpret=interpret, precision=precision,
                )))
            return _proofed(_blocked(make_fused_ssprk3_cov_nu4(
                self.grid, self.gravity, self.omega, dt, self.b_ext,
                self.nu4, scheme=self.scheme, limiter=self.limiter,
                interpret=interpret,
            )))
        from ..ops.pallas.swe_cov import (
            make_fused_ssprk3_cov_inkernel, make_fused_ssprk3_cov_multistep)

        if compact:
            import jax.numpy as jnp

            kernel_ensemble = ensemble if ensemble_impl == "kernel" else 0
            step = make_fused_ssprk3_cov_multistep(
                self.grid, self.gravity, self.omega, dt, self.b_ext,
                temporal_block,
                scheme=self.scheme, limiter=self.limiter,
                interpret=interpret,
                carry_dtype=(jnp.float32 if carry_dtype is None
                             else carry_dtype),
                h_offset=h_offset, h_scale=h_scale, u_scale=u_scale,
                seam=not _ablate_seam, ensemble=kernel_ensemble,
                precision=precision,
            )
            if ensemble and ensemble_impl == "vmap":
                from ..stepping import vmap_ensemble

                step = vmap_ensemble(step, ENSEMBLE_CARRY_AXES)
                step.ensemble = ensemble
            if temporal_block > 1:
                step.steps_per_call = temporal_block
            return _proofed(step)
        if (carry_dtype is not None or h_offset or h_scale != 1.0
                or u_scale != 1.0 or _ablate_seam):
            raise ValueError("carry_dtype/h_offset/u_scale/_ablate_seam "
                             "require the compact carry")
        return _proofed(_blocked(make_fused_ssprk3_cov_inkernel(
            self.grid, self.gravity, self.omega, dt, self.b_ext,
            scheme=self.scheme, limiter=self.limiter,
            interpret=interpret, precision=precision,
        )))

    def initial_state(self, h_ext, v_ext) -> State:
        """From extended Cartesian fields (the IC functions' output)."""
        return {
            "h": self.grid.interior(h_ext),
            "u": self.grid.interior(covariant_components(self.grid, v_ext)),
        }

    def to_cartesian(self, state: State):
        """Interior covariant velocity -> Cartesian (3, 6, n, n)."""
        g = self.grid
        iaa, iab, ibb = (g.interior(self.ginv_aa), g.interior(self.ginv_ab),
                         g.interior(self.ginv_bb))
        ua = iaa * state["u"][0] + iab * state["u"][1]
        ub = iab * state["u"][0] + ibb * state["u"][1]
        return (ua[None] * g.interior(g.e_a)
                + ub[None] * g.interior(g.e_b))

    def _fill_u(self, u_int):
        return self.exchange_u(embed_interior(self.grid, u_int))

    def rhs(self, state: State, t) -> State:
        grid = self.grid
        h_ext = self.fill(state["h"])
        u_ext = self._fill_u(state["u"])

        if self._pallas_rhs is not None:
            dh, du = self._pallas_rhs(h_ext, u_ext, self.b_ext)
        else:
            # Contravariant components and kinetic energy on the extended
            # grid (B's centered gradient reads one ghost deep).
            uc_a = self.ginv_aa * u_ext[0] + self.ginv_ab * u_ext[1]
            uc_b = self.ginv_ab * u_ext[0] + self.ginv_bb * u_ext[1]
            ke = 0.5 * (uc_a * u_ext[0] + uc_b * u_ext[1])

            ux, uy = covariant_face_normal_velocity(grid, u_ext)
            dh = -flux_divergence_faces(
                grid, h_ext, ux, uy, scheme=self.scheme, limiter=self.limiter
            )

            zeta = vorticity_cov(grid, u_ext)
            bern = self.gravity * (h_ext + self.b_ext) + ke
            h_, n, d = grid.halo, grid.n, grid.dalpha
            dba = (bern[..., h_:h_ + n, h_ + 1:h_ + n + 1]
                   - bern[..., h_:h_ + n, h_ - 1:h_ + n - 1]) / (2 * d)
            dbb = (bern[..., h_ + 1:h_ + n + 1, h_:h_ + n]
                   - bern[..., h_ - 1:h_ + n - 1, h_:h_ + n]) / (2 * d)

            absv = (zeta + self.fcor) * grid.interior(grid.sqrtg)
            dua = absv * grid.interior(uc_b) - dba
            dub = -absv * grid.interior(uc_a) - dbb
            du = jnp.stack([dua, dub])

        if self.nu4 > 0.0:
            l1h = laplacian(grid, h_ext)
            dh = dh - self.nu4 * laplacian(grid, self.fill(l1h))
            l1u = laplacian(grid, u_ext)
            du = du - self.nu4 * laplacian(grid, self._fill_u(l1u))
        return {"h": dh, "u": du}
