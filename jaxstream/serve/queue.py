"""Bounded scenario-request queue with admission control.

The queue is the server's backpressure surface: capacity is a hard
bound (``submit`` raises :class:`QueueFull` — or blocks, for callers
that want producer-side flow control) so a traffic burst shows up as
rejected admissions, never as unbounded host memory.  Group-aware pops
(:meth:`RequestQueue.pop_group`) keep FIFO order *within* a batching
group while letting the server refill a batch with packable requests
only — requests of the other group keep their queue position.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from .request import ScenarioRequest

__all__ = ["AdmissionRefused", "QueueFull", "RequestQueue"]


class QueueFull(RuntimeError):
    """submit() on a queue at capacity (non-blocking admission)."""


class AdmissionRefused(RuntimeError):
    """The server refused the request (health-driven admission
    control: too many guard events — see ``serve.max_guard_events``)."""


class RequestQueue:
    """FIFO of :class:`ScenarioRequest` with a hard capacity bound.

    Thread-safe: the CLI/benchmark submit from the main thread while a
    server drains, and tests hammer it from worker threads.  ``pop`` /
    ``pop_group`` are non-blocking (the serving loop polls at segment
    boundaries — its natural cadence — rather than parking a thread).
    """

    def __init__(self, capacity: int = 64):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def depth(self) -> int:
        return len(self)

    def submit(self, req: ScenarioRequest, block: bool = False,
               timeout: Optional[float] = None) -> None:
        """Enqueue; at capacity raise :class:`QueueFull` (default) or
        block until a slot frees (``block=True``)."""
        with self._not_full:
            if len(self._q) >= self.capacity:
                if not block:
                    raise QueueFull(
                        f"request queue at capacity {self.capacity}; "
                        "retry later (admission control)")
                if not self._not_full.wait_for(
                        lambda: len(self._q) < self.capacity,
                        timeout=timeout):
                    raise QueueFull(
                        f"request queue still at capacity "
                        f"{self.capacity} after {timeout}s")
            self._q.append(req)

    def pop(self) -> Optional[ScenarioRequest]:
        """Oldest request, or None when empty."""
        with self._not_full:
            if not self._q:
                return None
            req = self._q.popleft()
            self._not_full.notify()
            return req

    def pop_group(self, group: str) -> Optional[ScenarioRequest]:
        """Oldest request of one batching group (None if none queued).

        Requests of other groups keep their positions — group-local
        FIFO, which is what makes the refill order deterministic for a
        given submission order.
        """
        with self._not_full:
            for i, req in enumerate(self._q):
                if req.group == group:
                    del self._q[i]
                    self._not_full.notify()
                    return req
            return None
