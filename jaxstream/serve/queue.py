"""Bounded scenario-request queue with admission control.

The queue is the server's backpressure surface: capacity is a hard
bound (``submit`` raises :class:`QueueFull` — or blocks, for callers
that want producer-side flow control) so a traffic burst shows up as
rejected admissions, never as unbounded host memory.

Since round 12 the default server packs EVERY family into one batch
(orography rides as a traced per-member field), so the common pop is
strict queue-wide FIFO.  Group-aware pops (``pop(group=...)`` /
:meth:`RequestQueue.pop_group`) remain for the
``serve.group_by_orography: true`` parity mode: FIFO *within* a
batching group, letting the server refill a batch with packable
requests only while requests of the other group keep their queue
position.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import List, Optional

from ..obs import flight
from .request import ScenarioRequest

__all__ = ["AdmissionRefused", "QueueFull", "RequestQueue",
           "ServerDraining"]


class QueueFull(RuntimeError):
    """submit() on a queue at capacity (non-blocking admission)."""


class AdmissionRefused(RuntimeError):
    """The server refused the request (health-driven admission
    control: too many guard events — see ``serve.max_guard_events``)."""


class ServerDraining(AdmissionRefused):
    """submit() on a server that began its graceful drain (round 14):
    admissions are closed while in-flight members run to their final
    step.  Subclasses :class:`AdmissionRefused` so existing callers
    treating any refusal uniformly keep working; the gateway maps it
    to a typed 503 ``draining``."""


class RequestQueue:
    """FIFO of :class:`ScenarioRequest` with a hard capacity bound.

    Thread-safe: the CLI/benchmark submit from the main thread while a
    server drains, and tests hammer it from worker threads.  ``pop`` /
    ``pop_group`` are non-blocking (the serving loop polls at segment
    boundaries — its natural cadence — rather than parking a thread).
    """

    def __init__(self, capacity: int = 64):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def depth(self) -> int:
        return len(self)

    def submit(self, req: ScenarioRequest, block: bool = False,
               timeout: Optional[float] = None) -> None:
        """Enqueue; at capacity raise :class:`QueueFull` (default) or
        block until a slot frees (``block=True``)."""
        with self._not_full:
            if len(self._q) >= self.capacity:
                if not block:
                    raise QueueFull(
                        f"request queue at capacity {self.capacity}; "
                        "retry later (admission control)")
                if not self._not_full.wait_for(
                        lambda: len(self._q) < self.capacity,
                        timeout=timeout):
                    raise QueueFull(
                        f"request queue still at capacity "
                        f"{self.capacity} after {timeout}s")
            self._q.append(req)
            depth = len(self._q)
        flight.record("queue.admit", id=req.id, depth=depth)

    def pop(self, group: Optional[str] = None) -> Optional[ScenarioRequest]:
        """Oldest request, or None when empty.

        ``group`` restricts the pop to one batching group (the
        ``group_by_orography: true`` parity mode): requests of other
        groups keep their positions — group-local FIFO, which is what
        makes the refill order deterministic for a given submission
        order.  ``None`` (the mixed-orography default) is strict
        queue-wide FIFO.
        """
        with self._not_full:
            for i, req in enumerate(self._q):
                if group is None or req.group == group:
                    del self._q[i]
                    self._not_full.notify()
                    popped = req
                    break
            else:
                return None
        flight.record("queue.pop", id=popped.id)
        return popped

    def pop_group(self, group: str) -> Optional[ScenarioRequest]:
        """``pop(group=group)`` — kept as the round-11 spelling."""
        return self.pop(group)

    def remove(self, req: ScenarioRequest) -> bool:
        """Remove one request by identity; False when it is no longer
        queued (already popped for serving).  The submit/drain race
        unwind (round 14): a submitter that enqueued concurrently with
        ``begin_drain`` takes its request back out — either the removal
        succeeds and the caller refuses the submission, or the serving
        loop already owns it and will run it to completion."""
        with self._not_full:
            for i, r in enumerate(self._q):
                if r is req:
                    del self._q[i]
                    self._not_full.notify()
                    return True
            return False

    def requeue(self, reqs) -> None:
        """Push popped-but-unserved requests back to the FRONT, in
        their original order — the server's unwind path when a halting
        health guard fires after requests were speculatively popped
        for refill prep.  May exceed ``capacity`` transiently (these
        requests were already admitted once; dropping them on a guard
        trip would lose accepted traffic)."""
        reqs = list(reqs)
        with self._not_full:
            for req in reversed(reqs):
                self._q.appendleft(req)
        for req in reqs:
            flight.record("queue.requeue", id=req.id)

    def snapshot(self) -> List[str]:
        """Queued request ids in FIFO order, under the lock — the
        crash bundle's 'admitted but not yet packed' half of the
        open-request manifest (round 20)."""
        with self._lock:
            return [r.id for r in self._q]
