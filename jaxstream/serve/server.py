"""The continuous-batching ensemble server.

One :class:`EnsembleServer` deployment fixes the grid, dt and physics
(the ``grid:``/``time:``/``physics:``/``model:`` config sections) and
serves :class:`ScenarioRequest` traffic — IC family, perturbation
seed, run length, output subset — by packing requests into the member
axis of the round-7 batched steppers:

* **Shape-bucketed batching**: batch sizes come from a fixed bucket
  set (``serve.buckets``, default ``1,4,16``) and every bucket's
  masked-segment executable is compiled once and kept warm
  (``JAXSTREAM_COMPILE_CACHE`` persists even that across restarts), so
  steady-state serving triggers ZERO recompiles —
  :meth:`EnsembleServer.compile_count` is the proof surface the tests
  assert on.
* **Per-member run-length masking** (:func:`jaxstream.stepping.
  integrate_masked`): requests of any length share a batch; a member
  that finishes mid-segment is frozen bit-for-bit at its own final
  step and its slot is refilled from the queue at the next segment
  boundary instead of idling until the slowest member drains.
* **Slot-refill invariant**: refills happen ONLY at segment boundaries
  — injections are ``dynamic_update_slice`` on the member axis of the
  live carry, so the carry layout (and therefore the compiled
  executable) never changes (docs/DESIGN.md "Continuous batching").
* **Mixed-orography batches** (round 12, the default): the TC5
  mountain rides the batch as a *traced* per-member field — zeros for
  the flat families — so tc2/tc5/tc6/galewsky requests pack into ONE
  bucket in strict queue FIFO order, bitwise-equal to the round-11
  baked-static stepper (tested).  ``serve.group_by_orography: true``
  restores the round-11 batching groups (orography a stepper static,
  group-local FIFO, fused member-fold kernels where they compile).
* **Health-guarded eviction**: a per-member nonfinite count rides the
  compiled segment; a failing member is evicted alone (guard event
  carries the member index — and its chip, under placement) while the
  rest of the batch keeps integrating, and admission control refuses
  NEW traffic once ``serve.max_guard_events`` trips have accumulated.
* **Async result streaming**: per-member extraction starts its
  device->host copies behind the next segment's dispatch
  (:class:`jaxstream.io.async_pipeline.HostFetch`) and lands on the
  bounded :class:`...BackgroundWriter` — results never stall the
  batch.  The health stream itself rides a :class:`HostFetch` too:
  while its d2h copy chases the segment's compute, the host
  pre-builds the incoming requests' initial states for the slots it
  already knows will free (completion is host arithmetic on ``rem``),
  and the residual block is recorded as ``host_wait_s`` in the serve
  sink records.

**Multi-chip serving** (round 12, ``serve.placement:``): one server
process drives a whole mesh.  ``mode: member`` shards the packed
member axis across a 1-D ``('member',)`` device mesh — the SAME
masked-segment program compiled under member-axis ``in_shardings``
(GSPMD partitions the vmapped stepper; zero wire traffic; a B=16
bucket on 8 chips runs 2 members/chip), with slot refill a
sharding-preserving ``dynamic_update_slice`` whose incoming IC is
``device_put`` onto the mesh per refill.  ``mode: panel`` spreads each
request's six faces over the 2-D ``('panel', 'member')`` mesh through
:func:`jaxstream.parallel.shard_cov.make_sharded_cov_ensemble_stepper`
(the PR-3 batched exchange — one ppermute per schedule stage carries
all members' strips — composing with the PR-1 overlap phase split).
Placement off is byte-for-byte the single-chip round-11 path.
"""

from __future__ import annotations

import copy
import dataclasses
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Config, load_config
from ..geometry.cubed_sphere import build_grid
from ..io.async_pipeline import BackgroundWriter, HostFetch
from ..obs import flight
from ..obs import perf as obs_perf
from ..obs import trace as obs_trace
from ..obs.monitor import HealthMonitor
from ..obs.registry import (HOST_WAIT_BUCKETS_S, LATENCY_BUCKETS_S,
                            WALL_BUCKETS_S, MetricsRegistry)
from ..obs.sink import TelemetrySink, run_manifest
from ..parallel.mesh import available_devices, setup_ensemble_sharding
from ..physics import initial_conditions as ics
from ..stepping import SCHEMES, integrate_masked, vmap_ensemble
from ..utils import jax_compat
from ..utils.logging import get_logger
from .placement import PLACEMENT_MODES, BucketPlan, plan_placement
from . import warmpool as _warmpool
from .warmpool import HeadroomRefused
from ..plan import rules as _plan_rules
from ..plan.rules import RULES_VERSION as _PLAN_RULES_VERSION
from .queue import (AdmissionRefused, QueueFull, RequestQueue,
                    ServerDraining)
from .request import RequestResult, ScenarioRequest

__all__ = ["EnsembleServer", "serve_requests"]

log = get_logger(__name__)

#: Thread name of the server's background result writer.
SERVE_WRITER_THREAD_NAME = "jaxstream-serve-writer"


def _member_nonfinite(y, axes):
    """Per-member nonfinite count over the prognostic carry leaves:
    ``(B,)``.

    The on-device health stream of the serving loop — one small vector
    per segment, fetched at the boundary the refill already pays for.
    Under a placement mesh this is a plain GSPMD reduction: the
    reduced axes are unsharded, so each member's count is computed
    entirely on the chip(s) that hold it and only the tiny ``(B,)``
    result crosses the wire.
    """
    total = None
    for k, ax in axes.items():
        a = y[k]
        bad = jnp.sum((~jnp.isfinite(a)).astype(jnp.int32),
                      axis=tuple(i for i in range(a.ndim) if i != ax))
        total = bad if total is None else total + bad
    return total


class _Slot:
    """One member slot's host bookkeeping."""

    def __init__(self, req: ScenarioRequest):
        self.req = req
        self.done = 0                       # steps executed so far

    @property
    def remaining(self) -> int:
        return self.req.nsteps - self.done


class _Bucket:
    """One (group, B) compiled runtime: segment/extract/inject jits.

    ``plan`` is the bucket's :class:`...placement.BucketPlan`;
    ``mesh``/``carry_sh``/``rep_sh`` are set when the plan is sharded
    (``stack``/``put_member``/``put_rem`` then pin their outputs to the
    mesh so every steady-state call hits the same executable)."""

    def __init__(self, group: str, B: int, seg_fn, extract_fn, inject_fn,
                 axes, stack, member_carry, plan: BucketPlan,
                 mesh=None, carry_sh=None, rep_sh=None, proof=None,
                 cost=None):
        self.group = group
        self.B = B
        self.seg = seg_fn
        self.extract = extract_fn
        self.inject = inject_fn
        self.axes = axes
        self.plan = plan
        self.mesh = mesh
        #: Round 16: the bucket stepper's capability proof stamp
        #: (jaxstream.plan.proof) — surfaced in stats and telemetry.
        self.proof = proof
        #: Round 19: the bucket's cost stamp (jaxstream.obs.perf) —
        #: analytic per-step flops/bytes always; footprint bytes +
        #: XLA-vs-analytic flop ratio under ``serve.cost_stamps``;
        #: compile seconds from the warmup either way.
        self.cost = cost
        self._carry_sh = carry_sh
        self._rep = rep_sh
        self._stack = stack
        self._member_carry = member_carry

    def stack(self, trees):
        """Member trees -> the (device-placed) batch carry."""
        carry = self._stack(trees)
        if self._carry_sh is not None:
            carry = jax.device_put(carry, self._carry_sh)
        return carry

    def put_member(self, tree):
        """One member tree -> the inject operand (the per-slot
        ``device_put`` of the incoming IC under placement: replicated
        on the bucket's mesh so one inject executable serves every
        slot)."""
        member = self._member_carry(tree)
        if self._rep is not None:
            member = jax.device_put(
                member, jax.tree_util.tree_map(lambda _: self._rep,
                                               member))
        return member

    def put_rem(self, rem):
        op = jnp.asarray(rem, jnp.int32)
        if self._rep is not None:
            op = jax.device_put(op, self._rep)
        return op

    def jits(self):
        return (self.seg, self.extract, self.inject)


class EnsembleServer:
    """Config -> warm bucketed steppers -> packed request serving.

    ``config`` is the standard :class:`jaxstream.config.Config` surface
    (grid/time/physics/model + the ``serve:`` block); ``on_result`` is
    called with each :class:`RequestResult` from the background writer
    thread (after its fields are on host).  ``on_segment`` (round 14,
    the gateway's streaming hook) is called from the SERVING thread at
    every segment boundary with a list of per-slot progress dicts
    (``id``/``steps_done``/``nsteps``/``t``/``bucket``/``done`` — no
    wall-clock fields), strictly before any of that boundary's
    finalizations are queued, so a subscriber can never observe a
    request's result before its last segment event.  Use as a context
    manager, or call :meth:`close` when done.
    """

    def __init__(self, config=None,
                 on_result: Optional[Callable] = None,
                 on_segment: Optional[Callable] = None):
        self.config: Config = load_config(config)
        cfg = self.config
        s = cfg.serve
        if cfg.model.numerics != "dense":
            _plan_rules.fail("serve-dense")
        if cfg.model.name != "shallow_water_cov":
            # 'auto' would make the same config's Simulation build the
            # CARTESIAN model for tc2/tc5 — a server that silently
            # swapped models would break the documented B=1
            # bitwise-vs-Simulation contract.
            _plan_rules.fail("serve-covariant")
        if (cfg.precision.stage != "f32"
                or cfg.precision.strips not in ("auto", "f32")
                or cfg.precision.carry != "f32"):
            _plan_rules.fail("serve-f32")
        if cfg.parallelization.temporal_block > 1:
            _plan_rules.fail("serve-no-temporal-block")
        if (cfg.parallelization.use_shard_map
                or cfg.parallelization.tiles_per_edge > 1):
            _plan_rules.fail("serve-placement-not-shard-flags")
        if s.guards not in ("off", "evict", "halt"):
            raise ValueError(
                f"serve.guards={s.guards!r}; valid: 'off', 'evict', "
                "'halt'")
        try:
            self.buckets = tuple(sorted(
                {int(b) for b in str(s.buckets).split(",") if b.strip()}))
        except ValueError:
            raise ValueError(
                f"serve.buckets={s.buckets!r} must be a comma-separated "
                "list of positive ints") from None
        if not self.buckets or min(self.buckets) < 1:
            raise ValueError(
                f"serve.buckets={s.buckets!r} must name at least one "
                "positive batch size")
        if s.segment_steps < 1:
            raise ValueError(
                f"serve.segment_steps must be >= 1, got {s.segment_steps}")

        # ------------------------------------------------ placement plan
        self._grouping = bool(s.group_by_orography)
        p = s.placement
        if p.mode not in PLACEMENT_MODES:
            raise ValueError(
                f"serve.placement.mode={p.mode!r}; valid: "
                f"{PLACEMENT_MODES}")
        self._devices = None
        if p.mode != "off":
            devs = available_devices(p.device_type)
            n_dev = p.num_devices or len(devs)
            if n_dev > len(devs):
                raise ValueError(
                    f"serve.placement.num_devices={n_dev} but only "
                    f"{len(devs)} {p.device_type} devices exist. For "
                    f"CPU testing, start Python with XLA_FLAGS="
                    f"--xla_force_host_platform_device_count={n_dev}.")
            if p.mode == "member" and cfg.model.backend != "jnp":
                _plan_rules.fail("serve-member-jnp")
            if p.mode == "panel":
                if not self._grouping:
                    _plan_rules.fail("serve-panel-grouping")
                if cfg.time.scheme != "ssprk3":
                    _plan_rules.fail("serve-panel-ssprk3")
            self._plans: Dict[int, BucketPlan] = plan_placement(
                self.buckets, n_dev, p.mode)
            self._devices = list(devs[:n_dev])
        else:
            self._plans = plan_placement(self.buckets, 1, "off")

        halo = cfg.grid.halo
        if cfg.model.scheme == "ppm":
            halo = max(halo, 3)
        dtype = {"float32": jnp.float32, "float64": jnp.float64,
                 "bfloat16": jnp.bfloat16}[cfg.grid.dtype]
        self.grid = build_grid(cfg.grid.n, halo=halo,
                               radius=cfg.grid.radius, dtype=dtype,
                               metrics=cfg.grid.metrics)
        self.queue = RequestQueue(s.queue_capacity)
        self.monitor = (HealthMonitor(
            (), policy="warn" if s.guards == "evict" else "halt")
            if s.guards != "off" else None)
        self.on_result = on_result
        self.on_segment = on_segment
        self.results: Dict[str, RequestResult] = {}
        self.stats = {
            "submitted": 0, "refused": 0, "completed": 0, "evicted": 0,
            "batches": 0, "segments": 0, "refills": 0,
            "member_steps": 0, "occupancy_sum": 0.0,
            "utilization_sum": 0.0, "warmup_compiles": 0,
            "host_wait_s": 0.0, "resizes": 0, "last_occupancy": 0.0,
        }
        #: Live-resize state (round 14): the ACTIVE bucket cap.  The
        #: full configured bucket set stays warm; packing only uses
        #: buckets <= the cap, so autoscaling swaps among compiled
        #: executables and can never trigger a recompile.
        self._active_max = max(self.buckets)
        self._draining = False
        self._models: Dict[str, object] = {}
        self._ics: Dict[str, tuple] = {}
        self._b_zero = None
        self._b_oro = None
        self._impls: Dict[str, str] = {}
        self._buckets: Dict[tuple, _Bucket] = {}
        self._setups: Dict[tuple, object] = {}
        self._writer: Optional[BackgroundWriter] = None
        #: Round 17: request-scoped tracing (serve.trace).  One
        #: RequestTrace per in-flight admitted request; span records
        #: land in the serve sink at finalize — or, on SINK-LESS
        #: servers only, are retained in ``trace_spans`` (bounded by
        #: the caller's request count; a sinked deployment must read
        #: its sink, not this dict).
        self._trace_on = bool(s.trace)
        self._traces: Dict[str, obs_trace.RequestTrace] = {}
        self.trace_spans: Dict[str, List[dict]] = {}
        #: The sink gains a second writer when tracing is on (span
        #: records from the background writer thread, serve/guard
        #: records from the serving thread) — serialize the two.
        self._sink_lock = threading.Lock()
        #: Round 17: the scrapeable metrics registry (obs.registry) —
        #: updated at segment boundaries on the serving thread, latency
        #: observations on the writer thread, shed counters by the
        #: gateway; rendered by ``GET /v1/metrics``.
        self.metrics = MetricsRegistry()
        self._init_metrics()
        #: Round 19 (performance observatory): the per-bucket compile
        #: counters' last-seen totals (jaxstream_compiles_total moves
        #: when a bucket's jit cache grows — a steady-state recompile
        #: shows up on the scrape, not only in tests) and, under
        #: ``serve.memory_watch``, the device-memory watcher polled at
        #: every segment boundary.  Both live on the serving thread
        #: (the registry's one-writer-per-name rule).
        self._compiles_seen: Dict[tuple, int] = {}
        self._cost_stamps = bool(s.cost_stamps)
        self.memory_watcher = None
        if s.memory_watch:
            self.memory_watcher = obs_perf.MemoryWatcher(
                devices=(self._devices if self._devices is not None
                         else jax.devices()[:1]),
                registry=self.metrics,
                sink_write=self._sink_write)
        self._sink = None
        if s.sink:
            manifest_cfg = {
                "serving": True, "grid_n": cfg.grid.n,
                "dt": cfg.time.dt, "buckets": list(self.buckets),
                "segment_steps": s.segment_steps,
                "queue_capacity": s.queue_capacity,
                "guards": s.guards,
                "placement": p.mode,
                "group_by_orography": self._grouping,
                # Round 16: rule-table version the bucket proof
                # stamps were minted against (each 'serve' record
                # then names its bucket's plan + verdict).
                "rules_version": _PLAN_RULES_VERSION,
            }
            if self._trace_on:
                # Only stamped when tracing is ON, so an untraced
                # run's manifest stays byte-identical to round 14's.
                manifest_cfg["trace"] = True
            # Same contract for the round-19 observatory knobs: the
            # manifest names them only when they are on, so a
            # default-config run's sink stays byte-identical.
            if s.memory_watch:
                manifest_cfg["memory_watch"] = True
            if s.cost_stamps:
                manifest_cfg["cost_stamps"] = True
            self._sink = TelemetrySink(s.sink, run_manifest(
                config=manifest_cfg))
        self._fault_fired = False
        self._closed = False
        #: Round 20 (flight recorder): the serving blackbox.  SIGKILL
        #: cannot be trapped, so when a flight dir is configured the
        #: server keeps a LIVE crash bundle — atomically re-committed
        #: at segment boundaries (throttled) and forced on every admit
        #: — whose open-request manifest always names every admitted-
        #: but-unfinished request.  ``self._resident`` mirrors the
        #: batch loop's local resident list so the bundle can see what
        #: is packed, not just what is queued.
        self._resident: List[str] = []
        self._blackbox: Optional[flight.BundleWriter] = None
        self._flight_last = 0.0
        self._flight_min_interval = 0.25
        #: Latched by flight_dump: once a terminal reason (signal,
        #: HealthError, ...) has been committed, the live re-commits
        #: that keep running through a graceful drain must not revert
        #: the bundle's reason to "live".
        self._flight_reason = "live"
        fdir = flight.resolve_flight_dir(cfg)
        if fdir:
            self._blackbox = flight.BundleWriter(fdir)
        #: Round 21 (warm pools): the disk-backed executable pool
        #: (``serve.warm_pool``), the probe-gated persistent compile
        #: cache (``serve.compile_cache``), and the speculative
        #: compiler (``serve.speculate``).  The build lock serializes
        #: first-use bucket builds between the serving thread and the
        #: speculator thread; the deployment digest folds the config
        #: fields the plan key does NOT carry (dt, segment steps, nu4,
        #: dtype, donation, ...) into every pool entry key.
        self._deploy_digest = _warmpool.deployment_digest(cfg)
        self._build_lock = threading.RLock()
        self._warmpool: Optional[_warmpool.WarmPool] = None
        self._speculator = None
        #: Entries only persist when ``serve.warm_pool`` names a
        #: directory; a compile-cache-only deployment still gets a pool
        #: object (it owns the probe verdicts) but load/save stay off.
        self._pool_entries = bool(s.warm_pool)
        if s.warm_pool or s.compile_cache:
            pool_dir = s.warm_pool or s.compile_cache + ".pool"
            self._warmpool = _warmpool.WarmPool(
                pool_dir, compile_cache=s.compile_cache,
                sink_write=self._sink_write,
                counter_inc=self.metrics.counter_inc)
            if s.compile_cache:
                self._warmpool.enable_compile_cache()
        if s.speculate:
            if not self._pool_entries:
                raise ValueError(
                    "serve.speculate requires serve.warm_pool — a "
                    "speculative compile is only worth its thread when "
                    "the executable persists for the next process too")
            self._speculator = _warmpool.SpeculativeCompiler(self)

    # --------------------------------------------------- flight recorder
    def _open_requests(self) -> dict:
        """Queued + in-flight request ids with trace ids — the crash
        bundle's admitted-but-unfinished manifest."""
        return flight.open_request_manifest(self.queue.snapshot(),
                                            list(self._resident))

    def flight_commit(self, force: bool = False,
                      reason: str = "live") -> None:
        """(Re-)commit the live crash bundle.  Throttled unless forced;
        never raises out of the serving loop."""
        bb = self._blackbox
        if bb is None:
            return
        if reason != "live":
            self._flight_reason = reason
        reason = self._flight_reason
        now = time.perf_counter()
        if not force and now - self._flight_last < self._flight_min_interval:
            return
        self._flight_last = now
        try:
            bb.commit(
                reason,
                config={"serving": True, "grid_n": self.config.grid.n,
                        "buckets": list(self.buckets),
                        "segment_steps": self.config.serve.segment_steps},
                proofs=self.bucket_proofs(),
                cost_stamps=self.bucket_costs(),
                device_memory=self.memory_snapshot(),
                open_requests=self._open_requests(),
                extra={"stats": {k: v for k, v in self.stats.items()
                                 if isinstance(v, int)}})
        except Exception as e:     # forensics must never kill serving
            log.warning("flight bundle commit failed (%s: %s)",
                        type(e).__name__, e)

    def flight_dump(self, reason: str) -> None:
        """Force one bundle commit (crash/signal path) and announce it
        in the serve sink as typed ``flight`` + ``crash`` records."""
        if self._blackbox is None:
            return
        self.flight_commit(force=True, reason=reason)
        try:
            events, threads, dropped = flight.RECORDER.dump()
            self._sink_write({"kind": "flight", "events": len(events),
                              "threads": len(threads),
                              "dropped": dropped})
            self._sink_write({"kind": "crash",
                              "bundle": self._blackbox.bundle_id,
                              "path": self._blackbox.path,
                              "reason": reason})
        except Exception as e:
            log.warning("flight dump sink records failed (%s: %s)",
                        type(e).__name__, e)

    def _init_metrics(self):
        """Declare the scrape surface up front (names, types, bucket
        ladders and HELP text are part of the operator contract —
        present from the first scrape, not from first traffic)."""
        m = self.metrics
        m.counter("jaxstream_requests_submitted_total",
                  "requests admitted by submit()")
        m.counter("jaxstream_requests_completed_total",
                  "requests that reached a final state, by status")
        m.counter("jaxstream_requests_shed_total",
                  "typed admission refusals, by shed status")
        m.counter("jaxstream_segments_total",
                  "compiled masked segments executed")
        m.counter("jaxstream_member_steps_total",
                  "member-steps of work advanced")
        m.counter("jaxstream_guard_events_total",
                  "health-guard trips (member evictions)")
        m.counter("jaxstream_compiles_total",
                  "compiled executables per plan key (warmup included; "
                  "a moving counter at steady state is a recompile)")
        m.counter("jaxstream_warmpool_hits_total",
                  "warm-pool entry loads, by rung")
        m.counter("jaxstream_warmpool_misses_total",
                  "warm-pool misses, by reason")
        m.counter("jaxstream_warmpool_saves_total",
                  "warm-pool entries persisted, by rung")
        m.gauge("jaxstream_queue_depth", "request queue depth")
        m.gauge("jaxstream_queue_capacity", "request queue bound")
        m.gauge("jaxstream_active_bucket_cap",
                "largest batch-size bucket packing may use")
        m.gauge("jaxstream_occupancy",
                "slot occupancy of the last segment (active/B)")
        m.gauge("jaxstream_chip_occupancy",
                "per-member-shard slot occupancy of the last segment")
        m.gauge("jaxstream_chip_utilization",
                "per-member-shard advanced-step fraction of the last "
                "segment")
        m.histogram("jaxstream_request_latency_seconds",
                    LATENCY_BUCKETS_S,
                    "submit-to-result end-to-end latency")
        m.histogram("jaxstream_segment_wall_seconds", WALL_BUCKETS_S,
                    "wall seconds per compiled masked segment")
        m.histogram("jaxstream_host_wait_seconds", HOST_WAIT_BUCKETS_S,
                    "residual health-stream d2h block per boundary")
        m.gauge_set("jaxstream_queue_depth", 0)
        m.gauge_set("jaxstream_queue_capacity",
                    self.config.serve.queue_capacity)
        m.gauge_set("jaxstream_active_bucket_cap", self._active_max)

    def _sink_write(self, rec: dict) -> None:
        """Serialized sink write (serving thread + writer thread when
        tracing; the lock is uncontended otherwise)."""
        if self._sink is None:
            return
        with self._sink_lock:
            self._sink.write(rec)

    # ------------------------------------------------------------ lifecycle
    def close(self):
        """Drain the result writer and close the telemetry sink."""
        if self._closed:
            return
        self._closed = True
        if self._speculator is not None:
            sp, self._speculator = self._speculator, None
            sp.close()
        if self._writer is not None:
            w, self._writer = self._writer, None
            w.close()
        if self._sink is not None:
            self._sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Graceful-drain entry (round 14): close admissions NOW —
        every later :meth:`submit` raises :class:`ServerDraining` —
        while already-admitted requests keep serving to their own
        final step (:meth:`serve_forever` exits once the queue is
        empty).  Nothing is re-queued or dropped."""
        self._draining = True
        flight.record("serve.drain", queue_depth=len(self.queue))

    # ------------------------------------------------------- live resize
    @property
    def active_buckets(self) -> tuple:
        """The bucket sizes packing may currently use (resize scales
        the cap; the smallest bucket always stays available)."""
        active = tuple(b for b in self.buckets if b <= self._active_max)
        return active or (min(self.buckets),)

    def resize(self, max_bucket: int, reason: str = "",
               queue_depth: Optional[int] = None,
               occupancy: Optional[float] = None) -> int:
        """Live-resize the active bucket cap (round 14 autoscaling).

        ``max_bucket`` must be a CONFIGURED bucket — every legal cap
        maps to a warm executable, so a resize never compiles (the
        zero-steady-state-recompiles-after-resize criterion is by
        construction).  Takes effect at the next batch.  Under
        ``serve.placement`` this is also the placement lever: each
        bucket's plan spans a fixed device count, so raising the cap
        engages more chips.  Returns the previous cap; records an
        ``autoscale`` event in the serve sink.  Thread-safe in the
        only way that matters: the cap is a single attribute read once
        per batch by the serving thread.
        """
        if max_bucket not in self.buckets:
            raise ValueError(
                f"resize target {max_bucket} is not a configured "
                f"bucket {list(self.buckets)} — resizes must land on "
                "warm executables (add the size to serve.buckets)")
        if max_bucket > self._active_max:
            # Round 21 headroom enforcement (the first consumer of the
            # round-19 advisory): a scale-UP to a bucket whose stamped
            # footprint breaches serve.min_headroom_frac is refused
            # with a typed record.  Scale-downs free memory and are
            # never refused; unstamped plans are never refused.
            refusal = self.headroom_refusal(max_bucket)
            if refusal is not None:
                self.record_headroom_refusal(refusal,
                                             action="resize_refused")
                raise HeadroomRefused(
                    f"resize target {max_bucket} refused: stamped "
                    f"headroom {refusal['headroom_frac']:.4f} < "
                    f"serve.min_headroom_frac "
                    f"{refusal['min_headroom_frac']:.4f}")
        old, self._active_max = self._active_max, int(max_bucket)
        self.metrics.gauge_set("jaxstream_active_bucket_cap",
                               self._active_max)
        if old != max_bucket:
            self.stats["resizes"] += 1
            log.info("serve: resized active bucket cap %d -> %d%s",
                     old, max_bucket, f" ({reason})" if reason else "")
        flight.record("serve.resize", from_bucket=old,
                      to_bucket=int(max_bucket),
                      reason=reason or "manual")
        if self._sink is not None:
            self._sink_write({
                "kind": "autoscale", "from_bucket": old,
                "to_bucket": int(max_bucket),
                "queue_depth": (len(self.queue) if queue_depth is None
                                else int(queue_depth)),
                "occupancy": round(
                    self.stats["last_occupancy"] if occupancy is None
                    else float(occupancy), 4),
                "reason": reason or "manual",
            })
        if self._speculator is not None:
            self._speculator.nudge(self._active_max)
        return old

    # ------------------------------------------------------------- building
    def _group(self, req: ScenarioRequest) -> str:
        """The request's batching group: its orography group under
        ``group_by_orography: true``, the single ``'any'`` group (all
        families pack, strict FIFO) otherwise."""
        return req.group if self._grouping else "any"

    def _pop(self, group: str) -> Optional[ScenarioRequest]:
        return self.queue.pop(group if self._grouping else None)

    def _ic(self, family: str):
        """Cached base IC fields ``(h_ext, v_ext, b_ext)`` per family."""
        if family not in self._ics:
            p, m, g = self.config.physics, self.config.model, self.grid
            b_ext = None
            if family == "tc2":
                h, v = ics.williamson_tc2(g, p.gravity, p.omega,
                                          alpha_rot=m.ic_angle)
            elif family == "tc5":
                h, v, b_ext = ics.williamson_tc5(g, p.gravity, p.omega)
            elif family == "tc6":
                h, v = ics.williamson_tc6(g, p.gravity, p.omega)
            else:
                h, v = ics.galewsky(g, p.gravity, p.omega)
            self._ics[family] = (h, v, b_ext)
        return self._ics[family]

    def _b_ext(self, family: str):
        """The request's traced orography field (mixed batches): the
        TC5 mountain for 'tc5', cached zeros for the flat families.

        The mountain is ghost-filled through the SAME halo exchange
        ``SWEBase.__init__`` applies to a baked static — the analytic
        IC ghosts differ from the exchanged (continuation-resampled)
        ones, and bitwise parity with the round-11 stepper depends on
        feeding the stencils identical ghost values."""
        if family == "tc5":
            if self._b_oro is None:
                self._b_oro = self._model("any").exchange(
                    self._ic("tc5")[2])
            return self._b_oro
        if self._b_zero is None:
            self._b_zero = jnp.zeros_like(self.grid.sqrtg)
        return self._b_zero

    def _model(self, group: str):
        """Cached model per batching group.  'oro' bakes the TC5
        orography (the ``group_by_orography: true`` parity mode);
        'flat' and the mixed-batch 'any' group are flat-bottom — the
        mountain then rides the carry as a traced field."""
        if group not in self._models:
            from ..models.shallow_water_cov import CovariantShallowWater

            cfg = self.config
            p, m = cfg.physics, cfg.model
            b_ext = self._ic("tc5")[2] if group == "oro" else None
            self._models[group] = CovariantShallowWater(
                self.grid, gravity=p.gravity, omega=p.omega, b_ext=b_ext,
                scheme=m.scheme, limiter=m.limiter,
                nu4=p.hyperdiffusion, backend=m.backend)
        return self._models[group]

    def _request_state(self, req: ScenarioRequest):
        """A request's interior initial state (deterministic in seed).

        ``ic: 'array'`` requests carry the interior state themselves
        (round 18): the arrays go on device as-is — byte-preserving,
        so a checkpointed member or an EnKF analysis state resubmitted
        through the gateway continues bitwise (validated at admission
        by :meth:`validate_request`)."""
        if req.ic == "array":
            return {k: jnp.asarray(v) for k, v in req.state.items()}
        h, v, _ = self._ic(req.ic)
        if req.seed >= 0 and req.amplitude != 0.0:
            h = ics.perturbed_ensemble(self.grid, h, 2, seed=req.seed,
                                       amplitude=req.amplitude)[1]
        return self._model(self._group(req)).initial_state(h, v)

    def validate_request(self, req: ScenarioRequest) -> None:
        """Admission-time deployment validation (raises ValueError).

        The dataclass validated everything grid-independent; this
        checks what only the deployment knows — an ``ic: 'array'``
        state's shapes and dtype against the serving grid.  Runs in
        :meth:`submit` so a mismatched array is a typed 400 at the
        gateway, never a shape error mid-batch on the serving thread.
        """
        if req.ic != "array":
            return
        n = self.grid.n
        dtype = str(np.dtype(self.config.grid.dtype))
        expect = {"h": (6, n, n), "u": (2, 6, n, n)}
        for k, shape in expect.items():
            a = req.state[k]
            if tuple(a.shape) != shape:
                raise ValueError(
                    f"request {req.id!r}: ic 'array' field {k!r} has "
                    f"shape {tuple(a.shape)}; this deployment serves "
                    f"C{n} interior states of shape {shape}")
            if str(a.dtype) != dtype:
                raise ValueError(
                    f"request {req.id!r}: ic 'array' field {k!r} has "
                    f"dtype {a.dtype}; this deployment serves "
                    f"{dtype} states (byte-preserving continuations "
                    f"need the exact dtype)")

    def _member_tree(self, req: ScenarioRequest):
        """The request's member tree: interior state, plus its traced
        orography leaf on the mixed-batch path."""
        st = self._request_state(req)
        if not self._grouping:
            st = dict(st)
            st["b"] = self._b_ext(req.ic)
        return st

    def _setup_for(self, plan: BucketPlan):
        """The (cached) mesh/ShardingSetup of one sharded plan."""
        key = (plan.mode, plan.num_devices)
        if key not in self._setups:
            ptype = self.config.serve.placement.device_type
            layout = ("member" if plan.mode == "member"
                      else "panel_member")
            self._setups[key] = setup_ensemble_sharding(
                {"parallelization": {
                    "num_devices": plan.num_devices,
                    "device_type": ptype,
                    "overlap_exchange":
                        self.config.parallelization.overlap_exchange,
                }},
                members=plan.bucket, layout=layout)
        return self._setups[key]

    def _build_bucket(self, group: str, B: int, impl: str) -> _Bucket:
        cfg = self.config
        model = self._model(group)
        dt, seg = cfg.time.dt, cfg.serve.segment_steps
        plan = self._plans[B]
        setup = self._setup_for(plan) if plan.sharded else None

        if impl == "fused":
            step = model.make_fused_step(dt, ensemble=B)
            axes = {"h": 0, "u": 1, "strips_sn": 0, "strips_we": 0}
            member_carry = model.compact_state
            stack = (lambda trees:
                     model.ensemble_compact_state(
                         model.stack_ensemble(trees)))
        elif impl == "vmap":
            base = model.make_step(dt, cfg.time.scheme)
            axes = {"h": 0, "u": 1}
            step = vmap_ensemble(base, axes)
            member_carry = lambda st: st
            stack = model.stack_ensemble
        elif impl == "vmap_b":
            # Mixed-orography batches: the mountain is a traced
            # per-member carry leaf read by a per-step model rebind —
            # bitwise-equal to the baked-static stepper (the add/grad
            # ops are identical, only constant-ness changes; tested).
            axes = {"h": 0, "u": 1, "b": 0}
            scheme_fn = SCHEMES[cfg.time.scheme]

            def one(y, t, _m=model, _dt=dt):
                mm = copy.copy(_m)
                mm.b_ext = y["b"]
                out = scheme_fn(mm.rhs, {"h": y["h"], "u": y["u"]},
                                t, _dt)
                return {"h": out["h"], "u": out["u"], "b": y["b"]}

            step = vmap_ensemble(one, axes)
            member_carry = lambda st: st

            def stack(trees):
                return {"h": jnp.stack([tr["h"] for tr in trees]),
                        "u": jnp.stack([tr["u"] for tr in trees],
                                       axis=1),
                        "b": jnp.stack([tr["b"] for tr in trees])}
        elif impl == "panel":
            from ..parallel.shard_cov import (
                make_sharded_cov_ensemble_stepper)

            axes = {"h": 0, "u": 1}
            step = make_sharded_cov_ensemble_stepper(
                model, setup, dt, B, wrap_jit=False)
            member_carry = lambda st: st
            stack = model.stack_ensemble
        else:
            raise ValueError(f"unknown bucket impl {impl!r}")

        mesh = carry_sh = rep = None
        if setup is not None:
            mesh = setup.mesh
            carry_sh = {k: setup.ensemble_sharding_for(ax + 4)
                        for k, ax in axes.items()}
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(mesh, P())

        # The health stream counts the prognostics only — the traced
        # orography leaf is constant per member.
        nf_axes = {k: axes[k] for k in ("h", "u")}

        def seg_body(y, rem):
            y, _, rem = integrate_masked(step, y, 0.0, rem, seg, dt,
                                         axes, sharding=carry_sh)
            return y, rem, _member_nonfinite(y, nf_axes)

        def extract_body(y, idx):
            return {k: jnp.take(y[k], idx, axis=axes[k])
                    for k in ("h", "u")}

        def inject_body(y, idx, member):
            out = dict(y)
            for k, ax in axes.items():
                upd = jnp.expand_dims(member[k].astype(y[k].dtype), ax)
                out[k] = jax.lax.dynamic_update_slice_in_dim(
                    y[k], upd, idx, axis=ax)
            return out

        # Round 16: the bucket's capability proof stamp — which plan
        # this compiled masked segment implements, and whether the
        # static matrix covers it (jaxstream.plan).
        from ..plan.plan import CapabilityPlan
        from ..plan.proof import build_proof
        from ..plan.rules import normalize as plan_normalize

        tier = {"fused": "fused", "vmap": "classic",
                "vmap_b": "classic", "panel": "face"}[impl]
        if plan.mode == "member":
            tier = "gspmd"
        splan = plan_normalize(CapabilityPlan(
            tier=tier, n=cfg.grid.n, halo=self.grid.halo,
            scheme=cfg.time.scheme, ensemble=B,
            overlap=(cfg.parallelization.overlap_exchange
                     and plan.mode == "panel"),
            donate=cfg.serve.donate, serving=True,
            placement=("off" if plan.mode == "single" else plan.mode),
            serve_grouping=self._grouping,
            num_devices=plan.num_devices,
            backend=("pallas" if impl == "fused"
                     else cfg.model.backend),
            covariant=True))
        proof = build_proof(splan)
        # Round 19: the cost stamp rides next to the proof stamp —
        # analytic per-step flops/bytes now, measured fields at warmup.
        cost = obs_perf.build_cost(splan, plan_key=proof.plan_key)

        donate = (0,) if cfg.serve.donate else ()
        if mesh is None:
            seg_j = jax.jit(seg_body, donate_argnums=donate)
            ex_j = jax.jit(extract_body)
            inj_j = jax.jit(inject_body)
        else:
            member_sh = {k: rep for k in axes}
            seg_j = jax.jit(seg_body, donate_argnums=donate,
                            in_shardings=(carry_sh, rep),
                            out_shardings=(carry_sh, rep, rep))
            ex_j = jax.jit(extract_body,
                           in_shardings=(carry_sh, rep),
                           out_shardings={"h": rep, "u": rep})
            inj_j = jax.jit(inject_body,
                            in_shardings=(carry_sh, rep, member_sh),
                            out_shardings=carry_sh)
        return _Bucket(group, B, seg_j, ex_j, inj_j, axes, stack,
                       member_carry, plan, mesh=mesh,
                       carry_sh=carry_sh, rep_sh=rep, proof=proof,
                       cost=cost)

    def _impls_for(self, group: str, plan: BucketPlan) -> List[str]:
        """Candidate stepper impls for one bucket, most preferred
        first.  Panel-sharded plans run the shard_map ensemble stepper;
        mixed-orography servers run the traced-b vmapped classic;
        grouped servers keep the round-11 fused-then-vmap chain
        (member-sharded plans restrict it to the partitionable vmap —
        the backend gate in __init__ already enforced jnp)."""
        if plan.mode == "panel":
            return ["panel"]
        if not self._grouping:
            return ["vmap_b"]
        if group in self._impls:
            return [self._impls[group]]
        cfg = self.config
        fused_ok = (plan.mode == "single"
                    and cfg.time.scheme == "ssprk3"
                    and cfg.model.backend.startswith("pallas")
                    and cfg.physics.hyperdiffusion == 0.0)
        return ["fused", "vmap"] if fused_ok else ["vmap"]

    def _bucket(self, group: str, B: int) -> _Bucket:
        """The warm (group, B) runtime — built, compiled and probed on
        first use (the probe run IS the warmup).  Under a configured
        ``serve.warm_pool`` the three executables route through the
        disk pool first (round 21): on a full-AOT hit the probe run
        below executes pre-loaded executables — ZERO XLA compiles.
        The build lock serializes first-use builds between the serving
        thread and the speculative compiler (dict reads stay lock-free
        for the warm steady state)."""
        key = (group, B)
        bk = self._buckets.get(key)
        if bk is not None:
            return bk
        with self._build_lock:
            bk = self._buckets.get(key)
            if bk is not None:      # raced the speculator; it won
                return bk
            plan = self._plans[B]
            impls = self._impls_for(group, plan)
            err = None
            for impl in impls:
                try:
                    bk = self._build_bucket(group, B, impl)
                    t_warm = time.perf_counter()
                    self._warm_via_pool(bk)
                    self._warm_bucket(bk)
                    bk.cost.compile_seconds = round(
                        time.perf_counter() - t_warm, 4)
                    self._stamp_bucket(bk)
                    self._impls[group] = impl
                    self._buckets[key] = bk
                    self.stats["warmup_compiles"] = self.compile_count()
                    log.info("serve: bucket (%s, B=%d) warm (%s "
                             "stepper, placement %s x%d)", group, B,
                             impl, plan.mode, plan.num_devices)
                    return bk
                except Exception as e:
                    err = e
                    if impl != impls[-1]:
                        log.warning(
                            "serve: %s stepper unavailable for bucket "
                            "(%s, B=%d) (%s: %s); falling back",
                            impl, group, B, type(e).__name__, e)
            raise RuntimeError(
                f"serve: no stepper builds for bucket ({group}, B={B})"
            ) from err

    def _warm_member_tree(self, group: str):
        family = "tc5" if group == "oro" else "tc2"
        st = self._model(group).initial_state(*self._ic(family)[:2])
        if not self._grouping:
            st = dict(st)
            st["b"] = self._b_ext(family)
        return st

    def _warm_bucket(self, bk: _Bucket):
        """One dummy masked segment + extract + inject: compiles (and
        probes) every executable the bucket will ever run."""
        st = self._warm_member_tree(bk.group)
        carry = bk.stack([st] * bk.B)
        rem = np.zeros(bk.B, np.int64)
        rem[0] = self.config.serve.segment_steps
        carry, _, nf = bk.seg(carry, bk.put_rem(rem))
        jax.block_until_ready(nf)
        ex = bk.extract(carry, jnp.int32(0))
        carry = bk.inject(carry, jnp.int32(0), bk.put_member(st))
        jax.block_until_ready((ex["h"], carry["h"]))

    def _warm_via_pool(self, bk: _Bucket) -> Optional[str]:
        """Route the bucket's three executables through the warm pool
        (round 21).  On a hit the jits are REPLACED by the pool-loaded
        executables before the warmup probe runs — zero XLA compiles on
        the full-AOT rung; on a miss each is compiled ahead-of-time
        exactly once (the AOT ``Compiled`` becomes the bucket's
        callable, so the warmup probe never compiles again) and
        persisted.  Sharded buckets are a typed miss this round — a
        serialized executable is bound to one device assignment, and
        revalidating that across processes is future work.  Returns
        the rung of the SEGMENT executable (the expensive one), or
        None (pool off / sharded)."""
        pool = self._warmpool
        if pool is None or not self._pool_entries:
            return None
        plan_key = bk.proof.plan_key if bk.proof is not None else None
        if bk.mesh is not None:
            pool._record("miss", "cold", plan_key,
                         reason="sharded_unsupported")
            return None
        st = self._warm_member_tree(bk.group)
        carry = bk.stack([st] * bk.B)
        rem = np.zeros(bk.B, np.int64)
        rem[0] = self.config.serve.segment_steps
        donate = (0,) if self.config.serve.donate else ()
        specs = (
            ("seg", bk.seg, (carry, bk.put_rem(rem)), donate),
            ("extract", bk.extract, (carry, jnp.int32(0)), ()),
            ("inject", bk.inject,
             (carry, jnp.int32(0), bk.put_member(st)), ()),
        )
        fingerprint = (bk.proof.schedule_fingerprint
                       if bk.proof is not None else None)
        rules_version = (bk.proof.rules_version
                         if bk.proof is not None
                         else _PLAN_RULES_VERSION)
        # The proof's plan key names the STRATEGY (tier, scheme,
        # placement) but not which bucket or batching group compiled
        # under it — and every bucket/group pair is a different
        # program (different B in every shape, oro groups carry the
        # orography field).  Fold both in or B=2 stale-hits B=1's
        # entry and dies on the shape check.
        ident = f"{plan_key or 'unplanned'}/{bk.group}/B{bk.B}"
        seg_rung = None
        loaded = {}
        for name, jitted, args, dn in specs:
            ekey = _warmpool.entry_key(
                ident, fingerprint,
                rules_version, self._deploy_digest, name)
            warm = pool.load(ekey, ident)
            if warm is None:
                # Lowering never consumes donated buffers — donation
                # only matters at execution, so the example args stay
                # valid for every spec.
                compiled = jitted.lower(*args).compile()
                rung = pool.save(ekey, jitted, compiled, args,
                                 plan_key=ident, donate=dn)
                warm = _warmpool.WarmExecutable(
                    compiled, rung or "fresh", compiles=1)
            # The original jit surface rides along so the round-19
            # cost stamp can still lower+measure (measure_cost needs
            # .lower; an AOT Compiled has none).
            warm._jitted = jitted
            loaded[name] = warm
            if name == "seg":
                seg_rung = warm.rung
        bk.seg, bk.extract, bk.inject = (
            loaded["seg"], loaded["extract"], loaded["inject"])
        return seg_rung

    def _stamp_bucket(self, bk: _Bucket) -> None:
        """Round 19: fill the bucket cost stamp's measured fields.

        Under ``serve.cost_stamps`` the segment executable is compiled
        ONCE MORE ahead-of-time — the timed compile becomes the
        recorded ``compile_seconds`` (replacing the warmup wall, which
        includes a probe execution), XLA's cost/memory analysis fills
        the footprint bytes and the flops-vs-analytic ratio, and the
        advisory ``headroom_frac`` lands on the bucket plan when the
        memory watcher knows the per-chip capacity.  One typed 'perf'
        sink record per stamped bucket.  Off = analytic half + warmup
        wall only (zero extra compiles, sink untouched)."""
        if not self._cost_stamps:
            return
        seg = self.config.serve.segment_steps
        try:
            st = self._warm_member_tree(bk.group)
            carry = bk.stack([st] * bk.B)
            rem = np.zeros(bk.B, np.int64)
            rem[0] = seg
            obs_perf.measure_cost(
                # Under the warm pool bk.seg is a WarmExecutable; the
                # stamp lowers through the original jit surface it
                # carries (stamping is the documented one-extra-compile
                # opt-in either way).
                getattr(bk.seg, "_jitted", bk.seg), carry,
                bk.put_rem(rem),
                analytic=bk.cost.analytic, steps=seg,
                xla_visible=bk.cost.xla_visible, stamp=bk.cost)
        except Exception as e:
            bk.cost.memory = {
                "unavailable": f"measure failed "
                               f"({type(e).__name__}: {e})"}
            log.warning("serve: cost stamp for bucket (%s, B=%d) "
                        "unavailable (%s: %s)", bk.group, bk.B,
                        type(e).__name__, e)
        limit = None
        if self.memory_watcher is not None:
            if self.memory_watcher.last is None:
                self.memory_watcher.poll()
            limit = self.memory_watcher.limit_bytes()
        footprint = bk.cost.memory.get("total_bytes")
        if footprint and limit:
            bk.plan = bk.plan.with_headroom(footprint, limit)
            # placement_summary reads the shared per-B plan table;
            # buckets of different groups share a B entry — last
            # stamped wins there, each bucket's own value stays in
            # bucket_costs().
            self._plans[bk.B] = bk.plan
        if self._sink is not None:
            self._sink_write({
                "kind": "perf", "plan": bk.cost.plan_key,
                "bucket": bk.B, "group": bk.group,
                "compile_seconds": bk.cost.compile_seconds,
                "memory": bk.cost.memory,
                "analytic": bk.cost.analytic, "xla": bk.cost.xla,
                "flops_ratio": bk.cost.flops_ratio,
                "bytes_ratio": bk.cost.bytes_ratio,
                "in_band": bk.cost.in_band,
                "headroom_frac": bk.plan.headroom_frac,
            })

    def warmup(self, groups=("flat",), buckets=None):
        """Pre-compile the bucket set so the first real traffic hits
        warm executables (steady-state = zero recompiles).  ``groups``:
        which batching groups to warm ('flat' and/or 'oro'; on the
        mixed-orography default every name maps to the single packed
        group)."""
        for g in groups:
            if g not in ("flat", "oro", "any"):
                raise ValueError(f"unknown batching group {g!r}")
            if not self._grouping:
                g = "any"
            for B in (buckets or self.buckets):
                self._bucket(g, B)
        # Publish the warmup compiles on the scrape before any
        # traffic (the serving thread has not started — sequential,
        # so the one-writer-per-name rule holds).
        self._observe_perf()
        return self.compile_count()

    def compile_count(self) -> int:
        """Total compiled executables across every bucket's jits — the
        zero-steady-state-recompile assertion surface (-1 when the jax
        build exposes no cache-size introspection; the introspection
        itself is the shared ``jax_compat.compile_count`` helper the
        round-19 compile-event counters also read)."""
        total = 0
        for bk in self._buckets.values():
            for f in bk.jits():
                cs = jax_compat.compile_count(f)
                if cs is None:
                    return -1
                total += cs
        return total

    def placement_summary(self) -> Optional[dict]:
        """The resolved per-bucket placement (None when placement is
        off) — the CLI/bench surface of the planner."""
        p = self.config.serve.placement
        if p.mode == "off":
            return None
        return {
            "mode": p.mode,
            "device_type": p.device_type,
            "devices": len(self._devices),
            "buckets": {str(b): dataclasses.asdict(pl)
                        for b, pl in sorted(self._plans.items())},
        }

    def bucket_proofs(self) -> Dict[str, Optional[dict]]:
        """Per warm bucket: the capability proof stamp of its compiled
        masked segment (round 16) — plan key, canonical schedule
        fingerprint, rules version, matrix-coverage verdict."""
        return {f"{g}/B{B}": (bk.proof.to_json()
                              if bk.proof is not None else None)
                for (g, B), bk in sorted(self._buckets.items())}

    def bucket_costs(self) -> Dict[str, Optional[dict]]:
        """Per warm bucket: the cost stamp of its compiled masked
        segment (round 19) — analytic flops/bytes, footprint bytes (or
        the typed unavailable reason), compile seconds, the
        XLA-vs-analytic flop ratio, and the plan's advisory headroom.
        Surfaced by ``/v1/stats`` and ``scripts/serve.py``."""
        out: Dict[str, Optional[dict]] = {}
        for (g, B), bk in sorted(self._buckets.items()):
            if bk.cost is None:
                out[f"{g}/B{B}"] = None
                continue
            d = bk.cost.to_json()
            d["headroom_frac"] = bk.plan.headroom_frac
            out[f"{g}/B{B}"] = d
        return out

    def memory_snapshot(self) -> Optional[dict]:
        """The memory watcher's latest per-chip record (None when
        ``serve.memory_watch`` is off or nothing polled yet)."""
        return (self.memory_watcher.last
                if self.memory_watcher is not None else None)

    # ------------------------------------------------- warm pool (round 21)
    def warm_groups(self) -> tuple:
        """Batching groups the speculative compiler should warm: the
        groups that already have buckets (a live server speculates
        along the traffic it has seen), else the deployment's default
        group."""
        groups = {g for (g, _B) in self._buckets}
        if not groups:
            groups = {"any" if not self._grouping else "flat"}
        return tuple(sorted(groups))

    def headroom_refusal(self, B: int) -> Optional[dict]:
        """The typed refusal record for scaling to bucket ``B``, or
        None (= allowed).  Refuses ONLY when the bucket's plan carries
        a stamped ``headroom_frac`` (round 19 cost stamps) below
        ``serve.min_headroom_frac`` — an unstamped plan is never
        refused (enforcement needs evidence), and the default threshold
        0.0 only refuses footprints that already exceed capacity."""
        plan = self._plans.get(int(B))
        hf = getattr(plan, "headroom_frac", None)
        if hf is None:
            return None
        mn = self.config.serve.min_headroom_frac
        if hf >= mn:
            return None
        return {"kind": "headroom", "action": "", "bucket": int(B),
                "headroom_frac": round(float(hf), 4),
                "min_headroom_frac": float(mn)}

    def record_headroom_refusal(self, refusal: dict,
                                action: str) -> None:
        """Write one headroom refusal as a typed sink record + flight
        event (``action``: 'resize_refused' / 'speculate_refused')."""
        rec = dict(refusal)
        rec["action"] = action
        self._sink_write(rec)
        flight.record("serve.headroom_refused", bucket=rec["bucket"],
                      action=action,
                      headroom_frac=rec["headroom_frac"])

    def warmpool_summary(self) -> Optional[dict]:
        """The warm pool's ``/v1/stats`` surface (None = pool off):
        hit/miss/save/corrupt counters, per-rung hit counts, probe
        verdicts, and what the speculative compiler built/skipped."""
        if self._warmpool is None:
            return None
        out = self._warmpool.summary()
        if self._speculator is not None:
            out["speculative_built"] = [
                list(t) for t in self._speculator.built]
            out["speculative_skipped"] = len(self._speculator.skipped)
        return out

    # ------------------------------------------------------------ admission
    def refusal_reasons(self) -> List[str]:
        """Why a :meth:`submit` would be refused right now ([] =
        admissible).  The ONE definition both admission and readiness
        probes consume (the gateway's ``/v1/ready``), so a new refusal
        condition can never update one without the other.  Note
        ``queue_full`` is advisory for blocking submits — ``submit(
        block=True)`` waits a full queue out instead of refusing."""
        reasons = []
        if self._draining:
            reasons.append("draining")
        mx = self.config.serve.max_guard_events
        if (mx > 0 and self.monitor is not None
                and len(self.monitor.events) >= mx):
            reasons.append("admission_refused")
        if len(self.queue) >= self.queue.capacity:
            reasons.append("queue_full")
        return reasons

    def submit(self, req: ScenarioRequest, block: bool = False,
               timeout: Optional[float] = None) -> None:
        """Admit one request (raises :class:`QueueFull` at capacity,
        :class:`AdmissionRefused` when the health monitor has recorded
        ``serve.max_guard_events`` guard trips, :class:`ServerDraining`
        after :meth:`begin_drain`)."""
        if self._closed:
            raise RuntimeError("EnsembleServer is closed")
        self.validate_request(req)
        reasons = self.refusal_reasons()
        if "draining" in reasons:
            self.stats["refused"] += 1
            raise ServerDraining(
                f"server refused {req.id!r}: draining — admissions are "
                "closed while in-flight requests run to completion")
        if "admission_refused" in reasons:
            self.stats["refused"] += 1
            raise AdmissionRefused(
                f"server refused {req.id!r}: {len(self.monitor.events)} "
                f"guard events >= serve.max_guard_events="
                f"{self.config.serve.max_guard_events} — the "
                "deployment is unhealthy; investigate before admitting "
                "more traffic")
        # queue_full is the queue's own call: a blocking submit waits
        # it out, a non-blocking one gets QueueFull from queue.submit.
        req.submitted_wall = time.perf_counter()
        if self._trace_on:
            # The trace's root interval IS the latency interval: t0 is
            # the same stamp latency_s is measured from, so the leaf
            # sum telescopes to the reported latency by construction.
            # Registered BEFORE the queue publishes the request: the
            # serving thread may pop it the instant submit returns,
            # and a mark on an unregistered id is silently dropped —
            # an incomplete tree, found by review.
            self._traces[req.id] = obs_trace.RequestTrace(
                req.id, t0=req.submitted_wall)
        try:
            self.queue.submit(req, block=block, timeout=timeout)
        except Exception:
            self._traces.pop(req.id, None)
            raise
        if self._draining and self.queue.remove(req):
            self._traces.pop(req.id, None)
            # begin_drain raced the enqueue: serve_forever may already
            # have observed (empty queue, draining) and exited, which
            # would strand this request admitted-but-never-served.
            # Either we take it back out here and refuse it, or the
            # serving loop already popped it and will finish it.
            self.stats["refused"] += 1
            raise ServerDraining(
                f"server refused {req.id!r}: draining began during "
                "admission — the request was withdrawn, not stranded")
        self.stats["submitted"] += 1
        self.metrics.counter_inc("jaxstream_requests_submitted_total")
        # Forced (unthrottled) bundle re-commit on EVERY admission: the
        # last committed bundle must name every admitted-but-unfinished
        # request, so a SIGKILL at any instant leaves a manifest whose
        # open-request set includes this one.
        self.flight_commit(force=True)

    # -------------------------------------------------------------- serving
    def serve(self):
        """Drain the queue: pack -> masked segments -> refill, batch by
        batch, until no requests remain.  Returns ``self.results``."""
        try:
            while True:
                req = self.queue.pop()
                if req is None:
                    break
                self._run_batch(req)
        except BaseException as e:
            # Crash forensics (round 20): commit the black box and
            # stamp the sink BEFORE the writer flush below — a second
            # failure during flush must not cost us the bundle.
            self.flight_dump(reason=type(e).__name__)
            raise
        finally:
            if self._writer is not None:
                self._writer.flush()
        return self.results

    def serve_forever(self, stop=None, idle_wait: float = 0.01,
                      tick: Optional[Callable] = None,
                      idle_tick_s: float = 0.25):
        """Network-serving loop (round 14): drain batches until ``stop``
        (a ``threading.Event``) is set, parking ``idle_wait`` seconds
        between empty polls.  After :meth:`begin_drain`, exits once the
        queue is empty and every admitted request reached its final
        state (the writer is flushed on the way out, so results are
        delivered when this returns).  An escaping exception dumps the
        flight ring (crash bundle + ``flight``/``crash`` sink records)
        before propagating.

        ``tick``, when given, is called as ``tick(self)`` at every
        SEGMENT boundary — the autoscale hook: it observes queue depth
        + last-segment occupancy and may call :meth:`resize`.  Running
        it on the serving thread makes scaling decisions deterministic
        given queue state (no racing sampler thread); when a resize
        changes the active cap away from the running batch's bucket,
        that batch stops REFILLING — its in-flight members run to
        their own final step on the warm old-bucket executable — and
        packing resumes at the new cap with the very next batch (the
        live-resize migration path; no member is ever interrupted or
        re-queued).  While IDLE the hook runs at most once per
        ``idle_tick_s`` seconds, not once per poll: the policy's
        patience/cooldown counts are observations, and idle polls at
        ``idle_wait`` cadence would turn a few milliseconds of
        inter-burst silence into a full scale-down — exactly the flap
        the hysteresis exists to prevent.
        """
        last_idle_tick = float("-inf")
        try:
            while stop is None or not stop.is_set():
                req = self.queue.pop()
                if req is None and self._draining:
                    # A submit may have enqueued between the pop above
                    # and this flag read (its post-enqueue unwind then
                    # saw draining=False and kept the request): only
                    # exit the drain when the queue is confirmed empty
                    # AFTER the draining flag was observed.
                    req = self.queue.pop()
                    if req is None:
                        break
                if req is None:
                    # An idle server occupies zero slots; without this
                    # a final full segment would pin last_occupancy at
                    # 1.0 and block scale-down forever.
                    self.stats["last_occupancy"] = 0.0
                    now = time.monotonic()
                    if now - last_idle_tick >= idle_tick_s:
                        last_idle_tick = now
                        self._tick(tick)
                    time.sleep(idle_wait)
                    continue
                self._run_batch(req, tick=tick)
                last_idle_tick = float("-inf")
                if self._writer is not None:
                    self._writer.flush()
        except BaseException as e:
            self.flight_dump(reason=type(e).__name__)
            raise
        finally:
            if self._writer is not None:
                self._writer.flush()
        return self.results

    def _ensure_writer(self) -> BackgroundWriter:
        if self._writer is None or not self._writer.alive:
            self._writer = BackgroundWriter(
                max_pending=8, name=SERVE_WRITER_THREAD_NAME)
        return self._writer

    def _observe_perf(self) -> None:
        """Segment-boundary observability (round 19): the per-plan
        compile-event counters and — under ``serve.memory_watch`` —
        one device-memory poll.  Runs on the serving thread at the
        same cadence as the autoscale tick; the counter pass is a few
        dict/attribute reads when nothing compiled, and ZERO memory
        polling happens when the watcher is off."""
        # list(): the speculative compiler may insert a bucket
        # mid-iteration (round 21).
        for key, bk in list(self._buckets.items()):
            counts = [jax_compat.compile_count(f) for f in bk.jits()]
            cur = sum(c for c in counts if c is not None)
            prev = self._compiles_seen.get(key, 0)
            if cur > prev:
                self._compiles_seen[key] = cur
                self.metrics.counter_inc(
                    "jaxstream_compiles_total", cur - prev,
                    plan=(bk.proof.plan_key if bk.proof is not None
                          else f"{key[0]}/B{key[1]}"))
                flight.record(
                    "compile", delta=cur - prev,
                    plan=(bk.proof.plan_key if bk.proof is not None
                          else f"{key[0]}/B{key[1]}"))
        if self.memory_watcher is not None:
            rec = self.memory_watcher.poll()
            if rec is not None and rec.get("bytes_in_use"):
                flight.record("memory.watermark",
                              bytes_in_use=max(rec["bytes_in_use"]),
                              peak_bytes=max(rec["peak_bytes"] or [0]))

    def _tick(self, tick) -> None:
        """Boundary observers + the autoscale hook; a policy bug must
        not kill serving."""
        self._observe_perf()
        if tick is None:
            return
        try:
            tick(self)
        except Exception as e:
            log.warning("serve: autoscale tick failed (%s: %s)",
                        type(e).__name__, e)

    def _run_batch(self, first: ScenarioRequest, tick=None):
        """One batch's life: pack up to the best bucket, then segment /
        evict / extract / refill until every slot drains.  With a
        ``tick`` hook, a live resize away from this batch's bucket
        stops the refill so the batch winds down and serve_forever
        re-packs at the new cap."""
        cfg = self.config
        s, dt = cfg.serve, cfg.time.dt
        group = self._group(first)
        # The resize cap is read ONCE per batch (cap0): the packing
        # decision and the later wind-down comparison both derive from
        # the same read, so a resize from another thread between them
        # cannot be silently ignored.
        cap0 = self._active_max
        active = (tuple(b for b in self.buckets if b <= cap0)
                  or (min(self.buckets),))
        batch: List[ScenarioRequest] = [first]
        while len(batch) < max(active):
            r = self._pop(group)
            if r is None:
                break
            batch.append(r)
        B = next(b for b in active if b >= len(batch))
        bk = self._bucket(group, B)
        plan = bk.plan
        plan_key = bk.proof.plan_key if bk.proof is not None else None
        self.stats["batches"] += 1

        if self._trace_on:
            # queue.wait ends (and serve.pack opens) for the whole
            # initial batch at one stamp — the IC builds + the single
            # stack below are the batch's shared packing work.
            t_pack = time.perf_counter()
            for r in batch:
                self._mark(r.id, obs_trace.PACK, t_pack)
        trees = [self._member_tree(r) for r in batch]
        carry = bk.stack(trees + [trees[0]] * (B - len(batch)))
        slots: List[Optional[_Slot]] = (
            [_Slot(r) for r in batch] + [None] * (B - len(batch)))
        rem = np.zeros(B, np.int64)
        rem[:len(batch)] = [r.nsteps for r in batch]
        seg = s.segment_steps
        m_shards = plan.member_shards
        per_shard = B // m_shards
        chips = ([i // per_shard for i in range(B)]
                 if m_shards > 1 else None)
        # Live-resize wind-down (round 14): when the tick hook resizes
        # the cap away from this batch's packing decision (cap0,
        # above), the batch stops refilling — in-flight members finish
        # on the warm executable, then serve_forever re-packs at the
        # new cap.
        allow_refill = True

        while any(sl is not None for sl in slots):
            w0 = time.perf_counter()
            active_mask = [sl is not None for sl in slots]
            active_before = sum(active_mask)
            resident = [(i, sl.req.id) for i, sl in enumerate(slots)
                        if sl is not None]
            # The black box's in-flight view: updated BEFORE the
            # segment dispatches, so the crash bundle committed at
            # this boundary names exactly the members a kill during
            # the segment would strand.
            self._resident = [rid for _, rid in resident]
            carry, _, nf = bk.seg(carry, bk.put_rem(rem))
            # The health stream rides a HostFetch: its d2h copy chases
            # the segment's compute while the host does the boundary
            # work that does NOT depend on it — completion is pure
            # arithmetic on `rem`, so the incoming requests' initial
            # states can be built now, overlapping the device.
            nf_fetch = HostFetch(nf)
            new_rem = np.maximum(rem - seg, 0)
            n_free_pred = sum(
                1 for i, sl in enumerate(slots)
                if sl is not None and new_rem[i] == 0)
            prepped: List[tuple] = []
            for _ in range(n_free_pred if allow_refill else 0):
                r = self._pop(group)
                if r is None:
                    break
                if self._trace_on:
                    self._mark(r.id, obs_trace.PACK)
                prepped.append((r, self._member_tree(r)))
            hw0 = time.perf_counter()
            nf_host = np.asarray(nf_fetch.resolve(),
                                 np.float64).reshape(-1)
            hw1 = time.perf_counter()
            host_wait = hw1 - hw0
            wall = hw1 - w0
            steps_by_slot = rem - new_rem
            member_steps = int(np.sum(steps_by_slot))
            rem = new_rem
            for i, sl in enumerate(slots):
                if sl is not None:
                    sl.done = sl.req.nsteps - int(rem[i])
            if self._trace_on:
                # Three leaves per resident request per segment, at the
                # SHARED boundary stamps (w0/hw0/hw1): device compute,
                # health-stream host wait, then boundary work (evict/
                # extract/refill) which the next segment mark — or the
                # finalize mark — closes.  Segment leaves carry the
                # operator attribution: bucket, plan key, chip, steps.
                for i, rid in resident:
                    self._mark(rid, obs_trace.SEGMENT, w0, bucket=B,
                               plan=plan_key,
                               chip=(chips[i] if chips is not None
                                     else 0),
                               steps=int(steps_by_slot[i]))
                    self._mark(rid, obs_trace.HOST_WAIT, hw0)
                    self._mark(rid, obs_trace.BOUNDARY, hw1)
            # Per-segment progress stream (round 14, the gateway's
            # hook): one event per slot active during this segment,
            # emitted BEFORE any finalization from this boundary is
            # queued — no wall-clock fields, so the stream is
            # deterministic for a given packing.
            if self.on_segment is not None:
                progress = [
                    {"id": sl.req.id, "steps_done": sl.done,
                     "nsteps": sl.req.nsteps, "t": sl.done * dt,
                     "bucket": B, "done": bool(rem[i] == 0)}
                    for i, sl in enumerate(slots) if sl is not None]
                try:
                    self.on_segment(progress)
                except Exception as e:   # a subscriber bug must not
                    log.warning(         # kill the batch
                        "serve: on_segment hook failed (%s: %s)",
                        type(e).__name__, e)
            # Testing hook: host-side injection into the health STREAM
            # (never the state), mirroring observability.fault_step.
            fi = s.fault_member
            if (fi >= 0 and cfg.observability.fault_step >= 0
                    and not self._fault_fired and fi < B
                    and slots[fi] is not None
                    and slots[fi].done >= cfg.observability.fault_step):
                nf_host[fi] = max(nf_host[fi], 1.0)
                self._fault_fired = True
            completed = evicted = 0
            if self.monitor is not None:
                counts = np.where(
                    [sl is not None for sl in slots], nf_host, 0.0)
                steps = [sl.done if sl is not None else 0 for sl in slots]
                ts = [d * dt for d in steps]
                # 'halt' policy raises here (HealthError) — the writer
                # flush in serve()'s finally still lands prior
                # results, and the speculatively popped refill
                # requests go back to the queue head (they were
                # admitted; a guard trip must not lose them).
                try:
                    events = self.monitor.check_members(
                        steps, ts, counts, chips=chips)
                except BaseException:
                    if prepped:
                        self.queue.requeue(r for r, _ in prepped)
                    raise
                for ev in events:
                    i = ev["member"]
                    self._finish(slots[i], "evicted", None, ev)
                    rem[i] = 0
                    slots[i] = None
                    evicted += 1
                    if self._sink is not None:
                        # The event is already a schema-valid 'guard'
                        # record; under placement it names the chip.
                        self._sink_write(ev)
                if events:
                    self.metrics.counter_inc(
                        "jaxstream_guard_events_total", len(events))
            for i, sl in enumerate(slots):
                if sl is not None and rem[i] == 0:
                    fetch = HostFetch(bk.extract(carry, jnp.int32(i)))
                    self._finish(sl, "ok", fetch)
                    slots[i] = None
                    completed += 1
            refilled = 0
            if allow_refill:
                for i in range(B):
                    if slots[i] is not None:
                        continue
                    if prepped:
                        r, tree = prepped.pop(0)
                    else:
                        r = self._pop(group)
                        if r is None:
                            break
                        if self._trace_on:
                            self._mark(r.id, obs_trace.PACK)
                        tree = self._member_tree(r)
                    carry = bk.inject(carry, jnp.int32(i),
                                      bk.put_member(tree))
                    rem[i] = r.nsteps
                    slots[i] = _Slot(r)
                    refilled += 1
            # Prepped requests can never be left over: free slots >=
            # predicted completions (eviction only adds frees) and the
            # refill loop scans every slot, consuming prepped first.
            # A popped request silently dropped would be a lost-
            # traffic bug, so the invariant fails loudly.
            assert not prepped, (
                "serve refill invariant broken: speculatively popped "
                f"requests left unslotted: {[r.id for r, _ in prepped]}")
            st = self.stats
            st["segments"] += 1
            st["last_occupancy"] = active_before / B
            st["refills"] += refilled
            st["member_steps"] += member_steps
            st["occupancy_sum"] += active_before / B
            st["utilization_sum"] += member_steps / (B * seg)
            st["completed"] += completed
            st["evicted"] += evicted
            st["host_wait_s"] += host_wait
            m = self.metrics
            m.counter_inc("jaxstream_segments_total")
            if member_steps:
                m.counter_inc("jaxstream_member_steps_total",
                              member_steps)
            if completed:
                m.counter_inc("jaxstream_requests_completed_total",
                              completed, status="ok")
            if evicted:
                m.counter_inc("jaxstream_requests_completed_total",
                              evicted, status="evicted")
            m.gauge_set("jaxstream_queue_depth", len(self.queue))
            m.gauge_set("jaxstream_occupancy", active_before / B)
            m.observe("jaxstream_segment_wall_seconds", wall,
                      buckets=WALL_BUCKETS_S)
            m.observe("jaxstream_host_wait_seconds", host_wait,
                      buckets=HOST_WAIT_BUCKETS_S)
            for j in range(m_shards):
                occ_j = (sum(active_mask[j * per_shard:
                                         (j + 1) * per_shard])
                         / per_shard)
                util_j = (float(np.sum(
                    steps_by_slot[j * per_shard:(j + 1) * per_shard]))
                    / (per_shard * seg))
                m.gauge_set("jaxstream_chip_occupancy", occ_j,
                            chip=str(j))
                m.gauge_set("jaxstream_chip_utilization", util_j,
                            chip=str(j))
            if self._sink is not None:
                rec = {
                    "kind": "serve", "bucket": B, "group": group,
                    "plan": (bk.proof.plan_key
                             if bk.proof is not None else None),
                    "proof_verdict": (bk.proof.verdict
                                      if bk.proof is not None
                                      else None),
                    "occupancy": round(active_before / B, 4),
                    "utilization": round(member_steps / (B * seg), 4),
                    "queue_depth": len(self.queue),
                    "wall_s": round(wall, 6),
                    "host_wait_s": round(host_wait, 6),
                    "completed": completed, "evicted": evicted,
                    "refilled": refilled, "member_steps": member_steps,
                }
                if self._trace_on:
                    # Which requests this segment advanced (slot
                    # order) — the dashboard's live in-flight view.
                    rec["trace_ids"] = [
                        obs_trace.trace_id_for(rid)
                        for _, rid in resident]
                if plan.sharded:
                    rec["placement"] = plan.mode
                    rec["devices"] = plan.num_devices
                    rec["chip_occupancy"] = [
                        round(sum(active_mask[j * per_shard:
                                              (j + 1) * per_shard])
                              / per_shard, 4)
                        for j in range(m_shards)]
                    rec["chip_utilization"] = [
                        round(float(np.sum(
                            steps_by_slot[j * per_shard:
                                          (j + 1) * per_shard]))
                            / (per_shard * seg), 4)
                        for j in range(m_shards)]
                self._sink_write(rec)
            flight.record("serve.boundary", bucket=B,
                          active=active_before, completed=completed,
                          evicted=evicted, refilled=refilled,
                          queue_depth=len(self.queue))
            # Autoscale hook, once per segment boundary — queue depth
            # and last_occupancy are fresh here.  A resize ends this
            # batch's refill (see cap0 note above).
            self._tick(tick)
            # Post-boundary resident set (completions/evictions above
            # freed slots; refill re-occupied some) before the live
            # bundle re-commit — throttled, so a fast segment cadence
            # costs at most ~4 commits/second.
            self._resident = [sl.req.id for sl in slots
                              if sl is not None]
            self.flight_commit()
            if allow_refill and self._active_max != cap0:
                allow_refill = False
                log.info("serve: active cap resized %d -> %d mid-"
                         "batch; batch (B=%d) winds down without "
                         "refilling", cap0, self._active_max, B)

    def _mark(self, rid: str, name: str, t: Optional[float] = None,
              **attrs) -> None:
        """Add one trace mark for an in-flight request (no-op for
        untraced ids — e.g. requests admitted before a restart)."""
        tr = self._traces.get(rid)
        if tr is not None:
            tr.mark(name, t, **attrs)

    def _finish(self, slot: _Slot, status: str,
                fetch: Optional[HostFetch], event: Optional[dict] = None):
        """Queue one request's finalization on the background writer —
        the d2h copies (already in flight) resolve there, overlapping
        the next segment's compute.  The latency stamp moved (round
        17) from here to :meth:`_finalize`'s result-ready instant, so
        the reported latency covers the writer-queue wait and the d2h
        result fetch — the same interval the request's span tree
        tiles."""
        if self._trace_on:
            self._mark(slot.req.id, obs_trace.FINALIZE_WAIT)
        self._ensure_writer().submit(
            self._finalize, slot.req, status, slot.done, fetch, event)

    def _finalize(self, req: ScenarioRequest, status: str, done: int,
                  fetch: Optional[HostFetch], event: Optional[dict]):
        tr = self._traces.pop(req.id, None) if self._trace_on else None
        if tr is not None:
            tr.mark(obs_trace.RESULT_FETCH)
        fields = {}
        if fetch is not None:
            host = fetch.resolve()
            fields = {k: host[k] for k in req.outputs if k in host}
        if tr is not None:
            tr.mark(obs_trace.WRITER_FLUSH)
        t_final = done * self.config.time.dt
        out_dir = self.config.serve.output_dir
        if out_dir and fields:
            from ..io.history import HistoryWriter

            hw = HistoryWriter(
                os.path.join(out_dir, req.id),
                attrs={"request": req.id, "ic": req.ic,
                       "nsteps": req.nsteps, "status": status})
            hw.append(fields, t_final)
        # The result-ready instant: latency_s and the trace root close
        # on the SAME stamp, so the span tree's leaf sum telescopes to
        # the reported latency exactly (obs.trace module docstring).
        t_end = time.perf_counter()
        latency = (t_end - req.submitted_wall
                   if req.submitted_wall is not None else 0.0)
        res = RequestResult(
            id=req.id, ic=req.ic, nsteps=req.nsteps, status=status,
            t_final=t_final, steps_run=done, latency_s=latency,
            fields=fields, guard_event=event)
        if tr is not None:
            spans = tr.finish(status, t_end)
            if self._sink is not None:
                for sp in spans:
                    self._sink_write(sp)
            else:
                # Only sink-less (direct/embedded) servers retain the
                # spans in memory — a sinked deployment already
                # persisted them, and retaining every request's spans
                # forever would grow without bound under continuous
                # traffic (review finding).
                self.trace_spans[req.id] = spans
        self.metrics.observe("jaxstream_request_latency_seconds",
                             latency, buckets=LATENCY_BUCKETS_S,
                             status=status)
        self.results[req.id] = res
        if self.on_result is not None:
            self.on_result(res)

    # ------------------------------------------------------------ reporting
    @property
    def occupancy_mean(self) -> float:
        n = self.stats["segments"]
        return self.stats["occupancy_sum"] / n if n else 0.0

    @property
    def utilization_mean(self) -> float:
        n = self.stats["segments"]
        return self.stats["utilization_sum"] / n if n else 0.0

    def latencies(self) -> np.ndarray:
        return np.asarray(sorted(
            r.latency_s for r in self.results.values()))


def serve_requests(config, requests, warm_groups=None):
    """One-call serving: build a server, admit ``requests`` (blocking
    at the queue bound), drain, close.  Returns the server (results in
    ``server.results``, counters in ``server.stats``)."""
    server = EnsembleServer(config)
    try:
        if warm_groups:
            server.warmup(groups=warm_groups)
        pending = list(requests)
        while pending:
            # Admit what fits, serve a batch, repeat — producer-side
            # backpressure without a second thread.
            while pending:
                try:
                    server.submit(pending[0])
                except QueueFull:
                    break
                pending.pop(0)
            req = server.queue.pop()
            if req is not None:
                server._run_batch(req)
        server.serve()
    finally:
        server.close()
    return server
