"""The continuous-batching ensemble server.

One :class:`EnsembleServer` deployment fixes the grid, dt and physics
(the ``grid:``/``time:``/``physics:``/``model:`` config sections) and
serves :class:`ScenarioRequest` traffic — IC family, perturbation
seed, run length, output subset — by packing requests into the member
axis of the round-7 batched steppers:

* **Shape-bucketed batching**: batch sizes come from a fixed bucket
  set (``serve.buckets``, default ``1,4,16``) and every bucket's
  masked-segment executable is compiled once and kept warm
  (``JAXSTREAM_COMPILE_CACHE`` persists even that across restarts), so
  steady-state serving triggers ZERO recompiles —
  :meth:`EnsembleServer.compile_count` is the proof surface the tests
  assert on.
* **Per-member run-length masking** (:func:`jaxstream.stepping.
  integrate_masked`): requests of any length share a batch; a member
  that finishes mid-segment is frozen bit-for-bit at its own final
  step and its slot is refilled from the queue at the next segment
  boundary instead of idling until the slowest member drains.
* **Slot-refill invariant**: refills happen ONLY at segment boundaries
  — injections are ``dynamic_update_slice`` on the member axis of the
  live carry, so the carry layout (and therefore the compiled
  executable) never changes (docs/DESIGN.md "Continuous batching").
* **Health-guarded eviction**: a per-member nonfinite count rides the
  compiled segment; a failing member is evicted alone (guard event
  carries the member index, ``serve.guards: evict``) while the rest of
  the batch keeps integrating, and admission control refuses NEW
  traffic once ``serve.max_guard_events`` trips have accumulated.
* **Async result streaming**: per-member extraction starts its
  device->host copies behind the next segment's dispatch
  (:class:`jaxstream.io.async_pipeline.HostFetch`) and lands on the
  bounded :class:`...BackgroundWriter` — results never stall the
  batch.

Scope (deliberate, documented): single-process, single-chip serving of
the dense covariant shallow-water tier — the regime bench r05 showed
batching pays in (members x moderate resolution).  Requests are packed
only with requests of the same *batching group* (``tc5`` bakes an
orography array into the stepper as a compile-time static; the flat
families tc2/tc6/galewsky share one group) — group-local FIFO keeps
that deterministic.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Config, load_config
from ..geometry.cubed_sphere import build_grid
from ..io.async_pipeline import BackgroundWriter, HostFetch
from ..obs.monitor import HealthMonitor
from ..obs.sink import TelemetrySink, run_manifest
from ..physics import initial_conditions as ics
from ..stepping import integrate_masked, vmap_ensemble
from ..utils.logging import get_logger
from .queue import AdmissionRefused, QueueFull, RequestQueue
from .request import RequestResult, ScenarioRequest

__all__ = ["EnsembleServer", "serve_requests"]

log = get_logger(__name__)

#: Thread name of the server's background result writer.
SERVE_WRITER_THREAD_NAME = "jaxstream-serve-writer"


def _member_nonfinite(y, axes):
    """Per-member nonfinite count over every carry leaf: ``(B,)``.

    The on-device health stream of the serving loop — one small vector
    per segment, fetched at the boundary the refill already pays for.
    """
    total = None
    for k, ax in axes.items():
        a = y[k]
        bad = jnp.sum((~jnp.isfinite(a)).astype(jnp.int32),
                      axis=tuple(i for i in range(a.ndim) if i != ax))
        total = bad if total is None else total + bad
    return total


class _Slot:
    """One member slot's host bookkeeping."""

    def __init__(self, req: ScenarioRequest):
        self.req = req
        self.done = 0                       # steps executed so far

    @property
    def remaining(self) -> int:
        return self.req.nsteps - self.done


class _Bucket:
    """One (group, B) compiled runtime: segment/extract/inject jits."""

    def __init__(self, group: str, B: int, seg_fn, extract_fn, inject_fn,
                 axes, init_carry, member_carry):
        self.group = group
        self.B = B
        self.seg = seg_fn
        self.extract = extract_fn
        self.inject = inject_fn
        self.axes = axes
        self.init_carry = init_carry        # list of B states -> carry
        self.member_carry = member_carry    # interior state -> member leaves

    def jits(self):
        return (self.seg, self.extract, self.inject)


class EnsembleServer:
    """Config -> warm bucketed steppers -> packed request serving.

    ``config`` is the standard :class:`jaxstream.config.Config` surface
    (grid/time/physics/model + the ``serve:`` block); ``on_result`` is
    called with each :class:`RequestResult` from the background writer
    thread (after its fields are on host).  Use as a context manager,
    or call :meth:`close` when done.
    """

    def __init__(self, config=None,
                 on_result: Optional[Callable] = None):
        self.config: Config = load_config(config)
        cfg = self.config
        s = cfg.serve
        if cfg.model.numerics != "dense":
            raise ValueError(
                "the serving tier runs the dense covariant solvers; "
                "set model.numerics: dense")
        if cfg.model.name != "shallow_water_cov":
            # 'auto' would make the same config's Simulation build the
            # CARTESIAN model for tc2/tc5 — a server that silently
            # swapped models would break the documented B=1
            # bitwise-vs-Simulation contract.
            raise ValueError(
                f"model.name={cfg.model.name!r}: the serving tier runs "
                "the covariant production solver only — set model.name: "
                "shallow_water_cov (so an unbatched Simulation of the "
                "same config is the bitwise reference)")
        if (cfg.precision.stage != "f32"
                or cfg.precision.strips not in ("auto", "f32")
                or cfg.precision.carry != "f32"):
            raise ValueError(
                "the serving tier runs f32 numerics; the precision: "
                "block is not threaded through the bucket steppers yet "
                "— drop it rather than silently serving f32")
        if cfg.parallelization.temporal_block > 1:
            raise ValueError(
                "parallelization.temporal_block > 1 is not wired into "
                "the serving tier (per-member masking counts single "
                "steps); set temporal_block: 1")
        if (cfg.parallelization.use_shard_map
                or cfg.parallelization.tiles_per_edge > 1):
            raise ValueError(
                "the serving tier is single-chip for now (the member "
                "axis IS the batch dimension; scale out with one "
                "server process per chip) — drop use_shard_map/"
                "tiles_per_edge from the parallelization block")
        if s.guards not in ("off", "evict", "halt"):
            raise ValueError(
                f"serve.guards={s.guards!r}; valid: 'off', 'evict', "
                "'halt'")
        try:
            self.buckets = tuple(sorted(
                {int(b) for b in str(s.buckets).split(",") if b.strip()}))
        except ValueError:
            raise ValueError(
                f"serve.buckets={s.buckets!r} must be a comma-separated "
                "list of positive ints") from None
        if not self.buckets or min(self.buckets) < 1:
            raise ValueError(
                f"serve.buckets={s.buckets!r} must name at least one "
                "positive batch size")
        if s.segment_steps < 1:
            raise ValueError(
                f"serve.segment_steps must be >= 1, got {s.segment_steps}")

        halo = cfg.grid.halo
        if cfg.model.scheme == "ppm":
            halo = max(halo, 3)
        dtype = {"float32": jnp.float32, "float64": jnp.float64,
                 "bfloat16": jnp.bfloat16}[cfg.grid.dtype]
        self.grid = build_grid(cfg.grid.n, halo=halo,
                               radius=cfg.grid.radius, dtype=dtype,
                               metrics=cfg.grid.metrics)
        self.queue = RequestQueue(s.queue_capacity)
        self.monitor = (HealthMonitor(
            (), policy="warn" if s.guards == "evict" else "halt")
            if s.guards != "off" else None)
        self.on_result = on_result
        self.results: Dict[str, RequestResult] = {}
        self.stats = {
            "submitted": 0, "refused": 0, "completed": 0, "evicted": 0,
            "batches": 0, "segments": 0, "refills": 0,
            "member_steps": 0, "occupancy_sum": 0.0,
            "utilization_sum": 0.0, "warmup_compiles": 0,
        }
        self._models: Dict[str, object] = {}
        self._ics: Dict[str, tuple] = {}
        self._impls: Dict[str, str] = {}
        self._buckets: Dict[tuple, _Bucket] = {}
        self._writer: Optional[BackgroundWriter] = None
        self._sink = None
        if s.sink:
            self._sink = TelemetrySink(s.sink, run_manifest(
                config={
                    "serving": True, "grid_n": cfg.grid.n,
                    "dt": cfg.time.dt, "buckets": list(self.buckets),
                    "segment_steps": s.segment_steps,
                    "queue_capacity": s.queue_capacity,
                    "guards": s.guards,
                }))
        self._fault_fired = False
        self._closed = False

    # ------------------------------------------------------------ lifecycle
    def close(self):
        """Drain the result writer and close the telemetry sink."""
        if self._closed:
            return
        self._closed = True
        if self._writer is not None:
            w, self._writer = self._writer, None
            w.close()
        if self._sink is not None:
            self._sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- building
    def _ic(self, family: str):
        """Cached base IC fields ``(h_ext, v_ext, b_ext)`` per family."""
        if family not in self._ics:
            p, m, g = self.config.physics, self.config.model, self.grid
            b_ext = None
            if family == "tc2":
                h, v = ics.williamson_tc2(g, p.gravity, p.omega,
                                          alpha_rot=m.ic_angle)
            elif family == "tc5":
                h, v, b_ext = ics.williamson_tc5(g, p.gravity, p.omega)
            elif family == "tc6":
                h, v = ics.williamson_tc6(g, p.gravity, p.omega)
            else:
                h, v = ics.galewsky(g, p.gravity, p.omega)
            self._ics[family] = (h, v, b_ext)
        return self._ics[family]

    def _model(self, group: str):
        """Cached model per batching group (orography is stepper-baked)."""
        if group not in self._models:
            from ..models.shallow_water_cov import CovariantShallowWater

            cfg = self.config
            p, m = cfg.physics, cfg.model
            b_ext = self._ic("tc5")[2] if group == "oro" else None
            self._models[group] = CovariantShallowWater(
                self.grid, gravity=p.gravity, omega=p.omega, b_ext=b_ext,
                scheme=m.scheme, limiter=m.limiter,
                nu4=p.hyperdiffusion, backend=m.backend)
        return self._models[group]

    def _request_state(self, req: ScenarioRequest):
        """A request's interior initial state (deterministic in seed)."""
        h, v, _ = self._ic(req.ic)
        if req.seed >= 0 and req.amplitude != 0.0:
            h = ics.perturbed_ensemble(self.grid, h, 2, seed=req.seed,
                                       amplitude=req.amplitude)[1]
        return self._model(req.group).initial_state(h, v)

    def _build_bucket(self, group: str, B: int, impl: str) -> _Bucket:
        cfg = self.config
        model = self._model(group)
        dt, seg = cfg.time.dt, cfg.serve.segment_steps
        if impl == "fused":
            step = model.make_fused_step(dt, ensemble=B)
            axes = {"h": 0, "u": 1, "strips_sn": 0, "strips_we": 0}
            member_carry = model.compact_state
            init_carry = (lambda states:
                          model.ensemble_compact_state(
                              model.stack_ensemble(states)))
        else:
            base = model.make_step(dt, cfg.time.scheme)
            axes = {"h": 0, "u": 1}
            step = vmap_ensemble(base, axes)
            member_carry = lambda st: st
            init_carry = model.stack_ensemble

        def seg_body(y, rem):
            y, _, rem = integrate_masked(step, y, 0.0, rem, seg, dt, axes)
            return y, rem, _member_nonfinite(y, axes)

        def extract_body(y, idx):
            return {k: jnp.take(y[k], idx, axis=axes[k])
                    for k in ("h", "u")}

        def inject_body(y, idx, member):
            out = dict(y)
            for k, ax in axes.items():
                upd = jnp.expand_dims(member[k].astype(y[k].dtype), ax)
                out[k] = jax.lax.dynamic_update_slice_in_dim(
                    y[k], upd, idx, axis=ax)
            return out

        donate = (0,) if cfg.serve.donate else ()
        return _Bucket(group, B,
                       jax.jit(seg_body, donate_argnums=donate),
                       jax.jit(extract_body), jax.jit(inject_body),
                       axes, init_carry, member_carry)

    def _bucket(self, group: str, B: int) -> _Bucket:
        """The warm (group, B) runtime — built, compiled and probed on
        first use (fused kernels where they execute, the vmapped
        classic stepper otherwise; the probe run IS the warmup)."""
        key = (group, B)
        bk = self._buckets.get(key)
        if bk is not None:
            return bk
        cfg = self.config
        impls = [self._impls[group]] if group in self._impls else []
        if not impls:
            fused_ok = (cfg.time.scheme == "ssprk3"
                        and cfg.model.backend.startswith("pallas")
                        and self.config.physics.hyperdiffusion == 0.0)
            impls = (["fused", "vmap"] if fused_ok else ["vmap"])
        err = None
        for impl in impls:
            try:
                bk = self._build_bucket(group, B, impl)
                self._warm_bucket(bk)
                self._impls[group] = impl
                self._buckets[key] = bk
                self.stats["warmup_compiles"] = self.compile_count()
                log.info("serve: bucket (%s, B=%d) warm (%s stepper)",
                         group, B, impl)
                return bk
            except Exception as e:
                err = e
                if impl != impls[-1]:
                    log.warning(
                        "serve: %s stepper unavailable for bucket "
                        "(%s, B=%d) (%s: %s); falling back",
                        impl, group, B, type(e).__name__, e)
        raise RuntimeError(
            f"serve: no stepper builds for bucket ({group}, B={B})"
        ) from err

    def _warm_bucket(self, bk: _Bucket):
        """One dummy masked segment + extract + inject: compiles (and
        probes) every executable the bucket will ever run."""
        family = "tc5" if bk.group == "oro" else "tc2"
        st = self._model(bk.group).initial_state(*self._ic(family)[:2])
        carry = bk.init_carry([st] * bk.B)
        rem = jnp.zeros((bk.B,), jnp.int32
                        ).at[0].set(self.config.serve.segment_steps)
        carry, _, nf = bk.seg(carry, rem)
        jax.block_until_ready(nf)
        ex = bk.extract(carry, jnp.int32(0))
        carry = bk.inject(carry, jnp.int32(0), bk.member_carry(st))
        jax.block_until_ready((ex["h"], carry["h"]))

    def warmup(self, groups=("flat",), buckets=None):
        """Pre-compile the bucket set so the first real traffic hits
        warm executables (steady-state = zero recompiles).  ``groups``:
        which batching groups to warm ('flat' and/or 'oro')."""
        for g in groups:
            if g not in ("flat", "oro"):
                raise ValueError(f"unknown batching group {g!r}")
            for B in (buckets or self.buckets):
                self._bucket(g, B)
        return self.compile_count()

    def compile_count(self) -> int:
        """Total compiled executables across every bucket's jits — the
        zero-steady-state-recompile assertion surface (-1 when the jax
        build exposes no cache-size introspection)."""
        total = 0
        for bk in self._buckets.values():
            for f in bk.jits():
                cs = getattr(f, "_cache_size", None)
                if cs is None:
                    return -1
                total += cs()
        return total

    # ------------------------------------------------------------ admission
    def submit(self, req: ScenarioRequest, block: bool = False,
               timeout: Optional[float] = None) -> None:
        """Admit one request (raises :class:`QueueFull` at capacity,
        :class:`AdmissionRefused` when the health monitor has recorded
        ``serve.max_guard_events`` guard trips)."""
        if self._closed:
            raise RuntimeError("EnsembleServer is closed")
        mx = self.config.serve.max_guard_events
        if (mx > 0 and self.monitor is not None
                and len(self.monitor.events) >= mx):
            self.stats["refused"] += 1
            raise AdmissionRefused(
                f"server refused {req.id!r}: {len(self.monitor.events)} "
                f"guard events >= serve.max_guard_events={mx} — the "
                "deployment is unhealthy; investigate before admitting "
                "more traffic")
        req.submitted_wall = time.perf_counter()
        self.queue.submit(req, block=block, timeout=timeout)
        self.stats["submitted"] += 1

    # -------------------------------------------------------------- serving
    def serve(self):
        """Drain the queue: pack -> masked segments -> refill, batch by
        batch, until no requests remain.  Returns ``self.results``."""
        try:
            while True:
                req = self.queue.pop()
                if req is None:
                    break
                self._run_batch(req)
        finally:
            if self._writer is not None:
                self._writer.flush()
        return self.results

    def _ensure_writer(self) -> BackgroundWriter:
        if self._writer is None or not self._writer.alive:
            self._writer = BackgroundWriter(
                max_pending=8, name=SERVE_WRITER_THREAD_NAME)
        return self._writer

    def _run_batch(self, first: ScenarioRequest):
        """One batch's life: pack up to the best bucket, then segment /
        evict / extract / refill until every slot drains."""
        cfg = self.config
        s, dt = cfg.serve, cfg.time.dt
        group = first.group
        batch: List[ScenarioRequest] = [first]
        while len(batch) < max(self.buckets):
            r = self.queue.pop_group(group)
            if r is None:
                break
            batch.append(r)
        B = next(b for b in self.buckets if b >= len(batch))
        bk = self._bucket(group, B)
        self.stats["batches"] += 1

        states = [self._request_state(r) for r in batch]
        carry = bk.init_carry(states + [states[0]] * (B - len(batch)))
        slots: List[Optional[_Slot]] = (
            [_Slot(r) for r in batch] + [None] * (B - len(batch)))
        rem = np.zeros(B, np.int64)
        rem[:len(batch)] = [r.nsteps for r in batch]
        seg = s.segment_steps

        while any(sl is not None for sl in slots):
            w0 = time.perf_counter()
            active_before = sum(sl is not None for sl in slots)
            carry, _, nf = bk.seg(carry, jnp.asarray(rem, jnp.int32))
            nf_host = np.asarray(jax.device_get(nf), np.float64)
            wall = time.perf_counter() - w0
            new_rem = np.maximum(rem - seg, 0)
            member_steps = int(np.sum(rem - new_rem))
            rem = new_rem
            for i, sl in enumerate(slots):
                if sl is not None:
                    sl.done = sl.req.nsteps - int(rem[i])
            # Testing hook: host-side injection into the health STREAM
            # (never the state), mirroring observability.fault_step.
            fi = s.fault_member
            if (fi >= 0 and cfg.observability.fault_step >= 0
                    and not self._fault_fired and fi < B
                    and slots[fi] is not None
                    and slots[fi].done >= cfg.observability.fault_step):
                nf_host[fi] = max(nf_host[fi], 1.0)
                self._fault_fired = True
            completed = evicted = 0
            if self.monitor is not None:
                counts = np.where(
                    [sl is not None for sl in slots], nf_host, 0.0)
                steps = [sl.done if sl is not None else 0 for sl in slots]
                ts = [d * dt for d in steps]
                # 'halt' policy raises here (HealthError) — the writer
                # flush in serve()'s finally still lands prior results.
                for ev in self.monitor.check_members(steps, ts, counts):
                    i = ev["member"]
                    self._finish(slots[i], "evicted", None, ev)
                    rem[i] = 0
                    slots[i] = None
                    evicted += 1
            for i, sl in enumerate(slots):
                if sl is not None and rem[i] == 0:
                    fetch = HostFetch(bk.extract(carry, jnp.int32(i)))
                    self._finish(sl, "ok", fetch)
                    slots[i] = None
                    completed += 1
            refilled = 0
            for i in range(B):
                if slots[i] is not None:
                    continue
                r = self.queue.pop_group(group)
                if r is None:
                    break
                carry = bk.inject(carry, jnp.int32(i),
                                  bk.member_carry(self._request_state(r)))
                rem[i] = r.nsteps
                slots[i] = _Slot(r)
                refilled += 1
            st = self.stats
            st["segments"] += 1
            st["refills"] += refilled
            st["member_steps"] += member_steps
            st["occupancy_sum"] += active_before / B
            st["utilization_sum"] += member_steps / (B * seg)
            st["completed"] += completed
            st["evicted"] += evicted
            if self._sink is not None:
                self._sink.write({
                    "kind": "serve", "bucket": B, "group": group,
                    "occupancy": round(active_before / B, 4),
                    "utilization": round(member_steps / (B * seg), 4),
                    "queue_depth": len(self.queue),
                    "wall_s": round(wall, 6),
                    "completed": completed, "evicted": evicted,
                    "refilled": refilled, "member_steps": member_steps,
                })

    def _finish(self, slot: _Slot, status: str,
                fetch: Optional[HostFetch], event: Optional[dict] = None):
        """Queue one request's finalization on the background writer —
        the d2h copies (already in flight) resolve there, overlapping
        the next segment's compute."""
        latency = (time.perf_counter() - slot.req.submitted_wall
                   if slot.req.submitted_wall is not None else 0.0)
        self._ensure_writer().submit(
            self._finalize, slot.req, status, slot.done, latency, fetch,
            event)

    def _finalize(self, req: ScenarioRequest, status: str, done: int,
                  latency: float, fetch: Optional[HostFetch],
                  event: Optional[dict]):
        fields = {}
        if fetch is not None:
            host = fetch.resolve()
            fields = {k: host[k] for k in req.outputs if k in host}
        t_final = done * self.config.time.dt
        res = RequestResult(
            id=req.id, ic=req.ic, nsteps=req.nsteps, status=status,
            t_final=t_final, steps_run=done, latency_s=latency,
            fields=fields, guard_event=event)
        out_dir = self.config.serve.output_dir
        if out_dir and fields:
            from ..io.history import HistoryWriter

            hw = HistoryWriter(
                os.path.join(out_dir, req.id),
                attrs={"request": req.id, "ic": req.ic,
                       "nsteps": req.nsteps, "status": status})
            hw.append(fields, t_final)
        self.results[req.id] = res
        if self.on_result is not None:
            self.on_result(res)

    # ------------------------------------------------------------ reporting
    @property
    def occupancy_mean(self) -> float:
        n = self.stats["segments"]
        return self.stats["occupancy_sum"] / n if n else 0.0

    @property
    def utilization_mean(self) -> float:
        n = self.stats["segments"]
        return self.stats["utilization_sum"] / n if n else 0.0

    def latencies(self) -> np.ndarray:
        return np.asarray(sorted(
            r.latency_s for r in self.results.values()))


def serve_requests(config, requests, warm_groups=None):
    """One-call serving: build a server, admit ``requests`` (blocking
    at the queue bound), drain, close.  Returns the server (results in
    ``server.results``, counters in ``server.stats``)."""
    server = EnsembleServer(config)
    try:
        if warm_groups:
            server.warmup(groups=warm_groups)
        pending = list(requests)
        while pending:
            # Admit what fits, serve a batch, repeat — producer-side
            # backpressure without a second thread.
            while pending:
                try:
                    server.submit(pending[0])
                except QueueFull:
                    break
                pending.pop(0)
            req = server.queue.pop()
            if req is not None:
                server._run_batch(req)
        server.serve()
    finally:
        server.close()
    return server
