"""Scenario requests and their results — the server's wire schema.

A request names WHAT to simulate (IC family, perturbation seed and
amplitude, run length) and WHICH outputs to return; everything else
(grid, dt, physics) is fixed per server deployment, which is what makes
requests packable into one batched stepper.  The families are the
Galewsky/Williamson scenario set (Galewsky et al. 2004; Williamson et
al. 1992) the repo's IC module provides.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

__all__ = ["SWE_FAMILIES", "ScenarioRequest", "RequestResult"]

#: IC families a server can pack, keyed to their batching group: tc5
#: carries orography, the rest are flat.  Since round 12 the groups
#: only matter under ``serve.group_by_orography: true`` (the parity
#: mode that bakes orography into the stepper as a compile-time
#: static); the default server threads the mountain as a traced
#: per-member field (zeros for the flat families) so EVERY family
#: packs into one batch in strict queue FIFO order.
SWE_FAMILIES: Dict[str, str] = {
    "tc2": "flat",
    "tc5": "oro",
    "tc6": "flat",
    "galewsky": "flat",
    # Round 18: raw-array initial conditions — the request carries the
    # full interior prognostic state itself (``state``: h (6, n, n), u
    # (2, 6, n, n)), byte-preserved through the gateway's b64 array
    # codec.  The restart/assimilation primitive: a checkpointed
    # member or an EnKF analysis state re-enters the serving loop as
    # an ordinary request.  Flat-bottom (no orography is implied by an
    # array; tc5 continuations ride the traced per-member mountain of
    # the mixed-batch default only if resubmitted as 'tc5').
    "array": "flat",
}

#: Fields a request may ask back (interior prognostics).
OUTPUT_FIELDS = ("h", "u")


@dataclasses.dataclass
class ScenarioRequest:
    """One user scenario: IC family + perturbation + run length.

    ``seed``/``amplitude`` perturb the family's base height field with
    the deterministic ``perturbed_ensemble`` recipe (``amplitude = 0``
    or ``seed < 0`` = the unperturbed base IC).  ``nsteps`` is the run
    length in stepper calls — requests of ANY length pack together
    (per-member masking handles the remainders).  ``outputs`` is the
    subset of interior prognostic fields returned/written.
    """
    id: str
    ic: str = "tc5"
    nsteps: int = 1
    seed: int = -1
    amplitude: float = 1.0e-3
    outputs: Tuple[str, ...] = ("h",)
    #: Raw-array initial conditions (``ic: "array"``, round 18): the
    #: interior prognostic state ``{"h": (6, n, n), "u": (2, 6, n,
    #: n)}`` as host numpy arrays.  Shape/dtype are validated against
    #: the deployment's grid at admission (:meth:`EnsembleServer.
    #: validate_request`) — a mismatched array must land as a typed
    #: 400, never mid-batch on the serving thread.
    state: Optional[Dict] = None
    #: wall-clock bookkeeping, stamped by the server
    submitted_wall: Optional[float] = None

    def __post_init__(self):
        # Numeric fields are validated HERE, not where they are first
        # used: a wrong-typed seed/amplitude that passed admission
        # would otherwise raise mid-batch on the serving thread and
        # kill the whole deployment for one bad request (round 14 —
        # the gateway maps this ValueError to a typed 400).
        for fname in ("nsteps", "seed"):
            v = getattr(self, fname)
            if not isinstance(v, int) or isinstance(v, bool):
                raise ValueError(
                    f"request {self.id!r}: {fname} must be an int, "
                    f"got {type(v).__name__}")
        if (isinstance(self.amplitude, bool)
                or not isinstance(self.amplitude, (int, float))):
            raise ValueError(
                f"request {self.id!r}: amplitude must be a number, "
                f"got {type(self.amplitude).__name__}")
        self.amplitude = float(self.amplitude)
        if self.ic not in SWE_FAMILIES:
            raise ValueError(
                f"request {self.id!r}: unknown ic {self.ic!r}; valid: "
                f"{sorted(SWE_FAMILIES)}")
        if self.nsteps < 1:
            raise ValueError(
                f"request {self.id!r}: nsteps must be >= 1, got "
                f"{self.nsteps}")
        self.outputs = tuple(self.outputs)
        bad = [f for f in self.outputs if f not in OUTPUT_FIELDS]
        if bad:
            raise ValueError(
                f"request {self.id!r}: unknown output fields {bad}; "
                f"valid: {list(OUTPUT_FIELDS)}")
        if self.ic == "array":
            import numpy as np

            if not isinstance(self.state, dict):
                raise ValueError(
                    f"request {self.id!r}: ic 'array' needs a 'state' "
                    "mapping with the interior prognostic arrays "
                    "{'h': (6, n, n), 'u': (2, 6, n, n)}")
            if set(self.state) != set(OUTPUT_FIELDS):
                raise ValueError(
                    f"request {self.id!r}: ic 'array' state must "
                    f"carry exactly {sorted(OUTPUT_FIELDS)}; got "
                    f"{sorted(self.state)}")
            for k, v in self.state.items():
                if not isinstance(v, np.ndarray):
                    raise ValueError(
                        f"request {self.id!r}: state[{k!r}] must be a "
                        f"numpy array, got {type(v).__name__}")
            if self.seed >= 0 and self.amplitude != 0.0:
                raise ValueError(
                    f"request {self.id!r}: seed/amplitude "
                    "perturbations apply to the named IC families; "
                    "perturb the array client-side (or set seed: -1)")
        elif self.state is not None:
            raise ValueError(
                f"request {self.id!r}: 'state' is only valid with "
                "ic 'array'")

    @property
    def group(self) -> str:
        return SWE_FAMILIES[self.ic]

    @property
    def has_orography(self) -> bool:
        """True when the family carries a bottom mountain (tc5) — the
        request's traced per-member orography field is then the TC5
        topography instead of zeros."""
        return SWE_FAMILIES[self.ic] == "oro"

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioRequest":
        """Build from a JSONL trace line (unknown keys rejected)."""
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"request mapping has unknown keys {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        return cls(**d)


@dataclasses.dataclass
class RequestResult:
    """Outcome of one served request.

    ``status``: ``'ok'`` or ``'evicted'`` (the member went non-finite
    and was evicted by the health guard; ``guard_event`` then carries
    the monitor's event, including the member index).  ``fields`` holds
    the requested interior output arrays (host numpy) for completed
    requests — byte-identical, for a request served alone through the
    B=1 bucket, to an unbatched ``Simulation`` run of the same
    scenario.  ``latency_s`` is submit-to-completion wall time;
    ``steps_run`` how many steps actually executed (< ``nsteps`` only
    for evictions).
    """
    id: str
    ic: str
    nsteps: int
    status: str
    t_final: float
    steps_run: int
    latency_s: float
    fields: Dict[str, "object"] = dataclasses.field(default_factory=dict)
    guard_event: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"
