"""Placement planning for multi-chip serving (round 12).

One server process drives a whole mesh: the planner maps each
batch-size bucket onto the available devices in one of two composable
modes, chosen by ``serve.placement.mode``:

* ``member`` — **member-parallel**: the packed member axis shards
  across a 1-D ``('member',)`` device mesh, so a B=16 bucket on 8
  devices runs 2 members per chip.  Members never communicate, so the
  mode adds ZERO wire traffic; the masked segment is the SAME jitted
  program as the single-device path, compiled under member-axis
  ``in_shardings`` — GSPMD partitions the vmapped stepper, and the
  per-member values keep the repo's established member-batching
  contract (h bitwise vs the single-device packed run, u at the
  <= 1e-6 shape-dependent FMA budget — DESIGN.md "Batched ensemble
  execution").  Requires the classic (jnp) RHS: the fused Pallas
  kernels fold all members into one custom call GSPMD cannot split.
* ``panel`` — **panel-sharded**: each request's six cube faces spread
  across the ``panel`` axis of the 2-D ``('panel', 'member')`` mesh
  via :func:`jaxstream.parallel.shard_cov.
  make_sharded_cov_ensemble_stepper` — the PR-3 batched exchange (one
  ppermute per schedule stage carries ALL members' strips) composing
  with the PR-1 overlap phase split under
  ``parallelization.overlap_exchange``.  This is the large-grid mode:
  when one member's faces no longer fit (or fill) a chip, the panel
  axis is the scaling direction; needs a device count that is a
  multiple of 6.

A bucket that cannot use more than one device (B=1 under ``member``)
degrades to ``single`` — byte-for-byte the placement-off executable.
The planner is pure arithmetic (no jax, no devices), so the
device-count policies are unit-testable in microseconds and the same
accounting feeds ``scripts/comm_probe.py --serve``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

__all__ = ["PLACEMENT_MODES", "BucketPlan", "plan_bucket",
           "plan_placement", "plan_exchange_bytes_per_step",
           "placement_report"]

#: Legal ``serve.placement.mode`` values ('off' = the single-chip
#: round-11 code path, bitwise-unchanged).
PLACEMENT_MODES = ("off", "member", "panel")


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """How one batch-size bucket maps onto the device pool.

    ``mode`` is the *resolved* execution mode for this bucket —
    ``'single'`` (one device, the placement-off executable),
    ``'member'`` or ``'panel'`` — which may differ from the requested
    placement mode when the bucket cannot shard (B=1 member-parallel).
    ``num_devices`` counts the devices this bucket's executables span
    (``panel_shards * member_shards``); ``members_per_shard`` is the
    per-chip batch (per member *column* under ``panel`` — each column
    is 6 chips, one face each).
    """
    bucket: int
    mode: str
    num_devices: int
    panel_shards: int
    member_shards: int
    members_per_shard: int
    #: Round 19 (advisory): 1 - per_device_footprint/per_device_HBM
    #: for this bucket's measured segment executable (XLA's
    #: memory_analysis already reports per-device bytes for sharded
    #: executables) — recorded by the server when ``serve.cost_stamps``
    #: + a memory-stats-capable backend give it both sides
    #: (``jaxstream.obs.perf.headroom_fraction``), None otherwise.
    #: Reported in ``placement_report``/telemetry only; NO admission
    #: behavior change this round (docs/DESIGN.md "Performance
    #: observatory").
    headroom_frac: Optional[float] = None

    @property
    def sharded(self) -> bool:
        return self.num_devices > 1

    def with_headroom(self, footprint_bytes, limit_bytes) -> "BucketPlan":
        """This plan with the advisory headroom recorded (a new frozen
        value; None inputs leave the field None).  ``footprint_bytes``
        is per-device (memory_analysis of the sharded executable)."""
        from ..obs.perf import headroom_fraction

        return dataclasses.replace(self, headroom_frac=headroom_fraction(
            footprint_bytes, limit_bytes))


def _largest_divisor_leq(b: int, d: int) -> int:
    """Largest divisor of ``b`` that is <= ``d`` (>= 1)."""
    for m in range(min(b, d), 0, -1):
        if b % m == 0:
            return m
    return 1


def plan_bucket(bucket: int, num_devices: int, mode: str) -> BucketPlan:
    """Resolve one bucket's placement (see module docstring for modes).

    ``member``: the member-shard count is the largest divisor of the
    bucket not exceeding the device pool — every chip carries the same
    member count (the same rule :func:`jaxstream.parallel.mesh.
    setup_ensemble_sharding` enforces), and leftover devices stay idle
    for this bucket rather than skewing the batch.  ``panel``: the
    pool must be a multiple of 6 (one face per device along 'panel');
    the member axis takes the largest bucket divisor that fits
    ``num_devices // 6``.
    """
    if bucket < 1:
        raise ValueError(f"bucket must be >= 1, got {bucket}")
    if num_devices < 1:
        raise ValueError(f"num_devices must be >= 1, got {num_devices}")
    if mode not in PLACEMENT_MODES:
        raise ValueError(
            f"placement mode {mode!r}; valid: {PLACEMENT_MODES}")
    if mode == "off" or num_devices == 1:
        return BucketPlan(bucket, "single", 1, 1, 1, bucket)
    if mode == "member":
        m = _largest_divisor_leq(bucket, num_devices)
        if m == 1:
            return BucketPlan(bucket, "single", 1, 1, 1, bucket)
        return BucketPlan(bucket, "member", m, 1, m, bucket // m)
    # panel
    if num_devices % 6:
        raise ValueError(
            f"placement mode 'panel' spreads each request's 6 faces "
            f"over the 'panel' mesh axis; num_devices={num_devices} is "
            f"not a multiple of 6. Valid counts: 6, 12, 18, ... (use "
            f"mode 'member' for other pools).")
    m = _largest_divisor_leq(bucket, num_devices // 6)
    return BucketPlan(bucket, "panel", 6 * m, 6, m, bucket // m)


def plan_placement(buckets: Sequence[int], num_devices: int,
                   mode: str) -> Dict[int, BucketPlan]:
    """Per-bucket plans for a bucket set (one dict key per bucket)."""
    return {int(b): plan_bucket(int(b), num_devices, mode)
            for b in buckets}


def plan_exchange_bytes_per_step(plan: BucketPlan, n: int, halo: int,
                                 dtype_bytes: int = 4) -> float:
    """Halo-exchange wire bytes per *stepper step* for one bucket.

    ``member``/``single``: members never communicate — zero.
    ``panel``: the face tier's 12 ppermutes per step (4 race-free
    schedule stages x 3 RK stages), each shipping every local member's
    ``(3, halo, n)`` strip each way — the
    :func:`jaxstream.utils.comm_probe.batched_exchange_plan`
    ``wire_bytes_per_member_step`` scaled by the bucket (per-member
    wire bytes are invariant in B; stacking only amortizes launch
    latency).
    """
    if plan.mode != "panel":
        return 0.0
    per_member = 12 * 3 * halo * n * dtype_bytes
    return float(per_member * plan.bucket)


def placement_report(buckets: Sequence[int], num_devices: int,
                     n: int, halo: int,
                     dtype_bytes: int = 4) -> dict:
    """Static placement accounting for ``comm_probe --serve``.

    Pure arithmetic — no jax, no devices.  For each placement mode,
    per bucket: the resolved plan (devices, member shards, per-chip
    batch) and the exchange bytes per step it would put on the wire;
    a mode the pool cannot host (panel on a non-multiple-of-6 pool)
    reports ``skipped`` with the planner's message instead of raising.
    """
    out = {"num_devices": int(num_devices), "n": int(n),
           "halo": int(halo), "buckets": [int(b) for b in buckets],
           "modes": {}}
    for mode in ("member", "panel"):
        try:
            plans = plan_placement(buckets, num_devices, mode)
        except ValueError as e:
            out["modes"][mode] = {"skipped": str(e)}
            continue
        rows = []
        for b in sorted(plans):
            pl = plans[b]
            rows.append({
                "bucket": pl.bucket,
                "mode": pl.mode,
                "devices": pl.num_devices,
                "panel_shards": pl.panel_shards,
                "member_shards": pl.member_shards,
                "members_per_shard": pl.members_per_shard,
                "exchange_bytes_per_step": plan_exchange_bytes_per_step(
                    pl, n, halo, dtype_bytes),
                "headroom_frac": pl.headroom_frac,
            })
        out["modes"][mode] = {"buckets": rows}
    return out
