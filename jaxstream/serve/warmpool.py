"""Disk-backed warm pools: kill the compile tax (round 21).

Every scale-up event used to pay a multi-second jit compile — PR 5
measured 22.4 s cold vs 5.7 s warm, and the round-19 ``compile_seconds``
cost stamps measure it per plan.  This module converts that telemetry
into millisecond scale-up: each bucket's compiled masked-segment
executable (plus its extract/inject companions) is persisted to disk so
a restarted — or freshly spawned — :class:`~.server.EnsembleServer`
*loads* its warm pool instead of recompiling.

**Cache key.**  An entry is only reusable when the program AND the
environment match, so the key digests all of:

* the bucket's capability **plan key** (grid, tier, scheme, B,
  placement — ``jaxstream.plan``) and **proof fingerprint** (the
  canonical exchange-schedule digest; ``None`` hashes as such),
* the **rules version** the proof was minted against — a rule-table
  bump voids every stamp, so it must void every cached executable too,
* a **deployment digest** over the config fields the plan key does NOT
  carry (dt, segment steps, nu4, gravity, dtype, donation, grouping —
  a stale hit across any of these would be silently wrong *results*,
  not just a slow path),
* **jax + jaxlib version strings, backend platform, device count** —
  a serialized executable is an artifact of one exact toolchain.

**Degradation ladder** (:meth:`WarmPool.load` / :meth:`WarmPool.save`),
each rung a typed sink record, never a silent fallback:

1. ``aot`` — full compiled-executable serialization
   (``jax_compat.serialize_executable``): a load performs ZERO XLA
   compiles (the parity gate's ``compile_count`` proof).
2. ``stablehlo`` — ``jax.export`` StableHLO bytes: a load re-runs the
   backend compile but skips trace + lower.
3. ``compile_cache`` — jax's persistent compilation cache pointed at
   ``serve.compile_cache``.  This image's jaxlib (0.4.37) is
   *documented* to segfault when a different process deserializes CPU
   cache entries (the ``jax_compat.enable_compile_cache`` quarantine
   note), so the rung is gated behind a SUBPROCESS feature probe: a
   child process populates a scratch cache, a second child reloads
   from it, and only a clean double-exit unlocks the rung in the
   server process.  The verdict is cached per (jaxlib, backend) so the
   probe's ~seconds are paid once per pool directory.
4. ``cold`` — plain jit compile (today's behavior).

**Atomicity.**  Entries commit in the PR-20 flight-recorder style:
payload bytes land via tmp + ``os.replace``; the small meta JSON —
naming the payload sha256 and byte length — is written LAST, so a
reader either sees a complete entry or no entry.  A meta that points
at missing/short/digest-mismatched payload bytes is a TORN entry:
detected, deleted, recorded (``event: "corrupt"``), recompiled.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
import threading
from typing import Callable, Dict, List, Optional

from ..utils import jax_compat
from ..utils.logging import get_logger

__all__ = ["WarmPool", "WarmExecutable", "HeadroomRefused",
           "entry_key", "deployment_digest", "probe_rung",
           "SpeculativeCompiler", "RUNGS"]

log = get_logger(__name__)

#: The degradation ladder, best rung first.  ``cold`` is implicit —
#: the pool returning None IS the cold rung.
RUNGS = ("aot", "stablehlo", "compile_cache")


class HeadroomRefused(ValueError):
    """A resize/speculation target whose stamped per-chip footprint
    would breach ``serve.min_headroom_frac`` (the first CONSUMER of the
    round-19 advisory ``headroom_frac`` — advisory stays advisory for
    admission; only scale-up decisions enforce it)."""


# ------------------------------------------------------------- cache key
def deployment_digest(config) -> str:
    """Digest of the config fields the plan key does NOT carry.

    The plan key names grid size, tier, scheme, bucket and placement —
    but not dt, segment steps, hyperdiffusion, gravity, limiter or the
    carry dtype.  Two deployments differing in any of those compile
    DIFFERENT programs under the SAME plan key, so the warm-pool key
    must fold them in: a stale hit here would be wrong physics, not a
    slow path.
    """
    cfg = config
    ident = {
        "grid": {"n": cfg.grid.n, "halo": cfg.grid.halo,
                 "radius": cfg.grid.radius, "dtype": cfg.grid.dtype,
                 "metrics": cfg.grid.metrics},
        "time": {"dt": cfg.time.dt, "scheme": cfg.time.scheme},
        "physics": {"gravity": cfg.physics.gravity,
                    "omega": cfg.physics.omega,
                    "nu4": cfg.physics.hyperdiffusion,
                    "d2": cfg.physics.divergence_damping},
        "model": {"scheme": cfg.model.scheme,
                  "limiter": cfg.model.limiter,
                  "backend": cfg.model.backend,
                  "nu4_mode": cfg.model.nu4_mode,
                  "ic_angle": cfg.model.ic_angle},
        "precision": {"stage": cfg.precision.stage,
                      "strips": cfg.precision.strips,
                      "carry": cfg.precision.carry},
        "serve": {"segment_steps": cfg.serve.segment_steps,
                  "donate": cfg.serve.donate,
                  "group_by_orography": cfg.serve.group_by_orography},
    }
    blob = json.dumps(ident, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _environment_fields() -> dict:
    """The toolchain identity a serialized executable depends on."""
    import jax

    try:
        import jaxlib

        jaxlib_version = jaxlib.__version__
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        jaxlib_version = "unknown"
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
    }


def entry_key(plan_key: str, proof_fingerprint: Optional[str],
              rules_version: int, deploy_digest: str, fn: str,
              environment: Optional[dict] = None) -> str:
    """One warm-pool entry's content-addressed key (hex digest).

    ``fn`` names which of the bucket's executables the entry holds
    ('seg' / 'extract' / 'inject').  ``environment`` is injectable so
    the tier-1 invalidation tests can prove a jaxlib version-string
    change MISSES without installing a second jaxlib.
    """
    env = environment if environment is not None else _environment_fields()
    ident = {
        "plan_key": plan_key,
        "proof_fingerprint": proof_fingerprint,
        "rules_version": int(rules_version),
        "deploy": deploy_digest,
        "fn": fn,
        "env": {k: env.get(k) for k in
                ("jax", "jaxlib", "backend", "device_count")},
    }
    blob = json.dumps(ident, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:32]


# -------------------------------------------------------- warm callables
class WarmExecutable:
    """A pool-managed callable that keeps ``compile_count`` honest.

    The server's zero-steady-state-recompile proofs read
    ``fn._cache_size()`` through ``jax_compat.compile_count``; an AOT
    ``Compiled`` has no jit cache, so the wrapper reports the number of
    XLA compiles its construction actually performed: 0 for a
    pool-loaded executable (the warm path's zero-compile gate), 1 for
    a freshly AOT-compiled one.  ``stablehlo``-rung loads delegate to
    the inner jit's real cache (its first call IS one backend
    compile).
    """

    def __init__(self, call: Callable, rung: str,
                 compiles: Optional[int] = None):
        self._call = call
        self.rung = rung
        self._compiles = compiles

    def __call__(self, *args, **kwargs):
        return self._call(*args, **kwargs)

    def _cache_size(self) -> int:
        if self._compiles is not None:
            return self._compiles
        inner = jax_compat.compile_count(self._call)
        return 0 if inner is None else inner


# ------------------------------------------------------ subprocess probe
#: Child script of one probe arm.  argv: [rung, scratch_dir, phase]
#: phase 'write' populates (compile + serialize/cache-fill), phase
#: 'read' consumes what a DIFFERENT process wrote — the exact pattern
#: the jaxlib-0.4.37 quarantine note says can segfault, which is why
#: this runs in a child: a SIGSEGV costs an exit code, not the server.
_PROBE_SCRIPT = r"""
import os, sys
rung, scratch, phase = sys.argv[1], sys.argv[2], sys.argv[3]
os.environ.setdefault("JAX_PLATFORMS", os.environ.get(
    "JAXSTREAM_PROBE_PLATFORM", "cpu"))
import jax, jax.numpy as jnp
from jaxstream.utils import jax_compat
fn = jax.jit(lambda x: x * 2.0 + 1.0)
x = jnp.arange(8.0)
payload_path = os.path.join(scratch, "probe.bin")
if rung == "aot":
    if phase == "write":
        blob = jax_compat.serialize_executable(
            fn.lower(x).compile())
        with open(payload_path, "wb") as fh:
            fh.write(blob)
    else:
        with open(payload_path, "rb") as fh:
            blob = fh.read()
        loaded = jax_compat.deserialize_executable(blob)
        out = loaded(x)
        assert float(out[1]) == 3.0, out
elif rung == "compile_cache":
    jax_compat.enable_compile_cache(os.path.join(scratch, "cache"))
    out = fn(x)
    jax.block_until_ready(out)
    assert float(out[1]) == 3.0, out
    if phase == "write":
        entries = os.listdir(os.path.join(scratch, "cache"))
        assert entries, "compile cache stayed empty"
else:
    raise SystemExit(f"unknown probe rung {rung!r}")
"""


def probe_rung(rung: str, scratch_dir: str,
               timeout: float = 120.0) -> dict:
    """Cross-process feature probe of one warm-pool rung.

    Runs TWO child processes: a writer that compiles and persists (a
    serialized executable, or a populated compile cache), then a
    reader that consumes the writer's on-disk artifact — the
    cross-process deserialization this image's jaxlib is documented to
    segfault on for CPU compile-cache entries.  Returns a verdict dict
    ``{"rung", "ok", "detail"}``; a crash (any nonzero exit, including
    a signal) is a typed ``ok: False``, never an exception — the pool
    records the verdict and degrades a rung.
    """
    import subprocess

    if rung not in ("aot", "compile_cache"):
        raise ValueError(f"unprobed rung {rung!r}; probe covers "
                         "('aot', 'compile_cache')")
    os.makedirs(scratch_dir, exist_ok=True)
    env = dict(os.environ)
    # The probe must see the same platform the server runs, but never
    # inherit a live compile-cache env var that would alias scratch.
    env.pop("JAXSTREAM_COMPILE_CACHE", None)
    for phase in ("write", "read"):
        try:
            res = subprocess.run(
                [sys.executable, "-c", _PROBE_SCRIPT, rung,
                 scratch_dir, phase],
                capture_output=True, text=True, timeout=timeout,
                env=env)
        except subprocess.TimeoutExpired:
            return {"rung": rung, "ok": False,
                    "detail": f"{phase} probe timed out at {timeout}s"}
        if res.returncode != 0:
            tail = (res.stderr or res.stdout or "").strip()[-300:]
            return {"rung": rung, "ok": False,
                    "detail": (f"{phase} probe exited "
                               f"{res.returncode}: {tail}")}
    return {"rung": rung, "ok": True,
            "detail": "cross-process write+read probes exited clean"}


# --------------------------------------------------------------- the pool
@dataclasses.dataclass
class _Entry:
    """On-disk layout of one committed entry (meta side)."""
    key: str
    rung: str
    sha256: str
    length: int
    plan_key: str
    donate: tuple


class WarmPool:
    """One directory of serialized bucket executables + rung probes.

    ``sink_write`` receives the typed ``warmpool`` records (hit / miss
    / save / corrupt / probe / fallback — never a silent rung change);
    ``counter_inc`` is the metrics hook (``jaxstream_warmpool_*`` on
    ``/v1/metrics``).  Thread-safe: the speculative compiler and the
    serving thread share one pool under ``self._lock``.
    """

    def __init__(self, path: str, compile_cache: str = "",
                 sink_write: Optional[Callable] = None,
                 counter_inc: Optional[Callable] = None,
                 environment: Optional[dict] = None,
                 probe: Optional[Callable] = None):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.compile_cache = compile_cache
        self._sink_write = sink_write
        self._counter_inc = counter_inc
        self._environment = environment
        self._probe = probe if probe is not None else probe_rung
        self._lock = threading.Lock()
        self._verdicts: Dict[str, dict] = {}
        self.stats = {"hits": 0, "misses": 0, "saves": 0,
                      "corrupt": 0, "rungs": {}}
        self._cache_enabled = False

    # ------------------------------------------------------------ records
    def _record(self, event: str, rung: str, plan: Optional[str],
                **extra) -> None:
        rec = {"kind": "warmpool", "event": event, "rung": rung,
               "plan": plan}
        rec.update(extra)
        if self._sink_write is not None:
            try:
                self._sink_write(rec)
            except Exception as e:  # telemetry must never kill serving
                log.warning("warmpool sink record failed (%s: %s)",
                            type(e).__name__, e)
        if self._counter_inc is not None:
            try:
                if event == "hit":
                    self._counter_inc("jaxstream_warmpool_hits_total",
                                      1, rung=rung)
                elif event == "miss":
                    self._counter_inc(
                        "jaxstream_warmpool_misses_total", 1,
                        reason=str(extra.get("reason", "absent")))
                elif event == "save":
                    self._counter_inc("jaxstream_warmpool_saves_total",
                                      1, rung=rung)
            except Exception:
                pass

    # ------------------------------------------------------------- paths
    def _payload_path(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.bin")

    def _meta_path(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.json")

    def _atomic_write(self, path: str, data: bytes) -> None:
        from ..obs.flight import _atomic_write_bytes

        _atomic_write_bytes(path, data)

    # ------------------------------------------------------------ probing
    def rung_verdict(self, rung: str) -> dict:
        """The (cached) cross-process probe verdict of one rung.

        Cached two ways: in-process per pool, and on disk next to the
        entries keyed by (jaxlib, backend) — a fleet of servers
        sharing one pool directory pays the probe's seconds once.  The
        verdict lands in the sink as a typed ``probe`` record either
        way, so every deployment's telemetry says which rungs were
        trusted and why.
        """
        if rung in self._verdicts:
            return self._verdicts[rung]
        env = (self._environment if self._environment is not None
               else _environment_fields())
        tag = hashlib.sha256(json.dumps(
            {"rung": rung, "jaxlib": env.get("jaxlib"),
             "backend": env.get("backend")},
            sort_keys=True).encode()).hexdigest()[:16]
        vpath = os.path.join(self.path, f"probe_{rung}_{tag}.json")
        verdict = None
        if os.path.exists(vpath):
            try:
                with open(vpath) as fh:
                    verdict = json.load(fh)
                if verdict.get("rung") != rung:
                    verdict = None
            except Exception:
                verdict = None
        cached = verdict is not None
        if verdict is None:
            verdict = self._probe(
                rung, os.path.join(self.path, f"_probe_{rung}"))
            try:
                self._atomic_write(
                    vpath, json.dumps(verdict).encode())
            except OSError as e:
                log.warning("warmpool: probe verdict not cached "
                            "(%s: %s)", type(e).__name__, e)
        self._verdicts[rung] = verdict
        self._record("probe", rung, None, ok=bool(verdict.get("ok")),
                     detail=str(verdict.get("detail", "")),
                     cached=cached)
        return verdict

    def enable_compile_cache(self) -> bool:
        """Engage the ``compile_cache`` rung iff configured AND the
        subprocess probe proved cross-process deserialization safe on
        this toolchain.  Idempotent; returns whether the cache is on."""
        if self._cache_enabled:
            return True
        if not self.compile_cache:
            return False
        verdict = self.rung_verdict("compile_cache")
        if not verdict.get("ok"):
            self._record("fallback", "compile_cache", None,
                         reason=str(verdict.get("detail", "")))
            return False
        jax_compat.enable_compile_cache(self.compile_cache)
        self._cache_enabled = True
        return True

    # ------------------------------------------------------------ loading
    def load(self, key: str, plan_key: Optional[str] = None):
        """One entry -> a :class:`WarmExecutable`, or None (= cold).

        Every outcome is typed: a clean absent entry is a ``miss``
        (reason 'absent'); a meta whose payload is missing, short, or
        digest-mismatched is a torn/corrupt entry — deleted, recorded
        (``corrupt``), and reported as a miss so the caller recompiles;
        a payload that fails deserialization (e.g. a foreign jaxlib's
        bytes that slipped past the key — should be impossible) is the
        same corrupt path, never a crash.
        """
        with self._lock:
            return self._load_locked(key, plan_key)

    def _load_locked(self, key: str, plan_key: Optional[str]):
        mpath, ppath = self._meta_path(key), self._payload_path(key)
        if not os.path.exists(mpath):
            self.stats["misses"] += 1
            self._record("miss", "cold", plan_key, key=key,
                         reason="absent")
            return None
        try:
            with open(mpath) as fh:
                meta = json.load(fh)
            with open(ppath, "rb") as fh:
                payload = fh.read()
            if len(payload) != int(meta["length"]):
                raise ValueError(
                    f"payload is {len(payload)}B, meta says "
                    f"{meta['length']}B")
            digest = hashlib.sha256(payload).hexdigest()
            if digest != meta["sha256"]:
                raise ValueError("payload sha256 mismatch")
            rung = meta["rung"]
            if rung == "aot":
                call = jax_compat.deserialize_executable(payload)
                warm = WarmExecutable(call, "aot", compiles=0)
            elif rung == "stablehlo":
                call = jax_compat.deserialize_stablehlo(
                    payload,
                    donate_argnums=tuple(meta.get("donate", ())))
                warm = WarmExecutable(call, "stablehlo")
            else:
                raise ValueError(f"unknown entry rung {rung!r}")
        except Exception as e:
            # Torn/corrupt entry: loud, deleted, recompiled.
            self.stats["corrupt"] += 1
            self.stats["misses"] += 1
            for p in (mpath, ppath):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            log.warning(
                "warmpool: entry %s is torn/corrupt (%s: %s) — "
                "deleted; recompiling", key, type(e).__name__, e)
            self._record("corrupt", "cold", plan_key, key=key,
                         reason=f"{type(e).__name__}: {e}")
            self._record("miss", "cold", plan_key, key=key,
                         reason="corrupt")
            return None
        self.stats["hits"] += 1
        self.stats["rungs"][rung] = self.stats["rungs"].get(rung, 0) + 1
        self._record("hit", rung, plan_key, key=key)
        return warm

    # ------------------------------------------------------------- saving
    def save(self, key: str, jitted, compiled, example_args,
             plan_key: Optional[str] = None,
             donate: tuple = ()) -> Optional[str]:
        """Persist one freshly compiled executable at the best rung
        this build supports.  ``compiled`` is the AOT ``Compiled``
        (rung 1's payload); ``jitted`` + ``example_args`` feed the
        StableHLO export when rung 1 is unavailable.  Returns the rung
        saved at, or None (ladder exhausted — the typed ``fallback``
        records say which rungs refused and why)."""
        with self._lock:
            return self._save_locked(key, jitted, compiled,
                                     example_args, plan_key, donate)

    def _save_locked(self, key, jitted, compiled, example_args,
                     plan_key, donate):
        payload = rung = None
        # The aot/stablehlo rungs gate on API availability alone: their
        # loads were verified safe on this toolchain (and a corrupt
        # payload degrades through the typed torn-entry path anyway).
        # Only the compile_cache rung carries the documented
        # cross-process segfault class, so only it pays the subprocess
        # probe (jax_compat.enable_compile_cache quarantine note).
        if jax_compat.executable_serialization_available():
            try:
                payload = jax_compat.serialize_executable(compiled)
                rung = "aot"
            except RuntimeError as e:
                self._record("fallback", "aot", plan_key,
                             reason=str(e))
        else:
            self._record("fallback", "aot", plan_key,
                         reason="unavailable: no serialize_executable "
                                "in this jax build")
        if payload is None and jax_compat.stablehlo_serialization_available():
            try:
                payload = jax_compat.serialize_stablehlo(
                    jitted, *example_args)
                rung = "stablehlo"
            except RuntimeError as e:
                self._record("fallback", "stablehlo", plan_key,
                             reason=str(e))
        if payload is None:
            # Last resort below cold: the persistent compile cache
            # (probe-gated) at least makes the next cold compile warm.
            self.enable_compile_cache()
            return None
        meta = {"key": key, "rung": rung,
                "sha256": hashlib.sha256(payload).hexdigest(),
                "length": len(payload), "plan_key": plan_key,
                "donate": list(donate)}
        try:
            # Flight-recorder commit discipline: payload first, the
            # meta that makes the entry visible LAST — a kill between
            # the two leaves an invisible payload, not a torn entry.
            self._atomic_write(self._payload_path(key), payload)
            self._atomic_write(self._meta_path(key),
                               json.dumps(meta).encode())
        except OSError as e:
            self._record("fallback", rung, plan_key,
                         reason=f"entry write failed "
                                f"({type(e).__name__}: {e})")
            return None
        self.stats["saves"] += 1
        self._record("save", rung, plan_key, key=key,
                     bytes=len(payload))
        return rung

    def summary(self) -> dict:
        """The ``/v1/stats`` payload: counters + probed verdicts."""
        return {
            "path": self.path,
            "compile_cache": (self.compile_cache
                              if self._cache_enabled else ""),
            "hits": self.stats["hits"],
            "misses": self.stats["misses"],
            "saves": self.stats["saves"],
            "corrupt": self.stats["corrupt"],
            "rungs": dict(self.stats["rungs"]),
            "probes": {r: {"ok": v.get("ok"),
                           "detail": v.get("detail")}
                       for r, v in sorted(self._verdicts.items())},
        }


# ------------------------------------------------- speculative compiler
class SpeculativeCompiler:
    """Background compilation of ADJACENT plans (round 21).

    The autoscale policy moves the active cap one level at a time, so
    the plans worth having warm are exactly the next configured bucket
    up and down from the current cap.  ``nudge(cap)`` (called from
    ``EnsembleServer.resize`` and at attach) wakes a worker thread
    that builds those buckets through the server's own ``_bucket``
    path — same build lock, same warm-pool save — so a later
    ``resize()`` to a not-yet-warm size stops paying jit at a segment
    boundary.  Headroom-refused targets are skipped with the same
    typed record ``resize`` writes (the satellite's one enforcement).
    """

    THREAD_NAME = "jaxstream-serve-speculator"

    def __init__(self, server):
        self._server = server
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._targets: List[int] = []
        self._lock = threading.Lock()
        self.built: List[tuple] = []
        self.skipped: List[dict] = []
        self._thread = threading.Thread(
            target=self._run, name=self.THREAD_NAME, daemon=True)
        self._thread.start()

    def nudge(self, cap: int) -> None:
        srv = self._server
        buckets = list(srv.buckets)
        try:
            i = buckets.index(int(cap))
        except ValueError:
            return
        adjacent = [buckets[j] for j in (i + 1, i - 1)
                    if 0 <= j < len(buckets)]
        with self._lock:
            self._targets = adjacent
        self._wake.set()

    def close(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout)

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait()
            if self._stop.is_set():
                return
            self._wake.clear()
            with self._lock:
                targets, self._targets = self._targets, []
            for B in targets:
                if self._stop.is_set():
                    return
                try:
                    self._build(B)
                except Exception as e:
                    # A speculative compile failing must never hurt
                    # the server — the cold path still works.
                    log.warning(
                        "warmpool: speculative build of B=%d failed "
                        "(%s: %s)", B, type(e).__name__, e)

    def _build(self, B: int) -> None:
        srv = self._server
        for group in srv.warm_groups():
            if (group, B) in srv._buckets:
                continue
            refusal = srv.headroom_refusal(B)
            if refusal is not None:
                self.skipped.append(refusal)
                srv.record_headroom_refusal(
                    refusal, action="speculate_refused")
                continue
            srv._bucket(group, B)
            self.built.append((group, B))
