"""Continuous-batching ensemble server: simulation-as-a-service.

The member axis ``B`` (the round-7 batched ensemble substrate) is an
inference-style batch dimension; this package feeds it the way LLM
servers feed theirs — independent scenario requests (IC family,
perturbation seed, run length, output subset) packed into one batched
stepper whose compiled executables stay warm across requests, with
per-member run-length masking so a finished member's slot is refilled
from the request queue at the next segment boundary instead of idling
until the slowest member drains (ROADMAP open item 1; docs/USAGE.md
"Serving", docs/DESIGN.md "Continuous batching").

Round 12 adds multi-chip serving: the ``serve.placement:`` block maps
each batch-size bucket onto the available devices — member-parallel
(the packed member axis shards across a ``('member',)`` mesh) or
panel-sharded (each request's six faces spread over the
``('panel', 'member')`` mesh through the batched-exchange ensemble
stepper); :mod:`jaxstream.serve.placement` holds the pure planner.

Round 14 adds the network-serving hooks: ``serve_forever`` (the
gateway's drain loop with the per-segment autoscale tick),
``begin_drain`` + :class:`ServerDraining` (graceful shutdown — typed
refusals while in-flight members finish), ``resize`` (live bucket-cap
scaling among warm executables), and ``on_segment`` progress events —
the surface :mod:`jaxstream.gateway` and :mod:`jaxstream.loadgen`
build on.

Round 21 adds the warm-pool subsystem (:mod:`jaxstream.serve.
warmpool`): disk-backed serialized executables keyed by plan + proof +
toolchain so a restarted server loads instead of recompiling, a
probe-gated persistent compile cache, speculative compilation of
adjacent buckets, and :class:`HeadroomRefused` — the first enforcement
consumer of the round-19 advisory ``headroom_frac``.
"""

from .placement import BucketPlan, plan_placement, placement_report
from .queue import (AdmissionRefused, QueueFull, RequestQueue,
                    ServerDraining)
from .request import ScenarioRequest, RequestResult
from .server import EnsembleServer, serve_requests
from .warmpool import (HeadroomRefused, SpeculativeCompiler,
                       WarmExecutable, WarmPool)

__all__ = [
    "AdmissionRefused",
    "BucketPlan",
    "EnsembleServer",
    "HeadroomRefused",
    "QueueFull",
    "RequestQueue",
    "RequestResult",
    "ScenarioRequest",
    "ServerDraining",
    "SpeculativeCompiler",
    "WarmExecutable",
    "WarmPool",
    "placement_report",
    "plan_placement",
    "serve_requests",
]
