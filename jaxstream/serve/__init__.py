"""Continuous-batching ensemble server: simulation-as-a-service.

The member axis ``B`` (the round-7 batched ensemble substrate) is an
inference-style batch dimension; this package feeds it the way LLM
servers feed theirs — independent scenario requests (IC family,
perturbation seed, run length, output subset) packed into one batched
stepper whose compiled executables stay warm across requests, with
per-member run-length masking so a finished member's slot is refilled
from the request queue at the next segment boundary instead of idling
until the slowest member drains (ROADMAP open item 1; docs/USAGE.md
"Serving", docs/DESIGN.md "Continuous batching").
"""

from .queue import AdmissionRefused, QueueFull, RequestQueue
from .request import ScenarioRequest, RequestResult
from .server import EnsembleServer, serve_requests

__all__ = [
    "AdmissionRefused",
    "EnsembleServer",
    "QueueFull",
    "RequestQueue",
    "RequestResult",
    "ScenarioRequest",
    "serve_requests",
]
