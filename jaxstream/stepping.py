"""Time integration under a single top-level ``jit``.

The reference's core performance message is "compile once, no recompilation
during timestepping" (deck p.10; ``JAX-DevLab-Examples.py:94-96``).  Here
that is realized the idiomatic-JAX way: one ``jit`` wraps the *whole* step
(halo exchange + RHS + stage combination), and multi-step integration runs
under ``lax.scan``/``lax.fori_loop`` so Python never re-enters the loop —
stronger than the reference's 12-small-JITs-plus-composed-JIT mechanism
(SURVEY.md §7 pitfalls).

Schemes are written over pytrees so any model state (scalar h, Cartesian
velocity, tracers...) integrates unchanged.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import tree_util as jtu

from .utils.jax_compat import named_scope

__all__ = ["ssprk3_step", "rk4_step", "euler_step", "make_stepper",
           "blocked", "time_carry", "integrate", "integrate_masked",
           "integrate_with_history", "integrate_with_metrics",
           "vmap_ensemble", "jit_integrate",
           "jit_integrate_with_history"]


def time_carry(t):
    """The canonical time-scalar carry: ``jnp.asarray(t, dtype=float)``.

    ``dtype=float`` resolves to f64 under ``jax_enable_x64`` and f32
    otherwise — exactly what :func:`integrate` commits its loop carry
    to.  The async host pipeline passes segment boundaries' *device*
    time scalars straight back into the next segment through this form
    (instead of the synchronous path's ``float(t)`` round trip, which
    would block the dispatch on a d2h sync); the values are bitwise
    identical either way — a device f32/f64 scalar round-tripped
    through a python float converts back to the same bits.
    """
    return jnp.asarray(t, dtype=float)


def _axpy(y, dt, k):
    return jtu.tree_map(lambda a, b: a + dt * b, y, k)


def euler_step(rhs: Callable, y, t, dt):
    return _axpy(y, dt, rhs(y, t))


def ssprk3_step(rhs: Callable, y, t, dt):
    """Shu-Osher strong-stability-preserving RK3 (the north-star scheme)."""
    y1 = _axpy(y, dt, rhs(y, t))
    y2 = jtu.tree_map(
        lambda a, b: 0.75 * a + 0.25 * b, y, _axpy(y1, dt, rhs(y1, t + dt))
    )
    y3 = _axpy(y2, dt, rhs(y2, t + 0.5 * dt))
    return jtu.tree_map(lambda a, b: (a + 2.0 * b) / 3.0, y, y3)


def rk4_step(rhs: Callable, y, t, dt):
    k1 = rhs(y, t)
    k2 = rhs(_axpy(y, 0.5 * dt, k1), t + 0.5 * dt)
    k3 = rhs(_axpy(y, 0.5 * dt, k2), t + 0.5 * dt)
    k4 = rhs(_axpy(y, dt, k3), t + dt)
    return jtu.tree_map(
        lambda a, b1, b2, b3, b4: a + (dt / 6.0) * (b1 + 2 * b2 + 2 * b3 + b4),
        y, k1, k2, k3, k4,
    )


SCHEMES = {"euler": euler_step, "ssprk3": ssprk3_step, "rk4": rk4_step}


def make_stepper(rhs: Callable, dt: float, scheme: str = "ssprk3") -> Callable:
    """``step(y, t) -> y_next``; jit it (or trace it inside a larger jit)."""
    stepper = SCHEMES[scheme]

    def step(y, t):
        return stepper(rhs, y, t, dt)

    return step


def blocked(step: Callable, k: int, dt: float) -> Callable:
    """Fuse ``k`` steps into one ``block(y, t) -> y`` (temporal blocking).

    The returned block advances ``k * dt`` of model time per call with
    sequential ``t + i*dt`` sub-step times — numerically identical to k
    separate calls (same ops, same order); only the dispatch granularity
    changes.  Drive it with ``integrate(block, y, t, nblocks, k*dt)``.
    The per-tier *deep-halo* temporal blocking (exchange amortization,
    ``parallelization.temporal_block``) lives in the sharded steppers;
    this is the exact fusion used where the exchange data is local.
    """
    if k < 1:
        raise ValueError(f"blocked: k must be >= 1, got {k}")

    def block(y, t):
        for _ in range(k):
            y = step(y, t)
            t = t + dt  # sequential adds: bitwise-identical to k calls
        return y

    return block


def vmap_ensemble(step: Callable, axes) -> Callable:
    """Vmapped reference path for batched ensemble stepping.

    ``axes`` is a pytree matching the carry giving each leaf's member-
    axis position (e.g. ``{"h": 0, "u": 1}`` for the SWE interior state,
    where ``u``'s component axis precedes the member axis).  Returns
    ``vstep(y, t) -> y`` mapping ``step`` over the member axis with the
    time scalar broadcast.  This is the semantics oracle the batched
    kernel/exchange paths are tested against — vmap guarantees
    per-member arithmetic identical to B separate calls — and the
    fallback when a tier has no natively batched stepper.  Attributes
    (``steps_per_call``) carry over.
    """
    vstep = jax.vmap(step, in_axes=(axes, None), out_axes=axes)
    spc = getattr(step, "steps_per_call", 1)
    if spc != 1:
        vstep.steps_per_call = spc
    return vstep


def integrate(step: Callable, y0, t0: float, nsteps: int, dt: float,
              unroll: int = 4):
    """Run ``nsteps`` under one compiled ``lax.fori_loop``.

    Returns ``(y_final, t_final)``.  The carry keeps time as a traced
    scalar so restarts resume mid-run without recompiling (so the loop
    lowers to a ``while`` — ``lax.fori_loop(unroll=...)`` requires
    static bounds and cannot apply here).

    ``unroll`` runs that many steps per while iteration, with the
    ``nsteps % unroll`` remainder in a second (at most unroll-1
    iteration) plain loop: numerically identical to ``unroll=1`` —
    same ops in the same order, sequential time adds — but the
    per-iteration while-carry copies XLA cannot alias away are paid
    1/unroll as often.  Measured on the C384 TC5 fused stepper
    (single-session ladder, round 5): 3 336 (u=2) -> 3 386 (u=4) ->
    3 405 (u=8) steps/s; +2.0% at u=2 over the plain loop was the
    first measurement (DESIGN.md round-5 addendum).  Default 4: u=8's
    last +0.6% doubles the traced body again, which matters for large
    step graphs (the TT tier rides this function too).
    """
    if unroll < 1:
        raise ValueError(f"integrate: unroll must be >= 1, got {unroll}")

    def body(_, carry):
        y, t = carry
        return step(y, t), t + dt

    def body_u(_, carry):
        y, t = carry
        for _ in range(unroll):
            y = step(y, t)
            t = t + dt  # sequential adds: bitwise-identical to unroll=1
        return y, t

    # dtype=float -> float64 under jax_enable_x64, else float32: long runs
    # in x64 mode keep full time resolution (t ~ 1e6 s overwhelms f32 ulp).
    t0a = time_carry(t0)
    if unroll == 1:
        return jax.lax.fori_loop(0, nsteps, body, (y0, t0a))
    y, t = jax.lax.fori_loop(0, nsteps // unroll, body_u, (y0, t0a))
    return jax.lax.fori_loop(0, nsteps % unroll, body, (y, t))


def integrate_masked(step: Callable, y0, t0: float, rem0, nsteps: int,
                     dt: float, axes, sharding=None):
    """:func:`integrate` over a member-batched carry with per-member
    run-length masking — the continuous-batching serving loop's inner
    segment (``jaxstream.serve``).

    ``rem0`` is a ``(B,)`` integer vector of *remaining* stepper calls
    per member; ``axes`` is a pytree matching ``y0`` giving each leaf's
    member-axis position (the :func:`vmap_ensemble` convention, e.g.
    ``{"h": 0, "u": 1}``).  Every iteration steps the WHOLE batch, then
    keeps the new value only for members whose remaining count is still
    positive — a finished member's state is frozen bit-for-bit at its
    own final step while the rest of the batch drains, so a slot can be
    refilled at the next segment boundary instead of idling.  For a
    member with ``rem0[i] >= nsteps`` the masking select is
    ``where(True, new, old)`` — bitwise the unmasked :func:`integrate`
    with ``unroll=1`` (same step ops, same order).

    The time scalar is a single batch-wide carry (the shallow-water
    steppers are autonomous — ``t`` only sequences ``t + dt`` adds);
    per-member model time is host bookkeeping (``steps_done * dt``).
    Returns ``(y, t, rem)`` with ``rem`` decremented once per iteration
    for each then-active member (floor 0).

    ``sharding`` (round 12, multi-chip serving): a pytree of
    ``NamedSharding`` matching ``y0`` — each iteration's masked carry
    is pinned to it with ``with_sharding_constraint`` so GSPMD keeps
    the member (or panel) layout stable through the loop instead of
    ever deciding to reshard mid-segment.  Constraints never change
    values; ``None`` (the default) is the exact single-device path.
    """

    def body(_, carry):
        y, t, rem = carry
        # Name-stack annotation reusing the sink span name (round 17):
        # an XLA profiler capture of the serving loop shows the same
        # "serve.segment" region the request's sink span records carry,
        # so profile timelines and span trees line up by name.
        with named_scope("serve.segment"):
            y2 = step(y, t)
        active = rem > 0

        def sel(new, old, ax):
            shape = [1] * new.ndim
            shape[ax] = active.shape[0]
            return jnp.where(active.reshape(shape), new, old)

        y = jtu.tree_map(sel, y2, y, axes)
        if sharding is not None:
            y = jax.lax.with_sharding_constraint(y, sharding)
        return y, t + dt, rem - active.astype(rem.dtype)

    return jax.lax.fori_loop(
        0, nsteps, body,
        (y0, time_carry(t0), jnp.asarray(rem0, jnp.int32)))


def integrate_with_history(step: Callable, y0, t0: float, nsteps: int, dt: float,
                           stride: int, snapshot: Callable):
    """As :func:`integrate`, also stacking ``snapshot(y)`` every ``stride``
    steps via ``lax.scan`` (history output stays on device until fetched)."""

    def body(_, c):
        yy, tt = c
        return step(yy, tt), tt + dt

    def chunk(carry, _):
        carry = jax.lax.fori_loop(0, stride, body, carry)
        return carry, snapshot(carry[0])

    nchunks, rem = divmod(nsteps, stride)
    (y, t), hist = jax.lax.scan(
        chunk, (y0, time_carry(t0)), None, length=nchunks
    )
    if rem:  # don't silently drop the trailing nsteps % stride steps
        y, t = jax.lax.fori_loop(0, rem, body, (y, t))
    return y, t, hist


def integrate_with_metrics(step: Callable, y0, t0: float, ncalls: int,
                           dt: float, metric_fn: Callable, every: int,
                           n_samples: int, step0,
                           steps_per_call: int = 1,
                           fault_step: int = -1):
    """:func:`integrate` plus an on-device metric stream (zero host syncs).

    Runs ``ncalls`` stepper calls under one ``lax.fori_loop`` exactly as
    :func:`integrate` with ``unroll=1`` does — same ops in the same
    order, so enabling metrics must not perturb the state carry (tested
    bitwise in tests/test_obs.py) — and additionally evaluates
    ``metric_fn(y, t) -> (k_metrics,)`` after every ``every``-th call,
    writing the vector into column ``j`` of a ``(k_metrics, n_samples)``
    device buffer.  Sample ``j`` (0-based) is taken after call
    ``(j+1) * every``, i.e. at global step
    ``step0 + (j+1) * every * steps_per_call``; unsampled trailing calls
    (``ncalls % every``) still integrate, their steps are simply not
    observed.  Returns ``(y, t, buf)`` — the caller fetches ``buf``
    with ONE ``jax.device_get`` per segment
    (:func:`jaxstream.obs.metrics.fetch_buffer`).

    ``step0`` is a *traced* operand (the global step count before this
    segment) so one executable serves every segment.  ``fault_step >=
    0`` is the testing hook: the sample whose global step equals it is
    overwritten with NaN *in the stream only* — the state carry is
    untouched — so guard plumbing can be proven without integrating a
    real blowup (``fault_step`` must land on a sampled step to fire).
    """
    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    if n_samples < 1:
        raise ValueError(f"n_samples must be >= 1, got {n_samples}")
    t0a = time_carry(t0)
    vec_shape = jax.eval_shape(metric_fn, y0, t0a)
    buf0 = jnp.full((vec_shape.shape[0], n_samples), jnp.nan,
                    vec_shape.dtype)

    def body(i, carry):
        y, t, buf = carry
        y = step(y, t)
        t = t + dt

        def write(b):
            vec = metric_fn(y, t)
            if fault_step >= 0:
                g = step0 + (i + 1) * steps_per_call
                vec = jnp.where(jnp.equal(g, fault_step),
                                jnp.full_like(vec, jnp.nan), vec)
            j = (i + 1) // every - 1
            return jax.lax.dynamic_update_slice(
                b, vec[:, None].astype(b.dtype), (0, j))

        take = jnp.logical_and((i + 1) % every == 0,
                               (i + 1) // every <= n_samples)
        buf = jax.lax.cond(take, write, lambda b: b, buf)
        return y, t, buf

    return jax.lax.fori_loop(0, ncalls, body, (y0, t0a, buf0))


def jit_integrate(step: Callable, dt: float, unroll: int = 4,
                  donate: bool = True) -> Callable:
    """One compiled ``run(y0, t0, nsteps) -> (y, t)`` over :func:`integrate`.

    The state carry is DONATED (``donate_argnums=0``): without it XLA
    must keep both the input and output state alive across the loop —
    double-buffering every prognostic array — because the caller might
    still hold the input.  Integration carries are ping-pong by nature
    (the caller always replaces its state with the result), so donation
    lets XLA alias the two and halves the state's HBM residency.
    ``nsteps`` rides as a traced operand, so one executable serves any
    window length.  Callers must treat the passed-in state as consumed
    (re-donating an already-donated buffer is a runtime error on
    accelerators; CPU ignores donation).
    """
    fn = lambda y0, t0, nsteps: integrate(step, y0, t0, nsteps, dt,
                                          unroll=unroll)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def jit_integrate_with_history(step: Callable, dt: float, stride: int,
                               snapshot: Callable,
                               donate: bool = True) -> Callable:
    """``run(y0, t0, nsteps) -> (y, t, hist)`` over
    :func:`integrate_with_history`, state carry donated as in
    :func:`jit_integrate`.  ``nsteps`` is static here (the scan length
    must be concrete), so a new window length compiles a new program —
    use a fixed stride-aligned window for steady output cadences.
    """
    fn = lambda y0, t0, nsteps: integrate_with_history(
        step, y0, t0, nsteps, dt, stride, snapshot)
    return jax.jit(fn, static_argnums=2,
                   donate_argnums=(0,) if donate else ())
