"""Command-line entry: ``python -m jaxstream <cmd>``.

Subcommands:
  run <config.yaml>   end-to-end simulation from a config file
  info [config.yaml]  devices / mesh / grid summary without running
  schedule            print the race-free cube-edge exchange schedule
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_run(args):
    from .simulation import Simulation

    # Context-managed: drains/joins the async-pipeline writer thread
    # and closes the telemetry sink on the way out (a no-op when
    # io.async_pipeline is off).
    with Simulation(args.config) as sim:
        sim.run(args.nsteps)
        print(json.dumps({
            "steps": sim.step_count,
            "t_seconds": sim.t,
            "diagnostics": sim.diagnostics(),
        }))


def _cmd_info(args):
    import jax

    from .config import load_config
    from .parallel.mesh import setup_sharding

    cfg = load_config(args.config)
    devs = jax.devices()
    print(f"jax {jax.__version__}; {len(devs)} device(s): "
          f"{[f'{d.platform}:{d.id}' for d in devs]}")
    print(f"grid: C{cfg.grid.n} halo={cfg.grid.halo} dtype={cfg.grid.dtype} "
          f"({6 * cfg.grid.n ** 2} cells)")
    par = cfg.parallelization
    print(f"parallelization: tiles_per_edge={par.tiles_per_edge} "
          f"num_devices={par.num_devices} device_type={par.device_type} "
          f"use_shard_map={par.use_shard_map}")
    if par.num_devices > 1:
        try:
            setup = setup_sharding(cfg)
            print(f"mesh: panel={setup.panel} y={setup.sy} x={setup.sx}")
        except ValueError as e:
            print(f"mesh: unavailable here ({e})")
    tt = (f" numerics=tt(rank={cfg.model.tt_rank})"
          if cfg.model.numerics == "tt" else "")
    print(f"model: {cfg.model.initial_condition} scheme={cfg.model.scheme} "
          f"backend={cfg.model.backend}{tt}; dt={cfg.time.dt}s "
          f"duration={cfg.time.duration_days}d")


def _cmd_schedule(args):
    from .geometry.connectivity import build_connectivity, build_schedule

    schedule = build_schedule(build_connectivity())
    for s, stage in enumerate(schedule):
        pairs = ", ".join(
            f"F{l.face}.{'NESW'[l.edge]}<->F{b.face}.{'NESW'[b.edge]}"
            f"{'(rev)' if l.reversed_ else ''}"
            for l, b in stage
        )
        print(f"stage {s}: {pairs}")


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    # ``python -m jaxstream config.yaml`` == ``... run config.yaml``.
    if argv and argv[0] not in ("run", "info", "schedule", "-h", "--help"):
        argv = ["run"] + list(argv)

    p = argparse.ArgumentParser(prog="jaxstream")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("run", help="run a simulation from a config file")
    pr.add_argument("config")
    pr.add_argument("--nsteps", type=int, default=None,
                    help="override the configured duration")
    pr.set_defaults(fn=_cmd_run)

    pi = sub.add_parser("info", help="show devices / mesh / config summary")
    pi.add_argument("config", nargs="?", default=None)
    pi.set_defaults(fn=_cmd_info)

    ps = sub.add_parser("schedule", help="print the halo-exchange schedule")
    ps.set_defaults(fn=_cmd_schedule)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
