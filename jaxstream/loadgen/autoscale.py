"""Queue-depth / occupancy-driven autoscaling (round 14).

The policy is a PURE function — ``decide(policy, state, queue_depth,
occupancy) -> (state', target_bucket | None)`` — over the two signals
the PR-8 serving telemetry already records: request-queue depth (how
much traffic is waiting) and slot occupancy (how full the member axis
ran).  Purity is the testability contract: the hysteresis proofs in
tests/test_loadgen.py drive the function with synthetic observation
streams and assert it cannot flap, no servers involved.

Scaling acts on the ACTIVE BUCKET CAP (:meth:`EnsembleServer.resize`):
levels are an ascending subset of the server's configured bucket set,
all pre-compiled at warmup, so a resize swaps which warm executable
packs the next batch — zero recompiles by construction.  Under
``serve.placement`` the bucket cap IS the placement lever: each
bucket's :class:`BucketPlan` spans a fixed device count (a B=16 bucket
member-shards over 8 chips, B=4 over 4, B=1 runs single), so scaling
the cap up engages more chips and scaling down releases them.

Anti-flap hysteresis, three mechanisms stacked:

* **disjoint watermarks** — scale-up needs ``queue_depth >=
  queue_high``, scale-down needs ``queue_depth <= queue_low`` AND
  ``occupancy <= occ_low``, with ``queue_high > queue_low`` enforced
  at construction, so no single observation can satisfy both;
* **patience** — a direction must persist for ``patience``
  consecutive observations (a contradicting observation resets both
  streaks);
* **cooldown** — after any resize the policy ignores ``cooldown``
  observations, so consecutive resizes are at least ``cooldown +
  patience`` observations apart.

:class:`AutoscaleController` is the thin impure shell: it reads the
server's queue depth + last-segment occupancy, feeds the pure policy,
and applies resizes — the ``tick(server)`` callable
:meth:`EnsembleServer.serve_forever` evaluates at every segment
boundary (live autoscaling with no extra thread, deterministic given
the queue state; a resize ends the running batch's refill so packing
resumes at the new cap with the next batch).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

__all__ = ["AutoscalePolicy", "AutoscaleState", "decide",
           "AutoscaleController"]


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The pure scaling rule.  ``levels`` is the ascending ladder of
    active-bucket caps the policy may select (each must be a configured
    — therefore warm — server bucket)."""
    levels: Tuple[int, ...]
    queue_high: int = 4          # scale up at queue_depth >= queue_high
    queue_low: int = 0           # scale down at queue_depth <= queue_low
    occ_low: float = 0.5         # ... AND occupancy <= occ_low
    patience: int = 2            # consecutive observations required
    cooldown: int = 2            # observations ignored after a resize

    def __post_init__(self):
        levels = tuple(int(b) for b in self.levels)
        object.__setattr__(self, "levels", levels)
        if not levels or list(levels) != sorted(set(levels)):
            raise ValueError(
                f"levels must be a strictly ascending non-empty ladder, "
                f"got {self.levels}")
        if self.queue_high <= self.queue_low:
            raise ValueError(
                f"queue_high ({self.queue_high}) must exceed queue_low "
                f"({self.queue_low}) — disjoint watermarks are the "
                "anti-flap guarantee")
        if self.patience < 1 or self.cooldown < 0:
            raise ValueError(
                f"patience >= 1 and cooldown >= 0 required, got "
                f"patience={self.patience} cooldown={self.cooldown}")


@dataclasses.dataclass(frozen=True)
class AutoscaleState:
    """Immutable policy state threaded through :func:`decide`."""
    level: int = 0               # index into policy.levels
    up_streak: int = 0
    down_streak: int = 0
    cooldown_left: int = 0


def decide(policy: AutoscalePolicy, state: AutoscaleState,
           queue_depth: int, occupancy: float,
           ) -> Tuple[AutoscaleState, Optional[int]]:
    """One observation in, (new state, resize target | None) out.

    The target, when not None, is the bucket cap ``policy.levels[
    new_level]`` — the caller applies it (``server.resize``).  Pure:
    no clocks, no servers, no mutation.
    """
    if state.cooldown_left > 0:
        return dataclasses.replace(
            state, cooldown_left=state.cooldown_left - 1,
            up_streak=0, down_streak=0), None
    want_up = (queue_depth >= policy.queue_high
               and state.level < len(policy.levels) - 1)
    want_down = (queue_depth <= policy.queue_low
                 and occupancy <= policy.occ_low
                 and state.level > 0)
    if want_up:
        up = state.up_streak + 1
        if up >= policy.patience:
            new = dataclasses.replace(
                state, level=state.level + 1, up_streak=0,
                down_streak=0, cooldown_left=policy.cooldown)
            return new, policy.levels[new.level]
        return dataclasses.replace(state, up_streak=up,
                                   down_streak=0), None
    if want_down:
        down = state.down_streak + 1
        if down >= policy.patience:
            new = dataclasses.replace(
                state, level=state.level - 1, up_streak=0,
                down_streak=0, cooldown_left=policy.cooldown)
            return new, policy.levels[new.level]
        return dataclasses.replace(state, down_streak=down,
                                   up_streak=0), None
    return dataclasses.replace(state, up_streak=0, down_streak=0), None


class AutoscaleController:
    """The impure shell around :func:`decide` — the serving loop's
    per-segment-boundary ``tick(server)``.

    ``attach(server)`` validates the level ladder against the server's
    configured buckets and applies the initial level; each tick reads
    (queue depth, last-segment occupancy), runs the pure policy, and
    applies any resize through :meth:`EnsembleServer.resize` (which
    records the ``autoscale`` sink event).  ``events`` keeps the
    applied resizes for reports; ``summary()`` is the /v1/stats
    payload.
    """

    def __init__(self, policy: AutoscalePolicy,
                 state: Optional[AutoscaleState] = None):
        self.policy = policy
        self.state = state or AutoscaleState()
        self.events: List[dict] = []
        self.observations = 0

    def attach(self, server) -> None:
        bad = [b for b in self.policy.levels if b not in server.buckets]
        if bad:
            raise ValueError(
                f"autoscale levels {bad} are not configured server "
                f"buckets {list(server.buckets)} — every level must "
                "map to a warm executable (resizes must never compile)")
        server.resize(self.policy.levels[self.state.level],
                      reason="autoscale_attach")

    def __call__(self, server) -> Optional[int]:
        from ..serve.warmpool import HeadroomRefused

        queue_depth = len(server.queue)
        occupancy = float(server.stats.get("last_occupancy", 0.0))
        self.observations += 1
        prev = self.state
        self.state, target = decide(self.policy, self.state,
                                    queue_depth, occupancy)
        if target is None:
            return None
        try:
            old = server.resize(target, reason="autoscale",
                                queue_depth=queue_depth,
                                occupancy=occupancy)
        except HeadroomRefused:
            # Round 21: the server refused the scale-up on stamped
            # memory headroom (it already wrote the typed record).
            # Revert the LEVEL so the ladder stays truthful, but keep
            # the fresh cooldown — without it the policy would hammer
            # the refused level every observation.
            self.state = dataclasses.replace(self.state,
                                             level=prev.level)
            self.events.append({
                "observation": self.observations,
                "from_bucket": self.policy.levels[prev.level],
                "to_bucket": target, "queue_depth": queue_depth,
                "occupancy": round(occupancy, 4), "refused": True,
            })
            return None
        self.events.append({
            "observation": self.observations, "from_bucket": old,
            "to_bucket": target, "queue_depth": queue_depth,
            "occupancy": round(occupancy, 4),
        })
        return target

    def summary(self) -> dict:
        return {
            "levels": list(self.policy.levels),
            "level": self.state.level,
            "active_bucket": self.policy.levels[self.state.level],
            "observations": self.observations,
            "resizes": len(self.events),
            "events": list(self.events),
        }
