"""Closed-loop load harness + autoscaling (round 14).

The other half of the network front door: :mod:`.trace` generates
deterministic heavy-tailed arrival traces over the mixed Williamson/
Galewsky scenario population, :mod:`.harness` replays them against a
gateway over loopback HTTP and measures p50/p99 request latency,
goodput, and the typed-shed accounting, and :mod:`.autoscale` holds
the pure (queue depth, occupancy) -> bucket-cap policy (hysteresis,
cannot flap) plus the controller the serving loop ticks between
batches.  Together they earn the "heavy traffic" claim with measured
SLOs instead of asserting it — the ``serving_slo`` bench section and
``scripts/loadgen.py`` are the entry points.
"""

from .autoscale import (AutoscaleController, AutoscalePolicy,
                        AutoscaleState, decide)
from .harness import masked_records, run_load, summarize_outcomes
from .trace import generate_trace, read_trace, write_trace

__all__ = [
    "AutoscaleController", "AutoscalePolicy", "AutoscaleState",
    "decide", "generate_trace", "masked_records", "read_trace",
    "run_load", "summarize_outcomes", "write_trace",
]
