"""Closed-loop load harness over the HTTP gateway (round 14).

Replays a deterministic arrival trace (:mod:`.trace`) against a
gateway over loopback HTTP and measures what a production SLO cares
about: per-request latency percentiles (submit-to-final-result wall
time), goodput (member-steps of COMPLETED work per wall second — shed
or evicted work counts for nothing), and the shed/completed accounting
that proves overload behavior is the typed 429/503 contract.

"Closed loop" is meant twice:

* the client side runs a bounded worker pool — when every worker is
  busy, dispatch blocks, so offered load responds to service rate the
  way real clients with timeouts do (no unbounded open-loop pileup on
  the client);
* the serving side feeds its own telemetry (queue depth + occupancy)
  to the autoscale policy (:mod:`.autoscale`), which resizes the
  active bucket cap live — the measurement loop and the control loop
  close over the same signals.

Every outcome lands in the loadgen sink in TRACE ORDER from one writer
after the run (not arrival-of-completion order), so two runs of the
same trace produce byte-equal sink records once wall-clock fields are
masked — the replayability contract tests/test_loadgen.py asserts.
"""

from __future__ import annotations

import json
import threading
import time
from typing import List, Optional

import numpy as np

from ..gateway import protocol
from ..gateway.client import GatewayError, submit_streaming
from ..obs import trace as obs_trace
from ..obs.sink import TelemetrySink, read_records, run_manifest
from ..utils.logging import get_logger

__all__ = ["run_load", "summarize_outcomes", "masked_records",
           "SHED_STATUSES", "TIMING_FIELDS"]

log = get_logger(__name__)

#: Typed-shed outcome statuses (HTTP 429/503 admission refusals) —
#: the protocol's one error-code -> status map, value side.
SHED_STATUSES = tuple(protocol.SHED_STATUS.values())

#: Outcome/sink fields carrying wall-clock time — masked for the
#: byte-determinism comparison of two runs of the same trace.
TIMING_FIELDS = ("latency_s", "dispatched_at_s", "server_latency_s")


def _one_request(host: str, port: int, entry: dict, timeout: float,
                 trace: bool = False) -> dict:
    """Submit one trace entry, stream to completion, classify."""
    req = {k: entry[k] for k in
           ("id", "ic", "nsteps", "seed", "amplitude", "outputs")
           if k in entry}
    out = {"id": entry["id"], "ic": entry["ic"],
           "nsteps": int(entry["nsteps"])}
    if trace:
        # The deterministic trace identity — no protocol plumbing
        # needed (jaxstream.obs.trace digests the request id), so the
        # client-side records join the server's span trees by id.
        tid = obs_trace.trace_id_for(entry["id"])
        out["trace_id"] = tid
        out["span_id"] = obs_trace.span_id_for(tid, "client", 0)
        out["parent_id"] = obs_trace.root_span_id(tid)
    t0 = time.perf_counter()
    try:
        status, events = submit_streaming(host, port, req,
                                          timeout=timeout)
        out["latency_s"] = round(time.perf_counter() - t0, 6)
        out["http_status"] = status
        final = events[-1] if events else {}
        if final.get("event") == "result":
            out["status"] = final["summary"]["status"]      # ok/evicted
            out["steps_run"] = int(final["summary"]["steps_run"])
            if trace:
                # The server-reported end-to-end latency — the span
                # tree's root duration, which the completeness check
                # sums against (the client-side latency_s above
                # additionally carries the HTTP round trip).
                out["server_latency_s"] = float(
                    final["summary"].get("latency_s", 0.0))
        else:
            out["status"] = "error"
            out["steps_run"] = 0
            out["error"] = final.get("error", "truncated_stream")
        out["segments"] = sum(1 for ev in events
                              if ev.get("event") == "segment")
    except GatewayError as e:
        out["latency_s"] = round(time.perf_counter() - t0, 6)
        out["http_status"] = e.status
        shed = protocol.SHED_STATUS.get(e.error)
        out["status"] = shed or "error"
        out["steps_run"] = 0
        out["segments"] = 0
        if shed is None:
            out["error"] = e.error
    except Exception as e:
        out["latency_s"] = round(time.perf_counter() - t0, 6)
        out["http_status"] = 0
        out["status"] = "error"
        out["steps_run"] = 0
        out["segments"] = 0
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def run_load(host: str, port: int, trace: List[dict], *,
             time_scale: float = 1.0, max_workers: int = 8,
             request_timeout: float = 300.0,
             sink: str = "", dt: Optional[float] = None,
             trace_spans: bool = False,
             span_sinks: Optional[List[str]] = None) -> dict:
    """Replay ``trace`` against ``host:port``; return the SLO summary.

    ``time_scale`` multiplies the trace's arrival offsets (0 = replay
    as one burst); ``max_workers`` bounds in-flight client requests
    (the closed loop); ``dt`` (seconds per stepper call) converts
    goodput into aggregate sim-days/sec when given.  ``sink`` names a
    JSONL file for the per-request ``loadgen`` records + a ``bench``
    summary record.

    ``trace_spans`` (round 17): the gateway's deployment runs with
    ``serve.trace: true`` — loadgen records then carry
    ``trace_id``/``span_id``/``parent_id``, and when ``span_sinks``
    names the serve/gateway sink files the harness ASSERTS span
    completeness: every completed request must reassemble into exactly
    one root + >= 1 segment span whose leaf durations sum to the
    server-reported latency within the declared epsilon
    (``jaxstream.obs.trace``).  The summary gains ``spans_complete``
    (fraction) + ``span_failures`` — the bench ``serving_slo`` section
    enforces ``spans_complete == 1.0``.
    """
    sem = threading.BoundedSemaphore(max_workers)
    outcomes: List[Optional[dict]] = [None] * len(trace)
    threads = []
    t_start = time.perf_counter()

    def worker(i, entry):
        try:
            # Stamped BEFORE the request so the field really is the
            # dispatch offset (offered-load timeline), not completion.
            dispatched = round(time.perf_counter() - t_start, 6)
            out = _one_request(host, port, entry, request_timeout,
                               trace=trace_spans)
            out["dispatched_at_s"] = dispatched
            outcomes[i] = out
        finally:
            sem.release()

    # One short-lived DAEMON thread per dispatched request, bounded to
    # max_workers in flight by the semaphore.  Deliberately not a
    # ThreadPoolExecutor: its workers are non-daemon and joined at
    # interpreter exit, so one hung request would hang the CLI forever
    # — an abandoned (join-deadline-expired) daemon worker instead
    # dies with the process.  Thread churn is microseconds against an
    # HTTP round trip.
    for i, entry in enumerate(trace):
        target = float(entry.get("t", 0.0)) * time_scale
        delay = target - (time.perf_counter() - t_start)
        if delay > 0:
            time.sleep(delay)
        sem.acquire()                      # closed-loop backpressure
        th = threading.Thread(target=worker, args=(i, entry),
                              name=f"jaxstream-loadgen-{i}",
                              daemon=True)
        th.start()
        threads.append(th)
    # One overall deadline (not per-thread): the timeout bounds the
    # whole drain, not n_requests x timeout of sequential joins.
    deadline = time.perf_counter() + request_timeout
    for th in threads:
        th.join(max(0.0, deadline - time.perf_counter()))
    wall = time.perf_counter() - t_start
    # Freeze a snapshot: a worker that outlived its join timeout keeps
    # writing into `outcomes`, and the summary and the sink records
    # must agree with each other, not with whatever lands later.
    final = [dict(o) if o is not None else
             {"id": trace[i]["id"], "ic": trace[i]["ic"],
              "nsteps": int(trace[i]["nsteps"]), "status": "error",
              "error": "worker_timeout", "latency_s": wall,
              "http_status": 0, "steps_run": 0, "segments": 0}
             for i, o in enumerate(outcomes)]
    summary = summarize_outcomes(final, wall, dt=dt)
    if trace_spans and span_sinks:
        # Span-completeness assertion surface: every request the
        # harness saw COMPLETE (ok or evicted — the server owned it to
        # a final state) must have a full tree in the serve sinks.
        records = []
        for path in span_sinks:
            records.extend(read_records(path, kind="span"))
        latencies = {o["id"]: o.get("server_latency_s", 0.0)
                     for o in final if o["status"] in ("ok", "evicted")}
        cov = obs_trace.span_coverage(records, latencies)
        summary["spans_checked"] = cov["checked"]
        summary["spans_complete"] = cov["spans_complete"]
        summary["span_failures"] = cov["failures"]
    if sink:
        s = TelemetrySink(sink, run_manifest(config={
            "loadgen": True, "n_requests": len(trace),
            "time_scale": time_scale, "max_workers": max_workers,
        }))
        for out in final:                  # one writer, trace order
            s.write(dict(out, kind="loadgen"))
        s.write({"kind": "bench", "metric": "loadgen_summary",
                 "value": summary["goodput_member_steps_per_sec"],
                 "unit": "member-steps/sec goodput", **{
                     k: summary[k] for k in
                     ("completed", "shed", "errors", "latency_p50_s",
                      "latency_p99_s")}})
        s.close()
    return summary


def summarize_outcomes(outcomes: List[dict], wall_s: float,
                       dt: Optional[float] = None) -> dict:
    """Aggregate one run's outcomes into the SLO summary."""
    lat = np.asarray([o["latency_s"] for o in outcomes
                      if o["status"] == "ok"], np.float64)
    completed = sum(1 for o in outcomes if o["status"] == "ok")
    evicted = sum(1 for o in outcomes if o["status"] == "evicted")
    shed_by = {s: sum(1 for o in outcomes if o["status"] == s)
               for s in SHED_STATUSES}
    shed = sum(shed_by.values())
    errors = sum(1 for o in outcomes if o["status"] == "error")
    good_steps = sum(o.get("steps_run", 0) for o in outcomes
                     if o["status"] == "ok")
    summary = {
        "n_requests": len(outcomes),
        "completed": completed,
        "evicted": evicted,
        "shed": shed,
        "shed_by": shed_by,
        "errors": errors,
        # The overload contract: every request either completed
        # (ok/evicted — the server owned it to a final state) or was
        # shed with a TYPED 429/503.  Anything else is a bug.
        "accounting_exact": bool(
            completed + evicted + shed == len(outcomes) and errors == 0),
        "latency_p50_s": (round(float(np.percentile(lat, 50)), 4)
                          if len(lat) else None),
        "latency_p99_s": (round(float(np.percentile(lat, 99)), 4)
                          if len(lat) else None),
        "latency_max_s": (round(float(lat.max()), 4)
                          if len(lat) else None),
        "goodput_member_steps": int(good_steps),
        "goodput_member_steps_per_sec": round(good_steps / wall_s, 2)
        if wall_s > 0 else 0.0,
        "goodput_requests_per_sec": round(completed / wall_s, 3)
        if wall_s > 0 else 0.0,
        "wall_s": round(wall_s, 3),
    }
    if dt:
        summary["goodput_sim_days_per_sec"] = round(
            good_steps * dt / 86400.0 / wall_s, 4) if wall_s > 0 else 0.0
    return summary


def masked_records(path: str) -> List[str]:
    """The sink's ``loadgen`` records as canonical JSON strings with
    wall-clock fields zeroed — the byte-determinism comparison surface
    (two runs of the same trace must compare equal)."""
    out = []
    for rec in read_records(path, kind="loadgen"):
        for k in TIMING_FIELDS:
            if k in rec:
                rec[k] = 0.0
        out.append(json.dumps(rec, sort_keys=True))
    return out
