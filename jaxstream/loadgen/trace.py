"""Deterministic heavy-tailed arrival traces (round 14).

A trace is the load harness's replayable input: one JSONL line per
request, each carrying an arrival offset ``t`` (seconds from trace
start) plus the full :class:`ScenarioRequest` surface — IC family
drawn from a weighted mix of the Williamson/Galewsky scenario set, run
length from a ragged ladder (so members finish mid-segment and slots
refill), perturbation seed, and output subset.

Inter-arrival gaps are Lomax/Pareto-II distributed (``rng.pareto``):
genuinely heavy-tailed for ``tail_alpha <= 2`` — most gaps are short
(bursts that pile up the queue and trip the autoscaler) with rare long
silences (idle stretches that let it scale back down).  Everything is
driven by one seeded ``numpy`` generator, so a (seed, parameters) pair
reproduces the trace BYTE-for-byte — two generations of the same trace
serialize identically, which is what makes a load run replayable and
the loadgen sink comparable across runs (tests/test_loadgen.py).

Pure numpy + stdlib: no jax, importable anywhere (the CLI generates
traces on machines with no accelerator stack).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["DEFAULT_FAMILY_WEIGHTS", "DEFAULT_LENGTHS",
           "DEFAULT_OUTPUTS", "generate_trace", "write_trace",
           "read_trace"]

#: Default IC-family mix: the full scenario set the serving tier packs
#: (mixed-orography batches make tc5 ride with the flat families).
DEFAULT_FAMILY_WEIGHTS: Dict[str, float] = {
    "tc2": 0.3, "tc5": 0.3, "tc6": 0.2, "galewsky": 0.2,
}

#: Ragged run-length ladder (stepper calls) — deliberately not
#: segment-aligned so per-member masking and boundary refill are
#: always exercised.
DEFAULT_LENGTHS: Tuple[int, ...] = (1, 2, 3, 5, 8)

#: Output-subset choices a request may ask back.
DEFAULT_OUTPUTS: Tuple[Tuple[str, ...], ...] = (("h",), ("h", "u"))


def generate_trace(n_requests: int, seed: int, *,
                   mean_gap_s: float = 1.0, tail_alpha: float = 1.5,
                   family_weights: Optional[Dict[str, float]] = None,
                   lengths: Sequence[int] = DEFAULT_LENGTHS,
                   outputs: Sequence[Tuple[str, ...]] = DEFAULT_OUTPUTS,
                   amplitude: float = 1.0e-3,
                   id_prefix: str = "q") -> List[dict]:
    """``n_requests`` arrival entries, deterministic in ``seed``.

    ``mean_gap_s`` sets the mean inter-arrival gap (for
    ``tail_alpha > 1``; at ``alpha <= 1`` the Pareto mean diverges and
    ``mean_gap_s`` scales the distribution's minimum instead);
    ``tail_alpha`` the Pareto shape — smaller = heavier tail.  Entries
    are sorted by construction (cumulative gaps).
    """
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if tail_alpha <= 0:
        raise ValueError(f"tail_alpha must be > 0, got {tail_alpha}")
    weights = dict(family_weights or DEFAULT_FAMILY_WEIGHTS)
    fams = sorted(weights)
    p = np.asarray([weights[f] for f in fams], np.float64)
    if p.min() < 0 or p.sum() <= 0:
        raise ValueError(f"bad family weights {weights}")
    p = p / p.sum()
    lengths = [int(x) for x in lengths]
    if not lengths or min(lengths) < 1:
        raise ValueError(f"lengths must be positive ints, got {lengths}")
    outputs = [tuple(o) for o in outputs]

    rng = np.random.default_rng(seed)
    # Lomax gaps: mean of rng.pareto(a) is 1/(a-1) for a > 1.
    scale = (mean_gap_s * (tail_alpha - 1.0) if tail_alpha > 1.0
             else mean_gap_s)
    gaps = scale * rng.pareto(tail_alpha, size=n_requests)
    gaps[0] = 0.0                       # the first request opens the run
    ts = np.cumsum(gaps)
    trace = []
    for i in range(n_requests):
        fam = fams[int(rng.choice(len(fams), p=p))]
        trace.append({
            "t": round(float(ts[i]), 6),
            "id": f"{id_prefix}{i:04d}",
            "ic": fam,
            "nsteps": lengths[int(rng.integers(len(lengths)))],
            "seed": int(rng.integers(0, 2**31 - 1)),
            "amplitude": amplitude,
            "outputs": list(outputs[int(rng.integers(len(outputs)))]),
        })
    return trace


def write_trace(path: str, trace: List[dict]) -> None:
    """One sorted-key JSON line per entry — byte-stable for a given
    trace, so seed determinism is file-level."""
    with open(path, "w") as fh:
        for entry in trace:
            fh.write(json.dumps(entry, sort_keys=True) + "\n")


def read_trace(path: str) -> List[dict]:
    out = []
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i + 1}: not JSON ({e})")
            for key in ("t", "id", "ic", "nsteps"):
                if key not in entry:
                    raise ValueError(
                        f"{path}:{i + 1}: trace entry missing {key!r}")
            out.append(entry)
    if not out:
        raise ValueError(f"{path}: empty trace")
    return out
