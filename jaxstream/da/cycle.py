"""The EnKF cycling driver — forecast, observe, analyze, repeat.

Closes the forecast loop (ROADMAP open item 2): a hidden *truth* run
is observed through a seeded station network each ``da.cycle_steps``
steps, and the perturbed-IC member batch is pulled toward those
observations by the stochastic EnKF analysis
(:mod:`jaxstream.da.enkf`), then re-launched — the workload that turns
"runs test cases" into "runs a forecast system".  Two drivers share
every non-forecast piece (network, analysis jit, guards, sink
records):

* :func:`run_cycle` — **in-process**: the member batch rides the
  config's own batched stepper (the fused member-fold kernels where
  the plan resolves ``fused``, the vmapped classic otherwise), the
  forecast runs under :func:`jaxstream.stepping.integrate_with_metrics`
  with the round-18 ``h_spread``/``ens_mean_drift`` specs, and the
  spread-collapse / filter-divergence guards fire off the IN-LOOP
  device metric buffer — not a host-side recomputation.
* :func:`run_cycle_gateway` — **as a client**: the member batch (plus
  the hidden truth, riding the same bucket) persists across cycles
  *through the HTTP gateway* — per-member result fetch, analysis
  update, re-submit the analysis states as raw-array initial
  conditions (the round-18 ``ic: array`` request family).  One
  workload exercises admission, packing, per-member masking, result
  streaming and telemetry end to end.

Each cycle emits one typed ``da`` sink record (prior/posterior spread
and ensemble-mean RMSE vs the hidden truth, innovation statistics), so
``scripts/telemetry_report.py`` and the live dashboard render the
cycle as it runs.  All outputs are byte-deterministic for a given
config once :data:`DA_TIMING_KEYS` are masked.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Config, load_config
from ..geometry.cubed_sphere import build_grid
from ..models.shallow_water_cov import (ENSEMBLE_STATE_AXES,
                                        CovariantShallowWater)
from ..obs import metrics as obs_metrics
from ..obs.monitor import HealthError, HealthMonitor
from ..obs.sink import TelemetrySink, run_manifest
from ..physics import initial_conditions as ics
from ..plan import rules as plan_rules
from ..plan.plan import plan_for
from ..plan.proof import build_proof
from .. import stepping
from ..utils.logging import get_logger
from .enkf import area_weights, enkf_analysis, ensemble_rmse, \
    ensemble_spread
from .observations import (build_network, great_circle_weights,
                           perturbed_observations)

__all__ = ["DA_TIMING_KEYS", "DAGuards", "run_cycle",
           "run_cycle_gateway"]

log = get_logger(__name__)

#: ``da`` record keys that carry wall-clock time — masked by the
#: byte-determinism comparisons (everything else is deterministic for
#: a given config).
DA_TIMING_KEYS = ("wall_s",)


class DAGuards:
    """Spread-collapse / filter-divergence guards over the per-cycle
    ensemble statistics.

    Rides a :class:`jaxstream.obs.monitor.HealthMonitor` so guard
    events land in the same ``monitor.events`` surface every other
    guard in the repo uses (sink ``guard`` records, admission budgets).
    Two conditions, both classic EnKF failure modes:

    * ``spread_collapse``: the posterior spread fell below
      ``spread_collapse_factor`` times the INITIAL ensemble spread —
      the filter has become overconfident and will reject future
      observations (inflation too weak / observations too trusted).
    * ``filter_divergence``: the prior ensemble-mean RMSE exceeds
      ``divergence_ratio`` times the prior spread — the truth has left
      the ensemble's own uncertainty envelope, so the gain can no
      longer pull the mean back.

    Policy semantics mirror the monitor's: ``warn`` records and
    continues, ``halt`` raises :class:`HealthError` LOUDLY with the
    breaching cycle.
    """

    def __init__(self, policy: str, spread0: float,
                 collapse_factor: float, divergence_ratio: float):
        if policy not in ("off", "warn", "halt"):
            raise ValueError(
                f"da.guards={policy!r}; valid: 'off', 'warn', 'halt'")
        self.policy = policy
        self.spread0 = float(spread0)
        self.collapse_factor = float(collapse_factor)
        self.divergence_ratio = float(divergence_ratio)
        self.monitor = (HealthMonitor((), policy="warn")
                        if policy != "off" else None)

    @property
    def events(self) -> list:
        return self.monitor.events if self.monitor is not None else []

    def check(self, cycle: int, step: int, t: float,
              spread_prior: float, spread_post: float,
              rmse_prior: float) -> List[dict]:
        if self.monitor is None:
            return []
        breaches = []
        floor = self.collapse_factor * self.spread0
        if spread_post < floor:
            breaches.append((
                "spread_collapse", spread_post,
                f"posterior spread {spread_post:.3g} < "
                f"{self.collapse_factor:g} x initial spread "
                f"{self.spread0:.3g}"))
        if rmse_prior > self.divergence_ratio * max(spread_prior,
                                                    1e-30):
            breaches.append((
                "filter_divergence", rmse_prior,
                f"prior RMSE {rmse_prior:.3g} > "
                f"{self.divergence_ratio:g} x prior spread "
                f"{spread_prior:.3g}"))
        events = []
        for kind, value, detail in breaches:
            event = {
                "kind": "guard", "event": kind, "step": int(step),
                "t": float(t), "value": float(value),
                "policy": self.policy, "cycle": int(cycle),
                "last_good_step": self.monitor.last_good_step,
                "last_good_t": self.monitor.last_good_t,
            }
            events.append(event)
            self.monitor.events.append(event)
            log.warning("da guard: %s at cycle %d (%s) — policy %r",
                        kind, cycle, detail, self.policy)
            if self.policy == "halt":
                raise HealthError(kind, step, value,
                                  self.monitor.last_good_step,
                                  self.monitor.last_good_t)
        if not breaches:
            self.monitor.last_good_step = int(step)
            self.monitor.last_good_t = float(t)
        return events


class _Problem:
    """Shared setup of both drivers: grid, model, ICs, network,
    localization weights, analysis/stat jits.

    ``serving=True`` (the gateway-client driver) resolves the
    SERVING plan — the forecast executes in the deployment's bucket
    steppers, so that is the program the cycle's proof stamp names;
    the in-process driver resolves the config's own (da-marked)
    forecast plan, which the da-* rules constrain statically."""

    def __init__(self, cfg: Config, serving: bool = False):
        self.cfg = cfg
        d, ens = cfg.da, cfg.ensemble
        if d.cycles < 1:
            raise ValueError(
                f"da.cycles must be >= 1 to run a cycle, got "
                f"{d.cycles}")
        if d.cycle_steps < 1:
            raise ValueError(
                f"da.cycle_steps must be >= 1, got {d.cycle_steps}")
        if not 0.0 < d.spread_collapse_factor < 1.0:
            raise ValueError(
                "da.spread_collapse_factor must be in (0, 1), got "
                f"{d.spread_collapse_factor}")
        if d.inflation < 1.0:
            raise ValueError(
                f"da.inflation must be >= 1.0, got {d.inflation}")
        if ens.members < 2:
            # The serving resolution would not reach the da rules —
            # raise the same pointer the table carries.
            plan_rules.fail("da-needs-ensemble")
        # The plan layer owns composition legality (da-* rules on the
        # in-process forecast: members >= 2, dense f32 single-device
        # tiers, no temporal blocking) — rejected statically.
        self.plan = plan_for(cfg, serving=serving)
        self.proof = build_proof(self.plan)
        self.B = ens.members
        halo = cfg.grid.halo
        if cfg.model.scheme == "ppm":
            halo = max(halo, 3)
        dtype = {"float32": jnp.float32, "float64": jnp.float64,
                 "bfloat16": jnp.bfloat16}[cfg.grid.dtype]
        self.grid = build_grid(cfg.grid.n, halo=halo,
                               radius=cfg.grid.radius, dtype=dtype,
                               metrics=cfg.grid.metrics)
        p, m = cfg.physics, cfg.model
        name = m.initial_condition
        b_ext = None
        if name == "tc2":
            h, v = ics.williamson_tc2(self.grid, p.gravity, p.omega,
                                      alpha_rot=m.ic_angle)
        elif name == "tc5":
            h, v, b_ext = ics.williamson_tc5(self.grid, p.gravity,
                                             p.omega)
        elif name == "tc6":
            h, v = ics.williamson_tc6(self.grid, p.gravity, p.omega)
        elif name == "galewsky":
            h, v = ics.galewsky(self.grid, p.gravity, p.omega)
        else:
            raise ValueError(
                f"da cycling drives the shallow-water families "
                f"(tc2/tc5/tc6/galewsky); got initial_condition="
                f"{name!r}")
        self.model = CovariantShallowWater(
            self.grid, gravity=p.gravity, omega=p.omega, b_ext=b_ext,
            scheme=m.scheme, limiter=m.limiter,
            nu4=p.hyperdiffusion, backend=m.backend)
        # Hidden truth: the unperturbed IC.  Members 1..B of a (B+1)-
        # member perturbed draw — every member differs from the truth,
        # so the initial ensemble-mean error is nonzero and the
        # cycled-vs-free comparison measures the filter, not the IC.
        self.truth0 = self.model.initial_state(h, v)
        h_b = ics.perturbed_ensemble(self.grid, h, self.B + 1,
                                     seed=ens.seed,
                                     amplitude=ens.amplitude)
        members = [self.model.initial_state(h_b[i + 1], v)
                   for i in range(self.B)]
        self.ens0 = self.model.stack_ensemble(members)
        self.net = build_network(self.grid, d.nstations, d.obs_seed,
                                 d.obs_sigma)
        self.rho_xy = self.rho_yy = None
        if d.localization_km > 0.0:
            self.rho_xy, self.rho_yy = great_circle_weights(
                self.grid, self.net, d.localization_km)
        self.w = area_weights(self.grid)
        self.key0 = jax.random.PRNGKey(d.obs_seed)

        def stats_fn(h, truth_h):
            return {"spread": ensemble_spread(h, self.w),
                    "rmse": ensemble_rmse(h, truth_h, self.w)}

        def analysis_fn(h, u, truth_h, key):
            y_obs, eps = perturbed_observations(self.net, truth_h,
                                                key, self.B)
            h_a, u_a, st = enkf_analysis(
                h, u, self.net, y_obs, eps, inflation=d.inflation,
                rho_xy=self.rho_xy, rho_yy=self.rho_yy)
            st.update({f"{k}_post": v
                       for k, v in stats_fn(h_a, truth_h).items()})
            st.update(stats_fn(h, truth_h))
            return h_a, u_a, st

        self.stats = jax.jit(stats_fn)
        self.analysis = jax.jit(analysis_fn)

    def guards(self) -> DAGuards:
        d = self.cfg.da
        spread0 = float(self.stats(self.ens0["h"],
                                   self.truth0["h"])["spread"])
        return DAGuards(d.guards, spread0, d.spread_collapse_factor,
                        d.divergence_ratio)

    def manifest_config(self, mode: str, assimilate: bool) -> dict:
        d = self.cfg.da
        return {
            "da": True, "mode": mode, "assimilate": assimilate,
            "grid_n": self.cfg.grid.n, "dt": self.cfg.time.dt,
            "members": self.B, "cycles": d.cycles,
            "cycle_steps": d.cycle_steps, "nstations": self.net.p,
            "obs_sigma": d.obs_sigma, "inflation": d.inflation,
            "localization_km": d.localization_km,
            "plan": self.plan.key(), "proof_verdict":
                self.proof.verdict,
            "rules_version": plan_rules.RULES_VERSION,
        }


def _summary(mode: str, assimilate: bool, prob: _Problem,
             records: List[dict], guards: DAGuards) -> dict:
    rmses = [r["rmse"] for r in records]
    return {
        "mode": mode, "assimilate": assimilate,
        "plan": prob.plan.key(),
        "proof_verdict": prob.proof.verdict,
        "members": prob.B, "nstations": prob.net.p,
        "cycles": records,
        "final_rmse": rmses[-1] if rmses else None,
        "mean_rmse": (sum(rmses) / len(rmses)) if rmses else None,
        "final_spread": records[-1]["spread_post"] if records
        else None,
        "guard_events": list(guards.events),
    }


def run_cycle(config=None, assimilate: bool = True,
              sink: Optional[str] = None) -> dict:
    """In-process EnKF cycle on the config's batched stepper.

    ``assimilate=False`` runs the FREE ensemble — identical seeds,
    identical forecast executable, no analysis — the baseline the
    forecast claim is measured against.  Returns the summary dict
    (per-cycle records under ``"cycles"``); writes ``da`` sink
    records when ``da.sink`` (or ``sink``) names a path.
    """
    cfg = load_config(config)
    prob = _Problem(cfg)
    d, dt, seg = cfg.da, cfg.time.dt, cfg.da.cycle_steps
    m = prob.model

    fused = prob.plan.tier == "fused"
    if fused:
        # The batched compact carry (ENSEMBLE_CARRY_AXES layout): the
        # analysis rewrites h/u, so strips are re-packed per cycle.
        step = m.make_fused_step(dt, ensemble=prob.B)
        prep = m.ensemble_compact_state
    else:
        step = stepping.vmap_ensemble(
            m.make_step(dt, cfg.time.scheme), ENSEMBLE_STATE_AXES)
        prep = lambda st: st

    # Round-18 satellite: the ensemble statistics ride the DEVICE
    # metric buffer inside the compiled forecast segment — the guard
    # reads the in-loop h_spread row, not a host recomputation.
    ms = obs_metrics.build_metric_set(
        prob.grid, m, prob.ens0, ("h_spread", "ens_mean_drift"),
        dt, cfg.physics.gravity)
    metric_fn = lambda y, t: ms.values({"h": y["h"], "u": y["u"]})

    def forecast_fn(y, t, step0):
        return stepping.integrate_with_metrics(
            step, y, t, seg, dt, metric_fn, every=seg, n_samples=1,
            step0=step0, steps_per_call=1)

    forecast = jax.jit(forecast_fn)
    truth_step = m.make_step(dt, cfg.time.scheme)
    truth_seg = jax.jit(
        lambda y, t: stepping.integrate(truth_step, y, t, seg, dt,
                                        unroll=1))

    guards = prob.guards()
    sink_path = sink if sink is not None else d.sink
    tsink = (TelemetrySink(sink_path, run_manifest(
        config=prob.manifest_config("inprocess", assimilate)))
        if sink_path else None)
    records: List[dict] = []
    truth, y = prob.truth0, prep(prob.ens0)
    t = 0.0
    try:
        for c in range(d.cycles):
            w0 = time.perf_counter()
            truth, _ = truth_seg(truth, jnp.asarray(t, jnp.float32))
            y, _, buf = forecast(y, jnp.asarray(t, jnp.float32),
                                 jnp.int32(c * seg))
            buf_host = obs_metrics.fetch_buffer(buf)
            spread_inloop = float(buf_host[0, 0])
            drift_inloop = float(buf_host[1, 0])
            t = (c + 1) * seg * dt
            step_now = (c + 1) * seg
            h, u = y["h"], y["u"]
            key = jax.random.fold_in(prob.key0, c)
            if assimilate:
                h_a, u_a, st = prob.analysis(h, u, truth["h"], key)
                st = {k: float(v) for k, v in st.items()}
                y = prep({"h": h_a, "u": u_a})
            else:
                base = {k: float(v)
                        for k, v in prob.stats(h, truth["h"]).items()}
                st = dict(base)
                st.update({f"{k}_post": v for k, v in base.items()})
                st.update(innovation_mean=0.0, innovation_rms=0.0)
                y = prep({"h": h, "u": u})
            rec = {
                "kind": "da", "cycle": c, "step": step_now,
                "t": float(t), "mode": "inprocess",
                "spread": round(spread_inloop, 10),
                "rmse": round(st["rmse"], 10),
                "spread_post": round(st["spread_post"], 10),
                "rmse_post": round(st["rmse_post"], 10),
                "innovation_mean": round(st["innovation_mean"], 10),
                "innovation_rms": round(st["innovation_rms"], 10),
                "ens_mean_drift": round(drift_inloop, 10),
                "nobs": prob.net.p,
                "wall_s": round(time.perf_counter() - w0, 6),
            }
            records.append(rec)
            if tsink is not None:
                tsink.write(rec)
            try:
                events = guards.check(c, step_now, t, spread_inloop,
                                      st["spread_post"], st["rmse"])
            except HealthError:
                if tsink is not None:
                    for ev in guards.events:
                        tsink.write(ev)
                raise
            if tsink is not None:
                for ev in events:
                    tsink.write(ev)
    finally:
        if tsink is not None:
            tsink.close()
    return _summary("inprocess", assimilate, prob, records, guards)


def run_cycle_gateway(config=None, host: str = "127.0.0.1",
                      port: int = 0, assimilate: bool = True,
                      sink: Optional[str] = None,
                      timeout: float = 300.0) -> dict:
    """The EnKF cycle as a GATEWAY CLIENT (round 18's closed loop).

    Holds a persistent member batch — members 0..B-1 plus the hidden
    truth — across cycles through the HTTP gateway at ``(host,
    port)``: each cycle submits ``B + 1`` raw-array requests
    (``ic: array``), streams their results, runs the analysis update
    on the fetched member states, and re-submits the analysis states
    as the next cycle's initial conditions.  The truth rides the same
    batch (it is "hidden" from the *filter* — only its station
    observations enter the update), so every request packs into one
    bucket and per-member results are byte-deterministic run to run.

    The serving config should pin ``serve.buckets`` to the single
    bucket ``B + 1`` — a smaller warm bucket would let an early
    admission run in a different executable and break byte
    determinism across runs (docs/USAGE.md "Data assimilation").
    """
    from concurrent.futures import ThreadPoolExecutor

    from ..gateway import protocol, submit_streaming
    from ..gateway.client import final_result

    cfg = load_config(config)
    prob = _Problem(cfg, serving=True)
    d, dt, seg = cfg.da, cfg.time.dt, cfg.da.cycle_steps
    guards = prob.guards()
    sink_path = sink if sink is not None else d.sink
    tsink = (TelemetrySink(sink_path, run_manifest(
        config=prob.manifest_config("gateway", assimilate)))
        if sink_path else None)

    def submit_batch(cycle: int, states: Dict[str, dict]):
        """Submit one cycle's member batch; returns id -> result."""
        def one(item):
            rid, st = item
            body = {
                "id": rid, "ic": "array", "nsteps": seg,
                "outputs": ["h", "u"],
                "state": {k: protocol.encode_array(v)
                          for k, v in st.items()},
            }
            status, events = submit_streaming(host, port, body,
                                              timeout=timeout)
            res = final_result(events)
            if res is None or not res.ok:
                raise RuntimeError(
                    f"da gateway cycle {cycle}: request {rid!r} did "
                    f"not complete ok "
                    f"(status={getattr(res, 'status', None)!r})")
            return rid, res
        with ThreadPoolExecutor(max_workers=len(states)) as ex:
            return dict(ex.map(one, sorted(states.items())))

    def to_np(st):
        return {k: np.asarray(v) for k, v in st.items()}

    states = {f"m{i}": to_np(prob.model.member_state(prob.ens0, i))
              for i in range(prob.B)}
    states["truth"] = to_np(prob.truth0)
    records: List[dict] = []
    # Distinct id prefixes per run kind: a cycled run and its free
    # baseline often share one gateway (assimilate.py
    # --free-baseline), and trace ids are request-id digests — reused
    # ids would collide the two runs' span trees in the serve sink.
    prefix = "da" if assimilate else "dafree"
    try:
        for c in range(d.cycles):
            w0 = time.perf_counter()
            results = submit_batch(
                c, {f"{prefix}-c{c}-{k}": v
                    for k, v in states.items()})
            by_key = {rid.split("-", 2)[2]: res
                      for rid, res in results.items()}
            truth_h = jnp.asarray(by_key["truth"].fields["h"])
            h = jnp.stack([
                jnp.asarray(by_key[f"m{i}"].fields["h"])
                for i in range(prob.B)])
            u = jnp.stack([
                jnp.asarray(by_key[f"m{i}"].fields["u"])
                for i in range(prob.B)], axis=1)
            t = (c + 1) * seg * dt
            step_now = (c + 1) * seg
            key = jax.random.fold_in(prob.key0, c)
            if assimilate:
                # analysis_fn already computes the prior spread/rmse
                # on its inputs — no second stats launch needed.
                h_a, u_a, st = prob.analysis(h, u, truth_h, key)
                st = {k: float(v) for k, v in st.items()}
            else:
                h_a, u_a = h, u
                st = {k: float(v)
                      for k, v in prob.stats(h, truth_h).items()}
                st.update({f"{k}_post": st[k]
                           for k in ("spread", "rmse")})
                st.update(innovation_mean=0.0, innovation_rms=0.0)
            rec = {
                "kind": "da", "cycle": c, "step": step_now,
                "t": float(t), "mode": "gateway",
                "spread": round(st["spread"], 10),
                "rmse": round(st["rmse"], 10),
                "spread_post": round(st["spread_post"], 10),
                "rmse_post": round(st["rmse_post"], 10),
                "innovation_mean": round(st["innovation_mean"], 10),
                "innovation_rms": round(st["innovation_rms"], 10),
                "nobs": prob.net.p,
                "wall_s": round(time.perf_counter() - w0, 6),
            }
            records.append(rec)
            if tsink is not None:
                tsink.write(rec)
            try:
                events = guards.check(c, step_now, t, st["spread"],
                                      st["spread_post"], st["rmse"])
            except HealthError:
                if tsink is not None:
                    for ev in guards.events:
                        tsink.write(ev)
                raise
            if tsink is not None:
                for ev in events:
                    tsink.write(ev)
            h_np = np.asarray(h_a)
            u_np = np.asarray(u_a)
            states = {f"m{i}": {"h": h_np[i], "u": u_np[:, i]}
                      for i in range(prob.B)}
            states["truth"] = to_np(by_key["truth"].fields)
    finally:
        if tsink is not None:
            tsink.close()
    return _summary("gateway", assimilate, prob, records, guards)
