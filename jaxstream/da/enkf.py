"""The stochastic (perturbed-observations) EnKF analysis step.

Pure on-device linear algebra over the member axis — the whole update
is a handful of matmuls and one small solve, traced into ONE jit by
the cycle driver, so between forecast and analysis the ensemble state
never leaves the device (docs/DESIGN.md "EnKF as a service"):

* **Batched innovations**: every member's innovation ``d_i = (y + eps_i)
  - H x_i`` is formed in one ``(B, p)`` block (Burgers et al. 1998 —
  the stochastic perturbed-observations form, whose analysis ensemble
  has the correct posterior covariance in expectation).
* **B x B ensemble-space solve** (the default, ``localization_km: 0``):
  by the push-through identity the Kalman gain applied to the
  innovations is ``X'^T C^{-1} Y' D^T`` with ``C = (B-1) sigma^2 I_B +
  Y' Y'^T`` — a ``(B, B)`` solve however many cells or stations exist,
  the textbook reason the analysis lives comfortably on device.
* **Covariance localization by great-circle distance**
  (``localization_km > 0``): the Gaspari–Cohn taper of
  :func:`..da.observations.great_circle_weights` Schur-multiplies the
  sample covariances ``P_xy``/``P_yy``; the solve moves to observation
  space (``p x p`` — still tiny) because tapering breaks the low-rank
  structure the ensemble-space form exploits.  Small ensembles need
  this: spurious long-range sample covariances are what makes a raw
  B=4..16 EnKF update remote cells off noise.
* **Multiplicative inflation**: prior anomalies are scaled by
  ``inflation`` before the update — the standard counter to the
  sampling-error spread deficit that otherwise collapses the filter.

Every function is shape-polymorphic in the member count and f32
throughout (the serving tier's numerics — analysis states re-enter the
gateway as f32 ``ic: array`` payloads byte-unchanged).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..utils import diagnostics as diag
from .observations import ObservationNetwork, observe

__all__ = ["enkf_analysis", "ensemble_spread", "ensemble_rmse",
           "area_weights"]


def area_weights(grid):
    """Normalized interior cell-area weights ``(6, n, n)``, f32 (the
    analysis dtype) — so a coarse cubed-sphere corner cell does not
    count like an equatorial one.  The formula is the shared one in
    :mod:`jaxstream.utils.diagnostics` — the SAME weights back the
    in-loop ``h_spread`` MetricSpec, so the guard's prior (in-loop)
    and posterior (analysis) spreads can never drift apart."""
    return diag.ensemble_area_weights(grid, jnp.float32)


def ensemble_spread(h, w):
    """Area-weighted RMS ensemble spread of ``h`` ``(B, 6, n, n)``
    (:func:`jaxstream.utils.diagnostics.ensemble_spread`)."""
    return diag.ensemble_spread(h, w)


def ensemble_rmse(h, truth_h, w):
    """Area-weighted RMSE of the ensemble mean against the (hidden)
    truth field."""
    return diag.ensemble_mean_rmse(h, truth_h, w)


def _flatten_members(h, u):
    """(B, N) height block and (B, 2N) velocity block."""
    B = h.shape[0]
    return (h.reshape(B, -1),
            jnp.moveaxis(u, 1, 0).reshape(B, -1))


def enkf_analysis(h, u, net: ObservationNetwork, y_obs, obs_pert,
                  inflation: float = 1.0, rho_xy=None, rho_yy=None):
    """One analysis update of a member batch.

    ``h`` ``(B, 6, n, n)`` / ``u`` ``(2, B, 6, n, n)`` — the interior
    ensemble state in the repo's member-axis layout; ``y_obs`` ``(p,)``
    the measured station heights; ``obs_pert`` ``(B, p)`` the member
    observation perturbations (:func:`..da.observations.
    perturbed_observations`).  ``rho_xy``/``rho_yy`` switch on
    localization (both or neither).  Returns ``(h_a, u_a, stats)``
    with ``stats`` a dict of 0-d device scalars (innovation mean/RMS)
    — the caller fetches them with the cycle's one stats transfer.

    Both prognostics are updated by the same ensemble regression
    (heights observed, winds corrected through the sampled h–u
    covariances), which is what keeps analysis states balanced enough
    to re-enter the forecast without re-initialization.
    """
    if (rho_xy is None) != (rho_yy is None):
        raise ValueError("localization needs both rho_xy and rho_yy")
    B = h.shape[0]
    h_shape, u_shape = h.shape, u.shape
    Xh, Xu = _flatten_members(h, u)
    mh, mu = jnp.mean(Xh, axis=0), jnp.mean(Xu, axis=0)
    infl = jnp.asarray(inflation, Xh.dtype)
    Ah = infl * (Xh - mh)                  # prior anomalies, inflated
    Au = infl * (Xu - mu)
    Xh, Xu = mh + Ah, mu + Au              # the inflated prior
    h_prior = Xh.reshape(h_shape)
    Hx = observe(net, h_prior)             # (B, p)
    Yp = Hx - jnp.mean(Hx, axis=0)
    D = (y_obs[None, :] + obs_pert) - Hx   # batched innovations
    sigma2 = jnp.asarray(net.sigma, Xh.dtype) ** 2

    if rho_xy is None:
        # Ensemble-space form: K D^T = X'^T C^{-1} Y' D^T with
        # C = (B-1) sigma^2 I + Y' Y'^T  — one (B, B) solve.
        C = ((B - 1) * sigma2 * jnp.eye(B, dtype=Xh.dtype)
             + Yp @ Yp.T)
        W = jnp.linalg.solve(C, Yp @ D.T)  # (B, B)
        Xh_a = Xh + W.T @ Ah
        Xu_a = Xu + W.T @ Au
    else:
        # Observation-space form with Schur localization: P_yy and
        # P_xy tapered by great-circle distance, one (p, p) solve.
        Pyy = (rho_yy * (Yp.T @ Yp) / (B - 1)
               + sigma2 * jnp.eye(Yp.shape[1], dtype=Xh.dtype))
        S = jnp.linalg.solve(Pyy, D.T)     # (p, B)
        Kh = rho_xy * (Ah.T @ Yp) / (B - 1)            # (N, p)
        Ku = jnp.concatenate([rho_xy, rho_xy], axis=0) \
            * (Au.T @ Yp) / (B - 1)                    # (2N, p)
        Xh_a = Xh + (Kh @ S).T
        Xu_a = Xu + (Ku @ S).T

    innov = y_obs - jnp.mean(Hx, axis=0)
    stats = {
        "innovation_mean": jnp.mean(innov),
        "innovation_rms": jnp.sqrt(jnp.mean(innov * innov)),
    }
    h_a = Xh_a.reshape(h_shape)
    u_a = jnp.moveaxis(
        Xu_a.reshape((B, 2) + u_shape[2:]), 0, 1)
    return h_a, u_a, stats
