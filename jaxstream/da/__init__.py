"""Ensemble data assimilation (round 18): the EnKF cycle subsystem.

Closes ROADMAP open item 2 — synthetic observation networks over the
cubed sphere (:mod:`.observations`), the stochastic perturbed-
observations EnKF analysis as pure on-device linear algebra over the
member axis (:mod:`.enkf`), and the cycling driver (:mod:`.cycle`) in
two modes: in-process on the config's batched stepper, and as a
client holding a persistent member batch across cycles through the
HTTP gateway (``scripts/assimilate.py``; docs/USAGE.md "Data
assimilation").
"""

from .enkf import (area_weights, enkf_analysis, ensemble_rmse,
                   ensemble_spread)
from .observations import (ObservationNetwork, build_network,
                           great_circle_weights, observe,
                           perturbed_observations)
from .cycle import DA_TIMING_KEYS, DAGuards, run_cycle, \
    run_cycle_gateway

__all__ = [
    "ObservationNetwork", "build_network", "observe",
    "perturbed_observations", "great_circle_weights",
    "enkf_analysis", "ensemble_spread", "ensemble_rmse",
    "area_weights", "DA_TIMING_KEYS", "DAGuards", "run_cycle",
    "run_cycle_gateway",
]
