"""Synthetic observation networks over the cubed sphere.

The forecast loop's measurement half: a seeded *station set* — fixed
cell centers drawn deterministically over the sphere — whose
observation operator ``H`` is a pure-JAX gather over the interior
``(6, n, n)`` layout (advanced indexing on the last three axes, so the
SAME operator observes a single state or a whole ``(B, 6, n, n)``
member batch with no reshape).  Observations of the hidden truth run
are the truth's gathered heights plus seeded Gaussian error — the
standard synthetic-obs (OSSE) recipe the EnKF cycle assimilates
(Galewsky et al. 2004 jet as the chaotic test bed; docs/USAGE.md
"Data assimilation").

Everything is deterministic in ``(n, nstations, seed)``: station
draws use a ``numpy`` generator, observation noise a ``jax.random``
key folded per cycle, so two runs of one cycle configuration produce
byte-identical observation sequences (the acceptance criterion the
cycle tests byte-compare under).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["ObservationNetwork", "build_network", "observe",
           "perturbed_observations", "great_circle_weights"]


@dataclasses.dataclass(frozen=True)
class ObservationNetwork:
    """``nstations`` fixed h-observing stations at interior cell
    centers.  ``face``/``iy``/``ix`` index the interior ``(6, n, n)``
    layout; ``xyz`` is the stations' unit position (3, p) used for
    great-circle localization; ``sigma`` the observation error std
    (meters of h)."""

    face: np.ndarray            # (p,) int
    iy: np.ndarray              # (p,) int
    ix: np.ndarray              # (p,) int
    xyz: np.ndarray             # (3, p) float, unit vectors
    sigma: float

    @property
    def p(self) -> int:
        return int(self.face.shape[0])


def build_network(grid, nstations: int, seed: int,
                  sigma: float) -> ObservationNetwork:
    """Draw a seeded station set: ``nstations`` distinct interior
    cells, sampled uniformly over the global cell index space with a
    deterministic ``numpy`` generator.  Cell-uniform sampling is
    near-area-uniform on the cubed sphere (equiangular cells vary ~
    30% in area), which is all a synthetic network needs — the draw is
    part of the experiment's identity, not a physical station list."""
    n = grid.n
    if nstations < 1:
        raise ValueError(f"da.nstations must be >= 1, got {nstations}")
    if nstations > 6 * n * n:
        raise ValueError(
            f"da.nstations={nstations} exceeds the {6 * n * n} "
            f"interior cells of a C{n} grid")
    if sigma <= 0.0:
        raise ValueError(f"da.obs_sigma must be > 0, got {sigma}")
    rng = np.random.default_rng(seed)
    flat = rng.choice(6 * n * n, size=nstations, replace=False)
    flat.sort()                 # canonical order: network identity is
    face, rest = np.divmod(flat, n * n)      # the SET, not the draw
    iy, ix = np.divmod(rest, n)
    xyz_int = np.asarray(grid.interior(grid.xyz), np.float64)
    xyz = xyz_int[:, face, iy, ix]
    xyz = xyz / np.linalg.norm(xyz, axis=0, keepdims=True)
    return ObservationNetwork(face=face, iy=iy, ix=ix, xyz=xyz,
                              sigma=float(sigma))


def observe(net: ObservationNetwork, h):
    """The observation operator ``H``: gather station heights out of
    an interior ``(6, n, n)`` field — or a member batch ``(B, 6, n,
    n)``, returning ``(B, p)``.  A pure gather, so it traces into the
    analysis jit with no host round trip."""
    return h[..., net.face, net.iy, net.ix]


def perturbed_observations(net: ObservationNetwork, truth_h, key,
                           members: int):
    """One cycle's synthetic observations.

    Returns ``(y_obs, obs_perturbations)``: ``y_obs`` ``(p,)`` is
    ``H(truth) + sigma * eps0`` (the measured values), and
    ``obs_perturbations`` ``(B, p)`` the per-member stochastic
    observation perturbations of the perturbed-observations EnKF
    (Burgers et al. 1998) — drawn from the SAME fold of ``key`` so one
    key pins the cycle's whole stochastic state.
    """
    y_true = observe(net, truth_h)
    k_obs, k_mem = jax.random.split(key)
    eps0 = jax.random.normal(k_obs, y_true.shape, y_true.dtype)
    eps = jax.random.normal(k_mem, (members,) + y_true.shape,
                            y_true.dtype)
    return y_true + net.sigma * eps0, net.sigma * eps


def great_circle_weights(grid, net: ObservationNetwork,
                         radius_km: float):
    """Gaspari–Cohn-style covariance localization weights by
    great-circle distance.

    Returns ``(rho_xy, rho_yy)``: ``rho_xy`` ``(N, p)`` tapers the
    state–observation covariances (``N = 6 n^2`` interior cells, in
    flattened ``(6, n, n)`` order — the same order the analysis
    flattens state blocks into), ``rho_yy`` ``(p, p)`` the
    observation–observation covariances.  The taper is the compactly
    supported Gaspari & Cohn (1999) 5th-order polynomial with support
    ``2 * radius_km`` (half-width ``c = radius_km``), evaluated on the
    sphere's great-circle distances — zero beyond 2c, so distant
    spurious sample covariances are cut exactly.
    """
    if radius_km <= 0.0:
        raise ValueError(
            f"localization radius must be > 0 km, got {radius_km}")
    xyz_int = np.asarray(grid.interior(grid.xyz), np.float64)
    cells = xyz_int.reshape(3, -1)
    cells = cells / np.linalg.norm(cells, axis=0, keepdims=True)
    radius_m = float(radius_km) * 1.0e3

    def dist_to(points):
        cosang = np.clip(points.T @ net.xyz, -1.0, 1.0)
        return float(grid.radius) * np.arccos(cosang)

    rho_xy = _gaspari_cohn(dist_to(cells) / radius_m)
    rho_yy = _gaspari_cohn(dist_to(net.xyz) / radius_m)
    return (jnp.asarray(rho_xy, jnp.float32),
            jnp.asarray(rho_yy, jnp.float32))


def _gaspari_cohn(r: np.ndarray) -> np.ndarray:
    """Gaspari & Cohn (1999) eq. 4.10 taper; ``r`` = distance / c
    (c the half-support).  1 at r=0, 0 for r >= 2."""
    r = np.asarray(r, np.float64)
    out = np.zeros_like(r)
    near = r <= 1.0
    far = (r > 1.0) & (r < 2.0)
    x = r[near]
    out[near] = (-0.25 * x**5 + 0.5 * x**4 + 0.625 * x**3
                 - (5.0 / 3.0) * x**2 + 1.0)
    x = r[far]
    out[far] = (x**5 / 12.0 - 0.5 * x**4 + 0.625 * x**3
                + (5.0 / 3.0) * x**2 - 5.0 * x + 4.0
                - 2.0 / (3.0 * x))
    return np.clip(out, 0.0, 1.0)
