"""Vector halo exchange with great-circle (panel-basis) rotation.

The reference demonstrably exchanged vector fields in Cartesian components
("Cosine Bell Advection ... Cartesian Velocity Exchange", deck p.18) —
that path is the flagship one here too (:mod:`jaxstream.parallel.halo`
carries a leading component axis through untouched).  The north star's
alternative formulation carries velocity as *panel-local contravariant
components* ``(u^alpha, u^beta)`` and rotates them between panel bases at
each edge; this module implements that exchange.

The rotation is exact relative to the Cartesian route: a ghost cell's
value is the neighbor's vector re-expressed in the local panel's
(halo-extended) dual basis,

    T[i][j] = a_i^local(x_ghost) . e_j^nbr(x_src),

so ``T @ (u^a', u^b')_nbr = a^local . v_cartesian`` identically — the two
exchange formulations agree to roundoff (tested).  The 2x2 strips are
precomputed once at setup from the grid's stored bases; the hot path is
24 gathers + small elementwise FMAs + 24 scatters, fully fused under the
step ``jit``.

Layout: ``(2, 6, M, M)`` — component axis leading, like Cartesian vectors.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import jax.numpy as jnp

from ..geometry.connectivity import build_connectivity, build_schedule
from ..geometry.cubed_sphere import CubedSphereGrid
from .halo import _fill_corners, read_strip, write_strip

__all__ = ["make_vector_halo_exchanger", "to_contravariant", "to_cartesian"]


def to_contravariant(grid: CubedSphereGrid, v):
    """Cartesian ``(3, 6, M, M)`` -> contravariant ``(2, 6, M, M)``."""
    return jnp.stack([
        jnp.sum(v * grid.a_a, axis=0),
        jnp.sum(v * grid.a_b, axis=0),
    ])


def to_cartesian(grid: CubedSphereGrid, uv):
    """Contravariant ``(2, 6, M, M)`` -> Cartesian ``(3, 6, M, M)``."""
    return uv[0][None] * grid.e_a + uv[1][None] * grid.e_b


def _strip_indices(n: int, halo: int):
    """Index maps from canonical strip frame to flat (M*M) positions.

    Returns ``(src_idx, dst_idx)``: ``src_idx[edge]`` flat positions (in
    one face's (M, M)) of the interior boundary strip read by
    :func:`read_strip` in canonical (depth, along) order, and
    ``dst_idx[edge]`` the ghost-ring positions written by
    :func:`write_strip` for a canonical strip.
    """
    m = n + 2 * halo
    flat = np.arange(m * m).reshape(1, m, m)
    src_idx, dst_idx = [], []
    for e in range(4):
        s = np.asarray(read_strip(jnp.asarray(flat), 0, e, halo, n))
        src_idx.append(s.reshape(halo * n))
        marker = jnp.asarray(np.arange(halo * n).reshape(halo, n))
        out = np.asarray(
            write_strip(jnp.asarray(np.full((1, m, m), -1)), 0, e, marker)
        )[0]
        pos = np.argsort(out.ravel())[m * m - halo * n:]  # where out >= 0
        order = out.ravel()[pos]
        dst = np.empty(halo * n, dtype=np.int64)
        dst[order] = pos
        dst_idx.append(dst)
    return src_idx, dst_idx


def make_vector_halo_exchanger(
    grid: CubedSphereGrid,
    fill_corners: bool = True,
    components: str = "contravariant",
) -> Callable:
    """Build ``exchange(uv) -> uv`` for panel-local ``(2, 6, M, M)``.

    Ghost values are the neighbor's components rotated into the local
    panel's extended basis (see module docstring).  For contravariant
    components ``(u^a, u^b)`` the rotation is
    ``T[i][j] = a_i^local(ghost) . e_j^nbr(src)``; for covariant
    components ``(u_a, u_b) = (v.e_a, v.e_b)`` the roles of the two bases
    swap: ``T[i][j] = e_i^local(ghost) . a_j^nbr(src)`` (both follow from
    re-expressing the same Cartesian vector in the local basis).  Pure
    function; trace it inside the step ``jit``.
    """
    if components not in ("contravariant", "covariant"):
        raise ValueError(f"unknown components {components!r}")
    n, halo = grid.n, grid.halo
    m = grid.m
    adj = build_connectivity()
    schedule = build_schedule(adj)
    src_idx, dst_idx = _strip_indices(n, halo)

    # Basis arrays as host numpy for the precompute, in grid dtype.
    e_a = np.moveaxis(np.asarray(grid.e_a), 0, -1).reshape(6, m * m, 3)
    e_b = np.moveaxis(np.asarray(grid.e_b), 0, -1).reshape(6, m * m, 3)
    a_a = np.moveaxis(np.asarray(grid.a_a), 0, -1).reshape(6, m * m, 3)
    a_b = np.moveaxis(np.asarray(grid.a_b), 0, -1).reshape(6, m * m, 3)

    copies = []
    for stage in schedule:
        for pair in stage:
            for link in pair:
                src_flat = src_idx[link.nbr_edge].reshape(halo, n)
                if link.reversed_:
                    src_flat = src_flat[:, ::-1]
                src_flat = src_flat.reshape(-1)
                dst_flat = dst_idx[link.edge]
                # Contravariant: T[k,i,j] = a_i^local(ghost k).e_j^nbr(src k);
                # covariant: e_i^local . a_j^nbr.
                loc = (a_a, a_b) if components == "contravariant" else (e_a, e_b)
                nbr = (e_a, e_b) if components == "contravariant" else (a_a, a_b)
                al = np.stack([loc[0][link.face, dst_flat],
                               loc[1][link.face, dst_flat]], axis=1)  # (hn,2,3)
                en = np.stack([nbr[0][link.nbr_face, src_flat],
                               nbr[1][link.nbr_face, src_flat]], axis=2)  # (hn,3,2)
                T = al @ en  # (hn, 2, 2)
                copies.append((
                    link.face,
                    link.nbr_face,
                    jnp.asarray(src_flat),
                    jnp.asarray(dst_flat),
                    jnp.asarray(T.astype(np.asarray(grid.e_a).dtype)),
                ))

    def exchange(uv):
        if uv.shape != (2, 6, m, m):
            raise ValueError(
                f"vector halo exchanger built for n={n}, halo={halo} expects "
                f"(2, 6, {m}, {m}), got {uv.shape}"
            )
        flatuv = uv.reshape(2, 6, m * m)
        # All reads against the pre-exchange field (ghost targets are never
        # strip sources, so staging order is irrelevant here).
        updates = []
        for dst_f, src_f, s_idx, d_idx, T in copies:
            comp = flatuv[:, src_f, :][:, s_idx]          # (2, h*n)
            rot = jnp.einsum("kij,jk->ik", T, comp)        # (2, h*n)
            updates.append((dst_f, d_idx, rot))
        for dst_f, d_idx, rot in updates:
            flatuv = flatuv.at[:, dst_f, d_idx].set(rot)
        out = flatuv.reshape(2, 6, m, m)
        if fill_corners:
            out = _fill_corners(out, halo, n)
        return out

    return exchange
