"""Multi-host runtime: DCN across hosts, ICI within (SURVEY.md §2.6).

The reference runs single-process with GSPMD-implicit collectives and
names no communication backend; its declared scaling direction is more
tiles over more chips ("flexible 1 to 54+ devices", deck p.8).  On TPU
pods that means multi-host SPMD: one Python process per host, all hosts
running the same program, with XLA routing collectives over ICI inside a
slice and DCN between slices.  This module is that tier:

  * :func:`initialize` — ``jax.distributed.initialize`` from explicit
    args or the TPU environment (on Cloud TPU all arguments are
    auto-detected; on CPU/GPU clusters pass coordinator/num/id or set
    ``JAXSTREAM_COORD/NPROC/PROC_ID``).
  * :func:`pod_mesh` — the global ``('panel', 'y', 'x')`` mesh over all
    processes' devices, laid out so the panel axis (the halo-exchange
    axis: 12 edge permutes/step) stays *within* a host's ICI domain
    whenever ``local_device_count >= 6``, and the y/x block axes — whose
    sub-panel halos are nearest-neighbor — span hosts.
  * :func:`process_local_state` — build each host's shard of a global
    array without materializing the global (per-host IC evaluation +
    ``jax.make_array_from_process_local_data``).

Single-process callers get the same API: ``initialize`` is a no-op and
``pod_mesh`` degrades to the local-device mesh, so driver code is
identical from laptop CPU to pod.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.logging import get_logger

__all__ = ["initialize", "pod_mesh", "process_local_state", "is_distributed"]

log = get_logger(__name__)

_initialized = False


def is_distributed() -> bool:
    return jax.process_count() > 1


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Start the JAX distributed runtime (idempotent; no-op single-host).

    On Cloud TPU VMs all three arguments auto-detect from the metadata
    server; elsewhere they come from the arguments or the environment
    (``JAXSTREAM_COORD``, ``JAXSTREAM_NPROC``, ``JAXSTREAM_PROC_ID``).
    Must run before the backend initializes (same ordering rule as the
    reference's virtual-device flag, which it got wrong —
    ``JAX-DevLab-Examples.py:64-73``; SURVEY.md §7 pitfalls).
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("JAXSTREAM_COORD")
    num_processes = num_processes or _env_int("JAXSTREAM_NPROC")
    process_id = process_id if process_id is not None else _env_int("JAXSTREAM_PROC_ID")
    # A pod means multiple workers; single-entry TPU_WORKER_HOSTNAMES (set
    # by some single-chip TPU runtimes) must NOT trigger auto-init.
    workers = [w for w in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if w]
    on_tpu_pod = len(workers) > 1 or "MEGASCALE_COORDINATOR_ADDRESS" in os.environ
    if coordinator_address is None and not on_tpu_pod:
        log.info("distributed: single-process (no coordinator configured)")
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as e:
        # Pod-looking env without a reachable coordinator (or already
        # initialized): stay single-process rather than dying at import
        # time of every driver.
        log.warning("distributed: auto-init failed (%s); running single-process", e)
        return
    _initialized = True
    log.info(
        "distributed: process %d/%d, %d local + %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def pod_mesh(
    devices: Optional[Sequence] = None,
    panel: int = 6,
    axis_names=("panel", "y", "x"),
) -> Mesh:
    """Global 3-D mesh over every process's devices, ICI-aware.

    Device order: ``jax.devices()`` is grouped by process, and within a
    process by ICI locality.  Reshaping that order to ``(panel, y, x)``
    row-major puts the fastest-varying axis ('x') on adjacent devices, so
    with ``local >= 6`` whole panels sit inside one host: the 12
    cube-edge permutes ride ICI, and only sub-panel strip halos (y/x
    axes) cross DCN — the traffic that is both smallest and
    nearest-neighbor.  With fewer local devices than panels the panel
    axis necessarily spans hosts; the mesh is still valid, just
    DCN-heavier (log notes which).
    """
    devs = list(devices if devices is not None else jax.devices())
    d = len(devs)
    if d % panel:
        raise ValueError(
            f"device count {d} not divisible by panel={panel}; pass an "
            f"explicit device subset (got {d} global devices)"
        )
    rest = d // panel
    sy = int(np.sqrt(rest))
    while rest % sy:
        sy -= 1
    sx = rest // sy
    arr = np.array(devs).reshape(panel, sy, sx)
    local = jax.local_device_count()
    if local >= panel and d > local:
        log.info("pod mesh: panel axis within hosts (ICI); y/x over DCN")
    elif d > local:
        log.info("pod mesh: panel axis spans hosts (DCN on the edge permutes)")
    return Mesh(arr, axis_names)


def process_local_state(mesh: Mesh, spec: P, make_local):
    """Assemble a globally-sharded array from per-host pieces.

    ``make_local(index: tuple[slice, ...], global_shape) -> np.ndarray``
    is called once per host with the index block this host owns; the
    result becomes the local shard (no host ever holds the global array
    — required once C-scale fields exceed one host's memory).
    """
    sharding = NamedSharding(mesh, spec)

    def build(global_shape):
        # All local devices' slices; make_local evaluates only this block.
        local_idx = sharding.addressable_devices_indices_map(tuple(global_shape))
        # Union of this host's slices is a contiguous block in each dim
        # for row-major meshes; evaluate per device shard and stitch.
        arrays = [
            jax.device_put(np.ascontiguousarray(make_local(idx, global_shape)), dev)
            for dev, idx in local_idx.items()
        ]
        return jax.make_array_from_single_device_arrays(
            tuple(global_shape), sharding, arrays
        )

    return build
