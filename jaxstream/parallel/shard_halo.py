"""Explicit shard_map halo exchange: hand-scheduled ``lax.ppermute``.

The TPU-native flagship communication path (SURVEY.md §2.6): instead of
letting GSPMD infer collectives from whole-array updates (the reference's
implicit model, ``/root/reference/JAX-DevLab-Examples.py:192-195``), the
exchange runs *inside* ``jax.shard_map`` with the cube's 12 edge swaps
lowered to four ``lax.ppermute`` collectives over the ``'panel'`` mesh
axis — riding ICI with compile-time source/target pairs.

The mapping is exact: the reference's 4-stage race-free schedule (deck
p.9) is a proper edge coloring whose stages are perfect matchings on the
6 faces, so each stage *is* one bijective ``ppermute`` — every device
sends exactly one strip and receives exactly one strip per stage.  The
race-freedom invariant the reference enforces by staging becomes a
structural property of the collective.

Per-device variation (which of my 4 edges participates this stage;
whether the along-edge index reverses) cannot be Python control flow in
an SPMD program, so it is carried as *data*: small ``(6, 4)`` parameter
arrays sharded ``P('panel')``, selected with ``jnp.take``/``lax.switch``
on the local scalar.  The program stays uniform; the data differs.

Two tiers:

* :func:`make_shard_halo_program` — one face per device (``panel=6``
  mesh axis), the flagship 6-chip configuration.
* :func:`make_block_halo_program` — sub-panel tiling, the reference's
  planned ``tiles_per_edge > 1`` scaling
  (``/root/reference/JAX-DevLab-Examples.py:31-37``, left unimplemented
  there) made real: each face block-decomposes over the ``('y', 'x')``
  mesh axes (``s x s`` blocks, ``6 s^2`` devices).  Intra-panel halos are
  4 neighbor ``ppermute``s over the 'x'/'y' axes; the 12 cube edges
  remain 4 race-free stages, each ONE joint ``ppermute`` over the full
  ``('panel', 'y', 'x')`` product axis whose pairs connect only the
  face-boundary blocks (block index mirrored when the edge pair reverses
  orientation).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..geometry.connectivity import (
    EDGE_E,
    EDGE_N,
    EDGE_S,
    EDGE_W,
    build_connectivity,
    build_schedule,
)
from .halo import _fill_corners, read_strip, write_strip

__all__ = [
    "BlockHaloProgram",
    "ShardHaloProgram",
    "make_block_halo_program",
    "make_shard_halo_program",
]


class ShardHaloProgram:
    """Static schedule + per-device parameters for the ppermute exchange.

    Attributes:
      perms: list of 4 permutation lists [(src, dst), ...] — one bijection
        per stage, passed to ``lax.ppermute``.
      edge_sel: (6, 4) int32, ``edge_sel[f, s]`` = which edge of face f
        exchanges in stage s (my *send* strip and my *write* ghost edge).
      rev_sel: (6, 4) bool, whether the pair's along-edge index reverses.
    """

    def __init__(self, axis_name: str = "panel"):
        adj = build_connectivity()
        schedule = build_schedule(adj)
        self.axis_name = axis_name
        self.perms = []
        edge_sel = np.zeros((6, len(schedule)), dtype=np.int32)
        rev_sel = np.zeros((6, len(schedule)), dtype=bool)
        for s, stage in enumerate(schedule):
            perm = []
            for link, back in stage:
                perm.append((link.face, link.nbr_face))
                perm.append((back.face, back.nbr_face))
                edge_sel[link.face, s] = link.edge
                edge_sel[back.face, s] = back.edge
                rev_sel[link.face, s] = link.reversed_
                rev_sel[back.face, s] = back.reversed_
            # Perfect matching => bijection on all 6 faces.
            assert sorted(d for _, d in perm) == list(range(6))
            self.perms.append(perm)
        self.edge_sel = jnp.asarray(edge_sel)
        self.rev_sel = jnp.asarray(rev_sel)

    @property
    def params(self):
        """The (6, 4) per-device parameter arrays; shard with P('panel')."""
        return {"edge_sel": self.edge_sel, "rev_sel": self.rev_sel}


def make_shard_halo_program(
    n: int,
    halo: int,
    axis_name: str = "panel",
    fill_corners: bool = True,
):
    """Build ``(program, local_exchange)`` for use inside ``shard_map``.

    ``local_exchange(block, edge_sel, rev_sel)`` operates on a local
    ``(..., 1, M, M)`` extended block (one face per device) with this
    device's ``(1, 4)`` parameter rows, and performs the full cube-edge
    halo fill in 4 ``ppermute`` stages.
    """
    program = ShardHaloProgram(axis_name)

    def local_exchange(block, edge_sel, rev_sel):
        if block.shape[-3] != 1:
            raise ValueError(
                f"shard-halo path expects one face per device; got local "
                f"panel extent {block.shape[-3]} (use the GSPMD path in "
                f"jaxstream.parallel.halo for other tilings)"
            )
        for s, perm in enumerate(program.perms):
            block = _cube_stage(
                block, halo, n, axis_name, perm,
                edge_sel[0, s], rev_sel[0, s], active=None,
            )
        if fill_corners:
            block = _fill_corners(block, halo, n)
        return block

    return program, local_exchange


def _cube_stage(block, halo, n_loc, axis, perm, e_s, r_s, active):
    """One race-free cube-edge stage on a local 1-face block.

    Shared by the one-face-per-device and block-mesh programs: select my
    canonical strip for this stage by data, flip if the pair reverses,
    ``ppermute``, and write the received strip into my ghost ring.
    ``active=None`` means every device participates (perfect matching on
    faces); otherwise a scalar bool guards the write (boundary blocks
    only).
    """
    writers = [
        functools.partial(write_strip, face=0, edge=e) for e in range(4)
    ] + [lambda b, strip: b]  # branch 4: inactive, keep block
    # All 4 canonical strips; select mine for this stage by data.
    strips = jnp.stack(
        [read_strip(block, 0, e, halo, n_loc) for e in range(4)]
    )
    strip = jnp.take(strips, e_s, axis=0)
    strip = jnp.where(r_s, jnp.flip(strip, axis=-1), strip)
    strip = lax.ppermute(strip, axis, perm)
    idx = e_s if active is None else jnp.where(active, e_s, 4)
    return lax.switch(
        idx, [lambda b, st, w=w: w(b, strip=st) for w in writers],
        block, strip,
    )


def _block_coords(edge: int, k: int, s: int):
    """(iy, ix) mesh coordinates of block ``k`` along a face edge."""
    if edge == EDGE_S:
        return 0, k
    if edge == EDGE_N:
        return s - 1, k
    if edge == EDGE_W:
        return k, 0
    if edge == EDGE_E:
        return k, s - 1
    raise ValueError(edge)


class BlockHaloProgram:
    """Static schedule + per-device parameters for the block-mesh exchange.

    Devices form a ``(6, s, s)`` mesh ``('panel', 'y', 'x')``; each holds
    one extended ``(n_loc + 2*halo)^2`` block of a face (``n_loc = n/s``).

    Attributes:
      intra_perms: 4 ``(axis_name, [(src, dst), ...])`` neighbor shifts
        (E->W, W->E, N->S, S->N ghost fills within a face).
      cube_perms: 4 permutation lists over the joint ``(panel, y, x)``
        linear index (row-major), one per race-free schedule stage.
      edge_sel / rev_sel / active: ``(6, s, s, 4)`` per-device tables —
        which local edge exchanges in stage t, whether its along-edge
        index reverses, and whether this block participates (only
        face-boundary blocks touch cube edges).
    """

    def __init__(self, s: int, axis_names=("panel", "y", "x")):
        adj = build_connectivity()
        schedule = build_schedule(adj)
        self.s = s
        self.axis_names = tuple(axis_names)
        ax_panel, ax_y, ax_x = self.axis_names

        # Intra-panel neighbor shifts (empty perms when s == 1).
        fwd = [(i, i + 1) for i in range(s - 1)]
        bwd = [(i + 1, i) for i in range(s - 1)]
        self.intra_perms = [
            (ax_x, fwd, EDGE_E, EDGE_W),   # east strip -> eastern nbr's W ghost
            (ax_x, bwd, EDGE_W, EDGE_E),
            (ax_y, fwd, EDGE_N, EDGE_S),
            (ax_y, bwd, EDGE_S, EDGE_N),
        ]

        def lin(f, iy, ix):
            return (f * s + iy) * s + ix

        nstages = len(schedule)
        edge_sel = np.zeros((6, s, s, nstages), dtype=np.int32)
        rev_sel = np.zeros((6, s, s, nstages), dtype=bool)
        active = np.zeros((6, s, s, nstages), dtype=bool)
        self.cube_perms = []
        for t, stage in enumerate(schedule):
            perm = []
            for pair in stage:
                for link in pair:
                    # link: my face/edge -> neighbor face/edge (directed).
                    for k in range(s):
                        kk = s - 1 - k if link.reversed_ else k
                        src = lin(link.face, *_block_coords(link.edge, k, s))
                        dst = lin(link.nbr_face,
                                  *_block_coords(link.nbr_edge, kk, s))
                        perm.append((src, dst))
                    for k in range(s):
                        iy, ix = _block_coords(link.edge, k, s)
                        edge_sel[link.face, iy, ix, t] = link.edge
                        rev_sel[link.face, iy, ix, t] = link.reversed_
                        active[link.face, iy, ix, t] = True
            # Participants pair off exactly: every boundary block of the
            # stage's 6 edges sends one strip and receives one strip.
            assert len(set(d for _, d in perm)) == len(perm)
            self.cube_perms.append(perm)
        self.edge_sel = jnp.asarray(edge_sel)
        self.rev_sel = jnp.asarray(rev_sel)
        self.active = jnp.asarray(active)

    @property
    def params(self):
        """(6, s, s, 4) per-device tables; shard P('panel', 'y', 'x')."""
        return {"edge_sel": self.edge_sel, "rev_sel": self.rev_sel,
                "active": self.active}


def make_block_halo_program(
    n: int,
    halo: int,
    s: int,
    axis_names=("panel", "y", "x"),
    fill_corners: bool = True,
):
    """Build ``(program, local_exchange)`` for an ``s x s``-blocked mesh.

    ``local_exchange(block, edge_sel, rev_sel, active)`` operates on a
    local ``(..., 1, m_loc, m_loc)`` extended block with this device's
    ``(1, 1, 1, 4)`` parameter rows; it fills intra-panel ghosts with 4
    neighbor ``ppermute``s, then runs the 4 cube-edge stages as joint
    ``ppermute``s over the whole device product axis.  ``s == 1`` reduces
    to the one-face-per-device program.

    Ghost corners are averaged per block (diagnostics); the dimension-
    split stencils never read them, so block-seam corners carrying
    averaged rather than true diagonal data does not affect the numerics.
    """
    if n % s:
        raise ValueError(f"n={n} not divisible by blocks-per-edge s={s}")
    n_loc = n // s
    if n_loc < halo:
        raise ValueError(f"local block {n_loc} smaller than halo {halo}")
    program = BlockHaloProgram(s, axis_names)
    joint = program.axis_names

    def local_exchange(block, edge_sel, rev_sel, active):
        if block.shape[-3] != 1:
            raise ValueError(
                f"block-halo path expects one block per device; got local "
                f"panel extent {block.shape[-3]}"
            )
        # -- intra-panel ghosts (neighbor blocks of the same face) --------
        # All 4 reads before any write: reads are interior-only and
        # writes ghost-only, so expressing the independence lets XLA
        # overlap the neighbor ppermutes on ICI (same pattern as
        # halo.make_halo_exchanger's read-all-then-write-all).
        moved = []
        for axis, perm, e_send, e_recv in program.intra_perms:
            if not perm:
                continue
            strip = read_strip(block, 0, e_send, halo, n_loc)
            moved.append((e_recv, lax.ppermute(strip, axis, perm)))
        for e_recv, strip in moved:
            # Boundary blocks receive zeros here; the cube stages below
            # overwrite those face-edge ghosts with the real data.
            block = write_strip(block, 0, e_recv, strip)

        # -- cube-edge stages ---------------------------------------------
        for t, perm in enumerate(program.cube_perms):
            block = _cube_stage(
                block, halo, n_loc, joint, perm,
                edge_sel[0, 0, 0, t], rev_sel[0, 0, 0, t],
                active[0, 0, 0, t],
            )
        if fill_corners:
            block = _fill_corners(block, halo, n_loc)
        return block

    return program, local_exchange
