"""Explicit shard_map halo exchange: hand-scheduled ``lax.ppermute``.

The TPU-native flagship communication path (SURVEY.md §2.6): instead of
letting GSPMD infer collectives from whole-array updates (the reference's
implicit model, ``/root/reference/JAX-DevLab-Examples.py:192-195``), the
exchange runs *inside* ``jax.shard_map`` with the cube's 12 edge swaps
lowered to four ``lax.ppermute`` collectives over the ``'panel'`` mesh
axis — riding ICI with compile-time source/target pairs.

The mapping is exact: the reference's 4-stage race-free schedule (deck
p.9) is a proper edge coloring whose stages are perfect matchings on the
6 faces, so each stage *is* one bijective ``ppermute`` — every device
sends exactly one strip and receives exactly one strip per stage.  The
race-freedom invariant the reference enforces by staging becomes a
structural property of the collective.

Per-device variation (which of my 4 edges participates this stage;
whether the along-edge index reverses) cannot be Python control flow in
an SPMD program, so it is carried as *data*: small ``(6, 4)`` parameter
arrays sharded ``P('panel')``, selected with ``jnp.take``/``lax.switch``
on the local scalar.  The program stays uniform; the data differs.

Scope: one face per device along the panel axis (``panel=6``).  Sub-panel
tiling (``tiles_per_edge > 1``) runs through the GSPMD path in
:mod:`jaxstream.parallel.halo`; extending this explicit path to block
meshes is roadmap work.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..geometry.connectivity import build_connectivity, build_schedule
from .halo import _fill_corners, read_strip, write_strip

__all__ = ["ShardHaloProgram", "make_shard_halo_program"]


class ShardHaloProgram:
    """Static schedule + per-device parameters for the ppermute exchange.

    Attributes:
      perms: list of 4 permutation lists [(src, dst), ...] — one bijection
        per stage, passed to ``lax.ppermute``.
      edge_sel: (6, 4) int32, ``edge_sel[f, s]`` = which edge of face f
        exchanges in stage s (my *send* strip and my *write* ghost edge).
      rev_sel: (6, 4) bool, whether the pair's along-edge index reverses.
    """

    def __init__(self, axis_name: str = "panel"):
        adj = build_connectivity()
        schedule = build_schedule(adj)
        self.axis_name = axis_name
        self.perms = []
        edge_sel = np.zeros((6, len(schedule)), dtype=np.int32)
        rev_sel = np.zeros((6, len(schedule)), dtype=bool)
        for s, stage in enumerate(schedule):
            perm = []
            for link, back in stage:
                perm.append((link.face, link.nbr_face))
                perm.append((back.face, back.nbr_face))
                edge_sel[link.face, s] = link.edge
                edge_sel[back.face, s] = back.edge
                rev_sel[link.face, s] = link.reversed_
                rev_sel[back.face, s] = back.reversed_
            # Perfect matching => bijection on all 6 faces.
            assert sorted(d for _, d in perm) == list(range(6))
            self.perms.append(perm)
        self.edge_sel = jnp.asarray(edge_sel)
        self.rev_sel = jnp.asarray(rev_sel)

    @property
    def params(self):
        """The (6, 4) per-device parameter arrays; shard with P('panel')."""
        return {"edge_sel": self.edge_sel, "rev_sel": self.rev_sel}


def make_shard_halo_program(
    n: int,
    halo: int,
    axis_name: str = "panel",
    fill_corners: bool = True,
):
    """Build ``(program, local_exchange)`` for use inside ``shard_map``.

    ``local_exchange(block, edge_sel, rev_sel)`` operates on a local
    ``(..., 1, M, M)`` extended block (one face per device) with this
    device's ``(1, 4)`` parameter rows, and performs the full cube-edge
    halo fill in 4 ``ppermute`` stages.
    """
    program = ShardHaloProgram(axis_name)
    perms = program.perms

    def local_exchange(block, edge_sel, rev_sel):
        if block.shape[-3] != 1:
            raise ValueError(
                f"shard-halo path expects one face per device; got local "
                f"panel extent {block.shape[-3]} (use the GSPMD path in "
                f"jaxstream.parallel.halo for other tilings)"
            )
        writers = [
            functools.partial(write_strip, face=0, edge=e) for e in range(4)
        ]
        for s, perm in enumerate(perms):
            e_s = edge_sel[0, s]
            r_s = rev_sel[0, s]
            # All 4 canonical strips; select mine for this stage by data.
            strips = jnp.stack(
                [read_strip(block, 0, e, halo, n) for e in range(4)]
            )
            strip = jnp.take(strips, e_s, axis=0)
            strip = jnp.where(r_s, jnp.flip(strip, axis=-1), strip)
            strip = lax.ppermute(strip, axis_name, perm)
            block = lax.switch(
                e_s, [lambda b, st, w=w: w(b, strip=st) for w in writers],
                block, strip,
            )
        if fill_corners:
            block = _fill_corners(block, halo, n)
        return block

    return program, local_exchange
