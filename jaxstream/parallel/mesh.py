"""Device-mesh and sharding setup (the reference's ``setup_sharding``, redone).

Reference behavior being rebuilt (``/root/reference/JAX-DevLab-Examples.py:
19-85``): read the ``parallelization`` config block; validate
``num_tiles = 6 * tiles_per_edge**2`` against the device count with
remediation-text errors; build a device mesh and a ``NamedSharding`` that
partitions the panel axis; support a virtual-CPU-device tier for testing.

TPU-native redesign:
  * 3-D mesh ``('panel', 'y', 'x')`` instead of the reference's 1-D
    ``('tiles',)`` — panels shard over 'panel', and each panel's interior
    block-decomposes over 'y' x 'x' (the reference's planned
    ``tiles_per_edge > 1``, which it explicitly left unimplemented at
    ``JAX-DevLab-Examples.py:31-37``).
  * The virtual-device flag ordering bug is avoided: we never set
    ``XLA_FLAGS`` after backend init; the testing tier *requests* CPU
    devices (``jax.devices('cpu')``) which works under any default
    platform, and documents that the host-device-count flag must be set
    before Python starts (tests/conftest.py does it).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config, ParallelConfig
from ..utils.logging import get_logger

__all__ = ["ShardingSetup", "available_devices", "setup_sharding",
           "shard_state", "setup_ensemble_sharding",
           "shard_ensemble_state"]

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class ShardingSetup:
    mesh: Optional[Mesh]
    num_devices: int
    panel: int
    sy: int
    sx: int
    use_shard_map: bool = False
    overlap_exchange: bool = False
    temporal_block: int = 1
    #: member-axis extent of the device mesh (ensemble runs on the 2-D
    #: ``('panel', 'member')`` mesh from :func:`setup_ensemble_sharding`;
    #: 1 everywhere else).
    member: int = 1

    @property
    def scalar_spec(self) -> P:
        return P("panel", "y", "x")

    def spec_for(self, ndim: int) -> P:
        """PartitionSpec for an array whose last 3 axes are (6, ny, nx)."""
        return P(*((None,) * (ndim - 3) + ("panel", "y", "x")))

    def sharding_for(self, ndim: int):
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, self.spec_for(ndim))

    def ensemble_spec_for(self, ndim: int) -> P:
        """PartitionSpec for a batched-ensemble array whose last 4 axes
        are ``(B, 6, ny, nx)`` (member axis immediately before the face
        axis, the :data:`...shallow_water_cov.ENSEMBLE_STATE_AXES`
        layout).  On the ``('panel', 'member')`` mesh the member axis
        shards over 'member' and faces over 'panel'; on the 1-D
        ``('member',)`` mesh (layout='member' — any device count, zero
        wire traffic) only the member axis shards; on the plain
        ``('panel', 'y', 'x')`` mesh members are replicated (stacked
        locally per face device)."""
        axes = self.mesh.axis_names if self.mesh is not None else ()
        if "member" in axes:
            tail = ("member", "panel" if "panel" in axes else None,
                    None, None)
        else:
            tail = (None, "panel", "y", "x")
        return P(*((None,) * (ndim - 4) + tail))

    def ensemble_sharding_for(self, ndim: int):
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, self.ensemble_spec_for(ndim))


def available_devices(kind: str = "cpu"):
    """Every device of ``kind`` — the pool a placement planner sizes
    against (``'default'`` = the default platform; ``'tpu'`` falls
    back to the 'axon' PJRT plugin this image exposes the chip
    through).  The ONE device-selection rule — :func:`_pick_devices`
    is this plus a count requirement."""
    kind = (kind or "cpu").lower()
    if kind == "cpu":
        return jax.devices("cpu")
    if kind == "default":
        return jax.devices()
    if kind in ("tpu", "gpu", "axon"):
        try:
            return jax.devices(kind)
        except RuntimeError:
            if kind != "tpu":
                raise
            # This image exposes the TPU through the 'axon' PJRT plugin.
            return jax.devices("axon")
    raise ValueError(
        f"unknown device_type {kind!r}; use 'cpu', 'tpu' or 'gpu'")


def _pick_devices(kind: str, count: int):
    devs = available_devices(kind)
    if len(devs) < count:
        raise ValueError(
            f"requested num_devices={count} but only {len(devs)} {kind} devices "
            f"exist. For CPU testing, start Python with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={count}."
        )
    return devs[:count]


def _factor_mesh(num_devices: int, tiles_per_edge: int):
    """(panel, sy, sx) device-mesh dims for D devices at tiling t."""
    p = math.gcd(num_devices, 6)
    rest = num_devices // p
    # Near-square split of the per-panel block grid.
    sy = int(math.sqrt(rest))
    while rest % sy:
        sy -= 1
    sx = rest // sy
    if sy > tiles_per_edge or sx > tiles_per_edge:
        raise ValueError(
            f"num_devices={num_devices} needs a {sy}x{sx} sub-panel split but "
            f"tiles_per_edge={tiles_per_edge} only allows up to "
            f"{tiles_per_edge}x{tiles_per_edge}; raise tiles_per_edge."
        )
    return p, sy, sx


def _coerce_parallel_config(config: Any) -> ParallelConfig:
    """Config / ParallelConfig / raw reference-style dict / None -> the
    ParallelConfig — ONE coercion for every mesh entry point, so a new
    field cannot be silently dropped in one of them."""
    if isinstance(config, Config):
        return config.parallelization
    if isinstance(config, ParallelConfig):
        return config
    if config is None:
        return ParallelConfig()
    # raw dict, reference-style: config['parallelization'].get(...)
    block = dict(config.get("parallelization", {}))
    known = {f.name for f in dataclasses.fields(ParallelConfig)}
    return ParallelConfig(**{k: v for k, v in block.items()
                             if k in known})


def setup_sharding(config: Any = None) -> ShardingSetup:
    """Build the device mesh + shardings from a Config (or its dict form)."""
    par = _coerce_parallel_config(config)

    t = par.tiles_per_edge
    if t < 1:
        raise ValueError(f"tiles_per_edge must be >= 1, got {t}")
    if par.temporal_block < 1:
        raise ValueError(
            f"temporal_block must be >= 1, got {par.temporal_block}")
    num_tiles = 6 * t * t
    d = par.num_devices
    if d > num_tiles:
        raise ValueError(
            f"num_devices={d} exceeds num_tiles={num_tiles} "
            f"(= 6 * tiles_per_edge^2). Reduce num_devices or raise "
            f"tiles_per_edge."
        )
    if num_tiles % d != 0:
        divisors = [k for k in range(1, num_tiles + 1) if num_tiles % k == 0]
        raise ValueError(
            f"num_tiles={num_tiles} is not divisible by num_devices={d}. "
            f"Valid device counts: {divisors}."
        )

    if d == 1:
        log.info("sharding: single device (no mesh)")
        return ShardingSetup(mesh=None, num_devices=1, panel=1, sy=1, sx=1,
                             temporal_block=par.temporal_block)

    p, sy, sx = _factor_mesh(d, t)
    devs = np.array(_pick_devices(par.device_type, d)).reshape(p, sy, sx)
    mesh = Mesh(devs, ("panel", "y", "x"))
    log.info(
        "sharding: %d %s devices as mesh panel=%d y=%d x=%d (tiles_per_edge=%d)",
        d, par.device_type, p, sy, sx, t,
    )
    return ShardingSetup(mesh=mesh, num_devices=d, panel=p, sy=sy, sx=sx,
                         use_shard_map=par.use_shard_map,
                         overlap_exchange=par.overlap_exchange,
                         temporal_block=par.temporal_block)


def shard_state(setup: ShardingSetup, state):
    """device_put every leaf with the rank-appropriate (panel,y,x) spec."""
    if setup.mesh is None:
        return state
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, setup.sharding_for(a.ndim)), state
    )


def setup_ensemble_sharding(config: Any = None,
                            members: int = 1,
                            layout: str = "auto") -> ShardingSetup:
    """2-D ``('panel', 'member')`` device mesh for batched ensemble runs.

    The ensemble workload has two data-parallel axes: the six cube faces
    (halo exchange along it) and the ``B`` perturbed-IC members (no
    communication at all).  This factors ``num_devices = 6 * m`` into a
    ``(panel=6, member=m)`` mesh — faces exchange over 'panel' exactly as
    on the face tier (a ``ppermute`` naming only 'panel' is per-member-
    column automatically), members scatter over 'member' with zero
    wire traffic.  Each device then holds ``B / m`` members of one face,
    and the batched exchange ships their strips in ONE ppermute per
    schedule stage.

    When to prefer member-sharding over face-sharding: with more than 6
    devices the face tier is out of axes (tiles_per_edge intra-face
    blocks add seam traffic), while extra member shards are free —
    docs/USAGE.md "Ensembles" quantifies the trade.  ``members`` must be
    divisible by ``m`` so every device carries the same member count.

    ``layout='member'`` (round 12) builds a 1-D ``('member',)`` mesh
    instead: ONLY the member axis shards, one member column per device
    — any device count that divides ``members`` works (no
    multiple-of-6 constraint) because members never communicate.  This
    is the GSPMD path's layout (the vmapped stepper partitions over
    the member axis with zero wire traffic); the explicit
    ``use_shard_map`` steppers need the panel axis and reject it.
    ``'auto'``/``'panel_member'`` are the 2-D mesh above.
    """
    par = _coerce_parallel_config(config)
    if members < 1:
        raise ValueError(f"members must be >= 1, got {members}")
    if layout not in ("auto", "panel_member", "member"):
        raise ValueError(
            f"ensemble layout {layout!r}; valid: 'auto' (the 2-D "
            f"('panel', 'member') mesh), 'panel_member' (same, "
            f"explicit), 'member' (1-D member-only mesh)")
    d = par.num_devices
    if d == 1:
        log.info("ensemble sharding: single device (no mesh), %d members "
                 "stacked locally", members)
        return ShardingSetup(mesh=None, num_devices=1, panel=1, sy=1, sx=1,
                             temporal_block=par.temporal_block)
    if layout == "member":
        if par.use_shard_map:
            raise ValueError(
                "ensemble.layout: member is the GSPMD layout (the "
                "member axis only); the explicit shard_map steppers "
                "exchange over the panel axis — set use_shard_map: "
                "false, or layout: panel_member with num_devices a "
                "multiple of 6")
        if members % d:
            raise ValueError(
                f"ensemble.layout: member shards {members} members over "
                f"{d} devices, which must divide evenly; use a device "
                f"count that divides members (or fewer devices)")
        devs = np.array(_pick_devices(par.device_type, d))
        mesh = Mesh(devs, ("member",))
        log.info("ensemble sharding: %d %s devices as 1-D member mesh "
                 "(%d members -> %d per device)", d, par.device_type,
                 members, members // d)
        return ShardingSetup(mesh=mesh, num_devices=d, panel=1, sy=1,
                             sx=1, overlap_exchange=par.overlap_exchange,
                             temporal_block=par.temporal_block, member=d)
    if d % 6:
        raise ValueError(
            f"ensemble sharding factors num_devices as 6 faces x m member "
            f"shards; num_devices={d} is not a multiple of 6. Valid "
            f"counts: 6, 12, 18, ... (or num_devices: 1 for the "
            f"single-device batched stepper).")
    m = d // 6
    if members % m:
        raise ValueError(
            f"ensemble.members={members} is not divisible by the member-"
            f"shard count {m} (= num_devices/6); every device must carry "
            f"the same number of members. Use members that {m} divides, "
            f"or fewer devices.")
    devs = np.array(_pick_devices(par.device_type, d)).reshape(6, m)
    mesh = Mesh(devs, ("panel", "member"))
    log.info("ensemble sharding: %d %s devices as mesh panel=6 member=%d "
             "(%d members -> %d per device)", d, par.device_type, m,
             members, members // m)
    return ShardingSetup(mesh=mesh, num_devices=d, panel=6, sy=1, sx=1,
                         use_shard_map=par.use_shard_map,
                         overlap_exchange=par.overlap_exchange,
                         temporal_block=par.temporal_block, member=m)


def shard_ensemble_state(setup: ShardingSetup, state):
    """device_put a batched ensemble state (leaves ``(.., B, 6, ny, nx)``
    in the member-before-face layout) with the ensemble specs."""
    if setup.mesh is None:
        return state
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, setup.ensemble_sharding_for(a.ndim)),
        state)
