"""Cubed-sphere halo exchange — global-array (GSPMD) path.

This is the TPU-native rebuild of the reference's "Scalar Halo Exchange V2"
(``/root/reference/JAX-DevLab-Examples.py:89-246``, deck p.9-11), redesigned:

  * The reference JIT-compiles 12 separate edge-pair closures plus a
    composed JIT.  Here the whole exchange is one pure function of the
    extended field, meant to be traced *inside* the single top-level step
    ``jit`` — the reference's "compile once, no recompile during
    timestepping" invariant (deck p.10) kept, its redundant per-edge JITs
    dropped (SURVEY.md §7 pitfalls).
  * Orientation handling generalizes the reference's 1-deep scalar ops
    {N, T, R, TR} (``JAX-DevLab-Examples.py:143-163``): strips are read in
    a canonical (depth, along-edge) frame, so "T" is the strip transpose
    built into the frame and only the along-edge *reversal* remains as a
    data op — correct for any halo depth, unlike the reference's T==identity
    shortcut which only works for 1-deep scalars.
  * Under ``jax.jit`` with a ``NamedSharding`` over the panel (and x/y)
    axes, XLA's GSPMD partitioner lowers the 24 directed strip copies to
    ``collective_permute``s between panel shards — the reference's implicit
    communication model (SURVEY.md §2.6).  The explicit ``shard_map`` +
    ``lax.ppermute`` path is built on top of this in
    :mod:`jaxstream.parallel.shard_halo` (hand-scheduled collectives for the
    flagship TPU configuration).

Field layout: ``(..., 6, M, M)`` with ``M = n + 2*halo``; leading axes (e.g.
a Cartesian vector-component axis) are carried through untouched, which is
exactly the reference's "Cartesian Velocity Exchange" (deck p.18): vector
fields exchange componentwise with no rotation.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax.numpy as jnp

from ..geometry.connectivity import (
    EDGE_E,
    EDGE_N,
    EDGE_S,
    EDGE_W,
    EdgeLink,
    build_connectivity,
    build_schedule,
)

__all__ = ["canonicalize_strip", "make_halo_exchanger",
           "make_concat_exchanger", "place_strip", "read_strip",
           "write_strip"]


def canonicalize_strip(edge: int, raw):
    """Raw boundary slice -> canonical ``(..., halo, n)`` strip.

    The read-side twin of :func:`place_strip`: applies the per-edge frame
    transform of :func:`read_strip` to an already-sliced raw strip
    (S/N row blocks ``(..., halo, n)``, W/E column blocks
    ``(..., n, halo)``).  Shared with the fused stepper's strip router
    (jaxstream.ops.pallas.swe_step.route_strips).
    """
    if edge == EDGE_S:
        return raw
    if edge == EDGE_N:
        return jnp.flip(raw, axis=-2)
    if edge == EDGE_W:
        return jnp.swapaxes(raw, -1, -2)
    if edge == EDGE_E:
        return jnp.swapaxes(jnp.flip(raw, axis=-1), -1, -2)
    raise ValueError(edge)


def place_strip(edge: int, strip):
    """Canonical ``(..., halo, n)`` strip -> the exact ghost-ring block.

    The single source of truth for the edge-placement transform (inverse
    of :func:`read_strip`'s canonical frame): S flips depth, N is
    identity, W/E transpose to ``(..., n, halo)`` column blocks.  Used by
    :func:`write_strip`, :func:`make_concat_exchanger`, and the fused
    stepper's strip router (jaxstream.ops.pallas.swe_step.route_strips).
    """
    if edge == EDGE_S:
        return jnp.flip(strip, axis=-2)
    if edge == EDGE_N:
        return strip
    if edge == EDGE_W:
        return jnp.flip(jnp.swapaxes(strip, -1, -2), axis=-1)
    if edge == EDGE_E:
        return jnp.swapaxes(strip, -1, -2)
    raise ValueError(edge)


def read_strip(field, face: int, edge: int, halo: int, n: int):
    """Interior boundary strip of ``face``/``edge`` in canonical frame.

    Returns ``(..., halo, n)``: axis -2 is depth (0 = nearest the edge),
    axis -1 is the along-edge index (increasing alpha for S/N, increasing
    beta for E/W).  This is the rebuild of the reference's missing
    ``extract_boundary_data`` (called at ``JAX-DevLab-Examples.py:184-185``
    but never defined).
    """
    h, hn = halo, halo + n
    a = field[..., face, :, :]
    if edge == EDGE_S:
        return a[..., h : 2 * h, h:hn]
    if edge == EDGE_N:
        return jnp.flip(a[..., hn - h : hn, h:hn], axis=-2)
    if edge == EDGE_W:
        return jnp.swapaxes(a[..., h:hn, h : 2 * h], -1, -2)
    if edge == EDGE_E:
        return jnp.swapaxes(jnp.flip(a[..., h:hn, hn - h : hn], axis=-1), -1, -2)
    raise ValueError(edge)


def write_strip(field, face: int, edge: int, strip):
    """Write a canonical ``(..., halo, n)`` strip into the ghost ring.

    Rebuild of the reference's missing ``set_ghost_data``
    (``JAX-DevLab-Examples.py:192-195``).
    """
    h = strip.shape[-2]
    n = strip.shape[-1]
    hn = h + n
    placed = place_strip(edge, strip)
    if edge == EDGE_S:
        return field.at[..., face, 0:h, h:hn].set(placed)
    if edge == EDGE_N:
        return field.at[..., face, hn : hn + h, h:hn].set(placed)
    if edge == EDGE_W:
        return field.at[..., face, h:hn, 0:h].set(placed)
    if edge == EDGE_E:
        return field.at[..., face, h:hn, hn : hn + h].set(placed)
    raise ValueError(edge)


def _fill_corners(field, halo: int, n: int):
    """Fill the 4 h-by-h ghost corner blocks per face by edge-ghost averaging.

    Three panels meet at each cube corner, so no unique neighbor exists
    (SURVEY.md §7 "hard parts"); dimension-split stencils never read the
    corners, and the average keeps them finite for diagnostics/viz.
    """
    h, hn = halo, halo + n
    f = field
    # SW / SE / NW / NE corner blocks.
    f = f.at[..., 0:h, 0:h].set(
        0.5 * (f[..., 0:h, h : h + 1] + f[..., h : h + 1, 0:h])
    )
    f = f.at[..., 0:h, hn : hn + h].set(
        0.5 * (f[..., 0:h, hn - 1 : hn] + f[..., h : h + 1, hn : hn + h])
    )
    f = f.at[..., hn : hn + h, 0:h].set(
        0.5 * (f[..., hn : hn + h, h : h + 1] + f[..., hn - 1 : hn, 0:h])
    )
    f = f.at[..., hn : hn + h, hn : hn + h].set(
        0.5 * (f[..., hn : hn + h, hn - 1 : hn] + f[..., hn - 1 : hn, hn : hn + h])
    )
    return f


def directed_copies(adj=None, schedule=None):
    """Flatten the staged schedule to a static list of directed copies
    ``(dst_face, dst_edge, src_face, src_edge, reversed)`` — shared
    routing source of truth for the dense exchanger and the TT
    factored-strip exchange (jaxstream.tt.sphere)."""
    adj = adj or build_connectivity()
    schedule = schedule or build_schedule(adj)
    copies = []
    for stage in schedule:
        for link, back in stage:
            copies.append((link.face, link.edge, link.nbr_face,
                           link.nbr_edge, link.reversed_))
            copies.append((back.face, back.edge, back.nbr_face,
                           back.nbr_edge, back.reversed_))
    return copies


def make_halo_exchanger(
    n: int,
    halo: int,
    adj: Optional[List[List[EdgeLink]]] = None,
    schedule: Optional[List[List[Tuple[EdgeLink, EdgeLink]]]] = None,
    fill_corners: bool = True,
) -> Callable:
    """Build ``exchange(field) -> field`` for ``(..., 6, M, M)`` fields.

    The returned function is pure and trace-friendly: call it inside the
    top-level ``jit``.  Exchanges are applied in race-free stage order from
    the edge-coloring scheduler (functional ``.at[].set`` semantics already
    make races impossible — deck p.11 — the staging is kept as the
    documented communication schedule and for the shard_map path's benefit).
    """
    copies = directed_copies(adj, schedule)

    m = n + 2 * halo

    def exchange(field):
        if field.shape[-3:] != (6, m, m):
            raise ValueError(
                f"halo exchanger built for n={n}, halo={halo} expects a "
                f"(..., 6, {m}, {m}) field, got {field.shape}"
            )
        strips = []
        for df, de, sf, se, rev in copies:
            s = read_strip(field, sf, se, halo, n)
            if rev:
                s = jnp.flip(s, axis=-1)
            strips.append(s)
        # All reads before all writes: the classic double-buffer exchange,
        # expressed functionally.
        for (df, de, _, _, _), s in zip(copies, strips):
            field = write_strip(field, df, de, s)
        if fill_corners:
            field = _fill_corners(field, halo, n)
        return field

    return exchange


def make_concat_exchanger(
    n: int,
    halo: int,
    adj: Optional[List[List[EdgeLink]]] = None,
    fill_corners: bool = True,
) -> Callable:
    """Concat-layout exchange: rebuild each face in one pass.

    Value-identical to :func:`make_halo_exchanger` (same strips, same
    corner averaging) but expressed as 6 face reassemblies via
    ``jnp.concatenate`` instead of 48 sequential ``.at[].set`` updates —
    on a single device each exchange is then ~one full-field read + one
    write instead of a long chain of small scatter kernels.  This is the
    hot-loop formulation for the fused TPU stepper
    (:mod:`jaxstream.ops.pallas.swe_step`); the scatter formulation
    remains the one GSPMD partitions into collectives for the
    multi-device global-array path.
    """
    adj = adj or build_connectivity()
    m = n + 2 * halo

    def exchange(field):
        if field.shape[-3:] != (6, m, m):
            raise ValueError(
                f"halo exchanger built for n={n}, halo={halo} expects a "
                f"(..., 6, {m}, {m}) field, got {field.shape}"
            )
        faces = []
        for f in range(6):
            g = {}
            for e in range(4):
                link = adj[f][e]
                s = read_strip(field, link.nbr_face, link.nbr_edge, halo, n)
                if link.reversed_:
                    s = jnp.flip(s, axis=-1)
                g[e] = place_strip(e, s)
            interior = field[..., f, halo : halo + n, halo : halo + n]
            if fill_corners:
                # Same averaging as _fill_corners, from the placed strips.
                sw = 0.5 * (g[EDGE_S][..., :, :1] + g[EDGE_W][..., :1, :])
                se = 0.5 * (g[EDGE_S][..., :, -1:] + g[EDGE_E][..., :1, :])
                nw = 0.5 * (g[EDGE_N][..., :, :1] + g[EDGE_W][..., -1:, :])
                ne = 0.5 * (g[EDGE_N][..., :, -1:] + g[EDGE_E][..., -1:, :])
            else:
                z = jnp.zeros(g[EDGE_S].shape[:-2] + (halo, halo),
                              dtype=field.dtype)
                sw = se = nw = ne = z
            top = jnp.concatenate([sw, g[EDGE_S], se], axis=-1)
            mid = jnp.concatenate([g[EDGE_W], interior, g[EDGE_E]], axis=-1)
            bot = jnp.concatenate([nw, g[EDGE_N], ne], axis=-1)
            faces.append(jnp.concatenate([top, mid, bot], axis=-2))
        return jnp.stack(faces, axis=-3)

    return exchange
